"""Autopilot chaos smoke (`make chaos-smoke`): kill -> auto-shrink ->
burn-driven regrow -> degrade ladder -> shed -> preempt -> bitwise
resume, on one CPU, in minutes (ISSUE 19's proof harness).

    python tools/chaos_smoke.py [outdir] [--artifact PATH] [--round N]

The harness arms the daemon-plane fault clauses
(`dead@poll3,burst@poll5..12:alice*50` — utils/faultinject.poll_faults)
under a serving daemon with the autopilot ON and drives one scripted
storm through the policy loop:

  polls 1-2    warm serving traffic (alice/bob requests, flat path)
  poll 3       the resident elastic job's rank DIES: the autopilot — no
               operator — turns the InjectedRankDeath into
               `shrink_resume` onto survivor capacity, fault ledger
               carried through the manifest
  polls 5-12   a sustained synthetic SLO burn on alice: the hysteresis
               band grows the lane pool EXACTLY ONCE (checkpoint-fenced
               through the elastic manifest), then walks the degradation
               ladder one rung per sustained-hot window:
               class_consolidation -> itermax_cap -> shed_low_priority
  poll 13      a low-priority (bob) request hits rung 3 and is SHED with
               a structured failure result
  recovery     the burn window drains; the ladder steps back to full
               service one rung per sustained-calm window and the
               time-to-recover clock closes
  preempt      3 bob + 1 zoe requests over a 3-lane pool: zoe (high)
               preempts a bob lane through a parked-lane manifest; the
               victim resumes bitwise once the queue drains

and then ASSERTS the whole story:

- rc 0, every non-shed request served, exactly one grow, zero flaps;
- the recorded rung sequence is MONOTONE (|delta| <= 1 per autoscale
  record — no rung skipping, no intra-phase oscillation);
- the final manifest still carries the pre-death fault ledger (heal and
  every fence re-persist it — no probation amnesia);
- BITWISE parity #1 (heal/fence): the resident solver driven to
  completion equals a fresh `elastic_restore` twin from the same
  manifest generation on the same surviving mesh;
- BITWISE parity #2 (preempt): a scheduler run with preemption armed
  produces per-scenario fields bitwise-identical to the same request
  set served without priorities — the park/resume roundtrip is
  lossless;
- the merged artifact lints clean (check_artifact: `autoscale` +
  `chaos_trajectory` blocks) and carries the trend-gated
  autoscale_flaps / autoscale_time_to_recover_ms metrics.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import time
import warnings

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# CPU-stable chaos environment: must precede any jax import (the
# tools/lint.py convention); a TPU image just keeps its own backend
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")
# the scripted storm: one rank death at poll 3, then a sustained
# synthetic burn on alice across polls 5..12 (50 violating observations
# per poll — burn ~20x with everything in-window, far above burn_high)
os.environ["PAMPI_FAULTS"] = "dead@poll3," + ",".join(
    f"burst@poll{n}:alice*50" for n in range(5, 13))

PAR = """name dcavity
imax 12
jmax 12
re 10.0
te {te}
tau 0.5
itermax 8
eps 0.0001
omg 1.7
gamma 0.9
tpu_mesh 1
"""

_RESIDENT = dict(name="dcavity", imax=16, jmax=16, re=10.0, tau=0.5,
                 itermax=50, eps=1e-4, omg=1.7, gamma=0.9,
                 tpu_dtype="float32")
# the marker the ledger-carry assertion looks for at the END of the run:
# heal's shrink_resume and every grow/shrink fence must re-persist it
LEDGER = {"chaos_marker": 1, "transient_budget_spent": 0,
          "pallas_broken": False}


def _drop(qdir: str, name: str, te: float) -> None:
    with open(os.path.join(qdir, name), "w") as fh:
        fh.write(PAR.format(te=te))


def _sample(traj: dict, daemon) -> None:
    ap = daemon.autopilot
    burns = daemon.slo.burn_snapshot(time.time())
    traj["poll"].append(daemon.polls)
    traj["rung"].append(ap.rung)
    traj["lanes"].append(ap.lanes)
    traj["burn_max"].append(round(max(burns.values(), default=0.0), 3))


def main(argv: list[str]) -> int:
    ap_cli = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap_cli.add_argument("outdir", nargs="?",
                        default=os.path.join(REPO, "results", "chaos"))
    ap_cli.add_argument("--artifact", default="",
                        help="also merge the blocks into this committed "
                             "BENCH artifact (default: outdir-local only)")
    ap_cli.add_argument("--round", type=int, default=0,
                        help="artifact round number `n` (with --artifact)")
    args = ap_cli.parse_args(argv[1:])

    outdir = args.outdir
    shutil.rmtree(outdir, ignore_errors=True)
    qdir = os.path.join(outdir, "queue")
    os.makedirs(qdir, exist_ok=True)
    jsonl = os.path.join(outdir, "run.jsonl")
    os.environ["PAMPI_TELEMETRY"] = jsonl

    import numpy as np

    from pampi_tpu import fleet
    from pampi_tpu.fleet import FleetDaemon, ServeConfig
    from pampi_tpu.fleet.autopilot import ParkStore
    from pampi_tpu.fleet.scheduler import FleetScheduler
    from pampi_tpu.models.ns2d import NS2DSolver
    from pampi_tpu.utils import checkpoint as ckpt
    from pampi_tpu.utils import faultinject as fi
    from pampi_tpu.utils import telemetry as tm
    from pampi_tpu.utils.params import Parameter

    fleet.reset_templates()
    fi.reset()
    tm.reset()
    tm.start_run(tool="chaos_smoke")

    failures: list[str] = []

    # -- the resident elastic job: a mid-flight generation to die on ---
    manifest = os.path.join(outdir, "resident.elastic")
    pre = NS2DSolver(Parameter(te=0.03, **_RESIDENT))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        pre.run(progress=False)
    ckpt.save_elastic(manifest, pre, ledger=dict(LEDGER))
    param_full = Parameter(te=0.08, **_RESIDENT)

    daemon = FleetDaemon(ServeConfig(
        queue_dir=qdir, poll_s=0.01, max_lanes=2, max_queue=32,
        tenant_quota=8, classes="on",
        slo="default=60000,alice=800", slo_window_s=1.2,
        autopilot=("on:sustain=2,cooldown=2,max_lanes=3,min_lanes=1,"
                   "idle_polls=99,backlog_high=50"),
        priorities="zoe=high,bob=low"))
    pilot = daemon.autopilot
    pilot.register_resident(manifest, param_full)

    traj = {"poll": [], "rung": [], "lanes": [], "burn_max": []}

    # polls 1-2: warm traffic on the flat path
    _drop(qdir, "alice__w1.par", te=0.02)
    _drop(qdir, "bob__w2.par", te=0.02)
    for _ in range(2):
        daemon.poll_once()
        _sample(traj, daemon)

    # poll 3: the injected death -> heal; poll 4: calm filler
    for _ in range(2):
        daemon.poll_once()
        _sample(traj, daemon)
    if pilot.counts["heal"] != 1:
        failures.append(f"heal count {pilot.counts['heal']} != 1 after "
                        "the poll-3 death")
    if len(pilot.devices) != 7:
        failures.append(f"{len(pilot.devices)} survivors != 7 after one "
                        "casualty")

    # polls 5-12: the sustained burn — grow once, then walk the ladder
    # down to shed_low_priority (tight sleeps keep the 1.2 s SLO window
    # saturated across the whole storm)
    for _ in range(5, 13):
        daemon.poll_once()
        _sample(traj, daemon)
        time.sleep(0.02)
    if pilot.counts["grow"] != 1:
        failures.append(f"grow count {pilot.counts['grow']} != 1 during "
                        "the burn storm")
    if pilot.rung != 3:
        failures.append(f"rung {pilot.rung} != 3 (shed_low_priority) "
                        "after the sustained burn")

    # poll 13: a low-priority request meets rung 3 -> shed
    _drop(qdir, "bob__shed.par", te=0.02)
    daemon.poll_once()
    _sample(traj, daemon)
    shed_res = os.path.join(daemon.results_dir, "bob__shed.json")
    if not os.path.exists(shed_res):
        failures.append("no structured result for the shed request")
    else:
        with open(shed_res) as fh:
            row = json.load(fh)
        if not (row.get("failed") and row.get("shed")):
            failures.append(f"shed result is not a shed failure: {row}")

    # recovery: the burn window drains, the ladder climbs back to full
    # service and the time-to-recover clock closes
    for _ in range(20):
        if pilot.rung == 0 and pilot.recoveries_ms:
            break
        time.sleep(0.35)
        daemon.poll_once()
        _sample(traj, daemon)
    if pilot.rung != 0:
        failures.append(f"ladder never recovered (rung {pilot.rung})")
    if not pilot.recoveries_ms:
        failures.append("time-to-recover clock never closed")

    # preempt: 3 low + 1 high over a 3-lane pool — zoe evicts a bob
    # lane through a parked-lane manifest, the victim resumes bitwise
    for i in range(3):
        _drop(qdir, f"bob__p{i}.par", te=0.02 + 0.005 * i)
    _drop(qdir, "zoe__p9.par", te=0.02)
    daemon.poll_once()
    _sample(traj, daemon)
    daemon.stop()
    tm.finalize()

    served_expect = 2 + 4  # warmup + preempt phase (the shed one failed)
    if daemon.served != served_expect:
        failures.append(f"served {daemon.served} != {served_expect}")
    if daemon.failed != 1:
        failures.append(f"failed {daemon.failed} != 1 (the shed request)")
    if pilot.flaps != 0:
        failures.append(f"{pilot.flaps} capacity flaps (hysteresis band "
                        "failed)")
    if pilot.counts["degrade"] != 3 or pilot.counts["recover"] != 3:
        failures.append(
            f"ladder walked {pilot.counts['degrade']} down / "
            f"{pilot.counts['recover']} up (want 3/3)")

    # -- the flight record tells the same story -------------------------
    from tools import telemetry_report as tr

    records = tr.load(jsonl)
    sys.stdout.write(tr.render(records))
    auto = [r for r in records if r.get("kind") == "autoscale"]
    decisions = [r.get("decision") for r in auto]
    for want in ("heal", "grow", "degrade", "recover", "preempt",
                 "resume", "hold"):
        if want not in decisions:
            failures.append(f"no autoscale decision={want!r} record")
    if decisions.count("grow") != 1:
        failures.append(f"{decisions.count('grow')} grow records != 1")
    rung_seq = [r["rung"] for r in auto if r.get("rung") is not None]
    if any(abs(b - a) > 1 for a, b in zip(rung_seq, rung_seq[1:])):
        failures.append(f"recorded rung sequence skips rungs: {rung_seq}")
    parked = [r for r in auto if r.get("decision") == "preempt"]
    if not (parked and os.path.exists(parked[0].get("manifest", ""))):
        failures.append("preempt record names no parked-lane manifest "
                        "on disk")
    if not any(r.get("action") == "shed" for r in records
               if r.get("kind") == "admission"):
        failures.append("no admission action=shed record")

    # -- ledger carry: no probation amnesia through heal + fences -------
    man = ckpt._read_manifest(manifest)
    if man.get("ledger", {}).get("chaos_marker") != 1:
        failures.append("the fault ledger did not survive heal/fence "
                        f"(manifest ledger: {man.get('ledger')})")

    # -- bitwise parity #1: resident vs a clean twin from the same
    #    generation on the same surviving mesh -------------------------
    resident = pilot.resident.solver
    devs = pilot.devices[:pilot.resident.devices]
    twin = daemon.sched.elastic_restore(manifest, param_full,
                                        family="ns2d", devices=devs)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        resident.run(progress=False)
        twin.run(progress=False)
    if (resident.nt != twin.nt or resident.t != twin.t or not all(
            np.array_equal(np.asarray(getattr(resident, f)),
                           np.asarray(getattr(twin, f)))
            for f in ("u", "v", "p"))):
        failures.append(
            "healed resident is not bitwise-identical to a clean "
            f"restore from generation {man.get('generation')} on "
            f"{len(devs)} device(s)")

    # -- bitwise parity #2: preemption leaves every tenant's fields
    #    untouched vs the same requests served flat ---------------------
    def _preempt_requests():
        # tpu_mesh=1 keeps these single-device like the daemon's .par
        # template: a dist config would split the bucket per te into
        # sub-3-lane groups and never enter the continuous pool
        return ([(f"bob__q{i}", Parameter(name="dcavity", imax=12,
                                          jmax=12, re=10.0,
                                          te=0.02 + 0.005 * i, tau=0.5,
                                          itermax=8, eps=1e-4, omg=1.7,
                                          gamma=0.9, tpu_mesh="1"))
                 for i in range(3)]
                + [("zoe__q9", Parameter(name="dcavity", imax=12,
                                         jmax=12, re=10.0, te=0.02,
                                         tau=0.5, itermax=8, eps=1e-4,
                                         omg=1.7, gamma=0.9,
                                         tpu_mesh="1"))])

    armed = FleetScheduler(classes="on", lanes=3, isolate=False)
    armed.park_store = ParkStore(os.path.join(outdir, "parity_park"))
    armed.priority_of = lambda sid: 0 if sid.startswith("zoe") else 2
    flat = FleetScheduler(classes="on", lanes=3, isolate=False)
    for sid, param in _preempt_requests():
        armed.submit_param(sid, param)
    for sid, param in _preempt_requests():
        flat.submit_param(sid, param)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        res_a = {s.sid: s for s in armed.run().scenarios}
        res_f = {s.sid: s for s in flat.run().scenarios}
    if armed.park_store.parked_total < 1:
        failures.append("parity run never parked a lane (preemption "
                        "did not trigger)")
    for sid, a in sorted(res_a.items()):
        f = res_f.get(sid)
        if f is None or a.nt != f.nt or a.t != f.t or not all(
                np.array_equal(np.asarray(x), np.asarray(y))
                for x, y in zip(a.fields, f.fields)):
            failures.append(f"{sid}: preempted-run fields are not "
                            "bitwise-identical to the flat run")

    # -- artifact round trip -------------------------------------------
    from tools._artifact import write_merged
    from tools.check_artifact import lint_bench

    block = {"n": args.round, "cmd": "chaos_smoke", "rc": 0,
             "tail": f"chaos: heal=1 grow=1 flaps={pilot.flaps} "
                     f"recover_ms={max(pilot.recoveries_ms or [0])}",
             "telemetry_summary": tr.summary(records),
             "serving_summary": tr.serving_summary(records),
             "autoscale": tr.autoscale_summary(records),
             "metrics_summary": tr.metrics_summary(records),
             "slo": tr.slo_summary(records),
             "chaos_trajectory": traj}
    merged = write_merged(os.path.join(outdir, "CHAOS.json"), block)
    failures += lint_bench(merged, "CHAOS")
    names = {m.get("name") for m in merged.get("metrics", [])}
    for metric in ("autoscale_flaps", "autoscale_time_to_recover_ms"):
        if metric not in names:
            failures.append(
                f"merged artifact carries no normalized {metric}")
    if args.artifact:
        # the committed artifact keeps the chaos planes only: the
        # serving latency headlines here are storm-shaped, not the
        # warm-path series tools/perf_fleet.py seeds (same policy as
        # tools/soak.py)
        commit = {k: v for k, v in block.items()
                  if k != "serving_summary"}
        write_merged(args.artifact, commit)

    if failures:
        print("\nCHAOS SMOKE FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print(f"\nchaos smoke ok: heal -> grow(x1) -> ladder 0..3..0 -> "
          f"shed -> preempt/resume bitwise over {daemon.polls} polls; "
          f"flaps=0, time-to-recover "
          f"{max(pilot.recoveries_ms):.0f} ms; autoscale + trajectory "
          "blocks linted clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
