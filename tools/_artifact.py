"""Shared artifact-write helper for the perf tools: merge-preserving JSON
(the committed artifacts carry curated analysis fields the tools do not
produce — a re-run refreshes the measured keys without deleting those)."""

import json
import os


def merge_nested(old: dict, new: dict) -> dict:
    """Recursive merge: `new` wins per leaf key, but dict-valued keys merge
    key-by-key instead of being clobbered wholesale — so a re-run that
    refreshes a tool-produced nested record (e.g. a per-session block)
    keeps the curated fields an analyst added inside it (ADVICE round-5:
    the shallow dict.update lost any curated field whose top-level key
    collided with a tool key)."""
    out = dict(old)
    for k, v in new.items():
        if k in out and isinstance(out[k], dict) and isinstance(v, dict):
            out[k] = merge_nested(out[k], v)
        else:
            out[k] = v
    return out


def dist_step_decomposition(make_solver, key: str, reps: int = 3) -> dict:
    """Solve/non-solve step decomposition for a DISTRIBUTED NS-2D/3-D
    config — the mesh twin of bench.py's `_ns2d_step_line` protocol.
    `make_solver(itermax)` builds a ready dist solver (te far beyond reach
    so a chunk always runs its full CHUNK steps; eps below reach so every
    solve caps at itermax).

    step_ms comes from best-of-`reps` chunk dispatches fenced by a scalar
    readback. The solve share uses the repo's two-point differencing: a
    second build at 2×itermax isolates the pure per-iteration solve cost
    (`solve_iter_ms` = itermax × per-iteration), so the remainder
    (`nonsolve_ms` = step - solve_iter) carries the phase chain PLUS the
    per-solve envelope (layout conversions, loop setup) — exactly the
    budget the fused phase kernels and the p-layout fold move. TPU-only:
    off-TPU the timing fields stay null (XLA:CPU whole-program optimization
    makes the subtraction meaningless — the bench.py contract) and only the
    dispatch tag is recorded."""
    import time

    import jax

    from pampi_tpu.utils import dispatch, telemetry

    s = make_solver(None)  # production itermax build, records dispatch
    tag = dispatch.last(key)
    base = {"phases": tag, "steps_timed": type(s).CHUNK}
    if jax.default_backend() != "tpu":
        # one key set on every path (itermax/note null rather than absent)
        # so write_merged re-runs across hosts never leave stale fields
        telemetry.emit_decomposition(key, None, None, None, phases=tag)
        return {**base, "step_ms": None, "solve_iter_ms": None,
                "nonsolve_ms": None, "itermax": None,
                "decomposition_note": "TPU-only (see tools/_artifact.py)"}

    def step_ms_of(sv):
        steps = type(sv).CHUNK
        # initial_state matches the chunk's arity (telemetry appends the
        # in-band metrics vector); the fence reads the carried loop time
        args = sv.initial_state()
        ti = len(args) - (3 if sv._metrics else 2)
        out = sv._chunk_sm(*args)
        float(out[ti])  # compile + warm; scalar readback is the fence
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            out = sv._chunk_sm(*args)
            float(out[ti])
            best = min(best, time.perf_counter() - t0)
        return best / steps * 1e3

    step_ms = step_ms_of(s)
    itermax = s.param.itermax
    step2_ms = step_ms_of(make_solver(2 * itermax))
    solve_iter_ms = step2_ms - step_ms  # itermax extra capped iterations
    # the decomposition as shared telemetry spans (no-op when unset):
    # solve here is the PER-ITERATION cost times itermax — the same
    # two-point differencing the artifact records
    telemetry.emit_decomposition(key, step_ms, solve_iter_ms,
                                 step_ms - solve_iter_ms,
                                 phases=tag, itermax=itermax)
    return {**base,
            "step_ms": round(step_ms, 3),
            "solve_iter_ms": round(solve_iter_ms, 3),
            "nonsolve_ms": round(step_ms - solve_iter_ms, 3),
            "itermax": itermax,
            "decomposition_note": None}


def write_merged(path: str, rec: dict) -> dict:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    if os.path.exists(path):
        with open(path) as fh:
            old = json.load(fh)
        rec = merge_nested(old, rec)
    with open(path, "w") as fh:
        json.dump(rec, fh, indent=2)
        fh.write("\n")
    print(json.dumps(rec, indent=2))
    print(f"wrote {path}")
    return rec
