"""Shared artifact-write helper for the perf tools: merge-preserving JSON
(the committed artifacts carry curated analysis fields the tools do not
produce — a re-run refreshes the measured keys without deleting those)."""

import json
import os


def merge_nested(old: dict, new: dict) -> dict:
    """Recursive merge: `new` wins per leaf key, but dict-valued keys merge
    key-by-key instead of being clobbered wholesale — so a re-run that
    refreshes a tool-produced nested record (e.g. a per-session block)
    keeps the curated fields an analyst added inside it (ADVICE round-5:
    the shallow dict.update lost any curated field whose top-level key
    collided with a tool key)."""
    out = dict(old)
    for k, v in new.items():
        if k in out and isinstance(out[k], dict) and isinstance(v, dict):
            out[k] = merge_nested(out[k], v)
        else:
            out[k] = v
    return out


def write_merged(path: str, rec: dict) -> dict:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    if os.path.exists(path):
        with open(path) as fh:
            old = json.load(fh)
        rec = merge_nested(old, rec)
    with open(path, "w") as fh:
        json.dump(rec, fh, indent=2)
        fh.write("\n")
    print(json.dumps(rec, indent=2))
    print(f"wrote {path}")
    return rec
