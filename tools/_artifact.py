"""Shared artifact-write helper for the perf tools: merge-preserving JSON
(the committed artifacts carry curated analysis fields the tools do not
produce — a re-run refreshes the measured keys without deleting those)."""

import json
import os


def write_merged(path: str, rec: dict) -> dict:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    if os.path.exists(path):
        with open(path) as fh:
            old = json.load(fh)
        old.update(rec)
        rec = old
    with open(path, "w") as fh:
        json.dump(rec, fh, indent=2)
        fh.write("\n")
    print(json.dumps(rec, indent=2))
    print(f"wrote {path}")
    return rec
