"""Shared artifact-write helper for the perf tools: merge-preserving JSON
(the committed artifacts carry curated analysis fields the tools do not
produce — a re-run refreshes the measured keys without deleting those).

Every write also NORMALIZES the artifact (`schema_version` +
`metrics: [{name, value, unit, backend}]`, legacy keys untouched): the
measured numbers used to live only in free-form `parsed*` blocks and
`tail` strings, which made the perf trajectory machine-unreadable —
`tools/bench_trend.py` reads the normalized list, and
`tools/check_artifact.py` lints it."""

import json
import os

ARTIFACT_SCHEMA_VERSION = 1


def backend_tag(block: dict) -> str:
    """The cpu/tpu platform tag of one metric block — the partition
    bench_trend gates within (a CPU growth-container trend point must
    never gate against a chip number). Inference order: an explicit
    `backend` field ("pallas" and the on-TPU "jnp-fallback" are chip
    runs; plain "jnp" is the off-TPU path; literal platform names pass
    through), then the NS step lines' `phases` dispatch tag, then the
    TPU-only decomposition contract (null solve_ms + a
    decomposition_note = off-TPU)."""
    b = str(block.get("backend", "") or "")
    if b:
        return "cpu" if b in ("jnp", "cpu") else "tpu"
    phases = str(block.get("phases", "") or "")
    if phases:
        return "cpu" if "no TPU" in phases else "tpu"
    if block.get("decomposition_note") and block.get("solve_ms") is None:
        return "cpu"
    return "tpu"


def collect_metrics(rec: dict) -> list[dict]:
    """The normalized metric list of one artifact: every dict-valued
    block carrying {metric, value} (the bench.py JSON-line shape the
    `parsed*` keys hold) becomes one {name, value, unit, backend} entry,
    plus the `comm_hidden_fraction` block's headline number (ROADMAP
    item 2 — a HIGHER-is-better series bench_trend gates on, see its
    NAME_DIRECTIONS). Deterministic from the record alone, so re-merges
    are stable."""
    out = []
    seen = set()
    for block in rec.values():
        if not isinstance(block, dict) or "metric" not in block \
                or "value" not in block:
            continue
        name = str(block["metric"])
        if name in seen:
            continue
        seen.add(name)
        out.append({
            "name": name,
            "value": block["value"],
            "unit": block.get("unit"),
            "backend": backend_tag(block),
        })
    chf = rec.get("comm_hidden_fraction")
    # backend from the run the blocks were merged from (telemetry
    # summary), never the tpu default: the CPU smoke plane must not
    # seed a chip-gating series
    run_backend = (rec.get("telemetry_summary") or {}).get("backend")
    if isinstance(chf, dict) and isinstance(
            chf.get("hidden_fraction"), (int, float)) \
            and "comm_hidden_fraction" not in seen:
        out.append({
            "name": "comm_hidden_fraction",
            "value": chf["hidden_fraction"],
            "unit": "fraction",
            "backend": "tpu" if run_backend == "tpu" else "cpu",
        })
    fl = rec.get("fleet_summary")
    if isinstance(fl, dict) and isinstance(
            fl.get("scenarios_per_s"), (int, float)) \
            and "fleet_scenarios_per_s" not in seen:
        # the fleet throughput headline (ROADMAP item 3): a */s rate, so
        # bench_trend gates it higher-is-better by unit AND by name
        out.append({
            "name": "fleet_scenarios_per_s",
            "value": fl["scenarios_per_s"],
            "unit": "scenarios/s",
            "backend": "tpu" if run_backend == "tpu" else "cpu",
        })
    srv = rec.get("serving_summary")
    if isinstance(srv, dict):
        # the serving-v2 daemon headlines (fleet/serve.py): tenant-felt
        # latency and backlog pressure — bench_trend gates both
        # LOWER-is-better by name (NAME_DIRECTIONS)
        for name, key, unit in (
                ("fleet_p50_latency_ms", "p50_latency_ms", "ms"),
                ("fleet_queue_depth_max", "queue_depth_max",
                 "requests")):
            if isinstance(srv.get(key), (int, float)) \
                    and name not in seen:
                out.append({
                    "name": name,
                    "value": srv[key],
                    "unit": unit,
                    "backend": "tpu" if run_backend == "tpu" else "cpu",
                })
    mx = rec.get("metrics_summary")
    if isinstance(mx, dict) and "fleet_class_p95_ms" not in seen:
        # the SLO-plane tail headline (ISSUE 18): the WORST per-class
        # p95 from the folded registry histograms — one number per
        # artifact (the metrics list dedups by name), so the gate
        # watches the slowest class, not an average across classes
        class_p95 = [
            row.get("p95")
            for name, row in (mx.get("histograms") or {}).items()
            if str(name).startswith("fleet_class_latency_ms{")
            and isinstance(row, dict)
            and isinstance(row.get("p95"), (int, float))
        ]
        if class_p95:
            out.append({
                "name": "fleet_class_p95_ms",
                "value": round(max(class_p95), 3),
                "unit": "ms",
                "backend": "tpu" if run_backend == "tpu" else "cpu",
            })
    ap = rec.get("autoscale")
    if isinstance(ap, dict):
        # the autopilot headlines (ISSUE 19, fleet/autopilot.py): flap
        # count and worst breach→full-service recovery time — both
        # lower-is-better by name (bench_trend NAME_DIRECTIONS); the
        # summary folds them off the daemon's stop metrics
        for name, key, unit in (
                ("autoscale_flaps", "flaps", "transitions"),
                ("autoscale_time_to_recover_ms", "time_to_recover_ms",
                 "ms")):
            if isinstance(ap.get(key), (int, float)) \
                    and name not in seen:
                out.append({
                    "name": name,
                    "value": ap[key],
                    "unit": unit,
                    "backend": "tpu" if run_backend == "tpu" else "cpu",
                })
    slo = rec.get("slo")
    if isinstance(slo, dict) and "slo_violations" not in seen:
        # lifetime violation count across tenants (fleet/slo.py);
        # lower-is-better by name (bench_trend NAME_DIRECTIONS)
        totals = [
            row.get("violations_total", row.get("violations"))
            for row in slo.values() if isinstance(row, dict)
        ]
        nums = [v for v in totals if isinstance(v, (int, float))]
        if nums:
            out.append({
                "name": "slo_violations",
                "value": sum(nums),
                "unit": "requests",
                "backend": "tpu" if run_backend == "tpu" else "cpu",
            })
    return out


def merge_nested(old: dict, new: dict) -> dict:
    """Recursive merge: `new` wins per leaf key, but dict-valued keys merge
    key-by-key instead of being clobbered wholesale — so a re-run that
    refreshes a tool-produced nested record (e.g. a per-session block)
    keeps the curated fields an analyst added inside it (ADVICE round-5:
    the shallow dict.update lost any curated field whose top-level key
    collided with a tool key)."""
    out = dict(old)
    for k, v in new.items():
        if k in out and isinstance(out[k], dict) and isinstance(v, dict):
            out[k] = merge_nested(out[k], v)
        else:
            out[k] = v
    return out


def dist_step_decomposition(make_solver, key: str, reps: int = 3) -> dict:
    """Solve/non-solve step decomposition for a DISTRIBUTED NS-2D/3-D
    config — the mesh twin of bench.py's `_ns2d_step_line` protocol.
    `make_solver(itermax)` builds a ready dist solver (te far beyond reach
    so a chunk always runs its full CHUNK steps; eps below reach so every
    solve caps at itermax).

    step_ms comes from best-of-`reps` chunk dispatches fenced by a scalar
    readback. The solve share uses the repo's two-point differencing: a
    second build at 2×itermax isolates the pure per-iteration solve cost
    (`solve_iter_ms` = itermax × per-iteration), so the remainder
    (`nonsolve_ms` = step - solve_iter) carries the phase chain PLUS the
    per-solve envelope (layout conversions, loop setup) — exactly the
    budget the fused phase kernels and the p-layout fold move. TPU-only:
    off-TPU the timing fields stay null (XLA:CPU whole-program optimization
    makes the subtraction meaningless — the bench.py contract) and only the
    dispatch tag is recorded."""
    import time

    import jax

    from pampi_tpu.utils import dispatch, telemetry

    s = make_solver(None)  # production itermax build, records dispatch
    tag = dispatch.last(key)
    base = {"phases": tag, "steps_timed": type(s).CHUNK,
            "exchange_ms": None}
    if hasattr(s, "_halo_record") and telemetry.enabled():
        # the ROADMAP-mandated `exchange` span (serial critical-path cost
        # of one step's declared halo schedule — the comm-hidden-fraction
        # input next to the xprof device numbers); wall-clock, so it is
        # recorded on every backend (off-TPU trend-only, like all walls)
        from pampi_tpu.parallel.comm import (
            exchange_schedule_bytes,
            time_exchange_ms,
        )

        rec_h = s._halo_record()
        ex_ms = time_exchange_ms(s.comm, rec_h)
        telemetry.emit_span(f"{key}.exchange", ex_ms, path=rec_h["path"],
                            mesh=rec_h["mesh"], shard=rec_h["shard"],
                            bytes_per_step=exchange_schedule_bytes(rec_h),
                            mode="serial_probe")
        base["exchange_ms"] = round(ex_ms, 3)
    if jax.default_backend() != "tpu":
        # one key set on every path (itermax/note null rather than absent)
        # so write_merged re-runs across hosts never leave stale fields
        telemetry.emit_decomposition(key, None, None, None, phases=tag)
        return {**base, "step_ms": None, "solve_iter_ms": None,
                "nonsolve_ms": None, "itermax": None,
                "decomposition_note": "TPU-only (see tools/_artifact.py)"}

    def step_ms_of(sv):
        steps = type(sv).CHUNK
        # initial_state matches the chunk's arity (telemetry appends the
        # in-band metrics vector); the fence reads the carried loop time
        args = sv.initial_state()
        ti = len(args) - (3 if sv._metrics else 2)
        out = sv._chunk_sm(*args)
        float(out[ti])  # compile + warm; scalar readback is the fence
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            out = sv._chunk_sm(*args)
            float(out[ti])
            best = min(best, time.perf_counter() - t0)
        return best / steps * 1e3

    step_ms = step_ms_of(s)
    itermax = s.param.itermax
    step2_ms = step_ms_of(make_solver(2 * itermax))
    solve_iter_ms = step2_ms - step_ms  # itermax extra capped iterations
    # the decomposition as shared telemetry spans (no-op when unset):
    # solve here is the PER-ITERATION cost times itermax — the same
    # two-point differencing the artifact records
    telemetry.emit_decomposition(key, step_ms, solve_iter_ms,
                                 step_ms - solve_iter_ms,
                                 phases=tag, itermax=itermax)
    return {**base,
            "step_ms": round(step_ms, 3),
            "solve_iter_ms": round(solve_iter_ms, 3),
            "nonsolve_ms": round(step_ms - solve_iter_ms, 3),
            "itermax": itermax,
            "decomposition_note": None}


def write_merged(path: str, rec: dict) -> dict:
    if os.path.dirname(path):
        os.makedirs(os.path.dirname(path), exist_ok=True)
    if os.path.exists(path):
        with open(path) as fh:
            old = json.load(fh)
        rec = merge_nested(old, rec)
    # normalize on every write: schema version + the machine-readable
    # metric list (regenerated from the merged record, so curated AND
    # measured blocks both surface; legacy keys stay)
    rec["schema_version"] = ARTIFACT_SCHEMA_VERSION
    rec["metrics"] = collect_metrics(rec)
    with open(path, "w") as fh:
        json.dump(rec, fh, indent=2)
        fh.write("\n")
    print(json.dumps(rec, indent=2))
    print(f"wrote {path}")
    return rec
