"""Aggregate a PAMPI_TELEMETRY JSONL into a human-readable run report.

    python tools/telemetry_report.py run.jsonl [--merge ARTIFACT.json]

Renders the flight record (utils/telemetry.py schema): run metadata,
dispatch decisions, build/trace walls, per-chunk solver health
(residual/iterations/dt/velocity maxima, ms/step), divergence diagnostics
plus the PR 4 resilience records (rollback-recovery attempts, retry-budget
consumptions, checkpoint save/rotate/load/reject events), the shared
decomposition spans, static halo-exchange byte counts, driver solve
records, the device-time profiling plane (`xprof` records: per-scope /
per-collective / per-kernel device ms and the exchange device-vs-exposed
split), and the profiling region table. A telemetry write-failure
truncation (`finalize.dropped_records`) is surfaced loudly — a clipped
flight record must never read as a quiet run.

Dead-rank survival (schema v6): `dead`/`epoch`/`shrink` records and the
ckpt ledger events render as the coordinator section's MEMBERSHIP
subsection and summarize under `telemetry_summary.coord.membership`
(tools/check_artifact.py lints the shape; legacy artifacts pass).

Fleet runs (pampi_tpu/fleet/) add the multi-tenant dimension: chunk/
divergence/solve records carry a `scenario` id, rendered as a
per-scenario (per-tenant) table, and the scheduler's `fleet` record
(bucket modes, compile-vs-run walls, scenarios/s throughput, divergence
census) renders as the fleet section.

`--merge <path>` folds the machine-readable blocks into a
BENCH_rXX/MULTICHIP_rXX artifact via tools/_artifact.write_merged (the
merge-preserving convention): `telemetry_summary`, plus — when the run
captured them — a top-level `xprof_summary`, the `comm_hidden_fraction`
block ROADMAP item 2 is measured by (exchange device time vs its exposed
critical-path share vs the serial-probe `.exchange` span), the
`fleet_summary` block ROADMAP item 3 is measured by, the daemon's
`serving_summary`, and the serving-plane observability blocks (schema
v8): `metrics_summary` (registry snapshots folded last-per-source then
across sources), `slo` (per-tenant error-budget burn), and
`trace_decomposition` (per-stage request-latency decomposition + the
median-request waterfall whose stage sum must close on its end-to-end
latency) — tools/check_artifact.py lints all of them.
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def load(path: str) -> list[dict]:
    """Parse the JSONL; unparseable lines are reported, not fatal (a run
    killed mid-write may leave a torn last line)."""
    records = []
    with open(path) as fh:
        for n, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                print(f"warning: line {n} unparseable (torn write?)",
                      file=sys.stderr)
    return records


def _num(x) -> float:
    """Record scalars may be string-encoded non-finite floats ("nan"/"inf"
    — strict-JSON encoding, utils/telemetry._json_safe); float() restores
    them for formatting."""
    try:
        return float(x)
    except (TypeError, ValueError):
        return float("nan")


def _by_kind(records: list[dict]) -> dict[str, list[dict]]:
    out: dict[str, list[dict]] = {}
    for r in records:
        out.setdefault(r.get("kind", "?"), []).append(r)
    return out


def _strip(rec: dict, *extra: str) -> dict:
    """A record without its envelope fields (schema tag / kind / stamp),
    the shape every summary block carries — one helper so an envelope
    change lands in one place, not in a dozen hand-copies."""
    drop = ("v", "kind", "ts") + extra
    return {key: val for key, val in rec.items() if key not in drop}


def summary(records: list[dict]) -> dict:
    """The machine-readable summary block (`telemetry_summary` in merged
    artifacts; tools/check_artifact.py lints its shape)."""
    k = _by_kind(records)
    run = k.get("run", [{}])[0]
    chunks = [c for c in k.get("chunk", []) if c.get("steps")]
    # compile is in the first chunk only: steady-state ms/step excludes it
    steady = [c for c in chunks if not c.get("includes_compile")]
    last = chunks[-1] if chunks else None
    spans = {}
    for s in k.get("span", []):
        spans[s["name"]] = _strip(s, "name")
    out = {
        "schema_version": run.get("v", 1),
        "backend": run.get("backend"),
        "n_devices": run.get("n_devices"),
        "records": len(records),
        "dispatch": {d["key"]: d["value"] for d in k.get("dispatch", [])},
        "builds": {
            b.get("family", "?"): b.get("trace_wall_s")
            for b in k.get("build", [])
        },
        "chunks": {
            "count": len(chunks),
            "steps": sum(c["steps"] for c in chunks),
            "wall_s": round(sum(c["wall_s"] for c in chunks), 3),
            "ms_per_step_steady": (
                round(min(c["ms_per_step"] for c in steady), 3)
                if steady else None
            ),
            "last": None if last is None else {
                key: last.get(key)
                for key in ("nt", "t", "res", "iters", "dt",
                            "umax", "vmax", "wmax")
            },
        },
        "divergence": k.get("divergence", []) or None,
        "recoveries": [
            _strip(r)
            for r in k.get("recover", [])
        ] or None,
        "retries": [
            _strip(r)
            for r in k.get("retry", [])
        ] or None,
        "ckpt": {
            ev: sum(1 for c in k.get("ckpt", []) if c.get("event") == ev)
            for ev in ("save", "rotate", "load", "reject", "skip",
                       "elastic_save", "elastic_load",
                       "ledger_save", "ledger_restore")
        } if k.get("ckpt") else None,
        # the chunk-boundary agreement protocol's decision census
        # (schema v5; parallel/coordinator.py emits one `coord` record
        # per GLOBAL decision from rank 0) + the schema-v6 membership
        # subsection (dead-rank verdicts, shrink epochs, elastic
        # shrink-resumes) — built whenever either plane recorded
        "coord": _coord_summary(k),
        "warnings": [
            _strip(w)
            for w in k.get("warning", [])
        ] or None,
        "spans": spans or None,
        "solves": {
            "count": len(k.get("solve", [])),
            "last": (
                {key: k["solve"][-1].get(key)
                 for key in ("family", "iters", "res", "wall_s")}
                if k.get("solve") else None
            ),
        },
        "halo": [
            _strip(h)
            for h in k.get("halo", [])
        ] or None,
        "profile_regions": (
            k["finalize"][-1].get("profile_regions")
            if k.get("finalize") else None
        ),
        "dropped_records": (
            k["finalize"][-1].get("dropped_records")
            if k.get("finalize") else None
        ),
        # the xprof block deliberately does NOT ride here: --merge writes
        # it once as the top-level `xprof_summary` (the linted contract)
    }
    return out


def _coord_summary(k: dict):
    """The coordinator block of `summary`: decision census (v5) plus the
    dead-rank membership subsection (v6 — `dead`/`epoch`/`shrink`
    records). None when the run recorded neither plane, so pre-coord
    flight records keep their historical summary shape."""
    membership = None
    if k.get("dead") or k.get("epoch") or k.get("shrink"):
        membership = {
            "dead": [
                _strip(d)
                for d in k.get("dead", [])
            ] or None,
            "epochs": [
                _strip(e)
                for e in k.get("epoch", [])
            ] or None,
            "shrinks": [
                _strip(s)
                for s in k.get("shrink", [])
            ] or None,
        }
    if not k.get("coord") and membership is None:
        return None
    out = {
        "nranks": next(
            (c.get("nranks") for c in k.get("coord", [])
             if c.get("event") == "armed"), None),
        "decisions": {
            ev: n for ev in ("retry", "fallback", "rollback", "ckpt",
                             "giveup", "abort")
            if (n := sum(1 for c in k.get("coord", [])
                         if c.get("event") == ev))
        },
    }
    if membership is not None:
        out["membership"] = membership
    return out


def scenario_table(records: list[dict]) -> dict:
    """Per-scenario (per-tenant) aggregation of the scenario-tagged
    chunk/divergence records: {scenario: {chunks, steps, last_t,
    last_nt, diverged, first_bad_step}}. Empty dict when the run had no
    scenario dimension (solo runs — the pre-fleet shape)."""
    out: dict[str, dict] = {}
    for r in records:
        sid = r.get("scenario")
        if sid is None:
            continue
        row = out.setdefault(str(sid), {
            "chunks": 0, "steps": 0, "last_t": None, "last_nt": None,
            "diverged": False, "first_bad_step": None,
        })
        if r.get("kind") == "chunk":
            row["chunks"] += 1
            row["steps"] += r.get("steps") or 0
            row["last_t"] = r.get("t")
            row["last_nt"] = r.get("nt")
        elif r.get("kind") == "divergence":
            row["diverged"] = True
            row["first_bad_step"] = r.get("first_bad_step")
    return out


def fleet_summary(records: list[dict]):
    """The last `fleet` record, cleaned for the artifact (`fleet_summary`
    top-level block; tools/check_artifact.py lints it). The per-scenario
    table rides along so the artifact names every tenant served."""
    fl = [r for r in records if r.get("kind") == "fleet"]
    if not fl:
        return None
    out = _strip(fl[-1])
    table = scenario_table(records)
    if table:
        out["scenarios"] = table
    return out


def serving_summary(records: list[dict]):
    """The persistent-daemon serving block (`serving_summary` top-level
    in merged artifacts; tools/check_artifact.py lints it): the daemon's
    final `serving` stop record plus the admission/latency censuses —
    requests in, requests served/parked/deferred, swap count, the p50
    latency and max queue depth the bench_trend gate watches."""
    srv = [r for r in records if r.get("kind") == "serving"]
    if not srv:
        return None
    stop = next((r for r in reversed(srv) if r.get("event") == "stop"),
                srv[-1])
    admissions = [r for r in records if r.get("kind") == "admission"]
    lats = [r.get("ms") for r in records
            if r.get("kind") == "latency"
            and isinstance(r.get("ms"), (int, float))]
    actions: dict[str, int] = {}
    for a in admissions:
        act = str(a.get("action"))
        actions[act] = actions.get(act, 0) + 1
    out = _strip(stop, "event")
    out["requests"] = len(admissions)
    out["admission"] = actions or None
    if out.get("p50_latency_ms") is None and lats:
        # pre-stop-record flight records (or a daemon killed before
        # stop()): recompute with the daemon's own percentile formula
        # (fleet/serve._percentile — nearest-rank on the sorted list)
        vs = sorted(lats)
        out["p50_latency_ms"] = round(
            vs[min(len(vs) - 1, max(0, int(round(0.5 * (len(vs) - 1)))))],
            3)
    if out.get("max_latency_ms") is None and lats:
        out["max_latency_ms"] = round(max(lats), 3)
    out.setdefault("p50_latency_ms", None)
    out.setdefault("max_latency_ms", None)
    return out


def _label_str(name: str, labels: dict) -> str:
    if not labels:
        return str(name)
    inner = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


def metrics_summary(records: list[dict]):
    """Fold the `metrics` registry snapshots (schema v9, utils/metrics)
    into one artifact block. Snapshots are CUMULATIVE per process, so
    the fold takes the LAST snapshot per `source` (highest seq) and
    merges ACROSS sources only — the same counter/gauge/histogram fold
    `utils/metrics.merge_snapshots` gives the multi-rank `--merge`
    plane. Histograms summarize to {n, p50, p95, max} (quantiles at
    log-bucket resolution)."""
    ms = [r for r in records if r.get("kind") == "metrics"]
    if not ms:
        return None
    from pampi_tpu.utils import metrics as _mx

    last: dict[str, dict] = {}
    for r in ms:
        src = str(r.get("source"))
        if src not in last or (r.get("seq") or 0) \
                >= (last[src].get("seq") or 0):
            last[src] = r
    folded: dict = {"counters": [], "gauges": [], "histograms": []}
    for r in last.values():
        folded = _mx.merge_snapshots(
            folded, {key: r.get(key)
                     for key in ("counters", "gauges", "histograms")})
    hists = {}
    for h in folded["histograms"]:
        hists[_label_str(h["name"], h.get("labels") or {})] = {
            "n": h.get("n"),
            "p50": _mx.snapshot_quantile(h, 0.5),
            "p95": _mx.snapshot_quantile(h, 0.95),
            "max": h.get("max"),
        }
    return {
        "sources": len(last),
        "counters": {_label_str(c["name"], c.get("labels") or {}):
                     c["value"] for c in folded["counters"]},
        "gauges": {_label_str(g["name"], g.get("labels") or {}):
                   g["value"] for g in folded["gauges"]},
        "histograms": hists,
    }


def autoscale_summary(records: list[dict]):
    """The autopilot decision block (`autoscale` top-level in merged
    artifacts; tools/check_artifact.py lints it): every `autoscale`
    record the policy loop emitted — decision tally, the ordered
    non-hold transition log (heal/grow/shrink/degrade/recover/preempt/
    resume, the trajectory the chaos harness asserts on) and the final
    rung/lane posture."""
    recs = [r for r in records if r.get("kind") == "autoscale"]
    if not recs:
        return None
    decisions: dict[str, int] = {}
    for r in recs:
        d = str(r.get("decision"))
        decisions[d] = decisions.get(d, 0) + 1
    transitions = [
        {"poll": r.get("poll"), "decision": r.get("decision"),
         "rung": r.get("rung"), "rung_name": r.get("rung_name"),
         "lanes": r.get("lanes")}
        for r in recs if r.get("decision") != "hold"
    ]
    # the last policy-loop record (preempt/resume come from the
    # scheduler and carry no rung/lanes posture)
    final = next((r for r in reversed(recs)
                  if r.get("rung") is not None), recs[-1])
    # the trend-gated tallies ride the daemon's stop metrics
    # (fleet/autopilot.emit_stop_metrics) — folded here so
    # tools/_artifact.collect_metrics normalizes them off this block
    stop = {r.get("metric"): r.get("value") for r in records
            if r.get("kind") == "metric"
            and str(r.get("metric", "")).startswith("autoscale_")}
    return {
        "records": len(recs),
        "decisions": decisions,
        "transitions": transitions,
        "flaps": stop.get("autoscale_flaps"),
        "time_to_recover_ms": stop.get("autoscale_time_to_recover_ms"),
        "final": {"rung": final.get("rung"),
                  "rung_name": final.get("rung_name"),
                  "lanes": final.get("lanes"),
                  "capacity": final.get("capacity")},
    }


def slo_summary(records: list[dict]):
    """The per-tenant SLO block (`slo` top-level in merged artifacts):
    each tenant's LAST `slo` record — target, windowed requests/
    violations, lifetime violations, burn rate."""
    slos = [r for r in records if r.get("kind") == "slo"]
    if not slos:
        return None
    out: dict[str, dict] = {}
    for r in slos:  # later records overwrite: last-per-tenant wins
        out[str(r.get("tenant"))] = _strip(r, "tenant")
    return out


def trace_decomposition(records: list[dict]):
    """The per-stage latency decomposition of the request traces
    (utils/tracing, kind="trace"). Two views:

    - `stages`: population p50/p95 per critical stage (queue_wait/
      compile/execute/emit over every completed request) — the "where
      does latency go" table;
    - `p50_waterfall`: the MEDIAN request's own stage durations. This is
      the view the sums-to-e2e contract is checked on: percentiles are
      not additive (a bimodal fleet — cold-compile requests next to
      warm ones — has per-stage p50s that sum far from the e2e p50),
      but one request's stages tile its own end-to-end latency by
      construction, so the median request's waterfall IS the exact
      decomposition of the p50 latency. `p50_sum_ms` / `sum_residual`
      report the closure (tools/check_artifact.py + tools/soak.py
      assert residual <= 5%, covering rounding and any missing mark)."""
    traces = [r for r in records if r.get("kind") == "trace"]
    roots = [r for r in traces if r.get("stage") == "request"
             and r.get("status") == "ok"
             and isinstance(r.get("ms"), (int, float))]
    if not roots:
        return None
    from pampi_tpu.fleet.serve import _percentile

    by_trace: dict[str, dict] = {}
    for r in traces:
        if r.get("parent") == "request" \
                and isinstance(r.get("ms"), (int, float)):
            by_trace.setdefault(
                str(r.get("trace")), {})[str(r.get("stage"))] = r["ms"]
    stage_pop: dict[str, list] = {}
    for stages in by_trace.values():
        for stage, ms in stages.items():
            stage_pop.setdefault(stage, []).append(ms)
    # the median request: nearest-rank on the root e2e population (the
    # daemon's own percentile formula)
    ranked = sorted(roots, key=lambda r: r["ms"])
    median = ranked[min(len(ranked) - 1,
                        max(0, int(round(0.5 * (len(ranked) - 1)))))]
    waterfall = by_trace.get(str(median.get("trace")), {})
    p50_sum = round(sum(waterfall.values()), 4)
    e2e_p50 = _percentile([r["ms"] for r in roots], 0.5)
    return {
        "requests": len(roots),
        "e2e_ms": {"p50": e2e_p50,
                   "p95": _percentile([r["ms"] for r in roots], 0.95)},
        "stages": {
            stage: {"count": len(vals),
                    "p50": _percentile(vals, 0.5),
                    "p95": _percentile(vals, 0.95)}
            for stage, vals in sorted(stage_pop.items())
        },
        "p50_waterfall": {"sid": median.get("sid"),
                          "e2e_ms": median["ms"], **waterfall},
        "p50_sum_ms": p50_sum,
        "sum_residual": (round(abs(p50_sum - median["ms"])
                               / median["ms"], 6)
                         if median["ms"] else None),
    }


def xprof_summary(records: list[dict]):
    """The last captured device-trace region, cleaned for the artifact
    (`xprof_summary` top-level block; tools/check_artifact.py lints it)."""
    xs = [r for r in records if r.get("kind") == "xprof"]
    if not xs:
        return None
    return _strip(xs[-1])


def comm_hidden_fraction(records: list[dict]):
    """The ROADMAP item 2 measurement block: how much of the halo
    exchange hides behind compute. Inputs are the run's last `xprof`
    record (exchange device ms vs its exposed — critical-path — share,
    from the device trace) and the last `<family>.exchange` span (the
    serial probe: what the schedule costs when nothing overlaps it).
    hidden_fraction = 1 - exposed/device; today's serial schedule
    measures ~0 — the comm/compute-overlap refactor is judged by how far
    it rises. In wall-clock (degraded) mode only the serial probe
    exists: device == exposed == serial, hidden 0."""
    x = xprof_summary(records) or {}
    spans = [s for s in records if s.get("kind") == "span"
             and str(s.get("name", "")).endswith(".exchange")]
    serial = spans[-1].get("ms") if spans else None
    dev = x.get("exchange_device_ms")
    exp = x.get("exchange_exposed_ms")
    steps = x.get("steps")
    if not dev and serial is None:
        return None
    if x.get("mode") == "trace":
        # trace mode: device/exposed are TOTALS over the captured region,
        # normalized per step here; the serial span is per-step already.
        # A trace that attributed ZERO exchange time stays mode "trace"
        # with hidden None — an attribution failure (scope naming drift,
        # a single-device capture) must surface as nulls, never be
        # dressed up as a clean degraded measurement.
        def per_step(v):
            return round(v / steps, 4) if (v is not None and steps) else None

        dev_ps, exp_ps = per_step(dev), per_step(exp)
        hidden = (round(max(0.0, 1.0 - (exp or 0.0) / dev), 4)
                  if dev else None)
        mode = "trace"
    else:
        # degraded: only the serial probe exists — fully exposed
        dev_ps = exp_ps = serial
        hidden, mode = 0.0, "wallclock"
    return {
        "mode": mode,
        "steps": steps,
        "exchange_device_ms_per_step": dev_ps,
        "exchange_exposed_ms_per_step": exp_ps,
        "exchange_serial_ms_per_step": serial,
        "hidden_fraction": hidden,
    }


def render(records: list[dict]) -> str:
    """The human-readable report."""
    k = _by_kind(records)
    lines: list[str] = []
    add = lines.append
    run = k.get("run", [{}])[0]
    add("== run ==")
    add(f"  backend={run.get('backend')} devices={run.get('n_devices')} "
        f"processes={run.get('n_processes')} jax={run.get('jax_version')}")
    for key in ("tool", "config", "problem", "grid", "solver", "dtype"):
        if key in run:
            add(f"  {key}={run[key]}")

    if k.get("dispatch"):
        add("== dispatch decisions ==")
        seen = {}
        for d in k["dispatch"]:
            seen[d["key"]] = d["value"]
        for key, val in seen.items():
            add(f"  {key:<24} {val}")

    if k.get("build"):
        add("== builds (trace/build wall) ==")
        for b in k["build"]:
            extra = f" mesh={b['mesh']}" if "mesh" in b else ""
            add(f"  {b.get('family', '?'):<12} {b.get('trace_wall_s')}s "
                f"grid={b.get('grid')}{extra} phases={b.get('phases')}")

    chunks = k.get("chunk", [])
    if chunks:
        add("== chunks (per host sync; first is compile-inclusive) ==")
        add(f"  {'nt':>8} {'steps':>6} {'ms/step':>10} {'res':>12}"
            f" {'iters':>6} {'dt':>12} {'umax':>10} {'vmax':>10} {'wmax':>10}")
        for c in chunks:
            ms = c.get("ms_per_step")
            add(f"  {c.get('nt'):>8} {str(c.get('steps')):>6} "
                f"{'-' if ms is None else format(ms, '10.3f')} "
                f"{_num(c.get('res')):>12.4e} {c.get('iters'):>6} "
                f"{_num(c.get('dt')):>12.4e} {_num(c.get('umax')):>10.4g} "
                f"{_num(c.get('vmax')):>10.4g} {_num(c.get('wmax')):>10.4g}"
                + ("  [compile]" if c.get("includes_compile") else ""))

    scen = scenario_table(records)
    if scen:
        add("== scenarios (per tenant) ==")
        add(f"  {'scenario':<20} {'chunks':>7} {'steps':>7} {'last t':>12} "
            f"{'last nt':>8}  status")
        for sid, row in scen.items():
            status = ("DIVERGED @ step %s" % row["first_bad_step"]
                      if row["diverged"] else "ok")
            add(f"  {sid:<20} {row['chunks']:>7} {row['steps']:>7} "
                f"{_num(row['last_t']):>12.6g} {str(row['last_nt']):>8}  "
                f"{status}")

    for f in k.get("fleet", []):
        add("== fleet ==")
        add(f"  scenarios={f.get('n_scenarios')} "
            f"throughput={f.get('scenarios_per_s')} scenarios/s "
            f"diverged={((f.get('divergence_census') or {}).get('diverged'))}")
        for b in f.get("buckets") or []:
            swaps = (f" swaps={b['swaps']}" if "swaps" in b else "")
            add(f"  bucket {b.get('bucket'):<32} mode={b.get('mode'):<5} "
                f"lanes={b.get('lanes'):>3} "
                f"compile={b.get('compile_wall_s')}s "
                f"run={b.get('run_wall_s')}s{swaps}")

    srv = serving_summary(records)
    if srv is not None:
        add("== serving (persistent daemon) ==")
        add(f"  polls={srv.get('polls')} served={srv.get('served')} "
            f"parked={srv.get('parked')} deferred={srv.get('deferred')} "
            f"swaps={srv.get('swaps')}")
        add(f"  queue_depth_max={srv.get('queue_depth_max')} "
            f"p50_latency_ms={srv.get('p50_latency_ms')} "
            f"throughput={srv.get('scenarios_per_s')} scenarios/s")
        adm = srv.get("admission")
        if adm:
            add("  admission: " + " ".join(
                f"{a}={n}" for a, n in sorted(adm.items())))

    dec = trace_decomposition(records)
    if dec is not None:
        add("== request traces (per-stage latency decomposition) ==")
        add(f"  requests={dec['requests']} "
            f"e2e p50={dec['e2e_ms']['p50']} ms "
            f"p95={dec['e2e_ms']['p95']} ms")
        add(f"  {'stage':<14} {'count':>6} {'p50 ms':>12} {'p95 ms':>12}")
        for stage, row in dec["stages"].items():
            add(f"  {stage:<14} {row['count']:>6} "
                f"{_num(row['p50']):>12.3f} {_num(row['p95']):>12.3f}")
        wf = dec["p50_waterfall"]
        add(f"  -- median request waterfall ({wf.get('sid')}, "
            f"e2e {wf.get('e2e_ms')} ms; stage sum {dec['p50_sum_ms']} "
            f"ms, residual {dec['sum_residual']}) --")
        offset = 0.0
        for stage in ("queue_wait", "compile", "execute", "emit"):
            ms = wf.get(stage)
            if ms is None:
                continue
            add(f"    {stage:<12} [{offset:>10.3f} .. "
                f"{offset + ms:>10.3f}] {ms:>10.3f} ms")
            offset += ms

    mx = metrics_summary(records)
    if mx is not None:
        add("== metrics registry (folded snapshots) ==")
        add(f"  sources={mx['sources']}")
        for name, val in sorted(mx["counters"].items()):
            add(f"  counter    {name:<52} {val}")
        for name, val in sorted(mx["gauges"].items()):
            add(f"  gauge      {name:<52} {val}")
        for name, row in sorted(mx["histograms"].items()):
            add(f"  histogram  {name:<52} n={row['n']} "
                f"p50={row['p50']} p95={row['p95']} max={row['max']}")

    asc = autoscale_summary(records)
    if asc is not None:
        add("== autopilot (self-healing elastic control plane) ==")
        add("  decisions: " + " ".join(
            f"{d}={n}" for d, n in sorted(asc["decisions"].items())))
        fin = asc["final"]
        add(f"  final: rung={fin.get('rung')} "
            f"({fin.get('rung_name')}) lanes={fin.get('lanes')} "
            f"capacity={fin.get('capacity')}")
        for t in asc["transitions"]:
            # scheduler-side moves (preempt/resume) carry no poll/rung
            def _c(v):
                return "-" if v is None else v
            add(f"  poll {str(_c(t.get('poll'))):>4}  "
                f"{str(t.get('decision')):<10} "
                f"rung={_c(t.get('rung'))} lanes={_c(t.get('lanes'))}")

    slo = slo_summary(records)
    if slo is not None:
        add("== tenant SLOs (sliding-window error budget) ==")
        add(f"  {'tenant':<16} {'target ms':>10} {'requests':>9} "
            f"{'violations':>11} {'burn':>8}")
        for tenant, row in sorted(slo.items()):
            add(f"  {tenant:<16} {_num(row.get('target_ms')):>10.3f} "
                f"{row.get('requests'):>9} {row.get('violations'):>11} "
                f"{_num(row.get('burn_rate')):>8.2f}"
                + ("  BURN ALERT" if _num(row.get("burn_rate")) > 2
                   else ""))

    for d in k.get("divergence", []):
        add("== DIVERGENCE ==")
        add(f"  {d.get('family')}: state went non-finite at step "
            f"{d.get('first_bad_step')} (last good step "
            f"{d.get('last_good_step')})"
            if "first_bad_step" in d else
            f"  {d.get('family')}: non-finite residual {d.get('res')}")

    if k.get("coord") or k.get("dead") or k.get("epoch") or k.get("shrink"):
        add("== coordinator (agreed global decisions) ==")
        for c in k.get("coord", []):
            ev = c.get("event")
            if ev == "armed":
                add(f"  armed: {c.get('mode')} nranks={c.get('nranks')} "
                    f"(family {c.get('family')})")
                continue
            detail = {key: val for key, val in c.items()
                      if key not in ("v", "kind", "ts", "event",
                                     "boundary", "family")}
            add(f"  boundary {str(c.get('boundary')):>5}  {ev:<9} {detail}")
        if k.get("dead") or k.get("epoch") or k.get("shrink"):
            add("  -- membership (dead ranks / shrink epochs) --")
            for d in k.get("dead", []):
                ranks = d.get("ranks")
                add(f"  DEAD rank(s) {ranks if ranks else '(unattributed)'}"
                    f" at boundary {d.get('boundary')} -> epoch "
                    f"{d.get('epoch')} (watchdog {d.get('watchdog_s')}s,"
                    f" {d.get('nranks')} rank(s) before)")
            for e in k.get("epoch", []):
                add(f"  epoch {e.get('epoch')}: {e.get('nranks')} "
                    f"survivor(s) {e.get('survivors')}")
            for s in k.get("shrink", []):
                add(f"  shrink-resume [{s.get('family')}] on "
                    f"{s.get('survivors')} device(s) from generation "
                    f"{s.get('generation')} (t={_num(s.get('t')):.6g} "
                    f"nt={s.get('nt')}, dead {s.get('dead')})")

    if k.get("warning"):
        add("== warnings (degraded-but-proceeding subsystems) ==")
        for w in k["warning"]:
            add(f"  {w.get('component', '?'):<12} {w.get('reason')}")

    if k.get("recover"):
        add("== recovery (divergence rollback) ==")
        for r in k["recover"]:
            if r.get("gave_up"):
                add(f"  attempt {r.get('attempt')}: GAVE UP "
                    f"({r.get('reason')})")
            else:
                add(f"  attempt {r.get('attempt')}: rolled back to "
                    f"t={_num(r.get('t')):.6g} (step {r.get('nt')}, "
                    f"{r.get('source')}) dt_scale={r.get('dt_scale')}")

    if k.get("retry"):
        add("== retries (budget consumptions) ==")
        for r in k["retry"]:
            extra = (f" action={r['action']}" if "action" in r else
                     f" budget_left={r.get('budget_left')}")
            add(f"  {r.get('fault'):<10}{extra}")

    if k.get("ckpt"):
        add("== checkpoints ==")
        for c in k["ckpt"]:
            where = (f" t={_num(c.get('t')):.6g} nt={c.get('nt')}"
                     if "nt" in c else "")
            add(f"  {c.get('event'):<8} {c.get('path')}{where}"
                + (f"  [{c.get('generation')}]" if "generation" in c else "")
                + (f"  error={c.get('error')}" if "error" in c else ""))

    if k.get("solve"):
        add("== driver solves ==")
        for s in k["solve"]:
            add(f"  {s.get('family'):<14} it={s.get('iters'):>6} "
                f"res={_num(s.get('res')):.4e} wall={s.get('wall_s')}s")

    if k.get("span"):
        add("== spans ==")
        for s in k["span"]:
            meta = {key: val for key, val in s.items()
                    if key not in ("v", "kind", "ts", "name", "ms")}
            add(f"  {s['name']:<40} "
                f"{'-' if s.get('ms') is None else format(s['ms'], '10.3f')}"
                f" ms  {meta if meta else ''}")

    if k.get("halo"):
        add("== halo exchange (static per-shard) ==")
        for h in k["halo"]:
            tiers = h.get("tier_map") or {}
            multi = len(set(tiers.values())) > 1
            add(f"  {h.get('family'):<12} mesh={h.get('mesh')} "
                f"shard={h.get('shard')} path={h.get('path')} "
                f"depth1={h.get('exchange_bytes_depth1')}B"
                + (f" deep(H={h.get('deep_halo')})="
                   f"{h.get('deep_exchange_bytes')}B"
                   if h.get("deep_halo") else "")
                + f" per-step={h.get('exchanges_per_step')}"
                + (f" tiers={tiers} dcn={h.get('dcn_exchange_bytes')}B"
                   if multi or h.get("dcn_exchange_bytes") else ""))

    if k.get("xprof"):
        add("== device trace (xprof) ==")
        for x in k["xprof"]:
            add(f"  region={x.get('region')} mode={x.get('mode')} "
                f"steps={x.get('steps')} wall={x.get('wall_ms')}ms "
                f"tracks={x.get('tracks')} busy={x.get('busy_ms')}ms "
                f"idle={x.get('idle_ms')}ms")
            for title, block in (("scopes", x.get("scopes")),
                                 ("collectives", x.get("collectives")),
                                 ("kernels", x.get("kernels"))):
                if not block:
                    continue
                add(f"  -- {title} --")
                for name, ms in sorted(block.items(),
                                       key=lambda kv: -_num(kv[1])):
                    add(f"    {name:<44} {_num(ms):>10.3f} ms")
        chf = comm_hidden_fraction(records)
        if chf:
            add("== comm-hidden fraction ==")
            add(f"  mode={chf['mode']} "
                f"device={chf['exchange_device_ms_per_step']} ms/step "
                f"exposed={chf['exchange_exposed_ms_per_step']} ms/step "
                f"serial-probe={chf['exchange_serial_ms_per_step']} ms "
                f"hidden={chf['hidden_fraction']}")

    fin = k["finalize"][-1] if k.get("finalize") else {}
    if fin.get("dropped_records"):
        add("== TRUNCATED FLIGHT RECORD ==")
        add(f"  {fin['dropped_records']} record(s) dropped by telemetry "
            "write failures — this run's record is incomplete, not quiet")

    prof = (k["finalize"][-1].get("profile_regions")
            if k.get("finalize") else None)
    if prof:
        add("== profiling regions ==")
        add(f"  {'region':<24} {'calls':>6} {'wall_s':>10} {'device_s':>10}")
        for name, row in sorted(
            prof.items(), key=lambda kv: -(kv[1].get("wall_s") or 0)
        ):
            add(f"  {name:<24} {row.get('calls'):>6} "
                f"{str(row.get('wall_s')):>10} {str(row.get('device_s')):>10}")
    return "\n".join(lines) + "\n"


def main(argv: list[str]) -> int:
    if len(argv) < 2:
        print(__doc__.strip(), file=sys.stderr)
        return 1
    path = argv[1]
    merge_to = None
    if "--merge" in argv:
        i = argv.index("--merge")
        if i + 1 >= len(argv):
            print("--merge needs an artifact path", file=sys.stderr)
            return 1
        merge_to = argv[i + 1]
    records = load(path)
    if not records:
        print(f"no records in {path}", file=sys.stderr)
        return 1
    sys.stdout.write(render(records))
    if merge_to:
        from tools._artifact import write_merged

        block = {"telemetry_summary": summary(records)}
        xp = xprof_summary(records)
        if xp is not None:
            block["xprof_summary"] = xp
        chf = comm_hidden_fraction(records)
        if chf is not None:
            block["comm_hidden_fraction"] = chf
        fl = fleet_summary(records)
        if fl is not None:
            block["fleet_summary"] = fl
        srv = serving_summary(records)
        if srv is not None:
            block["serving_summary"] = srv
        mx = metrics_summary(records)
        if mx is not None:
            block["metrics_summary"] = mx
        slo = slo_summary(records)
        if slo is not None:
            block["slo"] = slo
        asc = autoscale_summary(records)
        if asc is not None:
            block["autoscale"] = asc
        dec = trace_decomposition(records)
        if dec is not None:
            block["trace_decomposition"] = dec
        write_merged(merge_to, block)
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
