"""Profile smoke: a tiny instrumented dist-NS run + trace ingestion on
whatever backend this host has (make profile-smoke — CPU-safe).

    python tools/profile_smoke.py [outdir]

Arms PAMPI_TELEMETRY + PAMPI_XPROF (defaults under results/profile_smoke/)
and drives a 16² NS2D dist chunk loop on the OVERLAPPED schedule
(`tpu_overlap on` + forced fused kernels, interpret mode off-TPU — one
instrumented run: the CPU profiler collects one session per process, so
the run that matters is the one captured), then renders the resulting
flight record: proving the whole device-time observability plane
end-to-end (trace capture, trace-event ingestion via utils/xprof, the
`exchange` span, the `xprof` record, the comm-hidden-fraction block)
AND the overlap schedule itself (the traced chunk posts the deep
exchange double-buffered: a prologue exchange precedes the loop and no
same-iteration kernel consumes the ppermute results —
`analysis/commcheck.overlap_schedule_violations`), before any TPU time
is spent. Exit 1 if the run produced no xprof record, no exchange span,
or a serialized overlap schedule (the plane or the overlap is broken,
not merely quiet). The measured hidden fraction stays ~0 here — CPU
thunks serialize regardless; the schedule's >0 CAPABILITY is what the
structural check pins, the real number belongs to the on-chip campaign.
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# CPU-stable smoke environment: must precede any jax import (the
# tools/lint.py convention); a TPU image just keeps its own backend
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")


def main(argv: list[str]) -> int:
    outdir = argv[1] if len(argv) > 1 else os.path.join(
        REPO, "results", "profile_smoke")
    os.makedirs(outdir, exist_ok=True)
    jsonl = os.path.join(outdir, "run.jsonl")
    if os.path.exists(jsonl):
        os.remove(jsonl)
    os.environ["PAMPI_TELEMETRY"] = jsonl
    os.environ["PAMPI_XPROF"] = os.path.join(outdir, "trace")

    from pampi_tpu.models.ns2d_dist import NS2DDistSolver
    from pampi_tpu.parallel.comm import CartComm
    from pampi_tpu.utils import dispatch, telemetry as tm
    from pampi_tpu.utils.params import Parameter

    tm.reset()
    tm.start_run(tool="profile_smoke")
    # the FULL item-3 schedule: overlapped fused step + grid-restricted
    # PRE halves (forced — the structural/smoke mode) + the jnp RB-SOR
    # solve so the SPLIT sweep loop dispatches (a pallas solve keeps
    # serial sweeps), on a tiered mesh so the per-tier census and the
    # dcn_exchange_bytes metric land end-to-end
    param = Parameter(name="dcavity", imax=16, jmax=16, re=10.0, te=0.02,
                      tau=0.5, itermax=10, eps=1e-4, omg=1.7, gamma=0.9,
                      tpu_fuse_phases="on", tpu_overlap="on",
                      tpu_overlap_restrict="on", tpu_solver="sor",
                      tpu_mesh_tiers="i=dcn")
    s = NS2DDistSolver(param, CartComm(ndims=2, dims=(2, 2),
                                       tiers=param.tpu_mesh_tiers))
    # compile OUTSIDE the capture (without executing the chunk): the
    # interpret-mode kernel build is Python-heavy enough to flood the
    # profiler's event cap and crowd out the execution events the
    # ingestion aggregates
    s._chunk_sm.lower(*s.initial_state()).compile()
    s.run(progress=False)

    # the grid-restriction accounting at the PRODUCTION geometry
    # (northstar 4096² on 8 ranks) — pure host math (the region plan is
    # static), recorded as a `pre_grid_cells` metric in the smoke
    # artifact: the banded halves must sweep strictly fewer cells than
    # the two full write-gated sweeps they replace. The 16² run above
    # is banding-DEGENERATE (one row block — equal, never more); the
    # win lives at grids with multiple row blocks.
    from pampi_tpu.ops import ns2d_fused as nf
    from pampi_tpu.parallel import overlap as ovl

    jl4, il4 = 4096 // 8, 4096
    br4, _h4, wp4, nb4 = nf.fused_deep_layout_2d(
        jl4, il4, "float64", nf.FUSE_DEEP_HALO - 1)
    plan4096 = ovl.region_plan((jl4, il4), nf.OVERLAP_RIM,
                               nf.FUSE_DEEP_HALO - 1, br4, nb4, wp4,
                               (True, False))
    if plan4096 is not None:
        tm.emit("metric", metric="pre_grid_cells",
                value=plan4096["cells"], unit="cells",
                geometry="4096x4096@(8,1)",
                full=plan4096["cells_full"])
    tm.finalize()

    from pampi_tpu.analysis.commcheck import (
        census_tiers,
        overlap_schedule_violations,
    )
    from pampi_tpu.analysis.jaxprcheck import trace_chunk

    jx = trace_chunk(s)
    # the combined proof: double-buffered deep exchange AND split solve
    # sweeps (sweeps=True is the ISSUE 13 sweep-loop mode)
    sched_errs = overlap_schedule_violations(
        jx, s._halo_record(), sweeps=True)
    tiers = census_tiers(jx.jaxpr, s.comm.tiers)

    from tools import telemetry_report as tr

    records = tr.load(jsonl)
    sys.stdout.write(tr.render(records))
    kinds = {r.get("kind") for r in records}
    spans = [r for r in records if r.get("kind") == "span"
             and str(r.get("name", "")).endswith(".exchange")]
    chf = tr.comm_hidden_fraction(records)
    rec = s._halo_record()
    print(f"\nsmoke: nt={s.nt} kinds={sorted(kinds)}")
    print(f"smoke: comm_hidden_fraction = {json.dumps(chf)}")
    print(f"smoke: overlap dispatch = {rec.get('overlap')} "
          f"path={rec.get('path')} "
          f"grid={dispatch.last('overlap_grid_ns2d_dist')} "
          f"sweeps={dispatch.last('sweep_split_ns2d_dist')}")
    print(f"smoke: pre_grid_cells = {rec.get('pre_grid_cells')} "
          f"(2x full sweep = {rec.get('pre_grid_cells_full')})")
    print("smoke: per-tier census = "
          + json.dumps({k: {"ppermute": v["ppermute"], "bytes": v["bytes"]}
                        for k, v in sorted(tiers.items())}))
    print(f"smoke: tier_map = {rec.get('tier_map')} "
          f"dcn_exchange_bytes = {rec.get('dcn_exchange_bytes')}")
    if "xprof" not in kinds:
        print("FAIL: no xprof record (capture or ingestion broken)",
              file=sys.stderr)
        return 1
    if not spans:
        print("FAIL: no .exchange span", file=sys.stderr)
        return 1
    if sched_errs:
        for e in sched_errs:
            print(f"FAIL overlap schedule: {e}", file=sys.stderr)
        return 1
    if not (dispatch.last("sweep_split_ns2d_dist") or "").startswith(
            "split"):
        print("FAIL: the solve sweeps did not dispatch split",
              file=sys.stderr)
        return 1
    if "dcn" not in tiers or tiers["dcn"]["bytes"] <= 0:
        print("FAIL: per-tier census carries no DCN traffic on the "
              "tiered mesh", file=sys.stderr)
        return 1
    if not rec.get("dcn_exchange_bytes"):
        print("FAIL: halo record carries no dcn_exchange_bytes",
              file=sys.stderr)
        return 1
    if not rec.get("pre_grid_cells") or rec["pre_grid_cells"] > \
            rec.get("pre_grid_cells_full", 0):
        print("FAIL: restricted pre_grid_cells missing or above the "
              "2x full-sweep count", file=sys.stderr)
        return 1
    if plan4096 is None or not plan4096["win"]:
        print("FAIL: the banded region plan does not beat the 2x full "
              "sweep at the production 4096^2 geometry", file=sys.stderr)
        return 1
    print(f"smoke: pre_grid_cells@4096x4096(8,1) = {plan4096['cells']} "
          f"< {plan4096['cells_full']} (2x full sweep; "
          f"{plan4096['cells'] / plan4096['cells_full']:.2f}x)")
    print("smoke: overlap schedule double-buffered AND solve sweeps "
          "split in the traced chunk (every exchange posted before the "
          "compute that hides it)")
    print(f"smoke ok -> {jsonl}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
