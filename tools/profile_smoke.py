"""Profile smoke: a tiny instrumented dist-NS run + trace ingestion on
whatever backend this host has (make profile-smoke — CPU-safe).

    python tools/profile_smoke.py [outdir]

Arms PAMPI_TELEMETRY + PAMPI_XPROF (defaults under results/profile_smoke/)
and drives a 16² NS2D dist chunk loop on the OVERLAPPED schedule
(`tpu_overlap on` + forced fused kernels, interpret mode off-TPU — one
instrumented run: the CPU profiler collects one session per process, so
the run that matters is the one captured), then renders the resulting
flight record: proving the whole device-time observability plane
end-to-end (trace capture, trace-event ingestion via utils/xprof, the
`exchange` span, the `xprof` record, the comm-hidden-fraction block)
AND the overlap schedule itself (the traced chunk posts the deep
exchange double-buffered: a prologue exchange precedes the loop and no
same-iteration kernel consumes the ppermute results —
`analysis/commcheck.overlap_schedule_violations`), before any TPU time
is spent. Exit 1 if the run produced no xprof record, no exchange span,
or a serialized overlap schedule (the plane or the overlap is broken,
not merely quiet). The measured hidden fraction stays ~0 here — CPU
thunks serialize regardless; the schedule's >0 CAPABILITY is what the
structural check pins, the real number belongs to the on-chip campaign.
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# CPU-stable smoke environment: must precede any jax import (the
# tools/lint.py convention); a TPU image just keeps its own backend
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")


def main(argv: list[str]) -> int:
    outdir = argv[1] if len(argv) > 1 else os.path.join(
        REPO, "results", "profile_smoke")
    os.makedirs(outdir, exist_ok=True)
    jsonl = os.path.join(outdir, "run.jsonl")
    if os.path.exists(jsonl):
        os.remove(jsonl)
    os.environ["PAMPI_TELEMETRY"] = jsonl
    os.environ["PAMPI_XPROF"] = os.path.join(outdir, "trace")

    from pampi_tpu.models.ns2d_dist import NS2DDistSolver
    from pampi_tpu.parallel.comm import CartComm
    from pampi_tpu.utils import telemetry as tm
    from pampi_tpu.utils.params import Parameter

    tm.reset()
    tm.start_run(tool="profile_smoke")
    param = Parameter(name="dcavity", imax=16, jmax=16, re=10.0, te=0.02,
                      tau=0.5, itermax=10, eps=1e-4, omg=1.7, gamma=0.9,
                      tpu_fuse_phases="on", tpu_overlap="on",
                      tpu_sor_layout="checkerboard")
    s = NS2DDistSolver(param, CartComm(ndims=2, dims=(2, 2)))
    # compile OUTSIDE the capture (without executing the chunk): the
    # interpret-mode kernel build is Python-heavy enough to flood the
    # profiler's event cap and crowd out the execution events the
    # ingestion aggregates
    s._chunk_sm.lower(*s.initial_state()).compile()
    s.run(progress=False)
    tm.finalize()

    from pampi_tpu.analysis.commcheck import overlap_schedule_violations
    from pampi_tpu.analysis.jaxprcheck import trace_chunk

    sched_errs = overlap_schedule_violations(
        trace_chunk(s), s._halo_record())

    from tools import telemetry_report as tr

    records = tr.load(jsonl)
    sys.stdout.write(tr.render(records))
    kinds = {r.get("kind") for r in records}
    spans = [r for r in records if r.get("kind") == "span"
             and str(r.get("name", "")).endswith(".exchange")]
    chf = tr.comm_hidden_fraction(records)
    print(f"\nsmoke: nt={s.nt} kinds={sorted(kinds)}")
    print(f"smoke: comm_hidden_fraction = {json.dumps(chf)}")
    print("smoke: overlap dispatch = "
          f"{s._halo_record().get('overlap')} "
          f"path={s._halo_record().get('path')}")
    if "xprof" not in kinds:
        print("FAIL: no xprof record (capture or ingestion broken)",
              file=sys.stderr)
        return 1
    if not spans:
        print("FAIL: no .exchange span", file=sys.stderr)
        return 1
    if sched_errs:
        for e in sched_errs:
            print(f"FAIL overlap schedule: {e}", file=sys.stderr)
        return 1
    print("smoke: overlap schedule double-buffered in the traced chunk "
          "(exchange posted before the compute that hides it)")
    print(f"smoke ok -> {jsonl}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
