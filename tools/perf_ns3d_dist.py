"""Decompose the NS-3D DISTRIBUTED step cost at 128^3 on the real chip.

Round-3 record was distributed 81.4 ms/step vs single-device 47.5 on a
(1,1,1) mesh shard. This tool's measurements located the cost in the octant
kernel's stored CA halos (2n planes on ALL axes even when the mesh axis has
size 1 — +25% window cells) and in runtime-qoff masks; the round-4 per-axis
deep-halo layout (parallel/octants_dist.OGeom.d) closed the gap to parity.

Modes (second argv word):
  full      chunk-vs-chunk + component timings        (default)
  envelope  itermax sweep: step-minus-solve envelope  (fixed-depth solves)
  solve     settled-state solve-vs-solve with iteration counts + field diff

Run on TPU: python tools/perf_ns3d_dist.py [chunk_steps] [mode]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from pampi_tpu.models.ns3d import NS3DSolver, make_pressure_solve_3d
from pampi_tpu.models.ns3d_dist import NS3DDistSolver
from pampi_tpu.ops import ns3d as ops
from pampi_tpu.parallel import octants_dist as od
from pampi_tpu.parallel.comm import (
    CartComm, get_offsets, halo_exchange, reduction,
)
from pampi_tpu.utils.params import Parameter

STEPS = int(sys.argv[1]) if len(sys.argv) > 1 else 32
MODE = sys.argv[2] if len(sys.argv) > 2 else "full"
DT = jnp.float32

from pampi_tpu.utils import xlacache  # noqa: E402

xlacache.enable()  # the big dist solver builds become disk loads


def bench(fn, *args, reps=3):
    out = fn(*args)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
    return best, out


def make_param(itermax=1000, eps=1e-3):
    param = Parameter()
    param.name = "dcavity3d"
    param.imax = param.jmax = param.kmax = 128
    param.xlength = param.ylength = param.zlength = 1.0
    param.re = 1000.0
    param.te = 1e9  # never stop inside the chunk
    param.tau = 0.5
    param.eps = eps
    param.itermax = itermax
    param.omg = 1.8
    param.tpu_dtype = "float32"
    return param


T0 = jnp.asarray(0.0, jnp.float32)
NT0 = jnp.asarray(0, jnp.int32)


def dist_chunk_msstep(param, comm, settle=2):
    d = NS3DDistSolver(param, comm=comm, dtype=DT)
    d.CHUNK = STEPS
    d._build()
    # initial_state matches the chunk's arity (telemetry appends the
    # in-band metrics vector); the u/v/w/p it carries ARE _init_sm's
    state = d.initial_state()
    for _ in range(settle):
        state = d._chunk_sm(*state)
    jax.block_until_ready(state)
    tsec, s2 = bench(d._chunk_sm, *state)
    return tsec * 1e3 / max(int(s2[5]) - int(state[5]), 1)


def single_chunk_msstep(param, settle=2):
    s = NS3DSolver(param, dtype=DT)
    s.CHUNK = STEPS
    s._chunk_fn = jax.jit(s._build_chunk())
    state = s.initial_state()
    for _ in range(settle):
        state = s._chunk_fn(*state)
    jax.block_until_ready(state)
    tsec, s2 = bench(s._chunk_fn, *state)
    return tsec * 1e3 / max(int(s2[5]) - int(state[5]), 1)


def build_ogeom(param, comm, d):
    kl, jl, il = d.kl, d.jl, d.il
    n_o = od.odist_clamp(
        max(param.tpu_ca_inner, param.tpu_sor_inner), kl, jl, il, comm.dims
    )
    return n_o, od.make_ogeom(param.kmax, param.jmax, param.imax,
                              kl, jl, il, n_o, DT, dims=comm.dims)


def settled_solve_inputs(param):
    """64 settled steps on the single-device solver, then the (p, rhs) that
    the NEXT pressure solve would see."""
    s = NS3DSolver(param, dtype=DT)
    s.CHUNK = 32
    s._chunk_fn = jax.jit(s._build_chunk())
    st = s.initial_state()
    for _ in range(2):
        st = s._chunk_fn(*st)
    jax.block_until_ready(st)
    g = s.grid
    bcs = {"top": param.bcTop, "bottom": param.bcBottom,
           "left": param.bcLeft, "right": param.bcRight,
           "front": param.bcFront, "back": param.bcBack}

    @jax.jit
    def nsi(u, v, w, p):
        dt = ops.compute_timestep_3d(
            u, v, w, jnp.asarray(s.dt_bound, DT), g.dx, g.dy, g.dz,
            param.tau)
        u, v, w = ops.set_boundary_conditions_3d(u, v, w, bcs)
        u = ops.set_special_bc_dcavity_3d(u)
        f, g_, h = ops.compute_fgh(u, v, w, dt, param.re, param.gx,
                                   param.gy, param.gz, param.gamma,
                                   g.dx, g.dy, g.dz)
        return p, ops.compute_rhs(f, g_, h, dt, g.dx, g.dy, g.dz)

    p0, rhs0 = nsi(st[0], st[1], st[2], st[3])
    jax.block_until_ready((p0, rhs0))
    return s, p0, rhs0


if MODE == "full":
    param = make_param()
    comm = CartComm(ndims=3)
    print(f"mesh dims: {comm.dims}")
    dist_ms = dist_chunk_msstep(param, comm)
    single_ms = single_chunk_msstep(param)
    print(f"dist chunk:   {dist_ms:7.2f} ms/step")
    print(f"single chunk: {single_ms:7.2f} ms/step")

    # the committed-artifact record (VERDICT r4 item 6: the 45.5-vs-45.3
    # parity number had no results/ file)
    import os

    from tools._artifact import write_merged

    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "results", "ns3d_dist_parity.json")
    write_merged(out, {
        "artifact": "ns3d_dist_parity",
        "config": f"dcavity3d 128^3 f32, Re=1000, eps=1e-3, itermax=1000, "
                  f"one shard of a {comm.dims} mesh, {STEPS} steps/chunk",
        "protocol": "settled 2 chunks, chunk-vs-chunk best-of-3 "
                    "(tools/perf_ns3d_dist.py full mode)",
        "backend": jax.default_backend(),
        "dist_ms_per_step": round(dist_ms, 2),
        "single_ms_per_step": round(single_ms, 2),
        "ratio": round(dist_ms / single_ms, 3),
    })

    dsolver = NS3DDistSolver(param, comm=comm, dtype=DT)
    n_o, og = build_ogeom(param, comm, dsolver)
    print(f"ogeom: n={og.n} d={og.d} bk={og.bk} "
          f"stored=({og.sp},{og.jp2},{og.ip2})")
    spec = P("k", "j", "i")
    pz = dsolver._init_sm()[3]

    def pack_unpack(pext):
        return od.unpack_o_to_ext(od.pack_ext_to_o(pext, og), og)

    pu = jax.jit(comm.shard_map(pack_unpack, in_specs=(spec,),
                                out_specs=spec))
    tsec, _ = bench(pu, pz)
    print(f"pack+unpack roundtrip (one small dispatch; tunnel-latency "
          f"dominated): {tsec*1e3:8.2f} ms")

elif MODE == "envelope":
    comm = CartComm(ndims=3)
    for itermax in (4, 32, 64):
        param = make_param(itermax=itermax, eps=1e-30)
        dms = dist_chunk_msstep(param, comm, settle=1)
        sms = single_chunk_msstep(param, settle=1)
        print(f"itermax={itermax:3d}: dist {dms:7.2f} ms/step  "
              f"single {sms:7.2f} ms/step  gap {dms-sms:6.2f}")

elif MODE == "solve":
    param = make_param()
    s, p0, rhs0 = settled_solve_inputs(param)
    g = s.grid
    solve_s = jax.jit(make_pressure_solve_3d(
        g.imax, g.jmax, g.kmax, g.dx, g.dy, g.dz, param.omg, param.eps,
        param.itermax, DT, backend="auto", n_inner=param.tpu_sor_inner,
        solver="sor", layout="auto"))
    tsec, (ps, res, it) = bench(solve_s, p0, rhs0)
    print(f"single solve: {tsec*1e3:8.2f} ms  res={float(res):.3e} "
          f"it={int(it)}")

    comm = CartComm(ndims=3)
    d = NS3DDistSolver(param, comm=comm, dtype=DT)
    from pampi_tpu.ops.sor_odist import make_rb_iters_odist

    kl, jl, il = d.kl, d.jl, d.il
    n_o, og = build_ogeom(param, comm, d)
    rb_o = make_rb_iters_odist(og, g.dx, g.dy, g.dz, param.omg, DT)
    epssq = param.eps * param.eps
    norm = float(g.imax * g.jmax * g.kmax)

    def solve_d(p, rhs):
        qoffs = jnp.stack([
            (get_offsets("k", kl) // 2).astype(jnp.int32),
            (get_offsets("j", jl) // 2).astype(jnp.int32),
            (get_offsets("i", il) // 2).astype(jnp.int32)])
        ro = od.o_exchange(od.pack_ext_to_o(rhs, og), comm, og)
        xo = od.pack_ext_to_o(p, og)

        def cond(c):
            return jnp.logical_and(c[1] >= epssq, c[2] < param.itermax)

        def body(c):
            xo, _, it = c
            xo = od.o_exchange(xo, comm, og)
            xo, r2 = rb_o(qoffs, xo, ro)
            return xo, reduction(r2, comm, "sum") / norm, it + n_o

        xo, res, it = lax.while_loop(
            cond, body, (xo, jnp.asarray(1.0, DT), jnp.asarray(0, jnp.int32)))
        return halo_exchange(od.unpack_o_to_ext(xo, og), comm), res, it

    spec = P("k", "j", "i")
    solve_dj = jax.jit(comm.shard_map(
        solve_d, in_specs=(spec, spec), out_specs=(spec, P(), P()),
        check_vma=False))
    tsec, (pd, res, it) = bench(solve_dj, p0, rhs0)
    print(f"dist solve:   {tsec*1e3:8.2f} ms  res={float(res):.3e} "
          f"it={int(it)}")
    print(f"|pd-ps| max = {float(jnp.max(jnp.abs(pd - ps))):.3e}")

else:
    raise SystemExit(f"unknown mode {MODE!r}: full|envelope|solve")
