"""Ragged-vs-divisible throughput on the real chip (VERDICT r4 item 2's
"measured ragged-vs-divisible throughput row", + item 6's missing
dist-quarters artifact).

What non-divisible grids cost on this framework: ragged runs ride the
flag-masked checkerboard per-shard kernel (ops/sor_obsdist, all-fluid
flags, halo 2n+1) because the compressed quarters layout structurally
needs even divisible extents. This tool measures, same-session:

1. dist-quarters solve-loop steady state at 4096^2, one shard of a (1,1)
   mesh (the round-4 95.2G protocol: capped 9600-iteration solves,
   solve-vs-solve two-point differencing 4800 vs 9600 iters so dispatch
   latency and the per-solve pack/init envelope cancel) — the committed
   artifact for the round-4 retune number;
2. the masked kernel, DIVISIBLE geometry (4096^2 single shard, H=2n),
   standalone chained-kernel differencing — what a flags path costs at
   this size;
3. the masked kernel, RAGGED geometry (4095^2 ceil-divided over a virtual
   (2,2) mesh, shard 0, H=2n+1), same protocol — the ragged fast path's
   actual rate.

Run on the real chip:  python tools/perf_ragged.py
Writes results/ragged_throughput.json (merge-preserving).
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax
import jax.numpy as jnp
import numpy as np

REPS = 3
N_INNER = 8  # the obsdist production depth (ca8; 16 OOMs at wide shards)


def quarters_solve_steady() -> dict:
    """The 95G protocol: one-shard 4096^2 DistPoissonSolver, quarters."""
    from pampi_tpu.models.poisson_dist import DistPoissonSolver
    from pampi_tpu.parallel.comm import CartComm
    from pampi_tpu.utils import dispatch
    from pampi_tpu.utils.params import Parameter

    def run(itermax):
        param = Parameter(imax=4096, jmax=4096, itermax=itermax, eps=1e-30,
                          omg=1.9, tpu_dtype="float32",
                          tpu_sor_layout="quarters", tpu_ca_inner=16,
                          tpu_sor_inner=16)
        s = DistPoissonSolver(param, CartComm(ndims=2, dims=(1, 1)),
                              problem=2)
        # memory pitfall 2: first call compiles _solve_first, second
        # _solve_resume — warm BOTH before timing
        s.solve()
        s.solve()
        best = float("inf")
        for _ in range(REPS):
            t0 = time.perf_counter()
            it, res = s.solve()
            best = min(best, time.perf_counter() - t0)
        assert it >= itermax, (it, itermax)
        return best, dispatch.last("poisson_dist")

    ta, _ = run(4800)
    tb, tag = run(9600)
    ups = 4096 * 4096 * 4800 / max(tb - ta, 1e-9)
    return {"updates_per_sec": round(ups / 1e9, 2), "unit": "G", "dispatch": tag,
            "protocol": "solve-vs-solve differencing 4800 vs 9600 iters"}


def masked_kernel_rate(gj, gi, jl, il, ragged: bool) -> dict:
    """Standalone chained-kernel rate (shard 0 offsets; the kernel takes
    offs as an argument, so no shard_map is needed — the multiblock-test
    pattern)."""
    from pampi_tpu.ops import sor_pallas as sp
    from pampi_tpu.ops.sor_obsdist import make_rb_iters_obsdist
    from pampi_tpu.parallel.stencil2d import ca_halo

    dx, dy = 1.0 / gi, 1.0 / gj
    rb, br, h = make_rb_iters_obsdist(
        gj, gi, jl, il, N_INNER, dx, dy, 1.9, jnp.float32, ragged=ragged,
    )
    H = ca_halo(N_INNER, ragged)
    rng = np.random.default_rng(5)
    ext = (jl + 2 * H, il + 2 * H)
    pd = jnp.asarray(rng.standard_normal(ext), jnp.float32)
    rd = jnp.asarray(rng.standard_normal(ext), jnp.float32)
    # all-fluid flags in the deep layout: 1 inside the global extended
    # domain, 0 beyond (the dead ring)
    gjv = np.arange(ext[0])[:, None] - (H - 1)
    giv = np.arange(ext[1])[None, :] - (H - 1)
    flg = ((gjv >= 0) & (gjv <= gj + 1) & (giv >= 0)
           & (giv <= gi + 1)).astype(np.float32)
    offs = jnp.asarray([0, 0], jnp.int32)
    p_p = sp.pad_array(pd, br, h)
    r_p = sp.pad_array(rd, br, h)
    f_p = sp.pad_array(jnp.asarray(flg), br, h)

    @jax.jit
    def chain(k, x):
        def body(_, c):
            x, acc = c
            x, r = rb(offs, x, r_p, f_p)
            return x, acc + r

        return jax.lax.fori_loop(
            0, k, body, (x, jnp.zeros((), jnp.float32))
        )

    def timed(k):
        out = chain(k, p_p)
        float(out[1])
        best = float("inf")
        for _ in range(REPS):
            t0 = time.perf_counter()
            float(chain(k, p_p)[1])
            best = min(best, time.perf_counter() - t0)
        return best

    # adaptive spans: the differential must be >= ~0.5 s or it sits inside
    # the tunnel's latency jitter (measurement pitfall; a 30 ms
    # differential once read 11.8G for a 21.0G kernel). Calibrate the
    # per-call cost LATENCY-FREE (two-point on the calibration itself —
    # ta/ka would fold the fixed dispatch+readback latency into the
    # estimate and undershoot the target exactly when latency is high)
    ka = 40
    ta = timed(ka)
    per = max((timed(2 * ka) - ta) / ka, 1e-6)
    kb = ka + max(80, int(0.6 / per))
    tb = timed(kb)
    iters = (kb - ka) * N_INNER
    ups = jl * il * iters / max(tb - ta, 1e-9)
    return {"updates_per_sec": round(ups / 1e9, 2), "unit": "G",
            "halo_depth": H, "shard": [jl, il], "n_inner": N_INNER,
            "spans": [ka, kb]}


def jnp_ca_ragged_rate(gj, gi, jl, il) -> dict:
    """The jnp CA path ragged runs took before round 5 (ca_rb_iters at
    n=1, H=3, under a 1x1 shard_map for the axis context) — the
    comparator the fast path replaced."""
    from jax.sharding import Mesh, PartitionSpec as P

    from pampi_tpu.parallel.stencil2d import ca_halo, ca_masks, ca_rb_iters

    n = 1
    H = ca_halo(n, True)
    dx, dy = 1.0 / gi, 1.0 / gj
    idx2, idy2 = 1.0 / (dx * dx), 1.0 / (dy * dy)
    factor = 0.5 * (dx * dx * dy * dy) / (dx * dx + dy * dy) * 1.9
    rng = np.random.default_rng(5)
    ext = (jl + 2 * H, il + 2 * H)
    pd = jnp.asarray(rng.standard_normal(ext), jnp.float32)
    rd = jnp.asarray(rng.standard_normal(ext), jnp.float32)
    mesh = Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1), ("j", "i"))

    def kern(k, x, r):
        m = ca_masks(jl, il, H, gj, gi, jnp.float32)

        def body(_, c):
            x, acc = c
            x, rr = ca_rb_iters(x, r, n, m, factor, idx2, idy2)
            return x, acc + rr

        return jax.lax.fori_loop(0, k[0], body,
                                 (x, jnp.zeros((), jnp.float32)))

    from pampi_tpu.parallel.comm import compat_shard_map

    f = jax.jit(compat_shard_map(
        kern, mesh=mesh, in_specs=(P(), P(), P()), out_specs=(P(), P()),
        check_vma=False,
    ))

    def timed(k):
        ka = jnp.asarray([k], jnp.int32)
        float(f(ka, pd, rd)[1])
        best = float("inf")
        for _ in range(REPS):
            t0 = time.perf_counter()
            float(f(ka, pd, rd)[1])
            best = min(best, time.perf_counter() - t0)
        return best

    # latency-free span calibration — see masked_kernel_rate
    ka = 40
    ta = timed(ka)
    per = max((timed(2 * ka) - ta) / ka, 1e-6)
    kb = ka + max(80, int(0.6 / per))
    tb = timed(kb)
    ups = jl * il * (kb - ka) * n / max(tb - ta, 1e-9)
    return {"updates_per_sec": round(ups / 1e9, 2), "unit": "G",
            "halo_depth": H, "shard": [jl, il], "n_inner": n,
            "spans": [ka, kb]}


def ragged_step_decomposition() -> dict:
    """Step-level solve/non-solve decomposition of a RAGGED fused NS-2D
    run (PR 2: ragged shards now ride the fused phase megakernels) — the
    mesh twin of bench.py's decomposition line, via
    tools/_artifact.dist_step_decomposition. Needs >= 4 devices for a
    genuinely ragged (2, 2) mesh; below that no solver is built, so every
    field (including the dispatch tag) is null with a note — the record
    keeps the SAME key set either way so write_merged's recursive merge
    never sees keys appear and disappear across hosts. Timing fields are
    additionally null off-TPU (the dist_step_decomposition contract)."""
    from tools._artifact import dist_step_decomposition

    if len(jax.devices()) < 4:
        return {"phases": None, "steps_timed": None,
                "step_ms": None, "solve_iter_ms": None, "nonsolve_ms": None,
                "itermax": None,
                "decomposition_note": "needs >= 4 devices for a ragged mesh"}
    from pampi_tpu.models.ns2d_dist import NS2DDistSolver
    from pampi_tpu.parallel.comm import CartComm
    from pampi_tpu.utils.params import Parameter

    def make_solver(itermax):
        param = Parameter(
            name="dcavity", imax=4095, jmax=4095, re=1000.0, te=1e9,
            tau=0.5, itermax=itermax or 100, eps=1e-30, omg=1.7, gamma=0.9,
            tpu_dtype="float32", tpu_sor_inner=N_INNER,
            tpu_ca_inner=N_INNER,
        )
        s = NS2DDistSolver(param, CartComm(ndims=2, dims=(2, 2)),
                           dtype=jnp.float32)
        assert s.ragged
        return s

    return dist_step_decomposition(make_solver, "ns2d_dist_phases",
                                   reps=REPS)


if __name__ == "__main__":
    from pampi_tpu.utils import telemetry, xlacache

    xlacache.enable()  # the two-point builds recompile the same kernels
    telemetry.start_run(tool="perf_ragged")
    rec = {
        "artifact": "ragged_throughput",
        "backend": jax.default_backend(),
        "protocol": "chained-kernel / solve-vs-solve two-point "
                    "differencing, best-of-%d, scalar fences; tool: "
                    "tools/perf_ragged.py" % REPS,
    }
    rec["quarters_divisible_4096_solve"] = quarters_solve_steady()
    rec["masked_divisible_4096"] = masked_kernel_rate(
        4096, 4096, 4096, 4096, ragged=False)
    rec["masked_ragged_4095"] = masked_kernel_rate(
        4095, 4095, 2048, 2048, ragged=True)
    rec["jnp_ca_ragged_4095"] = jnp_ca_ragged_rate(4095, 4095, 2048, 2048)
    rec["ragged_step_decomposition_4095"] = ragged_step_decomposition()
    for name in ("quarters_divisible_4096_solve", "masked_divisible_4096",
                 "masked_ragged_4095", "jnp_ca_ragged_4095"):
        # kernel-rate rows as shared span records (ms=None: these are
        # steady-state rates, not single-span walls)
        telemetry.emit_span(f"ragged_throughput.{name}", None, **rec[name])
    from tools._artifact import write_merged

    write_merged(os.path.join(REPO, "results", "ragged_throughput.json"),
                 rec)
