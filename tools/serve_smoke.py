"""Serve smoke: the persistent fleet daemon end-to-end on CPU
(make serve-smoke).

    python tools/serve_smoke.py [outdir]

Starts the daemon over a temp file-queue and submits a mixed queue that
exercises every serving contract at once:

- SIX distinct grids across THREE shape classes (12x12, 14x10, 10x12 ->
  the 2-D 16x16 rung; 20x20 -> the 32x32 rung; 8^3 and 10x9x8 -> the
  3-D 16^3 rung, serving v3): the status endpoint's per-class compile
  census must show AT MOST ONE compiled program per shape class (the
  pad-and-mask shared-compile contract — 3-D grids form their OWN
  rungs, one compile each).
- a 2-lane continuous pool under a 4-request class: at least one
  MID-RUN SWAP-IN (a queued scenario takes a finished/diverged lane's
  slot, zero retrace).
- one DIVERGED lane (u_init nan — the in-band sentinel retires it, the
  swap plane reuses its slot, the divergence census names it).
- one CLASS-INELIGIBLE request (tpu_solver fft): served through its
  exact-shape bucket, with the refusal reason recorded in the dispatch
  plane (`class_<bucket>` — ISSUE 15's visibility satellite).
- one MALFORMED .par: parked with a structured `warning` telemetry
  record, the daemon survives (the hardened load_queue path).

Then proves the observability plane end-to-end: live status endpoint
fields, telemetry (schema v9 serving/admission/latency records) through
report -> --merge -> check_artifact lint, the trend-gated
fleet_p50_latency_ms / fleet_queue_depth_max metrics in the merged
artifact, and a clean shutdown (rc 0).
"""

from __future__ import annotations

import json
import os
import shutil
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# CPU-stable smoke environment: must precede any jax import (the
# tools/lint.py convention); a TPU image just keeps its own backend
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

PAR = """name dcavity
imax {imax}
jmax {jmax}
re 10.0
te {te}
tau 0.5
itermax 10
eps 0.0001
omg 1.7
gamma 0.9
u_init {u}
tpu_mesh 1
"""

PAR3 = """name dcavity3d
imax {imax}
jmax {jmax}
kmax {kmax}
re 10.0
te 0.02
tau 0.5
itermax 8
eps 0.0001
omg 1.7
gamma 0.9
u_init {u}
tpu_mesh 1
"""


def _write_queue(qdir: str) -> int:
    """Returns the number of WELL-FORMED requests written."""
    reqs = [
        # the 16x16 shape class: 3 distinct grids + one same-grid
        # swap-in candidate; c2 diverges at step 1 (u_init nan)
        ("alice__c0.par", PAR.format(imax=12, jmax=12, te=0.03, u=0.0)),
        ("alice__c1.par", PAR.format(imax=14, jmax=10, te=0.03, u=0.01)),
        ("alice__c2.par", PAR.format(imax=10, jmax=12, te=0.03,
                                     u=float("nan"))),
        ("alice__c3.par", PAR.format(imax=12, jmax=12, te=0.05, u=0.02)),
        # the 32x32 shape class
        ("bob__wide.par", PAR.format(imax=20, jmax=20, te=0.03, u=0.0)),
        # the 3-D 16^3 shape class (serving v3): two distinct 3-D grids
        # must form their OWN class rung -> one compile for both
        ("dana__cube.par", PAR3.format(imax=8, jmax=8, kmax=8, u=0.0)),
        ("dana__slab.par", PAR3.format(imax=10, jmax=9, kmax=8, u=0.01)),
        # a class-INELIGIBLE request: fft solve -> exact-shape bucket,
        # refusal reason recorded under class_<bucket> (ISSUE 15)
        ("carol__fft.par", PAR.format(imax=12, jmax=12, te=0.03, u=0.0)
         + "tpu_solver fft\n"),
    ]
    for name, text in reqs:
        with open(os.path.join(qdir, name), "w") as fh:
            fh.write(text)
    # one malformed request: must be PARKED, never kill the daemon
    with open(os.path.join(qdir, "mallory__bad.par"), "w") as fh:
        fh.write("name dcavity\nimax notanumber\n")
    return len(reqs)


def main(argv: list[str]) -> int:
    outdir = argv[1] if len(argv) > 1 else os.path.join(
        REPO, "results", "serve_smoke")
    shutil.rmtree(outdir, ignore_errors=True)
    qdir = os.path.join(outdir, "queue")
    os.makedirs(qdir, exist_ok=True)
    jsonl = os.path.join(outdir, "run.jsonl")
    os.environ["PAMPI_TELEMETRY"] = jsonl

    from pampi_tpu.fleet import FleetDaemon, ServeConfig
    from pampi_tpu.utils import telemetry as tm

    tm.reset()
    tm.start_run(tool="serve_smoke")
    n_good = _write_queue(qdir)

    daemon = FleetDaemon(ServeConfig(
        queue_dir=qdir, poll_s=0.01, max_lanes=2, max_queue=32,
        tenant_quota=8, classes="on", max_polls=2))
    rc = daemon.run()
    tm.finalize()

    failures: list[str] = []
    if rc != 0:
        failures.append(f"daemon exited rc {rc}")

    # -- the live status endpoint --------------------------------------
    with open(daemon.status_path) as fh:
        st = json.load(fh)
    print(json.dumps(st, indent=1))
    if st["served"] != n_good:
        failures.append(f"served {st['served']} of {n_good}")
    if st["diverged"] != 1:
        failures.append(f"diverged census {st['diverged']} != 1")
    if st["parked"] != 1:
        failures.append(f"parked {st['parked']} != 1 (malformed .par)")
    if st["swaps"] < 1:
        failures.append("no mid-run lane swap-in happened")
    classes = st.get("classes") or {}
    cls_rows = {k: v for k, v in classes.items() if "_cls" in k}
    if len(cls_rows) != 3:
        failures.append(
            f"{len(cls_rows)} compiled shape classes (expected 3 rungs "
            f"— 16², 32², and the 3-D 16³ — for 6 distinct grids): "
            f"{classes}")
    if not any(k.startswith("ns3d_") for k in cls_rows):
        failures.append(
            f"no 3-D class rung in the compile census: {classes}")
    for label, compiles in classes.items():
        if compiles > 1:
            failures.append(
                f"class {label} compiled {compiles} programs — the "
                "shared-compile contract is one per shape class")
    if st["latency_ms"]["p50"] is None:
        failures.append("no p50 latency in the status endpoint")
    if not os.path.isdir(os.path.join(qdir, "parked")) or not os.listdir(
            os.path.join(qdir, "parked")):
        failures.append("malformed .par was not parked aside")
    results = sorted(os.listdir(daemon.results_dir))
    if len(results) != n_good:
        failures.append(f"result files {results} != {n_good} scenarios")

    # -- telemetry round trip: report -> merge -> lint -----------------
    from tools import telemetry_report as tr

    records = tr.load(jsonl)
    sys.stdout.write(tr.render(records))
    srv = tr.serving_summary(records)
    if not srv:
        failures.append("no serving_summary from the flight record")
    kinds = {r.get("kind") for r in records}
    for kind in ("serving", "admission", "latency", "swap", "warning"):
        if kind not in kinds:
            failures.append(f"no `{kind}` record in the flight record")
    div = [r for r in records if r.get("kind") == "divergence"
           and r.get("scenario")]
    if not div:
        failures.append("no scenario-tagged divergence record for the "
                        "nan lane")
    # per-request class-eligibility decisions (ISSUE 15): the fft
    # request's exact-shape landing must carry the refusal reason, and
    # eligible requests their padded-class record
    cls_disp = [r for r in records if r.get("kind") == "dispatch"
                and str(r.get("key", "")).startswith("class_")]
    refused = [r for r in cls_disp if "fft" in str(r.get("value"))
               and str(r.get("value", "")).startswith("exact")]
    if not refused:
        failures.append(
            "no class_<bucket> dispatch record carrying the fft "
            f"refusal reason (records: {[r.get('key') for r in cls_disp]})")
    if not any(str(r.get("value", "")).startswith("class (padded")
               for r in cls_disp):
        failures.append("no class_<bucket> record for an ELIGIBLE "
                        "request")

    artifact = os.path.join(outdir, "SERVE_SMOKE.json")
    from tools._artifact import write_merged
    from tools.check_artifact import lint_bench

    block = {"n": 0, "cmd": "serve_smoke", "rc": 0, "tail": "",
             "telemetry_summary": tr.summary(records),
             "fleet_summary": tr.fleet_summary(records),
             "serving_summary": srv}
    merged = write_merged(artifact, block)
    failures += lint_bench(merged, "SERVE_SMOKE")
    names = {m.get("name") for m in merged.get("metrics", [])}
    for metric in ("fleet_p50_latency_ms", "fleet_queue_depth_max"):
        if metric not in names:
            failures.append(
                f"merged artifact carries no normalized {metric}")

    if failures:
        print("\nSERVE SMOKE FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print(f"\nserve smoke ok: {st['served']} scenarios over "
          f"{len(cls_rows)} shape classes (2-D + 3-D, 1 compile each), "
          f"{st['swaps']} swap(s), 1 diverged lane isolated, 1 "
          f"malformed request parked, p50 latency "
          f"{st['latency_ms']['p50']} ms, clean shutdown")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
