"""Sweep n_inner (temporal blocking depth) x block_rows for the tblock
kernel on the real chip. Total RB iterations fixed so throughput numbers
compare directly with bench.py."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax import lax

from pampi_tpu.models.poisson import init_fields
from pampi_tpu.ops import sor_pallas as sp
from pampi_tpu.utils.params import Parameter

N = int(os.environ.get("SWEEP_N", 4096))
# total RB iterations per timed run (pick divisible by all k swept; raise it
# when the tunnel's per-dispatch latency floor is high — the loop is ONE
# dispatch, so iterations amortize the floor)
TOTAL = int(os.environ.get("SWEEP_TOTAL", 120))
KS = tuple(int(x) for x in os.environ.get("SWEEP_K", "3,4,5,6").split(","))
BRS = tuple(int(x) for x in os.environ.get("SWEEP_BR", "256").split(","))


def timeit(fn, *args):
    out = fn(*args)
    float(jax.tree.leaves(out)[-1].ravel()[0])
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        out = fn(*args)
        float(jax.tree.leaves(out)[-1].ravel()[0])
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    param = Parameter(imax=N, jmax=N, tpu_dtype="float32")
    p, rhs = init_fields(param, problem=2, dtype=jnp.float32)

    for k in KS:
        for br in BRS:
            try:
                rb, brr, h = sp.make_rb_iter_tblock(
                    N, N, 1.0 / N, 1.0 / N, 1.9, jnp.float32,
                    n_inner=k, block_rows=br,
                )
                pp = sp.pad_array(p, brr, h)
                rr = sp.pad_array(rhs, brr, h)

                @jax.jit
                def loop(p, rhs):
                    def body(_, c):
                        p, _ = c
                        return rb(p, rhs)
                    return lax.fori_loop(0, TOTAL // k, body,
                                         (p, jnp.float32(0)))

                t = timeit(loop, pp, rr)
                ups = N * N * TOTAL / t
                print(f"k={k:2d} br={br:4d} {t*1e3/TOTAL:7.3f}ms/it "
                      f"ups={ups/1e9:6.2f}e9  vs_base={ups/1.32e9:5.1f}x")
            except Exception as e:
                print(f"k={k:2d} br={br:4d} FAILED {type(e).__name__}: "
                      f"{str(e)[:120]}")


if __name__ == "__main__":
    main()
