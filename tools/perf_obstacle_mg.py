"""Obstacle-MG at scale: canal_obstacle 2048x512 (VERDICT r2 item 5).

The flag-masked configs are the one place the DCT direct solve is
structurally unavailable (non-constant coefficients), so multigrid is the
only O(1)-cycles pressure solver. This measures, on the real chip at the
scaled-up config (configs/canal_obstacle2048.par, f32):

- V-cycles per pressure solve at the config's eps (sampled steps from the
  settled state — the solve's own `it` output),
- ms/step for `tpu_solver mg` vs `sor` under the perf_ns2d4096 protocol
  (settle, then chained-step two-point differencing, best-of-REPS),

and writes results/obstacle_mg2048.json.

Run on the real chip:  python tools/perf_obstacle_mg.py
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax
import jax.numpy as jnp

from pampi_tpu.utils.params import read_parameter

SETTLE = 3
REPS = 6
PAR = os.path.join(REPO, "configs", "canal_obstacle2048.par")


def _build(solver: str):
    from pampi_tpu.models.ns2d import NS2DSolver

    param = read_parameter(PAR).replace(
        tpu_dtype="float32", tpu_solver=solver
    )
    s = NS2DSolver(param, dtype=jnp.float32)
    return s, param


def measure_step_ms(solver: str) -> float:
    s, _ = _build(solver)
    step = s._build_step()

    def k_steps(k):
        @jax.jit
        def run(state):
            return jax.lax.fori_loop(0, k, lambda _, c: step(*c), state)

        return run

    state = (s.u, s.v, s.p, jnp.asarray(0.0, jnp.float32),
             jnp.asarray(0, jnp.int32))
    state = k_steps(SETTLE)(state)
    float(state[3])

    def timed(k):
        run = k_steps(k)
        float(run(state)[3])
        best = float("inf")
        for _ in range(REPS):
            t0 = time.perf_counter()
            float(run(state)[3])
            best = min(best, time.perf_counter() - t0)
        return best

    ta = timed(1)
    kb = 1 + max(2, min(64, int(1.0 / max(ta, 1e-3))))
    tb = timed(kb)
    return max((tb - ta) / (kb - 1), 1e-9) * 1e3


def sample_cycles() -> dict:
    """Per-solve V-cycle counts and residuals over sampled steps — the
    PRODUCTION step with the solve's discarded outputs exposed
    (NS2DSolver._build_step instrumented=True), so the record describes the
    trajectory the shipped solver actually runs."""
    s, param = _build("mg")
    step_i = jax.jit(s._build_step(instrumented=True))
    u, v, p = s.u, s.v, s.p
    t = jnp.asarray(0.0, jnp.float32)
    nt = jnp.asarray(0, jnp.int32)
    cycles, residuals = [], []
    for _ in range(10):
        u, v, p, t, nt, res, it, _dt = step_i(u, v, p, t, nt)
        cycles.append(int(it))
        residuals.append(float(res))
    return {"cycles_per_solve": cycles, "final_residual": residuals[-1],
            "eps": param.eps}


if __name__ == "__main__":
    rec = {
        "artifact": "obstacle_mg2048",
        "config": "configs/canal_obstacle2048.par at f32 (2048x512, "
                  "obstacle 3.0,1.5->4.0,2.5, eps=1e-5, itermax=500)",
        "backend": jax.default_backend(),
    }
    rec.update(sample_cycles())
    rec["mg_ms_per_step"] = round(measure_step_ms("mg"), 2)
    rec["sor_ms_per_step"] = round(measure_step_ms("sor"), 2)
    out = os.path.join(REPO, "results", "obstacle_mg2048.json")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as fh:
        json.dump(rec, fh, indent=2)
        fh.write("\n")
    print(json.dumps(rec, indent=2))
    print(f"wrote {out}")
