"""Offline checkpoint verifier (`make ckpt-fsck CKPT=<path>`).

    python tools/ckpt_fsck.py [--survivors N] <checkpoint> [...]

Verifies a pampi_tpu checkpoint ON DISK without building a solver —
the operator's pre-restore sanity check and the post-incident triage
tool. Both formats:

- elastic manifest (utils/checkpoint.save_elastic): manifest parse +
  schema, every shard file's existence, embedded GENERATION match
  (a mixed-generation set is the crash-window signature), per-field
  slab CRC32, and the assembled-global CRC; renders generation, writing
  mesh, global shape, t/nt and a per-field status table.
- legacy single-.npz (save_checkpoint): zip container, schema version,
  mesh/shape metadata, per-field CRC32.

The `.prev` generation (when present) is verified too and reported as
the fallback's health — but only PRIMARY corruption fails the exit
code: a healthy primary over a rotted .prev is degraded redundancy,
not a broken checkpoint.

`--survivors N` (PR 12): additionally verify the set is restorable onto
an N-rank SURVIVOR mesh — the dead-rank shrink-resume's pre-flight.
Elastic only (the legacy .npz is mesh-locked by design): requires the
full shard row coverage the reshard reassembles from (any missing /
mixed-generation shard already fails above) AND the fault ledger in the
manifest, so the shrunk fleet resumes with the protocol state (spent
budget, pallas-broken verdict, shrink epoch) instead of probation
amnesia.

Exit 0 = every primary verified; 1 = any primary torn/corrupt/missing
(or, under --survivors, not shrink-restorable).
"""

from __future__ import annotations

import os
import sys
import zipfile

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from pampi_tpu.utils.checkpoint import (  # noqa: E402
    CKPT_VERSION,
    ELASTIC_VERSION,
    CheckpointCorruptError,
    _corrupt_classes,
    _crc,
    _read_manifest,
    is_elastic,
)


def _fsck_elastic(path: str) -> list[str]:
    """Verify one elastic manifest set; returns the error lines (empty =
    healthy). Prints the rendered report as it goes."""
    errs: list[str] = []
    try:
        man = _read_manifest(path)
    except _corrupt_classes() as exc:
        return [f"{path}: {exc}"]
    gen = int(man["generation"])
    print(f"  format   elastic v{man['version']} "
          f"(ckpt schema {man.get('ckpt_version', '?')}, "
          f"this build reads <= {ELASTIC_VERSION})")
    print(f"  generation {gen}   t={man['t']:.6g} nt={man['nt']}")
    print(f"  mesh     {man['mesh'] or [1]} -> global "
          f"{'x'.join(str(s) for s in man['global_shape'])} "
          f"{man['dtype']} ({man.get('nshards', len(man['shards']))} "
          f"shard(s))")
    gshape = tuple(int(s) for s in man["global_shape"])
    fields = {f: np.zeros(gshape, np.dtype(man["dtype"]))
              for f in man["fields"]}
    base = os.path.dirname(path)
    covered = np.zeros(gshape[0], bool)
    for sh in man["shards"]:
        spath = os.path.join(base, sh["file"]) if base else sh["file"]
        tag = f"shard r{sh['rank']} ({sh['file']})"
        try:
            z = np.load(spath)
        except FileNotFoundError:
            errs.append(f"{tag}: MISSING")
            continue
        except (ValueError, EOFError, zipfile.BadZipFile) as exc:
            errs.append(f"{tag}: unreadable ({exc})")
            continue
        with z:
            sgen = int(z["generation"])
            if sgen != gen:
                errs.append(f"{tag}: generation {sgen} != manifest {gen} "
                            "(MIXED-GENERATION set)")
                continue
            lo, hi = (int(x) for x in sh["rows"])
            covered[lo:hi] = True
            for f in man["fields"]:
                try:
                    slab = z[f]
                    ok = _crc(slab) == int(z[f"crc_{f}"])
                except (KeyError, ValueError, zipfile.BadZipFile) as exc:
                    errs.append(f"{tag}.{f}: unreadable ({exc})")
                    continue
                if not ok:
                    errs.append(f"{tag}.{f}: slab CRC32 MISMATCH")
                else:
                    fields[f][lo:hi] = slab
    if not covered.all():
        errs.append(f"{path}: shard rows cover {int(covered.sum())} of "
                    f"{gshape[0]} global rows")
    for f, arr in fields.items():
        status = "ok"
        if any(e for e in errs if f".{f}:" in e or "MISSING" in e
               or "MIXED" in e or "cover" in e):
            status = "UNVERIFIABLE (shard errors above)"
        elif _crc(arr) != int(man["crc"][f]):
            status = "global CRC32 MISMATCH"
            errs.append(f"{path}.{f}: assembled-global CRC32 mismatch")
        print(f"    field {f:<2} {status}")
    return errs


def _fsck_legacy(path: str) -> list[str]:
    errs: list[str] = []
    try:
        z = np.load(path)
    except FileNotFoundError:
        return [f"{path}: MISSING"]
    except (ValueError, EOFError, zipfile.BadZipFile) as exc:
        return [f"{path}: unreadable container ({exc})"]
    with z:
        ver = int(z["version"]) if "version" in z else 1
        mesh = list(z["mesh"]) if "mesh" in z else []
        shape = list(z["shape"]) if "shape" in z else "?"
        print(f"  format   legacy .npz v{ver} "
              f"(this build reads <= {CKPT_VERSION})")
        print(f"  mesh     {[int(m) for m in mesh] or [1]} -> stacked "
              f"{'x'.join(str(int(s)) for s in shape)}   "
              f"t={float(z['t']):.6g} nt={int(z['nt'])}")
        for f in ("u", "v", "w", "p"):
            if f not in z.files:
                continue
            key = f"crc_{f}"
            if key not in z.files:
                print(f"    field {f:<2} no CRC (v1 file; container "
                      "integrity only)")
                continue
            try:
                ok = _crc(z[f]) == int(z[key])
            except (ValueError, zipfile.BadZipFile) as exc:
                errs.append(f"{path}.{f}: unreadable ({exc})")
                print(f"    field {f:<2} UNREADABLE")
                continue
            print(f"    field {f:<2} {'ok' if ok else 'CRC32 MISMATCH'}")
            if not ok:
                errs.append(f"{path}.{f}: CRC32 mismatch")
    return errs


def _fsck_survivors(path: str, n: int, errs: list[str]) -> list[str]:
    """The shrink-restorability check: could `fleet.shrink_resume` land
    this set on an N-rank survivor mesh? Appends to (and returns) the
    error list; prints the verdict line either way."""
    try:
        man = _read_manifest(path)
    except _corrupt_classes():
        print(f"  survivors {n}: UNVERIFIABLE (manifest unreadable)")
        return errs  # the manifest error is already in errs
    new = []
    if any(errs):
        new.append(f"{path}: not shrink-restorable onto {n} rank(s) — "
                   "shard set incomplete (errors above)")
    if "ledger" not in man:
        new.append(f"{path}: no fault ledger in the manifest — a "
                   f"{n}-rank survivor resume would forget the fleet's "
                   "protocol state (spent budget, pallas verdict); "
                   "written without an armed coordinator?")
    status = "ok (full coverage + ledger)" if not new else "NOT RESTORABLE"
    print(f"  survivors {n}: {status}")
    nshards = man.get("nshards", len(man.get("shards", [])))
    if n != nshards:
        print(f"    (reshard {nshards} writing shard(s) -> {n} "
              "survivor rank(s) via NamedSharding)")
    errs += new
    return errs


def fsck(path: str, survivors: int | None = None) -> list[str]:
    """Verify primary + (informationally) .prev; returns PRIMARY errors."""
    print(f"== {path} ==")
    try:
        elastic = is_elastic(path)
    except CheckpointCorruptError:
        elastic = True
    errs = (_fsck_elastic if elastic else _fsck_legacy)(path)
    if survivors is not None:
        if elastic:
            errs = _fsck_survivors(path, survivors, errs)
        else:
            errs.append(f"{path}: --survivors needs an elastic manifest "
                        "(the legacy .npz is mesh-locked)")
    for e in errs:
        print(f"    ERROR {e}")
    prev = f"{path}.prev"
    if os.path.exists(prev):
        print(f"-- fallback generation {prev} --")
        perrs = (_fsck_elastic if is_elastic(prev) else _fsck_legacy)(prev)
        for e in perrs:
            print(f"    (prev) {e}")
        if errs and not perrs:
            print("  NOTE primary is damaged but the .prev generation "
                  "verifies — load_checkpoint/load_elastic will fall back")
    print(f"  verdict  {'CORRUPT' if errs else 'ok'}")
    return errs


def main(argv: list[str]) -> int:
    args = argv[1:]
    survivors = None
    if "--survivors" in args:
        i = args.index("--survivors")
        if i + 1 >= len(args) or not args[i + 1].isdigit() \
                or int(args[i + 1]) < 1:
            print("--survivors needs a rank count >= 1", file=sys.stderr)
            return 1
        survivors = int(args[i + 1])
        args = args[:i] + args[i + 2:]
    paths = args
    if not paths:
        print(__doc__.strip(), file=sys.stderr)
        return 1
    bad = 0
    for p in paths:
        bad += len(fsck(p, survivors=survivors))
    return 1 if bad else 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
