"""Fleet smoke: a tiny mixed scenario queue end-to-end on whatever
backend this host has (make fleet-smoke — CPU-safe).

    python tools/fleet_smoke.py [outdir]

Arms PAMPI_TELEMETRY and drives the whole serving stack: enqueue a mixed
queue (three same-bucket dcavity scenarios differing only in initial
conditions, one canal bucket with different BCs, one off-shape dcavity,
one 3-D scenario) -> bucket -> batch/execute (the `tpu_fleet auto`
policy) -> per-scenario results + the fleet summary artifact. Then
proves, before any TPU time is spent:

- DRIFT GATE: every lane's final fields are compared against its SOLO
  oracle (a fresh solver run through the historical `.run()` path) at
  the repo's ulp contract — exit 1 if any lane drifts. The vmap batch
  must serve exactly what a dedicated process would have.
- the telemetry plane carries the fleet dimension: scenario-tagged
  chunk records, a `fleet` record with buckets/throughput/census, the
  `fleet_summary` merge block, and `tools/check_artifact.py` accepting
  the merged artifact.
- the throughput metric is recorded (`fleet_scenarios_per_s`, backend-
  tagged) — the series `tools/bench_trend.py` gates higher-is-better.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# CPU-stable smoke environment: must precede any jax import (the
# tools/lint.py convention); a TPU image just keeps its own backend
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

ULP_TOL = 1e-12  # the repo's ulp contract (tests/test_overlap.py)


def _queue():
    from pampi_tpu.fleet import ScenarioRequest
    from pampi_tpu.utils.params import Parameter

    b2 = dict(name="dcavity", imax=16, jmax=16, re=10.0, te=0.02, tau=0.5,
              itermax=10, eps=1e-4, omg=1.7, gamma=0.9, tpu_mesh="1")
    b3 = dict(name="dcavity3d", imax=8, jmax=8, kmax=8, re=10.0, te=0.02,
              tau=0.5, itermax=8, eps=1e-4, omg=1.7, gamma=0.9,
              tpu_mesh="1")
    return [
        # one 3-lane vmap bucket: a u_init sweep of one configuration
        ScenarioRequest("cavity_a", Parameter(**b2)),
        ScenarioRequest("cavity_b", Parameter(**b2, u_init=0.05)),
        ScenarioRequest("cavity_c", Parameter(**b2, p_init=0.25)),
        # different BCs -> different signature -> its own bucket
        ScenarioRequest("canal", Parameter(**{**b2, "name": "canal",
                                              "bcLeft": 3, "bcRight": 3})),
        # different grid -> different bucket (shape bucketing)
        ScenarioRequest("cavity_wide",
                        Parameter(**{**b2, "imax": 24})),
        # a 3-D tenant rides the same queue
        ScenarioRequest("cavity3d", Parameter(**b3)),
    ]


def _solo_oracle(req):
    """The historical path: a dedicated solver for this request."""
    from pampi_tpu.fleet.queue import family_of

    if family_of(req.param) == "ns2d":
        from pampi_tpu.models.ns2d import NS2DSolver

        s = NS2DSolver(req.param)
        names = "uvp"
    else:
        from pampi_tpu.models.ns3d import NS3DSolver

        s = NS3DSolver(req.param)
        names = "uvwp"
    s.run(progress=False)
    return s, [np.asarray(getattr(s, n)) for n in names]


def main(argv: list[str]) -> int:
    outdir = argv[1] if len(argv) > 1 else os.path.join(
        REPO, "results", "fleet_smoke")
    os.makedirs(outdir, exist_ok=True)
    jsonl = os.path.join(outdir, "run.jsonl")
    if os.path.exists(jsonl):
        os.remove(jsonl)
    os.environ["PAMPI_TELEMETRY"] = jsonl

    from pampi_tpu.fleet import run_fleet
    from pampi_tpu.utils import telemetry as tm

    tm.reset()
    tm.start_run(tool="fleet_smoke")
    reqs = _queue()
    result = run_fleet(reqs)
    tm.finalize()

    failures: list[str] = []
    summary = result.summary
    print(json.dumps(summary, indent=2))
    if summary["n_scenarios"] != len(reqs):
        failures.append(
            f"served {summary['n_scenarios']} of {len(reqs)} scenarios")
    if len(summary["buckets"]) != 4:
        failures.append(
            f"{len(summary['buckets'])} buckets (expected 4: cavity "
            "sweep, canal, wide, 3-D)")
    modes = {b["bucket"]: b["mode"] for b in summary["buckets"]}
    if "vmap" not in modes.values():
        failures.append(f"no vmap bucket in {modes} — the batched "
                        "driver never ran")
    if summary["divergence_census"]["diverged"]:
        failures.append(
            f"clean queue reported divergence: "
            f"{summary['divergence_census']}")
    if not summary["scenarios_per_s"]:
        failures.append("no scenarios_per_s throughput recorded")

    # the drift gate: every lane vs its solo oracle
    for req in reqs:
        lane = result.by_sid(req.sid)
        oracle, fields = _solo_oracle(req)
        if lane.nt != oracle.nt:
            failures.append(
                f"{req.sid}: lane nt {lane.nt} != solo {oracle.nt}")
            continue
        names = "uvp" if len(lane.fields) == 3 else "uvwp"
        for name, a, b in zip(names, lane.fields, fields):
            d = np.abs(a - b)
            if not (np.isfinite(d).all() and
                    (d.max() if d.size else 0.0) < ULP_TOL):
                failures.append(
                    f"{req.sid}: field {name} drifted from its solo "
                    f"oracle (max |diff| {d.max():.3e})")

    # the telemetry plane end-to-end
    from tools import telemetry_report as tr

    records = tr.load(jsonl)
    sys.stdout.write(tr.render(records))
    fleet_recs = [r for r in records if r.get("kind") == "fleet"]
    if not fleet_recs:
        failures.append("no fleet record in the flight record")
    tagged = [r for r in records
              if r.get("kind") == "chunk" and r.get("scenario")]
    if not tagged:
        failures.append("no scenario-tagged chunk records — the "
                        "per-tenant dimension is missing")
    metric = [r for r in records if r.get("kind") == "metric"
              and r.get("metric") == "fleet_scenarios_per_s"]
    if not metric:
        failures.append("no fleet_scenarios_per_s metric record")

    # the merge + lint round trip
    artifact = os.path.join(outdir, "FLEET_SMOKE.json")
    if os.path.exists(artifact):
        os.remove(artifact)
    from tools._artifact import write_merged
    from tools.check_artifact import lint_bench

    block = {"n": 0, "cmd": "fleet_smoke", "rc": 0, "tail": "",
             "telemetry_summary": tr.summary(records),
             "fleet_summary": tr.fleet_summary(records)}
    merged = write_merged(artifact, block)
    failures += lint_bench(merged, "FLEET_SMOKE")
    if not any(m.get("name") == "fleet_scenarios_per_s"
               for m in merged.get("metrics", [])):
        failures.append("merged artifact carries no normalized "
                        "fleet_scenarios_per_s metric")

    if failures:
        print("\nFLEET SMOKE FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print(f"\nfleet smoke ok: {summary['n_scenarios']} scenarios / "
          f"{len(summary['buckets'])} buckets at "
          f"{summary['scenarios_per_s']} scenarios/s, every lane "
          "bitwise-or-ulp equal to its solo oracle")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
