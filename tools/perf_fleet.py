"""Fleet serving throughput: the `fleet_scenarios_per_s` headline.

    python tools/perf_fleet.py [n_scenarios] [--classes] [--merge A.json]

Serves a bucket of N same-signature dcavity scenarios (a u_init
parameter sweep — the canonical ensemble workload) twice through the
fleet scheduler and reports the WARM batch throughput: the second run
reuses the bucket's compiled program (the in-process template cache +
`utils/xlacache`), so the number is the serving rate a long-lived fleet
process sustains, not a compile benchmark. The cold wall is reported
alongside (compile amortization is the fleet's whole point — both
numbers belong in the artifact).

`--classes` (ISSUE 15): the MIXED-GRID shape-class workload — N
requests whose extents cycle within one power-of-two rung, served with
`FleetScheduler(classes="on")` so they coalesce into a single class
bucket (one compile; the fused class chunk wherever `tpu_fuse_phases`
dispatches). The warm headline becomes `fleet_class_scenarios_per_s`
(scenarios_per_s is computed from the run wall alone — compile excluded
by construction), trend-gated HIGHER-IS-BETTER from the first artifact,
so the fused-vs-jnp class win lands on the same gate as every other
serving number.

Sizes: 64² × 25 steps per scenario on TPU; 16² × a handful of steps
off-TPU (trend data only, like every CPU wall in BENCH history). Prints
one JSON line ({"metric": ..., "backend": <platform>}) and emits the
same through the telemetry metric record; `--merge` folds it into a
BENCH artifact whose normalized metrics list `tools/bench_trend.py`
then gates (NAME_DIRECTIONS pins the direction by name).
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax  # noqa: E402

from pampi_tpu.fleet import FleetScheduler, ScenarioRequest  # noqa: E402
from pampi_tpu.utils import telemetry  # noqa: E402
from pampi_tpu.utils.params import Parameter  # noqa: E402


def scenario_sweep(n: int, classes: bool = False):
    on_tpu = jax.default_backend() == "tpu"
    grid = 64 if on_tpu else 16
    te = 0.05 if on_tpu else 0.02
    base = dict(name="dcavity", imax=grid, jmax=grid, re=10.0, te=te,
                tau=0.5, itermax=10, eps=1e-4, omg=1.7, gamma=0.9,
                tpu_mesh="1", tpu_dtype="float32" if on_tpu else "float64")
    if not classes:
        return [
            ScenarioRequest(f"sweep{i:03d}",
                            Parameter(**base, u_init=0.001 * i))
            for i in range(n)
        ]
    # mixed GRIDS within one power-of-two rung: extents cycle below the
    # class so every request is a different shape sharing ONE compile
    lo = grid - grid // 4
    return [
        ScenarioRequest(
            f"cls{i:03d}",
            Parameter(**{**base,
                         "imax": lo + (i % (grid - lo + 1)),
                         "jmax": grid - (i % (grid - lo + 1))},
                      u_init=0.001 * i))
        for i in range(n)
    ]


def main(argv: list[str]) -> int:
    merge_to = None
    if "--merge" in argv:
        i = argv.index("--merge")
        merge_to = argv[i + 1]
        argv = argv[:i] + argv[i + 2:]
    classes = "--classes" in argv
    if classes:
        argv = [a for a in argv if a != "--classes"]
    n = int(argv[1]) if len(argv) > 1 else 8
    metric = ("fleet_class_scenarios_per_s" if classes
              else "fleet_scenarios_per_s")
    telemetry.start_run(tool="perf_fleet", scenarios=n, classes=classes)

    sched = FleetScheduler(classes="on" if classes else "off")  # + xlacache
    reqs = scenario_sweep(n, classes=classes)
    for req in reqs:
        sched.submit(req)
    t0 = time.perf_counter()
    cold = sched.run()
    cold_wall = time.perf_counter() - t0
    # warm pass: same bucket, fresh scenario ids — the template cache
    # serves the compiled program, so this is the steady serving rate
    for i, req in enumerate(reqs):
        sched.submit(ScenarioRequest(f"warm{i:03d}", req.param))
    t0 = time.perf_counter()
    warm = sched.run()
    warm_wall = time.perf_counter() - t0

    per_s = warm.summary["scenarios_per_s"]
    rec = {
        "metric": metric,
        "value": per_s,
        "unit": "scenarios/s",
        "backend": jax.default_backend(),
        "n_scenarios": n,
        "cold_wall_s": round(cold_wall, 3),
        "warm_wall_s": round(warm_wall, 3),
        "cold_scenarios_per_s": cold.summary["scenarios_per_s"],
        "buckets": warm.summary["buckets"],
        "diverged": warm.summary["divergence_census"]["diverged"],
    }
    print(json.dumps(rec))
    telemetry.emit("metric", **rec)
    telemetry.finalize()
    if merge_to:
        import re

        from tools._artifact import write_merged

        block = {"parsed_fleet_classes" if classes else "parsed_fleet":
                 rec}
        if not os.path.exists(merge_to):
            # a fresh artifact needs the BENCH wrapper keys the schema
            # lint requires (merging into a driver-written artifact
            # keeps the driver's own wrapper)
            m = re.search(r"_r(\d+)", os.path.basename(merge_to))
            block.update(
                n=int(m.group(1)) if m else 0,
                cmd=f"python tools/perf_fleet.py {n}"
                    + (" --classes" if classes else ""),
                rc=0,
                tail=json.dumps(rec),
            )
        write_merged(merge_to, block)
    return 0 if per_s else 1


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
