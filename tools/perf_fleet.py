"""Fleet serving throughput: the `fleet_scenarios_per_s` headline.

    python tools/perf_fleet.py [n_scenarios] [--merge ARTIFACT.json]

Serves a bucket of N same-signature dcavity scenarios (a u_init
parameter sweep — the canonical ensemble workload) twice through the
fleet scheduler and reports the WARM batch throughput: the second run
reuses the bucket's compiled program (the in-process template cache +
`utils/xlacache`), so the number is the serving rate a long-lived fleet
process sustains, not a compile benchmark. The cold wall is reported
alongside (compile amortization is the fleet's whole point — both
numbers belong in the artifact).

Sizes: 64² × 25 steps per scenario on TPU; 16² × a handful of steps
off-TPU (trend data only, like every CPU wall in BENCH history). Prints
one JSON line ({"metric": "fleet_scenarios_per_s", ...,
"backend": <platform>}) and emits the same through the telemetry metric
record; `--merge` folds it into a BENCH artifact whose normalized
metrics list `tools/bench_trend.py` then gates HIGHER-IS-BETTER
(NAME_DIRECTIONS pins the direction by name).
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax  # noqa: E402

from pampi_tpu.fleet import FleetScheduler, ScenarioRequest  # noqa: E402
from pampi_tpu.utils import telemetry  # noqa: E402
from pampi_tpu.utils.params import Parameter  # noqa: E402


def scenario_sweep(n: int):
    on_tpu = jax.default_backend() == "tpu"
    grid = 64 if on_tpu else 16
    te = 0.05 if on_tpu else 0.02
    base = dict(name="dcavity", imax=grid, jmax=grid, re=10.0, te=te,
                tau=0.5, itermax=10, eps=1e-4, omg=1.7, gamma=0.9,
                tpu_mesh="1", tpu_dtype="float32" if on_tpu else "float64")
    return [
        ScenarioRequest(f"sweep{i:03d}",
                        Parameter(**base, u_init=0.001 * i))
        for i in range(n)
    ]


def main(argv: list[str]) -> int:
    merge_to = None
    if "--merge" in argv:
        i = argv.index("--merge")
        merge_to = argv[i + 1]
        argv = argv[:i] + argv[i + 2:]
    n = int(argv[1]) if len(argv) > 1 else 8
    telemetry.start_run(tool="perf_fleet", scenarios=n)

    sched = FleetScheduler()  # arms xlacache
    reqs = scenario_sweep(n)
    for req in reqs:
        sched.submit(req)
    t0 = time.perf_counter()
    cold = sched.run()
    cold_wall = time.perf_counter() - t0
    # warm pass: same bucket, fresh scenario ids — the template cache
    # serves the compiled program, so this is the steady serving rate
    for i, req in enumerate(reqs):
        sched.submit(ScenarioRequest(f"warm{i:03d}", req.param))
    t0 = time.perf_counter()
    warm = sched.run()
    warm_wall = time.perf_counter() - t0

    per_s = warm.summary["scenarios_per_s"]
    rec = {
        "metric": "fleet_scenarios_per_s",
        "value": per_s,
        "unit": "scenarios/s",
        "backend": jax.default_backend(),
        "n_scenarios": n,
        "cold_wall_s": round(cold_wall, 3),
        "warm_wall_s": round(warm_wall, 3),
        "cold_scenarios_per_s": cold.summary["scenarios_per_s"],
        "buckets": warm.summary["buckets"],
        "diverged": warm.summary["divergence_census"]["diverged"],
    }
    print(json.dumps(rec))
    telemetry.emit("metric", **rec)
    telemetry.finalize()
    if merge_to:
        import re

        from tools._artifact import write_merged

        block = {"parsed_fleet": rec}
        if not os.path.exists(merge_to):
            # a fresh artifact needs the BENCH wrapper keys the schema
            # lint requires (merging into a driver-written artifact
            # keeps the driver's own wrapper)
            m = re.search(r"_r(\d+)", os.path.basename(merge_to))
            block.update(
                n=int(m.group(1)) if m else 0,
                cmd=f"python tools/perf_fleet.py {n}",
                rc=0,
                tail=json.dumps(rec),
            )
        write_merged(merge_to, block)
    return 0 if per_s else 1


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
