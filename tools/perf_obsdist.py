"""Distributed obstacle kernel at PRODUCTION shard size (VERDICT r3 item 4).

Round 3 measured the per-shard flag-masked Pallas kernel
(ops/sor_obsdist.py) at 4.2G site-updates/s on a 2048x512 shard — 36x off
the single-device masked kernel — and attributed the gap to per-block fixed
cost without measuring alternatives. This tool measures, on the real chip
at the canal_obstacle2048 geometry (2048x512 f32, one shard of a 1x1 mesh —
the same per-shard workload a v5e-8 run gives each chip):

- the single-device masked tblock kernel (make_obstacle_solver_fn) at
  several depths — the per-shard ceiling,
- the distributed solve (make_dist_obstacle_solver auto->pallas) at several
  CA depths — what the mesh path actually delivers per shard,

using fixed-iteration solves (eps below reach, itermax = ITS) under the
tunnel timing protocol (SKILL.md): chained solve dispatches fenced by a
SCALAR readback, per-solve cost by two-point differencing so the
per-dispatch latency floor (measured up to ~100 ms here) cancels. Writes
results/obsdist2048.json.

Run on the real chip:  python tools/perf_obsdist.py
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np

import jax
import jax.numpy as jnp

from pampi_tpu.utils.params import read_parameter

ITS = 512
REPS = 5
PAR = os.path.join(REPO, "configs", "canal_obstacle2048.par")


def main() -> dict:
    from pampi_tpu.ops import obstacle as obst
    from pampi_tpu.parallel.comm import CartComm
    from pampi_tpu.utils import dispatch as _dispatch
    from pampi_tpu.utils import telemetry
    from pampi_tpu.utils import xlacache

    xlacache.enable()  # the big-halo kernels cost ~25 min/compile
                       # through the remote-compile tunnel
    telemetry.start_run(tool="perf_obsdist")

    param = read_parameter(PAR)
    imax, jmax = param.imax, param.jmax
    dx, dy = param.xlength / imax, param.ylength / jmax
    DT = jnp.float32
    fluid = obst.build_fluid(imax, jmax, dx, dy, param.obstacles)
    m = obst.make_masks(fluid, dx, dy, param.omg, DT)
    rng = np.random.default_rng(0)
    p0 = jnp.asarray(rng.standard_normal((jmax + 2, imax + 2)), DT)
    rhs = jnp.asarray(rng.standard_normal((jmax + 2, imax + 2)), DT)
    sites = jmax * imax

    KA, KB = 1, 9

    def bench(fn):
        # warm (compile) + two-point differencing over chained solves:
        # per-solve = (t(KB) - t(KA)) / (KB - KA); solves chain through the
        # p carry so they serialize on device, the scalar fence avoids
        # transferring the field, and the dispatch-latency floor cancels
        out = fn(p0, rhs)
        float(out[1])

        def timed(k):
            best = float("inf")
            for _ in range(REPS):
                t0 = time.perf_counter()
                p = p0
                for _ in range(k):
                    p, res, it = fn(p, rhs)
                float(res)
                best = min(best, time.perf_counter() - t0)
            return best

        ta, tb = timed(KA), timed(KB)
        return max((tb - ta) / (KB - KA), 1e-9)

    rec = {
        "artifact": "obsdist2048",
        "config": f"canal_obstacle geometry {jmax}x{imax} f32, fixed "
                  f"{ITS}-iteration solves, one chip (= one shard's "
                  "workload), best-of-%d" % REPS,
        "backend": jax.default_backend(),
        "single_device": {},
        "dist_one_shard": {},
    }
    for n in (8, 16):
        solve = jax.jit(obst.make_obstacle_solver_fn(
            imax, jmax, dx, dy, 1e-30, ITS, m, DT, n_inner=n))
        t = bench(solve)
        rec["single_device"][f"n{n}"] = {
            "s": round(t, 4),
            "gups": round(sites * ITS / t / 1e9, 1),
        }
        telemetry.emit_span(f"obsdist2048.single[n{n}]", t * 1e3,
                            gups=rec["single_device"][f"n{n}"]["gups"])
        print(f"single n{n}: {t*1e3:.1f} ms "
              f"{rec['single_device'][f'n{n}']['gups']}G", flush=True)

    P = jax.sharding.PartitionSpec
    for can in (8, 16):
        comm = CartComm(ndims=2, dims=(1, 1))
        solve_d, used = obst.make_dist_obstacle_solver(
            comm, imax, jmax, jmax, imax, dx, dy, 1e-30, ITS, m, DT,
            ca_n=can, sor_inner=can)
        tag = _dispatch.last("obstacle_dist")

        def kern(p, r, _s=solve_d):
            return _s(p, r)

        sm = jax.jit(comm.shard_map(
            kern, in_specs=(P(), P()), out_specs=(P(), P(), P()),
            check_vma=not used,
        ))
        try:
            t = bench(sm)
        except Exception as e:  # record, don't lose the finished rows
            msg = str(e).splitlines()[0][:200] if str(e) else type(e).__name__
            rec["dist_one_shard"][f"ca{can}"] = {
                "error": msg, "dispatch": tag,
            }
            print(f"dist ca{can} [{tag}]: FAILED {e}"[:160], flush=True)
            continue
        rec["dist_one_shard"][f"ca{can}"] = {
            "s": round(t, 4),
            "gups": round(sites * ITS / t / 1e9, 1),
            "dispatch": tag,
        }
        telemetry.emit_span(f"obsdist2048.dist[ca{can}]", t * 1e3,
                            gups=rec["dist_one_shard"][f"ca{can}"]["gups"],
                            dispatch=tag)
        print(f"dist ca{can} [{tag}]: {t*1e3:.1f} ms "
              f"{rec['dist_one_shard'][f'ca{can}']['gups']}G", flush=True)

    # step-level solve/non-solve decomposition of the FUSED dist obstacle
    # run (PR 2: obstacle shards now ride the phase megakernels with
    # call-time flag slices) — bench.py's decomposition protocol on the
    # mesh, via tools/_artifact.dist_step_decomposition
    from pampi_tpu.models.ns2d_dist import NS2DDistSolver
    from tools._artifact import dist_step_decomposition

    def make_solver(itermax):
        p_step = param.replace(
            te=1e9, tau=0.5, eps=1e-30, itermax=itermax or param.itermax,
            tpu_dtype="float32", tpu_sor_inner=16, tpu_ca_inner=16,
        )
        return NS2DDistSolver(p_step, CartComm(ndims=2, dims=(1, 1)),
                              dtype=DT)

    rec["obstacle_step_decomposition"] = dist_step_decomposition(
        make_solver, "ns2d_dist_phases", reps=REPS)
    return rec


if __name__ == "__main__":
    rec = main()
    out = os.path.join(REPO, "results", "obsdist2048.json")
    with open(out, "w") as f:
        json.dump(rec, f, indent=2)
    print("wrote", out)
