"""Serving soak: a synthetic mixed-tenant request stream through the
persistent fleet daemon, committing the queue-depth/latency TRAJECTORY
artifact (make soak-smoke; the ROADMAP item 3 capacity-planning
measurement — trajectories, not endpoint scalars).

    python tools/soak.py [outdir] [--requests N] [--waves N]
                         [--artifact PATH] [--round N]

The generator emits WAVES of requests between daemon polls — mixed
grids (four 2-D shapes across two shape-class rungs + a 3-D rung),
mixed families (ns2d/ns3d), three tenants — with the failure modes a
real queue carries: every DIVERGE_EVERY-th request blows up at step 1
(u_init nan; the in-band sentinel retires the lane) and every
MALFORMED_EVERY-th file does not parse (parked with a warning record,
the daemon survives). Tenant SLOs are armed, so the run exercises the
whole schema-v8 observability plane: request traces, registry
snapshots, slo records, burn warnings.

Per poll, the soak samples the status endpoint into the trajectory
block (`soak_trajectory`: t_s + queue_depth/p50_ms/p95_ms/served/
deferred series — tools/check_artifact.lint_soak pins monotone
timestamps and equal-length series), then runs the full observability
round trip and ASSERTS:

- rc 0, every well-formed request served, malformed parked;
- the per-stage trace decomposition CLOSES: the median request's stage
  sum lands on its end-to-end latency within 5%
  (tools/telemetry_report.trace_decomposition — percentiles are not
  additive, so the closure contract is checked on the median request's
  own waterfall, the exact decomposition of the p50 latency);
- the merged artifact lints clean (check_artifact.lint_bench) and
  carries the trend-gated fleet_class_p95_ms / slo_violations metrics;
- the Prometheus text file exists next to status.json with the latency
  histogram series.

`--artifact PATH` additionally merges the blocks into a committed
BENCH artifact (with `--round N` as its `n`), which enters `make
lint`'s artifact + trend passes via the default BENCH_r*.json glob.
"""

from __future__ import annotations

import argparse
import os
import shutil
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# CPU-stable soak environment: must precede any jax import (the
# tools/lint.py convention); a TPU image just keeps its own backend
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

PAR = """name dcavity
imax {imax}
jmax {jmax}
re 10.0
te {te}
tau 0.5
itermax 8
eps 0.0001
omg 1.7
gamma 0.9
u_init {u}
tpu_mesh 1
"""

PAR3 = """name dcavity3d
imax {imax}
jmax {jmax}
kmax {kmax}
re 10.0
te 0.015
tau 0.5
itermax 6
eps 0.0001
omg 1.7
gamma 0.9
u_init {u}
tpu_mesh 1
"""

# the mixed-grid catalog: (tenant, 2-D grid | 3-D grid) cycled
# round-robin — two 2-D rungs (16^2, 32^2) + the 3-D 16^3 rung
CATALOG = (
    ("alice", (12, 12)),
    ("bob", (14, 10)),
    ("alice", (20, 20)),
    ("dana", (8, 8, 8)),
    ("bob", (12, 12)),
    ("alice", (10, 12)),
)
DIVERGE_EVERY = 5    # every 5th request blows up at step 1
MALFORMED_EVERY = 9  # every 9th file does not parse (parked)


def _request_text(i: int) -> tuple[str, str]:
    """(filename, .par text) of the i-th synthetic request."""
    tenant, grid = CATALOG[i % len(CATALOG)]
    if (i + 1) % MALFORMED_EVERY == 0:
        return (f"mallory__bad{i}.par", "name dcavity\nimax notanumber\n")
    u = float("nan") if (i + 1) % DIVERGE_EVERY == 0 else 0.01 * (i % 3)
    if len(grid) == 3:
        text = PAR3.format(imax=grid[0], jmax=grid[1], kmax=grid[2], u=u)
    else:
        # staggered end times exercise the per-lane te carry
        text = PAR.format(imax=grid[0], jmax=grid[1],
                          te=0.02 + 0.01 * (i % 2), u=u)
    return (f"{tenant}__s{i:03d}.par", text)


def main(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("outdir", nargs="?",
                    default=os.path.join(REPO, "results", "soak"))
    ap.add_argument("--requests", type=int, default=12,
                    help="total synthetic requests (default 12)")
    ap.add_argument("--waves", type=int, default=4,
                    help="request waves across polls (default 4)")
    ap.add_argument("--artifact", default="",
                    help="also merge the blocks into this committed "
                         "BENCH artifact (default: outdir-local only)")
    ap.add_argument("--round", type=int, default=0,
                    help="artifact round number `n` (with --artifact)")
    args = ap.parse_args(argv[1:])

    outdir = args.outdir
    shutil.rmtree(outdir, ignore_errors=True)
    qdir = os.path.join(outdir, "queue")
    os.makedirs(qdir, exist_ok=True)
    jsonl = os.path.join(outdir, "run.jsonl")
    os.environ["PAMPI_TELEMETRY"] = jsonl

    from pampi_tpu.fleet import FleetDaemon, ServeConfig
    from pampi_tpu.utils import telemetry as tm

    tm.reset()
    tm.start_run(tool="soak", requests=args.requests)

    # SLO targets: alice's tight target is violated by cold-compile
    # requests (the burn-alert plane fires on a real signal), the
    # default is generous enough that warm requests pass
    daemon = FleetDaemon(ServeConfig(
        queue_dir=qdir, poll_s=0.01, max_lanes=2, max_queue=32,
        tenant_quota=16, classes="on",
        slo="default=60000,alice=1500", slo_window_s=120.0,
        slo_burn_alert=2.0))

    # the wave plan: spread the request stream across polls so the
    # queue-depth trajectory actually moves (all-at-once would plot a
    # single spike)
    waves = max(1, args.waves)
    per_wave = [args.requests // waves
                + (1 if w < args.requests % waves else 0)
                for w in range(waves)]
    n_good = n_malformed = 0
    traj = {"t_s": [], "queue_depth": [], "p50_ms": [], "p95_ms": [],
            "served": [], "deferred": []}
    t0 = time.time()
    i = 0
    for wave in per_wave:
        for _ in range(wave):
            name, text = _request_text(i)
            i += 1
            if name.startswith("mallory__"):
                n_malformed += 1
            else:
                n_good += 1
            with open(os.path.join(qdir, name), "w") as fh:
                fh.write(text)
        st = daemon.poll_once()
        traj["t_s"].append(round(time.time() - t0, 4))
        traj["queue_depth"].append(st["queue_depth"])
        traj["p50_ms"].append(st["latency_ms"]["p50"])
        traj["p95_ms"].append(st["latency_ms"]["p95"])
        traj["served"].append(st["served"])
        traj["deferred"].append(st["deferred"])
    # drain polls: anything deferred at a wave boundary retries here
    while daemon.served + daemon.failed < n_good \
            and len(traj["t_s"]) < waves + 8:
        st = daemon.poll_once()
        traj["t_s"].append(round(time.time() - t0, 4))
        traj["queue_depth"].append(st["queue_depth"])
        traj["p50_ms"].append(st["latency_ms"]["p50"])
        traj["p95_ms"].append(st["latency_ms"]["p95"])
        traj["served"].append(st["served"])
        traj["deferred"].append(st["deferred"])
    st = daemon.stop()
    tm.finalize()

    failures: list[str] = []
    if st["served"] != n_good:
        failures.append(f"served {st['served']} of {n_good} well-formed "
                        "requests")
    if st["parked"] != n_malformed:
        failures.append(f"parked {st['parked']} != {n_malformed} "
                        "malformed requests")
    if st["diverged"] < 1:
        failures.append("no diverged lane (the nan injection vanished)")
    if not st.get("slo"):
        failures.append("no slo block in the status endpoint")

    # -- the scrape surface --------------------------------------------
    prom_path = daemon.metrics_path
    prom = open(prom_path).read() if os.path.exists(prom_path) else ""
    if "fleet_request_latency_ms_bucket" not in prom:
        failures.append(f"{prom_path}: no latency histogram series")

    # -- telemetry round trip: report -> decomposition -> merge -> lint
    from tools import telemetry_report as tr

    records = tr.load(jsonl)
    sys.stdout.write(tr.render(records))
    dec = tr.trace_decomposition(records)
    if dec is None:
        failures.append("no trace records -> no latency decomposition")
    else:
        res = dec.get("sum_residual")
        if not isinstance(res, (int, float)) or res > 0.05:
            failures.append(
                f"decomposition does not close: median request's stage "
                f"sum {dec.get('p50_sum_ms')} ms vs e2e p50 "
                f"{dec['e2e_ms']['p50']} ms (residual {res})")
    mx = tr.metrics_summary(records)
    if not mx:
        failures.append("no metrics_summary from the registry snapshots")
    slo = tr.slo_summary(records)
    if not slo:
        failures.append("no slo records in the flight record")

    from tools._artifact import write_merged
    from tools.check_artifact import lint_bench

    block = {"n": args.round, "cmd": "soak", "rc": 0,
             "tail": f"soak: {st['served']} served, "
                     f"{st['parked']} parked, "
                     f"p50 {st['latency_ms']['p50']} ms",
             "telemetry_summary": tr.summary(records),
             "fleet_summary": tr.fleet_summary(records),
             "serving_summary": tr.serving_summary(records),
             "metrics_summary": mx,
             "slo": slo,
             "trace_decomposition": dec,
             "soak_trajectory": traj}
    merged = write_merged(os.path.join(outdir, "SOAK.json"), block)
    failures += lint_bench(merged, "SOAK")
    names = {m.get("name") for m in merged.get("metrics", [])}
    for metric in ("fleet_p50_latency_ms", "fleet_queue_depth_max",
                   "fleet_class_p95_ms", "slo_violations"):
        if metric not in names:
            failures.append(
                f"merged artifact carries no normalized {metric}")
    if args.artifact:
        # the COMMITTED artifact drops the fleet/serving summary blocks:
        # their throughput/latency headlines are warm-path series seeded
        # by tools/perf_fleet.py and tools/serve_smoke.py — the soak's
        # cold-compile-dominated versions of the same metric names would
        # gate apples against oranges in bench_trend. The soak commits
        # the planes that are ITS headline: the trajectory block and the
        # registry/slo-derived tail metrics (fleet_class_p95_ms,
        # slo_violations — the ISSUE 18 gate series).
        commit = {k: v for k, v in block.items()
                  if k not in ("fleet_summary", "serving_summary")}
        write_merged(args.artifact, commit)

    if failures:
        print("\nSOAK FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print(f"\nsoak ok: {st['served']} served ({st['diverged']} diverged"
          f" lanes isolated, {st['parked']} malformed parked) over "
          f"{len(traj['t_s'])} polls; p50 {st['latency_ms']['p50']} ms,"
          f" decomposition residual {dec['sum_residual']}; trajectory +"
          " metrics + slo blocks linted clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
