"""Measure the 3-D red-black SOR iteration at NS-3D headline shapes on the
real chip: jnp half-sweep composition vs the fused Pallas kernel across
block_k / n_inner. Reports lattice-site updates/s (sites x RB-iterations /
wall); every row is also a shared telemetry span
(utils/telemetry.emit_span — the one perf-tool record protocol, no-op
unless PAMPI_TELEMETRY is set).
Run on TPU: python tools/perf_sor3d.py [K J I]"""

import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax import lax

from pampi_tpu.models.ns3d import (
    checkerboard_mask_3d,
    neumann_faces_3d,
    sor_coefficients_3d,
    sor_pass_3d,
)
from pampi_tpu.ops import sor3d_pallas as sp3

K, J, I = (int(a) for a in sys.argv[1:4]) if len(sys.argv) > 3 else (128, 128, 128)
DT = jnp.float32
ITERS = 200
dx, dy, dz, omega = 1.0 / I, 1.0 / J, 1.0 / K, 1.8

from pampi_tpu.utils import telemetry, xlacache  # noqa: E402

xlacache.enable()  # repeated kernel-variant builds become disk loads
telemetry.start_run(tool="perf_sor3d", grid=[K, J, I])


def timeit(fn, *args):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = fn(*args)
    jax.block_until_ready(out)
    return time.perf_counter() - t0


def report(tag, dt_s, rb_iters):
    ups = K * J * I * rb_iters / dt_s
    telemetry.emit_span(f"sor3d.{tag.strip().replace(' ', '_')}",
                        dt_s * 1e3, grid=[K, J, I], rb_iters=rb_iters,
                        gups=round(ups / 1e9, 2))
    print(f"{tag:34s} {dt_s*1e3:8.1f} ms  {ups/1e9:7.2f} G updates/s")
    return ups


p0 = jnp.zeros((K + 2, J + 2, I + 2), DT)
rhs = jnp.ones_like(p0)

# --- jnp baseline ---
factor, idx2, idy2, idz2 = sor_coefficients_3d(dx, dy, dz, omega)
odd = checkerboard_mask_3d(K, J, I, 1, DT)
even = checkerboard_mask_3d(K, J, I, 0, DT)


@jax.jit
def jnp_n(p):
    def body(_, c):
        p, _ = c
        p, r0 = sor_pass_3d(p, rhs, odd, factor, idx2, idy2, idz2)
        p, r1 = sor_pass_3d(p, rhs, even, factor, idx2, idy2, idz2)
        return neumann_faces_3d(p), r0 + r1

    return lax.fori_loop(0, ITERS, body, (p, jnp.zeros((), DT)))


base = report("jnp fused-XLA", timeit(jnp_n, p0), ITERS)

# --- pallas variants ---
for n_inner in (1, 2, 4):
    for bk in (8, 16, 32):
        try:
            rb, bk_ = sp3.make_rb_iter_tblock_3d(
                I, J, K, dx, dy, dz, omega, DT,
                n_inner=n_inner, block_k=bk, interpret=False,
            )
            pp = sp3.pad_array_3d(p0, bk_, n_inner)
            rp = sp3.pad_array_3d(rhs, bk_, n_inner)
            steps = ITERS // n_inner

            @jax.jit
            def pal_n(pp, rp, rb=rb, steps=steps):
                def body(_, c):
                    pp, _ = c
                    return rb(pp, rp)

                return lax.fori_loop(0, steps, body, (pp, jnp.zeros((), DT)))

            dt_s = timeit(pal_n, pp, rp)
            ups = report(f"pallas n_inner={n_inner} bk={bk_}", dt_s,
                         steps * n_inner)
            print(f"{'':34s} vs jnp: {ups/base:5.2f}x")
        except Exception as exc:  # noqa: BLE001 — sweep past bad configs
            print(f"pallas n_inner={n_inner} bk={bk}: FAILED "
                  f"{type(exc).__name__}: {str(exc)[:120]}")
