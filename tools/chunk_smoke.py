"""K-fused chunk smoke: the whole ISSUE 17 seam end-to-end on whatever
backend this host has (make chunk-smoke — CPU-safe, 8 forced host
devices).

    python tools/chunk_smoke.py [outdir]

Proves, before any TPU time is spent:

- PARITY: a K=4 fused chunk (tpu_chunk_fuse=4 — the scan-wrapped body)
  reaches the SAME fields as the historical one-step-per-body chunk
  (off) on the distributed 2-D family, jnp path bitwise and fused path
  at the ulp contract, over a full te-bounded run on a (2, 2) mesh.
- DEPTH CENSUS: the tiered depth config (tpu_mesh_tiers=i=dcn,
  tpu_exchange_depth=i=4) traces EXACTLY one slow-tier capture exchange
  per field per 4 steps — the dcn tier carries the depth-4 strips and
  ZERO historical per-step deep strips, the ici tier keeps its per-step
  exchange unchanged, and the per-tier byte sum equals the flat census.
- LAUNCHES/STEP: the traced K-block's static pallas_call count divided
  by K stays under the fusion contract's 3/step ceiling.
- the telemetry plane: the `launches_per_step` metric record, the merge
  into a BENCH-shaped artifact, and `tools/check_artifact.py` accepting
  the merged block (incl. the FUSE_LAUNCH_KEYS census keys).
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# CPU-stable smoke environment: must precede any jax import (the
# tools/lint.py convention); a TPU image just keeps its own backend
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

# K=4 vs historical on the FUSED phase path: identical arithmetic per
# step, but the scan body's time-gate cond and the per-K-block metrics
# latch reassociate a handful of f32 ops — last-ulp scale, like the
# interpret-fma twins. The jnp path is pinned bitwise (TOL 0).
TOL_FUSED = 2e-6


def _run_dist(failures: list[str], **kw):
    """One te-bounded NS2DDistSolver run on a (2, 2) mesh; returns
    (u, p, nt) as host arrays plus the dispatch snapshot."""
    from pampi_tpu.models.ns2d_dist import NS2DDistSolver
    from pampi_tpu.parallel.comm import CartComm
    from pampi_tpu.utils import dispatch as disp
    from pampi_tpu.utils.params import Parameter

    base = dict(name="dcavity", imax=16, jmax=16, re=10.0, te=0.1,
                tau=0.5, itermax=10, eps=1e-4, omg=1.7, gamma=0.9)
    base.update(kw)
    p = Parameter(**base)
    comm = CartComm(ndims=2, extents=(p.jmax, p.imax), dims=(2, 2),
                    tiers=p.tpu_mesh_tiers)
    s = NS2DDistSolver(p, comm=comm)
    s.run(progress=False)
    u, v, pp = s.fields()
    return np.asarray(u), np.asarray(pp), s.nt, dict(disp.snapshot())


def _parity(failures: list[str]) -> None:
    """K=4 vs historical, jnp bitwise + fused at the ulp contract."""
    for tag, extra, tol in (
            ("jnp", {}, 0.0),
            ("fused", {"tpu_fuse_phases": "on"}, TOL_FUSED)):
        u1, p1, nt1, _ = _run_dist(failures, tpu_chunk_fuse="off", **extra)
        u4, p4, nt4, snap = _run_dist(failures, tpu_chunk_fuse="4", **extra)
        rec = snap.get("ns2d_dist_chunk_fuse") or ""
        if "scan (K=4" not in rec:
            failures.append(f"{tag}: dispatch ns2d_dist_chunk_fuse = "
                            f"{rec!r} — the forced K=4 scan did not arm")
        if nt1 != nt4:
            failures.append(f"{tag}: K=4 ran {nt4} steps, historical "
                            f"{nt1} — external chunk arity drifted")
        d = max(float(np.abs(u4 - u1).max()), float(np.abs(p4 - p1).max()))
        m = max(float(np.abs(u1).max()), float(np.abs(p1).max()), 1.0)
        print(f"[parity {tag}] {rec} | nt {nt1}/{nt4} | "
              f"maxdiff {d:.3g} (scale {m:.3g})")
        if d > tol * m:
            failures.append(f"{tag}: K=4 vs historical maxdiff {d:.3g} "
                            f"beyond {tol} of scale {m:.3g}")


def _census_and_launches(failures: list[str]) -> list[dict]:
    """Trace the standard depth config once; pin the per-tier exchange
    census and the launches-per-step quotient off the SAME jaxpr."""
    from pampi_tpu.analysis import commcheck as cc
    from pampi_tpu.analysis import jaxprcheck as jc
    from pampi_tpu.utils import telemetry as tm

    cfg = next(c for c in jc.standard_configs()
               if c.name == "ns2d_dist_depth")
    tc = jc.trace_config(cfg)
    k = jc.chunk_fuse_k(tc.decisions)
    if k != 4:
        failures.append(f"depth config traced K={k}, expected 4 "
                        f"({tc.decisions})")

    def tier_count(tiers, tier, prefix):
        strips = tiers.get(tier, {}).get("strips", {})
        return sum(n for key, n in strips.items()
                   if key.startswith(prefix))

    tiers = cc.census_tiers(tc.jaxpr.jaxpr, tc.solver.comm.tiers)
    flat = cc.census(tc.jaxpr.jaxpr)
    # the amortization proof, per traced K=4 block: the dcn axis ships
    # 2 ppermutes per capture exchange × 2 fields (u, v) of the DEPTH-4
    # strip — one slow exchange per field per 4 steps — and NONE of the
    # historical per-step deep strips it replaced; the ici axis keeps
    # its per-step fresh exchange (4 = one per scan step, 2 fields
    # × 2 ppermutes would be 8 — paste refreshes u and v in ONE fused
    # pair per step)
    n_cap = tier_count(tiers, "dcn", "16x4:")
    n_old = tier_count(tiers, "dcn", "14x3:")
    n_ici = tier_count(tiers, "ici", "3x14:")
    print(f"[census] dcn capture 16x4 ×{n_cap}, dcn historical 14x3 "
          f"×{n_old}, ici fresh 3x14 ×{n_ici}")
    if n_cap != 4:
        failures.append(f"dcn tier carries {n_cap} depth-4 capture "
                        "ppermutes per K-block, the 1-exchange-per-"
                        "4-steps contract says 4 (2 fields × 2)")
    if n_old:
        failures.append(f"dcn tier still carries {n_old} historical "
                        "per-step deep strips — amortized AND kept")
    if n_ici != 4:
        failures.append(f"ici tier carries {n_ici} per-step fresh "
                        "ppermutes per K-block, expected 4 (depth "
                        "unchanged at 1 exchange per step)")
    tier_bytes = sum(t["bytes"] for t in tiers.values())
    if tier_bytes != flat["ppermute_bytes"]:
        failures.append(f"per-tier byte sum {tier_bytes} != flat census "
                        f"{flat['ppermute_bytes']}")

    n_launch = jc.count_prim(tc.jaxpr.jaxpr, "pallas_call")
    lps = n_launch / max(k, 1)
    print(f"[launches] {n_launch} pallas_call(s) / K={k} = {lps}/step")
    if k >= 2 and lps >= 3:
        failures.append(f"{lps}/step breaches the K-fusion contract's "
                        "3-launch ceiling")
    line = {"metric": "launches_per_step", "value": lps,
            "unit": "launches/step",
            "chunk_fuse_dispatch": tc.decisions.get(
                "ns2d_dist_chunk_fuse"),
            "pallas_calls": n_launch, "k": k,
            "config": f"{cfg.name} (smoke)"}
    tm.emit("metric", **line)
    return [line]


def main(argv: list[str]) -> int:
    outdir = argv[1] if len(argv) > 1 else os.path.join(
        REPO, "results", "chunk_smoke")
    os.makedirs(outdir, exist_ok=True)
    jsonl = os.path.join(outdir, "run.jsonl")
    if os.path.exists(jsonl):
        os.remove(jsonl)
    os.environ["PAMPI_TELEMETRY"] = jsonl

    from pampi_tpu.utils import telemetry as tm

    tm.reset()
    tm.start_run(tool="chunk_smoke")
    failures: list[str] = []
    _parity(failures)
    lines = _census_and_launches(failures)
    tm.finalize()

    # the telemetry plane end-to-end
    from tools import telemetry_report as tr

    records = tr.load(jsonl)
    metric = [r for r in records if r.get("kind") == "metric"
              and r.get("metric") == "launches_per_step"]
    if len(metric) != len(lines):
        failures.append(f"{len(metric)} launches_per_step records in "
                        f"the flight record, {len(lines)} emitted")

    # the merge + lint round trip (incl. the FUSE_LAUNCH_KEYS block rule)
    artifact = os.path.join(outdir, "CHUNK_SMOKE.json")
    if os.path.exists(artifact):
        os.remove(artifact)
    from tools._artifact import write_merged
    from tools.check_artifact import lint_bench

    block = {"n": 0, "cmd": "chunk_smoke", "rc": 0, "tail": "",
             "telemetry_summary": tr.summary(records)}
    if lines:
        block["parsed_launches"] = lines[0]
    merged = write_merged(artifact, block)
    failures += lint_bench(merged, "CHUNK_SMOKE")
    if not any(m.get("name") == "launches_per_step"
               for m in merged.get("metrics", [])):
        failures.append("merged artifact carries no normalized "
                        "launches_per_step metric")

    if failures:
        print("\nCHUNK SMOKE FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print("\nchunk smoke ok: K=4 parity (jnp bitwise, fused at ulp), "
          "1 dcn exchange per field per 4 steps with ici unchanged, "
          f"launches/step {lines[0]['value']} < 3, artifact lint clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
