"""Dead-rank survival smoke (`make dead-rank-smoke`).

    python tools/dead_rank_smoke.py

The whole survival chain on one CPU, end-to-end, in seconds: a
2-virtual-rank lockstep fleet (parallel/coordinator.LockstepSim — two
full NS-2D replicas agreeing at every chunk boundary) with an agreed
elastic checkpoint cadence; rank 1 is killed at its 5th chunk dispatch
(`dead@chunk5@rank1`); the smoke asserts

  1. the survivor's membership round raises the structured
     RankDeadError NAMING rank 1 (never a hang),
  2. `fleet.scheduler.shrink_resume` restores the newest agreed elastic
     generation (+ the fault ledger) onto the survivor capacity and the
     run COMPLETES at degraded capacity,
  3. the survivor's final state is BITWISE-identical to a clean run
     restored from the same generation on the same shrunk mesh — the
     elastic-reshard contract, exercised as the survival contract.

Exit 0 = all three hold. This is the fault-suite's quick dead-rank
loop; the pytest twins live in tests/test_coordinator.py and the real
kill-a-process acceptance case (capability-gated) in
tests/test_multihost.py.
"""

from __future__ import annotations

import os
import sys
import tempfile
import warnings

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["PAMPI_FAULTS"] = "dead@chunk5@rank1"

import numpy as np  # noqa: E402

from pampi_tpu.models.ns2d import NS2DSolver  # noqa: E402
from pampi_tpu.parallel import coordinator as co  # noqa: E402
from pampi_tpu.utils import checkpoint as ckpt  # noqa: E402
from pampi_tpu.utils import faultinject as fi  # noqa: E402
from pampi_tpu.utils.params import Parameter  # noqa: E402

_BASE = dict(name="dcavity", imax=16, jmax=16, re=10.0, te=0.08, tau=0.5,
             itermax=50, eps=1e-4, omg=1.7, gamma=0.9, tpu_chunk=2,
             tpu_coord_timeout=5.0, tpu_dtype="float32")


def main() -> int:
    with tempfile.TemporaryDirectory() as tmp:
        manifest = os.path.join(tmp, "ck.elastic")
        param = Parameter(tpu_checkpoint=manifest, tpu_ckpt_elastic=1,
                          **_BASE)
        solvers, loops = [], []
        for r in range(2):
            with fi.rank_scope(r):
                solvers.append(NS2DSolver(param))
        for r, solver in enumerate(solvers):
            loop = co.sim_rank_loop(solver, "ns2d", 3, r, ckpt_every=2)
            if r == 0:
                # the production shape (coord_ckpt_cadence): rank 0
                # publishes + writes the manifest WITH the fault ledger
                # at every agreed commit; peers vote but don't write
                def on_ckpt(state, ledger=None, s=solver):
                    s.u, s.v, s.p = state[0], state[1], state[2]
                    s.t, s.nt = float(state[3]), int(state[4])
                    ckpt.save_elastic(manifest, s, ledger=ledger)

                on_ckpt.takes_ledger = True
                loop.on_ckpt = on_ckpt
            loops.append(loop)

        verdict = None
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                co.LockstepSim(loops).run()
        except co.RankDeadError as exc:
            verdict = exc
            if verdict.ranks != [1]:
                print(f"FAIL: dead set {verdict.ranks} != [1]")
                return 1
            print(f"[1/3] survivor verdict ok: {verdict}")
        else:
            print("FAIL: the fleet completed — rank 1 was never "
                  "declared dead")
            return 1

        if not os.path.exists(manifest):
            print("FAIL: no elastic generation was committed before "
                  "the death")
            return 1
        man = ckpt._read_manifest(manifest)
        if "ledger" not in man:
            print("FAIL: the agreed commit carried no fault ledger")
            return 1
        gen = int(man["generation"])

        import jax

        from pampi_tpu.fleet.scheduler import shrink_resume

        shrunk = [jax.devices()[0]]  # the survivor's capacity
        resumed = shrink_resume(manifest, param, family="ns2d",
                                devices=shrunk, dead=verdict.ranks,
                                epoch=verdict.epoch)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            resumed.run(progress=False)
        if not (resumed.t > param.te
                and np.isfinite(np.asarray(resumed.u)).all()):
            print("FAIL: the shrink-resumed run did not complete finite")
            return 1
        print(f"[2/3] shrink-resume ok: generation {gen} -> "
              f"t={resumed.t:.4f} nt={resumed.nt} on 1 device")

        # the clean shrunk-mesh oracle: a fresh run restored from the
        # SAME generation on the same capacity must match bitwise
        oracle = NS2DSolver(param)
        ckpt.load_elastic(manifest, oracle)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            oracle.run(progress=False)
        if (resumed.nt != oracle.nt or resumed.t != oracle.t
                or not all(
                    np.array_equal(np.asarray(getattr(resumed, f)),
                                   np.asarray(getattr(oracle, f)))
                    for f in ("u", "v", "p"))):
            print("FAIL: survivor state is not bitwise-identical to the "
                  "clean shrunk-mesh run from the same generation")
            return 1
        print(f"[3/3] bitwise parity ok: survivor == clean shrunk-mesh "
              f"run from generation {gen} (nt={oracle.nt})")
        print("dead-rank smoke PASS")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
