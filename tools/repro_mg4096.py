"""Minimal repro for the mg device fault at 4096^2 NS-2D (BASELINE.md note).

Round 1 recorded: `tpu_solver mg` inside the NS-2D chunk at 4096^2 f32 hits
an XLA:TPU device fault (UNAVAILABLE class) on this chip, while fft and the
Pallas SOR run fine. This script isolates the nesting level at which the
fault appears:

  stage 1  mg solve alone (PoissonSolver-shaped: one jitted while_loop of
           V-cycles) at 4096^2
  stage 2  one NS-2D timestep with the mg pressure solve (solve while_loop
           nested in the step program)
  stage 3  the production chunk driver (step while_loop nested in the chunk
           while_loop) - the shape the original fault was recorded in

Run on the real chip:  python tools/repro_mg4096.py [N] [stages]
Prints PASS/FAULT per stage; exits nonzero on the first fault. Each stage
re-runs once on a fault to separate the persistent failure from the
transient-infra class (models/_driver._is_transient_device_fault).
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from pampi_tpu.models._driver import _is_transient_device_fault
from pampi_tpu.utils.params import Parameter

N = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
STAGES = sys.argv[2] if len(sys.argv) > 2 else "123"


def _attempt(label, fn):
    for attempt in (1, 2):
        try:
            fn()
            print(f"{label}: PASS (attempt {attempt})")
            return True
        except Exception as e:  # noqa: BLE001 - we classify and report
            transient = _is_transient_device_fault(e)
            print(
                f"{label}: FAULT attempt {attempt} "
                f"(transient-class={transient}): {type(e).__name__}: "
                f"{str(e)[:300]}"
            )
    return False


def launch_census():
    """The mg_launches_per_cycle line at the repro geometry (the same
    metric protocol as bench.py's _mg_launch_line — one static trace per
    knob setting, no device work), printed before any stage runs so a
    faulting stage still leaves the census on record: the fault's
    character (launch-bound ladder vs the 2-launch fused cycle) is the
    first thing the isolation needs."""
    from pampi_tpu.analysis.jaxprcheck import count_prim
    from pampi_tpu.ops.multigrid import make_mg_vcycle_2d
    from pampi_tpu.utils import dispatch, telemetry

    def cycle_launches(fused):
        vc = make_mg_vcycle_2d(N, N, 1.0 / N, 1.0 / N, jnp.float32,
                               fused=fused)
        z = jnp.zeros((N + 2, N + 2), jnp.float32)
        return count_prim(jax.make_jaxpr(vc)(z, z).jaxpr, "pallas_call")

    ladder = cycle_launches("off")
    fused = cycle_launches("on")
    line = {
        "metric": "mg_launches_per_cycle",
        "value": fused,
        "unit": "launches/cycle",
        "mg_dispatch": dispatch.last("mg2d_fused"),
        "ladder_launches": ladder,
        "config": f"dcavity {N}^2 f32 mg vcycle (repro)",
    }
    telemetry.emit("metric", **line)
    print(json.dumps(line), flush=True)


def stage1():
    from pampi_tpu.ops.multigrid import make_mg_solve_2d

    solve = jax.jit(make_mg_solve_2d(N, N, 1.0 / N, 1.0 / N, 1e-3, 20, jnp.float32))
    p = jnp.zeros((N + 2, N + 2), jnp.float32)
    rhs = jnp.ones((N + 2, N + 2), jnp.float32)
    out = solve(p, rhs)
    jax.block_until_ready(out)


def _param(te):
    return Parameter(
        name="dcavity", imax=N, jmax=N, re=1000.0, te=te, tau=0.5,
        itermax=20, eps=1e-3, omg=1.7, gamma=0.9, tpu_dtype="float32",
        tpu_solver="mg",
    )


def stage2():
    from pampi_tpu.models.ns2d import NS2DSolver

    s = NS2DSolver(_param(te=1.0), dtype=jnp.float32)
    step = jax.jit(s._build_step())
    out = step(s.u, s.v, s.p, jnp.asarray(0.0, jnp.float32), jnp.asarray(0, jnp.int32))
    jax.block_until_ready(out)


def stage3():
    from pampi_tpu.models.ns2d import NS2DSolver

    s = NS2DSolver(_param(te=1e-4), dtype=jnp.float32)  # a few steps, one chunk
    s.run(progress=False)


if __name__ == "__main__":
    print(f"backend={jax.default_backend()} N={N}")
    try:
        launch_census()
    except Exception as e:  # noqa: BLE001 - census must not sink the repro
        print(f"launch census failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    ok = True
    for st, fn in (("1-mg-solve-alone", stage1), ("2-ns-step", stage2), ("3-ns-chunk-driver", stage3)):
        if st[0] in STAGES:
            ok = _attempt(st, fn) and ok
            if not ok:
                break
    sys.exit(0 if ok else 1)
