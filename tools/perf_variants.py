"""Bound the compute cost of kernel components: time tblock k=4 br=256 as-is
vs with BC refresh removed vs with red-sweep only (halved stencil work).
Throwaway measurement harness — numerics of the stripped variants are WRONG
(no BC), only timings matter."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from pampi_tpu.models.poisson import init_fields
from pampi_tpu.ops import sor_pallas as sp
from pampi_tpu.utils.params import Parameter

N = int(os.environ.get("VAR_N", 4096))
TOTAL = int(os.environ.get("VAR_TOTAL", 96))  # one dispatch; raise to
# amortize a high tunnel latency floor
K = int(os.environ.get("VAR_K", 4))
BR = int(os.environ.get("VAR_BR", 256))


def make_variant(no_bc=False, red_only=False, no_res=False, inc_black=False,
                 bc_cond=False):
    dtype = jnp.float32
    h = sp.tblock_halo(K, dtype)
    wp = sp.padded_width(N)
    width = N + 2
    nblocks = -(-(N + 2) // BR)
    rp = nblocks * BR + 2 * h
    dx2 = (1.0 / N) ** 2
    factor = 1.9 * 0.5 * (dx2 * dx2) / (dx2 + dx2)
    idx2 = 1.0 / dx2

    def kernel(p_in, rhs, p_out, res, pw2, rw2, ob2, ld_sem, st_sem):
        b = pl.program_id(0)
        slot = b % 2
        nslot = (b + 1) % 2

        def load(k, s):
            return (
                pltpu.make_async_copy(
                    p_in.at[pl.ds(k * BR, BR + 2 * h), :], pw2.at[s],
                    ld_sem.at[s, 0]),
                pltpu.make_async_copy(
                    rhs.at[pl.ds(k * BR, BR + 2 * h), :], rw2.at[s],
                    ld_sem.at[s, 1]),
            )

        def store(k, s):
            return pltpu.make_async_copy(
                ob2.at[s], p_out.at[pl.ds(h + k * BR, BR), :], st_sem.at[s])

        @pl.when(b == 0)
        def _():
            res[0, 0] = jnp.zeros((), jnp.float32)
            for c in load(0, 0):
                c.start()

        @pl.when(b + 1 < nblocks)
        def _():
            for c in load(b + 1, nslot):
                c.start()

        for c in load(b, slot):
            c.wait()

        p = pw2[slot]
        rw = rw2[slot]

        def lap(x):
            e = jnp.roll(x, -1, axis=1)
            w = jnp.roll(x, 1, axis=1)
            n = jnp.roll(x, -1, axis=0)
            s = jnp.roll(x, 1, axis=0)
            return (e - 2.0 * x + w) * idx2 + (n - 2.0 * x + s) * idx2

        jj = b * BR - h + jax.lax.broadcasted_iota(jnp.int32, p.shape, 0)
        ii = jax.lax.broadcasted_iota(jnp.int32, p.shape, 1)
        interior = (jj >= 1) & (jj <= N) & (ii >= 1) & (ii <= width - 2)
        red = interior & (((ii + jj) % 2) == 0)
        black = interior & (((ii + jj) % 2) == 1)
        rgl = (jj == 0) & (ii >= 1) & (ii <= width - 2)
        rgh = (jj == N + 1) & (ii >= 1) & (ii <= width - 2)
        rint = (jj >= 1) & (jj <= N)
        cgl = (ii == 0) & rint
        cgh = (ii == width - 1) & rint

        r_red = r_blk = jnp.zeros_like(p)
        for t in range(K):
            if inc_black:
                # one lap; black residual reconstructed from the red deltas
                # (linear stencil: r_blk = r_all + factor*stencil(r_red))
                r_all = rw - lap(p)
                r_red = jnp.where(red, r_all, 0.0)
                p = p - factor * r_red
                corr = (
                    jnp.roll(r_red, -1, 1) + jnp.roll(r_red, 1, 1)
                    + jnp.roll(r_red, -1, 0) + jnp.roll(r_red, 1, 0)
                ) * idx2
                r_blk = jnp.where(black, r_all + factor * corr, 0.0)
                p = p - factor * r_blk
            else:
                r_red = jnp.where(red, rw - lap(p), 0.0)
                p = p - factor * r_red
                if not red_only:
                    r_blk = jnp.where(black, rw - lap(p), 0.0)
                    p = p - factor * r_blk
            if not no_bc:
                if bc_cond:
                    # row-ghost refresh only in the blocks that contain a
                    # ghost row (first/last) — scf.if at runtime
                    p = jax.lax.cond(
                        b == 0,
                        lambda q: jnp.where(rgl, jnp.roll(q, -1, axis=0), q),
                        lambda q: q, p)
                    p = jax.lax.cond(
                        b == nblocks - 1,
                        lambda q: jnp.where(rgh, jnp.roll(q, 1, axis=0), q),
                        lambda q: q, p)
                else:
                    p = jnp.where(rgl, jnp.roll(p, -1, axis=0), p)
                    p = jnp.where(rgh, jnp.roll(p, 1, axis=0), p)
                p = jnp.where(cgl, jnp.roll(p, -1, axis=1), p)
                p = jnp.where(cgh, jnp.roll(p, 1, axis=1), p)

        @pl.when(b >= 2)
        def _():
            store(b - 2, slot).wait()

        ob2[slot] = p[h:h + BR, :]
        store(b, slot).start()

        if not no_res:
            ro = r_red[h:h + BR, :]
            bo = r_blk[h:h + BR, :]
            res[0, 0] += jnp.sum(ro * ro) + jnp.sum(bo * bo)

        @pl.when(b == nblocks - 1)
        def _():
            store(b, slot).wait()
            if nblocks > 1:
                store(b - 1, nslot).wait()

    call = pl.pallas_call(
        kernel,
        grid=(nblocks,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 2,
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((1, 1), lambda b: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rp, wp), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, BR + 2 * h, wp), jnp.float32),
            pltpu.VMEM((2, BR + 2 * h, wp), jnp.float32),
            pltpu.VMEM((2, BR, wp), jnp.float32),
            pltpu.SemaphoreType.DMA((2, 2)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        compiler_params=pltpu.CompilerParams(vmem_limit_bytes=100 << 20),
    )
    return call, h


def timeit(callable_, p, rhs):
    @jax.jit
    def loop(p, rhs):
        def body(_, c):
            pp, _ = c
            pp, r = callable_(pp, rhs)
            return pp, r[0, 0]
        return lax.fori_loop(0, TOTAL // K, body, (p, jnp.float32(0)))

    out = loop(p, rhs)
    float(out[1])
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        out = loop(p, rhs)
        float(out[1])
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    param = Parameter(imax=N, jmax=N, tpu_dtype="float32")
    p, rhs = init_fields(param, problem=2, dtype=jnp.float32)
    for label, kw in [
        ("full        ", {}),
        ("no-bc       ", dict(no_bc=True)),
        ("no-res      ", dict(no_res=True)),
        ("red-only    ", dict(red_only=True)),
        ("red+nobc    ", dict(red_only=True, no_bc=True)),
        ("inc-black   ", dict(inc_black=True)),
        ("bc-cond     ", dict(bc_cond=True)),
        ("inc+cond    ", dict(inc_black=True, bc_cond=True)),
    ]:
        call, h = make_variant(**kw)
        pp = sp.pad_array(p, BR, h)
        rr = sp.pad_array(rhs, BR, h)
        t = timeit(call, pp, rr)
        print(f"{label} {t*1e3/TOTAL:7.3f} ms/it "
              f"ups={N*N*TOTAL/t/1e9:6.2f}e9")


if __name__ == "__main__":
    main()
