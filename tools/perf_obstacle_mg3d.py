"""3-D obstacle-MG at 96^3: cost decomposition + same-session comparator
(VERDICT r4 item 3: mg measured 169.1 ms/step vs capped SOR 18.9 in round
4, with no committed artifact and no decomposition — the 2-D twin's
ablation is what found its 59x).

Workload: dcavity3d 96^3 f32, Re=1000, box obstacle 0.3..0.6 on every
axis, eps=1e-3, itermax=1000 — the "96^3 box dcavity step" of BASELINE.md.

Measures (all in ONE session — cross-session comparators are the
documented pitfall):
- ms/step for tpu_solver mg and sor (capped smoother), two-point
  chained-step differencing;
- V-cycles per solve at the SETTLED production state (the solve's own it);
- per-CYCLE cost via fixed-cycle solves (eps=0, stall off, itermax=k;
  k=2 vs k=8 differenced), with ablations: no smoothing (n_pre=n_post=0:
  transfers + dense bottom only) and jnp smoothing (Pallas smoothers
  ablated) — splits cycle count x smoothing x hierarchy.

Run on the real chip:  python tools/perf_obstacle_mg3d.py
Writes results/obstacle_mg3d_96.json (merge-preserving curated keys).
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax
import jax.numpy as jnp

from pampi_tpu.utils.params import Parameter

SETTLE = 3
REPS = 5
N = 96
OBST = "0.3,0.3,0.3,0.6,0.6,0.6"


def make_param(solver: str) -> Parameter:
    return Parameter(
        name="dcavity3d", imax=N, jmax=N, kmax=N,
        xlength=1.0, ylength=1.0, zlength=1.0,
        re=1000.0, te=1e9, tau=0.5, itermax=1000, eps=1e-3, omg=1.8,
        gamma=0.9, obstacles=OBST, tpu_dtype="float32", tpu_solver=solver,
    )


def _build(solver: str):
    from pampi_tpu.models.ns3d import NS3DSolver

    s = NS3DSolver(make_param(solver), dtype=jnp.float32)
    return s


def _settled_state(s):
    step = s._build_step()

    def k_steps(k):
        @jax.jit
        def run(state):
            return jax.lax.fori_loop(0, k, lambda _, c: step(*c), state)

        return run

    state = (s.u, s.v, s.w, s.p, jnp.asarray(0.0, jnp.float32),
             jnp.asarray(0, jnp.int32))
    state = k_steps(SETTLE)(state)
    float(state[4])
    return state, k_steps


def measure_step_ms(solver: str) -> float:
    s = _build(solver)
    state, k_steps = _settled_state(s)

    def timed(k):
        run = k_steps(k)
        float(run(state)[4])
        best = float("inf")
        for _ in range(REPS):
            t0 = time.perf_counter()
            float(run(state)[4])
            best = min(best, time.perf_counter() - t0)
        return best

    ta = timed(1)
    kb = 1 + max(2, min(64, int(1.0 / max(ta, 1e-3))))
    tb = timed(kb)
    return max((tb - ta) / (kb - 1), 1e-9) * 1e3


def settled_p_rhs(s, state):
    """Rebuild the production solve inputs (p, rhs) at the settled state —
    the step's own pre-solve chain (models/ns3d._build_step)."""
    from pampi_tpu.ops import ns3d as ops
    from pampi_tpu.ops.obstacle3d import (
        apply_obstacle_velocity_bc_3d,
        mask_fgh,
    )

    param = s.param
    g = s.grid
    u, v, w, p = state[:4]

    @jax.jit
    def prep(u, v, w, p):
        dt = ops.compute_timestep_3d(
            u, v, w, jnp.asarray(s.dt_bound, jnp.float32),
            g.dx, g.dy, g.dz, param.tau,
        )
        bcs = {"top": param.bcTop, "bottom": param.bcBottom,
               "left": param.bcLeft, "right": param.bcRight,
               "front": param.bcFront, "back": param.bcBack}
        u, v, w = ops.set_boundary_conditions_3d(u, v, w, bcs)
        u = ops.set_special_bc_dcavity_3d(u)
        u, v, w = apply_obstacle_velocity_bc_3d(u, v, w, s.masks)
        f, g_, h = ops.compute_fgh(
            u, v, w, dt, param.re, param.gx, param.gy, param.gz,
            param.gamma, g.dx, g.dy, g.dz,
        )
        f, g_, h = mask_fgh(f, g_, h, u, v, w, s.masks)
        rhs = ops.compute_rhs(f, g_, h, dt, g.dx, g.dy, g.dz)
        return p, rhs

    return prep(u, v, w, p)


def fixed_cycle_solve_ms(s, p, rhs, n_pre=2, n_post=2,
                         jnp_smoothing=False) -> float:
    """Per-cycle cost: eps=0 + stall off burns exactly itermax cycles;
    two-point differencing between k=2 and k=8."""
    import pampi_tpu.ops.multigrid as mg

    g = s.grid
    saved = mg._PALLAS_SMOOTH_MIN_CELLS
    if jnp_smoothing:
        mg._PALLAS_SMOOTH_MIN_CELLS = 1 << 60
    try:
        def solve_k(k):
            fn = mg.make_obstacle_mg_solve_3d(
                g.imax, g.jmax, g.kmax, g.dx, g.dy, g.dz,
                0.0, k, s.masks, jnp.float32,
                n_pre=n_pre, n_post=n_post, stall_rtol=0.0,
            )
            return jax.jit(fn)

        def timed(k):
            fn = solve_k(k)
            out = fn(p, rhs)
            assert int(out[2]) == k
            float(out[1])
            best = float("inf")
            for _ in range(REPS):
                t0 = time.perf_counter()
                float(fn(p, rhs)[1])
                best = min(best, time.perf_counter() - t0)
            return best

        ta = timed(2)
        tb = timed(8)
        return max(tb - ta, 1e-9) / 6 * 1e3
    finally:
        mg._PALLAS_SMOOTH_MIN_CELLS = saved


def production_cycles(s, p, rhs) -> dict:
    import pampi_tpu.ops.multigrid as mg

    g = s.grid
    param = s.param
    fn = jax.jit(mg.make_obstacle_mg_solve_3d(
        g.imax, g.jmax, g.kmax, g.dx, g.dy, g.dz,
        param.eps, param.itermax, s.masks, jnp.float32,
        stall_rtol=param.tpu_mg_stall_rtol,
    ))
    pp, res, it = fn(p, rhs)
    return {"cycles": int(it), "residual": float(res),
            "eps_sq": param.eps ** 2}


if __name__ == "__main__":
    rec = {
        "artifact": "obstacle_mg3d_96",
        "config": f"dcavity3d {N}^3 f32, Re=1000, box obstacle {OBST}, "
                  "eps=1e-3, itermax=1000, omg=1.8",
        "protocol": "settled 3 steps; ms/step: chained-step two-point "
                    "differencing best-of-%d; per-cycle: fixed-cycle "
                    "solves (eps=0, stall off) k=2 vs k=8 differenced; "
                    "tool: tools/perf_obstacle_mg3d.py" % REPS,
        "backend": jax.default_backend(),
    }
    s = _build("mg")
    state, _ = _settled_state(s)
    p, rhs = settled_p_rhs(s, state)
    rec["production_solve"] = production_cycles(s, p, rhs)
    rec["ms_per_cycle"] = round(fixed_cycle_solve_ms(s, p, rhs), 3)
    rec["ms_per_cycle_jnp_smoothing"] = round(
        fixed_cycle_solve_ms(s, p, rhs, jnp_smoothing=True), 3)
    rec["ms_per_cycle_no_smoothing"] = round(
        fixed_cycle_solve_ms(s, p, rhs, n_pre=0, n_post=0), 3)
    rec["mg_ms_per_step"] = round(measure_step_ms("mg"), 2)
    rec["sor_capped_ms_per_step"] = round(measure_step_ms("sor"), 2)

    from tools._artifact import write_merged

    write_merged(os.path.join(REPO, "results", "obstacle_mg3d_96.json"), rec)
