"""DMVM per-region counter sweeps — the TPU twin of the reference's perl
likwid-mpirun scripts' ACTUAL job (assignment-3a/perl scripts/
bench-node.pl:17-27, bench-cluster.pl, bench-memdomain.pl: hardware-counter
runs of the DMVM region over the (N, NITER) grids at several rank counts).

Emits results/regions/dmvm-node.csv (single device, SequentialDMVM — the
per-node counter run) and, when more than one device is visible,
results/regions/dmvm-mesh.csv (RingDMVM over all devices — the cluster
twin), with COMPLETE columns:

    Ranks,NITER,N,region,calls,wall_s,device_s,MFlops

wall_s is the dispatch wall time to completion (scalar-fenced), device_s the
same quantity (the measurement is device-inclusive by construction — the
meaning the reference's likwid wall/counter pair degenerates to on a TPU),
MFlops = 2 N^2 iter / wall / 1e6 (main.c:93-95).

NITER is divided by SCALE (default 10; iteration-invariant metric) like the
bash twins' convention (scripts/bench-node.sh).

Usage: python tools/bench_dmvm_regions.py [SCALE] [outdir]
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax

GRID = [(1000, 1_000_000), (4000, 100_000), (10000, 10_000), (20000, 5_000)]


def sweep(kind: str, scale: int):
    from pampi_tpu.models.dmvm import RingDMVM, SequentialDMVM

    rows = []
    for n, niter in GRID:
        iters = max(1, niter // scale)
        if kind == "node":
            ranks = 1
            model = SequentialDMVM(n)
            _y, wall = model.run(iters)
            mflops = 1e-6 * 2.0 * n * n * iters / wall
        else:
            ranks = len(jax.devices())
            if n % ranks:
                # the ring block-shards x: skip non-divisible N (the CLI
                # guards the same case, models/dmvm.main) instead of
                # crashing the sweep after dmvm-node.csv is written
                print(f"mesh: N={n} skipped (not divisible by R={ranks})")
                continue
            model = RingDMVM(n, overlap=True)
            _y, wall, mflops = model.run(iters)
        rows.append((ranks, iters, n, "dmvm", 1, wall, wall, mflops))
        print(f"{kind}: N={n} iters={iters} ranks={ranks} "
              f"wall={wall:.3f}s {mflops:.0f} MFlops")
    return rows


def write_csv(path: str, rows) -> None:
    with open(path, "w") as fh:
        fh.write("Ranks,NITER,N,region,calls,wall_s,device_s,MFlops\n")
        for r in rows:
            fh.write(
                f"{r[0]},{r[1]},{r[2]},{r[3]},{r[4]},"
                f"{r[5]:.6f},{r[6]:.6f},{r[7]:.2f}\n"
            )
    print(f"wrote {path}")


if __name__ == "__main__":
    scale = int(sys.argv[1]) if len(sys.argv) > 1 else 10
    outdir = sys.argv[2] if len(sys.argv) > 2 else os.path.join(
        REPO, "results", "regions"
    )
    os.makedirs(outdir, exist_ok=True)
    write_csv(os.path.join(outdir, "dmvm-node.csv"), sweep("node", scale))
    if len(jax.devices()) > 1:
        write_csv(os.path.join(outdir, "dmvm-mesh.csv"),
                  sweep("mesh", scale))
