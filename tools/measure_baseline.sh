#!/bin/bash
# Regenerate the vs_baseline constant in bench.py: throughput of the
# reference's assignment-4 C solver at 4096^2 (single core), x8 for the
# 8-rank MPI baseline named in BASELINE.json.
set -e
work=$(mktemp -d)
cp -r /root/reference/assignment-4/src "$work/src"
gcc -O3 -march=native -o "$work/poisson" "$work"/src/*.c -lm
cat > "$work/big.par" <<EOF
name poisson
xlength 1.0
ylength 1.0
imax 4096
jmax 4096
itermax 20
eps 0.0
omg 1.9
EOF
cd "$work"
out=$(./poisson big.par | tail -1)  # "20 Walltime X.XXs"
secs=$(echo "$out" | sed 's/.*Walltime \([0-9.]*\)s/\1/')
python3 - "$secs" <<'EOF'
import sys
secs = float(sys.argv[1])
ups = 4096*4096*20/secs
print(f"C single-core: {ups:.3e} updates/s; 8-rank proxy: {8*ups:.3e}")
EOF
