"""North-star workload records (the literal BASELINE.json workload, end to end).

Two committed artifacts (VERDICT r2 item 2):

  python tools/northstar.py match      -> results/northstar_residual_match.json
      CPU, f64. Compiles the reference NS-2D solver from source
      (/root/reference/assignment-5/sequential/src, gcc -O3), runs the
      VERBATIM committed dcavity.par (100^2, Re=10, te=10 — the config whose
      golden outputs ship in the reference tree) to completion, runs this
      framework's CLI on the same .par at f64, and records the field-level
      match of the two converged solutions (max |du|, |dv|, mean-adjusted
      |dp|) against the < 1e-6 north-star bar, plus both "Solution took"
      wall-clocks (≙ assignment-5/sequential/src/main.c:63).

  python tools/northstar.py run4096 [te]  -> results/northstar_dcavity4096.json
      Real chip, f32. The north-star grid: dcavity 4096^2, Re=1000 (the
      assignment-6 dcavity physics on the 2-D north-star size), tau=0.5,
      itermax=100, eps=1e-3 — run END TO END through the production
      NS2DSolver (auto layout: the quarters Pallas kernel) for the given
      simulated interval (default te=0.15, ~10k steps: the viscous CFL bound
      0.5*Re*dx^2/2 = 1.49e-5 makes te=10 a ~670k-step workload no baseline
      runs either; the JSON records the honest per-step rate, the step count,
      the final pressure residual, and the linear-in-steps extrapolation).
      A post-run sampled window (python-side steps built from the same ops)
      counts SOR iterations/step so site-updates/s through the pressure
      solve is measured, not assumed.
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

REF_SRC = "/root/reference/assignment-5/sequential"
RESULTS = os.path.join(REPO, "results")


def _solution_took(output: str) -> float:
    m = re.search(r"Solution took\s+([0-9.]+)s", output)
    return float(m.group(1)) if m else float("nan")


def match() -> dict:
    import numpy as np

    from pampi_tpu.utils.datio import read_pressure, read_velocity

    rec = {"artifact": "northstar_residual_match",
           "config": "assignment-5/sequential/dcavity.par VERBATIM "
                     "(100^2, Re=10, te=10, itermax=1000, eps=1e-3)",
           "dtype": "float64 both sides"}
    with tempfile.TemporaryDirectory() as td:
        exe = os.path.join(td, "exe-ref")
        subprocess.run(
            ["gcc", "-O3", "-std=c99", "-D_GNU_SOURCE", "-o", exe]
            + sorted(
                os.path.join(REF_SRC, "src", f)
                for f in os.listdir(os.path.join(REF_SRC, "src"))
                if f.endswith(".c")
            )
            + ["-lm"],
            check=True, capture_output=True, text=True,
        )
        cdir = os.path.join(td, "c")
        jdir = os.path.join(td, "j")
        os.makedirs(cdir)
        os.makedirs(jdir)
        par = os.path.join(REF_SRC, "dcavity.par")

        t0 = time.perf_counter()
        cp = subprocess.run([exe, par], cwd=cdir, check=True,
                            capture_output=True, text=True, timeout=3600)
        rec["c_wall_s"] = round(time.perf_counter() - t0, 2)
        rec["c_solution_took_s"] = _solution_took(cp.stdout)

        env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO}
        env.pop("XLA_FLAGS", None)
        t0 = time.perf_counter()
        jp = subprocess.run([sys.executable, "-m", "pampi_tpu", par],
                            cwd=jdir, check=True, env=env,
                            capture_output=True, text=True, timeout=3600)
        rec["jax_wall_s"] = round(time.perf_counter() - t0, 2)
        rec["jax_solution_took_s"] = _solution_took(jp.stdout)

        pc = read_pressure(os.path.join(cdir, "pressure.dat"))
        uc, vc = read_velocity(os.path.join(cdir, "velocity.dat"))
        pj = read_pressure(os.path.join(jdir, "pressure.dat"))
        uj, vj = read_velocity(os.path.join(jdir, "velocity.dat"))
        dp = (pj - pj.mean()) - (pc - pc.mean())  # Neumann nullspace removed
        rec["max_abs_du"] = float(np.abs(uj - uc).max())
        rec["max_abs_dv"] = float(np.abs(vj - vc).max())
        rec["max_abs_dp_mean_adjusted"] = float(np.abs(dp).max())
        # velocities (the physical solution) are held to the <1e-6 bar —
        # which is also the .dat format's quantization floor (%f, 6
        # decimals), i.e. the tightest match the reference's own output
        # format can express. Pressure converges per-step to eps=1e-3 under
        # DIFFERENT SOR orderings (red-black here, lexicographic in C), so
        # its floor is the solve tolerance, not the format: held to <5e-6.
        rec["bar_uv"] = 1e-6
        rec["bar_p"] = 5e-6
        # the diffs are differences of 6-decimal fixed-point text, so round
        # away float-repr noise (1.000000000001e-06 is one quantum, not a
        # bar violation) before comparing
        rec["pass"] = bool(
            round(rec["max_abs_du"], 10) <= 1e-6
            and round(rec["max_abs_dv"], 10) <= 1e-6
            and round(rec["max_abs_dp_mean_adjusted"], 10) < 5e-6
        )
    return rec


def match4096(steps: int = 50) -> dict:
    """Field-level C-vs-TPU comparison AT THE NORTH-STAR GRID (VERDICT r3
    item 3): both drivers run the same generated dcavity 4096^2 .par for a
    fixed ~`steps`-step interval, f64 both sides, and the .dat fields are
    held to the `match` artifact's bars. The pressure solves are
    itermax-capped at this size for ANY solver the reference ships (measured:
    residual ~1e5 after 20000 sweeps at step 0 — eps is unreachable), so the
    capped trajectory depends on the sweep ORDERING; the framework side
    therefore runs `tpu_solver sor_lex` — the reference's lexicographic
    `solve` (assignment-5/sequential/src/solver.c:159-176) as the oracle
    mode — so both sides walk the SAME iterate sequence and the comparison
    is meaningful at the format floor. The SPEED claim stays with the rb
    quarters path (run4096); this artifact establishes that the framework
    advances the same physics as the C binary at this size."""
    import numpy as np

    from pampi_tpu.utils.datio import read_pressure, read_velocity

    N = 4096
    reynolds, tau = 1000.0, 0.5
    dx = 1.0 / N
    dt0 = tau * 0.5 * reynolds / (2.0 / (dx * dx))  # viscous-CFL dt
    te = (steps + 0.5) * dt0
    rec = {
        "artifact": "northstar_field_match_4096",
        "config": f"dcavity {N}^2, Re=1000, tau=0.5, itermax=100, eps=1e-3,"
                  f" omg=1.7, te={te:.6e} (~{steps} steps at the"
                  " viscous-bound dt), float64 BOTH sides",
        "solver_note": (
            "both sides run LEXICOGRAPHIC SOR: the C binary natively "
            "(solver.c:159-176), the framework via tpu_solver sor_lex "
            "(ops/sor.lex_sweep — the same dependency structure as a "
            "row-scan + associative within-row recurrence; only the "
            "floating-point association differs, at rounding level). "
            "Solves are itermax-capped at this size on both sides, so "
            "ordering-parity is what makes the capped trajectories "
            "comparable."
        ),
    }
    base = open(os.path.join(REF_SRC, "dcavity.par")).read()

    def patch(txt, key, val):
        return re.sub(rf"(?m)^({key}\s+)\S+", rf"\g<1>{val}", txt)

    for key, val in (("imax", N), ("jmax", N), ("re", reynolds),
                     ("te", f"{te:.9e}"), ("itermax", 100),
                     ("eps", 0.001), ("omg", 1.7), ("tau", tau)):
        base = patch(base, key, val)
    # framework-only keys (prefix-matched C parser skips them). tpu_chunk 1:
    # the f64 lex-scan step inside a MULTI-trip chunk while_loop crashes the
    # TPU worker at this size (probed: chunk=4 and 64 crash, a single-trip
    # chunk and the bare step run fine), so each dispatch carries one step.
    base += "\ntpu_solver sor_lex\ntpu_dtype float64\ntpu_chunk 1\n"

    # the C side is a ~30-min single-core run: keep its outputs in a cache
    # dir keyed by the generated .par, so a framework-side failure (or a
    # rerun) never repeats it. The cache is gitignored scratch, not an
    # artifact.
    cache = os.path.join(REPO, ".cache_match4096")
    os.makedirs(cache, exist_ok=True)
    par = os.path.join(cache, "dcavity4096.par")

    def c_view(txt):
        # the C parser ignores tpu_* keys, so framework-only knob changes
        # must not invalidate the ~30-min cached C run
        return "".join(ln for ln in txt.splitlines(True)
                       if not ln.startswith("tpu_"))

    stale = not (os.path.exists(par)
                 and c_view(open(par).read()) == c_view(base))
    if stale:
        with open(par, "w") as f:
            f.write(base)
    elif open(par).read() != base:
        with open(par, "w") as f:
            f.write(base)
    cdir = os.path.join(cache, "c")
    have_c = (not stale
              and os.path.exists(os.path.join(cdir, "pressure.dat"))
              and os.path.exists(os.path.join(cdir, "velocity.dat")))
    with tempfile.TemporaryDirectory() as td:
        if not have_c:
            exe = os.path.join(td, "exe-ref")
            subprocess.run(
                ["gcc", "-O3", "-std=c99", "-D_GNU_SOURCE", "-o", exe]
                + sorted(
                    os.path.join(REF_SRC, "src", f)
                    for f in os.listdir(os.path.join(REF_SRC, "src"))
                    if f.endswith(".c")
                )
                + ["-lm"],
                check=True, capture_output=True, text=True,
            )
            os.makedirs(cdir, exist_ok=True)
            t0 = time.perf_counter()
            cp = subprocess.run([exe, par], cwd=cdir, check=True,
                                capture_output=True, text=True,
                                timeout=7200)
            with open(os.path.join(cdir, "wall.txt"), "w") as f:
                f.write(f"{time.perf_counter() - t0:.2f}\n"
                        f"{_solution_took(cp.stdout)}\n")
        walls = open(os.path.join(cdir, "wall.txt")).read().split()
        rec["c_wall_s"] = float(walls[0])
        rec["c_solution_took_s"] = float(walls[1])
        jdir = os.path.join(td, "j")
        os.makedirs(jdir)

        # PREPEND the repo (unlike `match`, which replaces PYTHONPATH to
        # force cpu): the ambient path carries the accelerator plugin's
        # sitecustomize, and this artifact runs on the real chip
        inherited = os.environ.get("PYTHONPATH", "")
        env = {**os.environ,
               "PYTHONPATH": REPO + (":" + inherited if inherited else "")}
        t0 = time.perf_counter()
        jp = subprocess.run([sys.executable, "-m", "pampi_tpu", par],
                            cwd=jdir, check=True, env=env,
                            capture_output=True, text=True, timeout=7200)
        rec["jax_wall_s"] = round(time.perf_counter() - t0, 2)
        rec["jax_solution_took_s"] = _solution_took(jp.stdout)

        pc = read_pressure(os.path.join(cdir, "pressure.dat"))
        uc, vc = read_velocity(os.path.join(cdir, "velocity.dat"))
        pj = read_pressure(os.path.join(jdir, "pressure.dat"))
        uj, vj = read_velocity(os.path.join(jdir, "velocity.dat"))
        dp = (pj - pj.mean()) - (pc - pc.mean())
        rec["max_abs_du"] = float(np.abs(uj - uc).max())
        rec["max_abs_dv"] = float(np.abs(vj - vc).max())
        rec["max_abs_dp_mean_adjusted"] = float(np.abs(dp).max())
        # same bars as `match` (the .dat format floor; see that artifact)
        rec["bar_uv"] = 1e-6
        rec["bar_p"] = 5e-6
        rec["pass"] = bool(
            round(rec["max_abs_du"], 10) <= 1e-6
            and round(rec["max_abs_dv"], 10) <= 1e-6
            and round(rec["max_abs_dp_mean_adjusted"], 10) < 5e-6
        )
    return rec


def run4096(te: float = 0.15, lookahead: int = 2, chunk: int = 0) -> dict:
    import jax
    import jax.numpy as jnp

    from pampi_tpu.models.ns2d import NS2DSolver
    from pampi_tpu.utils.params import Parameter

    N = 4096
    param = Parameter(
        name="dcavity", imax=N, jmax=N, re=1000.0, te=te, tau=0.5,
        itermax=100, eps=1e-3, omg=1.7, gamma=0.9, tpu_dtype="float32",
        # every solve is itermax-capped at this size, so deeper temporal
        # blocking is pure win: 12.7 vs 21.3 ms/step at the n4 default
        # (round-3 depth sweep; the .par default stays 4 because small
        # CONVERGING workloads would overshoot by up to n-1 iterations)
        tpu_sor_inner=16,
        # headroom levers (VERDICT r4 item 5): deeper dispatch pipelining
        # and fewer host syncs (the flat capped-solve knob measured
        # neutral — see params.py tpu_flat_solve); recorded in the
        # artifact
        tpu_lookahead=lookahead, tpu_chunk=chunk, tpu_flat_solve=1,
    )
    from pampi_tpu.utils import telemetry

    telemetry.start_run(tool="northstar.run4096")
    s = NS2DSolver(param, dtype=jnp.float32)
    # compile OUTSIDE the timed window (refconfig precedent: the C side's
    # 'Solution took' is a solver-only timer, main.c:63): one chunk call
    # from the pristine state, result discarded (initial_state matches the
    # chunk's telemetry arity)
    warm = s._chunk_fn(*s.initial_state())
    float(warm[3])
    t0 = time.perf_counter()
    s.run(progress=True)
    wall = time.perf_counter() - t0
    steps = s.nt
    sites = N * N

    # sampled window from the FINAL state: the PRODUCTION step with the
    # solve's discarded outputs exposed (NS2DSolver._build_step
    # instrumented=True) — measures, not assumes, iterations/step
    step_i = jax.jit(s._build_step(instrumented=True))
    u, v, p = s.u, s.v, s.p
    t = jnp.asarray(s.t, jnp.float32)
    nt = jnp.asarray(s.nt, jnp.int32)
    iters, dts = [], []
    res = None
    for _ in range(20):
        u, v, p, t, nt, res, it, dt = step_i(u, v, p, t, nt)
        iters.append(int(it))
        dts.append(float(dt))
    mean_it = sum(iters) / len(iters)

    step_ms = wall / max(steps, 1) * 1e3

    # solve/non-solve phase decomposition (round 6): time the step's OWN
    # solve closure on the final state's rhs — non-solve = step - solve is
    # the phase chain the fused kernels (ops/ns2d_fused.py) replace; the
    # round-5 artifact measured it at 6.4 ms/step vs a ~0.8 ms HBM floor,
    # and the fusion acceptance bar is <= 1.6 ms/step. Shared protocol:
    # NS2DSolver.time_solve_ms (rhs via the solver's own pre-solve chain,
    # same harness bench.py records — the two artifacts stay comparable).
    from pampi_tpu.utils import dispatch as _dispatch

    if jax.default_backend() == "tpu":
        solve_ms = s.time_solve_ms(reps=10)
        phase_decomposition = {
            "step_ms": round(step_ms, 3),
            "solve_ms": round(solve_ms, 3),
            "nonsolve_ms": round(step_ms - solve_ms, 3),
            "fused_phases": _dispatch.last("ns2d_phases"),
            "round5_reference_nonsolve_ms": 6.4,
            "bar_nonsolve_ms": 1.6,
        }
    else:
        # off-TPU the standalone jitted solve compiles slower than the
        # solve fused into the chunk program, so step - solve goes
        # negative (see bench.py's identical guard) — don't record a
        # meaningless decomposition next to the acceptance bar
        phase_decomposition = {
            "step_ms": round(step_ms, 3),
            "solve_ms": None,
            "nonsolve_ms": None,
            "decomposition_note": "TPU-only (see bench.py)",
            "fused_phases": _dispatch.last("ns2d_phases"),
        }

    # the 8-rank MPI/ICX proxy at this workload: measured ~1.3G
    # updates/s/core x 8 = 10.56G; ms/step = sites*iters/10.56e9
    proxy_ms = sites * mean_it / 10.56e9 * 1e3
    rec = {
        "artifact": "northstar_dcavity4096",
        "config": f"dcavity {N}^2 f32, Re=1000, tau=0.5, itermax=100, "
                  "eps=1e-3, omg=1.7, tpu_solver sor, layout auto(=quarters)",
        "backend": jax.default_backend(),
        "te": te,
        "steps": steps,
        "wall_s": round(wall, 2),
        "ms_per_step": round(step_ms, 2),
        "vs_8rank_proxy_x": round(proxy_ms / step_ms, 2),
        "lookahead": lookahead,
        "chunk": chunk or "model default (64)",
        "site_steps_per_s": round(sites * steps / wall / 1e9, 3),
        "phase_decomposition": phase_decomposition,
        "sampled_sor_iters_per_step": round(mean_it, 1),
        "sampled_dt": dts[-1],
        "final_pressure_residual": float(res),
        "residual_note": (
            "itermax=100 caps every solve at this size (sampled iters/step "
            "= itermax): at 4096^2 SOR needs O(N) iterations to reach eps, "
            "so the per-step solve is a capped smoother — the reference C "
            "solver caps identically on this config (same while-loop bound, "
            "solver.c:604), exactly like its canal configs whose solves "
            "never converge; converged-solve equivalence vs the C binary is "
            "established by the `match` artifact on the reference's own "
            "committed config"
        ),
        "sor_site_updates_per_s_1e9": round(
            sites * mean_it / (step_ms / 1e3) / 1e9, 2
        ),
        "extrapolation_note": (
            "te=10 at the sampled dt would be "
            f"~{int(10 / dts[-1])} steps ~= "
            f"{round(10 / dts[-1] * step_ms / 1e3 / 3600, 1)} h on one chip "
            "(linear in steps; the 8-rank MPI/ICX baseline at the measured "
            "~1.3G updates/s/core-x8 proxy would need the same step count at "
            f"~{round(sites * mean_it / 10.56e9 * 1e3, 0)} ms/step)"
        ),
        "protocol_note": (
            "round 4: compile is excluded (one warm chunk call before the "
            "timed window — the C baseline's 'Solution took' is likewise a "
            "solver-only timer) and the chunk dispatch is pipelined "
            "(tpu_lookahead=2), which closed the end-to-end gap to the "
            "latency-cancelled chained-step rate: same-session protocol "
            "measured 17.3 ms/step (n16) vs this end-to-end number — the "
            "dispatch overhead that cost round 3 a 24-31 vs 12.7 spread is "
            "gone. Remaining session-to-session spread is chip/tunnel "
            "weather (round-3 protocol measured 12.7 on the same kernel)."
        ),
    }
    # the decomposition as shared telemetry spans + the artifact record
    # (no-ops when PAMPI_TELEMETRY is unset)
    telemetry.emit_decomposition(
        "northstar_dcavity4096", phase_decomposition["step_ms"],
        phase_decomposition["solve_ms"], phase_decomposition["nonsolve_ms"],
        phases=_dispatch.last("ns2d_phases"))
    telemetry.emit("metric", metric="northstar_dcavity4096_ms_per_step",
                   value=rec["ms_per_step"], unit="ms/step",
                   steps=steps, final_pressure_residual=rec["final_pressure_residual"])
    return rec


def refconfig() -> dict:
    """The literal 'dcavity wall-clock to converge' (BASELINE.json metric):
    the VERBATIM committed dcavity.par (100^2, Re=10, te=10) run end-to-end
    on the CURRENT backend at f32, recording the reference driver's own
    'Solution took' number for the BASELINE.md comparison row (the compiled
    C binary measures 154.5 s on this container's host; `match` re-measures
    it)."""
    import jax

    from pampi_tpu.models.ns2d import NS2DSolver
    from pampi_tpu.utils.params import read_parameter

    import jax.numpy as jnp

    param = read_parameter(os.path.join(REF_SRC, "dcavity.par")).replace(
        tpu_dtype="float32"
    )
    s = NS2DSolver(param)
    # compile OUTSIDE the timed window (the C side's 'Solution took' is a
    # solver-only timer, main.c:63): one chunk call from the pristine state,
    # result discarded — the solver's stored state is untouched
    warm = s._chunk_fn(*s.initial_state())
    float(warm[3])  # scalar fence
    t0 = time.perf_counter()
    s.run(progress=True)
    wall = time.perf_counter() - t0
    return {
        "artifact": "northstar_refconfig",
        "config": "assignment-5/sequential/dcavity.par VERBATIM, f32",
        "backend": jax.default_backend(),
        "steps": s.nt,
        "solution_took_s": round(wall, 2),
        "c_binary_note": (
            "the freshly compiled C binary's 'Solution took' on this "
            "container's host is recorded by the `match` artifact "
            "(84-155 s depending on host load)"
        ),
    }


if __name__ == "__main__":
    from pampi_tpu.utils import xlacache

    xlacache.enable()  # repeated 4096² builds become disk loads
    mode = sys.argv[1] if len(sys.argv) > 1 else "run4096"
    os.makedirs(RESULTS, exist_ok=True)
    if mode == "match":
        rec = match()
        out = os.path.join(RESULTS, "northstar_residual_match.json")
    elif mode == "match4096":
        steps = int(sys.argv[2]) if len(sys.argv) > 2 else 50
        rec = match4096(steps)
        out = os.path.join(RESULTS, "northstar_field_match_4096.json")
    elif mode == "run4096":
        te = float(sys.argv[2]) if len(sys.argv) > 2 else 0.15
        la = int(sys.argv[3]) if len(sys.argv) > 3 else 2
        ch = int(sys.argv[4]) if len(sys.argv) > 4 else 0
        rec = run4096(te, la, ch)
        out = os.path.join(RESULTS, "northstar_dcavity4096.json")
        # the ≥10x bar needs MARGIN across sessions (VERDICT r4 item 5):
        # keep every prior session's headline in the artifact instead of
        # overwriting it — and MERGE over the old record so curated
        # analysis keys (round5_margin_assessment, ...) survive re-runs
        # (tools/_artifact.write_merged below does the merge)
        if os.path.exists(out):
            with open(out) as fh:
                old = json.load(fh)
            prev = old.pop("previous_sessions", [])
            prev.append({
                k: old.get(k)
                for k in ("wall_s", "ms_per_step", "vs_8rank_proxy_x",
                          "steps", "te", "site_steps_per_s")
            })
            rec["previous_sessions"] = prev
    elif mode == "refconfig":
        rec = refconfig()
        out = os.path.join(RESULTS, "northstar_refconfig.json")
    else:
        raise SystemExit(
            f"unknown mode {mode!r} (match|match4096|run4096|refconfig)"
        )
    from tools._artifact import write_merged

    write_merged(out, rec)
