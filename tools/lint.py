"""tracecheck — the static contract checker driver (pampi_tpu/analysis/).

    python tools/lint.py [--only ast|halo|jaxpr|artifacts] [--update]
                         [--contracts PATH] [paths...]

Three passes (all by default, `make lint`):

  ast        repo lint rules over pampi_tpu/, tools/, tests/ (or the
             given paths) — file:line diagnostics, `# lint: allow(<rule>)`
             escapes (analysis/astlint.py)
  halo       stencil/Pallas access footprints vs declared halo depths
             (analysis/halocheck.py)
  jaxpr      the dispatch-matrix trace contracts vs CONTRACTS.json
             (analysis/jaxprcheck.py); `--update` regenerates the
             baseline after an intended program change
  artifacts  the committed BENCH/MULTICHIP schema lint
             (tools/check_artifact.py) — CI, the test suite and this
             driver share the one analysis layer

The jaxpr pass pins its environment (CPU backend, x64, 8 host devices —
the test harness environment) BEFORE importing jax, so the committed
baseline is reproducible on any machine with the same jax version; on a
different jax the hash comparison is reported as environment drift and
the structural contracts still run.

Exit 0 = clean; 1 = violations (one `file:line: [rule] message` per
line); 2 = driver error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONTRACTS = os.path.join(REPO, "CONTRACTS.json")

# the pinned trace environment — must precede any jax import
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_ENABLE_X64", "1")
if "--xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )

sys.path.insert(0, REPO)


def run_ast(paths) -> list:
    from pampi_tpu.analysis import astlint

    if paths:
        violations, errors = [], []
        for p in paths:
            if os.path.isdir(p):
                vs, errs = astlint.lint_tree(
                    os.path.dirname(os.path.abspath(p)) or ".",
                    subdirs=(os.path.basename(os.path.abspath(p)),))
                errors += errs
            else:
                # lint_file returns (violations, one error string or None)
                vs, err = astlint.lint_file(p, root=REPO)
                if err:
                    errors.append(err)
            violations += vs
    else:
        violations, errors = astlint.lint_tree(REPO)
    for e in errors:
        print(f"ast: {e}", file=sys.stderr)
    return violations + [
        astlint.Violation(e.split(":", 1)[0], 1, "parse-error", e)
        for e in errors
    ]


def run_halo() -> list:
    from pampi_tpu.analysis import halocheck

    return halocheck.check_all()


def run_jaxpr(update: bool, contracts_path: str) -> list:
    from pampi_tpu.analysis import jaxprcheck

    baseline = None
    if os.path.exists(contracts_path):
        with open(contracts_path) as fh:
            baseline = json.load(fh)
    elif not update:
        print(f"jaxpr: no baseline at {contracts_path} — tracing fresh "
              "(run with --update to commit one)", file=sys.stderr)
    violations, fresh = jaxprcheck.run(baseline=baseline, update=update)
    if update:
        with open(contracts_path, "w") as fh:
            json.dump(fresh, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"jaxpr: baseline written to {contracts_path} "
              f"({len(fresh['configs'])} configs)")
    return violations


def run_artifacts() -> list:
    from pampi_tpu.analysis.astlint import Violation

    import check_artifact as ca

    errs = []
    for path in ca.default_files():
        errs += [Violation(os.path.basename(path), 1, "artifact", e)
                 for e in ca.lint_file(path)]
    return errs


def main(argv) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--only", choices=("ast", "halo", "jaxpr", "artifacts"))
    ap.add_argument("--update", action="store_true",
                    help="regenerate the CONTRACTS.json baseline")
    ap.add_argument("--contracts", default=CONTRACTS)
    ap.add_argument("paths", nargs="*",
                    help="files/dirs for the ast pass (default: the repo)")
    args = ap.parse_args(argv[1:])

    passes = (args.only,) if args.only else ("ast", "halo", "jaxpr",
                                             "artifacts")
    total = 0
    for name in passes:
        if name == "ast":
            vs = run_ast(args.paths)
        elif name == "halo":
            vs = run_halo()
        elif name == "jaxpr":
            vs = run_jaxpr(args.update, args.contracts)
        else:
            vs = run_artifacts()
        for v in vs:
            print(str(v))
        status = "ok" if not vs else f"{len(vs)} violation(s)"
        print(f"[{name}] {status}")
        total += len(vs)
    return 1 if total else 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
