"""tracecheck — the static contract checker driver (pampi_tpu/analysis/).

    python tools/lint.py [--only PASS[,PASS...]] [--update]
                         [--contracts PATH] [--vmem-budget BYTES] [paths...]

Six analysis passes plus the artifact lint (all by default, `make lint`):

  ast        repo lint rules over pampi_tpu/, tools/, tests/ (or the
             given paths) — file:line diagnostics, `# lint: allow(<rule>)`
             escapes (analysis/astlint.py)
  halo       stencil/Pallas access footprints vs declared halo depths
             (analysis/halocheck.py)
  jaxpr      the dispatch-matrix trace contracts vs CONTRACTS.json
             (analysis/jaxprcheck.py); `--update` regenerates the
             baseline after an intended program change
  comm       collective census + per-step halo traffic bytes of every
             traced chunk vs the `comm` section of CONTRACTS.json and
             the solvers' static halo-byte records
             (analysis/commcheck.py); `--update` regenerates
  pallas     pallas_call block tiling, static VMEM footprint vs budget,
             grid×index-map bounds, aliasing (analysis/palcheck.py)
  prec       precision-flow contracts: the cast census vs the
             `precision` section of CONTRACTS.json, the implicit-
             downcast ban, f64 oracle purity, the reduction-order audit
             and the matrix-wide eps-floor check; advisory (bf16 scout)
             findings are reported on stderr, not gated
             (analysis/preccheck.py); `--update` regenerates
  artifacts  the committed BENCH/MULTICHIP/CONTRACTS schema lint
             (tools/check_artifact.py) — CI, the test suite and this
             driver share the one analysis layer
  trend      the BENCH perf-trend regression gate (tools/bench_trend.py):
             the newest point of every (metric, backend) series vs the
             best earlier same-backend point — a perf-regressing PR
             fails on CPU before any TPU time is spent

The jaxpr/comm/pallas/prec passes share ONE trace of the config matrix
per run (`jaxprcheck.trace_matrix`). `--only comm` is the overlap
refactor's inner loop (`make lint-comm`): the comm contract alone, one
matrix trace; `--only prec` (`make lint-prec`) is the mixed-precision
twin.

The trace passes pin their environment (CPU backend, x64, 8 host devices
— the test harness environment) BEFORE importing jax, so the committed
baseline is reproducible on any machine with the same jax version; on a
different jax the hash/count comparisons are reported as environment
drift and the structural contracts still run.

Exit 0 = clean; 1 = violations (one `file:line: [rule] message` per
line); 2 = driver error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONTRACTS = os.path.join(REPO, "CONTRACTS.json")

PASSES = ("ast", "halo", "jaxpr", "comm", "pallas", "prec", "artifacts",
          "trend")
TRACE_PASSES = ("jaxpr", "comm", "pallas", "prec")

# the pinned trace environment — must precede any jax import
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_ENABLE_X64", "1")
if "--xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )

sys.path.insert(0, REPO)


def run_ast(paths) -> list:
    from pampi_tpu.analysis import astlint

    if paths:
        violations, errors = [], []
        for p in paths:
            if os.path.isdir(p):
                vs, errs = astlint.lint_tree(
                    os.path.dirname(os.path.abspath(p)) or ".",
                    subdirs=(os.path.basename(os.path.abspath(p)),))
                errors += errs
            else:
                # lint_file returns (violations, one error string or None)
                vs, err = astlint.lint_file(p, root=REPO)
                if err:
                    errors.append(err)
            violations += vs
    else:
        violations, errors = astlint.lint_tree(REPO)
    for e in errors:
        print(f"ast: {e}", file=sys.stderr)
    return violations + [
        astlint.Violation(e.split(":", 1)[0], 1, "parse-error", e)
        for e in errors
    ]


def run_halo() -> list:
    from pampi_tpu.analysis import halocheck

    return halocheck.check_all()


def run_artifacts() -> list:
    from pampi_tpu.analysis.astlint import Violation

    import check_artifact as ca

    errs = []
    for path in ca.default_files():
        errs += [Violation(os.path.basename(path), 1, "artifact", e)
                 for e in ca.lint_file(path)]
    return errs


def run_trend() -> list:
    from pampi_tpu.analysis.astlint import Violation

    import bench_trend as bt

    return [Violation("BENCH_r*.json", 1, "bench-trend", e)
            for e in bt.lint()]


class TraceContext:
    """The shared state of the trace passes: the baseline on disk, one
    lazily-built trace of the config matrix, and the fresh baseline
    sections accumulated for --update (written once, merged, at the
    end — `--only comm --update` regenerates the comm section without
    touching the configs section, and vice versa)."""

    def __init__(self, contracts_path: str, update: bool):
        self.path = contracts_path
        self.update = update
        self.baseline = None
        if os.path.exists(contracts_path):
            with open(contracts_path) as fh:
                self.baseline = json.load(fh)
        elif not update:
            print(f"no baseline at {contracts_path} — tracing fresh "
                  "(run with --update to commit one)", file=sys.stderr)
        self._traced = None
        self.fresh_configs = None
        self.fresh_env = None
        self.fresh_comm = None
        self.fresh_prec = None

    def traced(self):
        if self._traced is None:
            from pampi_tpu.analysis import jaxprcheck

            self._traced = jaxprcheck.trace_matrix()
        return self._traced

    def env_matches(self) -> bool:
        from pampi_tpu.analysis import jaxprcheck

        return (self.baseline or {}).get("env") == jaxprcheck.environment()

    def run_jaxpr(self) -> list:
        from pampi_tpu.analysis import jaxprcheck

        violations, fresh = jaxprcheck.run(
            baseline=self.baseline, update=self.update,
            traced=self.traced())
        self.fresh_configs = fresh["configs"]
        self.fresh_env = fresh["env"]
        return violations

    def run_comm(self) -> list:
        from pampi_tpu.analysis import commcheck, jaxprcheck

        base_comm = (self.baseline or {}).get("comm")
        if base_comm is None and self.baseline is not None \
                and not self.update:
            print("comm: baseline has no comm section — tracing fresh "
                  "(run with --update to commit one)", file=sys.stderr)
        env_matches = self.env_matches()
        if base_comm is not None and not env_matches and not self.update:
            # the jaxpr pass owns the env-drift VIOLATION (one per run);
            # when comm runs alone, still say why counts aren't compared
            print("comm: baseline environment differs — census counts "
                  "not compared (structural rules still checked; "
                  "regenerate with tools/lint.py --update)",
                  file=sys.stderr)
        violations, fresh = commcheck.run(
            baseline=base_comm, update=self.update, traced=self.traced(),
            env_matches=env_matches)
        self.fresh_comm = fresh
        if self.fresh_env is None:
            self.fresh_env = jaxprcheck.environment()
        return violations

    def run_pallas(self, budget) -> list:
        from pampi_tpu.analysis import palcheck

        return palcheck.run(traced=self.traced(), budget=budget)

    def run_prec(self) -> list:
        from pampi_tpu.analysis import jaxprcheck, preccheck

        base_prec = (self.baseline or {}).get("precision")
        if base_prec is None and self.baseline is not None \
                and not self.update:
            print("prec: baseline has no precision section — tracing "
                  "fresh (run with --update to commit one)",
                  file=sys.stderr)
        env_matches = self.env_matches()
        if base_prec is not None and not env_matches and not self.update:
            print("prec: baseline environment differs — cast census not "
                  "compared (precision rules still checked; regenerate "
                  "with tools/lint.py --update)", file=sys.stderr)
        violations, fresh, notes = preccheck.run(
            baseline=base_prec, update=self.update, traced=self.traced(),
            env_matches=env_matches)
        for note in notes:
            print(f"prec advisory: {note}", file=sys.stderr)
        self.fresh_prec = fresh
        if self.fresh_env is None:
            self.fresh_env = jaxprcheck.environment()
        return violations

    def write(self) -> None:
        """Merge the fresh sections over the on-disk baseline and write
        once. Sections whose pass did not run this invocation are
        preserved — UNLESS the trace environment changed, in which case a
        preserved section would pair old-env hashes/counts with the new
        `env` key and silently defeat env-drift detection, so the
        missing section is regenerated from the shared matrix too (the
        traces are already in memory; only the bookkeeping re-runs)."""
        from pampi_tpu.analysis import commcheck, jaxprcheck, preccheck

        env_changed = (self.baseline or {}).get("env") != self.fresh_env
        if env_changed and self.baseline is not None:
            any_fresh = any(f is not None for f in (
                self.fresh_configs, self.fresh_comm, self.fresh_prec))
            if any_fresh and self.fresh_configs is None:
                _, fresh = jaxprcheck.run(update=True, traced=self.traced())
                self.fresh_configs = fresh["configs"]
            if any_fresh and self.fresh_comm is None:
                _, self.fresh_comm = commcheck.run(update=True,
                                                   traced=self.traced())
            if any_fresh and self.fresh_prec is None:
                _, self.fresh_prec, _ = preccheck.run(update=True,
                                                      traced=self.traced())
        merged = dict(self.baseline or {})
        merged["version"] = jaxprcheck.BASELINE_VERSION
        if self.fresh_env is not None:
            merged["env"] = self.fresh_env
        if self.fresh_configs is not None:
            merged["configs"] = self.fresh_configs
        if self.fresh_comm is not None:
            merged["comm"] = self.fresh_comm
        if self.fresh_prec is not None:
            merged["precision"] = self.fresh_prec
        with open(self.path, "w") as fh:
            json.dump(merged, fh, indent=1, sort_keys=True)
            fh.write("\n")
        sections = [s for s, fresh in (("configs", self.fresh_configs),
                                       ("comm", self.fresh_comm),
                                       ("precision", self.fresh_prec))
                    if fresh is not None]
        print(f"baseline written to {self.path} "
              f"(sections regenerated: {', '.join(sections)})")


def main(argv) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--only",
                    help="comma-separated subset of passes to run: "
                         + ",".join(PASSES))
    ap.add_argument("--update", action="store_true",
                    help="regenerate the CONTRACTS.json baseline "
                         "(configs/comm/precision sections of the "
                         "passes run)")
    ap.add_argument("--contracts", default=CONTRACTS)
    ap.add_argument("--vmem-budget", type=int, default=None,
                    help="override the pallas pass VMEM budget in bytes "
                         "(default: each kernel's declared "
                         "vmem_limit_bytes)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs for the ast pass (default: the repo)")
    args = ap.parse_args(argv[1:])

    if args.only:
        chosen = {p.strip() for p in args.only.split(",") if p.strip()}
        bad = [p for p in sorted(chosen) if p not in PASSES]
        if bad:
            print(f"unknown pass(es) {bad}; choose from {PASSES}",
                  file=sys.stderr)
            return 2
        # canonical order regardless of the flag's spelling: artifacts
        # must run AFTER a pending --update flush, trace passes share
        # one matrix in matrix order
        passes = tuple(p for p in PASSES if p in chosen)
    else:
        passes = PASSES

    ctx = None
    if any(p in TRACE_PASSES for p in passes):
        ctx = TraceContext(args.contracts, args.update)

    total = 0
    written = False
    for name in passes:
        if name == "ast":
            vs = run_ast(args.paths)
        elif name == "halo":
            vs = run_halo()
        elif name == "jaxpr":
            vs = ctx.run_jaxpr()
        elif name == "comm":
            vs = ctx.run_comm()
        elif name == "pallas":
            vs = ctx.run_pallas(args.vmem_budget)
        elif name == "prec":
            vs = ctx.run_prec()
        elif name == "trend":
            vs = run_trend()
        else:
            # the artifact lint reads CONTRACTS.json from disk — flush a
            # pending --update first so it lints the regenerated baseline
            if ctx is not None and args.update and not written:
                ctx.write()
                written = True
            vs = run_artifacts()
        for v in vs:
            print(str(v))
        status = "ok" if not vs else f"{len(vs)} violation(s)"
        print(f"[{name}] {status}")
        total += len(vs)
    if ctx is not None and args.update and not written:
        ctx.write()
    return 1 if total else 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
