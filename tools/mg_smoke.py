"""MG fused-cycle smoke: the whole ISSUE 16 seam end-to-end on whatever
backend this host has (make mg-smoke — CPU-safe via interpret mode).

    python tools/mg_smoke.py [outdir]

Proves, before any TPU time is spent:

- PARITY: the fused V-cycle (tpu_mg_fused on — the DOWN/UP Pallas pair)
  converges to the SAME iterate as the per-level jnp ladder (off) in the
  same number of cycles, on 2-D/3-D × plain/obstacle. The bottom budgets
  are shrunk so the tiny smoke grids build real multi-level plans (the
  same geometry trick tests/test_mg_fused.py uses).
- LAUNCH COUNT: every fused solve's traced program carries EXACTLY the
  2 pallas_calls its dispatch record advertises ("launches=2"), and the
  one-launch class cycle exactly 1 — the amortization property the
  kernels exist for, pinned statically (jaxprcheck.count_prim).
- REFUSAL: a ragged single-level plan refuses the fused cycle WITH a
  recorded reason (the dispatch record is the contract surface).
- the telemetry plane: the `mg_launches_per_cycle` metric record, the
  merge into a BENCH-shaped artifact, and `tools/check_artifact.py`
  accepting the merged block (incl. the MG_LAUNCH_KEYS census keys).
- EPS FLOOR (ISSUE 17): the parity cases A/B at eps=0 — the sanctioned
  FIXED-ITERATION comparison mode (every solve runs to itermax), silent
  by contract. A floor-adjacent eps instead warns at build time
  (utils/precision.check_eps_floor): near the f32 residual floor the
  loop residual is summation-order noise and fused-vs-ladder iteration
  counts legitimately diverge — the ROADMAP footgun this smoke pins
  shut from both sides (the warning fires, and exactly once).
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# CPU-stable smoke environment: must precede any jax import (the
# tools/lint.py convention); a TPU image just keeps its own backend
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

# fused-vs-ladder tolerance: both paths run the identical red-black ω=1
# arithmetic but the fused kernel evaluates full planes with masked-out
# dead cells, so f32 summation order differs at the ulp scale
TOL = 2e-5


def _parity(failures: list[str]) -> list[dict]:
    """The four fused-vs-ladder cases + launch pins. Returns the metric
    lines recorded along the way."""
    import jax
    import jax.numpy as jnp

    from pampi_tpu.analysis.jaxprcheck import count_prim
    from pampi_tpu.ops import multigrid as mg
    from pampi_tpu.ops import obstacle as obst
    from pampi_tpu.ops.obstacle3d import make_masks_3d
    from pampi_tpu.utils import dispatch as disp
    from pampi_tpu.utils import telemetry as tm

    dtype = jnp.float32
    lines = []

    # shrink the bottom budgets so 32²/16³ build REAL multi-level plans
    # (at the default budgets these grids are single-level and the fused
    # cycle would correctly refuse — a vacuous smoke)
    dct_save = mg._DCT_BOTTOM_MAX_CELLS
    dense_save = mg._DENSE_BOTTOM_MAX_CELLS

    def case(tag, key, make_pair, p0, rhs):
        s_off = jax.jit(make_pair("off"))
        fn_on = make_pair("on")
        rec = disp.last(key) or ""
        s_on = jax.jit(fn_on)
        if not rec.startswith("pallas_fused_cycle"):
            failures.append(f"{tag}: dispatch {key} = {rec!r} — the "
                            "forced fused cycle did not dispatch")
            return
        n_launch = count_prim(jax.make_jaxpr(fn_on)(p0, rhs).jaxpr,
                              "pallas_call")
        if n_launch != 2 or "launches=2" not in rec:
            failures.append(
                f"{tag}: traced solve carries {n_launch} pallas_call(s) "
                f"vs the 2-launch census {rec!r}")
        a, b = s_off(p0, rhs), s_on(p0, rhs)
        d = float(jnp.max(jnp.abs(a[0] - b[0])))
        m = max(float(jnp.max(jnp.abs(a[0]))), 1.0)
        it_off, it_on = int(a[2]), int(b[2])
        print(f"[{tag}] {rec} | it {it_off}/{it_on} | "
              f"maxdiff {d:.3g} (scale {m:.3g})")
        if it_off != it_on:
            failures.append(f"{tag}: fused took {it_on} cycles, the "
                            f"ladder {it_off}")
        if d > TOL * m:
            failures.append(f"{tag}: fused-vs-ladder maxdiff {d:.3g} "
                            f"beyond {TOL} of scale {m:.3g}")
        line = {"metric": "mg_launches_per_cycle", "value": n_launch,
                "unit": "launches/cycle", "mg_dispatch": rec,
                "ladder_launches": count_prim(
                    jax.make_jaxpr(make_pair("off"))(p0, rhs).jaxpr,
                    "pallas_call"),
                "config": f"{tag} (smoke)"}
        tm.emit("metric", **line)
        lines.append(line)

    try:
        # 2-D plain 32² (DCT budget 64 -> 2 levels)
        mg._DCT_BOTTOM_MAX_CELLS = 64
        n, h = 32, 1.0 / 32
        rng = np.random.default_rng(0)
        rhs = jnp.zeros((n + 2, n + 2), dtype).at[1:-1, 1:-1].set(
            jnp.asarray(rng.standard_normal((n, n)), dtype))
        p0 = jnp.zeros_like(rhs)
        case("plain2d", "mg2d_fused",
             lambda fused: mg.make_mg_solve_2d(
                 n, n, h, h, 0.0, 3, dtype, stall_rtol=0, fused=fused),
             p0, rhs)

        # 2-D obstacle 32² (dense budget 64 -> 2 levels)
        mg._DENSE_BOTTOM_MAX_CELLS = 64
        fluid = np.ones((n + 2, n + 2), bool)
        fluid[10:18, 12:22] = False
        m2 = obst.make_masks(fluid, h, h, 1.7, dtype)
        case("obs2d", "mg2d_obstacle_fused",
             lambda fused: mg.make_obstacle_mg_solve_2d(
                 n, n, h, h, 0.0, 3, m2, dtype, stall_rtol=0,
                 fused=fused),
             p0, rhs)

        # 3-D plain 16³ (DCT budget 512 -> 2 levels)
        mg._DCT_BOTTOM_MAX_CELLS = 512
        n3, h3 = 16, 1.0 / 16
        rhs3 = jnp.zeros((n3 + 2,) * 3, dtype).at[1:-1, 1:-1, 1:-1].set(
            jnp.asarray(rng.standard_normal((n3, n3, n3)), dtype))
        p3 = jnp.zeros_like(rhs3)
        case("plain3d", "mg3d_fused",
             lambda fused: mg.make_mg_solve_3d(
                 n3, n3, n3, h3, h3, h3, 0.0, 3, dtype, stall_rtol=0,
                 fused=fused),
             p3, rhs3)

        # 3-D obstacle 16³ (dense budget 512 -> 2 levels)
        mg._DENSE_BOTTOM_MAX_CELLS = 512
        fl3 = np.ones((n3 + 2,) * 3, bool)
        fl3[6:10, 5:9, 7:12] = False
        m3 = make_masks_3d(fl3, h3, h3, h3, 1.7, dtype)
        case("obs3d", "mg3d_obstacle_fused",
             lambda fused: mg.make_obstacle_mg_solve_3d(
                 n3, n3, n3, h3, h3, h3, 0.0, 3, m3, dtype, stall_rtol=0,
                 fused=fused),
             p3, rhs3)
    finally:
        mg._DCT_BOTTOM_MAX_CELLS = dct_save
        mg._DENSE_BOTTOM_MAX_CELLS = dense_save

    # refusal: a ragged (odd-extent) grid is a single-level plan at the
    # default budget — the knob forced on must still refuse WITH a reason
    mg.make_mg_solve_2d(33, 33, 1 / 33, 1 / 33, 0.0, 2, dtype,
                        stall_rtol=0, fused="on")
    reason = disp.last("mg2d_fused") or ""
    print(f"[ragged] mg2d_fused = {reason}")
    if not (reason.startswith("jnp") and "single-level" in reason):
        failures.append(f"ragged 33²: refusal reason missing from the "
                        f"dispatch record ({reason!r})")

    # eps-floor footgun (ISSUE 17): every parity case above compared at
    # eps=0, the fixed-iteration mode — silent by contract. A
    # floor-adjacent eps must warn at build time, and the telemetry
    # record must land in THIS flight record (main() counts it)
    import warnings as _w

    with _w.catch_warnings(record=True) as caught:
        _w.simplefilter("always")
        mg.make_mg_solve_2d(64, 64, 1 / 64, 1 / 64, 1e-7, 2, dtype,
                            stall_rtol=0)
    if not any("residual floor" in str(c.message) for c in caught):
        failures.append("64² at eps 1e-7: no eps-floor warning from "
                        "make_mg_solve_2d (utils/precision)")

    # the one-launch class cycle (fleet lane): exactly 1 pallas_call
    import jax

    from pampi_tpu.ops import mg_fused as mf

    cycle, plane, lmax = mf.make_class_cycle_2d(16, 16, dtype,
                                                interpret=True)
    live = jnp.asarray(12, jnp.int32)
    inv2 = jnp.asarray(144.0, dtype)
    ext, geo = mf.class_level_plan(live, live, inv2, inv2, lmax, dtype)
    z = jnp.zeros(plane, dtype)
    n_class = count_prim(
        jax.make_jaxpr(cycle)(z, z, ext, geo).jaxpr, "pallas_call")
    print(f"[class] cycle launches = {n_class} (levels<={lmax})")
    if n_class != 1:
        failures.append(f"class cycle carries {n_class} pallas_call(s), "
                        "the one-launch contract says 1")
    return lines


def main(argv: list[str]) -> int:
    outdir = argv[1] if len(argv) > 1 else os.path.join(
        REPO, "results", "mg_smoke")
    os.makedirs(outdir, exist_ok=True)
    jsonl = os.path.join(outdir, "run.jsonl")
    if os.path.exists(jsonl):
        os.remove(jsonl)
    os.environ["PAMPI_TELEMETRY"] = jsonl

    from pampi_tpu.utils import telemetry as tm

    tm.reset()
    tm.start_run(tool="mg_smoke")
    failures: list[str] = []
    lines = _parity(failures)
    tm.finalize()

    # the telemetry plane end-to-end
    from tools import telemetry_report as tr

    records = tr.load(jsonl)
    metric = [r for r in records if r.get("kind") == "metric"
              and r.get("metric") == "mg_launches_per_cycle"]
    if len(metric) != len(lines):
        failures.append(f"{len(metric)} mg_launches_per_cycle records in "
                        f"the flight record, {len(lines)} emitted")
    floor_warns = [r for r in records if r.get("kind") == "warning"
                   and r.get("component") == "precision"]
    if len(floor_warns) != 1:
        failures.append(
            f"{len(floor_warns)} precision eps-floor warning records in "
            "the flight record — the floor-adjacent build must emit "
            "exactly one, and the eps=0 parity cases none")

    # the merge + lint round trip (incl. the MG_LAUNCH_KEYS block rule)
    artifact = os.path.join(outdir, "MG_SMOKE.json")
    if os.path.exists(artifact):
        os.remove(artifact)
    from tools._artifact import write_merged
    from tools.check_artifact import lint_bench

    block = {"n": 0, "cmd": "mg_smoke", "rc": 0, "tail": "",
             "telemetry_summary": tr.summary(records)}
    if lines:
        block["parsed_mg"] = lines[0]
    merged = write_merged(artifact, block)
    failures += lint_bench(merged, "MG_SMOKE")
    if not any(m.get("name") == "mg_launches_per_cycle"
               for m in merged.get("metrics", [])):
        failures.append("merged artifact carries no normalized "
                        "mg_launches_per_cycle metric")

    if failures:
        print("\nMG SMOKE FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print(f"\nmg smoke ok: {len(lines)} fused-vs-ladder parity cases at "
          "2 launches/cycle each, the class cycle at 1, ragged refusal "
          "recorded, artifact lint clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
