"""Perf trend over the committed BENCH_r*.json artifacts + regression gate.

    python tools/bench_trend.py [--tolerance F] [--json] [files...]

Normalizes every BENCH artifact into one trend series (round -> metric ->
value, backend-tagged) from the `metrics` list `tools/_artifact.py` writes
(legacy artifacts fall back to the same normalizer over their `parsed*`
blocks — never to `tail`-string scraping), renders the trajectory table,
and FAILS (exit 1) when the newest point of any same-backend series
regresses beyond the tolerance vs the best earlier point of that series.

Backend partition: every point is tagged cpu|tpu
(`tools/_artifact.backend_tag`), and series are keyed (metric, backend) —
a CPU growth-container round can never gate against a chip number, and
vice versa. The cpu series gate at the wider CPU_TOLERANCE (growth
containers are different hardware round to round — see the constant's
rationale); tpu series keep the tight default. Direction comes from the
unit: `*/s` rates regress downward, `ms*` latencies regress upward;
metrics with unknown units render in the table but do not gate.

Runs as the `trend` pass of `tools/lint.py` (make lint / make
bench-trend), so a perf-regressing PR fails on CPU before any TPU time
is spent.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_TOLERANCE = 0.10

# the cpu series gate at a wider tolerance: CPU growth containers are
# NOT the same hardware round to round — the r08 container runs the
# byte-identical r06 poisson RB loop 21% slower when idle (67.1M vs
# 52.9M updates/s, best-of-many) — so a 10% cpu gate false-fires on
# container luck, not code. 0.35 covers the measured cross-container
# spread while still catching real order-of-magnitude breakage (a jnp
# fallback where a fused path gated, an accidental f64 promotion). The
# tpu series keep the tight gate: chip rounds run on the same part.
CPU_TOLERANCE = 0.35


def default_files() -> list[str]:
    return sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json")))


def _round_of(path: str, rec: dict) -> int:
    n = rec.get("n")
    if isinstance(n, int):
        return n
    m = re.search(r"_r(\d+)", os.path.basename(path))
    return int(m.group(1)) if m else -1


def load_points(files: list[str]) -> list[dict]:
    """Every artifact's normalized metric entries as trend points
    ({round, name, value, unit, backend, file})."""
    from tools._artifact import collect_metrics

    pts = []
    for path in files:
        try:
            with open(path) as fh:
                rec = json.load(fh)
        except (OSError, json.JSONDecodeError) as exc:
            print(f"warning: {os.path.basename(path)} unreadable ({exc})",
                  file=sys.stderr)
            continue
        if not isinstance(rec, dict):
            continue
        rnd = _round_of(path, rec)
        metrics = rec.get("metrics")
        if not isinstance(metrics, list) or not metrics:
            # legacy artifact: run the same normalizer over its blocks
            metrics = collect_metrics(rec)
        for m in metrics:
            if not isinstance(m, dict) or not isinstance(
                    m.get("value"), (int, float)):
                continue
            pts.append({"round": rnd, "name": str(m.get("name")),
                        "value": float(m["value"]),
                        "unit": m.get("unit"),
                        "backend": m.get("backend", "tpu"),
                        "file": os.path.basename(path)})
    return pts


def build_series(points: list[dict]) -> dict:
    """{(name, backend): [(round, value, unit), ...]} sorted by round;
    a repeated round within one series keeps the last-loaded point."""
    out: dict[tuple, dict] = {}
    for p in points:
        out.setdefault((p["name"], p["backend"]), {})[p["round"]] = (
            p["value"], p["unit"])
    return {
        key: [(r, v, u) for r, (v, u) in sorted(rounds.items())]
        for key, rounds in out.items()
    }


# metrics whose gate direction is a property of the metric itself, not
# its unit: the comm-hidden fraction (ROADMAP item 2) is the overlap
# refactor's headline — a DROP means exchange time slid back onto the
# critical path, so it regresses downward despite its unitless [0, 1]
# range. The fleet throughput (ROADMAP item 3, tools/perf_fleet.py) is
# named here explicitly even though its scenarios/s unit already gates
# upward — the serving headline must never silently degrade to
# render-only if its unit string drifts.
NAME_DIRECTIONS = {"comm_hidden_fraction": True,
                   "fleet_scenarios_per_s": True,
                   # the shape-class serving rate (serving v3,
                   # tools/perf_fleet.py --classes): mixed-grid requests
                   # through one class compile, warm, compile excluded —
                   # the fused-vs-jnp class win is gated upward from the
                   # first artifact
                   "fleet_class_scenarios_per_s": True,
                   # hierarchical-exchange + grid-restriction metrics
                   # (ROADMAP item 3): DCN bytes are the slow-fabric
                   # traffic of a multi-slice pod — fewer is better;
                   # pre_grid_cells is the summed PRE-half grid sweep
                   # (the restricted halves must stay below the 2x
                   # full-sweep count they replaced)
                   "dcn_exchange_bytes": False,
                   "pre_grid_cells": False,
                   # serving v2 (fleet/serve.py): tenant-felt request
                   # latency and the admission backlog high-water mark —
                   # both lower-is-better; fleet_scenarios_per_s above
                   # stays the higher-is-better throughput headline
                   "fleet_p50_latency_ms": False,
                   "fleet_queue_depth_max": False,
                   # the fused V-cycle launch census (ISSUE 16): Pallas
                   # launches one mg V-cycle costs at the north-star
                   # geometry (bench.py _mg_launch_line — a static trace
                   # count, so the gate is exact on any backend). Fewer
                   # is better: 2 is the fused DOWN/UP pair; a rise
                   # means the cycle fell back to the per-level launch
                   # ladder
                   "mg_launches_per_cycle": False,
                   # the K-fused chunk census (ISSUE 17): static Pallas
                   # launches of one traced K-step chunk divided by K
                   # (bench.py _launches_per_step_line — exact on any
                   # backend). Fewer is better: a rise means either the
                   # scan stopped fusing (K fell to 1) or the chunk body
                   # grew launches; jaxprcheck pins the hard < 3 ceiling,
                   # this gate catches drift below it
                   "launches_per_step": False,
                   # the serving-regime step time (ISSUE 17): 64²/256²
                   # dcavity ms/step where the per-step envelope the
                   # K-fusion amortizes is first-order; the unit already
                   # gates ms downward — named so a unit-string drift
                   # can never silently un-gate the serving headline
                   "ns2d_small_ms_per_step": False,
                   # the SLO plane (ISSUE 18, serving observability):
                   # the WORST per-class p95 request latency — the gate
                   # watches the tail class, not a fleet average, so one
                   # class regressing behind a healthy mean still fails
                   # lint like a perf regression — and the daemon's
                   # lifetime SLO violation count (fleet/slo.py); both
                   # lower-is-better
                   "fleet_class_p95_ms": False,
                   "slo_violations": False,
                   # the autopilot control plane (ISSUE 19,
                   # fleet/autopilot.py): time from the first hysteresis
                   # breach back to full service (rung 0, calm sustained)
                   # — the headline the chaos harness measures; and the
                   # flap count (opposite-direction capacity moves inside
                   # the flap window), whose ideal is zero — a rising
                   # flap count means the hysteresis band stopped doing
                   # its job. Both lower-is-better
                   "autoscale_time_to_recover_ms": False,
                   "autoscale_flaps": False}


def higher_is_better(unit, name: str | None = None) -> bool | None:
    """Gate direction from the metric name (NAME_DIRECTIONS), else the
    unit; None = render-only (no gate)."""
    if name in NAME_DIRECTIONS:
        return NAME_DIRECTIONS[name]
    u = str(unit or "")
    if u.endswith("/s"):
        return True
    if u.startswith("ms") or "ms/" in u:
        return False
    return None


def check_regressions(series: dict,
                      tolerance: float = DEFAULT_TOLERANCE) -> list[str]:
    """The gate: the NEWEST point of each (metric, backend) series vs the
    best EARLIER same-series point. Returns one diagnostic per
    regression beyond the tolerance."""
    errs = []
    for (name, backend), pts in sorted(series.items()):
        if len(pts) < 2:
            continue
        direction = higher_is_better(pts[-1][2], name)
        if direction is None:
            continue
        tol = tolerance if backend == "tpu" \
            else max(tolerance, CPU_TOLERANCE)
        last_round, last, _ = pts[-1]
        prior = [v for _, v, _ in pts[:-1]]
        best = max(prior) if direction else min(prior)
        if best == 0:
            continue
        ratio = last / best
        bad = ratio < 1.0 - tol if direction else ratio > 1.0 + tol
        if bad:
            arrow = "dropped" if direction else "rose"
            errs.append(
                f"{name} [{backend}]: r{last_round:02d} = {last:.6g} "
                f"{arrow} {abs(1.0 - ratio) * 100:.1f}% beyond the "
                f"{tol * 100:.0f}% tolerance vs the best earlier "
                f"point {best:.6g}")
    return errs


def render(series: dict) -> str:
    """The trajectory table: one row per (metric, backend), one column
    per round."""
    rounds = sorted({r for pts in series.values() for r, _, _ in pts})
    if not rounds:
        return "no trend points\n"
    name_w = max(len(f"{n} [{b}]") for n, b in series) + 2
    head = "metric".ljust(name_w) + "".join(
        f"{'r%02d' % r:>14}" for r in rounds)
    lines = [head]
    for (name, backend), pts in sorted(series.items()):
        by_round = {r: v for r, v, _ in pts}
        unit = pts[-1][2]
        row = f"{name} [{backend}]".ljust(name_w) + "".join(
            f"{by_round[r]:>14.5g}" if r in by_round else f"{'-':>14}"
            for r in rounds)
        lines.append(row + (f"  {unit}" if unit else ""))
    return "\n".join(lines) + "\n"


def lint(files=None, tolerance: float = DEFAULT_TOLERANCE) -> list[str]:
    """The tools/lint.py `trend` pass entry point: diagnostics only
    (empty = no regression). An EMPTY series set is itself a violation —
    the whole point of the normalized schema is that the trend input
    never parses to []."""
    files = default_files() if files is None else files
    if not files:
        return ["no BENCH_r*.json artifacts found"]
    series = build_series(load_points(files))
    if not series:
        return ["BENCH artifacts yielded zero trend points "
                "(normalized `metrics` lists missing or empty)"]
    return check_regressions(series, tolerance)


def main(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                    help="allowed fractional regression vs the best "
                         "same-backend point (default 0.10)")
    ap.add_argument("--json", action="store_true",
                    help="print the series as JSON instead of the table")
    ap.add_argument("files", nargs="*",
                    help="artifacts (default: the committed BENCH_r*.json)")
    args = ap.parse_args(argv[1:])
    files = args.files or default_files()
    if not files:
        print("no BENCH_r*.json artifacts found", file=sys.stderr)
        return 2
    series = build_series(load_points(files))
    if args.json:
        print(json.dumps(
            {f"{n} [{b}]": [{"round": r, "value": v, "unit": u}
                            for r, v, u in pts]
             for (n, b), pts in sorted(series.items())}, indent=2))
    else:
        sys.stdout.write(render(series))
    if not series:
        print("zero trend points — BENCH artifacts carry no normalized "
              "metrics", file=sys.stderr)
        return 1
    errs = check_regressions(series, args.tolerance)
    for e in errs:
        print(f"REGRESSION: {e}", file=sys.stderr)
    if not errs:
        print(f"trend ok: {len(series)} series, no regression beyond "
              f"{args.tolerance * 100:.0f}%")
    return 1 if errs else 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
