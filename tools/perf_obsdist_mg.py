"""Distributed obstacle-MG at scale: the committed-artifact measurement
(VERDICT r4 items 1 + 6).

Round 4 measured the one-shard 2048x512 distributed obstacle-MG at 4.26
ms/step (vs 1.55 single-device) but committed no artifact; round 5 moves
the dist smoothing onto the per-shard Pallas kernel
(ops/multigrid._pallas_dist_smoother_2d) and this tool records the result.

Protocol (memory: axon-tunnel rules): production `_chunk_sm` (64 steps per
dispatch), warm-compiled, settled one chunk, then CHAINED-CHUNK two-point
differencing — time 1 chunk and k chunks from the same settled state,
per-step = (t_k - t_1) / (steps_k - steps_1), scalar-readback fences only.
Comparators measured in the SAME session: single-device obstacle-MG
(tools/perf_obstacle_mg.py protocol) and the capped dist SOR smoother.

Run on the real chip:  python tools/perf_obsdist_mg.py
Writes results/obsdist_mg2048.json.
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax
import jax.numpy as jnp

from pampi_tpu.utils.params import read_parameter

REPS = 5
PAR = os.path.join(REPO, "configs", "canal_obstacle2048.par")


def measure_dist_step_ms(solver: str, dims=(1, 1)) -> dict:
    from pampi_tpu.models.ns2d_dist import NS2DDistSolver
    from pampi_tpu.parallel.comm import CartComm
    from pampi_tpu.utils import dispatch

    param = read_parameter(PAR).replace(
        tpu_dtype="float32", tpu_solver=solver,
        tpu_mesh=f"{dims[0]}x{dims[1]}",
    )
    comm = CartComm(ndims=2, dims=dims)
    before = dispatch.snapshot()  # the record is process-global
    s = NS2DDistSolver(param, comm, dtype=jnp.float32)
    # warm compile + settle one chunk (64 steps); initial_state matches
    # the chunk's arity (telemetry appends the in-band metrics vector)
    state = s._chunk_sm(*s.initial_state())
    float(state[3])

    def run_chunks(k):
        st = state
        for _ in range(k):
            st = s._chunk_sm(*st)
        float(st[3])  # scalar fence (no bulk transfer over the tunnel)
        return int(st[4])

    def timed(k):
        nt_end = run_chunks(k)  # warm this chain length
        best = float("inf")
        for _ in range(REPS):
            t_start = time.perf_counter()
            run_chunks(k)
            best = min(best, time.perf_counter() - t_start)
        return best, nt_end

    ta, nta = timed(1)
    tb, ntb = timed(4)
    steps = ntb - nta
    ms = max(tb - ta, 1e-9) / steps * 1e3
    return {
        "ms_per_step": round(ms, 3),
        # only the records THIS solver build wrote (stale keys from earlier
        # measurements in the same process would misattribute)
        "dispatch": {k: v for k, v in dispatch.snapshot().items()
                     if before.get(k) != v},
        "steps_differenced": steps,
    }


def _with_jnp_smoothing(fn, *args, **kw):
    """Run a measurement with the Pallas MG smoothers ablated (every level
    falls back to the jnp sweeps) — the pallas-vs-jnp smoothing ablation,
    reproducible in-tool."""
    import pampi_tpu.ops.multigrid as mg

    saved = mg._PALLAS_SMOOTH_MIN_CELLS
    mg._PALLAS_SMOOTH_MIN_CELLS = 1 << 60
    try:
        return fn(*args, **kw)
    finally:
        mg._PALLAS_SMOOTH_MIN_CELLS = saved


if __name__ == "__main__":
    from tools.perf_obstacle_mg import measure_step_ms as single_ms

    rec = {
        "artifact": "obsdist_mg2048",
        "config": "configs/canal_obstacle2048.par at f32 (2048x512, "
                  "obstacle 3.0,1.5->4.0,2.5, eps=1e-5, itermax=500), "
                  "one shard of a (1,1) mesh",
        "protocol": "production _chunk_sm (64 steps/dispatch), warm+settled "
                    "1 chunk, chained-chunk two-point differencing (1 vs 4 "
                    "chunks), best-of-%d, scalar fences" % REPS,
        "backend": jax.default_backend(),
    }
    rec["dist_mg"] = measure_dist_step_ms("mg")
    rec["dist_mg_jnp_smoothing"] = _with_jnp_smoothing(
        measure_dist_step_ms, "mg"
    )
    rec["dist_sor_capped"] = measure_dist_step_ms("sor")
    rec["single_mg_ms_per_step"] = round(single_ms("mg"), 3)
    rec["single_mg_jnp_smoothing_ms_per_step"] = round(
        _with_jnp_smoothing(single_ms, "mg"), 3
    )
    from tools._artifact import write_merged

    write_merged(os.path.join(REPO, "results", "obsdist_mg2048.json"), rec)
