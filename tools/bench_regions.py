"""Per-region device-time counter harness — the TPU twin of the reference's
perl likwid-mpirun scripts (assignment-3a/perl scripts/bench-node.pl:17-27
drive likwid hardware-counter sweeps per marker region; here each solver
phase is jitted and timed SEPARATELY to completion on the device, yielding
the counters a TPU exposes to the host: calls, device seconds/call, and
lattice-site update throughput).

Regions per problem (the reference's marker-candidate phases):
  poisson   : sor_iter (one red-black iteration at the production
              tpu_sor_inner granularity), solve (full convergence loop)
  dcavity/… : computeTimestep, setBC, computeFG, computeRHS, sor_iter,
              adaptUV   (solver.c phase names, assignment-5/-6)
  dcavity3d : 3-D versions of the same

Usage:  [PAMPI_PROFILE_CSV=out.csv] python tools/bench_regions.py <file.par> [reps]
Each phase: 2 warmup calls, then best-of-<reps> (default 10) wall time
around dispatch + block_until_ready — device-inclusive by construction.
Prints the table; writes the CSV via utils/profiling.py when
PAMPI_PROFILE_CSV is set (PAMPI_PROFILE is forced on for this harness).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("PAMPI_PROFILE", "1")

import jax
import jax.numpy as jnp

from pampi_tpu.utils import profiling as prof
from pampi_tpu.utils.params import Parameter, read_parameter
from pampi_tpu.utils.precision import resolve_dtype

# the axon tunnel's per-dispatch latency floor swings between ~25 us and
# ~100 ms by the minute; best-of over MANY reps is the only statistic that
# reliably punches through to device time (see BASELINE.md jitter note)
REPS = int(sys.argv[2]) if len(sys.argv) > 2 else 30


def _loop_timer(fn, k, *args):
    """Seconds for ONE dispatch of k chained fn applications + scalar fence.

    The phase runs inside a fori_loop, serialized with an
    optimization_barrier tying each iteration's input to the previous
    iteration's output scalar — XLA can neither hoist, fold, nor overlap
    the applications (arithmetic perturbation tricks get constant-folded).
    Amortizes the axon tunnel's per-dispatch latency (measured swinging
    25 us .. 100 ms), which single dispatches cannot escape."""
    x0, rest = args[0], args[1:]

    def loop(x, *rest):
        def body(_, carry):
            x, acc = carry
            x, acc = jax.lax.optimization_barrier((x, acc))
            out = fn(x, *rest)
            leaf = jax.tree_util.tree_leaves(out)[0]
            mid = leaf.size // 2
            return (x, jnp.ravel(leaf)[mid].astype(jnp.float32))

        return jax.lax.fori_loop(0, k, body, (x, jnp.float32(0)))[1]

    jloop = jax.jit(loop)
    float(jloop(x0, *rest))  # compile + warm
    best = float("inf")
    for _ in range(REPS):
        t0 = time.perf_counter()
        float(jloop(x0, *rest))
        best = min(best, time.perf_counter() - t0)
    return best


def _time(fn, *args):
    """Device-inclusive seconds per fn application by TWO-POINT differencing:
    per = (t(k_b) - t(k_a)) / (k_b - k_a). The dispatch-latency floor (which
    jitters 25 us .. 100 ms between dispatches, so it cannot be subtracted
    from separately-measured runs) appears in both terms and cancels; best-of
    REPS on each term suppresses the residual jitter. k_b is sized so the
    extra iterations carry >= ~0.25 s of phase work, refined once when the
    first estimate shows the probe overestimated the per-iteration cost."""
    ka = 16
    ta = _loop_timer(fn, ka, *args)
    kb = ka + max(32, min(16384, int(0.25 / max(ta / ka, 1e-6))))
    tb = _loop_timer(fn, kb, *args)
    per = max((tb - ta) / (kb - ka), 1e-9)
    if per * (kb - ka) < 0.3:  # diff too small vs jitter: one refinement
        # bound kc by MEASURED wall time per iteration (tb/kb, which includes
        # the latency floor), not the clamped difference — a negative diff
        # would otherwise size a multi-hour dispatch
        wall_cap = int(2.0 / max(tb / kb, 1e-7))
        kc = ka + max(32, min(262144, int(0.5 / per), wall_cap))
        if kc > kb * 2:
            tc = _loop_timer(fn, kc, *args)
            per = max((tc - ta) / (kc - ka), 1e-9)
        else:
            # the refinement could not run (the wall cap already bounds the
            # chain): the estimate comes from a < 0.3 s two-point difference
            # the code itself classifies as jitter-dominated — say so
            # instead of recording it silently (round-2 advisor finding)
            print(
                f"# WARNING: low-confidence estimate "
                f"(jitter-dominated {per * (kb - ka):.3f}s difference, "
                f"refinement infeasible at kc={kc} <= 2*kb={2 * kb})",
                file=sys.stderr,
            )
    return per


def _record(name, seconds, sites):
    prof.add_device_time(name, seconds)
    rate = sites / seconds if seconds > 0 else 0.0
    print(f"{name:<16} {seconds * 1e3:10.3f} ms  {rate / 1e9:8.2f}e9 sites/s")


def bench_poisson(param: Parameter, dtype):
    from pampi_tpu.models.poisson import (
        init_fields, make_rb_loop, make_solver_fn,
    )

    imax, jmax = param.imax, param.jmax
    dx, dy = param.xlength / imax, param.ylength / jmax
    p, rhs = init_fields(param, problem=2, dtype=dtype)
    step, prep, post, eff = make_rb_loop(
        imax, jmax, dx, dy, param.omg, dtype, "auto", param.tpu_sor_inner
    )
    pp, rr = prep(p), prep(rhs)
    t = _time(lambda a, b: step(a, b)[0], pp, rr)
    _record("sor_iter", t, imax * jmax * eff)

    # capped iteration count: the counter harness measures per-region rates,
    # not convergence (bench.py owns the convergence headline)
    solve = make_solver_fn(imax, jmax, dx, dy, param.omg, param.eps,
                           min(param.itermax, 500), dtype,
                           n_inner=param.tpu_sor_inner)
    jsolve = jax.jit(solve)
    it = int(jsolve(p, rhs)[2])  # scalar readback = the fence
    t0 = time.perf_counter()
    it = int(jsolve(p, rhs)[2])
    t = time.perf_counter() - t0
    _record("solve", t, imax * jmax * it)


def bench_ns2d(param: Parameter, dtype):
    from pampi_tpu.models.poisson import make_rb_loop
    from pampi_tpu.ops import ns2d as ops

    imax, jmax = param.imax, param.jmax
    dx, dy = param.xlength / imax, param.ylength / jmax
    shape = (jmax + 2, imax + 2)
    sites = imax * jmax
    u = jnp.full(shape, param.u_init, dtype)
    v = jnp.full(shape, param.v_init, dtype)
    p = jnp.full(shape, param.p_init, dtype)
    dt_bound = 0.5 * param.re / (1.0 / (dx * dx) + 1.0 / (dy * dy))
    dt = jnp.asarray(param.tau * dt_bound, dtype)

    _record("computeTimestep",
            _time(lambda a, b: ops.compute_timestep(a, b, dt_bound, dx, dy,
                                                    param.tau), u, v), sites)
    _record("setBC",
            _time(lambda a, b: ops.set_boundary_conditions(
                a, b, param.bcLeft, param.bcRight, param.bcBottom,
                param.bcTop), u, v), sites)
    f, g = ops.compute_fg(u, v, dt, param.re, param.gx, param.gy,
                          param.gamma, dx, dy)
    _record("computeFG",
            _time(lambda a, b: ops.compute_fg(a, b, dt, param.re, param.gx,
                                              param.gy, param.gamma, dx, dy),
                  u, v), sites)
    rhs = ops.compute_rhs(f, g, dt, dx, dy)
    _record("computeRHS",
            _time(lambda a, b: ops.compute_rhs(a, b, dt, dx, dy), f, g),
            sites)
    # the layout the NS-2D pressure solve actually ships for this config:
    # make_rb_loop's standard dispatch (auto -> quarters when eligible,
    # checkerboard otherwise — models/ns2d.make_pressure_solve round 3)
    step, prep, post, eff = make_rb_loop(
        imax, jmax, dx, dy, param.omg, dtype, "auto", param.tpu_sor_inner,
        layout=param.tpu_sor_layout,
    )
    _record("sor_iter",
            _time(lambda a, b: step(a, b)[0], prep(p), prep(rhs)),
            sites * eff)
    _record("adaptUV",
            _time(lambda a, b: ops.adapt_uv(a, b, f, g, p, dt, dx, dy), u, v),
            sites)


def bench_ns3d(param: Parameter, dtype):
    from pampi_tpu.models import ns3d as m3
    from pampi_tpu.ops import ns3d as ops

    imax, jmax, kmax = param.imax, param.jmax, param.kmax
    dx = param.xlength / imax
    dy = param.ylength / jmax
    dz = param.zlength / kmax
    shape = (kmax + 2, jmax + 2, imax + 2)
    sites = imax * jmax * kmax
    u = jnp.full(shape, param.u_init, dtype)
    v = jnp.full(shape, param.v_init, dtype)
    w = jnp.full(shape, param.w_init, dtype)
    p = jnp.full(shape, param.p_init, dtype)
    inv = 1.0 / (dx * dx) + 1.0 / (dy * dy) + 1.0 / (dz * dz)
    dt_bound = 0.5 * param.re / inv
    dt = jnp.asarray(param.tau * dt_bound, dtype)
    bcs = {
        "top": param.bcTop, "bottom": param.bcBottom,
        "left": param.bcLeft, "right": param.bcRight,
        "front": param.bcFront, "back": param.bcBack,
    }

    _record("computeTimestep",
            _time(lambda a, b, c: ops.compute_timestep_3d(
                a, b, c, dt_bound, dx, dy, dz, param.tau), u, v, w), sites)
    _record("setBC",
            _time(lambda a, b, c: ops.set_boundary_conditions_3d(a, b, c,
                                                                 bcs),
                  u, v, w), sites)
    f, g, h = ops.compute_fgh(u, v, w, dt, param.re, param.gx, param.gy,
                              param.gz, param.gamma, dx, dy, dz)
    _record("computeFG",
            _time(lambda a, b, c: ops.compute_fgh(
                a, b, c, dt, param.re, param.gx, param.gy, param.gz,
                param.gamma, dx, dy, dz), u, v, w), sites)
    rhs = ops.compute_rhs(f, g, h, dt, dx, dy, dz)
    _record("computeRHS",
            _time(lambda a, b, c: ops.compute_rhs(a, b, c, dt, dx, dy, dz),
                  f, g, h), sites)
    # per-iteration cost amortized over a fixed-count solve (eps=0 runs to
    # itermax; one pad/unpad per solve, like production use)
    cap = 48
    solve = m3.make_pressure_solve_3d(
        imax, jmax, kmax, dx, dy, dz, param.omg, 0.0, cap, dtype,
        n_inner=param.tpu_sor_inner,
    )
    jsolve = jax.jit(solve)
    it = int(jsolve(p, rhs)[2])  # scalar readback = the fence
    best = float("inf")
    for _ in range(max(2, REPS // 2)):
        t0 = time.perf_counter()
        it = int(jsolve(p, rhs)[2])
        best = min(best, time.perf_counter() - t0)
    _record("sor_iter", best / max(1, it), sites)
    _record("adaptUV",
            _time(lambda a, b, c: ops.adapt_uvw(a, b, c, f, g, h, p, dt,
                                                dx, dy, dz), u, v, w), sites)


def main():
    param = read_parameter(sys.argv[1], Parameter())
    if param.tpu_dtype == "float64":
        jax.config.update("jax_enable_x64", True)
    dtype = resolve_dtype(param.tpu_dtype)
    print(f"# {param.name} backend={jax.default_backend()} "
          f"dtype={param.tpu_dtype} reps={REPS}")
    prof.init()
    if param.name.startswith("poisson"):
        bench_poisson(param, dtype)
    elif param.name in ("dcavity3d", "canal3d"):
        bench_ns3d(param, dtype)
    else:
        bench_ns2d(param, dtype)
    prof.finalize()


if __name__ == "__main__":
    main()
