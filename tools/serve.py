"""Persistent fleet serving daemon — the CLI front of fleet/serve.py.

    python tools/serve.py QUEUE_DIR [options]

Watches QUEUE_DIR for `.par` request files (name them
`<tenant>__<scenario>.par` for per-tenant accounting), serves them
through the fleet scheduler (shape-class batching + continuous lane
swap + warm template/batch caches), and maintains a live status
endpoint at QUEUE_DIR/status.json. Drop a file named STOP into
QUEUE_DIR for a clean shutdown.

Options:
  --status PATH     status endpoint path (default QUEUE_DIR/status.json)
  --results DIR     per-scenario result files (default QUEUE_DIR/results)
  --base PATH       base .par applied under every request
  --lanes N         continuous-batch pool size per bucket (default 4)
  --max-queue N     admission: max accepted-and-unserved (default 64)
  --quota N         admission: per-tenant pending cap (default 8)
  --classes MODE    shape-class batching on|off|auto (default on)
  --poll S          queue-scan cadence seconds (default 0.5)
  --max-polls N     exit after N polls (0 = until STOP; smokes/CI)
  --slo SPEC        tenant SLO p95 targets, ms ("default=250,alice=100";
                    empty = SLO plane off)
  --slo-window S    sliding error-budget window seconds (default 60)
  --slo-burn-alert X  burn-rate warning threshold (default 2.0)
  --autopilot SPEC  the self-healing elastic policy loop
                    (fleet/autopilot.py): off | on[:k=v,...] — e.g.
                    "on:burn_high=4,sustain=3,max_lanes=8". Default
                    off; empty falls back to the base .par's
                    tpu_autopilot knob. Off constructs nothing — the
                    daemon is byte-identical to the policy-less build.
  --priorities SPEC tenant priority classes for the QoS plane
                    ("zoe=high,bob=low,default=normal"; empty = flat —
                    weighted admission and preemption both off)
  --parked-max N    parked/ retention: keep at most N parked malformed
                    files, delete the oldest beyond it (0 = unbounded;
                    status.json `parked_census` reports count + oldest
                    age either way)

Arm PAMPI_TELEMETRY for the flight record (serving/admission/latency/
trace/metrics/slo/autoscale records, schema v9 — utils/telemetry.py's
docstring is the kind table) — `tools/telemetry_report.py --merge`
folds the `serving_summary`/`metrics_summary`/`slo`/
`trace_decomposition`/`autoscale` blocks into BENCH artifacts and
`tools/bench_trend.py` gates fleet_p50_latency_ms /
fleet_queue_depth_max / fleet_class_p95_ms / slo_violations /
autoscale_time_to_recover_ms / autoscale_flaps lower-is-better. The
daemon also writes the registry as Prometheus text at `metrics.prom`
next to the status endpoint.
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(
        description="pampi-tpu fleet serving daemon")
    ap.add_argument("queue_dir")
    ap.add_argument("--status", default="")
    ap.add_argument("--results", default="")
    ap.add_argument("--base", default="")
    ap.add_argument("--lanes", type=int, default=4)
    ap.add_argument("--max-queue", type=int, default=64)
    ap.add_argument("--quota", type=int, default=8)
    ap.add_argument("--classes", default="on",
                    choices=("on", "off", "auto"))
    ap.add_argument("--poll", type=float, default=0.5)
    ap.add_argument("--max-polls", type=int, default=0)
    ap.add_argument("--slo", default="")
    ap.add_argument("--slo-window", type=float, default=60.0)
    ap.add_argument("--slo-burn-alert", type=float, default=2.0)
    ap.add_argument("--autopilot", default="")
    ap.add_argument("--priorities", default="")
    ap.add_argument("--parked-max", type=int, default=0)
    args = ap.parse_args(argv[1:])

    from pampi_tpu.fleet import FleetDaemon, ServeConfig
    from pampi_tpu.utils import telemetry as tm
    from pampi_tpu.utils.params import Parameter, read_parameter

    base = (read_parameter(args.base, Parameter())
            if args.base else None)
    tm.start_run(tool="serve", queue_dir=args.queue_dir)
    cfg = ServeConfig(
        queue_dir=args.queue_dir, status_path=args.status,
        results_dir=args.results, poll_s=args.poll,
        max_lanes=args.lanes, max_queue=args.max_queue,
        tenant_quota=args.quota, classes=args.classes,
        max_polls=args.max_polls, slo=args.slo,
        slo_window_s=args.slo_window,
        slo_burn_alert=args.slo_burn_alert,
        autopilot=args.autopilot, priorities=args.priorities,
        parked_max=args.parked_max)
    daemon = FleetDaemon(cfg, base=base)
    print(f"serving {args.queue_dir} (status: {daemon.status_path}; "
          f"drop {args.queue_dir}/STOP to shut down)")
    rc = daemon.run()
    tm.finalize()
    st = daemon.status()
    print(f"served {st['served']} scenario(s), parked {st['parked']}, "
          f"{st['swaps']} lane swap(s), p50 latency "
          f"{st['latency_ms']['p50']} ms")
    return rc


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
