"""NS-2D steady-step timing at the north-star grid (4096^2 f32), all three
pressure solvers under ONE protocol, so the BASELINE.md row compares like
with like.

Protocol: dcavity Re=1000, tau=0.5, eps=1e-3, itermax=100, f32. Build the
jitted step, run 5 settle steps (compile + let dt/p leave the cold-start
state), then measure by TWO-POINT differencing of chained-step dispatches:
per-step = (t(k_b) − t(k_a)) / (k_b − k_a), with k_b sized so the dispatch
carries ≥ ~1 s of work. Single-dispatch timing is unusable here — the axon
tunnel's per-dispatch latency floor swings 25 µs–100 ms (see BASELINE.md),
which differencing cancels exactly. Steps chain through the loop carry, so
they serialize naturally. Best-of-REPS on each term suppresses jitter.

Each measured row is also a shared telemetry span record
(utils/telemetry.emit_span; no-op unless PAMPI_TELEMETRY is set), so this
tool's output aggregates through tools/telemetry_report.py like every
other perf tool instead of living only in ad-hoc prints.

Run on the real chip:  python tools/perf_ns2d4096.py [solvers...]
Defaults to: sor fft mg.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from pampi_tpu.utils.params import Parameter

N = 4096
SETTLE = 5
REPS = 8


def measure(solver: str) -> float:
    from pampi_tpu.models.ns2d import NS2DSolver

    # "sor:quarters" / "sor:checkerboard" pins the SOR layout (default auto)
    layout = "auto"
    if ":" in solver:
        solver, layout = solver.split(":", 1)
    param = Parameter(
        name="dcavity", imax=N, jmax=N, re=1000.0, te=10.0, tau=0.5,
        itermax=100, eps=1e-3, omg=1.7, gamma=0.9, tpu_dtype="float32",
        tpu_solver=solver, tpu_sor_layout=layout,
    )
    s = NS2DSolver(param, dtype=jnp.float32)
    step = s._build_step()

    def k_steps(k):
        @jax.jit
        def run(state):
            return jax.lax.fori_loop(0, k, lambda _, c: step(*c), state)

        return run

    state = (s.u, s.v, s.p, jnp.asarray(0.0, jnp.float32),
             jnp.asarray(0, jnp.int32))
    state = k_steps(SETTLE)(state)
    float(state[3])  # scalar fence

    def timed(k):
        run = k_steps(k)
        float(run(state)[3])  # compile + warm
        best = float("inf")
        for _ in range(REPS):
            t0 = time.perf_counter()
            float(run(state)[3])
            best = min(best, time.perf_counter() - t0)
        return best

    ta = timed(1)
    kb = 1 + max(2, min(64, int(1.0 / max(ta, 1e-3))))
    tb = timed(kb)
    return max((tb - ta) / (kb - 1), 1e-9)


if __name__ == "__main__":
    from pampi_tpu.utils import telemetry, xlacache

    xlacache.enable()  # per-solver 4096² builds become disk loads
    solvers = sys.argv[1:] or ["sor", "fft", "mg"]
    telemetry.start_run(tool="perf_ns2d4096", solvers=solvers)
    print(f"backend={jax.default_backend()} N={N} itermax=100 eps=1e-3 f32")
    for sv in solvers:
        ms = measure(sv) * 1e3
        telemetry.emit_span(f"ns2d4096.step[{sv}]", ms,
                            grid=[N, N], itermax=100,
                            protocol="chained-step two-point differencing")
        print(f"{sv:4s}: {ms:8.2f} ms/step")
