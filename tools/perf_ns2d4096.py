"""NS-2D steady-step timing at the north-star grid (4096^2 f32), all three
pressure solvers under ONE protocol, so the BASELINE.md row compares like
with like.

Protocol: dcavity Re=1000, tau=0.5, eps=1e-3, itermax=100, f32. Build the
jitted step, run 5 settle steps (compile + let dt/p leave the cold-start
state), then best-of-10 single-step wall times (the axon tunnel jitters up
to ~50%, so best-of is the stable statistic — see BASELINE.md).

Run on the real chip:  python tools/perf_ns2d4096.py [solvers...]
Defaults to: sor fft mg.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from pampi_tpu.utils.params import Parameter

N = 4096
SETTLE = 5
REPS = 10


def measure(solver: str) -> float:
    from pampi_tpu.models.ns2d import NS2DSolver

    param = Parameter(
        name="dcavity", imax=N, jmax=N, re=1000.0, te=10.0, tau=0.5,
        itermax=100, eps=1e-3, omg=1.7, gamma=0.9, tpu_dtype="float32",
        tpu_solver=solver,
    )
    s = NS2DSolver(param, dtype=jnp.float32)
    step = jax.jit(s._build_step())
    u, v, p = s.u, s.v, s.p
    t = jnp.asarray(0.0, jnp.float32)
    nt = jnp.asarray(0, jnp.int32)
    for _ in range(SETTLE):
        u, v, p, t, nt = step(u, v, p, t, nt)
    jax.block_until_ready(p)
    best = float("inf")
    for _ in range(REPS):
        t0 = time.perf_counter()
        u, v, p, t, nt = step(u, v, p, t, nt)
        jax.block_until_ready(p)
        best = min(best, time.perf_counter() - t0)
    return best


if __name__ == "__main__":
    solvers = sys.argv[1:] or ["sor", "fft", "mg"]
    print(f"backend={jax.default_backend()} N={N} itermax=100 eps=1e-3 f32")
    for sv in solvers:
        ms = measure(sv) * 1e3
        print(f"{sv:4s}: {ms:8.2f} ms/step")
