"""Schema lint for the committed driver artifacts (BENCH_rXX.json /
MULTICHIP_rXX.json), the telemetry summary blocks merged into them, and
the static-analysis baseline (CONTRACTS.json).

    python tools/check_artifact.py [files...]

With no arguments, lints every BENCH_r*.json / MULTICHIP_r*.json in the
repo root plus CONTRACTS.json when present. Exit 1 with one line per
violation. A tier-1 test (tests/test_check_artifact.py) runs the lint
over the committed artifacts, so a driver round that writes a malformed
artifact — or a refactor that renames a decomposition field the analysts
rely on — fails CI instead of silently degrading the record. The same
lint runs as the `artifacts` pass of `tools/lint.py` (make lint): one
analysis layer for CI, the test suite, and the artifact check.

Contracts:
- BENCH: {n, cmd, rc, tail} required. `parsed*` blocks (the JSON lines
  bench.py prints) need {metric, value, unit}; NS step-line blocks
  additionally carry the solve/non-solve decomposition keys (values may be
  null off-TPU — the bench.py contract — but the KEYS must exist); the
  mg launch-census block (mg_launches_per_cycle, ISSUE 16) additionally
  carries {mg_dispatch, ladder_launches}.
- BENCH + MULTICHIP both carry the normalized schema tools/_artifact.py
  writes: {schema_version, metrics} with every metrics entry shaped
  {name, value, unit, backend} and backend in {cpu, tpu} — the
  machine-readable trend surface tools/bench_trend.py gates on.
- MULTICHIP: {n_devices, rc, ok, skipped, tail} required.
- xprof_summary / comm_hidden_fraction (optional until a PAMPI_XPROF run
  merges them): the utils/xprof record shape ({mode, ...; trace mode
  additionally scopes/collectives/exchange_device_ms}) and the ROADMAP
  item 2 block ({mode, steps, exchange device/exposed/serial per-step,
  hidden_fraction}).
- fleet_summary (optional until a fleet run merges one): the
  pampi_tpu/fleet scheduler's summary — {n_scenarios, buckets,
  scenarios_per_s, divergence_census}, every bucket row carrying
  {bucket, mode, lanes, compile_wall_s, run_wall_s} and the census
  {diverged, scenarios} — the ROADMAP item 3 serving record.
- serving observability blocks (optional until a schema-v8 daemon run
  merges them): metrics_summary (folded registry snapshots — counters/
  gauges/histograms, every histogram row carrying n/p50/p95/max), slo
  (per-tenant target + windowed counts + burn rate), trace_decomposition
  (stage table + median-request waterfall whose stage sum must close on
  its end-to-end latency within 5%), soak_trajectory (tools/soak.py:
  monotone t_s + equal-length queue-depth/latency series), autoscale
  (fleet/autopilot: decision tally + transition log + final rung/lane
  posture) and chaos_trajectory (tools/chaos_smoke.py: monotone poll
  axis, equal-length series, degradation ladder moving at most one
  rung per sample).
- telemetry_summary (optional until a run emits one): the
  tools/telemetry_report.summary shape — {schema_version, dispatch,
  chunks, records}; when the PR 4 resilience blocks are present,
  `recoveries`/`retries` must be lists of records and `ckpt` a
  save/rotate/load/reject count map.
- CONTRACTS: {version, env, configs, comm, precision} with env naming
  the trace environment (jax/x64/backend), every config entry carrying
  the jaxprcheck signature keys ({hash, outvars, pallas_calls, prims,
  dispatch}), every comm entry the commcheck census keys
  ({collectives, ppermute_bytes, strips, halo}) and every precision
  entry the preccheck census keys ({dtype, float_dtypes, casts,
  narrowing, reductions}) — comm and precision over the SAME config
  set as configs — a hand-edited or truncated baseline would
  otherwise turn the trace-identity, collective or precision-flow
  contract into a silent no-op.
"""

from __future__ import annotations

import ast
import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BENCH_REQUIRED = ("n", "cmd", "rc", "tail")
MULTICHIP_REQUIRED = ("n_devices", "rc", "ok", "skipped", "tail")
PARSED_REQUIRED = ("metric", "value", "unit")
# the decomposition keys every NS step line carries (bench.py
# _step_decomposition_line; null values are legal off-TPU)
DECOMP_KEYS = ("solve_ms", "nonsolve_ms", "phases", "steps_timed")
# the mg launch-census line (bench.py _mg_launch_line /
# tools/repro_mg4096.py, ISSUE 16): the dispatch decision and the
# ladder comparison count must ride the block — a census that cannot
# say WHICH cycle program it counted is not a census
MG_LAUNCH_KEYS = ("mg_dispatch", "ladder_launches")
# the K-fusion launch census (bench.py _launches_per_step_line,
# ISSUE 17): the quotient is meaningless without the dispatch record
# that names the K, the raw static count, and the divisor itself
FUSE_LAUNCH_KEYS = ("chunk_fuse_dispatch", "pallas_calls", "k")
SUMMARY_REQUIRED = ("schema_version", "dispatch", "chunks", "records")


def _missing(d: dict, keys, where: str) -> list[str]:
    return [f"{where}: missing key {key!r}" for key in keys if key not in d]


CKPT_EVENTS = ("save", "rotate", "load", "reject", "skip")

METRIC_ENTRY = ("name", "value", "unit", "backend")
CHF_KEYS = ("mode", "steps", "exchange_device_ms_per_step",
            "exchange_exposed_ms_per_step", "exchange_serial_ms_per_step",
            "hidden_fraction")
XPROF_TRACE_KEYS = ("scopes", "collectives", "exchange_device_ms",
                    "exchange_exposed_ms")


def lint_normalized(d: dict, where: str) -> list[str]:
    """The tools/_artifact.py normalized-schema keys every BENCH/MULTICHIP
    artifact carries: schema_version + the machine-readable metrics list
    bench_trend reads (so the perf trajectory never degrades back to
    tail-string scraping)."""
    errs = _missing(d, ("schema_version", "metrics"), where)
    metrics = d.get("metrics")
    if "metrics" not in d:
        return errs
    if not isinstance(metrics, list):
        # a null metrics is the same degradation as a missing one: the
        # trend input must always be a machine-readable list
        return errs + [f"{where}.metrics: not a list"]
    for i, m in enumerate(metrics):
        if not isinstance(m, dict):
            errs.append(f"{where}.metrics[{i}]: not a dict")
            continue
        errs += _missing(m, METRIC_ENTRY, f"{where}.metrics[{i}]")
        if m.get("backend") not in ("cpu", "tpu"):
            errs.append(f"{where}.metrics[{i}].backend: "
                        f"{m.get('backend')!r} not cpu|tpu")
    return errs


def lint_xprof_summary(d: dict, where: str) -> list[str]:
    errs = _missing(d, ("mode",), where)
    if d.get("mode") == "trace":
        errs += _missing(d, XPROF_TRACE_KEYS, where)
        for key in ("scopes", "collectives"):
            if key in d and not isinstance(d[key], dict):
                errs.append(f"{where}.{key}: not a dict")
    return errs


def lint_comm_hidden(d: dict, where: str) -> list[str]:
    errs = _missing(d, CHF_KEYS, where)
    h = d.get("hidden_fraction")
    if h is not None and not (isinstance(h, (int, float))
                              and 0.0 <= h <= 1.0):
        errs.append(f"{where}.hidden_fraction: {h!r} not in [0, 1]")
    return errs


FLEET_KEYS = ("n_scenarios", "buckets", "scenarios_per_s",
              "divergence_census")
FLEET_BUCKET_KEYS = ("bucket", "mode", "lanes", "compile_wall_s",
                     "run_wall_s")


def lint_fleet_summary(d: dict, where: str) -> list[str]:
    """The fleet serving record (pampi_tpu/fleet/scheduler.py summary):
    buckets + throughput + divergence census are required — a fleet
    artifact without its census would hide diverged tenants."""
    errs = _missing(d, FLEET_KEYS, where)
    buckets = d.get("buckets")
    if isinstance(buckets, list):
        for i, b in enumerate(buckets):
            if not isinstance(b, dict):
                errs.append(f"{where}.buckets[{i}]: not a dict")
                continue
            errs += _missing(b, FLEET_BUCKET_KEYS, f"{where}.buckets[{i}]")
            # mesh (scenario axis over a device mesh), class
            # (shape-class padded batch) and failed (a daemon-isolated
            # unschedulable bucket) joined in serving v2 — pure
            # addition, legacy artifacts carry only the first three
            if b.get("mode") not in ("vmap", "mesh", "class", "pjit",
                                     "solo", "failed"):
                errs.append(f"{where}.buckets[{i}].mode: "
                            f"{b.get('mode')!r} not "
                            "vmap|mesh|class|pjit|solo|failed")
    elif "buckets" in d:
        errs.append(f"{where}.buckets: not a list")
    census = d.get("divergence_census")
    if isinstance(census, dict):
        errs += _missing(census, ("diverged", "scenarios"),
                         f"{where}.divergence_census")
    elif "divergence_census" in d:
        errs.append(f"{where}.divergence_census: not a dict")
    return errs


SERVING_KEYS = ("polls", "served", "parked", "swaps", "queue_depth_max",
                "requests", "p50_latency_ms")


def lint_serving_summary(d: dict, where: str) -> list[str]:
    """The persistent-daemon serving block (fleet/serve.py via
    tools/telemetry_report.serving_summary): the admission/latency
    accounting is required — a serving artifact that cannot say what it
    parked or how long tenants waited is not a serving artifact. Legacy
    (pre-daemon) artifacts simply lack the block (optional)."""
    errs = _missing(d, SERVING_KEYS, where)
    adm = d.get("admission")
    if adm is not None and not isinstance(adm, dict):
        errs.append(f"{where}.admission: not a dict")
    for k in ("served", "parked", "swaps", "queue_depth_max"):
        v = d.get(k)
        if v is not None and not isinstance(v, (int, float)):
            errs.append(f"{where}.{k}: {v!r} not a number")
    return errs


METRICS_SUMMARY_KEYS = ("sources", "counters", "gauges", "histograms")
METRICS_HIST_KEYS = ("n", "p50", "p95", "max")
SLO_ROW_KEYS = ("target_ms", "window_s", "requests", "violations",
                "burn_rate")
TRACE_DECOMP_KEYS = ("requests", "e2e_ms", "stages", "p50_waterfall",
                     "p50_sum_ms", "sum_residual")
# the decomposition closure tolerance: the median request's stage sum
# must land on its end-to-end latency (exact by construction up to
# per-stage rounding and a missing mark — 5% catches a broken tiling)
TRACE_SUM_TOLERANCE = 0.05
SOAK_SERIES = ("t_s", "queue_depth", "p50_ms", "served")


def lint_metrics_summary(d: dict, where: str) -> list[str]:
    """The folded registry-snapshot block (tools/telemetry_report.
    metrics_summary over utils/metrics `metrics` records): the three
    instrument maps are required, and every histogram row must carry its
    count + quantile summary — a histogram that cannot say its n or p95
    defeats the reason the registry exists."""
    errs = _missing(d, METRICS_SUMMARY_KEYS, where)
    for key in ("counters", "gauges", "histograms"):
        if key in d and not isinstance(d[key], dict):
            errs.append(f"{where}.{key}: not a dict")
    hists = d.get("histograms")
    if isinstance(hists, dict):
        for name, row in hists.items():
            if not isinstance(row, dict):
                errs.append(f"{where}.histograms[{name}]: not a dict")
                continue
            errs += _missing(row, METRICS_HIST_KEYS,
                             f"{where}.histograms[{name}]")
    return errs


def lint_slo(d: dict, where: str) -> list[str]:
    """The per-tenant SLO block (fleet/slo via telemetry_report.
    slo_summary): every tenant row needs its target, windowed counts and
    burn rate — an SLO block that cannot say how fast a tenant burns its
    budget is not an SLO block. Burn must be non-negative."""
    errs = []
    for tenant, row in d.items():
        if not isinstance(row, dict):
            errs.append(f"{where}.{tenant}: not a dict")
            continue
        errs += _missing(row, SLO_ROW_KEYS, f"{where}.{tenant}")
        burn = row.get("burn_rate")
        if burn is not None and not (isinstance(burn, (int, float))
                                     and burn >= 0):
            errs.append(f"{where}.{tenant}.burn_rate: {burn!r} "
                        "not a non-negative number")
    return errs


def lint_trace_decomposition(d: dict, where: str) -> list[str]:
    """The request-trace decomposition block: stage table + the
    median-request waterfall, whose stage sum must CLOSE on its
    end-to-end latency within TRACE_SUM_TOLERANCE — the contract that
    the critical stages tile a request with no gap or overlap."""
    errs = _missing(d, TRACE_DECOMP_KEYS, where)
    res = d.get("sum_residual")
    if res is not None:
        if not isinstance(res, (int, float)):
            errs.append(f"{where}.sum_residual: {res!r} not a number")
        elif res > TRACE_SUM_TOLERANCE:
            errs.append(
                f"{where}.sum_residual: {res} — the median request's "
                f"stage sum ({d.get('p50_sum_ms')} ms) misses its "
                "end-to-end latency beyond "
                f"{TRACE_SUM_TOLERANCE:.0%} (broken stage tiling)")
    stages = d.get("stages")
    if isinstance(stages, dict):
        for stage, row in stages.items():
            if not isinstance(row, dict) or "p50" not in row \
                    or "p95" not in row:
                errs.append(f"{where}.stages[{stage}]: "
                            "missing p50/p95")
    elif "stages" in d:
        errs.append(f"{where}.stages: not a dict")
    return errs


def lint_soak(d: dict, where: str) -> list[str]:
    """The soak trajectory block (tools/soak.py): the time axis must be
    MONOTONE non-decreasing and every required series present with the
    same length — a capacity-planning trajectory with misaligned or
    time-warped samples plots lies."""
    errs = _missing(d, SOAK_SERIES, where)
    ts = d.get("t_s")
    if isinstance(ts, list):
        if any(not isinstance(t, (int, float)) for t in ts):
            errs.append(f"{where}.t_s: non-numeric timestamp")
        elif any(b < a for a, b in zip(ts, ts[1:])):
            errs.append(f"{where}.t_s: timestamps not monotone")
        for key in SOAK_SERIES[1:]:
            series = d.get(key)
            if isinstance(series, list) and len(series) != len(ts):
                errs.append(f"{where}.{key}: length {len(series)} != "
                            f"t_s length {len(ts)}")
            elif key in d and not isinstance(series, list):
                errs.append(f"{where}.{key}: not a list")
    elif "t_s" in d:
        errs.append(f"{where}.t_s: not a list")
    return errs


AUTOSCALE_KEYS = ("records", "decisions", "transitions", "final")
CHAOS_SERIES = ("poll", "rung", "lanes", "burn_max")


def lint_autoscale(d: dict, where: str) -> list[str]:
    """The autopilot decision block (fleet/autopilot via
    telemetry_report.autoscale_summary): the decision tally, the ordered
    transition log and the final rung/lane posture must all ride the
    block — an autoscale record that cannot say WHAT it decided and
    WHERE the fleet ended up is noise, not a control-plane audit."""
    errs = _missing(d, AUTOSCALE_KEYS, where)
    decisions = d.get("decisions")
    if isinstance(decisions, dict):
        for dec, n in decisions.items():
            if not (isinstance(n, int) and n >= 0):
                errs.append(f"{where}.decisions[{dec}]: {n!r} "
                            "not a non-negative count")
    elif "decisions" in d:
        errs.append(f"{where}.decisions: not a dict")
    trans = d.get("transitions")
    if isinstance(trans, list):
        for i, t in enumerate(trans):
            if not isinstance(t, dict) or "decision" not in t:
                errs.append(f"{where}.transitions[{i}]: "
                            "missing decision")
    elif "transitions" in d:
        errs.append(f"{where}.transitions: not a list")
    final = d.get("final")
    if isinstance(final, dict):
        errs += _missing(final, ("rung", "lanes"), f"{where}.final")
    elif "final" in d:
        errs.append(f"{where}.final: not a dict")
    return errs


def lint_chaos_trajectory(d: dict, where: str) -> list[str]:
    """The chaos recovery-trajectory block (tools/chaos_smoke.py): the
    poll axis must be monotone increasing, every series equal length,
    and the degradation ladder MONOTONE — the rung may only move one
    step per sample. A ladder that jumps rungs is not a ladder, and a
    trajectory with misaligned series plots lies about the recovery."""
    errs = _missing(d, CHAOS_SERIES, where)
    polls = d.get("poll")
    if isinstance(polls, list):
        if any(not isinstance(p, (int, float)) for p in polls):
            errs.append(f"{where}.poll: non-numeric sample")
        elif any(b <= a for a, b in zip(polls, polls[1:])):
            errs.append(f"{where}.poll: not monotone increasing")
        for key in CHAOS_SERIES[1:]:
            series = d.get(key)
            if isinstance(series, list) and len(series) != len(polls):
                errs.append(f"{where}.{key}: length {len(series)} != "
                            f"poll length {len(polls)}")
            elif key in d and not isinstance(series, list):
                errs.append(f"{where}.{key}: not a list")
    elif "poll" in d:
        errs.append(f"{where}.poll: not a list")
    rungs = d.get("rung")
    if isinstance(rungs, list) and all(
            isinstance(r, int) for r in rungs):
        if any(abs(b - a) > 1 for a, b in zip(rungs, rungs[1:])):
            errs.append(f"{where}.rung: ladder jumps more than one "
                        "rung between samples (non-monotone ladder)")
        if any(r < 0 for r in rungs):
            errs.append(f"{where}.rung: negative rung")
    return errs


def _lint_optional_blocks(d: dict, where: str) -> list[str]:
    errs = []
    for key, fn in (("xprof_summary", lint_xprof_summary),
                    ("comm_hidden_fraction", lint_comm_hidden),
                    ("fleet_summary", lint_fleet_summary),
                    ("serving_summary", lint_serving_summary),
                    ("metrics_summary", lint_metrics_summary),
                    ("slo", lint_slo),
                    ("trace_decomposition", lint_trace_decomposition),
                    ("soak_trajectory", lint_soak),
                    ("autoscale", lint_autoscale),
                    ("chaos_trajectory", lint_chaos_trajectory)):
        block = d.get(key)
        if block is None:
            continue
        if not isinstance(block, dict):
            errs.append(f"{where}.{key}: not a dict")
        else:
            errs += fn(block, f"{where}.{key}")
    return errs


def lint_telemetry_summary(d: dict, where: str) -> list[str]:
    errs = _missing(d, SUMMARY_REQUIRED, where)
    chunks = d.get("chunks")
    if isinstance(chunks, dict):
        errs += _missing(chunks, ("count", "steps"), f"{where}.chunks")
    elif "chunks" in d:
        errs.append(f"{where}.chunks: not a dict")
    # the PR 4 resilience blocks (optional; null when the run had none)
    for key, need in (("recoveries", "attempt"), ("retries", "fault")):
        block = d.get(key)
        if block is None:
            continue
        if not isinstance(block, list):
            errs.append(f"{where}.{key}: not a list")
        elif not all(isinstance(r, dict) and need in r for r in block):
            errs.append(f"{where}.{key}: record missing {need!r}")
    if d.get("ckpt") is not None:
        if not isinstance(d["ckpt"], dict):
            errs.append(f"{where}.ckpt: not a dict")
        else:
            # the legacy five are required; elastic_save/elastic_load
            # (schema v5) ride as extras so pre-elastic artifacts pass
            errs += _missing(d["ckpt"], CKPT_EVENTS, f"{where}.ckpt")
    # the schema-v5 coordinator decision census (optional until a
    # coordinated run merges one): a gutted block must be flagged — a
    # fleet artifact without its decision counts would hide that faults
    # were handled at all
    coord = d.get("coord")
    if coord is not None:
        if not isinstance(coord, dict):
            errs.append(f"{where}.coord: not a dict")
        else:
            errs += _missing(coord, ("decisions",), f"{where}.coord")
            if not isinstance(coord.get("decisions", {}), dict):
                errs.append(f"{where}.coord.decisions: not a dict")
            # the schema-v6 membership subsection (dead-rank verdicts /
            # shrink epochs / elastic shrink-resumes) — optional, so
            # pre-dead-rank artifacts pass; present but gutted is
            # flagged (a survival event with no dead set or epoch would
            # hide WHAT was survived)
            mem = coord.get("membership")
            if mem is not None:
                if not isinstance(mem, dict):
                    errs.append(f"{where}.coord.membership: not a dict")
                else:
                    for key, need in (("dead", "ranks"),
                                      ("epochs", "epoch"),
                                      ("shrinks", "survivors")):
                        block = mem.get(key)
                        if block is None:
                            continue
                        if not isinstance(block, list):
                            errs.append(
                                f"{where}.coord.membership.{key}: "
                                "not a list")
                        elif not all(isinstance(r, dict) and need in r
                                     for r in block):
                            errs.append(
                                f"{where}.coord.membership.{key}: "
                                f"record missing {need!r}")
    warns = d.get("warnings")
    if warns is not None:
        if not isinstance(warns, list):
            errs.append(f"{where}.warnings: not a list")
        elif not all(isinstance(w, dict) and "component" in w
                     for w in warns):
            errs.append(f"{where}.warnings: record missing 'component'")
    return errs


def lint_bench(d: dict, where: str = "BENCH") -> list[str]:
    errs = _missing(d, BENCH_REQUIRED, where)
    for key, block in d.items():
        if not key.startswith("parsed") or not isinstance(block, dict):
            continue
        errs += _missing(block, PARSED_REQUIRED, f"{where}.{key}")
        metric = str(block.get("metric", ""))
        if metric.startswith("ns2d_") and metric.endswith("ms_per_step"):
            errs += _missing(block, DECOMP_KEYS, f"{where}.{key}")
        if metric == "mg_launches_per_cycle":
            errs += _missing(block, MG_LAUNCH_KEYS, f"{where}.{key}")
        if metric == "launches_per_step":
            errs += _missing(block, FUSE_LAUNCH_KEYS, f"{where}.{key}")
    if isinstance(d.get("telemetry_summary"), dict):
        errs += lint_telemetry_summary(
            d["telemetry_summary"], f"{where}.telemetry_summary")
    errs += lint_normalized(d, where)
    errs += _lint_optional_blocks(d, where)
    return errs


# the per-family overlap dispatch keys the dryrun snapshot records
# (utils/dispatch.resolve_overlap); values are overlap-/serial-tagged
OVERLAP_SNAPSHOT_KEYS = ("overlap_ns2d_dist", "overlap_ns3d_dist")
# the dtype resolutions utils/precision.resolve_dtype records
# (ISSUE 20): every *_dtype snapshot value must lead with the resolved
# float dtype name so the record is lintable
DTYPE_SNAPSHOT_VALUES = ("float64", "float32", "float16", "bfloat16")


def lint_dispatch_snapshot(tail: str, where: str) -> list[str]:
    """The dryrun tail's `dispatch snapshot: {...}` line. Once a snapshot
    records ANY overlap_* decision (the comm/compute-overlap rounds),
    BOTH dist families must be present with an overlap|serial-tagged
    value — a dryrun that exercised one family's overlap knob but
    silently skipped the other would otherwise read as covered.
    Likewise every *_dtype resolution (utils/precision.resolve_dtype)
    must lead with the float dtype it resolved to. Pre-overlap /
    pre-dtype artifacts (no such key in the snapshot) pass unchanged."""
    m = re.search(r"dispatch snapshot: (\{.*\})", tail)
    if not m:
        return []
    try:
        snap = ast.literal_eval(m.group(1))
    except (ValueError, SyntaxError):
        return [f"{where}.tail: dispatch snapshot line unparseable"]
    if not isinstance(snap, dict):
        return []
    errs = []
    for key in snap:
        if str(key).endswith("_dtype"):
            val = str(snap.get(key, "") or "")
            if not val.startswith(DTYPE_SNAPSHOT_VALUES):
                errs.append(
                    f"{where}.tail snapshot: {key} does not lead with "
                    f"a resolved float dtype ({val!r})")
    if not any(str(k).startswith("overlap_") for k in snap):
        return errs
    for key in OVERLAP_SNAPSHOT_KEYS:
        val = str(snap.get(key, "") or "")
        if not val.startswith(("overlap", "serial")):
            errs.append(
                f"{where}.tail snapshot: {key} missing or not "
                f"overlap/serial-tagged ({val!r})")
    return errs


def lint_multichip(d: dict, where: str = "MULTICHIP") -> list[str]:
    errs = _missing(d, MULTICHIP_REQUIRED, where)
    if isinstance(d.get("telemetry_summary"), dict):
        errs += lint_telemetry_summary(
            d["telemetry_summary"], f"{where}.telemetry_summary")
    errs += lint_normalized(d, where)
    errs += _lint_optional_blocks(d, where)
    errs += lint_dispatch_snapshot(str(d.get("tail", "") or ""), where)
    return errs


CONTRACTS_REQUIRED = ("version", "env", "configs", "comm", "precision")
CONTRACTS_ENV = ("jax", "x64", "backend")
CONTRACTS_ENTRY = ("hash", "outvars", "pallas_calls", "prims", "dispatch")
# the commcheck census entry (analysis/commcheck.config_entry): a
# truncated comm section would silently no-op the collective contract
CONTRACTS_COMM_ENTRY = ("collectives", "ppermute_bytes", "strips", "halo")
# the preccheck census entry (analysis/preccheck.config_entry): same
# reasoning — a gutted precision entry would no-op the cast contract
CONTRACTS_PREC_ENTRY = ("dtype", "float_dtypes", "casts", "narrowing",
                        "reductions")


def lint_contracts(d: dict, where: str = "CONTRACTS") -> list[str]:
    """The analysis/jaxprcheck + commcheck + preccheck baseline shape
    (see module docstring)."""
    errs = _missing(d, CONTRACTS_REQUIRED, where)
    env = d.get("env")
    if isinstance(env, dict):
        errs += _missing(env, CONTRACTS_ENV, f"{where}.env")
    elif "env" in d:
        errs.append(f"{where}.env: not a dict")
    configs = d.get("configs")
    if isinstance(configs, dict):
        if not configs:
            errs.append(f"{where}.configs: empty")
        for name, entry in configs.items():
            if not isinstance(entry, dict):
                errs.append(f"{where}.configs.{name}: not a dict")
                continue
            errs += _missing(entry, CONTRACTS_ENTRY,
                             f"{where}.configs.{name}")
    elif "configs" in d:
        errs.append(f"{where}.configs: not a dict")
    comm = d.get("comm")
    if isinstance(comm, dict):
        if not comm:
            errs.append(f"{where}.comm: empty")
        for name, entry in comm.items():
            if not isinstance(entry, dict):
                errs.append(f"{where}.comm.{name}: not a dict")
                continue
            errs += _missing(entry, CONTRACTS_COMM_ENTRY,
                             f"{where}.comm.{name}")
            if not isinstance(entry.get("collectives"), dict):
                errs.append(f"{where}.comm.{name}.collectives: not a dict")
        # every traced config must carry a comm census (and no orphans) —
        # the two sections describe the one matrix
        if isinstance(configs, dict) and configs \
                and set(comm) != set(configs):
            errs.append(f"{where}.comm: config set differs from "
                        f"{where}.configs")
    elif "comm" in d:
        errs.append(f"{where}.comm: not a dict")
    prec = d.get("precision")
    if isinstance(prec, dict):
        if not prec:
            errs.append(f"{where}.precision: empty")
        for name, entry in prec.items():
            if not isinstance(entry, dict):
                errs.append(f"{where}.precision.{name}: not a dict")
                continue
            errs += _missing(entry, CONTRACTS_PREC_ENTRY,
                             f"{where}.precision.{name}")
            for key in ("casts", "reductions"):
                if key in entry and not isinstance(entry[key], dict):
                    errs.append(
                        f"{where}.precision.{name}.{key}: not a dict")
        # the precision census describes the same matrix as configs
        if isinstance(configs, dict) and configs \
                and set(prec) != set(configs):
            errs.append(f"{where}.precision: config set differs from "
                        f"{where}.configs")
    elif "precision" in d:
        errs.append(f"{where}.precision: not a dict")
    return errs


def lint_file(path: str) -> list[str]:
    base = os.path.basename(path)
    try:
        with open(path) as fh:
            d = json.load(fh)
    except (OSError, json.JSONDecodeError) as exc:
        return [f"{base}: unreadable ({exc})"]
    if not isinstance(d, dict):
        return [f"{base}: top level is not an object"]
    if base.startswith("BENCH"):
        return lint_bench(d, base)
    if base.startswith("MULTICHIP"):
        return lint_multichip(d, base)
    if base.startswith("CONTRACTS"):
        return lint_contracts(d, base)
    return [f"{base}: unknown artifact family "
            "(expected BENCH_*/MULTICHIP_*/CONTRACTS*)"]


def default_files() -> list[str]:
    """The committed artifact set (shared with tools/lint.py)."""
    files = sorted(
        glob.glob(os.path.join(REPO, "BENCH_r*.json"))
        + glob.glob(os.path.join(REPO, "MULTICHIP_r*.json"))
    )
    contracts = os.path.join(REPO, "CONTRACTS.json")
    if os.path.exists(contracts):
        files.append(contracts)
    return files


def main(argv: list[str]) -> int:
    files = argv[1:]
    if not files:
        files = default_files()
    if not files:
        print("no artifacts found", file=sys.stderr)
        return 1
    errors = []
    for path in files:
        errs = lint_file(path)
        errors += errs
        status = "FAIL" if errs else "ok"
        print(f"{status:>4}  {os.path.basename(path)}")
    for e in errors:
        print(f"  {e}", file=sys.stderr)
    return 1 if errors else 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
