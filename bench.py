"""Headline benchmark: lattice-site updates/sec/chip, Poisson 4096² red-black
SOR (the BASELINE.json metric).

Prints FOUR JSON lines:
  {"metric": "lattice_site_updates_per_sec_per_chip_poisson4096_rbsor", ...}
  {"metric": "ns2d_dcavity4096_ms_per_step", "value": ms, "solve_ms": ...,
   "nonsolve_ms": ..., "phases": <dispatch>, ...}
  {"metric": "ns2d_obstacle2048x512_ms_per_step", ...}  (PR 2: the fused
   obstacle variant's decomposition; ragged/dist twins live in
   tools/perf_ragged.py and tools/perf_obsdist.py)
  {"metric": "mg_launches_per_cycle", "value": N, "mg_dispatch": ...,
   "ladder_launches": ...}  (ISSUE 16: the fused V-cycle's static launch
   census — 2 with the DOWN/UP cycle kernels dispatched)
plus the ISSUE 17 serving/fusion lines: TWO "ns2d_small_ms_per_step"
lines (64² and 256² serving-regime dcavity, K=4 fused chunk, with the
historical one-step chunk's ms/step on the same line for the measured
win) and one "launches_per_step" line (static Pallas census of a traced
K=4 chunk divided by K — the < 3/step fusion-contract number).

The second line is the metric the fused step-phase kernels move (round 6):
the NS-2D north-star step time WITH its solve/non-solve decomposition, so
BENCH_*.json tracks the launch-overhead share directly — the round-5
artifact showed the Poisson kernel already at the vector-issue wall while
the non-solve phase chain (6.4 ms/step measured vs ~0.8 ms HBM-bound) was
the swing term the headline number could not see. Off-TPU the NS line runs
a 256² scaled-down twin of the same config (jnp phases, rate ~3 orders
lower — trend data only, like the Poisson line's off-TPU mode).

Method: 4096² grid, float32 (TPU-native), 9600 timed red-black iterations in
ONE dispatch (fixed count via fori_loop — steady-state throughput, no
convergence check; the dispatch must carry seconds of device work because the
tunnel's per-dispatch latency floor swings 25 µs–100 ms), best-of-12
dispatches after one warm-up; one update = one interior cell relaxed once
(red+black covers each cell exactly once per iteration, matching the
reference's per-iteration cell count). The pallas backend runs the
temporal-blocked kernel (N_INNER red-black iterations + Neumann BCs per HBM
sweep, ops/sor_pallas.py `_tblock_kernel`) — numerically identical to
per-iteration stepping (tests/test_sor_pallas.py). Off-TPU (jnp fallback)
the counts scale down ~50×: CPU throughput is ~3 orders lower and the
latency-floor rationale doesn't apply.

vs_baseline: the reference publishes no numbers (SURVEY.md §6). Baseline is
the measured throughput of the reference's own assignment-4 C solver
(gcc -O3 -march=native, lexicographic `solve`, 4096², 20 fixed iterations)
on this container's host CPU: 1.65e8 updates/s/core, linearly scaled to the
8-rank MPI baseline BASELINE.json names => 1.32e9 updates/s. Regenerate with
tools/measure_baseline.sh.
"""

import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 1)[0])

import jax
import jax.numpy as jnp
from jax import lax

from pampi_tpu.models.poisson import init_fields, make_rb_loop
from pampi_tpu.utils import xlacache
from pampi_tpu.utils.params import Parameter

BASELINE_8RANK_UPDATES_PER_S = 1.32e9  # see module docstring

N = 4096
# ITERS sizes ONE dispatch: the axon tunnel's per-dispatch latency floor
# swings 25 us .. 100 ms, so the timed fori_loop must carry seconds of
# device work or the floor inflates the measurement (round 1's ITERS=100
# was ~44 ms of work and under-recorded the kernel 2.2x: 18.09G vs the
# ~40G the same kernel measures latency-amortized). 9600 iterations of the
# quarters kernel ≈ 1.2 s per dispatch — worst-case floor haircut < 9%.
ITERS = 9600
N_INNER = 16  # temporal-blocking depth. The auto layout dispatches the
# QUARTER-decomposition kernel (ops/sor_quarters.py — all lanes productive,
# uniform shifts); at n_inner=16 the maker's default block height is 128
# quarter-rows (= 256 grid rows). Round-3 depth sweep (same-session,
# best-of-3 x ~1.2 s dispatches): n16/brq128 = 127-131G vs the round-2
# default n8/brq64's 76-84G under identical conditions — the absolute
# numbers swing ~2x session-to-session with tunnel weather (round 2's
# driver run recorded 151.2G at n8), but the n16/n8 ratio was stable at
# ~1.6x across three sweeps. The timed loop runs (ITERS // eff) * eff
# iterations and divides by exactly that count


def _timed_run(backend: str):
    on_tpu = jax.default_backend() == "tpu"
    iters = ITERS if on_tpu else 100
    reps = 12 if on_tpu else 3
    param = Parameter(imax=N, jmax=N, tpu_dtype="float32")
    p, rhs = init_fields(param, problem=2, dtype=jnp.float32)
    # prep carries the pallas padded layout through the loop (identity on
    # jnp); eff is the iterations one step call ACTUALLY performs — the jnp
    # path steps singly regardless of N_INNER
    step, prep, _post, eff = make_rb_loop(
        N, N, 1.0 / N, 1.0 / N, 1.9, jnp.float32, backend=backend,
        n_inner=N_INNER,
    )
    p, rhs = prep(p), prep(rhs)
    outer = iters // eff
    iters_done = outer * eff  # the count the rate formula divides by

    @jax.jit
    def run_iters(p, rhs):
        def body(_, carry):
            p, _res = carry
            return step(p, rhs)

        return lax.fori_loop(0, outer, body, (p, jnp.asarray(0.0, jnp.float32)))

    out = run_iters(p, rhs)
    float(out[1])  # warm-up + compile; scalar readback forces completion
    best = float("inf")
    # best-of-12 dispatches of ~1.2 s each: the axon tunnel + chip sharing
    # add up to ~50% run-to-run jitter (measured); min over many dispatches
    # approximates the chip's unthrottled rate
    for _ in range(reps):
        t0 = time.perf_counter()
        out = run_iters(p, rhs)
        # block_until_ready can return before completion under the axon
        # tunnel; a host readback of the carried residual is the fence
        float(out[1])
        best = min(best, time.perf_counter() - t0)
    return best, iters_done


def _run_with_retry(backend: str):
    """One same-backend retry on a transient device fault (the axon tunnel
    intermittently raises UNAVAILABLE on programs that run fine on the next
    dispatch — models/_driver.py): the headline number must not silently
    drop to the ~10x-slower jnp fallback because of one bad dispatch."""
    from pampi_tpu.models._driver import _is_transient_device_fault

    try:
        return _timed_run(backend)
    except Exception as exc:
        if _is_transient_device_fault(exc):
            print("transient device fault; retrying once", file=sys.stderr)
            return _timed_run(backend)
        raise


def _step_decomposition_line(param, metric, config, steps, reps):
    """Chunk-timed NS-2D ms/step + the TPU-only solve/non-solve split —
    the ONE protocol every bench step line uses (compile + warm with a
    scalar-readback fence, best-of-reps; the solve share via
    NS2DSolver.time_solve_ms, also what tools/northstar.py records —
    no hand-copied phase wiring to silently diverge). `param` must carry
    tpu_flat_solve=1 so every solve runs exactly itermax iterations and
    the step - solve subtraction is well-defined."""
    from pampi_tpu.models.ns2d import NS2DSolver
    from pampi_tpu.utils import dispatch, telemetry, xprof

    assert param.tpu_flat_solve, "decomposition needs the flat solve"
    s = NS2DSolver(param, dtype=jnp.float32)
    state = s.initial_state()
    out = s._chunk_fn(*state)
    float(out[3])  # compile + warm-up; scalar readback is the fence
    best = float("inf")
    # PAMPI_XPROF: device-trace the timed window (no-op when unset) —
    # the per-kernel attribution behind the headline number
    with xprof.capture(metric, steps=steps * reps):
        for _ in range(reps):
            t0 = time.perf_counter()
            out = s._chunk_fn(*state)
            float(out[3])
            best = min(best, time.perf_counter() - t0)
    step_ms = best / steps * 1e3
    line = {
        "metric": metric,
        "value": round(step_ms, 3),
        "unit": "ms/step",
        "phases": dispatch.last("ns2d_phases"),
        "steps_timed": steps,
        "config": config,
    }
    if jax.default_backend() != "tpu":
        # the decomposition is TPU-only: off-TPU the standalone jitted
        # solve compiles SLOWER than the same solve fused into the chunk
        # program (measured 91-120 vs 80 ms/step at 256² — XLA:CPU
        # whole-program optimization), so step - solve would go negative;
        # on TPU both are the same pallas kernel and the subtraction is
        # meaningful
        line = {**line, "solve_ms": None, "nonsolve_ms": None,
                "decomposition_note": "TPU-only (see bench.py)"}
    else:
        solve_ms = s.time_solve_ms(reps=reps)
        line = {**line, "solve_ms": round(solve_ms, 3),
                "nonsolve_ms": round(step_ms - solve_ms, 3)}
    # the decomposition as shared telemetry spans + the headline metric
    # record (no-ops when PAMPI_TELEMETRY is unset)
    telemetry.emit_decomposition(metric, step_ms, line["solve_ms"],
                                 line["nonsolve_ms"],
                                 phases=line["phases"], config=config)
    telemetry.emit("metric", **line)
    return line


def _ns2d_step_line():
    """NS-2D dcavity step time + solve/non-solve decomposition (the
    north-star config at 4096² on TPU, a 256² twin off-TPU)."""
    from pampi_tpu.utils.params import Parameter as _P

    on_tpu = jax.default_backend() == "tpu"
    n = 4096 if on_tpu else 256
    steps = 128 if on_tpu else 8
    param = _P(
        name="dcavity", imax=n, jmax=n, re=1000.0, te=1e9, tau=0.5,
        itermax=100, eps=1e-3, omg=1.7, gamma=0.9, tpu_dtype="float32",
        tpu_sor_inner=16, tpu_flat_solve=1, tpu_chunk=steps,
    )
    return _step_decomposition_line(
        param, f"ns2d_dcavity{n}_ms_per_step",
        f"dcavity {n}^2 f32 Re=1000 itermax=100 n_inner=16 flat",
        steps, 6 if on_tpu else 3,
    )


def _ns2d_obstacle_step_line():
    """The obstacle twin of _ns2d_step_line (PR 2: obstacle flag fields now
    ride the fused phase megakernels everywhere): flag-masked canal at the
    BASELINE obsdist geometry (2048x512 on TPU, a 256x64 twin off-TPU)."""
    from pampi_tpu.utils.params import Parameter as _P

    on_tpu = jax.default_backend() == "tpu"
    ni, nj = (2048, 512) if on_tpu else (256, 64)
    steps = 64 if on_tpu else 8
    param = _P(
        name="canal_obstacle", imax=ni, jmax=nj,
        xlength=16.0, ylength=4.0, re=100.0, te=1e9, tau=0.5,
        itermax=100, eps=1e-3, omg=1.7, gamma=0.9, u_init=1.0,
        bcLeft=3, bcRight=3, bcTop=1, bcBottom=1,
        obstacles="6.0,1.5,10.0,2.5",
        tpu_dtype="float32", tpu_solver="sor", tpu_sor_inner=16,
        tpu_flat_solve=1, tpu_chunk=steps,
    )
    return _step_decomposition_line(
        param, f"ns2d_obstacle{ni}x{nj}_ms_per_step",
        f"canal_obstacle {ni}x{nj} f32 Re=100 itermax=100 n_inner=16 flat",
        steps, 6 if on_tpu else 3,
    )


def _ns2d_small_step_line():
    """Small-grid serving-regime step lines (ISSUE 17): at 64²/256² the
    per-step envelope (loop plumbing, metrics latch, dispatch floor on
    TPU) is a first-order cost the 4096² north-star line cannot see —
    exactly the budget the K-fused chunk amortizes. Runs the SAME
    protocol as the big line (`_step_decomposition_line`) with the
    production K forced on (`tpu_chunk_fuse=4` traces the scan on any
    backend), and attaches the historical one-step-per-body chunk's
    ms/step to the same line so the artifact carries the measured win,
    not just the fused number. One line per grid, one shared metric
    name — the normalized trend series gates on the first (64²) point;
    the 256² twin stays a parsed block keyed by its config string."""
    from pampi_tpu.models.ns2d import NS2DSolver
    from pampi_tpu.utils import dispatch
    from pampi_tpu.utils.params import Parameter as _P

    lines = []
    for n in (64, 256):
        steps = 16

        def small_param(fuse):
            return _P(
                name="dcavity", imax=n, jmax=n, re=100.0, te=1e9,
                tau=0.5, itermax=20, eps=1e-3, omg=1.7, gamma=0.9,
                tpu_dtype="float32", tpu_sor_inner=8, tpu_flat_solve=1,
                tpu_chunk=steps, tpu_chunk_fuse=fuse,
            )

        # one serving-regime chunk is ~10 ms of work — the opposite end
        # of the latency-floor spectrum from the seconds-long headline
        # dispatches, so best-of-MANY cheap reps is what amortizes the
        # scheduler jitter here (the Poisson line's best-of-12 logic)
        reps = 24
        line = _step_decomposition_line(
            small_param("4"), "ns2d_small_ms_per_step",
            f"dcavity {n}^2 f32 serving-regime itermax=20 flat K=4",
            steps, reps,
        )
        line["chunk_fuse"] = dispatch.last("ns2d_chunk_fuse")
        # the A/B the fusion moves: the identical config at the
        # historical chunk (tpu_chunk_fuse=off — bitwise the pre-ISSUE-17
        # trace), timed with the same fence/best-of protocol
        s = NS2DSolver(small_param("off"), dtype=jnp.float32)
        state = s.initial_state()
        out = s._chunk_fn(*state)
        float(out[3])
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            out = s._chunk_fn(*state)
            float(out[3])
            best = min(best, time.perf_counter() - t0)
        line["historical_ms_per_step"] = round(best / steps * 1e3, 3)
        lines.append(line)
    return lines


def _launches_per_step_line():
    """Static launches-per-step census (ISSUE 17): the Pallas launch
    count of ONE traced K-fused chunk divided by K — the scan body
    traces once, so the static count covers K steps and the quotient is
    the per-step launch budget the fusion contract pins (< 3 for K ≥ 2,
    enforced by analysis/jaxprcheck.check_config). Counted from the
    standard-matrix `ns2d_fused_fft_k4` config (forced K=4, so the scan
    traces on any backend) — exact, no timing, same census protocol as
    `_mg_launch_line`."""
    from pampi_tpu.analysis import jaxprcheck as jc
    from pampi_tpu.utils import telemetry

    cfg = next(c for c in jc.standard_configs()
               if c.name == "ns2d_fused_fft_k4")
    tc = jc.trace_config(cfg)
    k = jc.chunk_fuse_k(tc.decisions)
    n_launch = jc.count_prim(tc.jaxpr.jaxpr, "pallas_call")
    line = {
        "metric": "launches_per_step",
        "value": n_launch / k,
        "unit": "launches/step",
        "chunk_fuse_dispatch": tc.decisions.get("ns2d_chunk_fuse"),
        "pallas_calls": n_launch,
        "k": k,
        "config": cfg.name,
    }
    telemetry.emit("metric", **line)
    return line


def _mg_launch_line():
    """The mg launch census (ISSUE 16): how many Pallas launches ONE
    V-cycle costs at the north-star mg geometry, counted STATICALLY from
    the traced cycle program (analysis/jaxprcheck.count_prim) — exact on
    any backend, no timing. The fused cycle pins 2 (DOWN + UP with the
    exact jnp bottom between); `ladder_launches` records the per-level
    ladder's count of the same plan for the amortization ratio (0 off-TPU
    where the ladder's smoothers stay jnp). Rides the same telemetry
    metric protocol as the step lines; the trend gate
    (tools/bench_trend.NAME_DIRECTIONS) holds the count down."""
    from pampi_tpu.analysis.jaxprcheck import count_prim
    from pampi_tpu.ops.multigrid import make_mg_vcycle_2d
    from pampi_tpu.utils import dispatch, telemetry

    on_tpu = jax.default_backend() == "tpu"
    # off-TPU: the smallest plain grid with a multi-level plan at the
    # default DCT-bottom budget (512² -> 256²), so the census is real
    n = 4096 if on_tpu else 512

    def cycle_launches(fused):
        vc = make_mg_vcycle_2d(n, n, 1.0 / n, 1.0 / n, jnp.float32,
                               fused=fused)
        z = jnp.zeros((n + 2, n + 2), jnp.float32)
        return count_prim(jax.make_jaxpr(vc)(z, z).jaxpr, "pallas_call")

    ladder = cycle_launches("off")
    fused = cycle_launches("on")
    line = {
        "metric": "mg_launches_per_cycle",
        "value": fused,
        "unit": "launches/cycle",
        "mg_dispatch": dispatch.last("mg2d_fused"),
        "ladder_launches": ladder,
        "config": f"dcavity {n}^2 f32 mg vcycle",
    }
    telemetry.emit("metric", **line)
    return line


def main() -> None:
    from pampi_tpu.utils import telemetry

    xlacache.enable()
    telemetry.start_run(tool="bench")
    backend = "pallas" if jax.default_backend() == "tpu" else "jnp"
    try:
        dt, iters = _run_with_retry("auto")
    except Exception as exc:  # pallas compile/runtime failure on this chip
        print(f"auto backend failed ({type(exc).__name__}); jnp fallback",
              file=sys.stderr)
        backend = "jnp-fallback"
        dt, iters = _run_with_retry("jnp")
    ups = N * N * iters / dt
    headline = {
        "metric": "lattice_site_updates_per_sec_per_chip_poisson4096_rbsor",
        "value": ups,
        "unit": "updates/s",
        "vs_baseline": ups / BASELINE_8RANK_UPDATES_PER_S,
        "backend": backend,
    }
    telemetry.emit("metric", **headline)
    print(json.dumps(headline), flush=True)
    try:
        print(json.dumps(_ns2d_step_line()), flush=True)
    except Exception as exc:  # the NS line must not sink the headline
        print(f"ns2d step line failed ({type(exc).__name__}: {exc})",
              file=sys.stderr)
    try:
        print(json.dumps(_ns2d_obstacle_step_line()), flush=True)
    except Exception as exc:
        print(f"ns2d obstacle step line failed ({type(exc).__name__}: {exc})",
              file=sys.stderr)
    try:
        print(json.dumps(_mg_launch_line()), flush=True)
    except Exception as exc:
        print(f"mg launch line failed ({type(exc).__name__}: {exc})",
              file=sys.stderr)
    try:
        for small in _ns2d_small_step_line():
            print(json.dumps(small), flush=True)
    except Exception as exc:
        print(f"ns2d small step line failed ({type(exc).__name__}: {exc})",
              file=sys.stderr)
    try:
        print(json.dumps(_launches_per_step_line()), flush=True)
    except Exception as exc:
        print(f"launches-per-step line failed ({type(exc).__name__}: {exc})",
              file=sys.stderr)


if __name__ == "__main__":
    main()
