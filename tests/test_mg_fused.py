"""Fused V-cycle (ISSUE 16, ops/mg_fused.py + the tpu_mg_fused knob):
the two-launch DOWN/UP Pallas cycle must converge to the SAME iterate as
the per-level jnp ladder it replaces (2-D/3-D × plain/obstacle), refuse
ragged single-level plans WITH a recorded reason, leave the knob-off
path bitwise-identical to the historical build, serve the fleet class
lane as a one-launch cycle, and — distributed — aggregate below-floor
bottoms into a replicated mini-V-cycle whose gathers carry the declared
`mg_aggregate.*` scope (commcheck's only RULE_RESHARD exemption).

Tier-1 carries one cheap representative per axis (2-D plain/obstacle
parity, the dist aggregation census, the static/refusal pins) to hold
its 870 s window; the 3-D, class-lane and FFT-coarse twins are
slow-marked — `make mg-suite` runs the complete matrix, and `make
mg-smoke` re-proves 2-D/3-D × plain/obstacle parity end-to-end."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pampi_tpu.analysis.jaxprcheck import count_prim
from pampi_tpu.ops import multigrid as mg
from pampi_tpu.utils import dispatch as disp

DT = jnp.float32

# both paths run the identical red-black ω=1 arithmetic, but the fused
# kernel evaluates full planes with masked-out dead cells, so f32
# summation order differs at the ulp scale
TOL = 2e-5


def _rhs2d(n, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.zeros((n + 2, n + 2), DT).at[1:-1, 1:-1].set(
        jnp.asarray(rng.standard_normal((n, n)), DT))


def _assert_fused_matches_ladder(tag, key, make_pair, p0, rhs):
    """Build the off/on pair, pin the dispatch record + 2-launch trace,
    and assert same-cycle-count ulp-scale parity."""
    s_off = jax.jit(make_pair("off"))
    fn_on = make_pair("on")
    rec = disp.last(key) or ""
    assert rec.startswith("pallas_fused_cycle"), (tag, rec)
    assert "launches=2" in rec, (tag, rec)
    n_launch = count_prim(jax.make_jaxpr(fn_on)(p0, rhs).jaxpr,
                          "pallas_call")
    assert n_launch == 2, (tag, n_launch, rec)
    a, b = s_off(p0, rhs), jax.jit(fn_on)(p0, rhs)
    assert int(a[2]) == int(b[2]), (tag, int(a[2]), int(b[2]))
    d = float(jnp.max(jnp.abs(a[0] - b[0])))
    scale = max(float(jnp.max(jnp.abs(a[0]))), 1.0)
    assert d <= TOL * scale, (tag, d, scale)


def test_fused_cycle_matches_ladder_2d(monkeypatch):
    # shrink the DCT budget so 32² builds a real 2-level plan (at the
    # default budget the grid is single-level -> a vacuous refusal)
    monkeypatch.setattr(mg, "_DCT_BOTTOM_MAX_CELLS", 64)
    n = 32
    h = 1.0 / n
    rhs = _rhs2d(n)
    _assert_fused_matches_ladder(
        "plain2d", "mg2d_fused",
        lambda fused: mg.make_mg_solve_2d(
            n, n, h, h, 0.0, 3, DT, stall_rtol=0, fused=fused),
        jnp.zeros_like(rhs), rhs)


def test_fused_cycle_matches_ladder_2d_obstacle(monkeypatch):
    from pampi_tpu.ops.obstacle import make_masks

    monkeypatch.setattr(mg, "_DENSE_BOTTOM_MAX_CELLS", 64)
    n = 32
    h = 1.0 / n
    fluid = np.ones((n + 2, n + 2), bool)
    fluid[10:18, 12:22] = False
    m = make_masks(fluid, h, h, 1.7, DT)
    rhs = _rhs2d(n)
    _assert_fused_matches_ladder(
        "obs2d", "mg2d_obstacle_fused",
        lambda fused: mg.make_obstacle_mg_solve_2d(
            n, n, h, h, 0.0, 3, m, DT, stall_rtol=0, fused=fused),
        jnp.zeros_like(rhs), rhs)


@pytest.mark.slow
def test_fused_cycle_matches_ladder_3d(monkeypatch):
    monkeypatch.setattr(mg, "_DCT_BOTTOM_MAX_CELLS", 512)
    n = 16
    h = 1.0 / n
    rng = np.random.default_rng(1)
    rhs = jnp.zeros((n + 2,) * 3, DT).at[1:-1, 1:-1, 1:-1].set(
        jnp.asarray(rng.standard_normal((n, n, n)), DT))
    _assert_fused_matches_ladder(
        "plain3d", "mg3d_fused",
        lambda fused: mg.make_mg_solve_3d(
            n, n, n, h, h, h, 0.0, 3, DT, stall_rtol=0, fused=fused),
        jnp.zeros_like(rhs), rhs)


@pytest.mark.slow
def test_fused_cycle_matches_ladder_3d_obstacle(monkeypatch):
    from pampi_tpu.ops.obstacle3d import make_masks_3d

    monkeypatch.setattr(mg, "_DENSE_BOTTOM_MAX_CELLS", 512)
    n = 16
    h = 1.0 / n
    fl3 = np.ones((n + 2,) * 3, bool)
    fl3[6:10, 5:9, 7:12] = False
    m3 = make_masks_3d(fl3, h, h, h, 1.7, DT)
    rng = np.random.default_rng(2)
    rhs = jnp.zeros((n + 2,) * 3, DT).at[1:-1, 1:-1, 1:-1].set(
        jnp.asarray(rng.standard_normal((n, n, n)), DT))
    _assert_fused_matches_ladder(
        "obs3d", "mg3d_obstacle_fused",
        lambda fused: mg.make_obstacle_mg_solve_3d(
            n, n, n, h, h, h, 0.0, 3, m3, DT, stall_rtol=0, fused=fused),
        jnp.zeros_like(rhs), rhs)


def test_knob_off_is_the_historical_program():
    """fused="off" (and the default) must not merely be numerically
    close to the pre-ISSUE-16 ladder — it must trace to the IDENTICAL
    program (the knob is purely additive)."""
    n = 64
    h = 1.0 / n
    rhs = _rhs2d(n)
    p0 = jnp.zeros_like(rhs)
    default = mg.make_mg_solve_2d(n, n, h, h, 0.0, 3, DT, stall_rtol=0)
    off = mg.make_mg_solve_2d(n, n, h, h, 0.0, 3, DT, stall_rtol=0,
                              fused="off")
    assert str(jax.make_jaxpr(default)(p0, rhs)) == \
        str(jax.make_jaxpr(off)(p0, rhs))


def test_ragged_single_level_refuses_with_reason():
    """A 33² grid is a single-level plan: the knob forced on must fall
    back to the jnp ladder AND say why in the dispatch record."""
    mg.make_mg_solve_2d(33, 33, 1 / 33, 1 / 33, 0.0, 2, DT,
                        stall_rtol=0, fused="on")
    reason = disp.last("mg2d_fused") or ""
    assert reason.startswith("jnp"), reason
    assert "single-level" in reason, reason


def test_expected_launches_derives_from_mg_record():
    """jaxprcheck's budget derivation reads the launch census verbatim
    from the fused-cycle dispatch record ("launches=N")."""
    from pampi_tpu.analysis.jaxprcheck import ChunkConfig, expected_launches

    cfg = ChunkConfig(name="x", family="ns2d", params={}, derive=True,
                      phases_key="ns2d_phases", mg_key="mg2d_fused")
    n, how = expected_launches(cfg, {
        "ns2d_phases": "jnp",
        "mg2d_fused": "pallas_fused_cycle (launches=2, levels=3)"})
    assert (n, how) == (2, "derived")
    n2, _ = expected_launches(cfg, {
        "ns2d_phases": "jnp",
        "mg2d_fused": "jnp_ladder (single-level plan)"})
    assert n2 == 0


# ---------------------------------------------------------------------
# fleet class lane (satellite 1): the one-launch class cycle serves the
# shape-class batcher; eligibility names the knob
# ---------------------------------------------------------------------

_B = dict(name="dcavity", imax=12, jmax=12, re=10.0, te=0.03, tau=0.5,
          itermax=8, eps=1e-4, omg=1.7, gamma=0.9, tpu_mesh="1",
          tpu_fuse_phases="off", tpu_solver="mg", tpu_mg_fused="on")


def _class_run(ic):
    from pampi_tpu import fleet
    from pampi_tpu.fleet.shapeclass import ClassSolver
    from pampi_tpu.utils.params import Parameter

    p = Parameter(**_B)
    tpl = ClassSolver(p, ic=ic, jc=ic)
    assert tpl._uses_pallas()
    rec = disp.last("mg_class_fused") or ""
    assert rec.startswith("pallas_class_cycle"), rec
    assert "launches=1" in rec, rec
    batched = fleet.BatchedSolver(tpl, [p], ["a"], family="ns2d_class")
    res = batched.results(batched.run())[0]
    assert not res["diverged"]
    return res


def test_class_eligibility_names_the_knob():
    from pampi_tpu.fleet import shapeclass as sc
    from pampi_tpu.utils.params import Parameter

    p = Parameter(**_B)
    assert sc.class_eligible(p) is None
    assert "tpu_mg_fused off" in sc.class_eligible(
        p.replace(tpu_mg_fused="off"))


@pytest.mark.slow
def test_class_mg_lane_matches_solo():
    """The class-cycle lane must converge to the solo mg solution: u/v
    at f32-accumulation scale; p mean-removed (the in-kernel smoothed
    bottom is a different coarse solver than the solo DCT bottom, so
    the pressure gauge differs — the CONTRACT deviation README
    documents)."""
    from pampi_tpu.models.ns2d import NS2DSolver
    from pampi_tpu.utils.params import Parameter

    p = Parameter(**_B)
    res = _class_run(16)
    solo = NS2DSolver(p)
    solo.run(progress=False)
    assert res["nt"] == solo.nt
    for name, a in zip("uvp", res["fields"]):
        ref = np.asarray(getattr(solo, name))
        if name == "p":
            a, ref = a - a.mean(), ref - ref.mean()
            tol = 0.05
        else:
            tol = 1e-5
        assert np.abs(a - ref).max() < tol, name


@pytest.mark.slow
def test_class_mg_lane_rung_invariant():
    """Padding invariance: the 16- and 32-cell class rungs run the
    identical per-lane arithmetic on different pads — bitwise equal."""
    f16 = _class_run(16)["fields"]
    f32 = _class_run(32)["fields"]
    for name, a, b in zip("uvp", f16, f32):
        assert np.abs(a - b).max() == 0.0, name


# ---------------------------------------------------------------------
# distributed bottoms (tentpole parts 2+3): coarse-level aggregation
# below the shard floor; FFT-preconditioned coarse for over-budget
# obstacle bottoms
# ---------------------------------------------------------------------


def _shard_solve(comm, solve, p0, rhs):
    from jax.sharding import PartitionSpec as P

    from pampi_tpu.parallel.comm import halo_exchange

    def kern(p_int, rhs_int):
        pe = halo_exchange(jnp.pad(p_int, 1), comm)
        re = halo_exchange(jnp.pad(rhs_int, 1), comm)
        p, res, it = solve(pe, re)
        return p[1:-1, 1:-1], res, it

    spec = P("j", "i")
    f = jax.jit(comm.shard_map(kern, in_specs=(spec, spec),
                               out_specs=(spec, P(), P()),
                               check_vma=False))
    p_out, res, it = f(p0[1:-1, 1:-1], rhs[1:-1, 1:-1])
    return f, np.asarray(p_out), float(res), int(it)


def test_dist_coarse_aggregation_matches_ladder(monkeypatch):
    """With the local ladder's bottom over the (shrunk) budget, the
    fused knob aggregates the gathered bottom into a replicated
    mini-V-cycle — recorded, and converging to the jnp-ladder iterate
    (mean-removed: the replicated bottom solve fixes a different
    gauge)."""
    from pampi_tpu.parallel.comm import CartComm

    monkeypatch.setattr(mg, "_DCT_BOTTOM_MAX_CELLS", 128)
    jmax = imax = 64
    dx = dy = 1.0 / imax
    dims = (2, 4)
    comm = CartComm(ndims=2, dims=dims)
    jl, il = jmax // dims[0], imax // dims[1]
    rng = np.random.default_rng(8)
    r = rng.standard_normal((jmax, imax))
    r -= r.mean()
    rhs = jnp.zeros((jmax + 2, imax + 2), DT).at[1:-1, 1:-1].set(
        jnp.asarray(r, DT))
    p0 = jnp.zeros_like(rhs)

    outs = {}
    traced = {}
    for knob in ("off", "on"):
        solve, _used = mg.make_dist_mg_solve_2d(
            comm, imax, jmax, jl, il, dx, dy, 1e-8, 30, DT, fused=knob)
        f, p_out, res, it = _shard_solve(comm, solve, p0, rhs)
        outs[knob] = p_out
        traced[knob] = jax.make_jaxpr(f)(p0[1:-1, 1:-1],
                                         rhs[1:-1, 1:-1]).jaxpr
    agg = disp.last("mg_dist_agg") or ""
    assert agg.startswith("replicated_vcycle"), agg
    assert disp.last("mg_dist_fused"), "the fused-refusal reason must land"

    a = outs["off"] - outs["off"].mean()
    b = outs["on"] - outs["on"].mean()
    assert np.abs(a - b).max() <= 1e-4 * np.abs(a).max()

    # the commcheck exemption (satellite 2): every all_gather of BOTH
    # builds (the ladder's replicated bottom solve gathers through the
    # same site) sits under the declared mg_aggregate.* scope, so the
    # RULE_RESHARD subtraction zeroes out — an unscoped gather would
    # leave a remainder and trip the ban
    from pampi_tpu.analysis.commcheck import aggregation_gathers, census

    for knob, jx in traced.items():
        declared = aggregation_gathers(jx)
        assert declared, (knob, "gathers must carry the named scope")
        assert set(declared) == {"mg_aggregate.gather2d"}, (knob, declared)
        assert sum(declared.values()) == \
            census(jx)["collectives"]["all_gather"], knob


@pytest.mark.slow
def test_dist_obstacle_fft_coarse(monkeypatch):
    """An over-budget obstacle bottom cannot be factorized dense: the
    knob routes the coarse correction through the FFT-preconditioned
    Richardson application — recorded, and not wrecking convergence."""
    from pampi_tpu.ops import obstacle as obst
    from pampi_tpu.parallel.comm import CartComm

    monkeypatch.setattr(mg, "_DENSE_BOTTOM_MAX_CELLS", 64)
    jmax, imax = 32, 64
    dx, dy = 4.0 / imax, 2.0 / jmax
    fluid = obst.build_fluid(imax, jmax, dx, dy, "1.2,0.5,2.0,1.1")
    m = obst.make_masks(fluid, dx, dy, 1.0, DT)
    dims = (2, 4)
    comm = CartComm(ndims=2, dims=dims)
    jl, il = jmax // dims[0], imax // dims[1]
    rng = np.random.default_rng(7)
    p0 = jnp.asarray(rng.standard_normal((jmax + 2, imax + 2)), DT)
    rhs = jnp.asarray(rng.standard_normal((jmax + 2, imax + 2)), DT)

    res = {}
    for knob in ("off", "on"):
        solve, _used = mg.make_dist_obstacle_mg_solve_2d(
            comm, imax, jmax, jl, il, dx, dy, 1e-8, 30, m, DT,
            fused=knob)
        _f, _p, res[knob], _it = _shard_solve(comm, solve, p0, rhs)
    coarse = disp.last("mg_dist_obstacle_coarse") or ""
    assert coarse.startswith("fft_richardson"), coarse
    assert res["on"] <= res["off"] * 4 + 1e-6, \
        "fft coarse must not wreck convergence"
