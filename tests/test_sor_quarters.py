"""Quarter-decomposition SOR layout (ops/sor_quarters.py + the pallas
kernel in ops/sor_pallas.py): layout bijection, neighbour identities via
trajectory equality with the masked reference path, the kernel vs the jnp
oracle (interpret mode), and the make_rb_loop layout dispatch.

Tolerance note: the quarter layout keeps the reference's per-cell
association term-for-term, but XLA contracts multiply-adds differently for
differently-structured programs, so equality with the masked path is
ulp-level (f32: ~4e-7 on O(1) fields; f64: ~1e-15), not bitwise. The
checkerboard layout remains the bitwise-oracle mode (`tpu_sor_layout
checkerboard`)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pampi_tpu.models.poisson import init_fields, make_rb_step, make_rb_loop
from pampi_tpu.ops import sor_pallas as sp
from pampi_tpu.ops.sor_quarters import (
    pack_quarters,
    rb_iter_quarters,
    unpack_quarters,
)
from pampi_tpu.utils.params import Parameter


def _factor(im, jm, omega=1.9):
    dx, dy = 1.0 / im, 1.0 / jm
    dx2, dy2 = dx * dx, dy * dy
    return dx, dy, omega * 0.5 * (dx2 * dy2) / (dx2 + dy2), 1.0 / dx2, 1.0 / dy2


def test_pack_unpack_roundtrip():
    rng = np.random.default_rng(0)
    p = jnp.asarray(rng.normal(size=(18, 34)))
    q = pack_quarters(p)
    np.testing.assert_array_equal(np.asarray(unpack_quarters(*q)), np.asarray(p))


@pytest.mark.parametrize("jm,im", [(16, 16), (32, 16), (126, 126)])
def test_oracle_matches_masked_path_f64(jm, im):
    """f64 quarters oracle vs the masked jnp reference step over 5 full
    iterations: ulp-level (see module docstring)."""
    param = Parameter(imax=im, jmax=jm)
    p, rhs = init_fields(param, problem=2, dtype=jnp.float64)
    dx, dy, factor, idx2, idy2 = _factor(im, jm)
    step = make_rb_step(im, jm, dx, dy, 1.9, jnp.float64, backend="jnp")
    q, qr = pack_quarters(p), pack_quarters(rhs)
    it = jax.jit(lambda q, qr: rb_iter_quarters(q, qr, factor, idx2, idy2))
    pj = p
    for _ in range(5):
        pj, resj = step(pj, rhs)
        q, rsq = it(q, qr)
    np.testing.assert_allclose(
        np.asarray(unpack_quarters(*q)), np.asarray(pj), rtol=0, atol=1e-13
    )
    assert float(rsq) / (im * jm) == pytest.approx(float(resj), rel=1e-10)


@pytest.mark.parametrize("jm,im,k,brq", [
    (30, 30, 1, None), (30, 30, 3, None),
    (126, 62, 4, None), (62, 126, 2, None),
    (126, 126, 3, 16), (126, 126, 4, 8),  # multi-block
])
def test_kernel_matches_oracle(jm, im, k, brq):
    """The pallas quarters kernel (interpret mode) vs k applications of the
    jnp oracle."""
    param = Parameter(imax=im, jmax=jm)
    p, rhs = init_fields(param, problem=2, dtype=jnp.float32)
    dx, dy, factor, idx2, idy2 = _factor(im, jm)
    rb, brr, h = sp.make_rb_iter_tblock_quarters(
        im, jm, dx, dy, 1.9, jnp.float32, n_inner=k, block_rows_q=brq,
        interpret=True,
    )
    pq, rq = sp.pad_quarters(p, brr, h), sp.pad_quarters(rhs, brr, h)
    pq, rsq = rb(pq, rq)
    out = sp.unpad_quarters(pq, jm, im, h)

    q, qr = pack_quarters(p), pack_quarters(rhs)
    for _ in range(k):
        q, osq = rb_iter_quarters(q, qr, factor, idx2, idy2)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(unpack_quarters(*q)), rtol=0, atol=2e-6
    )
    assert float(rsq) == pytest.approx(float(osq), rel=1e-5)


def test_make_rb_loop_dispatches_quarters():
    """layout='quarters' + backend='pallas' (interpret on CPU): the solve
    loop carries the stacked layout and converges like the jnp path."""
    im = jm = 64
    dx, dy, factor, idx2, idy2 = _factor(im, jm)
    param = Parameter(imax=im, jmax=jm)
    p, rhs = init_fields(param, problem=2, dtype=jnp.float32)

    step_q, prep, post, eff = make_rb_loop(
        im, jm, dx, dy, 1.9, jnp.float32, backend="pallas", n_inner=2,
        layout="quarters",
    )
    assert eff == 2
    pq, rq = prep(p), prep(rhs)
    for _ in range(10):
        pq, res_q = step_q(pq, rq)
    out = post(pq)

    step_j = make_rb_step(im, jm, dx, dy, 1.9, jnp.float32, backend="jnp")
    pj = p
    for _ in range(20):
        pj, res_j = step_j(pj, rhs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(pj), rtol=0,
                               atol=1e-5)
    assert float(res_q) == pytest.approx(float(res_j), rel=1e-4)


def test_quarters_rejects_odd_dims():
    with pytest.raises(ValueError, match="even"):
        make_rb_loop(65, 64, 1 / 65, 1 / 64, 1.9, jnp.float32,
                     backend="pallas", layout="quarters")


def test_auto_layout_falls_back_on_odd_dims():
    """layout='auto' with odd dims must silently use the checkerboard
    kernel, not error."""
    step, prep, post, eff = make_rb_loop(
        66, 63, 1 / 66, 1 / 63, 1.9, jnp.float32, backend="pallas",
        n_inner=2, layout="auto",
    )
    param = Parameter(imax=66, jmax=63)
    p, rhs = init_fields(param, problem=2, dtype=jnp.float32)
    pp, res = step(prep(p), prep(rhs))
    assert post(pp).shape == p.shape and float(res) >= 0.0
