"""Pallas red-black SOR kernel vs the jnp reference path.

The kernel must reproduce the jnp half-sweep pair (ops/sor.py `sor_pass`,
itself validated against the reference's golden p.dat) cell-for-cell: same
checkerboard cells, same red-then-black ordering, same residual accumulation.
Runs in interpret mode on the CPU test mesh."""

import jax.numpy as jnp
import numpy as np
import pytest

from pampi_tpu.models.poisson import (
    init_fields,
    make_rb_step,
    make_rb_step_padded,
    make_solver_fn,
)
from pampi_tpu.ops.sor_pallas import pick_block_rows, pad_array, unpad_array
from pampi_tpu.utils.params import Parameter


@pytest.mark.parametrize("shape", [(32, 32), (100, 100), (64, 32), (48, 96)])
def test_rb_step_padded_matches_jnp(shape):
    imax, jmax = shape
    param = Parameter(imax=imax, jmax=jmax)
    p0, rhs = init_fields(param, problem=2, dtype=jnp.float64)
    dx, dy = 1.0 / imax, 1.0 / jmax

    step_jnp = make_rb_step(imax, jmax, dx, dy, 1.9, jnp.float64, backend="jnp")
    step_pal, pad, unpad = make_rb_step_padded(
        imax, jmax, dx, dy, 1.9, jnp.float64, interpret=True, kernel="blocked"
    )

    p_j = p0
    p_p, rhs_p = pad(p0), pad(rhs)
    for _ in range(3):
        p_j, res_j = step_jnp(p_j, rhs)
        p_p, res_p = step_pal(p_p, rhs_p)
        np.testing.assert_allclose(
            np.asarray(unpad(p_p)), np.asarray(p_j), atol=1e-13
        )
        np.testing.assert_allclose(float(res_p), float(res_j), rtol=1e-12)


def test_rb_multiblock():
    """Force several row blocks so halo rows, the in-place write-back, and the
    tail-block masking are exercised across block boundaries."""
    imax, jmax = 64, 100  # 100+2 rows over BR=16 blocks -> ragged tail block
    param = Parameter(imax=imax, jmax=jmax)
    p0, rhs = init_fields(param, problem=2, dtype=jnp.float64)
    dx, dy = 1.0 / imax, 1.0 / jmax

    from pampi_tpu.ops.sor_pallas import make_rb_iter_pallas, neumann_bc_padded

    step_jnp = make_rb_step(imax, jmax, dx, dy, 1.9, jnp.float64, backend="jnp")
    rb16, br = make_rb_iter_pallas(
        imax, jmax, dx, dy, 1.9, jnp.float64, block_rows=16, interpret=True
    )
    p_j, res_j = step_jnp(p0, rhs)
    p_p, rsq = rb16(pad_array(p0, 16), pad_array(rhs, 16))
    p_p = neumann_bc_padded(p_p, jmax, imax)
    np.testing.assert_allclose(
        np.asarray(unpad_array(p_p, jmax, imax)), np.asarray(p_j), atol=1e-13
    )
    np.testing.assert_allclose(float(rsq / imax / jmax), float(res_j), rtol=1e-12)


def test_full_solve_matches_jnp():
    """Entire convergence loop (lax.while_loop carrying the padded array)."""
    imax = jmax = 64
    param = Parameter(imax=imax, jmax=jmax)
    p0, rhs = init_fields(param, problem=2, dtype=jnp.float64)
    dx = dy = 1.0 / 64
    eps, itermax = 1e-4, 2000

    sj = make_solver_fn(imax, jmax, dx, dy, 1.9, eps, itermax, jnp.float64,
                        backend="jnp")
    sp = make_solver_fn(imax, jmax, dx, dy, 1.9, eps, itermax, jnp.float64,
                        backend="pallas")
    pj, resj, itj = sj(p0, rhs)
    pp, resp, itp = sp(p0, rhs)
    assert int(itj) == int(itp)
    np.testing.assert_allclose(float(resp), float(resj), rtol=1e-10)
    np.testing.assert_allclose(np.asarray(pp), np.asarray(pj), atol=1e-10)


def test_pick_block_rows_aligned():
    from pampi_tpu.ops.sor_pallas import pick_block_rows_tblock, tblock_halo

    for jmax, imax in [(4096, 4096), (100, 100), (8192, 8192), (30, 50)]:
        br = pick_block_rows(jmax, imax, jnp.float32)
        assert br % 8 == 0 and br >= 8
        for n_inner in (1, 4, 8):
            br = pick_block_rows_tblock(jmax, imax, jnp.float32, n_inner)
            assert br % 8 == 0 and br >= tblock_halo(n_inner, jnp.float32)


@pytest.mark.parametrize("shape", [(32, 32), (100, 100), (64, 32), (48, 96)])
def test_fused_matches_jnp(shape):
    """The fused single-sweep kernel must match the jnp half-sweep pair
    cell-for-cell, including the residual, across several iterations."""
    imax, jmax = shape
    param = Parameter(imax=imax, jmax=jmax)
    p0, rhs = init_fields(param, problem=2, dtype=jnp.float64)
    dx, dy = 1.0 / imax, 1.0 / jmax

    step_jnp = make_rb_step(imax, jmax, dx, dy, 1.9, jnp.float64, backend="jnp")
    step_pal, pad, unpad = make_rb_step_padded(
        imax, jmax, dx, dy, 1.9, jnp.float64, interpret=True, kernel="fused"
    )

    p_j = p0
    p_p, rhs_p = pad(p0), pad(rhs)
    for _ in range(3):
        p_j, res_j = step_jnp(p_j, rhs)
        p_p, res_p = step_pal(p_p, rhs_p)
        np.testing.assert_allclose(
            np.asarray(unpad(p_p)), np.asarray(p_j), atol=1e-13
        )
        np.testing.assert_allclose(float(res_p), float(res_j), rtol=1e-12)


@pytest.mark.parametrize("shape", [(32, 32), (100, 100), (64, 32), (48, 96)])
@pytest.mark.parametrize("n_inner", [1, 2, 4])
def test_tblock_matches_jnp(shape, n_inner):
    """The temporal-blocked kernel (n_inner RB iterations + Neumann BCs per
    HBM sweep) must equal n_inner applications of the jnp step cell-for-cell,
    and its residual must be the last iteration's."""
    imax, jmax = shape
    param = Parameter(imax=imax, jmax=jmax)
    p0, rhs = init_fields(param, problem=2, dtype=jnp.float64)
    dx, dy = 1.0 / imax, 1.0 / jmax

    step_jnp = make_rb_step(imax, jmax, dx, dy, 1.9, jnp.float64, backend="jnp")
    step_pal, pad, unpad = make_rb_step_padded(
        imax, jmax, dx, dy, 1.9, jnp.float64, interpret=True,
        kernel="tblock", n_inner=n_inner,
    )

    p_j = p0
    p_p, rhs_p = pad(p0), pad(rhs)
    for _ in range(2):  # two sweeps: ghost state carried across calls
        for _ in range(n_inner):
            p_j, res_j = step_jnp(p_j, rhs)
        p_p, res_p = step_pal(p_p, rhs_p)
        np.testing.assert_allclose(
            np.asarray(unpad(p_p)), np.asarray(p_j), atol=1e-13
        )
        np.testing.assert_allclose(float(res_p), float(res_j), rtol=1e-12)


def test_tblock_multiblock():
    """Force several row blocks so the halo recompute depth (2 rows per inner
    iteration) and the ragged tail are exercised across block boundaries."""
    imax, jmax = 64, 100
    param = Parameter(imax=imax, jmax=jmax)
    p0, rhs = init_fields(param, problem=2, dtype=jnp.float64)
    dx, dy = 1.0 / imax, 1.0 / jmax

    from pampi_tpu.ops.sor_pallas import make_rb_iter_tblock, tblock_halo

    step_jnp = make_rb_step(imax, jmax, dx, dy, 1.9, jnp.float64, backend="jnp")
    rb, br, h = make_rb_iter_tblock(
        imax, jmax, dx, dy, 1.9, jnp.float64, n_inner=3, block_rows=16,
        interpret=True,
    )
    assert br == 16 and h == tblock_halo(3, jnp.float64)
    p_j = p0
    for _ in range(3):
        p_j, res_j = step_jnp(p_j, rhs)
    p_p, rsq = rb(pad_array(p0, 16, h), pad_array(rhs, 16, h))
    np.testing.assert_allclose(
        np.asarray(unpad_array(p_p, jmax, imax, h)), np.asarray(p_j),
        atol=1e-13,
    )
    np.testing.assert_allclose(float(rsq / imax / jmax), float(res_j),
                               rtol=1e-12)


def test_fused_multiblock():
    """Several row blocks: halo red-recompute, ragged tail masking, and the
    double-buffered store drain across block boundaries."""
    imax, jmax = 64, 100
    param = Parameter(imax=imax, jmax=jmax)
    p0, rhs = init_fields(param, problem=2, dtype=jnp.float64)
    dx, dy = 1.0 / imax, 1.0 / jmax

    from pampi_tpu.ops.sor_pallas import make_rb_iter_tblock, tblock_halo

    step_jnp = make_rb_step(imax, jmax, dx, dy, 1.9, jnp.float64, backend="jnp")
    rb16, br, h = make_rb_iter_tblock(
        imax, jmax, dx, dy, 1.9, jnp.float64, n_inner=1, block_rows=16,
        interpret=True,
    )
    assert br == 16 and h == tblock_halo(1, jnp.float64)
    p_j, res_j = step_jnp(p0, rhs)
    p_p, rsq = rb16(pad_array(p0, 16, h), pad_array(rhs, 16, h))
    np.testing.assert_allclose(
        np.asarray(unpad_array(p_p, jmax, imax, h)), np.asarray(p_j), atol=1e-13
    )
    np.testing.assert_allclose(float(rsq / imax / jmax), float(res_j), rtol=1e-12)


def test_tblock_kernel_composes_with_shard_map():
    """The per-shard-kernel + mesh-collective composition that multi-chip
    perf rides (per-device Pallas kernel, psum residual): the tblock kernel
    inside shard_map must match the direct call bitwise. check_vma=False
    because pallas_call declares no varying-mesh-axes info (the standard
    composition form; validated on real TPU hardware with identical
    results)."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    from pampi_tpu.ops import sor_pallas as sp

    N = 64
    param = Parameter(imax=N, jmax=N, tpu_dtype="float32")
    p, rhs = init_fields(param, problem=2, dtype=jnp.float32)
    rb, br, h = sp.make_rb_iter_tblock(
        N, N, 1.0 / N, 1.0 / N, 1.9, jnp.float32, n_inner=2, interpret=True
    )
    pp, rp = sp.pad_array(p, br, h), sp.pad_array(rhs, br, h)
    d_p, d_r = jax.jit(rb)(pp, rp)

    mesh = Mesh(np.array(jax.devices()[:2]), ("r",))

    def kern(pl_, rl_):
        out, r = rb(pl_, rl_)
        return out, jax.lax.pmax(r, "r")  # any collective proves the wiring

    from pampi_tpu.parallel.comm import compat_shard_map

    sm = compat_shard_map(kern, mesh=mesh, in_specs=(P(), P()),
                          out_specs=(P(), P()), check_vma=False)
    smf = jax.jit(sm)
    s_p, s_r = smf(pp, rp)
    assert float(d_r) == float(s_r)
    np.testing.assert_array_equal(np.asarray(d_p), np.asarray(s_p))


def test_quarters_bf16_storage_f32_compute():
    """bf16 dtype selects storage-only bf16: windows/HBM bf16, iteration
    and residual in f32. The trajectory tracks the f32 kernel to bf16
    resolution (~1e-2 on O(1) fields) and the residual comes back f32."""
    from pampi_tpu.ops import sor_pallas as sp

    N = 64
    param = Parameter(imax=N, jmax=N, tpu_dtype="float32")
    p, rhs = init_fields(param, problem=2, dtype=jnp.float32)

    outs = {}
    for dt in (jnp.float32, jnp.bfloat16):
        rb, brq, h = sp.make_rb_iter_tblock_quarters(
            N, N, 1.0 / N, 1.0 / N, 1.9, dt, n_inner=2, interpret=True
        )
        xq = sp.pad_quarters(p.astype(dt), brq, h)
        rq = sp.pad_quarters(rhs.astype(dt), brq, h)
        for _ in range(3):
            xq, res = rb(xq, rq)
        outs[dt] = (sp.unpad_quarters(xq, N, N, h), res)
    assert outs[jnp.bfloat16][1].dtype == jnp.float32
    f32_p = np.asarray(outs[jnp.float32][0], np.float32)
    bf_p = np.asarray(outs[jnp.bfloat16][0], np.float32)
    np.testing.assert_allclose(bf_p, f32_p, atol=4e-2, rtol=0)
    # the residuals agree within bf16 state drift (the f32 path itself is
    # regression-locked against the jnp oracle by test_tblock_matches_jnp
    # and tests/test_sor_quarters.py)
    np.testing.assert_allclose(
        float(outs[jnp.float32][1]), float(outs[jnp.bfloat16][1]),
        rtol=0.3,
    )


def test_quarters_vmem_feasibility_guard(monkeypatch):
    """Builds whose scratch sets exceed the VMEM budget raise a clear
    ValueError instead of crashing the Mosaic compiler at first dispatch
    (round-2 advisor finding). On such grids BOTH fused kernels are
    infeasible (the windows scale with the padded width), so: forced pallas
    propagates the error, auto falls all the way back to jnp."""
    from pampi_tpu.models import poisson
    from pampi_tpu.ops import sor_pallas as sp

    # an absurdly wide grid: w2p alone makes the windows infeasible
    wide = 600_000
    assert not sp.quarters_feasible(64, 8, sp.padded_width(wide // 2), 4)
    assert not sp.tblock_feasible(64, 8, sp.padded_width(wide), 4)
    with pytest.raises(ValueError, match="VMEM budget"):
        sp.make_rb_iter_tblock_quarters(
            wide, 64, 1.0 / wide, 1.0 / 64, 1.9, jnp.float32, interpret=True
        )
    with pytest.raises(ValueError, match="VMEM budget"):
        poisson.make_rb_loop(
            wide, 64, 1.0 / wide, 1.0 / 64, 1.9, jnp.float32,
            backend="pallas", n_inner=2, layout="auto",
        )
    # auto backend on the same grid lands on the jnp path (eff == 1)
    monkeypatch.setattr(poisson, "_use_pallas", lambda *a, **k: True)
    step, prep, post, eff = poisson.make_rb_loop(
        wide, 64, 1.0 / wide, 1.0 / 64, 1.9, jnp.float32,
        backend="auto", n_inner=2, layout="auto",
    )
    assert eff == 1  # jnp fallback, not a doomed kernel
