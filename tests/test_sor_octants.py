"""Octant-decomposition 3-D SOR layout (ops/sor_octants.py + the Pallas
kernel in ops/sor3d_pallas.py): bijection, oracle vs the masked 3-D
reference path, kernel vs oracle (interpret, incl. multi-block), and the
make_pressure_solve_3d layout dispatch. Tolerances: see
tests/test_sor_quarters.py — ulp-level equality across layouts."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pampi_tpu.models.ns3d import (
    checkerboard_mask_3d,
    make_pressure_solve_3d,
    neumann_faces_3d,
    sor_coefficients_3d,
    sor_pass_3d,
)
from pampi_tpu.ops import sor3d_pallas as sp3
from pampi_tpu.ops.sor_octants import (
    pack_octants,
    rb_iter_octants,
    unpack_octants,
)


def _rand(shape, dtype, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.normal(size=shape), dtype)


def test_pack_unpack_roundtrip():
    p = _rand((10, 14, 18), jnp.float64)
    np.testing.assert_array_equal(
        np.asarray(unpack_octants(pack_octants(p))), np.asarray(p)
    )


@pytest.mark.parametrize("km,jm,im", [(8, 8, 8), (16, 8, 12), (12, 16, 8)])
def test_oracle_matches_masked_path_f64(km, jm, im):
    """f64 octant oracle vs the masked 3-D reference composition
    (sor_pass_3d odd→even + neumann_faces_3d) over 4 iterations."""
    shape = (km + 2, jm + 2, im + 2)
    p, rhs = _rand(shape, jnp.float64, 1), _rand(shape, jnp.float64, 2)
    dx, dy, dz = 1.0 / im, 1.0 / jm, 1.0 / km
    factor, idx2, idy2, idz2 = sor_coefficients_3d(dx, dy, dz, 1.7)
    odd = checkerboard_mask_3d(km, jm, im, 1, jnp.float64)
    even = checkerboard_mask_3d(km, jm, im, 0, jnp.float64)
    pj = p
    for _ in range(4):
        pj, r0 = sor_pass_3d(pj, rhs, odd, factor, idx2, idy2, idz2)
        pj, r1 = sor_pass_3d(pj, rhs, even, factor, idx2, idy2, idz2)
        pj = neumann_faces_3d(pj)
    q, qr = pack_octants(p), pack_octants(rhs)
    for _ in range(4):
        q, rsq = rb_iter_octants(q, qr, factor, idx2, idy2, idz2)
    np.testing.assert_allclose(
        np.asarray(unpack_octants(q)), np.asarray(pj), rtol=0, atol=1e-13
    )
    assert float(rsq) == pytest.approx(float(r0 + r1), rel=1e-10)


@pytest.mark.parametrize("km,jm,im,k,bko", [
    (8, 8, 8, 1, None), (8, 8, 8, 3, None),
    (12, 10, 8, 4, 2), (22, 14, 14, 2, 4),  # multi-block (tail: 22%4=2)
])
def test_kernel_matches_oracle(km, jm, im, k, bko):
    shape = (km + 2, jm + 2, im + 2)
    p, rhs = _rand(shape, jnp.float32, 3), _rand(shape, jnp.float32, 4)
    dx, dy, dz = 1.0 / im, 1.0 / jm, 1.0 / km
    factor, idx2, idy2, idz2 = sor_coefficients_3d(dx, dy, dz, 1.7)
    rb, bk, h = sp3.make_rb_iter_tblock_3d_octants(
        im, jm, km, dx, dy, dz, 1.7, jnp.float32, n_inner=k, block_k=bko,
        interpret=True,
    )
    po, ro = sp3.pad_octants(p, bk, h), sp3.pad_octants(rhs, bk, h)
    po, rsq = rb(po, ro)
    out = sp3.unpad_octants(po, km, jm, im, h)
    q, qr = pack_octants(p), pack_octants(rhs)
    for _ in range(k):
        q, osq = rb_iter_octants(q, qr, factor, idx2, idy2, idz2)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(unpack_octants(q)), rtol=0, atol=2e-5
    )
    assert float(rsq) == pytest.approx(float(osq), rel=1e-4)


def test_pressure_solve_octants_matches_jnp():
    """layout='octants' forced through make_pressure_solve_3d (interpret on
    CPU, backend='pallas') vs the jnp masked solve: same iteration count,
    converged fields at ulp-sum tolerance."""
    km = jm = im = 12
    dx = 1.0 / im
    p = jnp.zeros((km + 2, jm + 2, im + 2), jnp.float32)
    rhs = _rand(p.shape, jnp.float32, 5)
    solve_o = jax.jit(make_pressure_solve_3d(
        im, jm, km, dx, dx, dx, 1.7, 0.0, 20, jnp.float32,
        backend="pallas", n_inner=2, layout="octants",
    ))
    solve_j = jax.jit(make_pressure_solve_3d(
        im, jm, km, dx, dx, dx, 1.7, 0.0, 20, jnp.float32,
        backend="jnp", n_inner=1, layout="checkerboard",
    ))
    po, res_o, it_o = solve_o(p, rhs)
    pj, res_j, it_j = solve_j(p, rhs)
    assert int(it_o) == int(it_j) == 20
    np.testing.assert_allclose(np.asarray(po), np.asarray(pj), rtol=0,
                               atol=1e-4)
    assert float(res_o) == pytest.approx(float(res_j), rel=1e-3)


def test_octants_rejects_odd_dims():
    with pytest.raises(ValueError, match="even"):
        make_pressure_solve_3d(
            15, 16, 16, 1 / 15, 1 / 16, 1 / 16, 1.7, 1e-3, 10, jnp.float32,
            backend="pallas", layout="octants",
        )
