"""Direct DCT-diagonalization Poisson solve (ops/dctpoisson.py,
tpu_solver=fft): machine-precision exactness of the discrete solve, the
solve-contract wrapper, and NS physics parity with the iterative solvers."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pampi_tpu.ops.dctpoisson import (
    dct2_matrix,
    make_dct_solve_2d,
    make_dct_solve_3d,
    poisson_dct_2d,
    poisson_dct_3d,
)
from pampi_tpu.utils.params import Parameter, read_parameter

DT = jnp.float64


def test_dct_matrix_orthonormal():
    for N in (4, 25, 37):
        D = dct2_matrix(N)
        np.testing.assert_allclose(D @ D.T, np.eye(N), atol=1e-12)


@pytest.mark.parametrize("shape", [(37, 52), (100, 100), (25, 100)])
def test_dct2d_solves_exactly(shape):
    J, I = shape
    dx, dy = 1.0 / I, 1.0 / J
    rng = np.random.default_rng(0)
    r = rng.standard_normal((J, I))
    r -= r.mean()
    sol = make_dct_solve_2d(I, J, dx, dy, DT)
    rhs = jnp.zeros((J + 2, I + 2), DT).at[1:-1, 1:-1].set(jnp.asarray(r, DT))
    p, res, it = jax.jit(sol)(jnp.zeros_like(rhs), rhs)
    assert int(it) == 1
    assert float(res) < 1e-20  # machine-precision residual in f64


def test_dct3d_solves_exactly():
    K, J, I = 25, 25, 100  # the canal3d coarse shape
    dx, dy, dz = 1.0 / I, 1.0 / J, 1.0 / K
    rng = np.random.default_rng(1)
    r = rng.standard_normal((K, J, I))
    r -= r.mean()
    sol = make_dct_solve_3d(I, J, K, dx, dy, dz, DT)
    rhs = jnp.zeros((K + 2, J + 2, I + 2), DT)
    rhs = rhs.at[1:-1, 1:-1, 1:-1].set(jnp.asarray(r, DT))
    p, res, it = jax.jit(sol)(jnp.zeros_like(rhs), rhs)
    assert int(it) == 1
    assert float(res) < 1e-20


def test_dct_matches_sor_solution():
    from pampi_tpu.models.poisson import make_solver_fn

    J = I = 48
    dx = dy = 1.0 / I
    rng = np.random.default_rng(2)
    r = rng.standard_normal((J, I))
    r -= r.mean()
    rhs = jnp.zeros((J + 2, I + 2), DT).at[1:-1, 1:-1].set(jnp.asarray(r, DT))
    p0 = jnp.zeros_like(rhs)
    p_d, _, _ = jax.jit(make_dct_solve_2d(I, J, dx, dy, DT))(p0, rhs)
    sor = jax.jit(make_solver_fn(I, J, dx, dy, 1.9, 1e-9, 100000, DT,
                                 backend="jnp"))
    p_s, _, _ = sor(p0, rhs)
    a = np.asarray(p_d)[1:-1, 1:-1]
    b = np.asarray(p_s)[1:-1, 1:-1]
    diff = (a - a.mean()) - (b - b.mean())
    assert np.sqrt((diff**2).mean()) < 1e-8


def test_ns2d_fft_matches_sor_run(reference_dir):
    from pampi_tpu.models.ns2d import NS2DSolver

    param = read_parameter(
        str(reference_dir / "assignment-5" / "sequential" / "dcavity.par")
    ).replace(te=0.05, imax=32, jmax=32, eps=1e-8)
    a = NS2DSolver(param)
    a.run(progress=False)
    b = NS2DSolver(param.replace(tpu_solver="fft"))
    b.run(progress=False)
    assert a.nt == b.nt
    np.testing.assert_allclose(np.asarray(a.u), np.asarray(b.u),
                               rtol=0, atol=1e-6)
    np.testing.assert_allclose(np.asarray(a.v), np.asarray(b.v),
                               rtol=0, atol=1e-6)


def test_ns3d_fft_matches_sor_run():
    from pampi_tpu.models.ns3d import NS3DSolver

    param = Parameter(
        name="dcavity3d", imax=16, jmax=16, kmax=16,
        re=10.0, te=0.05, tau=0.5, itermax=1000, eps=1e-8, omg=1.7,
        gamma=0.9,
    )
    a = NS3DSolver(param)
    a.run(progress=False)
    b = NS3DSolver(param.replace(tpu_solver="fft"))
    b.run(progress=False)
    assert a.nt == b.nt
    np.testing.assert_allclose(np.asarray(a.u), np.asarray(b.u),
                               rtol=0, atol=1e-6)


def test_fft_rejects_bfloat16():
    with pytest.raises(ValueError, match="bfloat16|float32"):
        make_dct_solve_2d(16, 16, 1 / 16, 1 / 16, jnp.bfloat16)


def test_dist_fft_matches_single_device():
    """Distributed fft (collective-matmul DCT) vs single-device fft: same
    exact solution on 2-D and 3-D meshes."""
    from pampi_tpu.models.poisson import PoissonSolver
    from pampi_tpu.models.poisson_dist import DistPoissonSolver
    from pampi_tpu.parallel.comm import CartComm

    param = Parameter(imax=64, jmax=64, itermax=10, eps=1e-12,
                      tpu_solver="fft")
    single = PoissonSolver(param, problem=2)
    it_s, res_s = single.solve()
    assert it_s == 1 and res_s < 1e-20
    for dims in [(2, 4), (8, 1), (1, 8)]:
        dist = DistPoissonSolver(param, CartComm(ndims=2, dims=dims),
                                 problem=2)
        it_d, res_d = dist.solve()
        assert it_d == 1
        assert res_d < 1e-20
        a = dist.full_field()[1:-1, 1:-1]
        b = np.asarray(single.p)[1:-1, 1:-1]
        diff = (a - a.mean()) - (b - b.mean())
        assert np.sqrt((diff**2).mean()) < 1e-10, dims


def test_dist_fft_ns3d_matches_single():
    from pampi_tpu.models.ns3d import NS3DSolver
    from pampi_tpu.models.ns3d_dist import NS3DDistSolver
    from pampi_tpu.parallel.comm import CartComm

    param = Parameter(
        name="dcavity3d", imax=16, jmax=16, kmax=16,
        re=10.0, te=0.05, tau=0.5, itermax=100, eps=1e-8, omg=1.7,
        gamma=0.9, tpu_solver="fft",
    )
    a = NS3DSolver(param)
    a.run(progress=False)
    b = NS3DDistSolver(param, CartComm(ndims=3, dims=(2, 2, 2)))
    b.run(progress=False)
    assert a.nt == b.nt
    ua, va, wa, pa = a.collect()
    ub, vb, wb, pb = b.collect()
    np.testing.assert_allclose(ua, ub, rtol=0, atol=1e-9)
    np.testing.assert_allclose(va, vb, rtol=0, atol=1e-9)
    np.testing.assert_allclose(wa, wb, rtol=0, atol=1e-9)
    np.testing.assert_allclose(pa - pa.mean(), pb - pb.mean(),
                               rtol=0, atol=1e-9)
