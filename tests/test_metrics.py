"""Serving-plane metrics registry (utils/metrics.py) + request tracing
(utils/tracing.py) — the ISSUE 18 observability layer contracts:

- HISTOGRAM QUANTILE PIN: log-bucket nearest-rank quantiles agree with
  the exact sorted-list computation (fleet/serve._percentile) within
  half a bucket (<5% relative) on small samples — the daemon's status
  percentiles may route through the bounded histogram without changing
  what a tenant reads;
- MERGE ALGEBRA: the histogram fold and the snapshot fold are
  associative and commutative, and merged counts equal the unmerged
  single-registry run — the cross-rank `--merge` fold is order-free;
- PROMETHEUS GOLDEN: the text exposition of a deterministic registry is
  byte-pinned (tests/fixtures/metrics_golden.prom) — scrape-format
  drift is a test failure, not a dashboard surprise;
- OFF-PATH IDENTITY: arming the registry (observations recorded) does
  not change the traced solver program — the shared jaxprcheck pin;
- TRACE TABLE: mint/mark/finish bound their state (no leaks), no-op
  with telemetry off, and emit a parented record set whose critical
  stages tile the end-to-end time exactly.
"""

import json
import pathlib

import pytest

from pampi_tpu.analysis.jaxprcheck import assert_offpath_identity
from pampi_tpu.fleet.serve import _percentile
from pampi_tpu.models.ns2d import NS2DSolver
from pampi_tpu.utils import metrics as mx
from pampi_tpu.utils import telemetry as tm
from pampi_tpu.utils import tracing
from pampi_tpu.utils.params import Parameter

FIXTURES = pathlib.Path(__file__).parent / "fixtures"

# a deterministic small sample spanning ~3 decades (the quantile pin
# must hold on SMALL samples — that is what a short daemon run holds)
SAMPLE = [3.7, 12.5, 12.9, 48.0, 51.2, 55.9, 210.0, 214.5, 220.1,
          221.7, 230.0, 980.4, 1010.0, 2404.9, 2630.2]


@pytest.fixture()
def tel_off(monkeypatch):
    monkeypatch.delenv("PAMPI_TELEMETRY", raising=False)
    tm.reset()
    tracing.reset()
    mx.reset()


@pytest.fixture()
def tel_on(tmp_path, monkeypatch):
    path = tmp_path / "run.jsonl"
    monkeypatch.setenv("PAMPI_TELEMETRY", str(path))
    tm.reset()
    tracing.reset()
    mx.reset()
    yield path
    tm.reset()
    tracing.reset()
    mx.reset()


# -- histogram quantiles ------------------------------------------------

def test_histogram_quantile_agrees_with_exact_on_small_samples():
    h = mx.Histogram("lat")
    for v in SAMPLE:
        h.observe(v)
    for q in (0.5, 0.95):
        exact = _percentile(SAMPLE, q)
        got = h.quantile(q)
        assert abs(got - exact) / exact < 0.05, (q, got, exact)
    # exact min/max ride alongside the buckets
    assert h.vmin == min(SAMPLE) and h.vmax == max(SAMPLE)
    assert h.n == len(SAMPLE)


def test_histogram_edges_and_floor_bucket():
    h = mx.Histogram("edges")
    # bucket k covers (BASE^(k-1), BASE^k]: an exact edge value must
    # land IN bucket k, not k+1 (the float-fuzz pullback)
    h.observe(mx.bucket_edge(8))
    assert h.counts == {8: 1}
    # non-positive and non-finite observations land in the floor bucket
    # and resolve to 0.0 — never a crash, never an unbounded index
    for bad in (0.0, -5.0, float("nan"), float("inf")):
        h.observe(bad)
    assert h.quantile(0.0) == 0.0
    assert h.n == 5


def test_histogram_merge_associative_commutative():
    def hist_of(values):
        h = mx.Histogram("m")
        for v in values:
            h.observe(v)
        return h

    a = hist_of(SAMPLE[:5])
    b = hist_of(SAMPLE[5:9])
    c = hist_of(SAMPLE[9:])
    ab_c = a.merge(b).merge(c)
    a_bc = a.merge(b.merge(c))
    ba_c = b.merge(a).merge(c)
    whole = hist_of(SAMPLE)
    for m in (ab_c, a_bc, ba_c):
        assert m.counts == whole.counts
        assert m.n == whole.n
        assert m.vmin == whole.vmin and m.vmax == whole.vmax
        assert abs(m.total - whole.total) < 1e-9
    # the merged quantile equals the single-registry quantile exactly
    # (same buckets -> same nearest-rank resolution)
    assert ab_c.quantile(0.95) == whole.quantile(0.95)


def test_snapshot_fold_and_roundtrip():
    r1, r2 = mx.Registry(), mx.Registry()
    for r, served, depth in ((r1, 3, 5), (r2, 4, 2)):
        r.counter("served", tenant="a").inc(served)
        r.gauge("depth").set(depth)
        for v in SAMPLE[:6]:
            r.histogram("lat", tenant="a").observe(v)
    s1, s2 = r1.snapshot(), r2.snapshot()
    fold = mx.merge_snapshots(s1, s2)
    assert fold == mx.merge_snapshots(s2, s1)  # commutative
    counters = {(c["name"],): c["value"] for c in fold["counters"]}
    assert counters[("served",)] == 7          # counters sum
    assert fold["gauges"][0]["value"] == 5     # gauges keep the max
    assert fold["histograms"][0]["n"] == 12    # histograms bucket-sum
    # snapshots are plain JSON and quantile-readable without a Registry
    again = json.loads(json.dumps(fold))
    assert mx.snapshot_quantile(again["histograms"][0], 0.5) \
        == mx.Histogram.from_dict(fold["histograms"][0]).quantile(0.5)
    # self-fold doubles (cumulative snapshots must never be summed
    # within a source — the reader contract this algebra implies)
    twice = mx.merge_snapshots(s1, s1)
    assert twice["counters"][0]["value"] == 6


def _snap_record(source: str, seq: int, served: int, depth: float):
    """One telemetry `metrics` record as the daemon emits it: a
    CUMULATIVE registry snapshot stamped with its (source, seq)
    lineage — the fold key telemetry_report.metrics_summary dedups on."""
    r = mx.Registry()
    r.counter("served").inc(served)
    r.gauge("depth").set(depth)
    return {"kind": "metrics", "source": source, "seq": seq,
            **r.snapshot()}


def test_metrics_fold_out_of_order_seq_last_per_source_wins():
    """The artifact fold takes the HIGHEST-seq snapshot per source even
    when records land out of order (a multi-rank merge file has no
    ordering guarantee): snapshots are cumulative, so folding any
    earlier one would double-count or under-count."""
    from tools import telemetry_report as tr

    records = [
        _snap_record("h1:100", 3, served=9, depth=2.0),   # newest first
        _snap_record("h1:100", 1, served=3, depth=7.0),
        _snap_record("h1:100", 2, served=6, depth=1.0),
    ]
    out = tr.metrics_summary(records)
    assert out["sources"] == 1
    # the seq-3 snapshot alone — not a sum across the cumulative series
    assert out["counters"]["served"] == 9
    assert out["gauges"]["depth"] == 2.0


def test_metrics_fold_duplicate_pid_seq_last_in_file_wins():
    """A replayed/duplicated (source, seq) pair must not double-count:
    the fold keeps exactly one snapshot per source, and on an exact
    (source, seq) tie the LAST record in the file wins (the `>=` in the
    fold — a rewritten snapshot supersedes its earlier flush)."""
    from tools import telemetry_report as tr

    records = [
        _snap_record("h1:100", 2, served=5, depth=4.0),
        _snap_record("h1:100", 2, served=7, depth=3.0),  # rewrite, wins
    ]
    out = tr.metrics_summary(records)
    assert out["sources"] == 1
    assert out["counters"]["served"] == 7
    assert out["gauges"]["depth"] == 3.0


def test_metrics_fold_across_sources_sums_last_snapshots_only():
    """Interleaved out-of-order arrivals from TWO sources: the fold
    merges across sources (counters sum, gauges max) but within each
    source only the newest snapshot contributes."""
    from tools import telemetry_report as tr

    records = [
        _snap_record("h1:100", 2, served=4, depth=1.0),
        _snap_record("h2:200", 1, served=10, depth=6.0),
        _snap_record("h1:100", 1, served=2, depth=9.0),   # stale, late
        _snap_record("h2:200", 2, served=11, depth=2.0),
    ]
    out = tr.metrics_summary(records)
    assert out["sources"] == 2
    assert out["counters"]["served"] == 4 + 11
    # max of the two LAST gauges (1.0, 2.0) — the stale seq-1 peak of
    # 9.0/6.0 must not leak into the fold
    assert out["gauges"]["depth"] == 2.0


def test_prometheus_render_golden():
    r = mx.Registry()
    r.counter("fleet_served_total", tenant="alice").inc(3)
    r.counter("fleet_served_total", tenant="bob").inc(1)
    r.gauge("fleet_queue_depth").set(4)
    h = r.histogram("fleet_request_latency_ms", tenant="alice")
    for v in (10.0, 100.0, 1000.0):
        h.observe(v)
    got = r.render_prometheus()
    golden = (FIXTURES / "metrics_golden.prom").read_text()
    assert got == golden
    # atomic write path produces the identical bytes
    assert got.endswith("\n")
    assert "# TYPE fleet_request_latency_ms histogram" in got
    assert 'le="+Inf"' in got


def test_registry_emits_versioned_snapshots(tel_on):
    r = mx.Registry()
    r.counter("c").inc()
    r.emit_snapshot(event="poll")
    r.counter("c").inc()
    r.emit_snapshot(event="stop")
    tm.finalize()
    recs = [json.loads(ln) for ln in open(tel_on) if ln.strip()]
    snaps = [r for r in recs if r["kind"] == "metrics"]
    assert [s["seq"] for s in snaps] == [1, 2]
    assert snaps[0]["source"] == snaps[1]["source"]
    assert snaps[-1]["counters"][0]["value"] == 2  # cumulative
    assert all(r["v"] == tm.SCHEMA_VERSION for r in snaps)


# -- off-path identity with the registry armed --------------------------

def test_offpath_jaxpr_identity_with_registry_armed(tel_off):
    """Observing into the registry is HOST work: a solver built while
    the registry holds live instruments traces the identical program
    (the ISSUE 18 all-host-side acceptance — shared jaxprcheck pin)."""
    mx.counter("fleet_served_total", tenant="t").inc(7)
    for v in SAMPLE:
        mx.histogram("fleet_request_latency_ms").observe(v)
    param = Parameter(name="dcavity", imax=16, jmax=16, re=10.0,
                      te=0.02, tau=0.5, itermax=8, eps=1e-4, omg=1.7,
                      gamma=0.9)
    assert_offpath_identity(lambda: NS2DSolver(param))


# -- request tracing ----------------------------------------------------

def test_tracing_noop_when_telemetry_off(tel_off):
    assert tracing.mint("sid") is None
    tracing.mark(None, "exec_start")
    tracing.note(None, bucket="b")
    tracing.finish(None)
    assert tracing.pending() == 0


def test_trace_stages_tile_end_to_end(tel_on):
    t = tracing.mint("alice__s0", tenant="alice")
    assert t is not None
    base = tracing._TRACES[t]["marks"]["admit"]
    for name, dt in (("bucket", 0.001), ("exec_start", 0.002),
                     ("run_start", 0.010), ("done", 0.050),
                     ("emit_end", 0.051)):
        tracing.mark(t, name, ts=base + dt)
    tracing.note(t, bucket="ns2d_16x16", family="ns2d")
    tracing.finish(t)
    assert tracing.pending() == 0
    tm.finalize()
    recs = [json.loads(ln) for ln in open(tel_on) if ln.strip()]
    spans = [r for r in recs if r["kind"] == "trace"]
    roots = [r for r in spans if r["stage"] == "request"]
    assert len(roots) == 1 and roots[0]["parent"] is None
    # every non-root span is parented — no orphans
    by_stage = {r["stage"]: r for r in spans}
    for r in spans:
        if r["stage"] != "request":
            assert r["parent"] in by_stage, r["stage"]
    # the critical stages tile the root exactly
    total = sum(by_stage[s]["ms"] for s in tracing.CRITICAL_STAGES)
    # each emitted ms is rounded to 4 decimals, so the tiling is exact
    # to the rounding grain (4 stages x 0.5e-4 ms)
    assert abs(total - roots[0]["ms"]) < 1e-3
    # detail marks are parented under queue_wait with no duration
    assert by_stage["bucket"]["parent"] == "queue_wait"
    assert by_stage["bucket"]["ms"] is None
    assert roots[0]["tenant"] == "alice"
    assert roots[0]["bucket"] == "ns2d_16x16"


def test_trace_table_bounded(tel_on, monkeypatch):
    monkeypatch.setattr(tracing, "MAX_TRACES", 8)
    ids = [tracing.mint(f"s{i}") for i in range(12)]
    assert tracing.pending() == 8  # oldest evicted, never unbounded
    tracing.finish(ids[-1])
    assert tracing.pending() == 7
    tracing.reset()
