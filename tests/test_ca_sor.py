"""Communication-avoiding distributed SOR (deep halos + local temporal
blocking, parallel/stencil2d.ca_* / stencil3d.ca_*): depth-H exchange
correctness, and exact trajectory parity with the single-device solvers for
n > 1 local iterations per exchange."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from pampi_tpu.models.poisson import PoissonSolver
from pampi_tpu.models.poisson_dist import DistPoissonSolver
from pampi_tpu.models.ns2d import NS2DSolver
from pampi_tpu.models.ns2d_dist import NS2DDistSolver
from pampi_tpu.models.ns3d import NS3DSolver
from pampi_tpu.models.ns3d_dist import NS3DDistSolver
from pampi_tpu.parallel.comm import CartComm, halo_exchange
from pampi_tpu.utils.params import Parameter


def test_deep_halo_exchange_fills_depth_strips():
    """Each shard's depth-H ghost strips must carry the neighbour's H
    innermost OWNED layers (rank-id pattern, the test.c discipline)."""
    H = 4
    jl = il = 8
    comm = CartComm(ndims=2, dims=(2, 4))

    def kern():
        j = jax.lax.axis_index("j")
        i = jax.lax.axis_index("i")
        rank = (j * 4 + i).astype(jnp.float32)
        x = jnp.full((jl + 2 * H, il + 2 * H), rank)
        return halo_exchange(x, comm, depth=H)

    out = jax.jit(
        comm.shard_map(kern, in_specs=(), out_specs=P("j", "i"))
    )()
    out = np.asarray(out)
    for bj in range(2):
        for bi in range(4):
            blk = out[bj * (jl + 2 * H):(bj + 1) * (jl + 2 * H),
                      bi * (il + 2 * H):(bi + 1) * (il + 2 * H)]
            rank = bj * 4 + bi
            own = blk[H:-H, H:-H]
            np.testing.assert_array_equal(own, rank)
            if bj > 0:
                np.testing.assert_array_equal(
                    blk[:H, H:-H], rank - 4
                )  # low-j ghosts from the j-neighbour
            else:
                np.testing.assert_array_equal(blk[:H, H:-H], rank)
            if bi < 3:
                np.testing.assert_array_equal(blk[H:-H, -H:], rank + 1)
            if bi > 0:
                np.testing.assert_array_equal(blk[H:-H, :H], rank - 1)


@pytest.mark.parametrize("n_ca", [2, 4])
def test_poisson_ca_inner_exact_parity(n_ca):
    """n local iterations per exchange: iteration-count-limited solve (the
    convergence check granularity is n, so pick itermax % n == 0) must equal
    the single-device trajectory bitwise."""
    param = Parameter(imax=32, jmax=32, itermax=80, eps=1e-30, omg=1.8,
                      tpu_ca_inner=n_ca)
    single = PoissonSolver(param, problem=2)
    it_s, res_s = single.solve()
    dist = DistPoissonSolver(param, CartComm(ndims=2), problem=2)
    it_d, res_d = dist.solve()
    assert it_d == it_s == 80
    assert res_d == pytest.approx(res_s, rel=1e-12)
    np.testing.assert_allclose(
        dist.full_field(), np.asarray(single.p), rtol=0, atol=1e-11
    )


def test_poisson_ca_inner_clamped_by_shard_extent():
    """tpu_ca_inner too deep for the shards (2n > min local extent) must be
    clamped, not crash: 8x1 mesh over jmax=16 → jl=2 → n capped at 1."""
    param = Parameter(imax=16, jmax=16, itermax=50, eps=1e-30, omg=1.7,
                      tpu_ca_inner=8)
    single = PoissonSolver(param, problem=2)
    single.solve()
    dist = DistPoissonSolver(param, CartComm(ndims=2, dims=(8, 1)), problem=2)
    it_d, _ = dist.solve()
    assert it_d == 50
    np.testing.assert_allclose(
        dist.full_field(), np.asarray(single.p), rtol=0, atol=1e-11
    )


def test_poisson_extent1_shards_fall_back_correctly():
    """A shard extent of 1 (jmax=8 over 8 shards) cannot ship depth-2 strips
    from owned cells; the per-half-sweep fallback must keep exact parity
    (regression: the CA path once ran here with H=2 and shipped ghost rows
    as owned data)."""
    param = Parameter(imax=8, jmax=8, itermax=60, eps=1e-30, omg=1.7)
    single = PoissonSolver(param, problem=2)
    it_s, res_s = single.solve()
    dist = DistPoissonSolver(param, CartComm(ndims=2, dims=(8, 1)), problem=2)
    it_d, res_d = dist.solve()
    assert it_d == it_s == 60
    assert res_d == pytest.approx(res_s, rel=1e-12)
    np.testing.assert_allclose(
        dist.full_field(), np.asarray(single.p), rtol=0, atol=1e-11
    )


def test_ns2d_ca_inner_exact_parity(reference_dir):
    """Full NS-2D stepper with n=2: pressure solves are itermax-capped (eps
    tiny, itermax % n == 0) so the whole run must equal single-device
    bitwise."""
    from pampi_tpu.utils.params import read_parameter

    param = read_parameter(
        str(reference_dir / "assignment-5" / "sequential" / "dcavity.par")
    ).replace(te=0.002, imax=32, jmax=32, itermax=40, eps=1e-30,
              tpu_ca_inner=2)
    single = NS2DSolver(param)
    single.run(progress=False)
    dist = NS2DDistSolver(param, CartComm(ndims=2, dims=(2, 4)))
    dist.run(progress=False)
    ud, vd, pd = dist.fields()
    assert dist.nt == single.nt
    np.testing.assert_array_equal(np.asarray(single.u), ud)
    np.testing.assert_array_equal(np.asarray(single.p), pd)


def test_ns3d_ca_inner_exact_parity():
    param = Parameter(
        name="dcavity3d", imax=16, jmax=16, kmax=16,
        re=10.0, te=0.015, tau=0.5, itermax=40, eps=1e-30, omg=1.7,
        gamma=0.9, tpu_ca_inner=2,
    )
    single = NS3DSolver(param)
    single.run(progress=False)
    dist = NS3DDistSolver(param, CartComm(ndims=3))
    dist.run(progress=False)
    assert dist.nt == single.nt
    for a, b in zip(single.collect(), dist.collect()):
        np.testing.assert_array_equal(a, b)


def test_ns3d_ca_converged_parity():
    """With a real eps the CA run may overshoot by < n iterations per solve;
    the converged states must still agree to solver tolerance."""
    param = Parameter(
        name="dcavity3d", imax=16, jmax=16, kmax=16,
        re=10.0, te=0.015, tau=0.5, itermax=100, eps=1e-4, omg=1.7,
        gamma=0.9,
    )
    a = NS3DSolver(param)
    a.run(progress=False)
    b = NS3DDistSolver(param.replace(tpu_ca_inner=4), CartComm(ndims=3))
    b.run(progress=False)
    assert a.nt == b.nt
    for x, y in zip(a.collect(), b.collect()):
        np.testing.assert_allclose(x, y, rtol=0, atol=5e-4)
