"""Comm/compute overlap (tpu_overlap): the double-buffered
interior/boundary schedule vs the serial fused schedule.

Contracts pinned here:
- trajectory parity: overlap-on equals overlap-off (the serial parity
  oracle) for plain/obstacle/ragged 2-D and 3-D configs at the repo's
  ulp contract — both paths run the identical Pallas kernels, the
  interior half's cone never reaches the exchanged strips, and max is
  reduction-order exact, so the only admissible gap is XLA fusing the
  jnp pieces (the solve) differently between the two compiled programs
  (fma contraction; observed 0 on most configs, last-ulp on 3-D
  obstacle);
- off-identity: tpu_overlap off and (auto, off-TPU) trace byte-identical
  programs — the CONTRACTS.json hash contract;
- schedule structure: the traced overlapped chunk posts the deep
  exchange double-buffered (prologue before the loop; no same-iteration
  kernel consumes the ppermute results) and the SERIAL chunk fails the
  same check — commcheck.overlap_schedule_violations' negative control;
- stale-buffer detection: a generation-skewed double buffer (the
  parallel/overlap.GEN_SKEW mutation hook) poisons t with NaN instead of
  silently consuming stale halos;
- halocheck: the overlap interior half's measured footprint excludes
  the exchanged strips, and a smuggled deeper read fails with the
  kernel's file:line;
- the persistent-exchange layer: persistent_exchange and the jitted
  exchange probe are cached (same object back), and the schedule traces
  the identical program to halo_exchange.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pampi_tpu.models.ns2d_dist import NS2DDistSolver
from pampi_tpu.models.ns3d_dist import NS3DDistSolver
from pampi_tpu.parallel import overlap as ovl
from pampi_tpu.parallel.comm import (
    CartComm,
    halo_exchange,
    make_exchange_probe,
    persistent_exchange,
)
from pampi_tpu.utils import dispatch
from pampi_tpu.utils.params import Parameter
from pampi_tpu.analysis import commcheck, halocheck
from pampi_tpu.analysis.jaxprcheck import count_prim, trace_chunk

_B2 = dict(name="dcavity", imax=16, jmax=16, re=10.0, te=0.02, tau=0.5,
           itermax=10, eps=1e-4, omg=1.7, gamma=0.9,
           tpu_fuse_phases="on", tpu_sor_layout="checkerboard")
_B3 = dict(name="dcavity3d", imax=8, jmax=8, kmax=8, re=10.0, te=0.02,
           tau=0.5, itermax=8, eps=1e-4, omg=1.7, gamma=0.9,
           tpu_fuse_phases="on")


def _run_pair_2d(param, dims):
    ser = NS2DDistSolver(param.replace(tpu_overlap="off"),
                         CartComm(ndims=2, dims=dims))
    ser.run(progress=False)
    assert dispatch.last("overlap_ns2d_dist") == "serial (tpu_overlap off)"
    o = NS2DDistSolver(param.replace(tpu_overlap="on"),
                       CartComm(ndims=2, dims=dims))
    o.run(progress=False)
    assert dispatch.last("overlap_ns2d_dist") == "overlap (forced)"
    assert o.nt == ser.nt and ser.nt > 1
    for n, (a, b) in zip("uvp", zip(ser.fields(), o.fields())):
        _assert_ulp_equal(a, b, n)
    return ser, o


def _assert_ulp_equal(a, b, name):
    d = np.abs(np.asarray(a) - np.asarray(b))
    assert np.isfinite(d).all() and d.max() < 1e-12, (name, d.max())


def test_overlap_matches_serial_2d_plain():
    _run_pair_2d(Parameter(**_B2), (2, 2))


def test_overlap_matches_serial_2d_obstacle():
    param = Parameter(name="canal_obstacle", imax=24, jmax=12, re=10.0,
                      te=0.02, tau=0.5, itermax=10, eps=1e-3, omg=1.7,
                      gamma=0.9, bcLeft=3, bcRight=3,
                      obstacles="0.3,0.3,0.6,0.6",
                      tpu_fuse_phases="on", tpu_sor_layout="checkerboard")
    ser, o = _run_pair_2d(param, (2, 2))
    assert ser.masks is not None


def test_overlap_matches_serial_2d_ragged():
    # 18 rows over a 4-mesh: ceil-divided 5-row shards with a dead tail.
    # Restriction FORCED so the banded grids run at the ragged/uneven
    # block bounds (auto declines at this degenerate geometry) — the
    # global-coordinate gating must keep the restricted halves exact.
    param = Parameter(**{**_B2, "imax": 18, "jmax": 18},
                      tpu_overlap_restrict="on")
    ser, o = _run_pair_2d(param, (4, 2))
    assert ser.ragged
    assert dispatch.last("overlap_grid_ns2d_dist").startswith("restricted")


def _run_pair_3d(param, dims=(2, 2, 2)):
    comm = CartComm(ndims=3, dims=dims)
    ser = NS3DDistSolver(param.replace(tpu_overlap="off"), comm)
    ser.run(progress=False)
    o = NS3DDistSolver(param.replace(tpu_overlap="on"), comm)
    o.run(progress=False)
    assert dispatch.last("overlap_ns3d_dist") == "overlap (forced)"
    assert o.nt == ser.nt and ser.nt >= 1
    for n, (a, b) in zip("uvwp", zip(ser.collect(), o.collect())):
        _assert_ulp_equal(a, b, n)
    return ser, o


def test_overlap_matches_serial_3d_plain():
    # 4-cell shards: the interior region is EMPTY, the boundary half
    # covers the whole block — the degenerate case must stay exact
    _run_pair_3d(Parameter(**_B3))


def test_overlap_matches_serial_3d_ragged():
    # restriction forced at the ragged bounds (see the 2-D twin)
    param = Parameter(**{**_B3, "imax": 9, "jmax": 9, "kmax": 9},
                      tpu_overlap_restrict="on")
    ser, _ = _run_pair_3d(param)
    assert ser.ragged
    assert dispatch.last("overlap_grid_ns3d_dist").startswith("restricted")


@pytest.mark.slow
def test_overlap_matches_serial_3d_obstacle():
    param = Parameter(**{**_B3, "imax": 16, "jmax": 16, "kmax": 16,
                         "obstacles": "0.3,0.3,0.3,0.7,0.7,0.7"})
    ser, _ = _run_pair_3d(param)
    assert ser.masks is not None


# ---------------------------------------------------------------------------
# program-shape contracts (trace-only, no chunk execution)
# ---------------------------------------------------------------------------

def test_overlap_off_is_bitwise_serial():
    """off == auto (off-TPU) == the historical serial program."""
    comm = CartComm(ndims=2, dims=(2, 2))
    jx_off = trace_chunk(
        NS2DDistSolver(Parameter(**_B2, tpu_overlap="off"), comm))
    jx_auto = trace_chunk(NS2DDistSolver(Parameter(**_B2), comm))
    assert dispatch.last("overlap_ns2d_dist") == "serial (no TPU)"
    assert str(jx_off) == str(jx_auto)


def test_overlap_launch_count_and_schedule():
    comm = CartComm(ndims=2, dims=(2, 2))
    ser = NS2DDistSolver(Parameter(**_B2), comm)
    jx_ser = trace_chunk(ser)
    o = NS2DDistSolver(Parameter(**_B2, tpu_overlap="on"), comm)
    jx_o = trace_chunk(o)
    # the split PRE adds exactly one launch (interior + boundary halves)
    assert count_prim(jx_o.jaxpr, "pallas_call") \
        == count_prim(jx_ser.jaxpr, "pallas_call") + 1
    # the overlapped chunk is double-buffered; the serial one is the
    # negative control (its PRE consumes the same-step exchange)
    assert commcheck.overlap_schedule_violations(jx_o, o._halo_record()) \
        == []
    errs = commcheck.overlap_schedule_violations(jx_ser,
                                                 ser._halo_record())
    assert any("SAME iteration" in e for e in errs)
    # per-step deep traffic unchanged: + one prologue per chunk
    rec_o, rec_s = o._halo_record(), ser._halo_record()
    assert rec_o["exchanges_per_step"] == rec_s["exchanges_per_step"]
    assert rec_o["exchanges_per_chunk"] == {"deep": 2}
    assert rec_o["path"] == "fused_overlap"


def test_overlap_jnp_path_refuses():
    """No fused kernels -> the serial schedule, with the reason
    recorded (the overlap rides the deep-halo step only)."""
    comm = CartComm(ndims=2, dims=(2, 2))
    NS2DDistSolver(
        Parameter(**{**_B2, "tpu_fuse_phases": "off",
                     "tpu_overlap": "on"}), comm)
    tag = dispatch.last("overlap_ns2d_dist")
    assert tag.startswith("serial (needs the fused deep-halo step")


def test_overlap_knob_validation():
    comm = CartComm(ndims=2, dims=(2, 2))
    with pytest.raises(ValueError, match="tpu_overlap"):
        NS2DDistSolver(Parameter(**_B2, tpu_overlap="sometimes"), comm)


def test_overlap_metrics_arity():
    """Telemetry-armed overlapped chunk keeps the in-band metrics
    contract: initial_state arity == chunk arity, sentinel ops on."""
    from pampi_tpu.utils import telemetry as tm

    import os

    os.environ["PAMPI_TELEMETRY"] = os.devnull
    try:
        tm.reset()
        comm = CartComm(ndims=2, dims=(2, 2))
        s = NS2DDistSolver(Parameter(**_B2, tpu_overlap="on"), comm)
        jx = trace_chunk(s)
        assert len(s.initial_state()) == len(jx.jaxpr.outvars) == 6
        assert "is_finite" in str(jx)
    finally:
        del os.environ["PAMPI_TELEMETRY"]
        tm.reset()


# ---------------------------------------------------------------------------
# stale-buffer detection (the generation-skew mutation)
# ---------------------------------------------------------------------------

def test_generation_skew_detected(monkeypatch):
    comm = CartComm(ndims=2, dims=(2, 2))
    monkeypatch.setattr(ovl, "GEN_SKEW", 1)
    s = NS2DDistSolver(Parameter(**_B2, tpu_overlap="on"), comm)
    out = s._chunk_sm(*s.initial_state())
    assert np.isnan(float(out[3])), \
        "a generation-skewed double buffer must poison t, not be consumed"
    monkeypatch.setattr(ovl, "GEN_SKEW", 0)
    s2 = NS2DDistSolver(Parameter(**_B2, tpu_overlap="on"), comm)
    out2 = s2._chunk_sm(*s2.initial_state())
    assert np.isfinite(float(out2[3]))


# ---------------------------------------------------------------------------
# halocheck: the interior half excludes the exchanged strips
# ---------------------------------------------------------------------------

def test_overlap_interior_footprint_clean():
    for entry in (halocheck.overlap_interior_entry_2d(),
                  halocheck.overlap_interior_entry_3d()):
        assert halocheck.check_entry(entry) == [], entry.name


@pytest.mark.parametrize("make", [halocheck.overlap_interior_entry_2d,
                                  halocheck.overlap_interior_entry_3d])
def test_overlap_interior_smuggled_read_fires(make):
    vs = halocheck.check_entry(make(smuggle=1))
    assert vs, "a read reaching the exchanged strips must be flagged"
    assert "ns2d_fused" in vs[0].path or "ns3d_fused" in vs[0].path
    assert vs[0].line > 0


# ---------------------------------------------------------------------------
# the persistent-exchange layer
# ---------------------------------------------------------------------------

def test_persistent_schedule_cached_and_identical():
    comm = CartComm(ndims=2, dims=(2, 2))
    s1 = persistent_exchange(comm, 4, jnp.float64)
    s2 = persistent_exchange(comm, 4, jnp.float64)
    assert s1 is s2, "schedules must be cached per (mesh, depth, dtype)"
    assert persistent_exchange(comm, 2, jnp.float64) is not s1
    # the schedule traces the IDENTICAL program to halo_exchange (the
    # wrapper name is part of the printed jaxpr, so both share one)
    spec = comm.spec()

    def traced(impl):
        def exchange(x):
            return impl(x)

        xx = jnp.zeros((2 * 16, 2 * 16))
        return jax.make_jaxpr(jax.jit(comm.shard_map(
            exchange, in_specs=(spec,), out_specs=spec)))(xx)

    jx_a = traced(s1)
    jx_b = traced(lambda x: halo_exchange(x, comm, depth=4))
    assert str(jx_a) == str(jx_b)
    # dtype contract: a schedule refuses a mismatched block
    with pytest.raises(TypeError, match="ExchangeSchedule"):
        s1(jnp.zeros((4, 4), jnp.float32))


def test_exchange_probe_cached():
    comm = CartComm(ndims=2, dims=(2, 2))
    rec = {"shard": [8, 8], "dtype": "float64", "deep_halo": 4,
           "exchanges_per_step": {"deep": 2}}
    fn_a, _ = make_exchange_probe(comm, rec)
    fn_b, _ = make_exchange_probe(comm, dict(rec))  # equal record, new dict
    assert fn_a is fn_b, "the jitted exchange probe must be cached per " \
                         "(mesh, record geometry, dtype)"
    fn_c, _ = make_exchange_probe(comm, {**rec, "deep_halo": 2})
    assert fn_c is not fn_a


def test_exchange_probe_not_served_across_tier_change():
    """The stale-probe bug class (ISSUE 13 satellite): a re-tiered mesh
    orders its exchange plan differently, so neither a cached schedule
    nor a cached probe may be served across a tier change."""
    rec = {"shard": [8, 8], "dtype": "float64", "deep_halo": 3,
           "exchanges_per_step": {"deep": 2}}
    flat = CartComm(ndims=2, dims=(2, 2))
    tiered = CartComm(ndims=2, dims=(2, 2), tiers="i=dcn")
    fn_a, _ = make_exchange_probe(flat, rec)
    fn_b, _ = make_exchange_probe(tiered, rec)
    assert fn_a is not fn_b
    assert persistent_exchange(flat, 3) is not persistent_exchange(
        tiered, 3)
    # the tiered plan posts the DCN axis first
    assert [x[1] for x in persistent_exchange(tiered, 3).plan] == ["i", "j"]
    assert [x[1] for x in persistent_exchange(flat, 3).plan] == ["j", "i"]


# ---------------------------------------------------------------------------
# grid-restricted halves (tpu_overlap_restrict)
# ---------------------------------------------------------------------------

def test_region_plan_bands():
    """The banded plan at a geometry where restriction wins: interior
    bands cover exactly the interior rows, the (P,1)-mesh boundary
    shrinks to two rim bands, and the summed cells beat 2x full."""
    from pampi_tpu.ops import ns2d_fused as nf

    jl = il = 40
    ext_pad = nf.FUSE_DEEP_HALO - 1
    br, _h, wp, nb = nf.fused_deep_layout_2d(jl, il, jnp.float32, ext_pad,
                                             block_rows=8)
    plan = ovl.region_plan((jl, il), nf.OVERLAP_RIM, ext_pad, br, nb, wp,
                           (True, True))
    assert plan["win"] and plan["cells"] < plan["cells_full"]
    # interior band covers the interior-merge rows
    lo = ext_pad + nf.OVERLAP_RIM
    hi = ext_pad + jl + 2 - nf.OVERLAP_RIM
    (s, n), = plan["int_bands"]
    assert s <= lo and s + n * br >= hi
    # column axis unpartitioned: the boundary half is two rim bands
    plan1 = ovl.region_plan((jl, il), nf.OVERLAP_RIM, ext_pad, br, nb,
                            wp, (True, False))
    assert len(plan1["bnd_bands"]) == 2
    assert plan1["cells"] < plan["cells"]
    # empty interior (tiny shard) -> no plan
    assert ovl.region_plan((4, 4), nf.OVERLAP_RIM, ext_pad, br, nb, wp,
                           (True, True)) is None


def test_region_plan_bands_stay_in_layout():
    """Merged bands never overhang the padded layout (regression: a thin
    leading shard whose two rim bands merge used to re-derive the block
    count by ceil without re-clamping the start — the band ran past
    nblocks*block_rows and the kernel build refused the grid). Every
    band of every half must sit inside [0, R) and be disjoint within
    its half, across a sweep of geometries including the repro."""
    from pampi_tpu.ops import ns2d_fused as nf

    ext_pad = nf.FUSE_DEEP_HALO - 1
    geoms = [((6, 40), 8, 2, 128)]  # the repro: rims merge on 2 blocks
    for jl in (3, 5, 6, 7, 9, 12, 40, 507, 510):
        br, _h, wp, nb = nf.fused_deep_layout_2d(jl, 64, jnp.float32,
                                                 ext_pad)
        geoms.append(((jl, 64), br, nb, wp))
    for (jl, il), br, nb, wp in geoms:
        R = nb * br
        for part in ((True, False), (True, True)):
            plan = ovl.region_plan((jl, il), nf.OVERLAP_RIM, ext_pad,
                                   br, nb, wp, part)
            if plan is None:
                continue
            for name in ("int_bands", "bnd_bands"):
                last = 0
                for s, n in plan[name]:
                    assert s >= 0 and s >= last and s + n * br <= R, (
                        (jl, il), part, name, plan[name], R)
                    last = s + n * br


def test_restricted_overlap_matches_serial_2d():
    """Forced grid restriction reproduces the serial trajectory (the
    16² shard degenerates to single-band grids — the wiring and merge
    coverage are what this pins; the banded-grid win is pinned by
    palcheck's restricted entries)."""
    param = Parameter(**_B2, tpu_overlap_restrict="on")
    ser = NS2DDistSolver(param.replace(tpu_overlap="off"),
                         CartComm(ndims=2, dims=(2, 2)))
    ser.run(progress=False)
    o = NS2DDistSolver(param.replace(tpu_overlap="on"),
                       CartComm(ndims=2, dims=(2, 2)))
    o.run(progress=False)
    assert dispatch.last("overlap_grid_ns2d_dist").startswith("restricted")
    rec = o._halo_record()
    assert rec["pre_grid_cells"] <= rec["pre_grid_cells_full"]
    assert o.nt == ser.nt and ser.nt > 1
    for n, (a, b) in zip("uvp", zip(ser.fields(), o.fields())):
        _assert_ulp_equal(a, b, n)


def test_restricted_grid_coverage_palcheck():
    """palcheck pins each restricted half's grid to its region: interior
    + boundary block counts strictly below the 2x full sweep."""
    from pampi_tpu.analysis import palcheck

    assert palcheck.restricted_grid_violations() == []
    entries = {name: expect for name, _jx, expect, _full
               in palcheck.restricted_grid_entries()}
    full = [e for n, e in entries.items() if "full" in n][0]
    halves = sum(e for n, e in entries.items() if "full" not in n)
    assert halves < 2 * full


def test_restriction_dropped_fires_halocheck():
    """The smuggled full-grid-half mutation: an interior region one rim
    layer too wide (the restriction dropped toward the strips) fails
    halocheck with the kernel's file:line."""
    from pampi_tpu.ops import ns2d_fused as nf

    vs = halocheck.check_entry(
        halocheck.overlap_interior_entry_2d(rim=nf.OVERLAP_RIM - 1))
    assert vs, "a rim-leaking interior region must be flagged"
    assert "ns2d_fused" in vs[0].path and vs[0].line > 0


def test_overlap_restrict_knob_validation():
    comm = CartComm(ndims=2, dims=(2, 2))
    with pytest.raises(ValueError, match="tpu_overlap_restrict"):
        NS2DDistSolver(Parameter(**_B2, tpu_overlap="on",
                                 tpu_overlap_restrict="maybe"), comm)


# ---------------------------------------------------------------------------
# split solve sweeps (ROADMAP item 3 layer 2)
# ---------------------------------------------------------------------------

_SPLIT = dict(_B2)
_SPLIT.pop("tpu_sor_layout")  # default layout -> the jnp CA solve


def test_sweep_split_matches_serial_and_proves():
    """Overlap with the jnp RB-SOR solve swaps to split sweeps: the
    trajectory equals the serial CA solve at the ulp contract, the
    traced chunk passes the sweep-loop schedule proof, and the SERIAL
    chunk is the negative control (its sweeps exchange at CA depth —
    nothing is split)."""
    param = Parameter(**_SPLIT, tpu_solver="sor")
    ser = NS2DDistSolver(param.replace(tpu_overlap="off"),
                         CartComm(ndims=2, dims=(2, 2)))
    ser.run(progress=False)
    o = NS2DDistSolver(param.replace(tpu_overlap="on"),
                       CartComm(ndims=2, dims=(2, 2)))
    o.run(progress=False)
    assert dispatch.last("sweep_split_ns2d_dist") == "split (jnp rb-sor)"
    assert o.nt == ser.nt and ser.nt > 1
    for n, (a, b) in zip("uvp", zip(ser.fields(), o.fields())):
        _assert_ulp_equal(a, b, n)
    assert commcheck.sweep_split_violations(
        trace_chunk(o), o._halo_record()) == []
    errs = commcheck.sweep_split_violations(
        trace_chunk(ser), ser._halo_record())
    assert errs, "a serial sweep loop must fail the split proof"
    # the combined mode stacks both proofs
    assert commcheck.overlap_schedule_violations(
        trace_chunk(o), o._halo_record(), sweeps=True) == []


def test_sweep_split_mg_smoother_matches_serial():
    """The dist MG smoother's jnp-fallback levels take the same split
    (make_dist_mg_solve_2d(split=True)) — trajectory unchanged."""
    param = Parameter(**{**_SPLIT, "eps": 1e-3}, tpu_solver="mg")
    ser = NS2DDistSolver(param.replace(tpu_overlap="off"),
                         CartComm(ndims=2, dims=(2, 2)))
    ser.run(progress=False)
    o = NS2DDistSolver(param.replace(tpu_overlap="on"),
                       CartComm(ndims=2, dims=(2, 2)))
    o.run(progress=False)
    assert dispatch.last("sweep_split_ns2d_dist") \
        == "split (mg jnp-smoother levels)"
    assert o.nt == ser.nt and ser.nt > 1
    for n, (a, b) in zip("uvp", zip(ser.fields(), o.fields())):
        _assert_ulp_equal(a, b, n)


# ---------------------------------------------------------------------------
# residual-adaptive itermax (tpu_itermax_adaptive)
# ---------------------------------------------------------------------------

def test_itermax_adaptive_slack_parity():
    """slack >= itermax caps nothing: the adaptive run is bitwise the
    static run (the budget formula can only return itermax); the
    decision lands as a dispatch record."""
    param = Parameter(**_SPLIT, tpu_solver="sor")
    a = NS2DDistSolver(param, CartComm(ndims=2, dims=(2, 2)))
    a.run(progress=False)
    b = NS2DDistSolver(param.replace(tpu_itermax_adaptive=10),
                       CartComm(ndims=2, dims=(2, 2)))
    b.run(progress=False)
    assert dispatch.last("itermax_adaptive_ns2d_dist") \
        == "adaptive (+10 slack)"
    assert a.nt == b.nt
    for n, (x, y) in zip("uvp", zip(a.fields(), b.fields())):
        assert np.array_equal(np.asarray(x), np.asarray(y)), n


def test_itermax_adaptive_declines_off_sor():
    param = Parameter(**_SPLIT, tpu_solver="fft",
                      tpu_itermax_adaptive=3)
    NS2DDistSolver(param, CartComm(ndims=2, dims=(2, 2)))
    assert dispatch.last("itermax_adaptive_ns2d_dist") \
        == "static (solve path carries no sweep budget)"
