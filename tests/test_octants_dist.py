"""Distributed octant-layout 3-D SOR (parallel/octants_dist + ops/sor_odist):
the 3-D companion of tests/test_quarters_dist.py."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pampi_tpu.models.ns3d import NS3DSolver
from pampi_tpu.models.ns3d_dist import NS3DDistSolver
from pampi_tpu.parallel import octants_dist as od
from pampi_tpu.parallel.comm import CartComm
from pampi_tpu.utils import dispatch
from pampi_tpu.utils.params import read_parameter

DC3 = "assignment-6/dcavity.par"


@pytest.mark.parametrize(
    "dims,kl,jl,il,offs",
    [
        # conservative all-halo geometry (dims unknown): any shard offsets
        (None, 8, 8, 8, ((0, 0, 0), (4, 0, 4), (0, 4, 0))),
        # size-1 mesh axes store no deep halo; their offsets are 0
        ((1, 2, 2), 16, 8, 8, ((0, 0, 0), (0, 4, 4), (0, 0, 4))),
        ((1, 1, 1), 16, 16, 16, ((0, 0, 0),)),
    ],
)
def test_twin_bitwise_matches_interpret_kernel(dims, kl, jl, il, offs):
    from pampi_tpu.models.ns3d import sor_coefficients_3d
    from pampi_tpu.ops.sor_odist import make_rb_iters_odist

    rng = np.random.default_rng(3)
    kmax = jmax = imax = 16
    g = od.make_ogeom(kmax, jmax, imax, kl, jl, il, 2, jnp.float64,
                      dims=dims)
    ext = jnp.asarray(rng.standard_normal((kl + 2, jl + 2, il + 2)))
    rhse = jnp.asarray(rng.standard_normal((kl + 2, jl + 2, il + 2)))
    xo = od.pack_ext_to_o(ext, g)
    ro = od.pack_ext_to_o(rhse, g)
    np.testing.assert_array_equal(
        np.asarray(od.unpack_o_to_ext(xo, g)), np.asarray(ext)
    )
    factor, idx2, idy2, idz2 = sor_coefficients_3d(
        1 / 16, 1 / 16, 1 / 16, 1.7
    )
    for off in offs:
        m = od.o_masks(g, *off)
        tx, tr = jax.jit(od.rb_iters_o_jnp, static_argnums=2)(
            xo, ro, g, m, factor, idx2, idy2, idz2
        )
        rb = make_rb_iters_odist(
            g, 1 / 16, 1 / 16, 1 / 16, 1.7, jnp.float64, interpret=True
        )
        kx, kr = rb(jnp.asarray(off, jnp.int32), xo, ro)
        band = slice(g.h, g.h + g.nblocks * g.bk)
        np.testing.assert_array_equal(
            np.asarray(tx[:, band]), np.asarray(kx[:, band])
        )
        np.testing.assert_allclose(float(tr), float(kr), rtol=1e-12)


@pytest.mark.parametrize("dims", [(2, 2, 2), (1, 2, 4), (2, 1, 1)])
def test_ns3d_dist_octants_vs_single(reference_dir, dims):
    """Forced-octants distributed NS-3D (interpret kernel on CPU) tracks the
    single-device checkerboard solver over several dcavity steps."""
    # first CFL dt at 16^3/Re=1000 is ~0.33, so te=0.5 yields several steps;
    # itermax capped (identically on both sides) for interpret-mode runtime
    param = read_parameter(str(reference_dir / DC3)).replace(
        te=0.5, imax=16, jmax=16, kmax=16, itermax=60,
        tpu_sor_layout="octants"
    )
    dist = NS3DDistSolver(param, CartComm(ndims=3, dims=dims))
    dist.run(progress=False)
    assert "octants" in dispatch.last("ns3d_dist")

    single = NS3DSolver(param.replace(tpu_sor_layout="checkerboard"))
    single.run(progress=False)
    assert dist.nt == single.nt > 1
    for a, b in zip(single.collect(), dist.collect()):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6, rtol=0
        )


def test_odist_clamp_and_eligibility(reference_dir):
    assert od.odist_clamp(8, 8, 8, 8) == 3
    assert od.odist_supported(16, 16, 16, 8, 4, 8)
    assert not od.odist_supported(15, 16, 16, 8, 4, 8)
    assert not od.odist_supported(16, 16, 16, 2, 4, 8)
    with pytest.raises(ValueError):
        # 12/4 = 3: odd per-shard k extent — forced octants must refuse
        NS3DDistSolver(
            read_parameter(
                str(reference_dir / "assignment-6" / "dcavity.par")
            ).replace(
                te=0.0, imax=12, jmax=12, kmax=12, tpu_sor_layout="octants"
            ),
            CartComm(ndims=3, dims=(4, 2, 1)),
        )


def test_obstacle3d_dist_pallas_bitwise_matches_jnp():
    """The 3-D per-shard flag-masked Pallas kernel (ops/sor_obsdist3d,
    interpret on CPU) is the same program as the jnp CA obstacle path —
    bitwise on the (2,2,2) mesh at matched CA depth (f64)."""
    from jax.sharding import PartitionSpec as P

    from pampi_tpu.ops import obstacle3d as o3
    from pampi_tpu.parallel.comm import CartComm, halo_exchange

    imax, jmax, kmax = 32, 16, 16
    dx, dy, dz = 8.0 / imax, 4.0 / jmax, 4.0 / kmax
    fluid = o3.build_fluid_3d(
        imax, jmax, kmax, dx, dy, dz, "3.0,1.5,1.5,5.0,2.5,2.5"
    )
    m = o3.make_masks_3d(fluid, dx, dy, dz, 1.7, jnp.float64)
    comm = CartComm(ndims=3, dims=(2, 2, 2))
    kl, jl, il = kmax // 2, jmax // 2, imax // 2
    rng = np.random.default_rng(1)
    p0 = jnp.asarray(rng.standard_normal((kmax + 2, jmax + 2, imax + 2)))
    rhs = jnp.asarray(rng.standard_normal((kmax + 2, jmax + 2, imax + 2)))

    outs = {}
    for backend in ("auto", "pallas"):  # auto on CPU = jnp CA
        solve, used_pallas = o3.make_dist_obstacle_solver_3d(
            comm, imax, jmax, kmax, kl, jl, il, dx, dy, dz, 1e-12, 40, m,
            jnp.float64, ca_n=2, sor_inner=2, backend=backend,
        )
        expect = "jnp_ca ca2" if backend == "auto" else "pallas ca2"
        assert dispatch.last("obstacle3d_dist") == expect
        assert used_pallas == (backend == "pallas")

        def kern(p_int, rhs_int, _solve=solve):
            pe = halo_exchange(jnp.pad(p_int, 1), comm)
            re = halo_exchange(jnp.pad(rhs_int, 1), comm)
            p, res, it = _solve(pe, re)
            return p[1:-1, 1:-1, 1:-1], res, it

        spec = P("k", "j", "i")
        f = jax.jit(comm.shard_map(
            kern, in_specs=(spec, spec), out_specs=(spec, P(), P()),
            check_vma=False,
        ))
        p_out, _res, it = f(p0[1:-1, 1:-1, 1:-1], rhs[1:-1, 1:-1, 1:-1])
        assert int(it) == 40
        outs[backend] = np.asarray(p_out)

    np.testing.assert_array_equal(outs["auto"], outs["pallas"])
