"""tools/check_artifact.py: the committed BENCH/MULTICHIP artifacts must
lint clean (tier-1 — a driver round that writes a malformed artifact, or a
refactor that renames a decomposition field, fails here), and the lint
must actually catch violations."""

import glob
import os

from tools import check_artifact as ca

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_committed_artifacts_lint_clean():
    files = sorted(
        glob.glob(os.path.join(REPO, "BENCH_r*.json"))
        + glob.glob(os.path.join(REPO, "MULTICHIP_r*.json"))
    )
    assert files, "no committed artifacts found"
    errors = [e for path in files for e in ca.lint_file(path)]
    assert errors == []


def test_lint_catches_missing_required():
    assert any("rc" in e for e in ca.lint_bench({"n": 1}))
    assert any("ok" in e for e in ca.lint_multichip({"n_devices": 8}))


# the tools/_artifact.py normalized schema every artifact now carries
_NORM = {"schema_version": 1, "metrics": []}


def test_lint_normalized_schema():
    """schema_version + the machine-readable metrics list are required
    (the bench_trend input must never degrade back to tail scraping);
    malformed entries and non-cpu/tpu backend tags are flagged."""
    base = {"n": 1, "cmd": "x", "rc": 0, "tail": "", **_NORM}
    assert ca.lint_bench(base) == []
    assert any("schema_version" in e for e in ca.lint_bench(
        {"n": 1, "cmd": "x", "rc": 0, "tail": "", "metrics": []}))
    assert any("metrics" in e for e in ca.lint_bench(
        {"n": 1, "cmd": "x", "rc": 0, "tail": "", "schema_version": 1}))
    bad = dict(base, metrics=[{"name": "m", "value": 1.0,
                               "unit": "x", "backend": "axon"}])
    assert any("cpu|tpu" in e for e in ca.lint_bench(bad))
    bad = dict(base, metrics=[{"name": "m"}])
    assert any("value" in e for e in ca.lint_bench(bad))


def test_lint_xprof_summary_block():
    base = {"n": 1, "cmd": "x", "rc": 0, "tail": "", **_NORM}
    good = dict(base, xprof_summary={
        "mode": "trace", "scopes": {}, "collectives": {},
        "exchange_device_ms": 1.0, "exchange_exposed_ms": 1.0})
    assert ca.lint_bench(good) == []
    wall = dict(base, xprof_summary={"mode": "wallclock", "wall_ms": 5.0})
    assert ca.lint_bench(wall) == []  # degraded mode carries less
    bad = dict(base, xprof_summary={"mode": "trace"})
    assert any("scopes" in e for e in ca.lint_bench(bad))


def test_lint_catches_gutted_decomposition():
    """An NS step line without the solve/non-solve decomposition keys is a
    schema violation — null VALUES are legal (off-TPU), missing KEYS are
    not."""
    good = {"n": 1, "cmd": "x", "rc": 0, "tail": "", **_NORM,
            "parsed_ns2d": {"metric": "ns2d_dcavity4096_ms_per_step",
                            "value": 1.0, "unit": "ms/step",
                            "solve_ms": None, "nonsolve_ms": None,
                            "phases": "jnp", "steps_timed": 8}}
    assert ca.lint_bench(good) == []
    bad = dict(good, parsed_ns2d={
        "metric": "ns2d_dcavity4096_ms_per_step", "value": 1.0,
        "unit": "ms/step"})
    assert any("solve_ms" in e for e in ca.lint_bench(bad))


def test_lint_catches_gutted_launch_census():
    """launches_per_step blocks must carry the K-fusion census keys
    (ISSUE 17) — a quotient with no dispatch record, raw count, or
    divisor cannot be audited; ns2d_small_ms_per_step rides the
    existing DECOMP_KEYS rule by its name shape."""
    good = {"n": 1, "cmd": "x", "rc": 0, "tail": "", **_NORM,
            "parsed_lps": {"metric": "launches_per_step", "value": 0.5,
                           "unit": "launches/step",
                           "chunk_fuse_dispatch": "scan (K=4)",
                           "pallas_calls": 2, "k": 4}}
    assert ca.lint_bench(good) == []
    bad = dict(good, parsed_lps={"metric": "launches_per_step",
                                 "value": 0.5, "unit": "launches/step"})
    assert any("chunk_fuse_dispatch" in e for e in ca.lint_bench(bad))
    small = dict(good, parsed_small={
        "metric": "ns2d_small_ms_per_step", "value": 0.4,
        "unit": "ms/step"})
    assert any("solve_ms" in e for e in ca.lint_bench(small))


def test_lint_telemetry_summary_block():
    base = {"n_devices": 8, "rc": 0, "ok": True, "skipped": False,
            "tail": "", **_NORM}
    good = dict(base, telemetry_summary={
        "schema_version": 1, "dispatch": {}, "records": 4,
        "chunks": {"count": 1, "steps": 8}})
    assert ca.lint_multichip(good) == []
    bad = dict(base, telemetry_summary={"records": 4})
    assert any("schema_version" in e for e in ca.lint_multichip(bad))


def test_lint_dispatch_snapshot_overlap_keys():
    """Once a dryrun snapshot records ANY overlap_* decision, BOTH dist
    families must carry an overlap/serial-tagged value; pre-overlap
    snapshots (and tails without one) pass unchanged."""
    ok_tail = ("OK ns2d-dist overlap mesh=(4, 2) [overlap (forced)]\n"
               "dispatch snapshot: {'overlap_ns2d_dist': 'overlap (forced)',"
               " 'overlap_ns3d_dist': 'serial (no TPU)'}\n")
    assert ca.lint_dispatch_snapshot(ok_tail, "M") == []
    # one family missing -> violation naming the key
    bad_tail = ("dispatch snapshot: {'overlap_ns2d_dist': "
                "'overlap (forced)'}\n")
    errs = ca.lint_dispatch_snapshot(bad_tail, "M")
    assert len(errs) == 1 and "overlap_ns3d_dist" in errs[0]
    # untagged value -> violation
    weird = ("dispatch snapshot: {'overlap_ns2d_dist': 'maybe', "
             "'overlap_ns3d_dist': 'overlap'}\n")
    errs = ca.lint_dispatch_snapshot(weird, "M")
    assert len(errs) == 1 and "overlap_ns2d_dist" in errs[0]
    # pre-overlap snapshot / no snapshot: pass
    assert ca.lint_dispatch_snapshot(
        "dispatch snapshot: {'ns2d_dist': 'jnp_ca'}\n", "M") == []
    assert ca.lint_dispatch_snapshot("no snapshot here\n", "M") == []
    # the committed r06 artifact carries both keys (the live subject)
    import json, os
    with open(os.path.join(ca.REPO, "MULTICHIP_r06.json")) as fh:
        d = json.load(fh)
    assert "overlap_ns2d_dist" in d["tail"] \
        and "overlap_ns3d_dist" in d["tail"]
    assert ca.lint_multichip(d, "MULTICHIP_r06") == []


def test_lint_autoscale_block():
    """The autopilot decision block (ISSUE 19): the decision tally, the
    ordered transition log and the final posture must all ride the
    block; a transition that cannot say what it decided is noise."""
    good = {"records": 25, "decisions": {"hold": 20, "grow": 1},
            "transitions": [{"decision": "grow", "poll": 7}],
            "final": {"rung": 0, "lanes": 3}}
    assert ca.lint_autoscale(good, "A") == []
    errs = ca.lint_autoscale({"records": 1}, "A")
    assert any("decisions" in e for e in errs) \
        and any("final" in e for e in errs)
    bad = dict(good, decisions={"grow": -1})
    assert any("non-negative" in e for e in ca.lint_autoscale(bad, "A"))
    bad = dict(good, transitions=[{"poll": 7}])
    assert any("missing decision" in e
               for e in ca.lint_autoscale(bad, "A"))
    bad = dict(good, final={"rung": 0})
    assert any("final" in e and "lanes" in e
               for e in ca.lint_autoscale(bad, "A"))


def test_lint_chaos_trajectory_block():
    """The chaos recovery trajectory: monotone poll axis, equal-length
    series, and a ladder that moves AT MOST one rung per sample — a
    ladder that jumps rungs is not a ladder."""
    good = {"poll": [1, 2, 3, 4], "rung": [0, 1, 2, 1],
            "lanes": [2, 2, 3, 3], "burn_max": [0.0, 5.0, 9.0, 2.0]}
    assert ca.lint_chaos_trajectory(good, "C") == []
    bad = dict(good, poll=[1, 3, 2, 4])
    assert any("monotone" in e
               for e in ca.lint_chaos_trajectory(bad, "C"))
    bad = dict(good, lanes=[2, 2, 3])
    assert any("length" in e
               for e in ca.lint_chaos_trajectory(bad, "C"))
    bad = dict(good, rung=[0, 2, 2, 1])
    assert any("more than one rung" in e
               for e in ca.lint_chaos_trajectory(bad, "C"))
    bad = dict(good, rung=[0, 1, 0, -1])
    assert any("negative rung" in e
               for e in ca.lint_chaos_trajectory(bad, "C"))
