"""Fused NS-3D step-phase kernels (ops/ns3d_fused.py) vs the jnp chain —
the 3-D twin of tests/test_ns2d_fused.py, same equivalence contract."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pampi_tpu.models.ns3d import NS3DSolver
from pampi_tpu.ops import ns3d as ops3
from pampi_tpu.ops import ns3d_fused as nf3
from pampi_tpu.utils import dispatch
from pampi_tpu.utils.params import Parameter


def _ulp_close(a, b, scale=None):
    a, b = np.asarray(a), np.asarray(b)
    tol = 1e-12 if a.dtype == np.float64 else 2e-5
    s = max(1.0, np.abs(b).max() if scale is None else scale)
    return np.abs(a - b).max() <= tol * s


@pytest.mark.parametrize("problem,bckw", [
    ("dcavity3d", {}),
    ("canal3d", dict(bcLeft=3, bcRight=3, bcFront=2, bcBack=2)),
])
@pytest.mark.parametrize("shape", [(16, 16, 16), (12, 20, 28)])
@pytest.mark.parametrize("block_k", [None, 4])
def test_phase_parity_3d(problem, bckw, shape, block_k):
    km, jm, im = shape
    param = Parameter(name=problem, imax=im, jmax=jm, kmax=km, re=100.0,
                      gamma=0.9, **bckw)
    dx, dy, dz = param.xlength / im, param.ylength / jm, param.zlength / km
    rng = np.random.default_rng(11)
    shp = (km + 2, jm + 2, im + 2)
    u = jnp.asarray(rng.normal(size=shp))
    v = jnp.asarray(rng.normal(size=shp))
    w = jnp.asarray(rng.normal(size=shp))
    p = jnp.asarray(rng.normal(size=shp))
    dt = jnp.asarray(0.011)
    bcs = {"top": param.bcTop, "bottom": param.bcBottom,
           "left": param.bcLeft, "right": param.bcRight,
           "front": param.bcFront, "back": param.bcBack}
    u1, v1, w1 = ops3.set_boundary_conditions_3d(u, v, w, bcs)
    if problem == "dcavity3d":
        u1 = ops3.set_special_bc_dcavity_3d(u1)
    else:
        u1 = ops3.set_special_bc_canal_3d(u1)
    f, g, h = ops3.compute_fgh(u1, v1, w1, dt, param.re, 0.0, 0.0, 0.0,
                               param.gamma, dx, dy, dz)
    rhs = ops3.compute_rhs(f, g, h, dt, dx, dy, dz)
    u2, v2, w2 = ops3.adapt_uvw(u1, v1, w1, f, g, h, p, dt, dx, dy, dz)

    pre, post, pad3, unpad3, _h = nf3.make_fused_step_3d(
        param, km, jm, im, dx, dy, dz, jnp.float64, interpret=True,
        block_k=block_k)
    offs = jnp.zeros((3,), jnp.int32)
    dt11 = jnp.full((1, 1), dt)
    up, vp, wp, fp, gp, hp, rp = pre(offs, dt11, pad3(u), pad3(v), pad3(w))
    assert jnp.array_equal(unpad3(up), u1)
    assert jnp.array_equal(unpad3(vp), v1)
    assert jnp.array_equal(unpad3(wp), w1)
    assert _ulp_close(unpad3(fp), f)
    assert _ulp_close(unpad3(gp), g)
    assert _ulp_close(unpad3(hp), h)
    assert _ulp_close(unpad3(rp), rhs, scale=float(jnp.abs(rhs).max()))
    up2, vp2, wp2, um, vm, wm = post(
        offs, dt11, up, vp, wp, fp, gp, hp, pad3(p))
    assert _ulp_close(unpad3(up2), u2)
    assert _ulp_close(unpad3(vp2), v2)
    assert _ulp_close(unpad3(wp2), w2)
    for got, ref in ((um, u2), (vm, v2), (wm, w2)):
        assert abs(float(got) - float(ops3.max_element(ref))) <= 1e-12


def _run_solver(fuse, run=True, **kw):
    base = dict(name="dcavity3d", imax=16, jmax=16, kmax=16, re=10.0,
                te=0.02, tau=0.5, itermax=40, eps=1e-4, omg=1.7, gamma=0.9)
    base.update(kw)
    s = NS3DSolver(Parameter(tpu_fuse_phases=fuse, **base))
    if run:
        s.run(progress=False)
    return s


@pytest.mark.parametrize("kw", [
    {},
    dict(name="canal3d", bcLeft=3, bcRight=3),
    dict(tpu_solver="fft"),
    dict(tau=-1.0, dt=0.004),
])
def test_solver_e2e_fused_matches_jnp_3d(kw):
    a, b = _run_solver("off", **kw), _run_solver("on", **kw)
    assert b._fused and not a._fused
    assert a.nt == b.nt
    for n in ("u", "v", "w", "p"):
        d = np.abs(np.asarray(getattr(a, n)) - np.asarray(getattr(b, n)))
        assert np.isfinite(d).all() and d.max() < 1e-9, n


def test_obstacle_phase_parity_3d():
    """The 3-D flag-masked mode (PR 2): obstacle velocity BC (priority-
    ordered tangential mirrors), F/G/H face masks and projection face
    masks vs the ops/obstacle3d.py jnp forms. Copies bitwise, compound
    terms at the ulp contract."""
    from pampi_tpu.ops import obstacle3d as obst3

    km, jm, im = 10, 12, 16
    param = Parameter(name="dcavity3d", imax=im, jmax=jm, kmax=km, re=50.0,
                      gamma=0.9, omg=1.7,
                      obstacles="0.3,0.3,0.3,0.7,0.7,0.7")
    dx, dy, dz = param.xlength / im, param.ylength / jm, param.zlength / km
    fluid = obst3.build_fluid_3d(im, jm, km, dx, dy, dz, param.obstacles)
    m = obst3.make_masks_3d(fluid, dx, dy, dz, param.omg, jnp.float64)
    assert m.any_obstacle
    rng = np.random.default_rng(11)
    shp = (km + 2, jm + 2, im + 2)
    u = jnp.asarray(rng.normal(size=shp))
    v = jnp.asarray(rng.normal(size=shp))
    w = jnp.asarray(rng.normal(size=shp))
    p = jnp.asarray(rng.normal(size=shp))
    dt = jnp.asarray(0.011)
    bcs = {"top": param.bcTop, "bottom": param.bcBottom,
           "left": param.bcLeft, "right": param.bcRight,
           "front": param.bcFront, "back": param.bcBack}
    u1, v1, w1 = ops3.set_boundary_conditions_3d(u, v, w, bcs)
    u1 = ops3.set_special_bc_dcavity_3d(u1)
    u1, v1, w1 = obst3.apply_obstacle_velocity_bc_3d(u1, v1, w1, m)
    f, g, h = ops3.compute_fgh(u1, v1, w1, dt, param.re, 0.0, 0.0, 0.0,
                               param.gamma, dx, dy, dz)
    f, g, h = obst3.mask_fgh(f, g, h, u1, v1, w1, m)
    rhs = ops3.compute_rhs(f, g, h, dt, dx, dy, dz)
    u2, v2, w2 = obst3.adapt_uvw_obstacle(u1, v1, w1, f, g, h, p, dt,
                                          dx, dy, dz, m)

    pre, post, pad3, unpad3, _h = nf3.make_fused_step_3d(
        param, km, jm, im, dx, dy, dz, jnp.float64, fluid=m.fluid,
        interpret=True, block_k=4)
    offs = jnp.zeros((3,), jnp.int32)
    dt11 = jnp.full((1, 1), dt)
    up, vp, wp, fp, gp, hp, rp = pre(offs, dt11, pad3(u), pad3(v), pad3(w))
    # BC + obstacle BC are flag multiplies of copies -> bitwise
    assert jnp.array_equal(unpad3(up), u1)
    assert jnp.array_equal(unpad3(vp), v1)
    assert jnp.array_equal(unpad3(wp), w1)
    assert _ulp_close(unpad3(fp), f)
    assert _ulp_close(unpad3(gp), g)
    assert _ulp_close(unpad3(hp), h)
    assert _ulp_close(unpad3(rp), rhs, scale=float(jnp.abs(rhs).max()))
    up2, vp2, wp2, um, vm, wm = post(
        offs, dt11, up, vp, wp, fp, gp, hp, pad3(p))
    assert _ulp_close(unpad3(up2), u2)
    assert _ulp_close(unpad3(vp2), v2)
    assert _ulp_close(unpad3(wp2), w2)
    for got, ref in ((um, u2), (vm, v2), (wm, w2)):
        assert abs(float(got) - float(ops3.max_element(ref))) <= 1e-12


def test_obstacle_3d_fused_e2e():
    """3-D obstacle flag fields fuse since PR 2 (in-kernel flag
    derivation): forced fused run matches the jnp chain e2e; auto off-TPU
    records the no-TPU decision, never a structural why_not."""
    kw = dict(obstacles="0.3,0.3,0.3,0.7,0.7,0.7", te=0.006,
              tpu_solver="sor", imax=16, jmax=16, kmax=12)
    a = _run_solver("off", **kw)
    b = _run_solver("on", **kw)
    assert b._fused and not a._fused
    assert a.nt == b.nt
    for n in ("u", "v", "w", "p"):
        d = np.abs(np.asarray(getattr(a, n)) - np.asarray(getattr(b, n)))
        assert np.isfinite(d).all() and d.max() < 1e-9, n
    # the auto decision is recorded at chunk build — no run needed
    s = _run_solver("auto", run=False, **kw)
    assert not s._fused
    assert dispatch.last("ns3d_phases") == "jnp (no TPU)"


def test_dist_fused_matches_single_3d():
    from pampi_tpu.models.ns3d_dist import NS3DDistSolver
    from pampi_tpu.parallel.comm import CartComm

    param = Parameter(name="dcavity3d", imax=16, jmax=16, kmax=16, re=10.0,
                      te=0.008, tau=0.5, itermax=40, eps=1e-4, omg=1.7,
                      gamma=0.9)
    single = NS3DSolver(param.replace(tpu_fuse_phases="off"))
    single.run(progress=False)
    sg = single.collect()
    for dims in [(2, 2, 2), (1, 2, 4)]:
        dist = NS3DDistSolver(param.replace(tpu_fuse_phases="on"),
                              CartComm(ndims=3, dims=dims))
        dist.run(progress=False)
        assert dispatch.last("ns3d_dist_phases") == "pallas_fused (forced)"
        dg = dist.collect()
        assert dist.nt == single.nt
        for n, (x, y) in zip("uvwp", zip(sg, dg)):
            d = np.abs(np.asarray(x) - np.asarray(y))
            assert np.isfinite(d).all() and d.max() < 1e-10, (dims, n)


# the recursive pallas-launch counter lives in the shared analysis
# layer (one home for every jaxpr pin — see tools/lint.py)
from pampi_tpu.analysis.jaxprcheck import count_prim as _count_prim


def test_dist_ragged_obstacle_fused_matches_single_3d():
    """The ragged + obstacle composition through the 3-D fused kernels
    (uneven block bounds, POST live-mask, call-time flag slices) vs the
    single-device jnp chain."""
    from pampi_tpu.models.ns3d_dist import NS3DDistSolver
    from pampi_tpu.parallel.comm import CartComm

    param = Parameter(name="dcavity3d", imax=17, jmax=16, kmax=12, re=10.0,
                      te=0.004, tau=0.5, itermax=40, eps=1e-4, omg=1.7,
                      gamma=0.9, obstacles="0.3,0.3,0.3,0.7,0.7,0.7")
    single = NS3DSolver(param.replace(tpu_fuse_phases="off"))
    single.run(progress=False)
    sg = single.collect()
    dist = NS3DDistSolver(param.replace(tpu_fuse_phases="on"),
                          CartComm(ndims=3, dims=(2, 2, 2)))
    assert dist.ragged and dist.masks is not None
    dist.run(progress=False)
    assert dispatch.last("ns3d_dist_phases") == "pallas_fused (forced)"
    dg = dist.collect()
    assert dist.nt == single.nt
    for n, (x, y) in zip("uvwp", zip(sg, dg)):
        d = np.abs(np.asarray(x) - np.asarray(y))
        assert np.isfinite(d).all() and d.max() < 1e-9, n


def test_launch_count_regression_3d():
    param = Parameter(name="dcavity3d", imax=16, jmax=16, kmax=16, re=10.0,
                      te=0.02, tau=0.5, itermax=20, eps=1e-3,
                      tpu_solver="fft")
    fused = NS3DSolver(param.replace(tpu_fuse_phases="on"))
    plain = NS3DSolver(param.replace(tpu_fuse_phases="off"))
    state = (plain.u, plain.v, plain.w, plain.p,
             jnp.asarray(0.0, jnp.float64), jnp.asarray(0, jnp.int32))
    jx_f = jax.make_jaxpr(fused._build_chunk())(*state)
    jx_p = jax.make_jaxpr(plain._build_chunk())(*state)
    assert _count_prim(jx_f.jaxpr, "pallas_call") == 2
    assert _count_prim(jx_p.jaxpr, "pallas_call") == 0


def test_launch_count_regression_obstacle_3d():
    """The fused 3-D obstacle chunk lowers to exactly TWO pallas kernels
    per step (the flag rides as a kernel input, not extra launches); the
    jnp eps-coefficient solve contributes none."""
    param = Parameter(name="dcavity3d", imax=16, jmax=16, kmax=12, re=10.0,
                      te=0.02, tau=0.5, itermax=20, eps=1e-3,
                      tpu_solver="sor",
                      obstacles="0.3,0.3,0.3,0.7,0.7,0.7")
    fused = NS3DSolver(param.replace(tpu_fuse_phases="on"))
    plain = NS3DSolver(param.replace(tpu_fuse_phases="off"))
    state = (plain.u, plain.v, plain.w, plain.p,
             jnp.asarray(0.0, jnp.float64), jnp.asarray(0, jnp.int32))
    jx_f = jax.make_jaxpr(fused._build_chunk())(*state)
    jx_p = jax.make_jaxpr(plain._build_chunk())(*state)
    assert _count_prim(jx_f.jaxpr, "pallas_call") == 2
    assert _count_prim(jx_p.jaxpr, "pallas_call") == 0
