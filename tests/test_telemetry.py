"""Flight-recorder telemetry (utils/telemetry.py).

Three contracts (ISSUE 3 acceptance):
- OFF-PATH ZERO COST: with PAMPI_TELEMETRY unset the solver chunk's jaxpr
  is the uninstrumented program — same output arity, same Pallas launch
  count as the PR-2 pinned values, no sentinel ops — and builds are
  deterministic (two off builds trace identically).
- JSONL ROUND-TRIP: a run with PAMPI_TELEMETRY set produces schema-
  versioned records that tools/telemetry_report.py loads, renders and
  summarizes, and whose summary block merges + lints cleanly.
- DIVERGENCE SENTINEL: an injected blow-up (huge fixed dt) surfaces a
  structured last-good-step diagnostic instead of silent NaN garbage.

Compile cost: every solver here is 16², itermax <= 20, a few steps —
the telemetry twin chunks are distinct traces by necessity, so the tests
keep each build tiny rather than sharing one (the marker-audit lever).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pampi_tpu.analysis.jaxprcheck import (
    assert_offpath_identity,
    count_prim as _count_prim,
)
from pampi_tpu.models.ns2d import NS2DSolver
from pampi_tpu.utils import telemetry as tm
from pampi_tpu.utils.params import Parameter


@pytest.fixture()
def tel_off(monkeypatch):
    monkeypatch.delenv("PAMPI_TELEMETRY", raising=False)
    tm.reset()


@pytest.fixture()
def tel_on(tmp_path, monkeypatch):
    path = tmp_path / "run.jsonl"
    monkeypatch.setenv("PAMPI_TELEMETRY", str(path))
    tm.reset()
    yield path
    tm.reset()


def _records(path):
    return [json.loads(ln) for ln in open(path) if ln.strip()]


_BASE = dict(name="dcavity", imax=16, jmax=16, re=10.0, te=0.02, tau=0.5,
             itermax=20, eps=1e-4, omg=1.7, gamma=0.9)


def test_offpath_jaxpr_identity(tel_off, tmp_path, monkeypatch):
    """PAMPI_TELEMETRY unset -> the chunk is the PRE-TELEMETRY program:
    5 outputs (u, v, p, t, nt), zero sentinel ops, deterministic trace;
    setting it changes ONLY the in-band additions (6th output, isfinite),
    never the Pallas launch count. The off-path pin itself lives in ONE
    place — analysis/jaxprcheck.assert_offpath_identity, shared with
    tests/test_faultinject.py and the `make lint` trace contracts."""
    param = Parameter(**_BASE)
    off1, jx_off1 = assert_offpath_identity(lambda: NS2DSolver(param))
    assert not off1._metrics
    n_pallas_off = _count_prim(jx_off1.jaxpr, "pallas_call")

    monkeypatch.setenv("PAMPI_TELEMETRY", str(tmp_path / "r.jsonl"))
    tm.reset()
    on = NS2DSolver(param)
    jx_on = jax.make_jaxpr(on._build_chunk())(*on.initial_state())
    assert on._metrics
    assert len(jx_on.jaxpr.outvars) == 6  # + the metrics vector
    assert "is_finite" in str(jx_on)
    assert _count_prim(jx_on.jaxpr, "pallas_call") == n_pallas_off


def test_offpath_fused_launch_count(tel_off, tmp_path, monkeypatch):
    """The fused-phase chunk keeps its PR-2 pinned launch count (2: pre +
    post, fft solve contributes none) with telemetry on AND off — the
    metrics ride the already-carried scalars, zero extra launches."""
    param = Parameter(tpu_fuse_phases="on", tpu_solver="fft",
                      **{**_BASE, "te": 0.05, "itermax": 40})
    off = NS2DSolver(param)
    jx_off = jax.make_jaxpr(off._build_chunk())(*off.initial_state())
    assert _count_prim(jx_off.jaxpr, "pallas_call") == 2
    assert len(jx_off.jaxpr.outvars) == 5
    assert "is_finite" not in str(jx_off)

    monkeypatch.setenv("PAMPI_TELEMETRY", str(tmp_path / "r.jsonl"))
    tm.reset()
    on = NS2DSolver(param)
    assert on._fused and on._metrics
    jx_on = jax.make_jaxpr(on._build_chunk())(*on.initial_state())
    assert _count_prim(jx_on.jaxpr, "pallas_call") == 2
    assert len(jx_on.jaxpr.outvars) == 6


def test_jsonl_schema_roundtrip(tel_on):
    """End-to-end: run -> JSONL -> report render + summary -> artifact
    merge -> schema lint."""
    s = NS2DSolver(Parameter(tpu_chunk=2, **_BASE))
    s.run(progress=False)
    tm.finalize()
    recs = _records(tel_on)
    kinds = {r["kind"] for r in recs}
    assert {"run", "dispatch", "build", "chunk", "finalize"} <= kinds
    for r in recs:  # schema: every record versioned and kind-tagged
        assert r["v"] == tm.SCHEMA_VERSION and "kind" in r and "ts" in r
    chunks = [r for r in recs if r["kind"] == "chunk"]
    assert len(chunks) >= 2  # tpu_chunk=2 forces multiple host syncs
    assert chunks[0]["includes_compile"] and not chunks[1]["includes_compile"]
    assert chunks[-1]["nt"] == s.nt
    assert sum(c["steps"] for c in chunks) == s.nt
    last = chunks[-1]
    assert np.isfinite(last["res"]) and last["dt"] > 0
    # umax is the carried max |u| incl. ghosts (ops/ns2d.max_element) of
    # the final state, at the f32 in-band precision
    assert np.isclose(last["umax"], float(np.abs(np.asarray(s.u)).max()),
                      rtol=1e-6)

    # report round-trip
    from tools import telemetry_report as tr

    loaded = tr.load(str(tel_on))
    assert len(loaded) == len(recs)
    text = tr.render(loaded)
    for needle in ("dispatch decisions", "builds", "chunks", "ns2d_phases"):
        assert needle in text
    summ = tr.summary(loaded)
    assert summ["chunks"]["steps"] == s.nt
    assert summ["dispatch"]["ns2d_phases"].startswith("jnp")
    assert summ["divergence"] is None

    # artifact merge + lint (the BENCH_rXX telemetry_summary block)
    from tools import check_artifact as ca
    from tools._artifact import write_merged

    art = str(tel_on.parent / "BENCH_test.json")
    with open(art, "w") as fh:
        json.dump({"n": 7, "cmd": "bench", "rc": 0, "tail": ""}, fh)
    merged = write_merged(art, {"telemetry_summary": summ})
    assert ca.lint_bench(merged) == []
    # a gutted summary block must be flagged
    assert ca.lint_bench({"n": 1, "cmd": "", "rc": 0, "tail": "",
                          "telemetry_summary": {"records": 1}}) != []


def test_divergence_sentinel(tel_on):
    """Injected blow-up (fixed dt=1.0 — wildly unstable on this config):
    the run still completes (semantics unchanged), but the flight record
    carries a structured divergence diagnostic naming the last-good step,
    and a warning surfaces it."""
    param = Parameter(**{**_BASE, "re": 1000.0, "te": 6.5, "tau": -1.0,
                         "dt": 1.0, "itermax": 10, "tpu_chunk": 4})
    s = NS2DSolver(param)
    with pytest.warns(UserWarning, match="non-finite.*last good step"):
        s.run(progress=False)
    # divergence records carry non-finite scalars BY DESIGN — the JSONL
    # must still be STRICT JSON (string-encoded "nan"/"inf", no Python
    # NaN tokens a jq/JS/merged-artifact consumer would choke on)
    def no_const(tok):
        raise AssertionError(f"non-strict JSON token {tok!r}")

    for ln in open(tel_on):
        json.loads(ln, parse_constant=no_const)
    recs = _records(tel_on)
    div = [r for r in recs if r["kind"] == "divergence"]
    assert len(div) == 1  # latched once, not per chunk
    d = div[0]
    assert d["family"] == "ns2d"
    assert d["first_bad_step"] >= 1
    assert d["last_good_step"] == d["first_bad_step"] - 1
    assert d["first_bad_step"] <= s.nt
    # the tripping scalar: string-encoded, float() restores non-finite
    assert not np.isfinite(float(d["res"]))
    # the report surfaces it
    from tools import telemetry_report as tr

    text = tr.render(recs)
    assert "DIVERGENCE" in text
    assert str(d["last_good_step"]) in text
    assert tr.summary(recs)["divergence"] is not None


def test_divergence_sentinel_dist(tel_on):
    """The dist chunk carries the same sentinel (replicated scalars)."""
    from pampi_tpu.models.ns2d_dist import NS2DDistSolver
    from pampi_tpu.parallel.comm import CartComm

    param = Parameter(**{**_BASE, "re": 1000.0, "te": 6.5, "tau": -1.0,
                         "dt": 1.0, "itermax": 10})
    s = NS2DDistSolver(param, CartComm(ndims=2, dims=(2, 2)))
    with pytest.warns(UserWarning, match="non-finite"):
        s.run(progress=False)
    div = [r for r in _records(tel_on) if r["kind"] == "divergence"]
    assert len(div) == 1 and div[0]["family"] == "ns2d_dist"
    assert div[0]["last_good_step"] == div[0]["first_bad_step"] - 1


def test_span_and_metric_records(tel_on):
    """The shared span protocol (the one decomposition record every perf
    tool emits) and the halo record helper."""
    with tm.span("unit.block", tool="test"):
        pass
    tm.emit_decomposition("unit.decomp", 10.0, 6.0, 4.0, phases="x")
    tm.emit_decomposition("unit.off_tpu", None, None, None)
    recs = _records(tel_on)
    spans = {r["name"]: r for r in recs if r["kind"] == "span"}
    assert "unit.block" in spans and spans["unit.block"]["ms"] >= 0
    assert spans["unit.decomp.step"]["ms"] == 10.0
    assert spans["unit.decomp.solve"]["ms"] == 6.0
    assert spans["unit.decomp.nonsolve"]["ms"] == 4.0
    assert "unit.off_tpu.step" in spans  # TPU-only fields: step span only
    assert "unit.off_tpu.solve" not in spans
    # static halo bytes: 2-D axis-by-axis full strips, both directions
    assert tm.halo_exchange_bytes((8, 16), 1, 4) == (2 * 18 + 2 * 10) * 4


def test_bad_path_degrades_not_crashes(monkeypatch):
    """An unwritable PAMPI_TELEMETRY path costs the flight record, never
    the run: one warning, then telemetry stands down and the solver runs
    to completion."""
    monkeypatch.setenv("PAMPI_TELEMETRY", "/no/such/dir/run.jsonl")
    tm.reset()
    with pytest.warns(UserWarning, match="telemetry disabled"):
        s = NS2DSolver(Parameter(**_BASE))  # first emit is dispatch.record
    s.run(progress=False)  # later emits are no-ops, the run completes
    assert s.nt > 0
    tm.reset()


def test_span_survives_raise(tel_on):
    """A raising block still leaves its span record (the crash-surviving
    contract — that block is the one worth reading)."""
    with pytest.raises(RuntimeError, match="boom"):
        with tm.span("unit.crash"):
            raise RuntimeError("boom")
    spans = [r for r in _records(tel_on) if r["kind"] == "span"]
    assert [s["name"] for s in spans] == ["unit.crash"]
    assert spans[0]["ms"] >= 0


def test_dist_halo_record(tel_on):
    """Dist solver construction emits the static per-shard halo-exchange
    byte counts for the dispatched path."""
    from pampi_tpu.models.ns2d_dist import NS2DDistSolver
    from pampi_tpu.parallel.comm import CartComm

    NS2DDistSolver(Parameter(**{**_BASE, "imax": 32, "jmax": 32}),
                   CartComm(ndims=2, dims=(4, 2)))
    halo = [r for r in _records(tel_on) if r["kind"] == "halo"]
    assert len(halo) == 1
    h = halo[0]
    assert h["shard"] == [8, 16] and h["mesh"] == [4, 2]
    isz = jnp.dtype(jnp.float64).itemsize  # x64 test default
    assert h["exchange_bytes_depth1"] == tm.halo_exchange_bytes(
        (8, 16), 1, isz)
    assert h["path"] in ("jnp", "fused")
    assert "exchanges_per_step" in h


def test_initial_state_arity(tel_off, tmp_path, monkeypatch):
    """initial_state tracks the built chunk's arity (the tools call the
    chunk with it — bench.py, tools/_artifact.dist_step_decomposition)."""
    s_off = NS2DSolver(Parameter(**_BASE))
    assert len(s_off.initial_state()) == 5
    monkeypatch.setenv("PAMPI_TELEMETRY", str(tmp_path / "r.jsonl"))
    tm.reset()
    s_on = NS2DSolver(Parameter(**_BASE))
    st = s_on.initial_state()
    assert len(st) == 6 and st[5].shape == (tm.METRICS_LEN,)
    out = s_on._chunk_fn(*st)
    assert len(out) == 6
    float(out[3])  # the loop-time fence every tool uses still holds
