"""Multi-process (multi-host) launch tests.

The reference exercises multi-node by oversubscribed `mpirun -n` locally
(SURVEY.md §4); the TPU-native equivalent is scripts/launch-multihost.sh
starting N python processes that join one jax.distributed process group
(Gloo collectives on CPU), with the device mesh spanning all processes.
These tests run the REAL cross-process path — separate OS processes,
cross-process ppermute/psum — not the in-process virtual mesh the rest of
the suite uses. They are gated on backend capability, not blanket-skipped:
`multihost.multiprocess_capable()` probes whether THIS jax build can run
cross-process collectives on the current backend (TPU/GPU yes; CPU only
with a gloo-enabled jaxlib), so on real hardware — where ROADMAP item 4
names this file the acceptance suite — the gate opens by itself.
"""

import pathlib
import subprocess

import numpy as np
import pytest

from pampi_tpu.parallel.multihost import multiprocess_capable

_capable, _reason = multiprocess_capable()
pytestmark = pytest.mark.skipif(not _capable, reason=_reason)

REPO = pathlib.Path(__file__).resolve().parent.parent
LAUNCHER = REPO / "scripts" / "launch-multihost.sh"

def _env(**extra):
    """Minimal clean environment: keep the interpreter reachable, drop any
    inherited sitecustomize/platform config that would defeat the cpu mesh."""
    import os, sys

    bindir = os.path.dirname(sys.executable)
    base = {"PATH": f"{bindir}:/usr/bin:/bin", "HOME": os.environ.get("HOME", "/tmp")}
    base.update(extra)
    return base


def _launch(par, tmp_path, n="2", devices="2", timeout=600):
    """Run the multi-process launcher on a .par file; returns the process."""
    proc = subprocess.run(
        [str(LAUNCHER), n, str(par)],
        cwd=tmp_path,
        env=_env(PAMPI_LOCAL_DEVICES=devices),
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    return proc


def _oracle(par, tmp_path):
    """Single-process single-device run of the same config in oracle_dir."""
    proc = subprocess.run(
        ["python", "-m", "pampi_tpu", str(par)],
        cwd=tmp_path / "oracle_dir",
        env=_env(JAX_PLATFORMS="cpu", PYTHONPATH=str(REPO)),
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    return proc


POISSON_PAR = """\
name       poisson
xlength    1.0
ylength    1.0
imax       32
jmax       32
itermax    100000
eps        0.00001
omg        1.9
tpu_mesh   auto
tpu_dtype  float64
"""


@pytest.mark.slow
def test_two_process_poisson_matches_single_process(tmp_path):
    """2 processes x 2 virtual CPU devices = one 4-device mesh across
    process boundaries. The distributed red-black trajectory is
    iteration-exact, so the converged p.dat must match a single-process
    single-device solve to float64 roundoff."""
    par = tmp_path / "poisson.par"
    par.write_text(POISSON_PAR)

    proc = _launch(par, tmp_path)
    # rank-0 log is echoed to stdout: "<iterations> ... Walltime X.XXs"
    assert "Walltime" in proc.stdout
    # non-master must not print (rank-0-only convention)
    r1 = (tmp_path / "multihost-r1.log").read_text()
    assert "Walltime" not in r1

    # single-process oracle on one device, same config
    oracle = _oracle(par, tmp_path)

    ours = np.loadtxt(tmp_path / "p.dat")
    ref = np.loadtxt(tmp_path / "oracle_dir" / "p.dat")
    assert ours.shape == ref.shape
    np.testing.assert_allclose(ours, ref, rtol=0, atol=1e-12)

    # same iteration count printed by both (first token of the result line)
    it_multi = proc.stdout.split("Walltime")[0].split()[-1]
    it_single = oracle.stdout.split("Walltime")[0].split()[-1]
    assert it_multi == it_single


DCAVITY_PAR = """\
name       dcavity
xlength    1.0
ylength    1.0
imax       16
jmax       16
re         10.0
te         0.05
dt         0.02
tau        0.5
itermax    200
eps        0.001
omg        1.7
gamma      0.9
tpu_mesh   auto
tpu_dtype  float64
tpu_checkpoint ckpt.npz
"""


@pytest.mark.slow
def test_two_process_ns2d_writes_outputs_and_checkpoint(tmp_path):
    """NS-2D under the multi-process runtime: the collective assemble path
    (_assemble -> CartComm.collect) and the checkpoint save must work when
    shards span processes, and only rank 0 may write files."""
    par = tmp_path / "dcavity.par"
    par.write_text(DCAVITY_PAR)

    proc = _launch(par, tmp_path)
    assert "Solution took" in proc.stdout
    for out in ("pressure.dat", "velocity.dat", "ckpt.npz"):
        assert (tmp_path / out).exists(), out
    # the checkpoint holds the full (jmax+2, imax+2) global fields
    z = np.load(tmp_path / "ckpt.npz")
    assert z["p"].ndim >= 2 and z["nt"] > 0

    # restart across processes: every rank re-reads the checkpoint and
    # re-places fields on the global sharding (the load-side device_put)
    par2 = tmp_path / "dcavity_restart.par"
    text2 = DCAVITY_PAR.replace("te         0.05", "te         0.08")
    assert "0.08" in text2  # guard the replace against format drift
    par2.write_text(text2 + "tpu_restart ckpt.npz\n")
    proc2 = _launch(par2, tmp_path)
    assert "Restarted from ckpt.npz" in proc2.stdout
    assert "Solution took" in proc2.stdout


NS3D_PAR = """\
name       dcavity3d
xlength    1.0
ylength    1.0
zlength    1.0
imax       8
jmax       8
kmax       8
re         10.0
te         0.05
dt         0.02
tau        0.5
itermax    50
eps        0.001
omg        1.7
gamma      0.9
tpu_mesh   auto
tpu_dtype  float64
tpu_vtk    sharded
"""


@pytest.mark.slow
def test_two_process_sharded_vtk_write(tmp_path):
    """The MPI-IO exercise, for real: 2 OS processes, each writing ONLY its
    own addressable shards' slabs at their byte offsets into one shared VTK
    file — no global gather. The result must be byte-identical to the
    single-process binary write."""
    par = tmp_path / "dcavity3d.par"
    par.write_text(NS3D_PAR)

    _launch(par, tmp_path)
    vtk = tmp_path / "dcavity.vtk"
    assert vtk.exists()

    _oracle(par, tmp_path)
    ref = tmp_path / "oracle_dir" / "dcavity.vtk"
    assert ref.exists()
    assert vtk.read_bytes() == ref.read_bytes()


@pytest.mark.slow
def test_two_process_ns3d_full_precision_parity(tmp_path):
    """Full NS-3D step sequence across REAL OS processes, compared at FULL
    f64 precision (the sharded-VTK test compares the f32 file bytes): the
    end-state checkpoint of a 2-process × 2-device run must be
    byte-identical to the single-process single-device oracle — fields,
    t, and nt. This is the cross-process surface of assignment-6's
    commExchange/commShift/commReduction (comm.c:184-244) exercised by a
    complete dcavity3d run."""
    par = tmp_path / "dc3.par"
    par.write_text(NS3D_PAR.replace("tpu_vtk    sharded",
                                    "tpu_checkpoint end.npz"))

    _launch(par, tmp_path)
    _oracle(par, tmp_path)

    # the dist checkpoint stores per-shard extended blocks + mesh dims (a
    # mesh-mismatched load is refused), so reload BOTH end states in this
    # process and compare the collected global fields bitwise
    from pampi_tpu.models.ns3d import NS3DSolver
    from pampi_tpu.models.ns3d_dist import NS3DDistSolver
    from pampi_tpu.parallel.comm import CartComm
    from pampi_tpu.utils import checkpoint as ckpt
    from pampi_tpu.utils.params import Parameter, read_parameter

    param = read_parameter(str(par), Parameter())
    dims = tuple(int(x) for x in np.load(tmp_path / "end.npz")["mesh"])
    dist = NS3DDistSolver(param, CartComm(ndims=3, dims=dims))
    ckpt.load_checkpoint(str(tmp_path / "end.npz"), dist)
    single = NS3DSolver(param)
    ckpt.load_checkpoint(str(tmp_path / "oracle_dir" / "end.npz"), single)
    assert dist.nt == single.nt and dist.nt > 0
    assert dist.t == single.t
    for a, b in zip(single.collect(), dist.collect()):
        np.testing.assert_array_equal(a, b)


def _mkdir_oracle(tmp_path):
    (tmp_path / "oracle_dir").mkdir(exist_ok=True)


@pytest.fixture(autouse=True)
def _dirs(tmp_path):
    _mkdir_oracle(tmp_path)


@pytest.mark.slow
def test_two_process_dmvm_ring(tmp_path):
    """DMVM CLI under the multi-process launcher: the ring spans both
    processes' devices (4-device ring across 2 OS processes — the 3a/3b
    multi-node run), rank 0 alone prints the result line and CSV row."""
    proc = subprocess.run(
        [str(LAUNCHER), "2", "512", "5"],
        cwd=tmp_path,
        env=_env(PAMPI_LOCAL_DEVICES="2", PAMPI_CSV="dmvm.csv"),
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # result line: "iter N MFlops walltime"
    line = [l for l in proc.stdout.splitlines()
            if l.startswith("5 512 ")]
    assert line, proc.stdout
    rows = (tmp_path / "dmvm.csv").read_text().strip().splitlines()
    assert len(rows) == 1  # rank-0 only, one row per RUN
    assert rows[0].startswith("4,5,512,")  # Ranks=4: the ring spans processes
    # non-master printed nothing
    assert "512" not in (tmp_path / "multihost-r1.log").read_text()


@pytest.mark.slow
def test_two_process_halo_test(tmp_path):
    """--halo-test under the multi-process launcher: the rank-id exchange
    runs over the cross-process mesh and rank 0 writes every dump file."""
    proc = subprocess.run(
        [str(LAUNCHER), "2", "--halo-test", "2"],
        cwd=tmp_path,
        env=_env(PAMPI_LOCAL_DEVICES="2"),
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "wrote 16 ghost-face dumps" in proc.stdout  # 4 ranks x 4 faces
    files = sorted(tmp_path.glob("halo-*-r*.txt"))
    assert len(files) == 16
    # neighbour's rank id must appear in the exchanged ghost face:
    # 2x2 mesh, rank 0 at (0,0); its top ghost row comes from rank 2 (j+1)
    top = np.loadtxt(tmp_path / "halo-top-r0.txt")
    assert (top[1:-1] == 2.0).all()


QUARTERS_PAR = """\
name       poisson
xlength    1.0
ylength    1.0
imax       32
jmax       32
itermax    120
eps        0.0000000001
omg        1.9
tpu_mesh   auto
tpu_dtype  float64
tpu_sor_layout quarters
tpu_ca_inner 2
tpu_sor_inner 2
"""


@pytest.mark.slow
def test_two_process_poisson_quarters_kernel(tmp_path):
    """The round-3 production path ACROSS OS PROCESSES: forced quarters
    dispatches the per-shard kernel (interpret on CPU) with the
    quarter-space deep exchange riding cross-process ppermutes. The
    converged field must match the single-process jnp oracle (checkerboard)
    to f64 roundoff."""
    par = tmp_path / "poisson.par"
    par.write_text(QUARTERS_PAR)
    proc = _launch(par, tmp_path)
    assert "Walltime" in proc.stdout

    oracle_par = tmp_path / "oracle.par"
    oracle_par.write_text(
        QUARTERS_PAR.replace("tpu_sor_layout quarters",
                             "tpu_sor_layout checkerboard")
        .replace("tpu_mesh   auto", "tpu_mesh   1")
    )
    _oracle(oracle_par, tmp_path)

    ours = np.loadtxt(tmp_path / "p.dat")
    ref = np.loadtxt(tmp_path / "oracle_dir" / "p.dat")
    np.testing.assert_allclose(ours, ref, rtol=0, atol=1e-11)


@pytest.mark.slow
def test_four_process_poisson_quarters_kernel(tmp_path):
    """Rank counts beyond 2 (VERDICT r4 item 8; the reference's harness ran
    8-288 ranks, assignment-3a bench-cluster.sh): a 2x2 mesh across FOUR
    OS processes — every interior shard edge crosses a process boundary in
    both axes — running the per-shard quarters kernel (interpret on CPU)
    with the quarter-space deep exchange riding cross-process ppermutes.
    Field must match the single-process jnp oracle to f64 roundoff."""
    par = tmp_path / "poisson.par"
    par.write_text(QUARTERS_PAR.replace("tpu_mesh   auto", "tpu_mesh   2x2"))
    proc = _launch(par, tmp_path, n="4", devices="1", timeout=900)
    assert "Walltime" in proc.stdout
    # ranks 1..3 exist and stay silent (rank-0-only printing)
    for r in (1, 2, 3):
        log = tmp_path / f"multihost-r{r}.log"
        assert log.exists(), log
        assert "Walltime" not in log.read_text()

    oracle_par = tmp_path / "oracle.par"
    oracle_par.write_text(
        QUARTERS_PAR.replace("tpu_sor_layout quarters",
                             "tpu_sor_layout checkerboard")
        .replace("tpu_mesh   auto", "tpu_mesh   1")
    )
    _oracle(oracle_par, tmp_path)

    ours = np.loadtxt(tmp_path / "p.dat")
    ref = np.loadtxt(tmp_path / "oracle_dir" / "p.dat")
    np.testing.assert_allclose(ours, ref, rtol=0, atol=1e-11)


# ---------------------------------------------------------------------------
# PR 10: coordinated fault handling + elastic checkpoints across REAL
# OS processes (ROADMAP item 4's acceptance cases — the virtual-rank
# lockstep twins live in tests/test_coordinator.py and prove the
# protocol logic on CPU; these prove the allgather transport and the
# cross-process checkpoint surfaces on capable backends).
# ---------------------------------------------------------------------------

COORD_PAR = DCAVITY_PAR.replace("tpu_checkpoint ckpt.npz", "")


@pytest.mark.slow
def test_two_process_transient_retried_by_coordinator(tmp_path):
    """The lifted transient_budget=0 ban, for real: a rank-1-local
    injected transient under a 2-process launch is agreed at the chunk
    boundary and retried GLOBALLY (the whole job completes, bitwise
    equal to the uninjected run) — where the PR 4 guard would have
    killed the job. The coord retry decision is a flight-recorder line
    on rank 0."""
    import json

    par = tmp_path / "dcavity.par"
    par.write_text(COORD_PAR)
    _launch(par, tmp_path)  # uninjected oracle, same launch shape
    (tmp_path / "oracle_p.dat").write_bytes(
        (tmp_path / "pressure.dat").read_bytes())

    proc = subprocess.run(
        [str(LAUNCHER), "2", str(par)],
        cwd=tmp_path,
        env=_env(PAMPI_LOCAL_DEVICES="2",
                 PAMPI_FAULTS="transient@chunk2@rank1",
                 PAMPI_TELEMETRY=str(tmp_path / "coord.jsonl")),
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "Solution took" in proc.stdout
    assert (tmp_path / "pressure.dat").read_bytes() == \
        (tmp_path / "oracle_p.dat").read_bytes()
    recs = [json.loads(ln) for ln in open(tmp_path / "coord.jsonl")
            if ln.strip()]
    armed = [r for r in recs if r["kind"] == "coord"
             and r.get("event") == "armed"]
    assert armed and armed[0]["mode"] == "multihost" \
        and armed[0]["nranks"] == 2
    retries = [r for r in recs if r["kind"] == "coord"
               and r.get("event") == "retry"]
    assert len(retries) == 1


@pytest.mark.slow
def test_two_process_kill_and_shrink_resume(tmp_path):
    """ISSUE 12 acceptance, for real: rank 1's OS PROCESS dies mid-run
    (injected death — the process exits at its 4th chunk dispatch, after
    at least one agreed elastic commit). The surviving rank must die
    LOUDLY within the timeout budget — via the boundary watchdog's
    structured RankDeadError when the death lands at the rendezvous, or
    via the backend collective failure when it lands mid-dispatch (the
    documented remaining window) — never hang; the elastic manifest +
    fault ledger survive; and the operator resume (the walkthrough the
    survivor prints: relaunch on the survivor count with tpu_restart)
    completes the run from the agreed generation."""
    import json

    par = tmp_path / "dcavity.par"
    par.write_text(COORD_PAR.replace(
        "tpu_dtype  float64",
        "tpu_dtype  float64\n"
        "tpu_checkpoint ck.elastic\n"
        "tpu_ckpt_elastic 1\n"
        "tpu_ckpt_every 2\n"
        "tpu_coord_timeout 20\n"))
    proc = subprocess.run(
        [str(LAUNCHER), "2", str(par)],
        cwd=tmp_path,
        env=_env(PAMPI_LOCAL_DEVICES="2",
                 PAMPI_FAULTS="dead@chunk4@rank1",
                 PAMPI_TELEMETRY=str(tmp_path / "dead.jsonl")),
        capture_output=True,
        text=True,
        timeout=600,  # the non-hang bound: a wedge fails HERE
    )
    assert proc.returncode != 0  # the injected death must not read clean
    r1 = tmp_path / "multihost-r1.log"
    logs = proc.stdout + proc.stderr + (
        r1.read_text() if r1.exists() else "")
    assert "injected dead" in logs  # rank 1 died the injected death
    if "DEAD rank(s)" in logs:
        # the watchdog path: the structured verdict is also a
        # flight-recorder `dead` line on the surviving rank
        recs = [json.loads(ln) for ln in open(tmp_path / "dead.jsonl")
                if ln.strip()]
        assert any(r["kind"] == "dead" for r in recs)

    manifest = tmp_path / "ck.elastic"
    assert manifest.exists()  # at least one agreed commit pre-death
    man = json.loads(manifest.read_text())
    assert "ledger" in man and man["nt"] > 0

    # the operator walkthrough: relaunch on the survivor count with
    # tpu_restart — the manifest reshards onto the shrunk (here:
    # single-process) capacity and the ledger restores protocol state
    par2 = tmp_path / "resume.par"
    par2.write_text(par.read_text() + "tpu_restart ck.elastic\n")
    proc2 = subprocess.run(
        ["python", "-m", "pampi_tpu", str(par2)],
        cwd=tmp_path,
        env=_env(JAX_PLATFORMS="cpu", PYTHONPATH=str(REPO)),
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert proc2.returncode == 0, proc2.stdout + proc2.stderr
    assert "Restarted from ck.elastic" in proc2.stdout
    assert "Solution took" in proc2.stdout
    for out in ("pressure.dat", "velocity.dat"):
        assert (tmp_path / out).exists(), out


@pytest.mark.slow
def test_two_process_elastic_checkpoint_restores_on_one_process(tmp_path):
    """Elastic shrink across the process boundary: a 2-process x
    2-device run writes the manifest + shard set; THIS single process
    then restores it onto one device and onto a different in-process
    mesh — the manifest's mesh is metadata, not a contract."""
    par = tmp_path / "dcavity.par"
    par.write_text(COORD_PAR.replace(
        "tpu_dtype  float64",
        "tpu_dtype  float64\ntpu_checkpoint ck.elastic\n"
        "tpu_ckpt_elastic 1"))
    _launch(par, tmp_path)
    manifest = tmp_path / "ck.elastic"
    assert manifest.exists()

    import json

    from pampi_tpu.models.ns2d import NS2DSolver
    from pampi_tpu.utils import checkpoint as ckpt
    from pampi_tpu.utils.params import Parameter, read_parameter

    man = json.loads(manifest.read_text())
    assert man["format"] == "pampi-elastic-ckpt" and man["nt"] > 0
    param = read_parameter(str(par), Parameter())
    single = NS2DSolver(param)
    ckpt.load_elastic(str(manifest), single)
    assert single.nt == man["nt"] and single.t == man["t"]
    assert np.isfinite(np.asarray(single.u)).all()
    # fsck agrees the set is healthy
    proc = subprocess.run(
        ["python", str(REPO / "tools" / "ckpt_fsck.py"), str(manifest)],
        capture_output=True, text=True, env=_env(PYTHONPATH=str(REPO)),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
