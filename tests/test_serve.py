"""Fleet serving v2 (ISSUE 14): shape-class batching, per-lane te /
continuous lane swap, fleet-over-mesh, and the persistent daemon.

Contracts pinned here:
- shape classes: power-of-two rung selection (floor, idempotency,
  waste bound — the palcheck contract), class-bucket routing, and the
  PADDED-LANE PARITY oracle: a lane padded into its class program
  (grid extents as per-lane traced data, dead cells masked from every
  reduction) equals its unpadded solo run at the repo's ulp contract,
  for dcavity AND canal BC families and across mixed grids in one
  batch;
- per-lane te: a batch of mixed end times rides ONE compiled program
  (te carried in the chunk state) and equals N solo runs bitwise on
  the jnp path — the PR 9 follow-on regression;
- continuous batching: lanes swapped in mid-flight (finished AND
  diverged slots) produce results bitwise-identical to solo runs, with
  zero retrace per (signature, lanes) — the compiled batch object and
  chunk function survive every swap and warm rerun;
- fleet-over-mesh: the scenario axis sharded across the (8-device
  test) mesh serves lanes bitwise-equal to solo, and the compiled
  program contains no resharding collectives (the commcheck ban at the
  HLO level);
- the daemon: file-queue intake, admission + per-tenant accounting,
  malformed .par PARKED with a structured warning (the hardened
  load_queue path), live status endpoint, serving telemetry (schema
  v9) through report/merge/lint — plus the ISSUE 18 observability
  plane: shape-class rung signatures, tenant SLO burn accounting
  (window edges, edge-triggered alerts), and the daemon's request
  traces / registry histograms / slo block end to end.
"""

import json

import jax
import numpy as np
import pytest

from pampi_tpu import fleet
from pampi_tpu.fleet import shapeclass as sc
from pampi_tpu.fleet.shapeclass import ClassSolver
from pampi_tpu.models.ns2d import NS2DSolver
from pampi_tpu.utils import telemetry as tm
from pampi_tpu.utils.params import Parameter

_B = dict(name="dcavity", imax=12, jmax=12, re=10.0, te=0.03, tau=0.5,
          itermax=8, eps=1e-4, omg=1.7, gamma=0.9, tpu_mesh="1",
          tpu_fuse_phases="off")

ULP_TOL = 1e-12  # the repo's ulp contract (tests/test_overlap.py)


def _assert_lane(got_fields, solo, bitwise=False):
    for name, got in zip("uvp", got_fields):
        ref = np.asarray(getattr(solo, name))
        if bitwise:
            assert np.array_equal(got, ref), name
        else:
            d = np.abs(got - ref)
            assert np.isfinite(d).all() and d.max() < ULP_TOL, \
                (name, d.max())


# -- shape-class selection ---------------------------------------------

def test_class_selection_units():
    assert sc.class_extent(8) == 16 and sc.class_extent(16) == 16
    assert sc.class_extent(17) == 32 and sc.class_extent(100) == 128
    assert sc.class_grid((20, 48)) == (32, 64)  # rungs differ per axis
    # idempotent: a padded grid re-bucketed lands in the same compile
    for n in (8, 12, 16, 17, 64, 100):
        c = sc.class_extent(n)
        assert sc.class_extent(c) == c
    # the waste bound at a geometry where the rungs differ
    assert sc.padding_waste((20, 48)) < sc.WASTE_BOUND
    assert sc.padding_waste((17, 17)) < sc.WASTE_BOUND
    assert sc.padding_waste((16, 16)) < sc.WASTE_BOUND


def test_class_eligibility_reasons():
    p = Parameter(**_B)
    assert sc.class_eligible(p) is None
    assert "obstacle" in sc.class_eligible(
        p.replace(obstacles="0.3,0.3,0.6,0.6"))
    assert "tpu_solver" in sc.class_eligible(p.replace(tpu_solver="fft"))
    assert "tpu_sor_layout" in sc.class_eligible(
        p.replace(tpu_sor_layout="quarters"))
    assert "floor" in sc.class_eligible(p.replace(imax=4))
    assert "forced" in sc.class_eligible(p.replace(tpu_fleet="solo"))
    # 3-D families are ELIGIBLE since serving v3 (their own rungs); the
    # floor checks kmax too, and dist lanes still keep their exact bucket
    p3 = Parameter(name="dcavity3d", imax=8, jmax=8, kmax=8,
                   tpu_mesh="1", seen_keys=("kmax",))
    assert sc.class_eligible(p3) is None
    assert "floor" in sc.class_eligible(p3.replace(kmax=4))
    assert "distributed" in sc.class_eligible(p3.replace(tpu_mesh="auto"))


def test_lane_state_refuses_oversized_grid():
    # the swap-lane path feeds requests straight into lane_state: an
    # eligible grid that exceeds the class rungs must refuse loudly
    # (the __init__ guard, per lane) instead of silently saturating the
    # live mask and cropping a wrong-shaped result
    p = Parameter(**_B)
    tpl = ClassSolver(p, ic=16, jc=16)
    with pytest.raises(ValueError, match="exceeds class"):
        tpl.lane_state(p.replace(imax=20, jmax=20))


def test_class_bucket_routing():
    p = Parameter(**_B)
    reqs = [
        fleet.ScenarioRequest("a", p),
        fleet.ScenarioRequest("b", p.replace(imax=14, jmax=10)),
        fleet.ScenarioRequest("w", p.replace(imax=20, jmax=20)),
        fleet.ScenarioRequest("x", p.replace(imax=4)),  # below floor
    ]
    exact = fleet.bucket(reqs, classes=False)
    assert len(exact) == 4  # the PR 9 routing, untouched
    classed = fleet.bucket(reqs, classes=True)
    labels = {k.label: [r.sid for r in v] for k, v in classed.items()}
    assert len(classed) == 3, labels  # 16-class, 32-class, exact 4x12
    assert ["a", "b"] in list(labels.values())
    cls_keys = [k for k in classed if k.sig.startswith("cls")]
    assert {k.grid for k in cls_keys} == {(16, 16), (32, 32)}


def test_palcheck_shapeclass_contract(monkeypatch):
    from pampi_tpu.analysis import palcheck

    assert palcheck.shapeclass_violations() == []
    # mutation: a non-idempotent rung ladder must be flagged
    real = sc.class_extent
    monkeypatch.setattr(sc, "class_extent",
                        lambda n: real(n) + (0 if n % 2 else 1))
    vs = palcheck.shapeclass_violations()
    assert vs and any(v.rule == "shapeclass-waste" for v in vs)


# -- padded-lane parity -------------------------------------------------

def test_padded_class_lanes_match_solo_mixed_grids():
    p = Parameter(**_B)
    p2 = p.replace(imax=14, jmax=10, u_init=0.02)
    tpl = ClassSolver(p, ic=16, jc=16)
    batched = fleet.BatchedSolver(tpl, [p, p2], ["a", "b"],
                                  family="ns2d_class")
    results = batched.results(batched.run())
    for lane_param, res in zip((p, p2), results):
        solo = NS2DSolver(lane_param)
        solo.run(progress=False)
        assert not res["diverged"]
        assert res["nt"] == solo.nt and solo.nt > 0
        assert res["fields"][0].shape == (lane_param.jmax + 2,
                                          lane_param.imax + 2)
        _assert_lane(res["fields"], solo)


def test_padded_class_lane_canal_bcs():
    p = Parameter(**{**_B, "name": "canal", "bcLeft": 3, "bcRight": 3,
                     "imax": 14, "jmax": 9})
    tpl = ClassSolver(p, ic=16, jc=16)
    batched = fleet.BatchedSolver(tpl, [p], ["k"], family="ns2d_class")
    res = batched.results(batched.run())[0]
    solo = NS2DSolver(p)
    solo.run(progress=False)
    assert res["nt"] == solo.nt > 0
    _assert_lane(res["fields"], solo)


# -- the fused class chunk (ISSUE 15): production kernels per lane ------

_BF = dict(_B, tpu_fuse_phases="on", tpu_solver="sor",
           tpu_sor_layout="checkerboard")
_B3 = dict(name="dcavity3d", imax=8, jmax=8, kmax=8, re=10.0, te=0.02,
           tau=0.5, itermax=8, eps=1e-4, omg=1.7, gamma=0.9,
           tpu_mesh="1", seen_keys=("kmax",))


def test_class_3d_selection_and_routing():
    p3 = Parameter(**_B3)
    assert sc.class_grid((8, 10, 9)) == (16, 16, 16)
    reqs = [
        fleet.ScenarioRequest("a", p3),
        fleet.ScenarioRequest("b", p3.replace(imax=10, jmax=9)),
        fleet.ScenarioRequest("c", Parameter(**_B)),  # 2-D rides its own
    ]
    classed = fleet.bucket(reqs, classes=True)
    assert len(classed) == 2  # one 3-D 16³ class + one 2-D 16² class
    k3 = next(k for k in classed if k.family == "ns3d")
    assert k3.grid == (16, 16, 16) and k3.sig.startswith("cls")
    assert [r.sid for r in classed[k3]] == ["a", "b"]


def test_class_eligibility_recorded_per_request():
    from pampi_tpu.utils import dispatch

    p = Parameter(**_B)
    ineligible = p.replace(tpu_solver="fft")
    reqs = [fleet.ScenarioRequest("good", p),
            fleet.ScenarioRequest("bad", ineligible)]
    buckets = fleet.bucket(reqs, classes=True)
    assert len(buckets) == 2
    exact = next(k for k in buckets if not k.sig.startswith("cls"))
    cls = next(k for k in buckets if k.sig.startswith("cls"))
    # the refusal reason rides the dispatch snapshot under the exact
    # bucket the request silently landed on (the tpu_overlap convention)
    assert "fft" in dispatch.last(f"class_{exact.label}")
    assert dispatch.last(f"class_{cls.label}").startswith("class (padded")


def test_fused_class_chunk_launch_count():
    # the launch-count pin: the fused class chunk stays at PRE + solve +
    # POST per step (2-D; the 3-D chunk is PRE + POST around the jnp
    # class solve) — trace-only, the jaxprcheck matrix twin
    from pampi_tpu.analysis.jaxprcheck import count_prim, trace_chunk
    from pampi_tpu.fleet.shapeclass import Class3DSolver

    p = Parameter(**_BF)
    tpl = ClassSolver(p, ic=16, jc=16)
    assert tpl._fused and tpl._uses_pallas()
    b = fleet.BatchedSolver(tpl, [p], ["a"], family="ns2d_class")
    assert count_prim(trace_chunk(b).jaxpr, "pallas_call") == 3
    p3 = Parameter(**_B3, tpu_fuse_phases="on")
    tpl3 = Class3DSolver(p3, ic=16, jc=16, kc=16)
    assert tpl3._fused
    b3 = fleet.BatchedSolver(tpl3, [p3], ["a"], family="ns3d_class")
    assert count_prim(trace_chunk(b3).jaxpr, "pallas_call") == 2


def test_padded_class_solve_matches_jnp_class_solve():
    # the padded-class Pallas solve == the jnp class solve on the masked
    # (live) cells — same per-cell update arithmetic, extent-gated
    import jax
    import jax.numpy as jnp

    from pampi_tpu.fleet.shapeclass import (
        _index_grids,
        lane_geometry,
        make_class_solve,
        make_padded_class_solve,
    )
    from pampi_tpu.ops.sor_pallas import pad_array, unpad_array

    p = Parameter(**{**_B, "tpu_sor_inner": 1, "itermax": 6,
                     "eps": 1e-30})  # itermax-capped: both run 6 iters
    jc = ic = 16
    grids = _index_grids(jc, ic)
    jnp_solve = make_class_solve(p, jc, ic, jnp.float64, grids)
    pal_solve, br, h = make_padded_class_solve(p, jc, ic, jnp.float64)
    rng = np.random.default_rng(7)
    for jmax, imax in ((12, 12), (10, 14)):
        gm = lane_geometry(p.replace(imax=imax, jmax=jmax))
        live = ((np.arange(jc + 2)[:, None] <= jmax + 1)
                & (np.arange(ic + 2)[None, :] <= imax + 1))
        p0 = jnp.asarray(np.where(live, rng.normal(size=(jc + 2, ic + 2)),
                                  0.0))
        rhs = jnp.asarray(np.where(live,
                                   rng.normal(size=(jc + 2, ic + 2)),
                                   0.0))
        args = [jnp.asarray(v) for v in gm]
        pj, resj, itj = jax.jit(jnp_solve)(
            p0, rhs, args[0], args[1], args[5], args[6], args[7],
            args[8])
        ext = jnp.asarray([[jmax, imax]], jnp.int32)
        sgeo = jnp.asarray([[gm[5], gm[6], gm[7]]])
        pp, resp, itp = jax.jit(pal_solve)(
            pad_array(p0, br, h), pad_array(rhs, br, h), ext, sgeo,
            jnp.asarray(gm[8]))
        pp = unpad_array(pp, jc, ic, h)
        assert int(itj) == int(itp) == 6
        mask = np.asarray(live)
        assert np.array_equal(np.asarray(pj)[mask], np.asarray(pp)[mask])
        assert abs(float(resj) - float(resp)) <= 1e-12 * max(
            1.0, abs(float(resj)))


@pytest.mark.slow
def test_fused_class_lanes_match_fused_solo_mixed_grids():
    # ISSUE 15 acceptance: a padded lane on the PRODUCTION kernels
    # (fused PRE + padded-class solve + POST) matches its exact-shape
    # FUSED solo at the ulp contract — mixed grids in one batch
    p = Parameter(**_BF)
    p2 = p.replace(imax=14, jmax=10, u_init=0.02)
    tpl = ClassSolver(p, ic=16, jc=16)
    assert tpl._fused
    batched = fleet.BatchedSolver(tpl, [p, p2], ["a", "b"],
                                  family="ns2d_class")
    results = batched.results(batched.run())
    for lane_param, res in zip((p, p2), results):
        solo = NS2DSolver(lane_param)
        assert solo._fused  # the oracle is the fused solo, same kernels
        solo.run(progress=False)
        assert not res["diverged"]
        assert res["nt"] == solo.nt and solo.nt > 0
        _assert_lane(res["fields"], solo)
    # the canal BC family rides the same fused class program (the
    # inflow profile's dy is per-lane SMEM data in the PRE kernel)
    pc = Parameter(**{**_BF, "name": "canal", "bcLeft": 3, "bcRight": 3,
                      "imax": 14, "jmax": 9})
    tplc = ClassSolver(pc, ic=16, jc=16)
    assert tplc._fused
    bc = fleet.BatchedSolver(tplc, [pc], ["k"], family="ns2d_class")
    res = bc.results(bc.run())[0]
    soloc = NS2DSolver(pc)
    soloc.run(progress=False)
    assert res["nt"] == soloc.nt > 0
    _assert_lane(res["fields"], soloc)


@pytest.mark.slow
def test_fused_class_lane_3d_matches_fused_solo():
    from pampi_tpu.fleet.shapeclass import Class3DSolver
    from pampi_tpu.models.ns3d import NS3DSolver

    p3 = Parameter(**_B3, tpu_fuse_phases="on")
    p3b = p3.replace(imax=10, jmax=9, u_init=0.01)
    tpl = Class3DSolver(p3, ic=16, jc=16, kc=16)
    assert tpl._fused
    batched = fleet.BatchedSolver(tpl, [p3, p3b], ["a", "b"],
                                  family="ns3d_class")
    results = batched.results(batched.run())
    for lane_param, res in zip((p3, p3b), results):
        solo = NS3DSolver(lane_param)
        assert solo._fused
        solo.run(progress=False)
        assert res["nt"] == solo.nt > 0
        assert res["fields"][0].shape == (lane_param.kmax + 2,
                                          lane_param.jmax + 2,
                                          lane_param.imax + 2)
        for name, got in zip("uvwp", res["fields"]):
            ref = np.asarray(getattr(solo, name))
            d = np.abs(got - ref)
            assert np.isfinite(d).all() and d.max() < ULP_TOL, \
                (name, d.max())


def test_class_3d_jnp_lanes_match_solo():
    # the 3-D jnp class chain (the parity oracle) vs jnp solos
    from pampi_tpu.fleet.shapeclass import Class3DSolver
    from pampi_tpu.models.ns3d import NS3DSolver

    p3 = Parameter(**_B3)
    p3b = p3.replace(imax=10, jmax=9, u_init=0.01)
    tpl = Class3DSolver(p3, ic=16, jc=16, kc=16)
    assert not tpl._fused
    batched = fleet.BatchedSolver(tpl, [p3, p3b], ["a", "b"],
                                  family="ns3d_class")
    results = batched.results(batched.run())
    for lane_param, res in zip((p3, p3b), results):
        solo = NS3DSolver(lane_param)
        solo.run(progress=False)
        assert res["nt"] == solo.nt > 0
        for name, got in zip("uvwp", res["fields"]):
            ref = np.asarray(getattr(solo, name))
            d = np.abs(got - ref)
            assert np.isfinite(d).all() and d.max() < ULP_TOL, \
                (name, d.max())


# -- per-lane te (the PR 9 follow-on regression) ------------------------

def test_mixed_te_batch_matches_n_solo_bitwise():
    p = Parameter(**_B)
    tpl = NS2DSolver(p)
    params = [p.replace(te=0.02), p.replace(te=0.05, u_init=0.03),
              p.replace(te=0.08)]
    batched = fleet.BatchedSolver(tpl, params, ["a", "b", "c"])
    assert batched._te_carry  # mixed te auto-arms the carry
    results = batched.results(batched.run())
    nts = [r["nt"] for r in results]
    assert len(set(nts)) == 3  # each lane stopped at ITS OWN te
    for lane_param, res in zip(params, results):
        solo = NS2DSolver(lane_param)
        solo.run(progress=False)
        assert res["nt"] == solo.nt > 0
        assert abs(res["t"] - solo.t) == 0.0
        _assert_lane(res["fields"], solo, bitwise=True)


def test_te_left_the_bucket_signature():
    p = Parameter(**_B)
    assert fleet.signature_hash(p.replace(te=0.5)) \
        == fleet.signature_hash(p)
    buckets = fleet.bucket([
        fleet.ScenarioRequest("a", p),
        fleet.ScenarioRequest("b", p.replace(te=0.06)),
    ])
    assert len(buckets) == 1  # one compile serves both end times


# -- continuous batching ------------------------------------------------

def test_continuous_swap_parity_and_zero_retrace():
    from pampi_tpu.fleet import scheduler as sch

    fleet.reset_templates()
    p = Parameter(**_B)
    sched = fleet.FleetScheduler(lanes=2)
    params = [p.replace(u_init=0.01 * i) for i in range(4)]
    for i, lp in enumerate(params):
        sched.submit_param(f"s{i}", lp)
    res = sched.run()
    row = res.summary["buckets"][0]
    assert row["lanes"] == 4 and row["swaps"] == 2
    # every scenario — swapped-in lanes included — equals its solo twin
    # bitwise (the template is the oracle driver, zero extra compiles)
    tpl = sch._TEMPLATES[next(iter(sch._TEMPLATES))][0]
    for i, lp in enumerate(params):
        sch._reset_lane(tpl, lp)
        tpl.run(progress=False)
        r = res.by_sid(f"s{i}")
        assert r.nt == tpl.nt > 0
        _assert_lane(r.fields, tpl, bitwise=True)
    # zero retrace per (signature, lanes): the warm rerun REBINDS the
    # same compiled batch object — no jit, no compile wall
    batch_obj = next(iter(sch._BATCHES.values()))
    chunk_obj = batch_obj._chunk_fn
    for i in range(4, 7):
        sched.submit_param(f"s{i}", p.replace(u_init=0.01 * i))
    res2 = sched.run()
    assert res2.summary["buckets"][0]["compile_wall_s"] == 0.0
    assert next(iter(sch._BATCHES.values())) is batch_obj
    assert batch_obj._chunk_fn is chunk_obj
    assert res2.by_sid("s5").nt == res.by_sid("s1").nt


def test_cached_template_serves_new_te():
    # te is signature-excluded: a later run with a DIFFERENT uniform te
    # hits the same cached template — the batch must auto-arm the te
    # carry instead of serving the template's stale baked end time
    fleet.reset_templates()
    p = Parameter(**{**_B, "te": 0.02})
    sched = fleet.FleetScheduler()
    sched.submit_param("a", p)
    sched.submit_param("b", p.replace(u_init=0.01))
    sched.run()
    sched.submit_param("c", p.replace(te=0.06))
    sched.submit_param("d", p.replace(te=0.06, u_init=0.01))
    res = sched.run()
    solo = NS2DSolver(p.replace(te=0.06))
    solo.run(progress=False)
    assert res.by_sid("c").nt == solo.nt > 0
    _assert_lane(res.by_sid("c").fields, solo, bitwise=True)


def test_continuous_swap_reuses_diverged_slot():
    fleet.reset_templates()
    p = Parameter(**_B)
    sched = fleet.FleetScheduler(lanes=2)
    sched.submit_param("bad", p.replace(u_init=float("nan")))
    sched.submit_param("ok1", p)
    sched.submit_param("ok2", p.replace(u_init=0.02))
    res = sched.run()
    assert res.by_sid("bad").diverged
    assert res.summary["divergence_census"]["scenarios"] == ["bad"]
    solo = NS2DSolver(p)
    solo.run(progress=False)
    assert not res.by_sid("ok1").diverged
    _assert_lane(res.by_sid("ok1").fields, solo, bitwise=True)
    assert not res.by_sid("ok2").diverged  # rode the freed slot


# -- fleet-over-mesh ----------------------------------------------------

@pytest.mark.skipif(len(jax.devices()) < 2,
                    reason="fleet-over-mesh needs a multi-device host")
def test_mesh_mode_parity_and_no_resharding():
    fleet.reset_templates()
    n_dev = len(jax.devices())
    # forced: auto prefers mesh only on real accelerator backends (the
    # CPU virtual mesh shares one core — vmap wins there)
    p = Parameter(**{**_B, "tpu_fleet": "mesh"})
    sched = fleet.FleetScheduler()
    for i in range(n_dev):
        sched.submit_param(f"m{i}", p.replace(u_init=0.004 * i))
    res = sched.run()
    row = res.summary["buckets"][0]
    assert row["mode"] == "mesh" and row["lanes"] == n_dev
    solo = NS2DSolver(p.replace(u_init=0.004 * 2))
    solo.run(progress=False)
    _assert_lane(res.by_sid("m2").fields, solo, bitwise=True)
    # the compiled program must not reshard the lanes (the commcheck
    # ban, checked at the HLO level where GSPMD inserts collectives)
    from pampi_tpu.fleet import scheduler as sch

    batched = next(b for (s, n, mode, tc), b in sch._BATCHES.items()
                   if mode == "mesh")
    hlo = batched._chunk_fn.lower(
        *batched.initial_state()).compile().as_text()
    for resharder in ("all-gather", "all-to-all", "reduce-scatter"):
        assert resharder not in hlo, resharder


def test_resolve_fleet_mesh_validation():
    from pampi_tpu.utils import dispatch

    p = Parameter(**_B, tpu_fleet="mesh")
    with pytest.raises(ValueError, match="divisible"):
        dispatch.resolve_fleet(p, 3, False, "k")
    with pytest.raises(ValueError, match="SCENARIO"):
        dispatch.resolve_fleet(p, 8, True, "k")
    n_dev = len(jax.devices())
    assert dispatch.resolve_fleet(p, n_dev, False, "k") == "mesh"


# -- the hardened queue intake -----------------------------------------

def test_load_queue_on_error_parks_malformed(tmp_path):
    good = tmp_path / "ok.par"
    good.write_text("name dcavity\nimax 12\njmax 12\nte 0.02\n")
    bad = tmp_path / "bad.par"
    bad.write_text("name dcavity\nimax notanumber\n")
    pois = tmp_path / "poisson.par"
    pois.write_text("name poisson\nimax 12\n")
    errors = []
    reqs = fleet.load_queue([str(good), str(bad), str(pois)],
                            on_error=lambda p, e: errors.append(p))
    assert [r.sid for r in reqs] == ["ok"]
    assert errors == [str(bad), str(pois)]
    # default behavior unchanged: a malformed file still raises
    with pytest.raises(SystemExit):
        fleet.load_queue([str(bad)])


# -- the persistent daemon ---------------------------------------------

def test_daemon_end_to_end(tmp_path, monkeypatch):
    from pampi_tpu.fleet import FleetDaemon, ServeConfig

    fleet.reset_templates()
    jsonl = tmp_path / "run.jsonl"
    monkeypatch.setenv("PAMPI_TELEMETRY", str(jsonl))
    tm.reset()
    qdir = tmp_path / "queue"
    qdir.mkdir()
    par = ("name dcavity\nimax {imax}\njmax 12\nre 10.0\nte 0.02\n"
           "tau 0.5\nitermax 8\neps 0.0001\nomg 1.7\ngamma 0.9\n"
           "tpu_mesh 1\ntpu_fuse_phases off\n")
    (qdir / "alice__a.par").write_text(par.format(imax=12))
    (qdir / "alice__b.par").write_text(par.format(imax=14))
    (qdir / "mallory__bad.par").write_text("name dcavity\nimax zzz\n")
    daemon = FleetDaemon(ServeConfig(
        queue_dir=str(qdir), poll_s=0.01, max_lanes=2, max_polls=1,
        classes="on"))
    assert daemon.run() == 0
    st = json.loads((qdir / "status.json").read_text())
    assert st["served"] == 2 and st["parked"] == 1
    assert st["per_tenant"]["alice"]["served"] == 2
    assert len(st["classes"]) == 1  # both grids share the 16x16 class
    assert st["latency_ms"]["p50"] is not None
    assert sorted(f.name for f in (qdir / "results").iterdir()) == [
        "alice__a.json", "alice__b.json"]
    assert (qdir / "parked" / "mallory__bad.par").exists()
    tm.finalize()
    records = [json.loads(line)
               for line in jsonl.read_text().splitlines()]
    kinds = {r["kind"] for r in records}
    assert {"serving", "admission", "latency", "warning"} <= kinds
    park = [r for r in records if r["kind"] == "warning"]
    assert park and park[0]["component"] == "fleet.serve"
    accepts = [r for r in records if r["kind"] == "admission"
               and r["action"] == "accept"]
    assert {a["tenant"] for a in accepts} == {"alice"}


def test_daemon_tenant_quota_defers(tmp_path, monkeypatch):
    from pampi_tpu.fleet import FleetDaemon, ServeConfig

    fleet.reset_templates()
    monkeypatch.setenv("PAMPI_TELEMETRY", str(tmp_path / "q.jsonl"))
    tm.reset()
    qdir = tmp_path / "queue"
    qdir.mkdir()
    par = ("name dcavity\nimax 12\njmax 12\nte 0.02\ntau 0.5\n"
           "itermax 8\ntpu_mesh 1\n")
    for i in range(3):
        (qdir / f"alice__r{i}.par").write_text(par)
    daemon = FleetDaemon(ServeConfig(
        queue_dir=str(qdir), poll_s=0.01, max_lanes=2, max_polls=1,
        tenant_quota=2, classes="off"))
    daemon.poll_once()
    st = daemon.status()
    # quota 2: the third request stays queued (deferred), retried later
    assert st["served"] == 2 and st["deferred"] == 1
    daemon.poll_once()
    assert daemon.status()["served"] == 3
    daemon.stop()
    tm.reset()


def test_daemon_survives_unschedulable_request(tmp_path, monkeypatch):
    # a WELL-FORMED .par whose knob combo cannot be scheduled (forced
    # mesh, 1 lane on a multi-device host) must degrade to a failed
    # request + warning record — never kill the daemon (other tenants
    # keep their service)
    from pampi_tpu.fleet import FleetDaemon, ServeConfig

    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device host to make mesh indivisible")
    fleet.reset_templates()
    monkeypatch.setenv("PAMPI_TELEMETRY", str(tmp_path / "f.jsonl"))
    tm.reset()
    qdir = tmp_path / "queue"
    qdir.mkdir()
    par = ("name dcavity\nimax 12\njmax 12\nte 0.02\ntau 0.5\n"
           "itermax 8\ntpu_mesh 1\ntpu_fleet mesh\n")
    (qdir / "bad__mesh1.par").write_text(par)
    good = par.replace("tpu_fleet mesh", "tpu_fleet auto")
    daemon = FleetDaemon(ServeConfig(
        queue_dir=str(qdir), poll_s=0.01, max_lanes=2, max_polls=1,
        classes="off"))
    daemon.poll_once()
    assert daemon.status()["failed"] == 1
    # the daemon is still alive and serves the next tenant
    (qdir / "alice__ok.par").write_text(good)
    daemon.poll_once()
    st = daemon.status()
    assert st["served"] == 1 and st["failed"] == 1
    daemon.stop()
    tm.reset()


# -- serving telemetry / artifact plumbing -----------------------------

def test_serving_summary_merge_and_lint(tmp_path, monkeypatch):
    from tools import telemetry_report as tr
    from tools._artifact import write_merged
    from tools.check_artifact import lint_bench, lint_serving_summary

    jsonl = tmp_path / "srv.jsonl"
    monkeypatch.setenv("PAMPI_TELEMETRY", str(jsonl))
    tm.reset()
    tm.emit("serving", event="start", queue_dir="q")
    tm.emit("admission", action="accept", sid="a", tenant="t")
    tm.emit("admission", action="park", path="bad.par")
    tm.emit("latency", scenario="a", ms=12.5)
    tm.emit("swap", family="fleet.ns2d", lane=0, scenario="b")
    tm.emit("serving", event="stop", polls=1, served=1, diverged=0,
            parked=1, deferred=0, swaps=1, queue_depth_max=2,
            scenarios_per_s=3.5)
    records = tr.load(str(jsonl))
    srv = tr.serving_summary(records)
    assert srv["served"] == 1 and srv["p50_latency_ms"] == 12.5
    assert srv["admission"] == {"accept": 1, "park": 1}
    artifact = tmp_path / "SRV.json"
    merged = write_merged(str(artifact), {
        "n": 0, "cmd": "t", "rc": 0, "tail": "",
        "telemetry_summary": tr.summary(records),
        "serving_summary": srv})
    assert lint_bench(merged, "SRV") == []
    names = {m["name"] for m in merged["metrics"]}
    assert {"fleet_p50_latency_ms", "fleet_queue_depth_max"} <= names
    # a gutted serving block must be flagged
    assert lint_serving_summary({"served": 1}, "X")
    tm.reset()


# -- serving observability (ISSUE 18) -----------------------------------

def test_class_sig_hash_disambiguates_rungs():
    """Two requests with EQUAL knobs but different class rungs must get
    different class signatures: the scheduler's _TEMPLATES cache is
    sig-keyed, and a collision hands a 16^2 class template to a 32^2
    bucket — every lane then trips the exceeds-class guard (the
    pre-existing bug the soak surfaced). Same rung, different request
    extents: SAME signature (that sharing is the whole point of shape
    classes)."""
    p16 = Parameter(**{**_B, "imax": 12, "jmax": 12})
    p16b = Parameter(**{**_B, "imax": 14, "jmax": 10})
    p32 = Parameter(**{**_B, "imax": 20, "jmax": 20})
    assert sc.class_sig_hash(p16) == sc.class_sig_hash(p16b)
    assert sc.class_sig_hash(p16) != sc.class_sig_hash(p32)


def test_parse_slo_spec():
    from pampi_tpu.fleet.slo import parse_slo_spec

    assert parse_slo_spec("") == {}
    assert parse_slo_spec(None) == {}
    assert parse_slo_spec("default=250, alice=100") == {
        "default": 250.0, "alice": 100.0}
    for bad in ("alice", "alice=fast", "=250", "alice=-5"):
        with pytest.raises(ValueError):
            parse_slo_spec(bad)


def test_slo_burn_rate_window_edges():
    """Pure-python burn math on a fake clock: the sliding window is
    inclusive at its edge (an exactly-window_s-old outcome still
    counts) and prunes just past it; the alert is EDGE-triggered (one
    warning per crossing, re-armed below threshold)."""
    from pampi_tpu.fleet.slo import BUDGET, SloTracker

    t = SloTracker({"default": 100.0}, window_s=10.0, burn_alert=2.0)
    # 10 requests at t=0..9, 2 violations
    for i in range(10):
        violated = t.observe("a", 250.0 if i < 2 else 50.0, float(i))
        assert violated == (i < 2)
    assert t.burn_rate("a", 9.0) == round((2 / 10) / BUDGET, 4)
    # at now=10.0 the t=0 entry sits exactly AT the edge: still counted
    assert t.burn_rate("a", 10.0) == round((2 / 10) / BUDGET, 4)
    # one tick past: the first violation leaves the window
    assert t.burn_rate("a", 10.0 + 1e-6) == round((1 / 9) / BUDGET, 4)
    # far past: empty window -> None (no data), lifetime total kept
    assert t.burn_rate("a", 100.0) is None
    assert t.violations_total == {"a": 2}
    # untracked tenant (no default match removed): target_for falls
    # back to default, an unknown spec has no accounting
    t2 = SloTracker({"alice": 100.0})
    assert t2.observe("bob", 9999.0, 0.0) is False
    assert t2.burn_rate("bob", 0.0) is None


def test_slo_alert_edge_triggered(tmp_path, monkeypatch):
    from pampi_tpu.fleet.slo import SloTracker

    jsonl = tmp_path / "slo.jsonl"
    monkeypatch.setenv("PAMPI_TELEMETRY", str(jsonl))
    tm.reset()
    t = SloTracker({"default": 10.0}, window_s=5.0, burn_alert=2.0)
    for i in range(4):
        t.observe("a", 100.0, 0.1 * i)  # every request violates
    t.poll(0.5)   # burn 20.0 -> ONE warning
    t.poll(0.6)   # still burning -> no second warning
    t.poll(100.0)  # window empty -> burn 0, alert re-armed
    for i in range(4):
        t.observe("a", 100.0, 100.0 + 0.1 * i)
    t.poll(100.5)  # second crossing -> second warning
    tm.finalize()
    records = [json.loads(ln) for ln in jsonl.read_text().splitlines()]
    warns = [r for r in records if r["kind"] == "warning"
             and r.get("component") == "slo"]
    assert len(warns) == 2
    slo_recs = [r for r in records if r["kind"] == "slo"]
    assert len(slo_recs) == 4  # one per tracked tenant per poll
    assert slo_recs[0]["burn_rate"] == 20.0
    assert {r["v"] for r in records} == {tm.SCHEMA_VERSION}
    tm.reset()


def test_daemon_observability_end_to_end(tmp_path, monkeypatch):
    """The whole ISSUE 18 plane through one daemon session: request
    traces (minted at admission, every span parented, critical stages
    tile each request's end-to-end latency, no table leaks), histogram
    status percentiles agreeing with the exact computation, slo records
    + status block, registry snapshots, and the report/merge/lint round
    trip with the new blocks."""
    from pampi_tpu.fleet import FleetDaemon, ServeConfig
    from pampi_tpu.utils import tracing
    from tools import telemetry_report as tr
    from tools._artifact import write_merged
    from tools.check_artifact import lint_bench

    fleet.reset_templates()
    jsonl = tmp_path / "obs.jsonl"
    monkeypatch.setenv("PAMPI_TELEMETRY", str(jsonl))
    tm.reset()
    tracing.reset()
    qdir = tmp_path / "queue"
    qdir.mkdir()
    par = ("name dcavity\nimax {imax}\njmax 12\nre 10.0\nte 0.02\n"
           "tau 0.5\nitermax 8\neps 0.0001\nomg 1.7\ngamma 0.9\n"
           "tpu_mesh 1\ntpu_fuse_phases off\n")
    (qdir / "alice__t0.par").write_text(par.format(imax=12))
    (qdir / "alice__t1.par").write_text(par.format(imax=14))
    (qdir / "bob__t2.par").write_text(par.format(imax=12))
    daemon = FleetDaemon(ServeConfig(
        queue_dir=str(qdir), poll_s=0.01, max_lanes=2, max_polls=2,
        classes="on", slo="default=60000,alice=0.001"))
    assert daemon.run() == 0
    assert tracing.pending() == 0  # every minted trace flushed
    tm.finalize()

    st = json.loads((qdir / "status.json").read_text())
    assert st["served"] == 3
    # the SLO block: alice's absurd 0.001 ms target makes every alice
    # request a violation; bob rides the generous default
    assert st["slo"]["alice"]["violations"] == 2
    assert st["slo"]["alice"]["burn_rate"] == 20.0
    assert st["slo"]["bob"]["violations"] == 0
    # the Prometheus scrape file sits next to status.json
    prom = (qdir / "metrics.prom").read_text()
    assert "fleet_request_latency_ms_bucket" in prom
    assert 'fleet_served_total{tenant="alice"} 2' in prom

    records = tr.load(str(jsonl))
    # trace continuity: every span parented under a root of its trace,
    # critical stages tile each root's e2e exactly (pre-rounding)
    spans = [r for r in records if r["kind"] == "trace"]
    roots = {r["trace"]: r for r in spans if r["stage"] == "request"}
    assert len(roots) == 3
    for r in spans:
        assert r["trace"] in roots
        if r["stage"] != "request":
            assert r["parent"] is not None
    for trace, root in roots.items():
        stages = {r["stage"]: r["ms"] for r in spans
                  if r["trace"] == trace and r["parent"] == "request"}
        assert set(stages) == set(tracing.CRITICAL_STAGES)
        assert abs(sum(stages.values()) - root["ms"]) < 1e-2
    # histogram percentiles vs the exact per-request latencies
    lats = [r["ms"] for r in records if r["kind"] == "latency"]
    assert len(lats) == 3
    for q in (0.5, 0.95):
        exact = fleet.serve._percentile(lats, q)
        assert abs(st["latency_ms"]["p%d" % (q * 100)] - exact) \
            / exact < 0.05
    assert st["latency_ms"]["max"] == round(max(lats), 3)

    # report/merge/lint round trip with the new blocks
    dec = tr.trace_decomposition(records)
    assert dec["requests"] == 3
    assert dec["sum_residual"] <= 0.05
    mxs = tr.metrics_summary(records)
    assert mxs["sources"] == 1
    slo = tr.slo_summary(records)
    assert set(slo) == {"alice", "bob"}
    text = tr.render(records)
    assert "request traces" in text and "tenant SLOs" in text
    merged = write_merged(str(tmp_path / "OBS.json"), {
        "n": 0, "cmd": "t", "rc": 0, "tail": "",
        "telemetry_summary": tr.summary(records),
        "serving_summary": tr.serving_summary(records),
        "metrics_summary": mxs, "slo": slo,
        "trace_decomposition": dec})
    assert lint_bench(merged, "OBS") == []
    names = {m["name"] for m in merged["metrics"]}
    assert {"fleet_class_p95_ms", "slo_violations"} <= names
    tm.reset()
    tracing.reset()
