"""Preccheck (pampi_tpu/analysis/preccheck.py + the prec driver pass) —
ISSUE 20 acceptance:

- CAST CENSUS: a traced subset round-trips through the `precision`
  baseline (update -> check clean -> update byte-stable); a declared
  `precision.cast(x, dtype, why)` downcast is censused under its @why
  scope and passes; an implicit downcast fails at its file:line.
- ORACLE PURITY: the committed f64 parity oracles carry zero sub-f64
  compute; a smuggled `.astype(float32)` in an oracle trace fails with
  both the purity and the implicit-cast rule at the seeded line.
- REDUCTION ORDER: an f32 `jnp.sum` feeding a while convergence
  predicate fails at its file:line unless its '<file>:<dtype>' key is
  declared in `precision.DECLARED_ORDER_SENSITIVE`.
- EPS FLOOR: the matrix-wide static (eps, ncells, dtype) check fires
  when eps sits within a decade of the dtype residual floor; the bf16
  advisory scouts report it as an advisory note, not a violation.
- BASELINE DRIFT: a tampered precision baseline fails with the per-key
  src->dst census diff; `--only prec --update` through the driver
  preserves the configs/comm sections byte-identically.
- AST dtype-policy: raw `.astype(<literal>)` / `jnp.float64(...)` /
  `dtype=<literal>` inside models/ops builders is flagged; the
  per-line allow escape and non-builder/non-solver trees are exempt.
- ARTIFACT LINT: a truncated or gutted precision section of
  CONTRACTS.json is a lint error; a dispatch-snapshot `*_dtype` record
  must lead with the resolved float dtype.

Compile cost: everything TRACES (make_jaxpr) — no jit execution.
"""

import json
import os
import types

import pytest

from pampi_tpu.analysis import astlint, commcheck, jaxprcheck, preccheck
from pampi_tpu.utils import precision

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
THIS = os.path.basename(__file__)


# ---------------------------------------------------------------------------
# shared traces
# ---------------------------------------------------------------------------

def _subset():
    keep = {"ns2d_jnp", "ns2d_dist_jnp", "ns2d_bf16_sor"}
    return [c for c in jaxprcheck.standard_configs() if c.name in keep]


@pytest.fixture(scope="module")
def prec_traced():
    """One traced subset shared by the precision tests (each config is a
    solver build — don't pay it per test): an f64 oracle, a dist chunk
    with an f64 convergence reduction, and a bf16 advisory scout."""
    return jaxprcheck.trace_matrix(_subset())


def _stub(fn, *args, dtype=None, oracle=False, advisory=False,
          params=None):
    """A hand-built TracedConfig over a tiny function — the mutation
    harness (the real matrix never contains the seeded defect)."""
    import jax
    import jax.numpy as jnp

    cfg = jaxprcheck.ChunkConfig("seeded", "ns2d", dict(params or {}),
                                 oracle=oracle, advisory=advisory)
    solver = types.SimpleNamespace(dtype=jnp.dtype(dtype or jnp.float64))
    return types.SimpleNamespace(cfg=cfg, solver=solver,
                                 jaxpr=jax.make_jaxpr(fn)(*args),
                                 decisions={})


# ---------------------------------------------------------------------------
# census round trip + committed-matrix properties
# ---------------------------------------------------------------------------

def test_prec_roundtrip_stable(prec_traced):
    """update -> check clean -> update again byte-stable (the precision
    section --update contract)."""
    vs, fresh, notes = preccheck.run(traced=prec_traced, update=True)
    assert vs == [], [str(v) for v in vs]
    vs, _, _ = preccheck.run(baseline=fresh, traced=prec_traced)
    assert vs == [], [str(v) for v in vs]
    _, again, _ = preccheck.run(traced=prec_traced, update=True)
    assert json.dumps(again, sort_keys=True) == json.dumps(
        fresh, sort_keys=True)


def test_oracle_configs_pure_f64(prec_traced):
    """The jnp parity oracle traces ONLY f64 float compute — the
    property the mixed-precision knob must never break."""
    oracle = next(t for t in prec_traced if t.cfg.name == "ns2d_jnp")
    assert oracle.cfg.oracle
    assert preccheck.subf64_sites(oracle.jaxpr.jaxpr) == []
    entry, _, _ = preccheck.config_entry(oracle)
    assert entry["float_dtypes"] == ["float64"]
    assert entry["narrowing"] == 0


def test_advisory_scout_census_pinned(prec_traced):
    """The bf16 scout's entry prices the future mixed-precision lane:
    bf16 compute, a non-empty narrowing census, and the f32 residual
    accumulation declared in DECLARED_ORDER_SENSITIVE."""
    scout = next(t for t in prec_traced
                 if t.cfg.name == "ns2d_bf16_sor")
    assert scout.cfg.advisory
    entry, _, _ = preccheck.config_entry(scout)
    assert entry["dtype"] == "bfloat16"
    assert entry["advisory"] is True
    assert entry["narrowing"] > 0
    assert "bfloat16" in entry["float_dtypes"]
    assert "sor.py:float32" in entry["reductions"]
    assert "sor.py:float32" in precision.DECLARED_ORDER_SENSITIVE


# ---------------------------------------------------------------------------
# mutation: the four rules
# ---------------------------------------------------------------------------

def test_smuggled_astype_in_oracle_flagged():
    """A f32 detour smuggled into an f64 oracle fails BOTH ways: the
    purity rule and the implicit-downcast ban, each at the seeded
    file:line."""
    import jax.numpy as jnp

    def leaky(x):
        y = x.astype(jnp.float32) * 2.0  # the smuggled narrow compute
        return y.astype(jnp.float64)

    t = _stub(leaky, jnp.zeros((4,), jnp.float64), oracle=True)
    vs, _, notes = preccheck.check_config(t, None, True)
    assert notes == []
    rules = {v.rule for v in vs}
    assert preccheck.RULE_ORACLE in rules
    assert preccheck.RULE_CAST in rules
    for v in vs:
        assert THIS in v.message and ":" in v.message
    cast = next(v for v in vs if v.rule == preccheck.RULE_CAST)
    assert "float64 -> float32" in cast.message
    assert "precision.cast" in cast.message


def test_declared_cast_censused_not_flagged():
    """The same downcast routed through utils/precision.cast carries its
    why on the census key and passes the ban."""
    import jax.numpy as jnp

    def declared(x):
        y = precision.cast(x, jnp.float32, "metrics")
        return y.astype(jnp.float64)

    t = _stub(declared, jnp.zeros((4,), jnp.float64))
    vs, entry, _ = preccheck.check_config(t, None, True)
    assert [v for v in vs if v.rule == preccheck.RULE_CAST] == []
    assert entry["casts"].get("float64->float32@metrics") == 1
    assert entry["narrowing"] == 1


def test_undeclared_convergence_reduction_flagged(monkeypatch):
    """An f32 sum feeding a while convergence predicate is the fused-vs-
    ladder hazard class: flagged at its file:line unless the
    '<file>:<dtype>' trade is declared in the registry."""
    import jax
    import jax.numpy as jnp

    def solve(x):
        def cond(c):
            i, r, _ = c
            return (r > jnp.float32(1e-6)) & (i < 10)

        def body(c):
            i, _, x = c
            x = x * jnp.float32(0.5)
            return i + 1, jnp.sum(x * x), x

        return jax.lax.while_loop(cond, body,
                                  (0, jnp.float32(1e9), x))

    t = _stub(solve, jnp.ones((8,), jnp.float32), dtype=jnp.float32)
    monkeypatch.setattr(precision, "DECLARED_ORDER_SENSITIVE",
                        frozenset())
    vs, entry, _ = preccheck.check_config(t, None, True)
    red = [v for v in vs if v.rule == preccheck.RULE_REDUCE]
    assert len(red) == 1
    assert THIS in red[0].message
    assert f"{THIS}:float32" in red[0].message
    assert entry["reductions"] == {f"{THIS}:float32": 1}
    # declaring the trade (with a why, in code review) clears it
    monkeypatch.setattr(precision, "DECLARED_ORDER_SENSITIVE",
                        frozenset({f"{THIS}:float32"}))
    vs, _, _ = preccheck.check_config(t, None, True)
    assert [v for v in vs if v.rule == preccheck.RULE_REDUCE] == []


def test_f64_convergence_reduction_passes():
    """An f64-accumulated residual needs no declaration — the audit
    gates only sub-f64 order-sensitive accumulation."""
    import jax
    import jax.numpy as jnp

    def solve(x):
        def cond(c):
            i, r, _ = c
            return (r > 1e-12) & (i < 10)

        def body(c):
            i, _, x = c
            x = x * 0.5
            return i + 1, jnp.sum(x * x), x

        return jax.lax.while_loop(cond, body, (0, jnp.float64(1e9), x))

    t = _stub(solve, jnp.ones((8,), jnp.float64))
    vs, entry, _ = preccheck.check_config(t, None, True)
    assert [v for v in vs if v.rule == preccheck.RULE_REDUCE] == []
    assert "float64" in "".join(entry["reductions"]) \
        or entry["reductions"] == {f"{THIS}:float64": 1}


def test_eps_floor_static_check():
    """The build-time check_eps_floor warning, generalized: a sub-f64
    config whose eps sits within a decade of the residual floor fails
    statically; an advisory config reports the same finding as a note."""
    import jax.numpy as jnp

    params = dict(eps=1e-7, imax=64, jmax=64)
    t = _stub(lambda x: x * 2, jnp.ones((4,), jnp.float32),
              dtype=jnp.float32, params=params)
    vs, _, notes = preccheck.check_config(t, None, True)
    floor = [v for v in vs if v.rule == preccheck.RULE_FLOOR]
    assert len(floor) == 1
    assert "residual floor" in floor[0].message
    # an f64 config at the same eps is safely above its (zero) floor
    t64 = _stub(lambda x: x * 2, jnp.ones((4,), jnp.float64),
                params=params)
    vs, _, _ = preccheck.check_config(t64, None, True)
    assert [v for v in vs if v.rule == preccheck.RULE_FLOOR] == []
    # the advisory spelling: same finding, reported not gated
    ta = _stub(lambda x: x * 2, jnp.ones((4,), jnp.float32),
               dtype=jnp.float32, params=params, advisory=True)
    vs, _, notes = preccheck.check_config(ta, None, True)
    assert vs == []
    assert any(f"[{preccheck.RULE_FLOOR}]" in n for n in notes)


def test_bf16_scout_floor_advisory(prec_traced):
    """The real bf16 scout at 16x16 sits UNDER its ~0.12 residual floor
    with the standard eps — exactly the price the advisory lane exists
    to report before the tpu_dtype knob lands."""
    vs, _, notes = preccheck.run(traced=prec_traced, update=True)
    assert vs == []
    floor_notes = [n for n in notes
                   if f"[{preccheck.RULE_FLOOR}]" in n]
    assert any(n.startswith("ns2d_bf16_sor:") for n in floor_notes)


# ---------------------------------------------------------------------------
# mutation: baseline drift
# ---------------------------------------------------------------------------

def test_tampered_precision_baseline_diffed(prec_traced):
    """A hand-edited cast census fails with the per-key src->dst diff
    (and the fresh sites' file:line), not a bare hash mismatch."""
    _, fresh, _ = preccheck.run(traced=prec_traced, update=True)
    tampered = json.loads(json.dumps(fresh))
    entry = tampered["ns2d_bf16_sor"]
    key = next(k for k in entry["casts"] if "->bfloat16@" in k)
    entry["casts"][key] += 2
    vs, _, _ = preccheck.run(baseline=tampered, traced=prec_traced)
    drift = [v for v in vs if v.rule == preccheck.RULE_BASELINE]
    assert len(drift) == 1
    assert "ns2d_bf16_sor" in drift[0].message
    assert key in drift[0].message and "->" in drift[0].message
    assert "--update" in drift[0].message


def test_missing_baseline_entry_flagged(prec_traced):
    """A config added without --update fails (no silent fresh-trace
    fallback once a precision baseline exists)."""
    _, fresh, _ = preccheck.run(traced=prec_traced, update=True)
    fresh.pop("ns2d_dist_jnp")
    vs, _, _ = preccheck.run(baseline=fresh, traced=prec_traced)
    missing = [v for v in vs if v.rule == preccheck.RULE_BASELINE]
    assert any("ns2d_dist_jnp" in v.message and "--update" in v.message
               for v in missing)


def test_env_mismatch_census_not_compared(prec_traced):
    """A baseline from another toolchain skips the census comparison
    (the jaxpr pass owns the one env-drift violation) but still runs
    the precision rules."""
    _, fresh, _ = preccheck.run(traced=prec_traced, update=True)
    tampered = json.loads(json.dumps(fresh))
    tampered["ns2d_jnp"]["casts"] = {"float64->float32@implicit": 99}
    vs, _, _ = preccheck.run(baseline=tampered, traced=prec_traced,
                             env_matches=False)
    assert vs == [], [str(v) for v in vs]


def test_driver_prec_update_preserves_other_sections(tmp_path,
                                                     prec_traced):
    """`--only prec --update` through the driver regenerates ONLY the
    precision section: configs/comm ride through byte-identically and
    the rewrite is a no-op diff on an already-current baseline."""
    import sys

    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import lint as lint_mod
    finally:
        sys.path.pop(0)

    _, configs_fresh = jaxprcheck.run(traced=prec_traced, update=True)
    _, comm_fresh = commcheck.run(traced=prec_traced, update=True)
    _, prec_fresh, _ = preccheck.run(traced=prec_traced, update=True)
    full = dict(configs_fresh, comm=comm_fresh, precision=prec_fresh)
    path = tmp_path / "CONTRACTS.json"
    path.write_text(json.dumps(full, indent=1, sort_keys=True) + "\n")
    before = path.read_text()

    ctx = lint_mod.TraceContext(str(path), update=True)
    ctx._traced = prec_traced
    vs = ctx.run_prec()
    assert vs == [], [str(v) for v in vs]
    assert ctx.fresh_configs is None and ctx.fresh_comm is None
    ctx.write()
    assert path.read_text() == before


# ---------------------------------------------------------------------------
# astlint dtype-policy
# ---------------------------------------------------------------------------

def _lint_src(tmp_path, src, name):
    path = tmp_path / name
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(src)
    vs, err = astlint.lint_file(str(path), root=str(tmp_path))
    assert err is None
    return [v for v in vs if v.rule == astlint.DTYPE_POLICY]


def test_dtype_policy_flags_builder_literals(tmp_path):
    """Raw dtype spellings inside a solver/ops builder are flagged per
    line; the same code outside a builder (host-side setup) is not."""
    src = ("import jax.numpy as jnp\n"
           "def make_solver_fn(x):\n"
           "    a = x.astype(jnp.float32)\n"
           "    b = jnp.float64(2.0)\n"
           "    c = jnp.zeros((2,), dtype='float32')\n"
           "    d = x.astype(jnp.float32)  # lint: allow(dtype-policy) t\n"
           "    return a, b, c, d\n"
           "def helper(x):\n"
           "    return x.astype(jnp.float32)\n")
    vs = _lint_src(tmp_path, src, "pampi_tpu/ops/seeded.py")
    assert [v.line for v in vs] == [3, 4, 5]
    assert "resolve_dtype" in vs[0].message \
        or "precision" in vs[0].message
    # the same file outside the policy dirs is exempt by location
    vs = _lint_src(tmp_path, src, "pampi_tpu/utils/seeded.py")
    assert vs == []


# ---------------------------------------------------------------------------
# artifact lint: the precision section + dtype dispatch records
# ---------------------------------------------------------------------------

def test_artifact_lint_precision_section(prec_traced):
    """A truncated or gutted precision section of CONTRACTS.json is a
    lint error, not a silent no-op."""
    from tools import check_artifact as ca

    _, configs_fresh = jaxprcheck.run(traced=prec_traced, update=True)
    _, comm_fresh = commcheck.run(traced=prec_traced, update=True)
    _, prec_fresh, _ = preccheck.run(traced=prec_traced, update=True)
    full = dict(configs_fresh, comm=comm_fresh, precision=prec_fresh)
    assert ca.lint_contracts(full) == []
    # a missing section fails outright
    gone = {k: v for k, v in full.items() if k != "precision"}
    assert any("precision" in e for e in ca.lint_contracts(gone))
    # a dropped config breaks the same-matrix invariant
    broken = json.loads(json.dumps(full))
    broken["precision"].popitem()
    assert any(".precision" in e for e in ca.lint_contracts(broken))
    # a gutted entry loses its census keys
    broken2 = json.loads(json.dumps(full))
    next(iter(broken2["precision"].values())).pop("casts")
    assert any("casts" in e for e in ca.lint_contracts(broken2))


def test_dispatch_snapshot_dtype_record_linted():
    """The resolve_dtype record in a dryrun tail must lead with the
    float dtype it resolved to — a raw knob echo is a lint error."""
    from tools import check_artifact as ca

    ok = "dispatch snapshot: {'ns2d_dtype': 'bfloat16 (tpu_dtype=bf16)'}"
    assert ca.lint_dispatch_snapshot(ok, "M") == []
    bad = "dispatch snapshot: {'ns2d_dtype': 'bf16'}"
    errs = ca.lint_dispatch_snapshot(bad, "M")
    assert errs and "ns2d_dtype" in errs[0]


def test_resolve_dtype_records_decision():
    """utils/precision.resolve_dtype streams the resolved dtype into the
    dispatch probe under its record_key (satellite c)."""
    from pampi_tpu.utils import dispatch

    dt = precision.resolve_dtype("bf16", record_key="seeded_dtype")
    import jax.numpy as jnp

    assert jnp.dtype(dt) == jnp.dtype(jnp.bfloat16)
    rec = dispatch.snapshot().get("seeded_dtype", "")
    assert rec.startswith("bfloat16")
    assert "tpu_dtype=bf16" in rec
