"""Scenario-fleet serving (pampi_tpu/fleet/): batched multi-tenant runs.

Contracts pinned here:
- fleet parity: a batch-of-N vmapped run equals N solo runs of the same
  traced program at the repo's ulp contract — BITWISE on the jnp and
  dist paths (vmap batches `lax.while_loop` by per-lane select), last-
  ulp only on the fused kernels (the batched grid re-associates fma like
  every layout precedent) — across all four families, jnp AND fused;
- diverged-lane isolation: one injected-NaN lane (PAMPI_FAULTS
  `nan@lane<K>:<field>` — host-side, the compiled chunk is untouched)
  freezes at its divergence, emits a scenario-tagged divergence record,
  and never perturbs its batchmates bitwise;
- bucket routing: mixed-shape queues split into shared-trace buckets,
  per-lane init keys and drive housekeeping stay OUT of the knob
  signature, trace-shaping knobs stay IN, and the signature hash is
  stable across Parameter instances;
- the `tpu_fleet` dispatch knob: validation, forced modes, the auto
  policy (vmap for multi-lane single-device buckets, pjit for dist /
  singleton buckets), decisions recorded like `tpu_overlap`;
- the vmapped dist chunk censuses the SAME collectives as its solo twin
  with zero resharding collectives and intact exchange scopes (the
  commcheck contract that makes vmap-batching safe on a mesh);
- telemetry: scenario-tagged chunk records, the fleet summary record,
  the `fleet_summary` merge block and its check_artifact lint.
"""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from pampi_tpu import fleet
from pampi_tpu.models.ns2d import NS2DSolver
from pampi_tpu.utils import dispatch
from pampi_tpu.utils import telemetry as tm
from pampi_tpu.utils.params import Parameter

_B2 = dict(name="dcavity", imax=16, jmax=16, re=10.0, te=0.02, tau=0.5,
           itermax=10, eps=1e-4, omg=1.7, gamma=0.9, tpu_mesh="1")
_B3 = dict(name="dcavity3d", imax=8, jmax=8, kmax=8, re=10.0, te=0.02,
           tau=0.5, itermax=8, eps=1e-4, omg=1.7, gamma=0.9, tpu_mesh="1")

ULP_TOL = 1e-12  # the repo's ulp contract (tests/test_overlap.py)


def _build(param, dims=None):
    if dims is not None:
        from pampi_tpu.parallel.comm import CartComm

        if fleet.family_of(param) == "ns2d":
            from pampi_tpu.models.ns2d_dist import NS2DDistSolver

            return NS2DDistSolver(param, CartComm(ndims=2, dims=dims))
        from pampi_tpu.models.ns3d_dist import NS3DDistSolver

        return NS3DDistSolver(param, CartComm(ndims=3, dims=dims))
    if fleet.family_of(param) == "ns2d":
        return NS2DSolver(param)
    from pampi_tpu.models.ns3d import NS3DSolver

    return NS3DSolver(param)


def _assert_close(a, b, name, bitwise):
    a, b = np.asarray(a), np.asarray(b)
    if bitwise:
        assert np.array_equal(a, b), (name, np.abs(a - b).max())
    else:
        d = np.abs(a - b)
        assert np.isfinite(d).all() and d.max() < ULP_TOL, (name, d.max())


def _parity_case(base, dims=None, bitwise=True, lanes=2):
    """Batch-of-N through BatchedSolver vs N solo drives of the SAME
    template program (the scheduler's pjit path is the oracle driver —
    independent-build oracles are the fleet-smoke gate)."""
    from pampi_tpu.fleet.scheduler import _reset_lane

    param = Parameter(**base)
    template = _build(param, dims)
    params = [param.replace(u_init=0.01 * i) for i in range(lanes)]
    batched = fleet.BatchedSolver(
        template, params, [f"s{i}" for i in range(lanes)])
    final = batched.run()
    results = batched.results(final)
    n_fields = batched._n_fields
    names = ("u", "v", "p") if n_fields == 3 else ("u", "v", "w", "p")
    for lane_param, res in zip(params, results):
        assert not res["diverged"]
        _reset_lane(template, lane_param)
        template.run(progress=False)
        assert res["nt"] == template.nt and template.nt > 0
        assert abs(res["t"] - template.t) < 1e-12
        for name, got in zip(names, res["fields"]):
            _assert_close(got, getattr(template, name), name, bitwise)


# -- fleet parity: all four families, jnp and fused --------------------
# Tier-1 keeps one representative per axis (2-D jnp + fused, 3-D jnp,
# 2-D dist jnp); the interpret-kernel-heavy fused/3-D-dist combinations
# carry the `slow` mark to hold the tier-1 870 s window (the PR 2 trim
# precedent) and run via `make fleet-suite`.

def test_parity_ns2d_jnp_bitwise():
    _parity_case(dict(_B2, tpu_fuse_phases="off"), lanes=3)


def test_parity_ns2d_fused_ulp():
    _parity_case(dict(_B2, tpu_fuse_phases="on", tpu_solver="sor",
                      tpu_sor_layout="checkerboard", tpu_sor_inner=1),
                 bitwise=False)


def test_parity_ns3d_jnp_bitwise():
    _parity_case(dict(_B3, tpu_fuse_phases="off"))


@pytest.mark.slow
def test_parity_ns3d_fused_ulp():
    _parity_case(dict(_B3, tpu_fuse_phases="on", tpu_solver="fft"),
                 bitwise=False)


def test_parity_ns2d_dist_jnp_bitwise():
    _parity_case(dict(_B2, tpu_mesh="2x2", tpu_fuse_phases="off",
                      tpu_sor_layout="checkerboard"), dims=(2, 2))


@pytest.mark.slow
def test_parity_ns2d_dist_fused_ulp():
    _parity_case(dict(_B2, tpu_mesh="2x2", tpu_fuse_phases="on",
                      tpu_sor_layout="checkerboard"), dims=(2, 2),
                 bitwise=False)


@pytest.mark.slow
def test_parity_ns3d_dist_jnp_bitwise():
    _parity_case(dict(_B3, tpu_mesh="2x2x2", tpu_fuse_phases="off"),
                 dims=(2, 2, 2))


@pytest.mark.slow
def test_parity_ns3d_dist_fused_ulp():
    _parity_case(dict(_B3, tpu_mesh="2x2x2", tpu_fuse_phases="on"),
                 dims=(2, 2, 2), bitwise=False)


# -- diverged-lane isolation -------------------------------------------

def test_lane_fault_isolation_bitwise(faults, tmp_path, monkeypatch,
                                      recwarn):
    jsonl = tmp_path / "fleet.jsonl"
    monkeypatch.setenv("PAMPI_TELEMETRY", str(jsonl))
    tm.reset()
    faults("nan@lane1:u")
    param = Parameter(**_B2)
    params = [param.replace(u_init=0.01 * i) for i in range(3)]
    template = _build(param)
    batched = fleet.BatchedSolver(template, params, ["t0", "t1", "t2"],
                                  family="ns2d")
    results = batched.results(batched.run())
    assert [r["diverged"] for r in results] == [False, True, False]
    # the poisoned lane froze at its first (diverging) chunk and its
    # divergence record names it; batchmates ran to te
    records = [json.loads(line) for line in jsonl.read_text().splitlines()]
    div = [r for r in records if r["kind"] == "divergence"]
    assert [d.get("scenario") for d in div] == ["t1"]
    assert div[0]["first_bad_step"] == 1
    tagged = [r for r in records if r["kind"] == "chunk"
              and "scenario" in r]
    assert {r["scenario"] for r in tagged} == {"t0", "t1", "t2"}
    # clean-lane isolation is BITWISE vs clean solo runs (telemetry still
    # armed so the chunk arity matches; the clause is spent, solo builds
    # never consult lane clauses anyway)
    from pampi_tpu.utils import faultinject as fi

    fi.reset()
    monkeypatch.delenv("PAMPI_FAULTS")
    for i in (0, 2):
        solo = _build(params[i])
        solo.run(progress=False)
        for name, got in zip("uvp", results[i]["fields"]):
            _assert_close(got, getattr(solo, name), (i, name),
                          bitwise=True)
        assert results[i]["nt"] == solo.nt


def test_lane_fault_spec_validation(faults):
    from pampi_tpu.utils import faultinject as fi

    faults("nan@lane0:u,inf@lane2:p")
    taken = fi.take_lane_faults()
    assert [(f, n) for f, n, _ in taken] == [("u", 0), ("p", 2)]
    assert np.isnan(taken[0][2]) and np.isinf(taken[1][2])
    # a spent clause stays spent for this generation
    assert fi.take_lane_faults() == ()
    # lane clauses never leak into the solver-generation (step) take
    fi.reset()
    assert fi.take_field_faults() == ()
    with pytest.raises(fi.FaultSpecError):
        faults("nan@lane1")  # lane clauses need a :<field>
        fi.take_lane_faults()


# -- bucket routing -----------------------------------------------------

def test_bucket_routing_mixed_queue():
    reqs = [
        fleet.ScenarioRequest("a", Parameter(**_B2)),
        fleet.ScenarioRequest("b", Parameter(**_B2, u_init=0.3)),
        fleet.ScenarioRequest("c", Parameter(**{**_B2, "imax": 24})),
        fleet.ScenarioRequest("d", Parameter(**{**_B2, "re": 20.0})),
        fleet.ScenarioRequest("e", Parameter(**_B3)),
    ]
    buckets = fleet.bucket(reqs)
    sids = {key.label: [r.sid for r in v] for key, v in buckets.items()}
    assert len(buckets) == 4
    # a+b share a trace (u_init is per-lane state); c is another shape;
    # d bakes a different re into the trace; e is 3-D
    groups = sorted(sids.values())
    assert ["a", "b"] in groups
    fams = {key.family for key in buckets}
    assert fams == {"ns2d", "ns3d"}
    grids = {key.grid for key in buckets if key.family == "ns2d"}
    assert (24, 16) in grids and (16, 16) in grids


def test_knob_signature_stability():
    a, b = Parameter(**_B2), Parameter(**_B2)
    assert fleet.signature_hash(a) == fleet.signature_hash(b)
    # per-lane state keys and drive housekeeping stay OUT — and since
    # serving v2, te too (carried per lane in the batched chunk state;
    # dist buckets sub-split per te in the scheduler)
    assert fleet.signature_hash(a.replace(u_init=9.0)) \
        == fleet.signature_hash(a)
    assert fleet.signature_hash(a.replace(tpu_checkpoint="x.npz")) \
        == fleet.signature_hash(a)
    assert fleet.signature_hash(a.replace(tpu_fleet="pjit")) \
        == fleet.signature_hash(a)
    assert fleet.signature_hash(a.replace(te=0.03)) \
        == fleet.signature_hash(a)
    # trace-shaping knobs stay IN
    for change in (dict(re=20.0), dict(itermax=11),
                   dict(tpu_solver="fft"), dict(name="canal"),
                   dict(obstacles="0.3,0.3,0.6,0.6"),
                   dict(tpu_mesh="2x2")):
        assert fleet.signature_hash(a.replace(**change)) \
            != fleet.signature_hash(a), change


def test_fleet_refuses_poisson():
    with pytest.raises(ValueError, match="poisson"):
        fleet.family_of(Parameter(name="poisson"))


def test_fleet_refuses_restart_requests():
    # silently serving a fresh t=0 run where the tenant asked for a
    # checkpoint restart would be a wrong answer, not a degraded one
    with pytest.raises(ValueError, match="tpu_restart"):
        fleet.bucket_key(Parameter(**_B2, tpu_restart="ckpt.npz"))


def test_lane_fault_charge_survives_ineligible_batch(faults):
    from pampi_tpu.utils import faultinject as fi

    faults("nan@lane2:u")
    # a 2-lane batch cannot express lane 2: the charge must stay armed
    assert fi.take_lane_faults(n_lanes=2, fields=("u", "v", "p")) == ()
    # ...and a w-clause must not be spent by a 2-D family
    fi.reset()
    faults("nan@lane0:w")
    assert fi.take_lane_faults(n_lanes=3, fields=("u", "v", "p")) == ()
    # the batch the clause was aimed at still consumes it
    fi.reset()
    faults("nan@lane2:u")
    taken = fi.take_lane_faults(n_lanes=3, fields=("u", "v", "p"))
    assert [(f, n) for f, n, _ in taken] == [("u", 2)]


def test_reset_lane_applies_tenant_drive_knobs():
    # drive-time knobs are excluded from the bucket signature (same
    # bucket) but each pjit lane must run under ITS OWN recovery policy,
    # not whichever tenant built the template
    from pampi_tpu.fleet.scheduler import _reset_lane

    param = Parameter(**_B2, tpu_fuse_phases="off")
    template = _build(param)
    tenant = param.replace(tpu_recover_ring=4, tpu_recover_dt_scale=0.25,
                           tpu_lookahead=0, tpu_retry_replenish=3)
    assert fleet.bucket_key(tenant) == fleet.bucket_key(param)
    _reset_lane(template, tenant)
    assert template.param.tpu_recover_ring == 4
    assert template.param.tpu_recover_dt_scale == 0.25
    assert template.param.tpu_lookahead == 0
    assert template.param.tpu_retry_replenish == 3
    # trace-shaping fields stay the template's (signature-equal anyway)
    assert template.param.te == param.te


def test_vmap_batch_heals_template_contamination():
    # a recovery dt clamp / pallas fallback left on the cached template
    # by an earlier bucket must be healed BEFORE the next batch builds
    # (a dirty _dt_scale would be baked into the batched trace and serve
    # every lane a clamped trajectory) and again after it
    from pampi_tpu.fleet import scheduler as sch

    fleet.reset_templates()
    s = fleet.FleetScheduler()
    param = Parameter(**_B2)
    s.submit_param("a", param)
    s.submit_param("b", param.replace(u_init=0.01))
    s.run()
    template = next(iter(sch._TEMPLATES.values()))[0]
    template._backend = "jnp"  # as a mid-batch fallback leaves it
    template._dt_scale = 0.5   # as a ring recovery leaves it
    s.submit_param("c", param.replace(u_init=0.02))
    s.submit_param("d", param.replace(u_init=0.03))
    res = s.run()
    assert template._backend == "auto" and template._dt_scale == 1.0
    assert res.summary["divergence_census"]["diverged"] == 0
    # the batch served the HEALED program: lanes equal fresh solo runs
    solo = _build(param.replace(u_init=0.02))
    solo.run(progress=False)
    for name, got in zip("uvp", res.by_sid("c").fields):
        _assert_close(got, getattr(solo, name), name, bitwise=True)


def test_vmap_batch_takes_drive_knobs_from_requests():
    # one drive loop per batch: its retry/recovery policy comes from the
    # FIRST request, never from whichever tenant built the template
    param = Parameter(**_B2, tpu_fuse_phases="off")
    template = _build(param)
    tenant = param.replace(tpu_retry_replenish=3, tpu_lookahead=0,
                           tpu_recover_ring=4)
    batched = fleet.BatchedSolver(template, [tenant, tenant], ["a", "b"])
    assert batched.param.tpu_retry_replenish == 3
    assert batched.param.tpu_lookahead == 0
    assert batched.param.tpu_recover_ring == 4
    assert batched.param.te == template.param.te  # trace fields: template's


def test_reset_lane_clears_recovery_contamination():
    # a previous tenant's divergence recovery (cumulative dt clamp) or
    # pallas fallback must not leak into the next tenant's program
    from pampi_tpu.fleet.scheduler import _reset_lane

    param = Parameter(**_B2, tpu_fuse_phases="off")
    template = _build(param)
    clean = _build(param)
    clean.run(progress=False)
    template._dt_scale = 0.5  # as RingRecovery.attempt would leave it
    template._backend = "jnp"  # as a pallas fallback would leave it
    _reset_lane(template, param)
    assert template._dt_scale == 1.0 and template._backend == "auto"
    template.run(progress=False)
    assert template.nt == clean.nt
    for name in "uvp":
        _assert_close(getattr(template, name), getattr(clean, name),
                      name, bitwise=True)


# -- the tpu_fleet knob -------------------------------------------------

def test_resolve_fleet_validation_and_policy():
    p = Parameter(**_B2)
    with pytest.raises(ValueError, match="tpu_fleet"):
        dispatch.resolve_fleet(p.replace(tpu_fleet="batch"), 2, False, "k")
    assert dispatch.resolve_fleet(p, 3, False, "fleet_t") == "vmap"
    assert dispatch.last("fleet_t").startswith("vmap")
    assert dispatch.resolve_fleet(p, 3, True, "fleet_t") == "pjit"
    assert dispatch.last("fleet_t").startswith("pjit (dist")
    assert dispatch.resolve_fleet(p, 1, False, "fleet_t") == "pjit"
    for forced in ("vmap", "pjit", "solo"):
        assert dispatch.resolve_fleet(
            p.replace(tpu_fleet=forced), 1, True, "fleet_t") == forced


# -- the vmapped dist chunk's collective contract -----------------------

def test_dist_fleet_census_matches_solo():
    from pampi_tpu.analysis.commcheck import census, scoped_exchanges
    from pampi_tpu.analysis.jaxprcheck import trace_chunk

    param = Parameter(**_B2, tpu_fuse_phases="off",
                      tpu_sor_layout="checkerboard")
    solo = _build(param, dims=(2, 2))
    batched = fleet.BatchedSolver(solo, [param, param], ["a", "b"])
    jx_solo = trace_chunk(solo)
    jx_fleet = trace_chunk(batched)
    c_solo, c_fleet = census(jx_solo.jaxpr), census(jx_fleet.jaxpr)
    # identical collective COUNTS: lanes ride the messages, they never
    # add messages — and zero resharding collectives
    assert c_fleet["collectives"] == c_solo["collectives"]
    for resharder in ("all_gather", "all_to_all", "reduce_scatter"):
        assert c_fleet["collectives"][resharder] == 0
    # the exchange scopes survive vmap (device-time attribution intact)
    assert any(scoped_exchanges(jx_fleet.jaxpr))


# -- scheduler end-to-end ----------------------------------------------

def test_scheduler_routes_and_reuses_templates():
    fleet.reset_templates()
    sched = fleet.FleetScheduler()
    for sid, p in (("a", Parameter(**_B2)),
                   ("b", Parameter(**_B2, u_init=0.05)),
                   ("w", Parameter(**{**_B2, "imax": 24}))):
        sched.submit_param(sid, p)
    res = sched.run()
    assert res.summary["n_scenarios"] == 3
    by_mode = {b["mode"] for b in res.summary["buckets"]}
    assert by_mode == {"vmap", "pjit"}  # 2-lane bucket + singleton
    assert res.summary["divergence_census"] == {
        "diverged": 0, "scenarios": []}
    assert res.summary["scenarios_per_s"] > 0
    assert res.by_sid("a").nt == res.by_sid("b").nt > 0
    # the queue drained; the first batch built its templates cold
    assert sched.requests == []
    assert all(b["template_cached"] is False
               for b in res.summary["buckets"])
    # a second same-shape batch REBINDS the cached compiled batch (zero
    # retrace — the warm serving path): same BatchedSolver object, zero
    # compile wall, and the lanes still get their own results
    from pampi_tpu.fleet import scheduler as sch

    warm_batch = next(iter(sch._BATCHES.values()))
    sched.submit_param("c", Parameter(**_B2, u_init=0.07))
    sched.submit_param("d", Parameter(**_B2, u_init=0.09))
    res2 = sched.run()
    assert res2.summary["buckets"][0]["template_cached"] is True
    assert res2.summary["buckets"][0]["compile_wall_s"] == 0.0
    assert next(iter(sch._BATCHES.values())) is warm_batch
    assert res2.by_sid("c").nt > 0 and not res2.by_sid("d").diverged
    # dispatch decisions recorded per bucket, tpu_overlap-style
    snap = dispatch.snapshot()
    assert any(k.startswith("fleet_ns2d_16x16") and "vmap" in v
               for k, v in snap.items())


def test_scheduler_solo_mode_matches_vmap():
    fleet.reset_templates()
    param = Parameter(**_B2, tpu_fleet="solo")
    reqs = [fleet.ScenarioRequest(f"s{i}", param.replace(u_init=0.01 * i))
            for i in range(2)]
    solo_res = fleet.run_fleet(reqs)
    assert all(b["mode"] == "solo" for b in solo_res.summary["buckets"])
    vm = [fleet.ScenarioRequest(f"s{i}",
                                param.replace(tpu_fleet="vmap",
                                              u_init=0.01 * i))
          for i in range(2)]
    vm_res = fleet.run_fleet(vm)
    for i in range(2):
        a, b = solo_res.scenarios[i], vm_res.scenarios[i]
        assert a.nt == b.nt
        for idx, (fa, fb) in enumerate(zip(a.fields, b.fields)):
            _assert_close(fa, fb, idx, bitwise=True)


# -- telemetry / artifact plumbing --------------------------------------

def test_scenario_scope_tags_records(tmp_path, monkeypatch):
    jsonl = tmp_path / "scope.jsonl"
    monkeypatch.setenv("PAMPI_TELEMETRY", str(jsonl))
    tm.reset()
    with tm.scenario_scope("tenant42"):
        tm.emit("solve", family="poisson", iters=3)
        tm.emit("chunk", family="x", scenario="explicit")
    tm.emit("solve", family="poisson", iters=4)
    recs = [json.loads(line) for line in jsonl.read_text().splitlines()]
    by_kind = {}
    for r in recs:
        by_kind.setdefault(r["kind"], []).append(r)
    assert by_kind["solve"][0]["scenario"] == "tenant42"
    assert by_kind["chunk"][0]["scenario"] == "explicit"  # explicit wins
    assert "scenario" not in by_kind["solve"][1]


def test_fleet_summary_merge_and_lint(tmp_path):
    from tools import telemetry_report as tr
    from tools._artifact import write_merged
    from tools.check_artifact import lint_bench, lint_fleet_summary

    records = [
        {"v": 4, "kind": "run", "backend": "cpu"},
        {"v": 4, "kind": "chunk", "family": "ns2d", "scenario": "a",
         "steps": 5, "t": 0.02, "nt": 5, "wall_s": 0.1,
         "ms_per_step": 20.0, "includes_compile": True},
        {"v": 4, "kind": "divergence", "family": "ns2d", "scenario": "b",
         "first_bad_step": 3},
        {"v": 4, "kind": "fleet", "n_scenarios": 2,
         "buckets": [{"bucket": "ns2d_16x16_abc", "family": "ns2d",
                      "grid": [16, 16], "mode": "vmap", "lanes": 2,
                      "compile_wall_s": 0.5, "run_wall_s": 1.0}],
         "scenarios_per_s": 2.0,
         "divergence_census": {"diverged": 1, "scenarios": ["b"]}},
    ]
    fl = tr.fleet_summary(records)
    assert fl["scenarios_per_s"] == 2.0
    assert fl["scenarios"]["b"]["diverged"] is True
    assert fl["scenarios"]["a"]["steps"] == 5
    art = tmp_path / "BENCH_r99.json"
    merged = write_merged(str(art), {
        "n": 99, "cmd": "t", "rc": 0, "tail": "",
        "telemetry_summary": tr.summary(records),
        "fleet_summary": fl,
    })
    assert lint_bench(merged, "B") == []
    # the throughput surfaces in the normalized metric list, cpu-tagged
    entry = [m for m in merged["metrics"]
             if m["name"] == "fleet_scenarios_per_s"]
    assert entry and entry[0]["backend"] == "cpu"
    # a censusless fleet block is a lint violation, not a quiet pass
    bad = dict(fl)
    bad.pop("divergence_census")
    assert any("divergence_census" in e
               for e in lint_fleet_summary(bad, "F"))
    bad2 = dict(fl)
    bad2["buckets"] = [{"bucket": "x"}]
    assert any("mode" in e for e in lint_fleet_summary(bad2, "F"))


def test_fleet_record_renders(tmp_path, monkeypatch):
    from tools import telemetry_report as tr

    jsonl = tmp_path / "fleet.jsonl"
    monkeypatch.setenv("PAMPI_TELEMETRY", str(jsonl))
    tm.reset()
    fleet.reset_templates()
    reqs = [fleet.ScenarioRequest(f"s{i}",
                                  Parameter(**_B2, u_init=0.01 * i))
            for i in range(2)]
    fleet.run_fleet(reqs)
    tm.finalize()
    out = tr.render(tr.load(str(jsonl)))
    assert "== fleet ==" in out
    assert "== scenarios (per tenant) ==" in out
    assert "s0" in out and "s1" in out
