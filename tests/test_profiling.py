"""Profiling region hooks (utils/profiling.py — the LIKWID-marker parity
layer): no-op when disabled, wall-clock accounting when enabled."""

import io

from pampi_tpu.utils import profiling as prof


def test_disabled_is_noop(monkeypatch):
    monkeypatch.setattr(prof, "_MODE", "0")
    prof.reset()
    prof.init()
    with prof.region("solve"):
        pass
    out = io.StringIO()
    prof.finalize(out)
    assert out.getvalue() == ""


def test_enabled_accounts_regions(monkeypatch):
    monkeypatch.setattr(prof, "_MODE", "1")
    prof.reset()
    prof.init()
    for _ in range(3):
        with prof.region("solve"):
            pass
    with prof.region("writeResult"):
        pass
    out = io.StringIO()
    prof.finalize(out)
    txt = out.getvalue()
    assert "solve" in txt and "writeResult" in txt
    assert prof._counts["solve"] == 3
