"""Profiling region hooks (utils/profiling.py — the LIKWID-marker parity
layer): no-op when disabled, wall-clock accounting when enabled.
PAMPI_PROFILE is read at call time through utils/flags.env — tests arm it
via the environment, the same surface production uses."""

import io

from pampi_tpu.utils import profiling as prof


def test_disabled_is_noop(monkeypatch):
    monkeypatch.setenv("PAMPI_PROFILE", "0")
    prof.reset()
    prof.init()
    with prof.region("solve"):
        pass
    out = io.StringIO()
    prof.finalize(out)
    assert out.getvalue() == ""


def test_enabled_accounts_regions(monkeypatch):
    monkeypatch.setenv("PAMPI_PROFILE", "1")
    prof.reset()
    prof.init()
    for _ in range(3):
        with prof.region("solve"):
            pass
    with prof.region("writeResult"):
        pass
    out = io.StringIO()
    prof.finalize(out)
    txt = out.getvalue()
    assert "solve" in txt and "writeResult" in txt
    assert prof._counts["solve"] == 3


def test_finalize_idempotent_and_atexit(monkeypatch, tmp_path):
    """finalize() must be safe to call twice (the atexit hook + the
    driver's explicit call): the table prints once and the CSV is not
    rewritten; init() re-arms for the next init/finalize pair."""
    monkeypatch.setenv("PAMPI_PROFILE", "1")
    csv = tmp_path / "regions.csv"
    monkeypatch.setenv("PAMPI_PROFILE_CSV", str(csv))
    prof.reset()
    prof.init()
    assert prof._atexit_registered  # early-exit safety net is armed
    with prof.region("solve"):
        pass
    out1, out2 = io.StringIO(), io.StringIO()
    prof.finalize(out1)
    assert "solve" in out1.getvalue() and csv.exists()
    csv.unlink()
    prof.finalize(out2)  # second call: no table, no CSV rewrite
    assert out2.getvalue() == ""
    assert not csv.exists()
    prof.init()  # re-armed
    out3 = io.StringIO()
    prof.finalize(out3)
    assert "solve" in out3.getvalue()


def test_table_accessor(monkeypatch):
    """table() — the telemetry finalize record's source — mirrors the
    wall/device accounting."""
    monkeypatch.setenv("PAMPI_PROFILE", "1")
    prof.reset()
    prof.init()
    with prof.region("solve"):
        pass
    prof.add_device_time("kernel", 1.5, calls=2)
    t = prof.table()
    assert t["solve"]["calls"] == 1 and t["solve"]["wall_s"] >= 0
    assert t["solve"]["device_s"] is None
    assert t["kernel"] == {"calls": 2, "wall_s": 1.5, "device_s": 1.5}
    prof.reset()
