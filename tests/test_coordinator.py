"""Coordinated multi-host fault handling (parallel/coordinator.py, PR 10
tentpole) on the VIRTUAL-RANK simulation path: N full solver instances
driven in lockstep through the same agree-then-act protocol the real
multi-process allgather transport runs, so the global decisions — shared
transient budget, agreed rollback generation, checkpoint vote, abort —
are tier-1-provable on this CPU container. tests/test_multihost.py holds
the real cross-process acceptance cases (capability-gated, un-gate on
TPU/GPU or a gloo jaxlib).

Compile cost: every solver is 16², tpu_chunk=2, a handful of steps (the
test_faultinject sizing lever); the 4-rank cases pay 4 small builds by
design — that IS the simulated fleet.
"""

import json
import warnings

import numpy as np
import pytest

from pampi_tpu.models.ns2d import NS2DSolver
from pampi_tpu.parallel import coordinator as co
from pampi_tpu.utils import faultinject as fi
from pampi_tpu.utils import telemetry as tm
from pampi_tpu.utils.params import Parameter

_BASE = dict(name="dcavity", imax=16, jmax=16, re=10.0, te=0.05, tau=0.5,
             itermax=50, eps=1e-4, omg=1.7, gamma=0.9)


@pytest.fixture()
def tel_on(tmp_path, monkeypatch):
    path = tmp_path / "run.jsonl"
    monkeypatch.setenv("PAMPI_TELEMETRY", str(path))
    tm.reset()
    yield path
    tm.reset()


def _records(path, kind=None):
    recs = [json.loads(ln) for ln in open(path) if ln.strip()]
    return recs if kind is None else [r for r in recs if r["kind"] == kind]


def _fleet(n, param=None, **loop_kw):
    """n virtual ranks: each a full NS2DSolver built under its
    rank_scope (so @rank<R> clauses arm only their target), wrapped in a
    CoordinatedLoop mirroring the run() wiring."""
    param = param or Parameter(tpu_chunk=2, **_BASE)
    solvers, loops = [], []
    for r in range(n):
        with fi.rank_scope(r):
            solvers.append(NS2DSolver(param))
    for r, s in enumerate(solvers):
        loops.append(co.sim_rank_loop(s, "ns2d", 3, r, **loop_kw))
    return solvers, loops


def _quiet_run(loops):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return co.LockstepSim(loops).run()


# ---------------------------------------------------------------------------
# the merge rule + the seam itself
# ---------------------------------------------------------------------------

def test_merge_words_semantics():
    """done = min (ALL ranks must finish), faults/divergence/vote = max
    (any rank's fault is everyone's), rollback target = min (the
    shallowest common generation)."""
    a = co.blank_word()
    a[co.W_DONE] = 1
    b = co.blank_word()
    b[co.W_FAULT] = 1
    b[co.W_DIVERGED] = 1
    b[co.W_ROLLBACK_NT] = 8
    c = co.blank_word()
    c[co.W_DONE] = 1
    c[co.W_ROLLBACK_NT] = 4
    c[co.W_CKPT] = 1
    m = co.merge_words(np.stack([a, b, c]))
    assert m[co.W_DONE] == 0          # b is not done
    assert m[co.W_FAULT] == 1
    assert m[co.W_DIVERGED] == 1
    assert m[co.W_ROLLBACK_NT] == 4   # the common (shallowest) generation
    assert m[co.W_CKPT] == 1
    # a lone clean word merges to itself (the SoloCoordinator identity)
    clean = co.blank_word()
    np.testing.assert_array_equal(co.merge_words(clean), clean)


def test_solo_coordinator_is_bitwise_identical():
    """tpu_coord on under one process: the protocol path (1-rank
    coordinator) must reproduce the historical uncoordinated run
    BITWISE — same compiled chunk, same confirmations, no trace change
    (the coordinator is host-side only)."""
    ref = NS2DSolver(Parameter(tpu_chunk=2, **_BASE))
    ref.run(progress=False)
    s = NS2DSolver(Parameter(tpu_chunk=2, tpu_coord="on", **_BASE))
    s.run(progress=False)
    assert s.nt == ref.nt and s.t == ref.t
    np.testing.assert_array_equal(np.asarray(s.u), np.asarray(ref.u))
    np.testing.assert_array_equal(np.asarray(s.v), np.asarray(ref.v))
    np.testing.assert_array_equal(np.asarray(s.p), np.asarray(ref.p))
    from pampi_tpu.utils import dispatch

    assert dispatch.last("coord_ns2d") == "coordinated (forced, 1 process)"


def test_coord_knob_validation():
    s = NS2DSolver(Parameter(tpu_chunk=2, tpu_coord="bogus", **_BASE))
    with pytest.raises(ValueError, match="tpu_coord"):
        s.run(progress=False)


def test_auto_is_uncoordinated_single_process():
    """The default leaves single-process runs on the exact historical
    loop: make_coordinator returns None and records why."""
    assert co.make_coordinator(Parameter(**_BASE), "ns2d") is None
    from pampi_tpu.utils import dispatch

    assert dispatch.last("coord_ns2d") == "uncoordinated (single process)"
    assert not co.coord_armed(Parameter(**_BASE))
    assert co.coord_armed(Parameter(tpu_coord="on", **_BASE))


# ---------------------------------------------------------------------------
# the fault-suite smoke: 4 simulated ranks, rank-2 transient + rank-0
# divergence rollback — identical post-recovery state on every rank
# ---------------------------------------------------------------------------

def test_four_rank_transient_retried_globally(faults, tel_on):
    """An injected rank-LOCAL transient (rank 2, chunk 2) is agreed at
    the boundary and the chunk re-dispatched on EVERY rank: all four
    finals match the uninjected solo run bitwise (same compiled chunk,
    same inputs), and the decision is one flight-recorder `coord`
    line."""
    ref = NS2DSolver(Parameter(tpu_chunk=2, **_BASE))
    ref.run(progress=False)
    faults("transient@chunk2@rank2")
    solvers, loops = _fleet(4)
    _quiet_run(loops)
    for r, s in enumerate(solvers):
        assert s.nt == ref.nt, f"rank {r}"
        np.testing.assert_array_equal(np.asarray(s.u), np.asarray(ref.u))
        np.testing.assert_array_equal(np.asarray(s.p), np.asarray(ref.p))
    retries = [r for r in _records(tel_on, "coord")
               if r["event"] == "retry"]
    assert len(retries) == 1  # one GLOBAL decision, one line (rank 0)
    assert retries[0]["budget_left"] == 0


def test_four_rank_divergence_rolls_every_rank_back(faults, tel_on):
    """A rank-0-only corruption diverges rank 0; the merged word rolls
    EVERY rank back to the same agreed generation and every rank
    re-drives with the same clamped dt — post-recovery state identical
    on all ranks, finite, past te. The fault-suite coordinator smoke."""
    faults("nan@step5:u@rank0")
    solvers, loops = _fleet(
        4, Parameter(tpu_chunk=2, tpu_recover_ring=4, **_BASE))
    _quiet_run(loops)
    ref = solvers[0]
    assert ref.t > _BASE["te"]
    for r, s in enumerate(solvers):
        assert np.isfinite(np.asarray(s.u)).all(), f"rank {r}"
        assert s._dt_scale == 0.5, f"rank {r}"  # ONE agreed clamp each
        assert s.nt == ref.nt and s.t == ref.t, f"rank {r}"
        np.testing.assert_array_equal(np.asarray(s.u), np.asarray(ref.u))
        np.testing.assert_array_equal(np.asarray(s.p), np.asarray(ref.p))
    rolls = [r for r in _records(tel_on, "coord")
             if r["event"] == "rollback"]
    assert len(rolls) == 1
    assert rolls[0]["target_nt"] == 4  # the boundary before the bad step


def test_global_budget_spans_ranks_and_aborts_everywhere(faults):
    """The budget is GLOBAL: back-to-back transients on DIFFERENT ranks
    inside one replenish window exhaust the single shared charge, and
    the agreed decision is a clean abort on every rank — never one rank
    dying inside a collective."""
    faults("transient@chunk2@rank2,transient@chunk3@rank0")
    _solvers, loops = _fleet(
        4, Parameter(tpu_chunk=2, tpu_retry_replenish=50, **_BASE))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with pytest.raises(co.CoordinatorAbort, match="budget exhausted"):
            co.LockstepSim(loops).run()


def test_global_budget_replenishes_after_agreed_clean_chunks(faults):
    """Spaced rank-local transients past the replenish window both
    retry (the shared budget refills on AGREED clean boundaries) and
    the fleet completes — the PR 4 replenish semantics, now global."""
    faults("transient@chunk2@rank1,transient@chunk6@rank3")
    solvers, loops = _fleet(
        4, Parameter(tpu_chunk=1, tpu_retry_replenish=3, **_BASE),
        replenish_after=3)
    _quiet_run(loops)
    for s in solvers:
        assert s.t > _BASE["te"]
        assert np.isfinite(np.asarray(s.u)).all()


def test_checkpoint_vote_commits_on_every_rank(faults, tel_on):
    """The agreed checkpoint vote: every rank's on_ckpt commit fires at
    the SAME boundaries (the manifest write itself is rank-0-gated in
    production; the agreement is what this pins), and each commit is a
    `coord` ckpt line."""
    commits = {r: [] for r in range(3)}
    solvers, loops = _fleet(3, Parameter(tpu_chunk=2, **_BASE))
    for r, loop in enumerate(loops):
        loop.ckpt_every = 2
        loop.on_ckpt = lambda s, r=r: commits[r].append(
            int(s[4]))  # nt at the commit point
    _quiet_run(loops)
    assert commits[0]  # the cadence fired at least once
    assert commits[0] == commits[1] == commits[2]  # same agreed boundaries
    votes = [r for r in _records(tel_on, "coord") if r["event"] == "ckpt"]
    assert len(votes) == len(commits[0])


def test_abort_on_unreplenished_budget_is_loud_not_divergent(faults):
    """tpu_coord off under one process keeps the historical path even
    with rank clauses armed (targeting rank 0 = this process): the
    uncoordinated loop's own budget handles it."""
    faults("transient@chunk2@rank0")
    s = NS2DSolver(Parameter(tpu_chunk=2, tpu_coord="off", **_BASE))
    with pytest.warns(UserWarning, match="transient"):
        s.run(progress=False)
    assert s.t > _BASE["te"]


def test_coordinated_pallas_fallback_completes(faults, tel_on):
    """The W_FALLBACK decision through the production seam: an injected
    pallas failure under the 1-rank coordinator swaps to the jnp chunk
    via the agreed word (retry() on the failing rank, mirrored on
    peers) and the run completes — one `coord` fallback line."""
    faults("pallas@chunk2")
    s = NS2DSolver(Parameter(tpu_fuse_phases="on", tpu_solver="fft",
                             tpu_chunk=2, tpu_coord="on", **_BASE))
    assert s._uses_pallas()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        s.run(progress=False)
    assert s._backend == "jnp" and s.t > _BASE["te"]
    assert np.isfinite(np.asarray(s.u)).all()
    falls = [r for r in _records(tel_on, "coord")
             if r["event"] == "fallback"]
    assert len(falls) == 1


# ---------------------------------------------------------------------------
# xlacache wedge hardening (satellite): dead cache path -> warn + uncached
# ---------------------------------------------------------------------------

def test_xlacache_unusable_dir_proceeds_uncached(tmp_path, monkeypatch,
                                                 tel_on):
    """A cache path that cannot be used (here: a FILE where the dir
    should be) degrades to warn-and-run-uncached with a structured
    telemetry `warning` record — never a blocked run."""
    from pampi_tpu.utils import xlacache

    bogus = tmp_path / "cachefile"
    bogus.write_text("not a directory")
    monkeypatch.setenv("PAMPI_XLA_CACHE", str(bogus))
    with pytest.warns(UserWarning, match="UNCACHED"):
        assert xlacache.enable() is None
    warns = _records(tel_on, "warning")
    assert len(warns) == 1 and warns[0]["component"] == "xlacache"
    from tools import check_artifact as ca
    from tools import telemetry_report as tr

    summ = tr.summary(_records(tel_on))
    assert summ["warnings"][0]["component"] == "xlacache"
    assert ca.lint_telemetry_summary(summ, "X") == []


def test_xlacache_hung_probe_times_out(tmp_path, monkeypatch, tel_on):
    """The documented wedge (xlacache.py): storage that HANGS (a dead
    shared mount — os calls block forever) is bounded by the probe
    timeout; the run proceeds uncached instead of wedging the fleet."""
    import time

    from pampi_tpu.utils import xlacache

    monkeypatch.setenv("PAMPI_XLA_CACHE", str(tmp_path / "cache"))
    monkeypatch.setenv("PAMPI_XLA_CACHE_TIMEOUT", "0.2")
    monkeypatch.setattr(xlacache.os, "makedirs",
                        lambda *a, **k: time.sleep(5))
    with pytest.warns(UserWarning, match="UNCACHED"):
        assert xlacache.enable() is None
    warns = _records(tel_on, "warning")
    assert warns and "probe exceeded" in warns[0]["reason"]


# ---------------------------------------------------------------------------
# coord records through the report + artifact lint (schema v5)
# ---------------------------------------------------------------------------

def test_coord_records_render_and_lint(tel_on):
    tm.emit("coord", event="armed", family="ns2d_dist", mode="multihost",
            nranks=4, rank=0)
    tm.emit("coord", event="retry", boundary=3, family="ns2d_dist",
            budget_left=0, t=0.5)
    tm.emit("coord", event="rollback", boundary=7, family="ns2d_dist",
            target_nt=8, t=0.25)
    tm.emit("ckpt", event="elastic_save", path="ck", generation=2,
            mesh=[2, 4], t=0.5, nt=10, rotated=True)
    tm.emit("ckpt", event="elastic_load", path="ck", generation=2,
            mesh_now=[2, 2], t=0.5, nt=10)

    from tools import check_artifact as ca
    from tools import telemetry_report as tr

    recs = _records(tel_on)
    text = tr.render(recs)
    for needle in ("coordinator (agreed global decisions)",
                   "armed: multihost nranks=4", "retry", "rollback",
                   "elastic_save", "elastic_load"):
        assert needle in text, needle
    summ = tr.summary(recs)
    assert summ["coord"]["nranks"] == 4
    assert summ["coord"]["decisions"] == {"retry": 1, "rollback": 1}
    assert summ["ckpt"]["elastic_save"] == 1
    assert summ["ckpt"]["elastic_load"] == 1
    where = "BENCH.telemetry_summary"
    assert ca.lint_telemetry_summary(summ, where) == []
    # gutted blocks are FLAGGED, not waved through
    assert ca.lint_telemetry_summary({**summ, "coord": "zap"}, where)
    assert ca.lint_telemetry_summary({**summ, "coord": {}}, where)
    assert ca.lint_telemetry_summary(
        {**summ, "warnings": [{"reason": "no component"}]}, where)


def test_fallback_mirrors_onto_transient_rank(faults, tel_on):
    """Review regression: a rank that raised a TRANSIENT in the same
    round a peer took the pallas fallback must STILL mirror the swap —
    guarding on 'did I raise anything' would leave it on the pallas
    program and desynchronize the fleet. Rank 0 pallas-fails and rank 1
    transient-fails at the same boundary; both must end on jnp with
    identical state."""
    faults("pallas@chunk2@rank0,transient@chunk2@rank1")
    param = Parameter(tpu_fuse_phases="on", tpu_solver="fft",
                      tpu_chunk=2, **_BASE)
    solvers = []
    for r in range(2):
        with fi.rank_scope(r):
            solvers.append(NS2DSolver(param))
    loops = []
    for r, s in enumerate(solvers):
        from pampi_tpu.models._driver import pallas_retry

        loop = co.sim_rank_loop(s, "ns2d", 3, r)
        loop.retry = pallas_retry(s, "pressure solve")
        loops.append(loop)
    _quiet_run(loops)
    for r, s in enumerate(solvers):
        assert s._backend == "jnp", f"rank {r} kept the pallas program"
        assert s.t > _BASE["te"]
    assert solvers[0].nt == solvers[1].nt
    np.testing.assert_array_equal(np.asarray(solvers[0].u),
                                  np.asarray(solvers[1].u))
