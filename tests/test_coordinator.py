"""Coordinated multi-host fault handling (parallel/coordinator.py, PR 10
tentpole) on the VIRTUAL-RANK simulation path: N full solver instances
driven in lockstep through the same agree-then-act protocol the real
multi-process allgather transport runs, so the global decisions — shared
transient budget, agreed rollback generation, checkpoint vote, abort —
are tier-1-provable on this CPU container. tests/test_multihost.py holds
the real cross-process acceptance cases (capability-gated, un-gate on
TPU/GPU or a gloo jaxlib).

Compile cost: every solver is 16², tpu_chunk=2, a handful of steps (the
test_faultinject sizing lever); the 4-rank cases pay 4 small builds by
design — that IS the simulated fleet.
"""

import json
import warnings

import numpy as np
import pytest

from pampi_tpu.models.ns2d import NS2DSolver
from pampi_tpu.parallel import coordinator as co
from pampi_tpu.utils import faultinject as fi
from pampi_tpu.utils import telemetry as tm
from pampi_tpu.utils.params import Parameter

_BASE = dict(name="dcavity", imax=16, jmax=16, re=10.0, te=0.05, tau=0.5,
             itermax=50, eps=1e-4, omg=1.7, gamma=0.9)


@pytest.fixture()
def tel_on(tmp_path, monkeypatch):
    path = tmp_path / "run.jsonl"
    monkeypatch.setenv("PAMPI_TELEMETRY", str(path))
    tm.reset()
    yield path
    tm.reset()


def _records(path, kind=None):
    recs = [json.loads(ln) for ln in open(path) if ln.strip()]
    return recs if kind is None else [r for r in recs if r["kind"] == kind]


def _fleet(n, param=None, **loop_kw):
    """n virtual ranks: each a full NS2DSolver built under its
    rank_scope (so @rank<R> clauses arm only their target), wrapped in a
    CoordinatedLoop mirroring the run() wiring."""
    param = param or Parameter(tpu_chunk=2, **_BASE)
    solvers, loops = [], []
    for r in range(n):
        with fi.rank_scope(r):
            solvers.append(NS2DSolver(param))
    for r, s in enumerate(solvers):
        loops.append(co.sim_rank_loop(s, "ns2d", 3, r, **loop_kw))
    return solvers, loops


def _quiet_run(loops):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return co.LockstepSim(loops).run()


# ---------------------------------------------------------------------------
# the merge rule + the seam itself
# ---------------------------------------------------------------------------

def test_merge_words_semantics():
    """done = min (ALL ranks must finish), faults/divergence/vote = max
    (any rank's fault is everyone's), rollback target = min (the
    shallowest common generation)."""
    a = co.blank_word()
    a[co.W_DONE] = 1
    b = co.blank_word()
    b[co.W_FAULT] = 1
    b[co.W_DIVERGED] = 1
    b[co.W_ROLLBACK_NT] = 8
    c = co.blank_word()
    c[co.W_DONE] = 1
    c[co.W_ROLLBACK_NT] = 4
    c[co.W_CKPT] = 1
    m = co.merge_words(np.stack([a, b, c]))
    assert m[co.W_DONE] == 0          # b is not done
    assert m[co.W_FAULT] == 1
    assert m[co.W_DIVERGED] == 1
    assert m[co.W_ROLLBACK_NT] == 4   # the common (shallowest) generation
    assert m[co.W_CKPT] == 1
    # a lone clean word merges to itself (the SoloCoordinator identity)
    clean = co.blank_word()
    np.testing.assert_array_equal(co.merge_words(clean), clean)


def test_solo_coordinator_is_bitwise_identical():
    """tpu_coord on under one process: the protocol path (1-rank
    coordinator) must reproduce the historical uncoordinated run
    BITWISE — same compiled chunk, same confirmations, no trace change
    (the coordinator is host-side only)."""
    ref = NS2DSolver(Parameter(tpu_chunk=2, **_BASE))
    ref.run(progress=False)
    s = NS2DSolver(Parameter(tpu_chunk=2, tpu_coord="on", **_BASE))
    s.run(progress=False)
    assert s.nt == ref.nt and s.t == ref.t
    np.testing.assert_array_equal(np.asarray(s.u), np.asarray(ref.u))
    np.testing.assert_array_equal(np.asarray(s.v), np.asarray(ref.v))
    np.testing.assert_array_equal(np.asarray(s.p), np.asarray(ref.p))
    from pampi_tpu.utils import dispatch

    assert dispatch.last("coord_ns2d") == "coordinated (forced, 1 process)"


def test_coord_knob_validation():
    s = NS2DSolver(Parameter(tpu_chunk=2, tpu_coord="bogus", **_BASE))
    with pytest.raises(ValueError, match="tpu_coord"):
        s.run(progress=False)


def test_auto_is_uncoordinated_single_process():
    """The default leaves single-process runs on the exact historical
    loop: make_coordinator returns None and records why."""
    assert co.make_coordinator(Parameter(**_BASE), "ns2d") is None
    from pampi_tpu.utils import dispatch

    assert dispatch.last("coord_ns2d") == "uncoordinated (single process)"
    assert not co.coord_armed(Parameter(**_BASE))
    assert co.coord_armed(Parameter(tpu_coord="on", **_BASE))


# ---------------------------------------------------------------------------
# the fault-suite smoke: 4 simulated ranks, rank-2 transient + rank-0
# divergence rollback — identical post-recovery state on every rank
# ---------------------------------------------------------------------------

def test_four_rank_transient_retried_globally(faults, tel_on):
    """An injected rank-LOCAL transient (rank 2, chunk 2) is agreed at
    the boundary and the chunk re-dispatched on EVERY rank: all four
    finals match the uninjected solo run bitwise (same compiled chunk,
    same inputs), and the decision is one flight-recorder `coord`
    line."""
    ref = NS2DSolver(Parameter(tpu_chunk=2, **_BASE))
    ref.run(progress=False)
    faults("transient@chunk2@rank2")
    solvers, loops = _fleet(4)
    _quiet_run(loops)
    for r, s in enumerate(solvers):
        assert s.nt == ref.nt, f"rank {r}"
        np.testing.assert_array_equal(np.asarray(s.u), np.asarray(ref.u))
        np.testing.assert_array_equal(np.asarray(s.p), np.asarray(ref.p))
    retries = [r for r in _records(tel_on, "coord")
               if r["event"] == "retry"]
    assert len(retries) == 1  # one GLOBAL decision, one line (rank 0)
    assert retries[0]["budget_left"] == 0


def test_four_rank_divergence_rolls_every_rank_back(faults, tel_on):
    """A rank-0-only corruption diverges rank 0; the merged word rolls
    EVERY rank back to the same agreed generation and every rank
    re-drives with the same clamped dt — post-recovery state identical
    on all ranks, finite, past te. The fault-suite coordinator smoke."""
    faults("nan@step5:u@rank0")
    solvers, loops = _fleet(
        4, Parameter(tpu_chunk=2, tpu_recover_ring=4, **_BASE))
    _quiet_run(loops)
    ref = solvers[0]
    assert ref.t > _BASE["te"]
    for r, s in enumerate(solvers):
        assert np.isfinite(np.asarray(s.u)).all(), f"rank {r}"
        assert s._dt_scale == 0.5, f"rank {r}"  # ONE agreed clamp each
        assert s.nt == ref.nt and s.t == ref.t, f"rank {r}"
        np.testing.assert_array_equal(np.asarray(s.u), np.asarray(ref.u))
        np.testing.assert_array_equal(np.asarray(s.p), np.asarray(ref.p))
    rolls = [r for r in _records(tel_on, "coord")
             if r["event"] == "rollback"]
    assert len(rolls) == 1
    assert rolls[0]["target_nt"] == 4  # the boundary before the bad step


def test_global_budget_spans_ranks_and_aborts_everywhere(faults):
    """The budget is GLOBAL: back-to-back transients on DIFFERENT ranks
    inside one replenish window exhaust the single shared charge, and
    the agreed decision is a clean abort on every rank — never one rank
    dying inside a collective."""
    faults("transient@chunk2@rank2,transient@chunk3@rank0")
    _solvers, loops = _fleet(
        4, Parameter(tpu_chunk=2, tpu_retry_replenish=50, **_BASE))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with pytest.raises(co.CoordinatorAbort, match="budget exhausted"):
            co.LockstepSim(loops).run()


def test_global_budget_replenishes_after_agreed_clean_chunks(faults):
    """Spaced rank-local transients past the replenish window both
    retry (the shared budget refills on AGREED clean boundaries) and
    the fleet completes — the PR 4 replenish semantics, now global."""
    faults("transient@chunk2@rank1,transient@chunk6@rank3")
    solvers, loops = _fleet(
        4, Parameter(tpu_chunk=1, tpu_retry_replenish=3, **_BASE),
        replenish_after=3)
    _quiet_run(loops)
    for s in solvers:
        assert s.t > _BASE["te"]
        assert np.isfinite(np.asarray(s.u)).all()


def test_checkpoint_vote_commits_on_every_rank(faults, tel_on):
    """The agreed checkpoint vote: every rank's on_ckpt commit fires at
    the SAME boundaries (the manifest write itself is rank-0-gated in
    production; the agreement is what this pins), and each commit is a
    `coord` ckpt line."""
    commits = {r: [] for r in range(3)}
    solvers, loops = _fleet(3, Parameter(tpu_chunk=2, **_BASE))
    for r, loop in enumerate(loops):
        loop.ckpt_every = 2
        loop.on_ckpt = lambda s, r=r: commits[r].append(
            int(s[4]))  # nt at the commit point
    _quiet_run(loops)
    assert commits[0]  # the cadence fired at least once
    assert commits[0] == commits[1] == commits[2]  # same agreed boundaries
    votes = [r for r in _records(tel_on, "coord") if r["event"] == "ckpt"]
    assert len(votes) == len(commits[0])


def test_abort_on_unreplenished_budget_is_loud_not_divergent(faults):
    """tpu_coord off under one process keeps the historical path even
    with rank clauses armed (targeting rank 0 = this process): the
    uncoordinated loop's own budget handles it."""
    faults("transient@chunk2@rank0")
    s = NS2DSolver(Parameter(tpu_chunk=2, tpu_coord="off", **_BASE))
    with pytest.warns(UserWarning, match="transient"):
        s.run(progress=False)
    assert s.t > _BASE["te"]


def test_coordinated_pallas_fallback_completes(faults, tel_on):
    """The W_FALLBACK decision through the production seam: an injected
    pallas failure under the 1-rank coordinator swaps to the jnp chunk
    via the agreed word (retry() on the failing rank, mirrored on
    peers) and the run completes — one `coord` fallback line."""
    faults("pallas@chunk2")
    s = NS2DSolver(Parameter(tpu_fuse_phases="on", tpu_solver="fft",
                             tpu_chunk=2, tpu_coord="on", **_BASE))
    assert s._uses_pallas()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        s.run(progress=False)
    assert s._backend == "jnp" and s.t > _BASE["te"]
    assert np.isfinite(np.asarray(s.u)).all()
    falls = [r for r in _records(tel_on, "coord")
             if r["event"] == "fallback"]
    assert len(falls) == 1


# ---------------------------------------------------------------------------
# xlacache wedge hardening (satellite): dead cache path -> warn + uncached
# ---------------------------------------------------------------------------

def test_xlacache_unusable_dir_proceeds_uncached(tmp_path, monkeypatch,
                                                 tel_on):
    """A cache path that cannot be used (here: a FILE where the dir
    should be) degrades to warn-and-run-uncached with a structured
    telemetry `warning` record — never a blocked run."""
    from pampi_tpu.utils import xlacache

    bogus = tmp_path / "cachefile"
    bogus.write_text("not a directory")
    monkeypatch.setenv("PAMPI_XLA_CACHE", str(bogus))
    with pytest.warns(UserWarning, match="UNCACHED"):
        assert xlacache.enable() is None
    warns = _records(tel_on, "warning")
    assert len(warns) == 1 and warns[0]["component"] == "xlacache"
    from tools import check_artifact as ca
    from tools import telemetry_report as tr

    summ = tr.summary(_records(tel_on))
    assert summ["warnings"][0]["component"] == "xlacache"
    assert ca.lint_telemetry_summary(summ, "X") == []


def test_xlacache_hung_probe_times_out(tmp_path, monkeypatch, tel_on):
    """The documented wedge (xlacache.py): storage that HANGS (a dead
    shared mount — os calls block forever) is bounded by the probe
    timeout; the run proceeds uncached instead of wedging the fleet."""
    import time

    from pampi_tpu.utils import xlacache

    monkeypatch.setenv("PAMPI_XLA_CACHE", str(tmp_path / "cache"))
    monkeypatch.setenv("PAMPI_XLA_CACHE_TIMEOUT", "0.2")
    monkeypatch.setattr(xlacache.os, "makedirs",
                        lambda *a, **k: time.sleep(5))
    with pytest.warns(UserWarning, match="UNCACHED"):
        assert xlacache.enable() is None
    warns = _records(tel_on, "warning")
    assert warns and "probe exceeded" in warns[0]["reason"]


# ---------------------------------------------------------------------------
# coord records through the report + artifact lint (schema v5)
# ---------------------------------------------------------------------------

def test_coord_records_render_and_lint(tel_on):
    tm.emit("coord", event="armed", family="ns2d_dist", mode="multihost",
            nranks=4, rank=0)
    tm.emit("coord", event="retry", boundary=3, family="ns2d_dist",
            budget_left=0, t=0.5)
    tm.emit("coord", event="rollback", boundary=7, family="ns2d_dist",
            target_nt=8, t=0.25)
    tm.emit("ckpt", event="elastic_save", path="ck", generation=2,
            mesh=[2, 4], t=0.5, nt=10, rotated=True)
    tm.emit("ckpt", event="elastic_load", path="ck", generation=2,
            mesh_now=[2, 2], t=0.5, nt=10)

    from tools import check_artifact as ca
    from tools import telemetry_report as tr

    recs = _records(tel_on)
    text = tr.render(recs)
    for needle in ("coordinator (agreed global decisions)",
                   "armed: multihost nranks=4", "retry", "rollback",
                   "elastic_save", "elastic_load"):
        assert needle in text, needle
    summ = tr.summary(recs)
    assert summ["coord"]["nranks"] == 4
    assert summ["coord"]["decisions"] == {"retry": 1, "rollback": 1}
    assert summ["ckpt"]["elastic_save"] == 1
    assert summ["ckpt"]["elastic_load"] == 1
    where = "BENCH.telemetry_summary"
    assert ca.lint_telemetry_summary(summ, where) == []
    # gutted blocks are FLAGGED, not waved through
    assert ca.lint_telemetry_summary({**summ, "coord": "zap"}, where)
    assert ca.lint_telemetry_summary({**summ, "coord": {}}, where)
    assert ca.lint_telemetry_summary(
        {**summ, "warnings": [{"reason": "no component"}]}, where)


def test_membership_records_render_and_lint(tel_on):
    """Schema v6: the dead/epoch/shrink kinds and the ckpt ledger events
    render in the coord section's membership subsection, summarize into
    coord.membership, and lint clean — while a legacy (pre-v6) summary
    without the membership key still passes, and a gutted membership
    block is flagged."""
    tm.emit("coord", event="armed", family="ns2d_dist", mode="multihost",
            nranks=2, rank=0)
    tm.emit("dead", ranks=[1], epoch=1, boundary=5, nranks=2,
            watchdog_s=5.0, family="ns2d_dist")
    tm.emit("epoch", epoch=1, nranks=1, survivors=[0])
    tm.emit("shrink", family="ns2d_dist", path="ck", survivors=1,
            generation=3, dead=[1], epoch=1, t=0.5, nt=10)
    tm.emit("ckpt", event="ledger_save", path="ck", generation=3,
            ledger={"budget_spent": 1, "epoch": 0})
    tm.emit("ckpt", event="ledger_restore", path="ck", rebuilt=True,
            ledger={"budget_spent": 1, "epoch": 0})

    from tools import check_artifact as ca
    from tools import telemetry_report as tr

    recs = _records(tel_on)
    # the membership kinds arrived in v6; later schema bumps
    # (v7: the serving plane) must keep rendering them
    assert recs[0]["v"] == tm.SCHEMA_VERSION >= 6
    text = tr.render(recs)
    for needle in ("membership (dead ranks / shrink epochs)",
                   "DEAD rank(s) [1]", "epoch 1: 1 survivor(s) [0]",
                   "shrink-resume [ns2d_dist] on 1 device(s) from "
                   "generation 3", "ledger_save", "ledger_restore"):
        assert needle in text, needle
    summ = tr.summary(recs)
    mem = summ["coord"]["membership"]
    assert mem["dead"][0]["ranks"] == [1]
    assert mem["epochs"][0]["survivors"] == [0]
    assert mem["shrinks"][0]["generation"] == 3
    assert summ["ckpt"]["ledger_save"] == 1
    assert summ["ckpt"]["ledger_restore"] == 1
    where = "BENCH.telemetry_summary"
    assert ca.lint_telemetry_summary(summ, where) == []
    # legacy summaries (no membership subsection) still pass
    legacy = {**summ, "coord": {"nranks": 2, "decisions": {"retry": 1}}}
    assert ca.lint_telemetry_summary(legacy, where) == []
    # gutted membership blocks are FLAGGED, not waved through
    for gutted in ("zap", {"dead": [{"no_ranks": 1}]},
                   {"epochs": "zap"}):
        bad = {**summ, "coord": {**summ["coord"], "membership": gutted}}
        assert ca.lint_telemetry_summary(bad, where), gutted


# ---------------------------------------------------------------------------
# PR 12: the dead-rank matrix — watchdog, membership agreement, shrink
# epoch, elastic shrink-resume, ledger persistence
# ---------------------------------------------------------------------------

def _warm(solvers):
    """Pre-compile each replica's chunk (one discarded functional call)
    so a small watchdog window judges DISPATCHES, not first-call
    compiles."""
    for s in solvers:
        out = s._chunk_fn(*s.initial_state())
        float(out[3])


def test_dead_rank_at_boundary_is_structured(faults, tel_on):
    """A rank that stops answering (dead@chunk3@rank1) is agreed DEAD by
    the survivor within one watchdog window: the same RankDeadError
    names the rank, the survivor set and the incremented shrink epoch,
    and the verdict is a flight-recorder `dead` + `epoch` pair — never a
    hang, never an anonymous timeout."""
    faults("dead@chunk3@rank1")
    _solvers, loops = _fleet(2)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with pytest.raises(co.RankDeadError, match=r"DEAD rank\(s\) \[1\]"):
            co.LockstepSim(loops).run()
    dead = _records(tel_on, "dead")
    assert len(dead) == 1
    assert dead[0]["ranks"] == [1] and dead[0]["epoch"] == 1
    epochs = _records(tel_on, "epoch")
    assert len(epochs) == 1
    assert epochs[0]["survivors"] == [0] and epochs[0]["nranks"] == 1


def test_hang_past_watchdog_is_dead(faults, tel_on, monkeypatch):
    """Mid-dispatch death via hang: the rank never raises — it just
    stops coming back — and ONLY the watchdog can tell. With the hang
    armed past the window, the survivor's collection round times out on
    rank 1 and the membership round declares it dead, exactly like the
    stop-answering shape."""
    monkeypatch.setenv("PAMPI_FAULT_HANG_S", "30")
    faults("hang@chunk3@rank1")
    solvers, loops = _fleet(2)
    _warm(solvers)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with pytest.raises(co.RankDeadError) as excinfo:
            co.LockstepSim(loops, watchdog=0.5).run()
    assert excinfo.value.ranks == [1]
    assert excinfo.value.survivors == [0]
    # the cancel broadcast bounds the abandoned sleeper: give it a beat
    # to unwind its rank_scope before the next test builds solvers
    import time

    time.sleep(0.2)


def test_double_death_names_both(faults, tel_on):
    """Two ranks dying in the same round: the OR-merged dead mask names
    BOTH, the survivors still agree one epoch — degraded-capacity
    accounting never undercounts the loss."""
    faults("dead@chunk3@rank1,dead@chunk3@rank2")
    _solvers, loops = _fleet(3)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with pytest.raises(co.RankDeadError) as excinfo:
            co.LockstepSim(loops).run()
    assert excinfo.value.ranks == [1, 2]
    assert excinfo.value.survivors == [0]
    assert _records(tel_on, "dead")[0]["ranks"] == [1, 2]


def test_death_during_rollback_still_agreed(faults, tel_on):
    """Death AFTER an agreed divergence rollback: the fleet first rolls
    every rank back (the PR 10 protocol), then rank 1 dies on the
    re-drive — the survivor holds the rolled-back state and still gets
    the structured verdict. Protocol states compose; neither eats the
    other's record."""
    faults("nan@step3:u@rank0,dead@chunk5@rank1")
    solvers, loops = _fleet(
        2, Parameter(tpu_chunk=2, tpu_recover_ring=4, **_BASE))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with pytest.raises(co.RankDeadError) as excinfo:
            co.LockstepSim(loops).run()
    assert excinfo.value.ranks == [1]
    rolls = [r for r in _records(tel_on, "coord")
             if r["event"] == "rollback"]
    assert len(rolls) == 1  # the rollback happened BEFORE the death
    assert _records(tel_on, "dead")[0]["epoch"] == 1
    # the survivor's confirmed state is the agreed rolled-back
    # trajectory: finite (the corruption was rolled away pre-death)
    assert np.isfinite(np.asarray(loops[0]._confirmed[0])).all()
    del solvers  # replicas only exist to anchor the loops


def test_dead_rank_shrink_resume_bitwise(faults, tel_on, tmp_path):
    """THE survival contract (ISSUE 12 acceptance): rank 1 dies at chunk
    5 of a 2-rank coordinated run with an agreed elastic checkpoint
    cadence; the survivor raises the structured verdict, shrink-resumes
    from the newest agreed generation onto one device, completes — and
    the final state is BITWISE-identical to a clean run restored from
    the same generation on the same shrunk capacity. The manifest also
    carries the fault ledger (the no-amnesia payload)."""
    from pampi_tpu.fleet.scheduler import shrink_resume
    from pampi_tpu.utils import checkpoint as ckpt

    manifest = str(tmp_path / "ck.elastic")
    faults("dead@chunk5@rank1")
    param = Parameter(tpu_chunk=2, tpu_checkpoint=manifest,
                      tpu_ckpt_elastic=1, **dict(_BASE, te=0.08))
    solvers, loops = [], []
    for r in range(2):
        with fi.rank_scope(r):
            solvers.append(NS2DSolver(param))
    for r, s in enumerate(solvers):
        loop = co.sim_rank_loop(s, "ns2d", 3, r, ckpt_every=2)
        if r == 0:
            def on_ckpt(state, ledger=None, s=s):
                s.u, s.v, s.p = state[0], state[1], state[2]
                s.t, s.nt = float(state[3]), int(state[4])
                ckpt.save_elastic(manifest, s, ledger=ledger)

            on_ckpt.takes_ledger = True
            loop.on_ckpt = on_ckpt
        loops.append(loop)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with pytest.raises(co.RankDeadError) as excinfo:
            co.LockstepSim(loops).run()
    man = ckpt._read_manifest(manifest)
    assert "ledger" in man  # the agreed commit persisted protocol state
    gen = int(man["generation"])
    assert gen >= 1

    import jax

    shrunk = [jax.devices()[0]]
    resumed = shrink_resume(manifest, param, family="ns2d",
                            devices=shrunk, dead=excinfo.value.ranks,
                            epoch=excinfo.value.epoch)
    assert resumed.nt == man["nt"]  # the newest agreed generation
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        resumed.run(progress=False)
    assert resumed.t > 0.08

    oracle = NS2DSolver(param)
    ckpt.load_elastic(manifest, oracle)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        oracle.run(progress=False)
    assert resumed.nt == oracle.nt and resumed.t == oracle.t
    np.testing.assert_array_equal(np.asarray(resumed.u),
                                  np.asarray(oracle.u))
    np.testing.assert_array_equal(np.asarray(resumed.v),
                                  np.asarray(oracle.v))
    np.testing.assert_array_equal(np.asarray(resumed.p),
                                  np.asarray(oracle.p))
    shrinks = _records(tel_on, "shrink")
    assert len(shrinks) == 1 and shrinks[0]["dead"] == [1]
    assert shrinks[0]["generation"] == gen


def test_cli_resume_after_death_policy(tmp_path):
    """The driver's dead-rank policy hook (cli._resume_after_death):
    armed (tpu_dead_resume 1 + elastic manifest on disk) it
    shrink-resumes onto this process's devices and completes the run;
    disarmed it surfaces the structured error and returns None (exit 3
    at the cli)."""
    from pampi_tpu import cli
    from pampi_tpu.utils import checkpoint as ckpt

    manifest = str(tmp_path / "ck.elastic")
    param = Parameter(tpu_chunk=2, tpu_checkpoint=manifest,
                      tpu_ckpt_elastic=1, **_BASE)
    donor = NS2DSolver(param)  # t=0: the resume drives the whole run
    ckpt.save_elastic(manifest, donor,
                      ledger={"budget_spent": 0, "epoch": 1})
    exc = co.RankDeadError(ranks=[1], epoch=1, boundary=3,
                           family="ns2d", survivors=[0])
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        solver = cli._resume_after_death(param, exc, is3d=False)
    assert solver is not None
    assert solver.t > _BASE["te"]
    assert np.isfinite(np.asarray(solver.u)).all()

    assert cli._resume_after_death(
        param.replace(tpu_dead_resume=0), exc, is3d=False) is None
    assert cli._resume_after_death(
        param.replace(tpu_checkpoint=""), exc, is3d=False) is None


def test_ledger_keeps_pallas_broken_verdict(tmp_path):
    """No probation amnesia (ISSUE 12 acceptance): a manifest carrying a
    pallas-broken verdict parks the restored solver on the jnp path at
    load time, pallas_retry latches the dead verdict (no restore, ever),
    and the coordinated loop seeds the spent budget + shrink epoch —
    rank-symmetric because every rank reads the same manifest."""
    from pampi_tpu.models._driver import pallas_retry
    from pampi_tpu.utils import checkpoint as ckpt

    manifest = str(tmp_path / "ck.elastic")
    param = Parameter(tpu_fuse_phases="on", tpu_solver="fft",
                      tpu_chunk=2, **_BASE)
    donor = NS2DSolver(param)
    assert donor._uses_pallas()
    ledger = {"budget_spent": 1, "epoch": 2,
              "pallas": {"broken": True, "on_jnp": True,
                         "backend": "jnp"}}
    ckpt.save_elastic(manifest, donor, ledger=ledger)

    restored = NS2DSolver(param)
    assert restored._backend != "jnp"
    ckpt.load_elastic(manifest, restored)
    assert restored._fault_ledger["pallas"]["broken"] is True
    assert restored._backend == "jnp"  # parked on jnp at load
    hook = pallas_retry(restored, "pressure solve", restore_after=2)
    assert hook._dead  # the verdict survived the restart
    for _ in range(6):
        assert hook.on_clean_chunk() is None  # never restored
    loop = co.sim_rank_loop(restored, "ns2d", 3, 0)
    loop.retry = hook           # the production wiring carries the hook
    assert loop.epoch == 2      # the shrink epoch carried over
    assert loop._budget == 0    # spent charge carried over (of 1)
    assert loop.ledger()["pallas"]["broken"] is True  # round-trips


def test_short_run_end_of_run_manifest_keeps_ledger(tmp_path):
    """Regression (found driving the CLI): a coordinated run that
    completes BEFORE the first checkpoint-cadence boundary never fires
    on_ckpt, so without the completion stash the end-of-run elastic
    write dropped the ledger and `ckpt_fsck --survivors` declared a
    healthy manifest CORRUPT. The agreed-done ledger must reach the
    solver so save_elastic's _fault_ledger fallback persists it."""
    from pampi_tpu.utils import checkpoint as ckpt

    manifest = str(tmp_path / "ck.elastic")
    param = Parameter(tpu_coord="on", tpu_checkpoint=manifest,
                      tpu_ckpt_elastic=1, tpu_chunk=2,
                      tpu_ckpt_every=1000, **_BASE)
    s = NS2DSolver(param)
    s.run(progress=False)
    assert s._fault_ledger is not None  # stashed at loop completion
    ckpt.save_elastic(manifest, s)  # the cli's end-of-run write
    led = json.load(open(manifest)).get("ledger")
    assert led is not None and led["budget_spent"] == 0
    import subprocess
    import sys as _sys

    import tools.ckpt_fsck as fsck_mod

    r = subprocess.run([_sys.executable, fsck_mod.__file__,
                        "--survivors", "1", manifest],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "survivors 1: ok" in r.stdout


def test_fallback_mirrors_onto_transient_rank(faults, tel_on):
    """Review regression: a rank that raised a TRANSIENT in the same
    round a peer took the pallas fallback must STILL mirror the swap —
    guarding on 'did I raise anything' would leave it on the pallas
    program and desynchronize the fleet. Rank 0 pallas-fails and rank 1
    transient-fails at the same boundary; both must end on jnp with
    identical state."""
    faults("pallas@chunk2@rank0,transient@chunk2@rank1")
    param = Parameter(tpu_fuse_phases="on", tpu_solver="fft",
                      tpu_chunk=2, **_BASE)
    solvers = []
    for r in range(2):
        with fi.rank_scope(r):
            solvers.append(NS2DSolver(param))
    loops = []
    for r, s in enumerate(solvers):
        from pampi_tpu.models._driver import pallas_retry

        loop = co.sim_rank_loop(s, "ns2d", 3, r)
        loop.retry = pallas_retry(s, "pressure solve")
        loops.append(loop)
    _quiet_run(loops)
    for r, s in enumerate(solvers):
        assert s._backend == "jnp", f"rank {r} kept the pallas program"
        assert s.t > _BASE["te"]
    assert solvers[0].nt == solvers[1].nt
    np.testing.assert_array_equal(np.asarray(solvers[0].u),
                                  np.asarray(solvers[1].u))
