"""Affinity module tests (reference: assignment-4/src/affinity.c — a manual
toolbox module there; exercised automatically here)."""

import os
import threading

import pytest

from pampi_tpu.utils import affinity

needs_sched = pytest.mark.skipif(
    not hasattr(os, "sched_setaffinity"), reason="no sched_setaffinity"
)


@needs_sched
def test_get_processor_id_is_lowest_in_mask():
    assert affinity.get_processor_id() == min(os.sched_getaffinity(0))


@needs_sched
def test_pin_process_round_trip():
    original = os.sched_getaffinity(0)
    target = min(original)
    try:
        assert affinity.pin_process(target)
        assert os.sched_getaffinity(0) == {target}
        assert affinity.get_processor_id() == target
    finally:
        os.sched_setaffinity(0, original)


@needs_sched
def test_pin_thread_affects_only_calling_thread():
    original = os.sched_getaffinity(0)
    if len(original) < 2:
        pytest.skip("needs >=2 CPUs to observe a per-thread mask")
    cpus = sorted(original)
    seen = {}

    def worker():
        tid = threading.get_native_id()
        seen["pinned"] = affinity.pin_thread(cpus[1])
        seen["thread_mask"] = os.sched_getaffinity(tid)

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    try:
        assert seen["pinned"], "pin_thread refused the target CPU"
        assert seen["thread_mask"] == {cpus[1]}
        # the main thread's mask is untouched
        assert os.sched_getaffinity(threading.get_native_id()) == original
    finally:
        os.sched_setaffinity(0, original)


def test_invalid_cpu_returns_false():
    assert affinity.pin_process(10**6) is False
