"""Runtime-flag tests: PAMPI_DEBUG / PAMPI_VERBOSE (≙ the reference's
-DDEBUG / -DVERBOSE build options, assignment-6/config.mk:72-84)."""

import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent

POISSON_PAR = """\
name       poisson
imax       16
jmax       16
itermax    500
eps        0.001
omg        1.9
tpu_dtype  float64
"""

DCAVITY_PAR = """\
name       dcavity
imax       16
jmax       16
re         10.0
te         0.05
dt         0.02
tau        0.5
itermax    50
eps        0.001
omg        1.7
gamma      0.9
tpu_dtype  float64
"""


def _run(par_text, tmp_path, **flag):
    par = tmp_path / "run.par"
    par.write_text(par_text)
    env = {
        "PATH": f"{os.path.dirname(sys.executable)}:/usr/bin:/bin",
        "HOME": os.environ.get("HOME", "/tmp"),
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": str(REPO),
        **flag,
    }
    proc = subprocess.run(
        [sys.executable, "-m", "pampi_tpu", str(par)],
        cwd=tmp_path, env=env, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    return proc.stdout


def test_debug_prints_per_iteration_residuals(tmp_path):
    out = _run(POISSON_PAR, tmp_path, PAMPI_DEBUG="1")
    lines = [l for l in out.splitlines() if "Residuum:" in l]
    # "<it> Residuum: <res>", 0-based, one per iteration, count == printed it
    assert lines and lines[0].split()[0] == "0"
    it = int(out.split("Walltime")[0].split()[-1])
    assert len(lines) == it
    assert int(lines[-1].split()[0]) == it - 1


def test_debug_off_prints_nothing(tmp_path):
    out = _run(POISSON_PAR, tmp_path)
    assert "Residuum:" not in out


def _parse_time_lines(out):
    """-> [(TIME, TIMESTEP)] from 'TIME <t> , TIMESTEP <dt>' lines."""
    lines = [l for l in out.splitlines() if l.startswith("TIME ")]
    return [(float(l.split()[1]), float(l.split()[4])) for l in lines]


def _assert_time_is_post_increment(pairs):
    """The reference prints TIME after `t += dt` (A5 main.c:52-57,
    A6 main.c:58-62): line i carries the cumulative sum of TIMESTEPs
    through step i — never a leading 0.0."""
    acc = 0.0
    for time_v, dt_v in pairs:
        acc += dt_v
        assert abs(time_v - acc) < 1e-9, (time_v, acc)


def test_verbose_prints_time_per_step_and_no_progress_bar(tmp_path):
    out = _run(DCAVITY_PAR, tmp_path, PAMPI_VERBOSE="1")
    lines = [l for l in out.splitlines() if l.startswith("TIME ")]
    assert lines and ", TIMESTEP " in lines[0]
    _assert_time_is_post_increment(_parse_time_lines(out))
    assert "[" not in out.split("Solution took")[0].split("omega")[-1]


def test_verbose_off_shows_progress_bar(tmp_path):
    out = _run(DCAVITY_PAR, tmp_path)
    assert "TIME " not in out
    assert "[" in out  # the 10-segment progress bar rendered


def test_flags_work_distributed(tmp_path):
    # 8-device virtual mesh (tpu_mesh auto): rank-0 shard prints once per
    # convergence check / step — no per-shard duplication
    import os

    extra = {
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "PAMPI_DEBUG": "1",
        "PAMPI_VERBOSE": "1",
    }
    out = _run(DCAVITY_PAR.replace("imax       16", "imax       16")
               + "tpu_mesh   auto\n", tmp_path, **extra)
    res_lines = [l for l in out.splitlines() if "Residuum:" in l]
    time_lines = [l for l in out.splitlines() if l.startswith("TIME ")]
    assert res_lines and time_lines
    # rank-0-only: TIME lines are unique (no 8x duplicates)
    assert len(time_lines) == len(set(time_lines))
    _assert_time_is_post_increment(_parse_time_lines(out))


def test_xla_cache_enable_and_disable(monkeypatch, tmp_path):
    import jax

    from pampi_tpu.utils import xlacache

    prev = jax.config.jax_compilation_cache_dir
    try:
        monkeypatch.setenv("PAMPI_XLA_CACHE", str(tmp_path / "c"))
        assert xlacache.enable() == str(tmp_path / "c")
        assert (tmp_path / "c").is_dir()
        assert jax.config.jax_compilation_cache_dir == str(tmp_path / "c")
        jax.config.update("jax_compilation_cache_dir", prev)
        monkeypatch.setenv("PAMPI_XLA_CACHE", "0")
        assert xlacache.enable() is None
        # disabled means the config was left untouched
        assert jax.config.jax_compilation_cache_dir == prev
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


DCAVITY3D_PAR = """\
name       dcavity3d
imax       32
jmax       32
kmax       32
re         1000.0
te         0.02
dt         0.02
tau        0.5
itermax    1000
eps        0.001
omg        1.8
gamma      0.9
tpu_dtype  float64
tpu_mesh   1
"""


def test_verbose_prints_solver_config_block_3d(tmp_path):
    """PAMPI_VERBOSE on a 3-D run emits the reference's printConfig block
    (A6 solver.c:36-73) with COMPUTED values matching the captured
    reference-run log (tests/fixtures/dc3b.log: same 32^3 dcavity grid)."""
    out = _run(DCAVITY3D_PAR, tmp_path, PAMPI_VERBOSE="1")
    assert "Parameters for #dcavity3d#" in out
    assert "\tCell size (dx, dy, dz): 0.031250, 0.031250, 0.031250" in out
    assert "\tdt bound: 0.162760" in out  # 0.5*Re/(3/dx^2), the fixture value
    _assert_time_is_post_increment(_parse_time_lines(out))
    # and not there without the flag
    out2 = _run(DCAVITY3D_PAR, tmp_path)
    assert "Parameters for #" not in out2
