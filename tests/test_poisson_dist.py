"""Distributed Poisson: iteration-for-iteration equivalence with the
single-device solver (SURVEY.md §7 stage 4) on the faked 8-device CPU mesh."""

import numpy as np
import pytest

from pampi_tpu.models.poisson import PoissonSolver
from pampi_tpu.models.poisson_dist import DistPoissonSolver
from pampi_tpu.parallel.comm import CartComm
from pampi_tpu.utils.datio import read_matrix
from pampi_tpu.utils.params import Parameter, read_parameter


def test_dist_matches_single_device_small():
    param = Parameter(imax=32, jmax=32, itermax=200, eps=1e-30, omg=1.8)
    single = PoissonSolver(param, problem=2)
    it_s, res_s = single.solve()
    dist = DistPoissonSolver(param, CartComm(ndims=2), problem=2)
    it_d, res_d = dist.solve()
    assert it_d == it_s == 200
    # same trajectory up to reduction order (f64 psum tree vs serial sum)
    assert res_d == pytest.approx(res_s, rel=1e-12)
    np.testing.assert_allclose(
        dist.full_field(), np.asarray(single.p), rtol=0, atol=1e-11
    )


def test_dist_convergence_iteration_parity(reference_dir):
    param = read_parameter(str(reference_dir / "assignment-4" / "poisson.par"))
    single = PoissonSolver(param, problem=2)
    it_s, res_s = single.solve()
    dist = DistPoissonSolver(param, CartComm(ndims=2), problem=2)
    it_d, res_d = dist.solve()
    # convergence-on-residual: identical trajectory => identical (±1) iterations
    assert abs(it_d - it_s) <= 1
    assert res_d < param.eps**2


@pytest.mark.golden
def test_dist_matches_golden_pdat(reference_dir):
    param = read_parameter(str(reference_dir / "assignment-4" / "poisson.par"))
    dist = DistPoissonSolver(param, CartComm(ndims=2), problem=2)
    dist.solve()
    golden = read_matrix(str(reference_dir / "assignment-4" / "p.dat"))
    ours = dist.full_field()
    gi, oi = golden[1:-1, 1:-1], ours[1:-1, 1:-1]
    diff = (oi - oi.mean()) - (gi - gi.mean())
    assert np.sqrt((diff**2).mean()) < 1e-5


def test_dist_resume_matches_one_long_solve():
    # itermax-limited solve + resume must equal one long solve (ghost
    # reconstruction on resume uses Neumann walls, not the analytic init)
    long = DistPoissonSolver(
        Parameter(imax=32, jmax=32, itermax=60, eps=1e-30, omg=1.8), CartComm(ndims=2)
    )
    long.solve()
    short = DistPoissonSolver(
        Parameter(imax=32, jmax=32, itermax=30, eps=1e-30, omg=1.8), CartComm(ndims=2)
    )
    short.solve()
    short.solve()
    np.testing.assert_array_equal(long.full_field(), short.full_field())


def test_dist_1d_mesh_also_works():
    # degenerate mesh shapes must work too (1-D row decomposition, ≙ A4's plan)
    param = Parameter(imax=16, jmax=16, itermax=50, eps=1e-30, omg=1.7)
    single = PoissonSolver(param, problem=2)
    single.solve()
    dist = DistPoissonSolver(param, CartComm(ndims=2, dims=(8, 1)), problem=2)
    dist.solve()
    np.testing.assert_allclose(
        dist.full_field(), np.asarray(single.p), rtol=0, atol=1e-11
    )
