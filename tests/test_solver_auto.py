"""`tpu_solver auto` (utils/dispatch.resolve_solver, VERDICT r4 item 4):
the measured solver matrix — plain -> fft, obstacles -> mg (2-D and 3-D),
ragged -> sor — encoded in dispatch instead of living only in BASELINE.md
prose. The default stays `sor` (reference-trajectory parity); every model
resolves BEFORE its solver-compatibility checks."""

import numpy as np

import jax.numpy as jnp

from pampi_tpu.utils import dispatch
from pampi_tpu.utils.params import Parameter


def test_default_stays_sor():
    assert Parameter().tpu_solver == "sor"


def test_auto_plain_poisson_resolves_fft():
    from pampi_tpu.models.poisson import PoissonSolver

    s = PoissonSolver(Parameter(imax=32, jmax=32, tpu_solver="auto"),
                      problem=2)
    assert s.param.tpu_solver == "fft"
    assert dispatch.last("solver_auto").startswith("fft")
    it, res = s.solve()
    assert int(it) == 1  # the direct solve's contract


def test_auto_obstacle_2d_resolves_mg():
    from pampi_tpu.models.ns2d import NS2DSolver

    s = NS2DSolver(Parameter(
        name="canal", imax=32, jmax=16, re=100.0, te=0.02,
        obstacles="0.3,0.2,0.5,0.4", tpu_solver="auto",
    ))
    assert s.param.tpu_solver == "mg"
    assert dispatch.last("solver_auto").startswith("mg")


def test_auto_obstacle_3d_resolves_mg():
    """3-D obstacles -> mg: the same-session 96³ decomposition measured mg
    at 9.66 vs capped SOR 46.68 ms/step (results/obstacle_mg3d_96.json)."""
    from pampi_tpu.models.ns3d import NS3DSolver

    s = NS3DSolver(Parameter(
        name="dcavity3d", imax=16, jmax=16, kmax=16, re=10.0, te=0.02,
        obstacles="0.3,0.3,0.3,0.6,0.6,0.6", tpu_solver="auto",
    ))
    assert s.param.tpu_solver == "mg"
    assert dispatch.last("solver_auto").startswith("mg")


def test_auto_ragged_dist_resolves_sor():
    """On a grid the mesh does not divide, auto picks sor — the only
    solver the pad-with-mask decomposition supports — instead of raising
    the way an explicit mg/fft would."""
    from pampi_tpu.models.poisson_dist import DistPoissonSolver
    from pampi_tpu.parallel.comm import CartComm

    param = Parameter(imax=17, jmax=33, itermax=30, eps=1e-30,
                      tpu_solver="auto")
    s = DistPoissonSolver(param, CartComm(ndims=2, dims=(4, 2)), problem=2)
    assert s.param.tpu_solver == "sor"
    assert "ragged" in dispatch.last("solver_auto")


def test_auto_plain_dist_resolves_fft_and_matches_explicit():
    from pampi_tpu.models.poisson_dist import DistPoissonSolver
    from pampi_tpu.parallel.comm import CartComm

    comm = CartComm(ndims=2, dims=(2, 4))
    pa = Parameter(imax=32, jmax=32, itermax=100, eps=1e-10,
                   tpu_solver="auto")
    a = DistPoissonSolver(pa, comm, problem=2)
    assert a.param.tpu_solver == "fft"
    a.solve()
    b = DistPoissonSolver(pa.replace(tpu_solver="fft"), comm, problem=2)
    b.solve()
    np.testing.assert_array_equal(a.full_field(), b.full_field())


def test_auto_run_end_to_end_matches_explicit_fft():
    from pampi_tpu.models.ns2d import NS2DSolver

    param = Parameter(
        name="dcavity", imax=16, jmax=16, re=10.0, te=0.05, tau=0.5,
        itermax=200, eps=1e-6, omg=1.7, gamma=0.9, tpu_solver="auto",
    )
    a = NS2DSolver(param)
    a.run(progress=False)
    b = NS2DSolver(param.replace(tpu_solver="fft"))
    b.run(progress=False)
    assert a.nt == b.nt
    np.testing.assert_array_equal(np.asarray(a.u), np.asarray(b.u))
    np.testing.assert_array_equal(np.asarray(a.p), np.asarray(b.p))


def test_explicit_solver_not_touched():
    from pampi_tpu.models.ns2d import NS2DSolver

    s = NS2DSolver(Parameter(name="dcavity", imax=16, jmax=16, re=10.0,
                             te=0.02, tpu_solver="mg"))
    assert s.param.tpu_solver == "mg"
