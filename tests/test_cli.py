"""CLI driver dispatch (pampi_tpu/cli.py): the reference's L6 convention —
parse argv -> read .par -> echo -> run -> write -> walltime — plus the
framework keys' validation paths."""

import numpy as np
import pytest

from pampi_tpu.cli import main


def _par(tmp_path, text):
    p = tmp_path / "run.par"
    p.write_text(text)
    return str(p)


def _run(tmp_path, monkeypatch, text):
    monkeypatch.chdir(tmp_path)
    return main(["pampi", _par(tmp_path, text)])


def test_poisson_dispatch_writes_pdat(tmp_path, monkeypatch, capsys):
    rc = _run(tmp_path, monkeypatch, """
name poisson
imax 32
jmax 32
itermax 500
eps 1e-6
omg 1.8
tpu_mesh 1
""")
    assert rc == 0
    out = capsys.readouterr().out
    assert "Walltime" in out
    assert (tmp_path / "p.dat").exists()
    assert np.loadtxt(tmp_path / "p.dat").shape == (34, 34)


def test_ns2d_dispatch_writes_dat_files(tmp_path, monkeypatch, capsys):
    rc = _run(tmp_path, monkeypatch, """
name dcavity
imax 16
jmax 16
re 10.0
te 0.02
tau 0.5
itermax 100
eps 1e-4
omg 1.8
gamma 0.9
tpu_mesh 1
""")
    assert rc == 0
    assert "Solution took" in capsys.readouterr().out
    assert (tmp_path / "pressure.dat").exists()
    assert (tmp_path / "velocity.dat").exists()


def test_ns3d_dispatch_writes_vtk(tmp_path, monkeypatch, capsys):
    rc = _run(tmp_path, monkeypatch, """
name dcavity3d
imax 8
jmax 8
kmax 8
re 10.0
te 0.02
tau 0.5
itermax 50
eps 1e-3
omg 1.7
gamma 0.9
tpu_mesh 1
tpu_vtk binary
tpu_solver fft
""")
    assert rc == 0
    data = (tmp_path / "dcavity.vtk").read_bytes()
    assert b"BINARY" in data[:100]


def test_bad_solver_and_vtk_rejected(tmp_path, monkeypatch, capsys):
    rc = _run(tmp_path, monkeypatch, "name poisson\ntpu_solver gauss\n")
    assert rc == 1
    assert "tpu_solver" in capsys.readouterr().err
    rc = _run(tmp_path, monkeypatch,
              "name dcavity3d\nkmax 8\ntpu_vtk pdf\n")
    assert rc == 1
    assert "tpu_vtk" in capsys.readouterr().err


def test_unknown_problem_rejected(tmp_path, monkeypatch, capsys):
    rc = _run(tmp_path, monkeypatch, "name vortexstreet\n")
    assert rc == 1
    assert "Unknown problem" in capsys.readouterr().err


def test_obstacles_rejected_for_poisson_and_3d(tmp_path, monkeypatch, capsys):
    rc = _run(tmp_path, monkeypatch,
              "name poisson\nobstacles 0.2,0.2,0.4,0.4\n")
    assert rc == 1
    assert "obstacle" in capsys.readouterr().err
    rc = _run(tmp_path, monkeypatch,
              "name dcavity3d\nkmax 8\nobstacles 0.2,0.2,0.4,0.4\n")
    assert rc == 1


def test_cli_rejects_negative_chunk_and_lookahead(tmp_path, monkeypatch, capsys):
    """Negative tpu_chunk would make every chunk dispatch a no-op (the
    while-cond k < chunk is false from k=0) and spin the driver forever;
    the CLI validates both keys up front like every other tpu_* key."""
    for key in ("tpu_chunk", "tpu_lookahead"):
        rc = _run(tmp_path, monkeypatch, f"""
name poisson
imax 8
jmax 8
itermax 10
eps 0.001
omg 1.7
{key} -1
""")
        assert rc == 1
        assert "tpu_chunk and tpu_lookahead" in capsys.readouterr().err
