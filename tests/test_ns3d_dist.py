"""Distributed NS-3D: exact equality with the single-device solver on 3-D
mesh shapes (the capability assignment-6 leaves as an unfinished skeleton,
completed here; equivalence policy in models/ns3d_dist.py).

Property breadth mirrors the 2-D suite: balanced and extreme/degenerate
meshes (single-axis 8-way splits ≙ the commShift/commExchange surfaces of
assignment-6/src/comm.c:196-244 under maximal seam count), the
communication-avoiding deep-halo knob (`tpu_ca_inner`), obstacles × mesh,
checkpoint/restart × mesh, and canal outflow with the flow axis sharded.
Every comparison is BITWISE (np.testing.assert_array_equal), stricter than
the reference's own MPI parity.
"""

import numpy as np
import pytest

from pampi_tpu.models.ns3d import NS3DSolver
from pampi_tpu.models.ns3d_dist import NS3DDistSolver
from pampi_tpu.parallel.comm import CartComm
from pampi_tpu.utils.params import Parameter, read_parameter

# single-device runs are the oracle for several dist variants: cache them,
# keyed on the FULL parameter set with dist-only knobs normalized away
_single_cache = {}


def _single(param):
    import dataclasses

    key = dataclasses.astuple(param.replace(tpu_ca_inner=1))
    if key not in _single_cache:
        s = NS3DSolver(param)
        s.run(progress=False)
        _single_cache[key] = (s.nt, s.collect())
    return _single_cache[key]


def _compare(param, dims, dist_param=None):
    nt, fields = _single(param)
    dist = NS3DDistSolver(dist_param or param, CartComm(ndims=3, dims=dims))
    dist.run(progress=False)
    assert dist.nt == nt
    for a, b in zip(fields, dist.collect()):
        np.testing.assert_array_equal(a, b)


def _dc16(reference_dir, **kw):
    kw = {"imax": 16, "jmax": 16, "kmax": 16, "te": 0.5, "re": 100.0, **kw}
    return read_parameter(
        str(reference_dir / "assignment-6" / "dcavity.par")
    ).replace(**kw)


@pytest.mark.parametrize("dims", [(2, 2, 2), (1, 2, 4), (4, 2, 1)])
def test_dcavity3d_dist_exact_vs_single(reference_dir, dims):
    _compare(_dc16(reference_dir), dims)


@pytest.mark.parametrize("dims", [(8, 1, 1), (1, 1, 8), (2, 4, 1)])
def test_dcavity3d_dist_extreme_meshes(reference_dir, dims):
    """Single-axis 8-way and flat decompositions: the maximum seam count on
    one axis plus degenerate axes whose both faces are physical walls —
    the commIsBoundary/MPI_PROC_NULL edge cases of the 3-D topology."""
    _compare(_dc16(reference_dir, te=0.2), dims)


@pytest.mark.parametrize("n", [1, 2, 3])
def test_dcavity3d_dist_ca_inner_sweep(reference_dir, n):
    """tpu_ca_inner ∈ {1,2,3}: n fused red-black iterations per depth-2n
    halo exchange. Local extents 8×8×8 keep every n unclamped
    (stencil2d.ca_clamp cap = 4). Bitwise parity requires itermax-capped
    pressure solves with itermax % n == 0 (with a real eps the CA run may
    legitimately stop up to n−1 iterations late — that envelope is covered
    by test_ca_sor.py::test_ns3d_ca_converged_parity); itermax=36 is
    divisible by 1, 2, and 3."""
    base = _dc16(reference_dir, te=0.1, itermax=36, eps=1e-30)
    _compare(base, (2, 2, 2), dist_param=base.replace(tpu_ca_inner=n))


def test_canal3d_dist_exact_vs_single(reference_dir):
    # outflow + uniform-inflow special BC across a full 3-D decomposition
    param = read_parameter(
        str(reference_dir / "assignment-6" / "canal.par")
    ).replace(imax=48, jmax=16, kmax=16, te=0.5)
    _compare(param, (2, 2, 2))


def test_canal3d_dist_flow_axis_fully_sharded(reference_dir):
    """(1,1,8): all 8 shards in a line along the FLOW axis — inflow special
    BC on the first shard only, outflow on the last only, 7 interior seams
    that every F/G/H shift and exchange must cross."""
    param = read_parameter(
        str(reference_dir / "assignment-6" / "canal.par")
    ).replace(imax=48, jmax=16, kmax=16, te=0.2)
    _compare(param, (1, 1, 8))


_OBST = Parameter(
    name="dcavity3d", imax=16, jmax=8, kmax=8,
    xlength=2.0, ylength=1.0, zlength=1.0,
    re=50.0, te=0.06, dt=0.02, tau=0.5, itermax=100, eps=1e-5,
    omg=1.7, gamma=0.9,
    bcLeft=1, bcRight=1, bcBottom=1, bcTop=1, bcFront=1, bcBack=1,
    obstacles="0.5,0.25,0.25,1.0,0.75,0.75",
    tpu_dtype="float64",
)


@pytest.mark.parametrize("dims", [(1, 1, 8), (2, 1, 4)])
def test_obstacle3d_dist_extreme_meshes(dims):
    """Obstacle box spanning shard seams on extreme meshes (the balanced
    meshes are covered in test_obstacle3d.py): shard-sliced global masks ×
    maximal flow-axis seam count."""
    _compare(_OBST, dims)


def test_obstacle3d_dist_with_ca_inner():
    """Obstacles × deep-halo CA blocks: the eps-coefficient masked sweep
    fused n=2 per exchange must match single-device bitwise."""
    _compare(_OBST, (1, 2, 4), dist_param=_OBST.replace(tpu_ca_inner=2))


def test_restart_mid_run_matches_uninterrupted_extreme_mesh(tmp_path,
                                                           reference_dir):
    """Checkpoint at te=0.2, restore into a fresh solver on the SAME
    (1,2,4) mesh with tpu_ca_inner=2, continue to te=0.5: the collected
    fields must equal both the uninterrupted distributed run and the
    single-device oracle bitwise (test_checkpoint.py covers (2,2,2))."""
    from pampi_tpu.utils import checkpoint as ckpt

    dims = (1, 2, 4)
    # itermax-capped solves (itermax % 2 == 0, eps tiny) so the ca_inner=2
    # trajectory is bitwise-reproducible against the single-device oracle
    base = _dc16(reference_dir, itermax=40, eps=1e-30)  # te=0.5
    knobbed = base.replace(tpu_ca_inner=2)

    first = NS3DDistSolver(knobbed.replace(te=0.2),
                           CartComm(ndims=3, dims=dims))
    first.run(progress=False)
    path = str(tmp_path / "ck.npz")
    ckpt.save_checkpoint(path, first)

    resumed = NS3DDistSolver(knobbed, CartComm(ndims=3, dims=dims))
    ckpt.load_checkpoint(path, resumed)
    resumed.run(progress=False)

    nt, fields = _single(base)
    assert resumed.nt == nt
    for a, b in zip(fields, resumed.collect()):
        np.testing.assert_array_equal(a, b)
