"""Distributed NS-3D: exact equality with the single-device solver on 3-D
mesh shapes (the capability assignment-6 leaves as an unfinished skeleton,
completed here; equivalence policy in models/ns3d_dist.py)."""

import numpy as np
import pytest

from pampi_tpu.models.ns3d import NS3DSolver
from pampi_tpu.models.ns3d_dist import NS3DDistSolver
from pampi_tpu.parallel.comm import CartComm
from pampi_tpu.utils.params import read_parameter


def _compare(param, dims):
    single = NS3DSolver(param)
    single.run(progress=False)
    dist = NS3DDistSolver(param, CartComm(ndims=3, dims=dims))
    dist.run(progress=False)
    assert dist.nt == single.nt
    for a, b in zip(single.collect(), dist.collect()):
        np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("dims", [(2, 2, 2), (1, 2, 4), (4, 2, 1)])
def test_dcavity3d_dist_exact_vs_single(reference_dir, dims):
    param = read_parameter(
        str(reference_dir / "assignment-6" / "dcavity.par")
    ).replace(imax=16, jmax=16, kmax=16, te=0.5, re=100.0)
    _compare(param, dims)


def test_canal3d_dist_exact_vs_single(reference_dir):
    # outflow + uniform-inflow special BC across a full 3-D decomposition
    param = read_parameter(
        str(reference_dir / "assignment-6" / "canal.par")
    ).replace(imax=48, jmax=16, kmax=16, te=0.5)
    _compare(param, (2, 2, 2))
