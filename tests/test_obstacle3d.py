"""3-D flag-field obstacle tests (ops/obstacle3d.py) — the 3-D counterpart
of tests/test_obstacle.py: geometry/validation, no-slip surface behavior,
eps-coefficient pressure solve, and the full NS-3D solver with a box."""

import jax.numpy as jnp
import numpy as np
import pytest

from pampi_tpu.ops import obstacle3d as o3
from pampi_tpu.utils.params import Parameter


def test_parse_boxes():
    boxes = o3.parse_obstacles_3d("1,2,3,4,5,6; 9,8,7,6,5,4")
    assert boxes[0] == (1, 2, 3, 4, 5, 6)
    assert boxes[1] == (6, 5, 4, 9, 8, 7)  # min/max normalized
    assert o3.parse_obstacles_3d("") == []
    with pytest.raises(ValueError):
        o3.parse_obstacles_3d("1,2,3,4")  # 2-D rect in a 3-D run


def _fluid(spec, n=12, length=1.0):
    h = length / n
    return o3.build_fluid_3d(n, n, n, h, h, h, spec), h


def test_build_fluid_box_and_ghost_shell():
    fluid, h = _fluid("0.25,0.25,0.25,0.75,0.75,0.75")
    assert not fluid[6, 6, 6]          # box interior is obstacle
    assert fluid[1, 1, 1]              # corner fluid
    assert fluid[0].all() and fluid[-1].all()  # ghost shell always fluid
    assert fluid[:, 0].all() and fluid[:, :, -1].all()


def test_thin_wall_rejected():
    # n=8: cell centers at (i-0.5)/8; (0.4,0.5) catches only x=0.4375 —
    # a 1-cell-thin plate spanning y,z
    with pytest.raises(ValueError):
        _fluid("0.4,0.2,0.2,0.5,0.8,0.8", n=8)


def test_velocity_bc_no_slip_surfaces():
    fluid, h = _fluid("0.25,0.25,0.25,0.75,0.75,0.75")
    m = o3.make_masks_3d(fluid, h, h, h, 1.7, jnp.float64)
    rng = np.random.default_rng(0)
    shape = fluid.shape
    u = jnp.asarray(rng.standard_normal(shape))
    v = jnp.asarray(rng.standard_normal(shape))
    w = jnp.asarray(rng.standard_normal(shape))
    u, v, w = o3.apply_obstacle_velocity_bc_3d(u, v, w, m)
    un, vn, wn = np.asarray(u), np.asarray(v), np.asarray(w)
    f = np.asarray(fluid)
    uf = f & np.roll(f, -1, 2)
    vf = f & np.roll(f, -1, 1)
    wf = f & np.roll(f, -1, 0)
    uf[:, :, -1] = vf[:, -1, :] = wf[-1, :, :] = True
    # obstacle-adjacent faces (exactly one side obstacle) are zeroed
    one_obs_u = ~uf & (f | np.roll(f, -1, 2))
    assert np.abs(un[one_obs_u]).max() == 0.0
    one_obs_v = ~vf & (f | np.roll(f, -1, 1))
    assert np.abs(vn[one_obs_v]).max() == 0.0
    one_obs_w = ~wf & (f | np.roll(f, -1, 0))
    assert np.abs(wn[one_obs_w]).max() == 0.0
    # interpolated wall velocity vanishes: a buried u-face one j-row below a
    # fluid-fluid face holds its negation (horizontal obstacle wall between)
    both_u = ~f & ~np.roll(f, -1, 2)
    north_ff = np.roll(uf, -1, 1)
    sel = both_u & north_ff
    if sel.any():
        np.testing.assert_allclose(
            un[sel], -np.roll(un, -1, 1)[sel], rtol=0, atol=1e-14
        )
    # fluid-fluid faces untouched by the mirror machinery
    rng2 = np.random.default_rng(0)
    u0 = rng2.standard_normal(shape)
    np.testing.assert_array_equal(un[uf & (np.arange(shape[2]) < shape[2] - 1)],
                                  u0[uf & (np.arange(shape[2]) < shape[2] - 1)])


def test_pressure_solve_converges_and_respects_neumann():
    fluid, h = _fluid("0.25,0.25,0.25,0.75,0.75,0.75", n=12)
    m = o3.make_masks_3d(fluid, h, h, h, 1.7, jnp.float64)
    n = 12
    solve = o3.make_obstacle_solver_fn_3d(
        n, n, n, h, h, h, 1e-8, 20000, m, jnp.float64
    )
    rng = np.random.default_rng(1)
    rhs = rng.standard_normal((n + 2, n + 2, n + 2))
    # Neumann-compatible RHS: zero mean over fluid cells
    fi = np.asarray(m.p_mask, bool)
    rhs_i = rhs[1:-1, 1:-1, 1:-1]
    rhs_i[fi] -= rhs_i[fi].mean()
    rhs_i[~fi] = 0.0
    rhs[1:-1, 1:-1, 1:-1] = rhs_i
    p0 = jnp.zeros((n + 2, n + 2, n + 2))
    p, res, it = solve(p0, jnp.asarray(rhs))
    assert float(res) < 1e-16
    assert 0 < int(it) < 20000
    # obstacle cells never updated
    pn = np.asarray(p)[1:-1, 1:-1, 1:-1]
    assert np.abs(pn[~fi]).max() == 0.0


def test_uniform_no_obstacle_matches_plain_solver():
    """Empty spec ⇒ eps coefficients all 1 ⇒ identical update to the plain
    3-D red-black solve (jnp path), step for step."""
    from pampi_tpu.models.ns3d import make_pressure_solve_3d

    n, h = 8, 1.0 / 8
    fluid = o3.build_fluid_3d(n, n, n, h, h, h, "")
    m = o3.make_masks_3d(fluid, h, h, h, 1.7, jnp.float64)
    solve_o = o3.make_obstacle_solver_fn_3d(n, n, n, h, h, h, 1e-6, 40, m,
                                            jnp.float64)
    solve_p = make_pressure_solve_3d(n, n, n, h, h, h, 1.7, 1e-6, 40,
                                     jnp.float64, backend="jnp")
    rng = np.random.default_rng(2)
    rhs = jnp.asarray(rng.standard_normal((n + 2, n + 2, n + 2)))
    p0 = jnp.zeros((n + 2, n + 2, n + 2))
    po, ro, io_ = solve_o(p0, rhs)
    pp, rp, ip = solve_p(p0, rhs)
    assert int(io_) == int(ip)
    np.testing.assert_allclose(np.asarray(po), np.asarray(pp),
                               rtol=0, atol=1e-12)


@pytest.mark.slow
def test_dcavity3d_with_box_runs_and_is_divergence_free():
    """Closed lid-driven box + obstacle: all-NOSLIP walls keep the Neumann
    problem COMPATIBLE (zero net boundary flux), so the pressure solve
    converges and the projected field must be discretely divergence-free in
    the fluid. (An OUTFLOW canal is globally mass-imbalanced at early steps
    — its residual floors at the incompatibility on ANY solver, reference
    included, so it cannot serve as this check.)"""
    param = Parameter(
        name="dcavity3d", imax=16, jmax=16, kmax=16,
        xlength=1.0, ylength=1.0, zlength=1.0,
        re=100.0, te=0.3, dt=0.02, tau=0.5, itermax=2000, eps=1e-6,
        omg=1.7, gamma=0.9,
        bcLeft=1, bcRight=1, bcBottom=1, bcTop=1, bcFront=1, bcBack=1,
        obstacles="0.25,0.25,0.25,0.6,0.6,0.6",
        tpu_dtype="float64",
    )
    from pampi_tpu.models.ns3d import NS3DSolver

    s = NS3DSolver(param, dtype=jnp.float64)
    assert s.masks is not None and s.masks.any_obstacle
    s.run(progress=False)
    assert s.nt > 0
    u, v, w = np.asarray(s.u), np.asarray(s.v), np.asarray(s.w)
    f = np.asarray(s.masks.fluid, bool)
    g = s.grid
    # velocities on obstacle faces are zero after the run
    uf = np.asarray(s.masks.u_face, bool)
    assert np.abs(u[1:-1, 1:-1, 1:-1][~uf[1:-1, 1:-1, 1:-1]]).max() < 1e-12
    # divergence over interior fluid cells is solver-converged small
    div = (
        (u[1:-1, 1:-1, 1:-1] - u[1:-1, 1:-1, :-2]) / g.dx
        + (v[1:-1, 1:-1, 1:-1] - v[1:-1, :-2, 1:-1]) / g.dy
        + (w[1:-1, 1:-1, 1:-1] - w[:-2, 1:-1, 1:-1]) / g.dz
    )
    interior_fluid = f[1:-1, 1:-1, 1:-1]
    assert np.sqrt((div[interior_fluid] ** 2).mean()) < 1e-3


def test_fft_rejected_mg_accepted_with_obstacles():
    """fft structurally cannot solve flag fields; mg can since round 4
    (make_obstacle_mg_solve_3d)."""
    from pampi_tpu.models.ns3d import NS3DSolver

    param = Parameter(
        name="canal3d", imax=8, jmax=8, kmax=8, obstacles="0.2,0.2,0.2,0.6,0.6,0.6",
        tpu_solver="fft", tpu_dtype="float64",
    )
    with pytest.raises(ValueError):
        NS3DSolver(param, dtype=jnp.float64)
    NS3DSolver(param.replace(tpu_solver="mg"), dtype=jnp.float64)  # builds


@pytest.mark.slow
def test_obstacle3d_dist_exact_vs_single():
    """Distributed 3-D obstacles: the shard-sliced global masks + CA
    eps-coefficient solve must reproduce the single-device trajectory
    bitwise on any mesh shape (the 2-D guarantee, carried to 3-D)."""
    from pampi_tpu.models.ns3d import NS3DSolver
    from pampi_tpu.models.ns3d_dist import NS3DDistSolver
    from pampi_tpu.parallel.comm import CartComm

    param = Parameter(
        name="dcavity3d", imax=16, jmax=8, kmax=8,
        xlength=2.0, ylength=1.0, zlength=1.0,
        re=50.0, te=0.06, dt=0.02, tau=0.5, itermax=100, eps=1e-5,
        omg=1.7, gamma=0.9,
        bcLeft=1, bcRight=1, bcBottom=1, bcTop=1, bcFront=1, bcBack=1,
        obstacles="0.5,0.25,0.25,1.0,0.75,0.75",
        tpu_dtype="float64",
    )
    single = NS3DSolver(param, dtype=jnp.float64)
    single.run(progress=False)
    for dims in [(2, 2, 2), (1, 2, 4)]:
        dist = NS3DDistSolver(param, CartComm(ndims=3, dims=dims))
        dist.run(progress=False)
        assert dist.nt == single.nt, dims
        for a, b in zip(single.collect(), dist.collect()):
            np.testing.assert_array_equal(a, b)


def test_obstacle3d_dist_rejects_fft_accepts_mg():
    """fft structurally cannot solve flag fields on a mesh; mg can since
    round 4 (make_dist_obstacle_mg_solve_3d)."""
    from pampi_tpu.models.ns3d_dist import NS3DDistSolver
    from pampi_tpu.parallel.comm import CartComm

    param = Parameter(
        name="dcavity3d", imax=8, jmax=8, kmax=8,
        obstacles="0.2,0.2,0.2,0.6,0.6,0.6", tpu_solver="fft",
        tpu_dtype="float64",
    )
    with pytest.raises(ValueError, match="obstacle"):
        NS3DDistSolver(param, CartComm(ndims=3))
    NS3DDistSolver(param.replace(tpu_solver="mg"), CartComm(ndims=3))


def test_dist_obstacle_mg_3d_matches_single_device():
    """NS-3D distributed obstacle-MG vs the single-device 3-D obstacle MG:
    same physics on a 3-D mesh (the 2-D guarantee carried to 3-D)."""
    from pampi_tpu.models.ns3d import NS3DSolver
    from pampi_tpu.models.ns3d_dist import NS3DDistSolver
    from pampi_tpu.parallel.comm import CartComm

    param = Parameter(
        name="dcavity3d", imax=16, jmax=16, kmax=16, re=10.0, te=0.02,
        tau=0.5, itermax=500, eps=1e-3, omg=1.7, gamma=0.9,
        obstacles="0.35,0.35,0.35,0.65,0.65,0.65", tpu_solver="mg",
    )
    a = NS3DSolver(param)
    a.run(progress=False)
    ac = a.collect()
    for dims in [(2, 2, 2), (1, 2, 4)]:
        b = NS3DDistSolver(param, CartComm(ndims=3, dims=dims))
        b.run(progress=False)
        assert a.nt == b.nt, dims
        for fa, fb in zip(ac, b.collect()):
            # the distributed residual is a psum of shard-local sums, so a
            # convergence-gated cycle can flip at the eps threshold; fields
            # then agree at the per-solve tolerance (eps=1e-3), not tighter
            np.testing.assert_allclose(np.asarray(fa), fb, rtol=0, atol=5e-4)


@pytest.mark.parametrize("n_inner", [1, 2])
def test_masked_kernel_matches_jnp_trajectory(n_inner):
    """The flag-masked 3-D Pallas kernel (interpret mode) must reproduce the
    jnp eps-coefficient trajectory — same structure as the uniform kernel's
    parity test (tests/test_sor3d_pallas.py)."""
    from pampi_tpu.models.ns3d import checkerboard_mask_3d, neumann_faces_3d
    from pampi_tpu.ops.sor3d_pallas import (
        make_rb_iter_tblock_3d,
        pad_array_3d,
        unpad_array_3d,
    )

    DT = jnp.float32
    K, J, I = 10, 12, 14
    dx, dy, dz, omega = 1.0 / I, 1.0 / J, 1.0 / K, 1.7
    fluid = o3.build_fluid_3d(I, J, K, 1.0 / I, 1.0 / J, 1.0 / K,
                              "0.2,0.2,0.2,0.6,0.6,0.6")
    m = o3.make_masks_3d(fluid, dx, dy, dz, omega, DT)

    rng = np.random.default_rng(7)
    p0 = jnp.asarray(rng.standard_normal((K + 2, J + 2, I + 2)), DT)
    rhs = jnp.asarray(rng.standard_normal((K + 2, J + 2, I + 2)), DT)

    odd = checkerboard_mask_3d(K, J, I, 1, DT)
    even = checkerboard_mask_3d(K, J, I, 0, DT)
    idx2, idy2, idz2 = 1.0 / dx**2, 1.0 / dy**2, 1.0 / dz**2

    def one(p, rhs):
        p, r0 = o3.sor_pass_obstacle_3d(p, rhs, odd, m, idx2, idy2, idz2)
        p, r1 = o3.sor_pass_obstacle_3d(p, rhs, even, m, idx2, idy2, idz2)
        return neumann_faces_3d(p), r0 + r1

    rb, bk = make_rb_iter_tblock_3d(
        I, J, K, dx, dy, dz, omega, DT, n_inner=n_inner, interpret=True,
        fluid=np.asarray(m.fluid),
    )
    pp = pad_array_3d(p0, bk, n_inner)
    rp = pad_array_3d(rhs, bk, n_inner)

    want = p0
    for _outer in range(3):
        pp, res = rb(pp, rp)
        wres = None
        for _ in range(n_inner):
            want, wres = one(want, rhs)
        got = unpad_array_3d(pp, K, J, I, n_inner)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=0, atol=5e-5)
        assert float(res) == pytest.approx(float(wres), rel=1e-4)


def test_obstacle_solver_fn_pallas_backend_matches_jnp():
    """make_obstacle_solver_fn_3d(backend='pallas', interpret via CPU) and
    the jnp path must agree on the converged field at n_inner=1."""
    n = 10
    hh = 1.0 / n
    fluid = o3.build_fluid_3d(n, n, n, hh, hh, hh, "0.3,0.3,0.3,0.7,0.7,0.7")
    m = o3.make_masks_3d(fluid, hh, hh, hh, 1.7, jnp.float32)
    rng = np.random.default_rng(3)
    rhs = rng.standard_normal((n + 2, n + 2, n + 2)).astype(np.float32)
    fi = np.asarray(m.p_mask, bool)
    ri = rhs[1:-1, 1:-1, 1:-1]
    ri[fi] -= ri[fi].mean()
    ri[~fi] = 0.0
    rhs[1:-1, 1:-1, 1:-1] = ri
    p0 = jnp.zeros((n + 2, n + 2, n + 2), jnp.float32)
    s_jnp = o3.make_obstacle_solver_fn_3d(n, n, n, hh, hh, hh, 1e-4, 500, m,
                                          jnp.float32, backend="jnp")
    s_pal = o3.make_obstacle_solver_fn_3d(n, n, n, hh, hh, hh, 1e-4, 500, m,
                                          jnp.float32, backend="pallas",
                                          n_inner=1)
    pj, rj, ij = s_jnp(p0, jnp.asarray(rhs))
    pp_, rp_, ip_ = s_pal(p0, jnp.asarray(rhs))
    assert int(ij) == int(ip_)
    np.testing.assert_allclose(np.asarray(pp_), np.asarray(pj),
                               rtol=0, atol=1e-4)


def test_obstacle_mg_3d_matches_sor_physics():
    """tpu_solver mg on a 3-D obstacle config reproduces the obstacle-SOR
    run's physics (both converge each pressure solve to the same eps) —
    the 3-D twin of test_obstacle_mg_in_ns2d_step."""
    from pampi_tpu.models.ns3d import NS3DSolver

    param = Parameter(
        name="dcavity3d", imax=16, jmax=16, kmax=16, re=10.0, te=0.05,
        tau=0.5, itermax=500, eps=1e-3, omg=1.7, gamma=0.9,
        obstacles="0.35,0.35,0.35,0.65,0.65,0.65",
    )
    a = NS3DSolver(param)
    a.run(progress=False)
    b = NS3DSolver(param.replace(tpu_solver="mg"))
    b.run(progress=False)
    assert a.nt == b.nt > 1
    np.testing.assert_allclose(np.asarray(a.u), np.asarray(b.u),
                               rtol=0, atol=2e-4)
    np.testing.assert_allclose(np.asarray(a.w), np.asarray(b.w),
                               rtol=0, atol=2e-4)


def test_obstacle_mg_3d_converges_fast():
    """The 3-D obstacle V-cycle with the exact dense bottom reaches the
    residual floor in O(few) cycles where obstacle SOR needs O(10^3)
    sweeps."""
    import jax

    from pampi_tpu.ops import obstacle3d as o3
    from pampi_tpu.ops.multigrid import make_obstacle_mg_solve_3d

    K = J = I = 32
    dx = dy = dz = 1.0 / I
    fluid = o3.build_fluid_3d(I, J, K, dx, dy, dz, "0.3,0.3,0.3,0.6,0.6,0.6")
    m = o3.make_masks_3d(fluid, dx, dy, dz, 1.7, jnp.float64)
    rng = np.random.default_rng(3)
    fl = np.asarray(m.p_mask) > 0
    r = rng.standard_normal((K, J, I)) * fl
    r[fl] -= r[fl].mean()  # Neumann-compatible over the (connected) fluid
    rhs = jnp.zeros((K + 2, J + 2, I + 2), jnp.float64)
    rhs = rhs.at[1:-1, 1:-1, 1:-1].set(jnp.asarray(r, jnp.float64))
    p0 = jnp.zeros_like(rhs)
    mg = jax.jit(make_obstacle_mg_solve_3d(I, J, K, dx, dy, dz, 1e-8, 100,
                                           m, jnp.float64))
    p, res, it = mg(p0, rhs)
    assert float(res) < 1e-16 or int(it) < 40
    assert int(it) <= 40

    solve_sor = jax.jit(o3.make_obstacle_solver_fn_3d(
        I, J, K, dx, dy, dz, 1e-8, 100000, m, jnp.float64, backend="jnp"))
    ps, _, it_s = solve_sor(p0, rhs)
    assert int(it_s) > 20 * int(it)
    mask = np.asarray(m.p_mask) > 0
    a = np.asarray(p)[1:-1, 1:-1, 1:-1]
    b = np.asarray(ps)[1:-1, 1:-1, 1:-1]
    d = (a - a[mask].mean()) - (b - b[mask].mean())
    assert np.abs(d[mask]).max() < 1e-6
