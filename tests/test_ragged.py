"""Ragged (non-divisible) decomposition: grid-aware dims_create + the
pad-with-mask policy (round-4 capability close of VERDICT item 2).

The reference runs ANY grid on ANY rank count via the remainder-spread
sizeOfRank (assignment-6/src/comm.c:19-22); uniform XLA shardings instead
(a) pick a factorization the grid divides when one exists (grid-aware
dims_create) and (b) otherwise ceil-divide into uniform blocks whose
trailing dead cells the global-coordinate masks exclude from updates,
residuals, walls and collection."""

import numpy as np
import pytest

from pampi_tpu.models.poisson import PoissonSolver
from pampi_tpu.models.poisson_dist import DistPoissonSolver
from pampi_tpu.parallel.comm import CartComm, dims_create
from pampi_tpu.utils.params import Parameter


def test_dims_create_grid_aware():
    # blind MPI_Dims_create stays non-increasing-balanced
    assert dims_create(8, 2) == (4, 2)
    assert dims_create(8, 3) == (2, 2, 2)
    # the reference's own canal.par (200x50) on 8 devices: the blind (4,2)
    # would need 50 % 4 == 0 — grid-aware picks the feasible (2,4)
    assert dims_create(8, 2, (50, 200)) == (2, 4)
    # canal3d.par (200x50x50): a fully-divisible factorization is chosen
    dims = dims_create(8, 3, (50, 50, 200))
    assert all(e % p == 0 for e, p in zip((50, 50, 200), dims))
    # perfect ties keep the round-3 ordering (no churn on square grids)
    assert dims_create(8, 2, (4096, 4096)) == (4, 2)
    assert dims_create(8, 3, (128, 128, 128)) == (2, 2, 2)


def test_local_shape_ragged_ceil():
    comm = CartComm(ndims=2, dims=(4, 2))
    assert comm.local_shape((52, 52)) == (13, 26)
    with pytest.raises(ValueError):
        comm.local_shape((50, 50))
    assert comm.local_shape((50, 50), ragged=True) == (13, 25)


@pytest.mark.parametrize("dims,shape", [
    ((4, 2), (50, 50)),   # ragged along j (13*4 = 52 > 50)
    ((2, 4), (50, 54)),   # ragged along i (14*4 = 56 > 54)
    ((8, 1), (18, 16)),   # ragged 1-D rows incl. a nearly-dead last shard
])
def test_ragged_poisson_matches_single_device(dims, shape):
    jmax, imax = shape
    param = Parameter(imax=imax, jmax=jmax, itermax=120, eps=1e-30, omg=1.8)
    single = PoissonSolver(param, problem=2)
    it_s, res_s = single.solve()
    dist = DistPoissonSolver(param, CartComm(ndims=2, dims=dims), problem=2)
    assert dist.ragged
    it_d, res_d = dist.solve()
    assert it_d == it_s
    assert res_d == pytest.approx(res_s, rel=1e-12)
    np.testing.assert_allclose(
        dist.full_field(), np.asarray(single.p), rtol=0, atol=1e-11
    )


def test_ragged_resume_matches_one_long_solve():
    param = dict(imax=18, jmax=18, eps=1e-30, omg=1.7)
    long = DistPoissonSolver(
        Parameter(itermax=60, **param), CartComm(ndims=2, dims=(4, 2))
    )
    long.solve()
    short = DistPoissonSolver(
        Parameter(itermax=30, **param), CartComm(ndims=2, dims=(4, 2))
    )
    short.solve()
    short.solve()
    np.testing.assert_array_equal(long.full_field(), short.full_field())


def test_ragged_refuses_structured_direct_solvers():
    with pytest.raises(ValueError, match="ragged"):
        DistPoissonSolver(
            Parameter(imax=50, jmax=50, tpu_solver="mg"),
            CartComm(ndims=2, dims=(4, 2)),
        )


@pytest.mark.parametrize("dims,shape", [
    ((4, 2), (18, 20)),   # ragged along j
    ((2, 4), (20, 18)),   # ragged along i
    ((8, 1), (18, 16)),   # wall ghost row opens a fully-dead shard
])
def test_ragged_ns2d_dcavity_matches_single(reference_dir, dims, shape):
    from pampi_tpu.models.ns2d import NS2DSolver
    from pampi_tpu.models.ns2d_dist import NS2DDistSolver
    from pampi_tpu.utils.params import read_parameter

    jmax, imax = shape
    param = read_parameter(
        str(reference_dir / "assignment-5" / "sequential" / "dcavity.par")
    ).replace(te=0.02, imax=imax, jmax=jmax, itermax=60)
    single = NS2DSolver(param)
    single.run(progress=False)
    dist = NS2DDistSolver(param, CartComm(ndims=2, dims=dims))
    assert dist.ragged
    dist.run(progress=False)
    assert dist.nt == single.nt > 1
    ud, vd, pd = dist.fields()
    np.testing.assert_array_equal(np.asarray(single.u), ud)
    np.testing.assert_array_equal(np.asarray(single.v), vd)
    np.testing.assert_array_equal(np.asarray(single.p), pd)


def test_ragged_ns2d_canal_matches_single(reference_dir):
    """Canal exercises OUTFLOW walls + the global-y parabolic inflow on a
    ragged mesh (50 rows over 4 j-shards)."""
    from pampi_tpu.models.ns2d import NS2DSolver
    from pampi_tpu.models.ns2d_dist import NS2DDistSolver
    from pampi_tpu.utils.params import read_parameter

    param = read_parameter(
        str(reference_dir / "assignment-5" / "sequential" / "canal.par")
    ).replace(te=0.2, itermax=40)
    single = NS2DSolver(param)
    single.run(progress=False)
    dist = NS2DDistSolver(param, CartComm(ndims=2, dims=(4, 2)))
    assert dist.ragged  # 50 % 4 != 0
    dist.run(progress=False)
    assert dist.nt == single.nt > 1
    ud, vd, pd = dist.fields()
    np.testing.assert_array_equal(np.asarray(single.u), ud)
    np.testing.assert_array_equal(np.asarray(single.v), vd)
    np.testing.assert_array_equal(np.asarray(single.p), pd)


def test_ragged_ns2d_refuses_direct_solvers_accepts_obstacles(reference_dir):
    """mg/fft still need divisible extents (coarsening/diagonalization);
    obstacles COMPOSE with ragged since round 5 (VERDICT r4 item 2)."""
    from pampi_tpu.models.ns2d_dist import NS2DDistSolver
    from pampi_tpu.utils.params import read_parameter

    param = read_parameter(
        str(reference_dir / "assignment-5" / "sequential" / "dcavity.par")
    ).replace(imax=18, jmax=18, tpu_solver="fft")
    with pytest.raises(ValueError, match="ragged"):
        NS2DDistSolver(param, CartComm(ndims=2, dims=(4, 2)))
    # obstacle + sor on the same ragged mesh builds
    NS2DDistSolver(
        param.replace(tpu_solver="sor", obstacles="0.3,0.3,0.6,0.6"),
        CartComm(ndims=2, dims=(4, 2)),
    )


def test_ragged_ns2d_obstacle_matches_single(reference_dir):
    """The north-star composition (VERDICT r4 item 2): a flag-masked canal
    on a mesh the grid does NOT divide tracks the single-device obstacle
    run exactly — the reference's remainder ranks run the identical solver
    (assignment-6/src/comm.c:19-22)."""
    from pampi_tpu.models.ns2d import NS2DSolver
    from pampi_tpu.models.ns2d_dist import NS2DDistSolver
    from pampi_tpu.utils.params import Parameter

    param = Parameter(
        name="canal_obstacle", imax=66, jmax=34, xlength=4.0, ylength=1.0,
        re=100.0, te=0.05, tau=0.5, itermax=120, eps=1e-4, omg=1.7,
        gamma=0.9, bcLeft=3, bcRight=3, bcBottom=1, bcTop=1,
        obstacles="1.0,0.3,1.5,0.7",
    )
    single = NS2DSolver(param)
    single.run(progress=False)
    for dims in [(4, 2), (2, 4)]:
        dist = NS2DDistSolver(param, CartComm(ndims=2, dims=dims))
        assert dist.ragged  # 34 % 4 != 0 / 66 % 4 != 0
        dist.run(progress=False)
        assert dist.nt == single.nt > 1
        ud, vd, pd = dist.fields()
        np.testing.assert_array_equal(np.asarray(single.u), ud)
        np.testing.assert_array_equal(np.asarray(single.v), vd)
        np.testing.assert_array_equal(np.asarray(single.p), pd)


def test_ragged_obsdist_kernel_matches_jnp_ca():
    """The per-shard flag-masked kernel at ragged halo depth (2n+1,
    interpret mode) against the jnp CA path — the ragged Pallas fast path
    is bitwise (same CA discipline, VERDICT r4 item 2)."""
    import jax
    import jax.numpy as jnp

    from pampi_tpu.ops import obstacle as obst
    from pampi_tpu.parallel.comm import halo_exchange
    from jax.sharding import PartitionSpec as P

    imax, jmax = 33, 18  # (4, 2) mesh: jl=5 (4*5=20), il=17 (2*17=34)
    dx, dy = 4.0 / imax, 2.0 / jmax
    fluid = obst.build_fluid(imax, jmax, dx, dy, "1.2,0.5,2.0,1.1")
    m = obst.make_masks(fluid, dx, dy, 1.7, jnp.float64)
    dims = (4, 2)
    comm = CartComm(ndims=2, dims=dims)
    jl, il = -(-jmax // dims[0]), -(-imax // dims[1])
    assert jl * dims[0] != jmax and il * dims[1] != imax
    rng = np.random.default_rng(11)
    p0 = jnp.asarray(rng.standard_normal((jmax + 2, imax + 2)))
    rhs = jnp.asarray(rng.standard_normal((jmax + 2, imax + 2)))
    # dead-cell padding of the global fields (ceil-padded stacked layout)
    pj, pi = jl * dims[0] + 2, il * dims[1] + 2
    p0 = jnp.zeros((pj, pi), p0.dtype).at[: jmax + 2, : imax + 2].set(p0)
    rhs = jnp.zeros((pj, pi), rhs.dtype).at[: jmax + 2, : imax + 2].set(rhs)

    outs = {}
    for backend in ("auto", "pallas"):
        solve, used = obst.make_dist_obstacle_solver(
            comm, imax, jmax, jl, il, dx, dy, 1e-12, 40, m, jnp.float64,
            ca_n=2, sor_inner=2, backend=backend, ragged=True,
        )
        assert used == (backend == "pallas")

        def kern(p_int, rhs_int, _solve=solve):
            pe = halo_exchange(jnp.pad(p_int, 1), comm)
            re = halo_exchange(jnp.pad(rhs_int, 1), comm)
            p, res, it = _solve(pe, re)
            return p[1:-1, 1:-1], res, it

        spec = P("j", "i")
        f = jax.jit(comm.shard_map(
            kern, in_specs=(spec, spec), out_specs=(spec, P(), P()),
            check_vma=False,
        ))
        p_out, res, it = f(p0[1:-1, 1:-1], rhs[1:-1, 1:-1])
        outs[backend] = (np.asarray(p_out), int(it), float(res))

    assert outs["auto"][1] == outs["pallas"][1] == 40
    np.testing.assert_array_equal(outs["auto"][0], outs["pallas"][0])
    np.testing.assert_allclose(outs["auto"][2], outs["pallas"][2],
                               rtol=1e-12)


@pytest.mark.parametrize("dims,shape", [
    ((4, 2, 1), (10, 10, 12)),  # ragged along k
    ((1, 2, 4), (10, 10, 18)),  # ragged along i
])
def test_ragged_ns3d_dcavity_matches_single(reference_dir, dims, shape):
    from pampi_tpu.models.ns3d import NS3DSolver
    from pampi_tpu.models.ns3d_dist import NS3DDistSolver
    from pampi_tpu.parallel.comm import CartComm
    from pampi_tpu.utils.params import read_parameter

    kmax, jmax, imax = shape
    param = read_parameter(
        str(reference_dir / "assignment-6" / "dcavity.par")
    ).replace(te=2.5, imax=imax, jmax=jmax, kmax=kmax, itermax=40)
    single = NS3DSolver(param)
    single.run(progress=False)
    dist = NS3DDistSolver(param, CartComm(ndims=3, dims=dims))
    assert dist.ragged
    dist.run(progress=False)
    assert dist.nt == single.nt > 1
    for a, b in zip(single.collect(), dist.collect()):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-11, rtol=0
        )


def test_ragged_ns3d_canal_matches_single(reference_dir):
    from pampi_tpu.models.ns3d import NS3DSolver
    from pampi_tpu.models.ns3d_dist import NS3DDistSolver
    from pampi_tpu.parallel.comm import CartComm
    from pampi_tpu.utils.params import read_parameter

    param = read_parameter(
        str(reference_dir / "assignment-6" / "canal.par")
    ).replace(te=1.0, imax=18, jmax=10, kmax=10, itermax=30)
    single = NS3DSolver(param)
    single.run(progress=False)
    dist = NS3DDistSolver(param, CartComm(ndims=3, dims=(2, 1, 4)))
    assert dist.ragged
    dist.run(progress=False)
    assert dist.nt == single.nt > 1
    for a, b in zip(single.collect(), dist.collect()):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-11, rtol=0
        )


def test_canal3d_par_runs_under_auto_mesh(reference_dir):
    """canal3d.par (200x50x50) auto-meshes to a feasible factorization on
    the 8-device pool (VERDICT round-3 'Done' criterion)."""
    from pampi_tpu.models.ns3d_dist import NS3DDistSolver
    from pampi_tpu.utils.params import read_parameter

    param = read_parameter(str(reference_dir / "assignment-6" / "canal.par"))
    solver = NS3DDistSolver(param.replace(te=0.0))
    assert all(
        e % p == 0
        for e, p in zip((50, 50, 200), solver.comm.dims)
    ), solver.comm.dims
    assert not solver.ragged


def test_canal_par_runs_under_auto_mesh(reference_dir):
    """The VERDICT round-3 repro: the reference's committed canal.par
    (200x50) failed under tpu_mesh auto on 8 devices. Grid-aware auto now
    picks a feasible mesh and the run proceeds."""
    from pampi_tpu.models.ns2d_dist import NS2DDistSolver
    from pampi_tpu.utils.params import read_parameter

    param = read_parameter(
        str(reference_dir / "assignment-5" / "sequential" / "canal.par")
    ).replace(te=0.05, itermax=20)
    solver = NS2DDistSolver(param)  # auto mesh from the 8-device CPU pool
    assert all(
        e % p == 0 for e, p in zip((50, 200), solver.comm.dims)
    ), solver.comm.dims
    solver.run(progress=False)
    assert solver.nt > 0


def test_ragged_ns3d_obstacle_matches_single():
    """3-D ragged x obstacles (round 5): a box-obstructed cavity on a mesh
    the grid does not divide tracks the single-device obstacle run exactly
    (jnp CA path; the 3-D kernel stays divisible-only)."""
    from pampi_tpu.models.ns3d import NS3DSolver
    from pampi_tpu.models.ns3d_dist import NS3DDistSolver
    from pampi_tpu.utils.params import Parameter

    param = Parameter(
        name="dcavity3d", imax=10, jmax=10, kmax=9, re=10.0, te=0.04,
        tau=0.5, itermax=100, eps=1e-4, omg=1.7, gamma=0.9,
        obstacles="0.3,0.3,0.3,0.6,0.6,0.6",
    )
    single = NS3DSolver(param)
    single.run(progress=False)
    dist = NS3DDistSolver(param, CartComm(ndims=3, dims=(2, 2, 2)))
    assert dist.ragged  # 9 % 2 != 0
    dist.run(progress=False)
    assert dist.nt == single.nt > 1
    for a, b in zip(single.collect(), dist.collect()):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
