"""NS-3D regression tests.

Oracle: the reference assignment-6 build (non-MPI path), compiled with the
single-line fix for its un-reset-residual bug (SURVEY.md §2.1; our solver
resets per iteration as a documented deviation, so the oracle gets the same
fix). Fixtures in tests/fixtures/ are the oracle's VTK outputs; our output
must match to the writer's 1e-6 precision — including the replicated quirks
(dvwdz V(i,j,k+1), lid loop bounds, uniform canal inflow)."""

import pathlib

import numpy as np
import pytest

from pampi_tpu.models.ns3d import NS3DSolver
from pampi_tpu.utils.params import read_parameter
from pampi_tpu.utils.vtkio import read_vtk_ascii

FIXDIR = pathlib.Path(__file__).parent / "fixtures"


def _run_and_compare(reference_dir, tmp_path, par, overrides, fixture):
    param = read_parameter(str(reference_dir / "assignment-6" / par)).replace(
        **overrides
    )
    s = NS3DSolver(param)
    s.run(progress=False)
    out = tmp_path / "out.vtk"
    s.write_result(str(out))
    so, vo = read_vtk_ascii(str(out))
    sg, vg = read_vtk_ascii(str(FIXDIR / fixture))
    assert np.abs(so["pressure"] - sg["pressure"]).max() <= 1e-6
    for c in range(3):
        assert np.abs(vo["velocity"][c] - vg["velocity"][c]).max() <= 1e-6
    return s


@pytest.mark.golden
def test_dcavity3d_exact_vs_oracle(reference_dir, tmp_path):
    s = _run_and_compare(
        reference_dir,
        tmp_path,
        "dcavity.par",
        dict(imax=32, jmax=32, kmax=32, te=1.0),
        "dcavity3d_32_te1.0.vtk",
    )
    assert s.nt == 112  # oracle log step count (fixtures/dc3b.log)


@pytest.mark.golden
def test_canal3d_exact_vs_oracle(reference_dir, tmp_path):
    _run_and_compare(
        reference_dir,
        tmp_path,
        "canal.par",
        dict(imax=48, jmax=16, kmax=16, te=0.5),
        "canal3d_48x16x16_te0.5.vtk",
    )


def test_vtk_roundtrip(tmp_path):
    from pampi_tpu.utils.grid import Grid
    from pampi_tpu.utils.vtkio import VtkWriter

    g = Grid(imax=3, jmax=4, kmax=2)
    rng = np.random.default_rng(0)
    s = rng.normal(size=(2, 4, 3))
    u, v, w = (rng.normal(size=(2, 4, 3)) for _ in range(3))
    wr = VtkWriter("t", g, fmt="ascii", path=str(tmp_path / "t.vtk"))
    wr.scalar("pressure", s)
    wr.vector("velocity", u, v, w)
    wr.close()
    so, vo = read_vtk_ascii(str(tmp_path / "t.vtk"))
    np.testing.assert_allclose(so["pressure"], s, atol=1e-6)
    np.testing.assert_allclose(vo["velocity"][0], u, atol=1e-6)

    # binary mode writes big-endian f64 streams
    wr = VtkWriter("t", g, fmt="binary", path=str(tmp_path / "tb.vtk"))
    wr.scalar("pressure", s)
    wr.close()
    raw = open(tmp_path / "tb.vtk", "rb").read()
    idx = raw.index(b"LOOKUP_TABLE default\n") + len(b"LOOKUP_TABLE default\n")
    vals = np.frombuffer(raw[idx : idx + 8 * s.size], dtype=">f8")
    np.testing.assert_array_equal(vals.reshape(s.shape), s)


def test_normalize_pressure_3d_interior_only():
    import jax.numpy as jnp

    from pampi_tpu.ops.ns3d import normalize_pressure_3d

    p = jnp.arange(5 * 4 * 6, dtype=jnp.float64).reshape(5, 4, 6)
    out = normalize_pressure_3d(p, imax=4, jmax=2, kmax=3)
    interior = out[1:-1, 1:-1, 1:-1]
    assert abs(float(interior.mean())) < 1e-12
    # ghosts untouched
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(p[0]))
