"""ShardedVtkWriter (the completed MPI-IO exercise): offset-addressed slab
writes must reproduce the serial binary writer byte-for-byte, from plain
numpy slabs and from the addressable shards of a mesh-sharded jax array."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pampi_tpu.utils.grid import Grid
from pampi_tpu.utils.vtkio import ShardedVtkWriter, VtkWriter, shards_of


def _mk_grid(imax, jmax, kmax):
    return Grid(imax=imax, jmax=jmax, kmax=kmax)


def _serial_bytes(tmp_path, grid, s, uvw):
    path = str(tmp_path / "serial.vtk")
    w = VtkWriter("t", grid, fmt="binary", path=path)
    w.scalar("pressure", s)
    w.vector("velocity", *uvw)
    w.close()
    return open(path, "rb").read()


def _slab_split(arr, splits):
    """Cut (K, J, I) into slabs at the given index triples."""
    (ks, js, is_) = splits
    out = []
    kb = [0, *ks, arr.shape[0]]
    jb = [0, *js, arr.shape[1]]
    ib = [0, *is_, arr.shape[2]]
    for a in range(len(kb) - 1):
        for b in range(len(jb) - 1):
            for c in range(len(ib) - 1):
                out.append(
                    (
                        arr[kb[a]:kb[a + 1], jb[b]:jb[b + 1], ib[c]:ib[c + 1]],
                        (kb[a], jb[b], ib[c]),
                    )
                )
    return out


@pytest.mark.parametrize("splits", [
    ([4], [6], [5]),          # 2x2x2 even-ish blocks
    ([1, 7], [], [3, 4]),     # ragged 3x1x3
])
def test_sharded_matches_serial_bytes(tmp_path, splits):
    rng = np.random.default_rng(7)
    kmax, jmax, imax = 8, 12, 10
    grid = _mk_grid(imax, jmax, kmax)
    s = rng.standard_normal((kmax, jmax, imax))
    u, v, w = (rng.standard_normal((kmax, jmax, imax)) for _ in range(3))
    want = _serial_bytes(tmp_path, grid, s, (u, v, w))

    path = str(tmp_path / "sharded.vtk")
    sw = ShardedVtkWriter("t", grid, path=path)
    sw.scalar("pressure", _slab_split(s, splits))
    sw.vector(
        "velocity",
        [(su, sv, sw_, o) for ((su, o), (sv, _), (sw_, _2)) in zip(
            _slab_split(u, splits), _slab_split(v, splits),
            _slab_split(w, splits))],
    )
    sw.close()
    got = open(path, "rb").read()
    assert got == want


def test_slab_bounds_checked(tmp_path):
    grid = _mk_grid(4, 4, 4)
    sw = ShardedVtkWriter("t", grid, path=str(tmp_path / "x.vtk"))
    with pytest.raises(ValueError):
        sw.scalar("s", [(np.zeros((4, 4, 5)), (0, 0, 0))])
    sw.close()


def test_ns3d_dist_sharded_write_matches_serial(tmp_path):
    """End-to-end: a distributed NS-3D run's sharded write equals its serial
    binary write byte-for-byte."""
    from pampi_tpu.models.ns3d_dist import NS3DDistSolver
    from pampi_tpu.parallel.comm import CartComm, dims_create
    from pampi_tpu.utils.params import Parameter

    dims = dims_create(8, 3)
    comm = CartComm(ndims=3, dims=dims, devices=jax.devices()[:8])
    param = Parameter(
        name="dcavity3d",
        imax=8 * dims[2], jmax=8 * dims[1], kmax=8 * dims[0],
        re=10.0, te=0.05, tau=0.5, itermax=50, eps=1e-4, omg=1.7,
        gamma=0.9, tpu_dtype="float64",
    )
    s = NS3DDistSolver(param, comm)
    s.run(progress=False)
    serial = str(tmp_path / "serial.vtk")
    sharded = str(tmp_path / "sharded.vtk")
    s.write_result(path=serial, fmt="binary")
    s.write_result_sharded(path=sharded)
    assert open(sharded, "rb").read() == open(serial, "rb").read()


def test_shards_of_distributed_array(tmp_path):
    """A mesh-sharded jax array's addressable shards drive the writer with no
    global gather; bytes must still equal the serial writer's."""
    rng = np.random.default_rng(9)
    kmax, jmax, imax = 8, 8, 16
    grid = _mk_grid(imax, jmax, kmax)
    s = rng.standard_normal((kmax, jmax, imax))
    devs = np.array(jax.devices()[:8]).reshape(2, 2, 2)
    mesh = Mesh(devs, ("k", "j", "i"))
    arr = jax.device_put(
        jnp.asarray(s), NamedSharding(mesh, P("k", "j", "i"))
    )
    slabs = shards_of(arr)
    assert len(slabs) == 8
    assert sorted(o for _, o in slabs) == sorted(
        (a * 4, b * 4, c * 8) for a in range(2) for b in range(2)
        for c in range(2)
    )

    u, v, w = (rng.standard_normal((kmax, jmax, imax)) for _ in range(3))
    want = _serial_bytes(tmp_path, grid, s, (u, v, w))
    path = str(tmp_path / "dist.vtk")
    sw = ShardedVtkWriter("t", grid, path=path)
    sw.scalar("pressure", slabs)
    uvw_slabs = [
        (u[o[0]:o[0] + d.shape[0], o[1]:o[1] + d.shape[1],
          o[2]:o[2] + d.shape[2]],
         v[o[0]:o[0] + d.shape[0], o[1]:o[1] + d.shape[1],
           o[2]:o[2] + d.shape[2]],
         w[o[0]:o[0] + d.shape[0], o[1]:o[1] + d.shape[1],
           o[2]:o[2] + d.shape[2]],
         o)
        for d, o in slabs
    ]
    sw.vector("velocity", uvw_slabs)
    sw.close()
    assert open(path, "rb").read() == want
