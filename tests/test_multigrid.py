"""Geometric multigrid (ops/multigrid.py, tpu_solver=mg): converges to the
same solution as the reference's SOR algorithm in O(1) V-cycles, same
eps-residual stopping contract; end-to-end via the Poisson golden file and
the NS steppers."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pampi_tpu.ops.multigrid import (
    make_mg_solve_2d,
    make_mg_solve_3d,
    mg_levels,
)
from pampi_tpu.utils.params import Parameter, read_parameter

DT = jnp.float64


def _compatible_rhs_2d(J, I, seed=0):
    rng = np.random.default_rng(seed)
    r = rng.standard_normal((J, I))
    r -= r.mean()
    return jnp.zeros((J + 2, I + 2), DT).at[1:-1, 1:-1].set(jnp.asarray(r, DT))


def test_mg_levels_plan():
    assert mg_levels(128, 128) == [(128, 128), (64, 64), (32, 32),
                                   (16, 16), (8, 8), (4, 4)]
    assert mg_levels(100, 100) == [(100, 100), (50, 50), (25, 25)]
    assert mg_levels(33, 33) == [(33, 33)]  # degenerate: smoothing only


def test_mg2d_matches_sor_solution_in_few_cycles():
    from pampi_tpu.models.poisson import make_solver_fn

    J = I = 64
    dx = dy = 1.0 / I
    rhs = _compatible_rhs_2d(J, I)
    p0 = jnp.zeros((J + 2, I + 2), DT)
    mg = jax.jit(make_mg_solve_2d(I, J, dx, dy, 1e-7, 100, DT))
    p_mg, res, it = mg(p0, rhs)
    assert int(it) <= 15  # O(1) cycles, not O(N^1.17) sweeps
    assert float(res) < 1e-14

    sor = jax.jit(make_solver_fn(I, J, dx, dy, 1.9, 1e-7, 100000, DT,
                                 backend="jnp"))
    p_s, _, it_s = sor(p0, rhs)
    assert int(it_s) > 20 * int(it)  # the speedup is algorithmic
    a = np.asarray(p_mg)[1:-1, 1:-1]
    b = np.asarray(p_s)[1:-1, 1:-1]
    diff = (a - a.mean()) - (b - b.mean())  # all-Neumann: mod constants
    assert np.sqrt((diff**2).mean()) < 1e-7


def test_mg3d_matches_sor_solution_in_few_cycles():
    from pampi_tpu.models.ns3d import make_pressure_solve_3d

    K = J = I = 32
    dx = dy = dz = 1.0 / I
    rng = np.random.default_rng(1)
    r = rng.standard_normal((K, J, I))
    r -= r.mean()
    rhs = jnp.zeros((K + 2, J + 2, I + 2), DT).at[1:-1, 1:-1, 1:-1].set(
        jnp.asarray(r, DT)
    )
    p0 = jnp.zeros_like(rhs)
    mg = jax.jit(make_mg_solve_3d(I, J, K, dx, dy, dz, 1e-7, 100, DT))
    p_mg, res, it = mg(p0, rhs)
    assert int(it) <= 20
    assert float(res) < 1e-14
    sor = jax.jit(make_pressure_solve_3d(I, J, K, dx, dy, dz, 1.8, 1e-7,
                                         100000, DT, backend="jnp"))
    p_s, _, it_s = sor(p0, rhs)
    assert int(it_s) > 10 * int(it)
    a = np.asarray(p_mg)[1:-1, 1:-1, 1:-1]
    b = np.asarray(p_s)[1:-1, 1:-1, 1:-1]
    diff = (a - a.mean()) - (b - b.mean())
    assert np.sqrt((diff**2).mean()) < 1e-7


def test_mg_on_odd_grid_still_converges():
    """100² coarsens only to 25² (3 levels) — fewer levels, still O(few)
    cycles."""
    J = I = 100
    dx = dy = 1.0 / I
    rhs = _compatible_rhs_2d(J, I, seed=2)
    p0 = jnp.zeros((J + 2, I + 2), DT)
    mg = jax.jit(make_mg_solve_2d(I, J, dx, dy, 1e-6, 200, DT))
    _, res, it = mg(p0, rhs)
    assert float(res) < 1e-12
    assert int(it) < 60


@pytest.mark.golden
def test_poisson_mg_matches_golden_pdat(reference_dir):
    """End-to-end: the Poisson driver with tpu_solver=mg reproduces the
    committed golden p.dat field (mean-adjusted interior — the same
    converged state, reached in ~100x fewer iterations)."""
    from pampi_tpu.models.poisson import PoissonSolver
    from pampi_tpu.utils.datio import read_matrix

    param = read_parameter(
        str(reference_dir / "assignment-4" / "poisson.par")
    ).replace(tpu_solver="mg")
    s = PoissonSolver(param, problem=2)
    it, res = s.solve()
    assert res < param.eps**2
    assert it < 100
    golden = read_matrix(str(reference_dir / "assignment-4" / "p.dat"))
    ours = np.asarray(s.p)
    gi = golden[1:-1, 1:-1]
    oi = ours[1:-1, 1:-1]
    diff = (oi - oi.mean()) - (gi - gi.mean())
    assert np.sqrt((diff**2).mean()) < 1e-5


@pytest.mark.golden
def test_ns2d_mg_matches_sor_run(reference_dir):
    """Full NS-2D runs: tpu_solver=mg must reproduce the sor run's physics
    (both converge each pressure solve to the same eps)."""
    from pampi_tpu.models.ns2d import NS2DSolver

    param = read_parameter(
        str(reference_dir / "assignment-5" / "sequential" / "dcavity.par")
    ).replace(te=0.05, imax=32, jmax=32, eps=1e-6)
    a = NS2DSolver(param)
    a.run(progress=False)
    b = NS2DSolver(param.replace(tpu_solver="mg"))
    b.run(progress=False)
    assert a.nt == b.nt
    np.testing.assert_allclose(np.asarray(a.u), np.asarray(b.u),
                               rtol=0, atol=1e-4)
    np.testing.assert_allclose(np.asarray(a.v), np.asarray(b.v),
                               rtol=0, atol=1e-4)


def test_ns3d_mg_matches_sor_run():
    from pampi_tpu.models.ns3d import NS3DSolver

    param = Parameter(
        name="dcavity3d", imax=16, jmax=16, kmax=16,
        re=10.0, te=0.025, tau=0.5, itermax=500, eps=1e-6, omg=1.7,
        gamma=0.9,
    )
    a = NS3DSolver(param)
    a.run(progress=False)
    b = NS3DSolver(param.replace(tpu_solver="mg"))
    b.run(progress=False)
    assert a.nt == b.nt
    np.testing.assert_allclose(np.asarray(a.u), np.asarray(b.u),
                               rtol=0, atol=1e-4)


def test_obstacle_solver_dispatch_rules():
    """fft structurally cannot solve flag fields; mg now can (round 3)."""
    from pampi_tpu.models.ns2d import NS2DSolver

    param = Parameter(
        name="canal", imax=32, jmax=16, re=100.0, te=1.0,
        obstacles="0.3,0.2,0.5,0.4", tpu_solver="fft",
    )
    with pytest.raises(ValueError, match="obstacle"):
        NS2DSolver(param)
    NS2DSolver(param.replace(tpu_solver="mg"))  # builds


# ---------------------------------------------------------------------
# distributed multigrid
# ---------------------------------------------------------------------


def test_dist_mg_poisson_matches_single_device_mg():
    """Distributed MG must converge to the single-device MG answer (same
    algorithm: distributed smoothing + replicated bottom) on any mesh."""
    from pampi_tpu.models.poisson import PoissonSolver
    from pampi_tpu.models.poisson_dist import DistPoissonSolver
    from pampi_tpu.parallel.comm import CartComm

    param = Parameter(imax=64, jmax=64, itermax=100, eps=1e-10, omg=1.8,
                      tpu_solver="mg")
    single = PoissonSolver(param, problem=2)
    it_s, res_s = single.solve()
    assert it_s < 30
    for dims in [(2, 4), (8, 1)]:
        dist = DistPoissonSolver(param, CartComm(ndims=2, dims=dims),
                                 problem=2)
        it_d, res_d = dist.solve()
        assert res_d < param.eps**2
        assert abs(it_d - it_s) <= 3, (dims, it_d, it_s)
        a = dist.full_field()[1:-1, 1:-1]
        b = np.asarray(single.p)[1:-1, 1:-1]
        diff = (a - a.mean()) - (b - b.mean())
        assert np.sqrt((diff**2).mean()) < 1e-8, dims


def test_dist_mg_ns3d_matches_sor_physics():
    """NS-3D on a 3-D mesh with tpu_solver=mg: same converged physics as the
    distributed SOR run (both solves reach the same eps)."""
    from pampi_tpu.models.ns3d_dist import NS3DDistSolver
    from pampi_tpu.parallel.comm import CartComm

    param = Parameter(
        name="dcavity3d", imax=16, jmax=16, kmax=16,
        re=10.0, te=0.025, tau=0.5, itermax=500, eps=1e-6, omg=1.7,
        gamma=0.9,
    )
    a = NS3DDistSolver(param, CartComm(ndims=3, dims=(2, 2, 2)))
    a.run(progress=False)
    b = NS3DDistSolver(param.replace(tpu_solver="mg"),
                       CartComm(ndims=3, dims=(2, 2, 2)))
    b.run(progress=False)
    assert a.nt == b.nt
    ua, va, wa, pa = a.collect()
    ub, vb, wb, pb = b.collect()
    np.testing.assert_allclose(ua, ub, rtol=0, atol=1e-4)
    np.testing.assert_allclose(va, vb, rtol=0, atol=1e-4)
    np.testing.assert_allclose(wa, wb, rtol=0, atol=1e-4)
    # all-Neumann pressure is defined up to a constant; only ∇p is physical
    np.testing.assert_allclose(pa - pa.mean(), pb - pb.mean(),
                               rtol=0, atol=1e-4)


def test_dist_mg_ns2d_matches_single_mg(reference_dir):
    """NS-2D distributed mg vs single-device mg: both converge each solve to
    eps; fields agree to solver tolerance on a 2-D mesh."""
    from pampi_tpu.models.ns2d import NS2DSolver
    from pampi_tpu.models.ns2d_dist import NS2DDistSolver
    from pampi_tpu.parallel.comm import CartComm

    param = read_parameter(
        str(reference_dir / "assignment-5" / "sequential" / "dcavity.par")
    ).replace(te=0.05, imax=32, jmax=32, eps=1e-6, tpu_solver="mg")
    a = NS2DSolver(param)
    a.run(progress=False)
    b = NS2DDistSolver(param, CartComm(ndims=2, dims=(2, 4)))
    b.run(progress=False)
    ud, vd, pd = b.fields()
    assert a.nt == b.nt
    np.testing.assert_allclose(np.asarray(a.u), ud, rtol=0, atol=1e-4)
    np.testing.assert_allclose(np.asarray(a.v), vd, rtol=0, atol=1e-4)
    pa = np.asarray(a.p)[1:-1, 1:-1]
    pi = pd[1:-1, 1:-1]
    np.testing.assert_allclose(pa - pa.mean(), pi - pi.mean(),
                               rtol=0, atol=1e-4)


def test_obstacle_mg_matches_sor_and_converges_fast():
    """Obstacle-capable MG (make_obstacle_mg_solve_2d): rediscretized
    eps-coefficient operator per level, fluid-ANY flag coarsening. Must
    agree with the obstacle SOR solver's converged field and get there in
    O(10) cycles where SOR needs O(10^4) sweeps (VERDICT r2 item 5)."""
    import jax

    from pampi_tpu.ops import obstacle as obst
    from pampi_tpu.ops.multigrid import make_obstacle_mg_solve_2d

    imax, jmax = 128, 64
    xl, yl = 16.0, 4.0
    dx, dy = xl / imax, yl / jmax
    fluid = obst.build_fluid(imax, jmax, dx, dy, "3.0,1.5,4.0,2.5")
    m = obst.make_masks(fluid, dx, dy, 1.7, jnp.float64)
    rng = np.random.default_rng(0)
    rhs_i = rng.standard_normal((jmax, imax)) * np.asarray(m.p_mask)
    rhs_i -= rhs_i.sum() / m.n_fluid * np.asarray(m.p_mask)  # compatible
    rhs = jnp.zeros((jmax + 2, imax + 2)).at[1:-1, 1:-1].set(
        jnp.asarray(rhs_i)
    )
    p0 = jnp.zeros((jmax + 2, imax + 2))

    mg = jax.jit(make_obstacle_mg_solve_2d(
        imax, jmax, dx, dy, 1e-8, 100, m, jnp.float64
    ))
    p_mg, res_mg, it_mg = mg(p0, rhs)
    assert int(it_mg) <= 30, int(it_mg)
    assert float(res_mg) < 1e-16

    sor = jax.jit(obst.make_obstacle_solver_fn(
        imax, jmax, dx, dy, 1e-8, 200000, m, jnp.float64, backend="jnp"
    ))
    p_s, _, it_s = sor(p0, rhs)
    # the O(1)-cycles claim with fixed floors (a coupled ratio would fail
    # on a one-cycle platform difference): MG O(10), SOR O(10^4)
    assert int(it_s) > 10_000

    pm = np.asarray(p_mg)[1:-1, 1:-1]
    ps = np.asarray(p_s)[1:-1, 1:-1]
    mask = np.asarray(m.p_mask) > 0
    d = (pm - pm[mask].mean()) - (ps - ps[mask].mean())
    assert np.abs(d[mask]).max() < 1e-6


def test_obstacle_mg_in_ns2d_step():
    """tpu_solver mg accepts obstacle configs in the NS-2D model. The
    comparison config must have CONVERGING pressure solves (canal's floor
    above eps would leave both paths itermax-capped and incomparable), so:
    an obstructed lid-driven cavity at eps=1e-3."""
    from pampi_tpu.models.ns2d import NS2DSolver

    param = Parameter(
        name="dcavity", imax=64, jmax=64, re=10.0, te=0.05, tau=0.5,
        itermax=500, eps=1e-3, omg=1.7, gamma=0.9,
        obstacles="0.35,0.35,0.65,0.65",
    )
    s_mg = NS2DSolver(param.replace(tpu_solver="mg"))
    s_mg.run(progress=False)
    s_sor = NS2DSolver(param.replace(tpu_solver="sor"))
    s_sor.run(progress=False)
    assert s_mg.nt == s_sor.nt > 1
    np.testing.assert_allclose(
        np.asarray(s_mg.u), np.asarray(s_sor.u), atol=2e-4, rtol=0
    )


def test_mg_stall_rtol_zero_restores_itermax_parity():
    """tpu_mg_stall_rtol=0 disables the stall detector: an un-convergeable
    solve (eps below the f64 attainable floor) burns the full itermax like
    the reference's capped solves; the default detector stops it early at
    the floor with the same final residual."""
    J = I = 32
    dx = dy = 1.0 / I
    rhs = _compatible_rhs_2d(J, I)
    p0 = jnp.zeros((J + 2, I + 2), DT)
    itermax = 60
    capped = jax.jit(make_mg_solve_2d(I, J, dx, dy, 1e-30, itermax, DT,
                                      stall_rtol=0.0))
    p_c, res_c, it_c = capped(p0, rhs)
    assert int(it_c) == itermax  # reference parity: burns the budget
    # a loose tolerance treats the round-off jitter at the floor as a stall
    # (the 1e-4 default deliberately does not — jitter can exceed it)
    stalled = jax.jit(make_mg_solve_2d(I, J, dx, dy, 1e-30, itermax, DT,
                                       stall_rtol=0.9))
    p_s, res_s, it_s = stalled(p0, rhs)
    assert 2 <= int(it_s) < itermax  # detector fired at the floor
    # both sit on the same round-off floor, orders of magnitude below eps=0
    # attainability but equal to each other within the jitter
    assert float(res_s) < 1e-25 and float(res_c) < 1e-25


def test_mg_stall_rtol_par_key_roundtrip(tmp_path):
    """The .par grammar carries tpu_mg_stall_rtol (default 1e-4; 0 = off)."""
    f = tmp_path / "t.par"
    f.write_text("name t\ntpu_mg_stall_rtol 0.0  # itermax parity\n")
    p = read_parameter(str(f))
    assert p.tpu_mg_stall_rtol == 0.0
    assert Parameter().tpu_mg_stall_rtol == pytest.approx(1e-4)


def test_pallas_smoother_matches_jnp_plain_mg(monkeypatch):
    """backend="pallas" (interpret off-TPU) routes MG smoothing through the
    temporal-blocked kernel; the smoother arithmetic is the same red-black
    ω=1 sweep, so the V-cycle trajectory must match the jnp smoother's.

    The production bottom budget would collapse 64² to a DCT-only plan
    (neither smoother would execute — a vacuous test), so the budget is
    shrunk to force a multi-level plan through the smoothing path."""
    from pampi_tpu.ops import multigrid as mgmod
    from pampi_tpu.ops.multigrid import _truncate_levels, mg_levels

    monkeypatch.setattr(mgmod, "_DCT_BOTTOM_MAX_CELLS", 1024)

    J = I = 64
    dx = dy = 1.0 / I
    # vacuity guard: the plan must carry a smoothed level above the bottom
    assert len(_truncate_levels(mg_levels(J, I), 1024)) > 1
    rhs = _compatible_rhs_2d(J, I)
    p0 = jnp.zeros((J + 2, I + 2), DT)
    mg_j = jax.jit(make_mg_solve_2d(I, J, dx, dy, 1e-7, 50, DT))
    mg_p = jax.jit(make_mg_solve_2d(I, J, dx, dy, 1e-7, 50, DT,
                                    backend="pallas"))
    pj, resj, itj = mg_j(p0, rhs)
    pp, resp, itp = mg_p(p0, rhs)
    assert int(itj) == int(itp)
    np.testing.assert_allclose(np.asarray(pp), np.asarray(pj),
                               rtol=0, atol=1e-11)
    np.testing.assert_allclose(float(resp), float(resj), rtol=1e-6)


def test_pallas_smoother_matches_jnp_obstacle_mg():
    from pampi_tpu.ops import obstacle as obst
    from pampi_tpu.ops.multigrid import make_obstacle_mg_solve_2d

    J = I = 64
    dx = dy = 1.0 / I
    fluid = obst.build_fluid(I, J, dx, dy, "0.3,0.3,0.7,0.6")
    m = obst.make_masks(fluid, dx, dy, 1.7, DT)
    rhs = _compatible_rhs_2d(J, I)
    p0 = jnp.zeros((J + 2, I + 2), DT)
    mg_j = jax.jit(make_obstacle_mg_solve_2d(I, J, dx, dy, 1e-7, 50, m, DT))
    mg_p = jax.jit(make_obstacle_mg_solve_2d(I, J, dx, dy, 1e-7, 50, m, DT,
                                             backend="pallas"))
    pj, resj, itj = mg_j(p0, rhs)
    pp, resp, itp = mg_p(p0, rhs)
    assert int(itj) == int(itp)
    np.testing.assert_allclose(np.asarray(pp), np.asarray(pj),
                               rtol=0, atol=1e-11)


def test_dist_obstacle_mg_matches_single_device_obstacle_mg():
    """NS-2D distributed obstacle-MG (make_dist_obstacle_mg_solve_2d) vs
    the single-device obstacle MG: a converging obstructed-cavity config
    (eps reachable) must produce the same physics on a mesh — the VERDICT
    r3 item 6 'done' bar."""
    from pampi_tpu.models.ns2d import NS2DSolver
    from pampi_tpu.models.ns2d_dist import NS2DDistSolver
    from pampi_tpu.parallel.comm import CartComm

    param = Parameter(
        name="dcavity", imax=64, jmax=64, re=10.0, te=0.02, tau=0.5,
        itermax=500, eps=1e-3, omg=1.7, gamma=0.9,
        obstacles="0.35,0.35,0.65,0.65", tpu_solver="mg",
    )
    a = NS2DSolver(param)
    a.run(progress=False)
    # one mesh: each extra mesh is another full shard_map-MG compile (the
    # dominant cost on the 1-core tier-1 host); (1, 8) single-axis meshes
    # stay covered by the quarters/octants dist suites
    for dims in [(2, 4)]:
        b = NS2DDistSolver(param, CartComm(ndims=2, dims=dims))
        b.run(progress=False)
        ud, vd, pd = b.fields()
        assert a.nt == b.nt, dims
        np.testing.assert_allclose(np.asarray(a.u), ud, rtol=0, atol=2e-4)
        np.testing.assert_allclose(np.asarray(a.v), vd, rtol=0, atol=2e-4)


def test_pallas_smoother_matches_jnp_3d(monkeypatch):
    """backend="pallas" (interpret off-TPU) routes 3-D MG smoothing through
    the temporal-blocked kernel; trajectory must match the jnp smoother's
    (plain and obstacle variants). The plain budget is shrunk so 16³ keeps
    a smoothed level (see the 2-D twin's vacuity note); the obstacle plan's
    1024-cell dense budget already leaves one."""
    from pampi_tpu.ops import multigrid as mgmod
    from pampi_tpu.ops import obstacle3d as o3
    from pampi_tpu.ops.multigrid import (
        _truncate_levels, make_obstacle_mg_solve_3d, mg_levels,
    )

    monkeypatch.setattr(mgmod, "_DCT_BOTTOM_MAX_CELLS", 512)

    K = J = I = 12
    dx = dy = dz = 1.0 / I
    # vacuity guards: both plans must carry a smoothed level above the
    # bottom
    assert len(_truncate_levels(mg_levels(K, J, I), 512)) > 1
    assert len(_truncate_levels(mg_levels(K, J, I),
                                mgmod._DENSE_BOTTOM_MAX_CELLS)) > 1
    rng = np.random.default_rng(4)
    r = rng.standard_normal((K, J, I))
    r -= r.mean()
    rhs = jnp.zeros((K + 2, J + 2, I + 2), DT).at[1:-1, 1:-1, 1:-1].set(
        jnp.asarray(r, DT))
    p0 = jnp.zeros_like(rhs)
    mg_j = jax.jit(make_mg_solve_3d(I, J, K, dx, dy, dz, 1e-7, 40, DT))
    mg_p = jax.jit(make_mg_solve_3d(I, J, K, dx, dy, dz, 1e-7, 40, DT,
                                    backend="pallas"))
    pj, resj, itj = mg_j(p0, rhs)
    pp, resp, itp = mg_p(p0, rhs)
    assert int(itj) == int(itp)
    np.testing.assert_allclose(np.asarray(pp), np.asarray(pj),
                               rtol=0, atol=1e-11)

    fluid = o3.build_fluid_3d(I, J, K, dx, dy, dz, "0.3,0.3,0.3,0.6,0.6,0.6")
    m = o3.make_masks_3d(fluid, dx, dy, dz, 1.7, DT)
    og_j = jax.jit(make_obstacle_mg_solve_3d(I, J, K, dx, dy, dz, 1e-7, 40,
                                             m, DT))
    og_p = jax.jit(make_obstacle_mg_solve_3d(I, J, K, dx, dy, dz, 1e-7, 40,
                                             m, DT, backend="pallas"))
    pj, _, itj = og_j(p0, rhs)
    pp, _, itp = og_p(p0, rhs)
    assert int(itj) == int(itp)
    np.testing.assert_allclose(np.asarray(pp), np.asarray(pj),
                               rtol=0, atol=1e-11)


# ---------------------------------------------------------------------
# distributed Pallas smoothers (round 5: VERDICT r4 item 1 — the dist MG
# factories smooth through the per-shard flag-masked kernel at eligible
# levels; backend="pallas" forces interpret mode off-TPU)
# ---------------------------------------------------------------------


def _shard_solve_2d(comm, dims, solve, p0, rhs):
    from jax.sharding import PartitionSpec as P

    from pampi_tpu.parallel.comm import halo_exchange

    def kern(p_int, rhs_int):
        pe = halo_exchange(jnp.pad(p_int, 1), comm)
        re = halo_exchange(jnp.pad(rhs_int, 1), comm)
        p, res, it = solve(pe, re)
        return p[1:-1, 1:-1], res, it

    spec = P("j", "i")
    f = jax.jit(comm.shard_map(
        kern, in_specs=(spec, spec), out_specs=(spec, P(), P()),
        check_vma=False,
    ))
    p_out, res, it = f(p0[1:-1, 1:-1], rhs[1:-1, 1:-1])
    return np.asarray(p_out), float(res), int(it)


def test_dist_obstacle_mg_pallas_smoother_matches_jnp():
    """backend="pallas" routes the dist obstacle-MG's eligible-level
    smoothing through the per-shard flag-masked kernel (one deep exchange
    per n sweeps). Same CA discipline as the dist obstacle SOR -> the
    trajectory must be BITWISE-equal to the exchange-per-half-sweep jnp
    smoothing."""
    from pampi_tpu.ops import obstacle as obst
    from pampi_tpu.ops.multigrid import make_dist_obstacle_mg_solve_2d
    from pampi_tpu.parallel.comm import CartComm

    jmax, imax = 32, 64
    dx, dy = 4.0 / imax, 2.0 / jmax
    fluid = obst.build_fluid(imax, jmax, dx, dy, "1.2,0.5,2.0,1.1")
    m = obst.make_masks(fluid, dx, dy, 1.0, DT)
    dims = (2, 4)
    comm = CartComm(ndims=2, dims=dims)
    jl, il = jmax // dims[0], imax // dims[1]
    rng = np.random.default_rng(7)
    p0 = jnp.asarray(rng.standard_normal((jmax + 2, imax + 2)))
    rhs = jnp.asarray(rng.standard_normal((jmax + 2, imax + 2)))

    outs = {}
    for backend in ("auto", "pallas"):  # auto on CPU = jnp sweeps
        solve, used = make_dist_obstacle_mg_solve_2d(
            comm, imax, jmax, jl, il, dx, dy, 1e-8, 30, m, DT,
            backend=backend,
        )
        assert used == (backend == "pallas")
        outs[backend] = _shard_solve_2d(comm, dims, solve, p0, rhs)

    assert outs["auto"][2] == outs["pallas"][2]
    np.testing.assert_array_equal(outs["auto"][0], outs["pallas"][0])


def test_dist_plain_mg_pallas_smoother_matches_jnp():
    """Plain dist MG smooths through the same kernel with an ALL-FLUID flag
    field: every eps coefficient is 1, so the arithmetic is the plain
    stencil up to fp association — ulp-equivalent, not bitwise (the
    quarters-layout precedent)."""
    from pampi_tpu.ops.multigrid import make_dist_mg_solve_2d
    from pampi_tpu.parallel.comm import CartComm

    jmax = imax = 32
    dx = dy = 1.0 / imax
    dims = (2, 4)
    comm = CartComm(ndims=2, dims=dims)
    jl, il = jmax // dims[0], imax // dims[1]
    rng = np.random.default_rng(8)
    r = rng.standard_normal((jmax, imax))
    r -= r.mean()
    rhs = jnp.zeros((jmax + 2, imax + 2), DT).at[1:-1, 1:-1].set(
        jnp.asarray(r, DT))
    p0 = jnp.zeros_like(rhs)

    outs = {}
    for backend in ("auto", "pallas"):
        solve, used = make_dist_mg_solve_2d(
            comm, imax, jmax, jl, il, dx, dy, 1e-8, 30, DT,
            backend=backend,
        )
        assert used == (backend == "pallas")
        outs[backend] = _shard_solve_2d(comm, dims, solve, p0, rhs)

    assert abs(outs["auto"][2] - outs["pallas"][2]) <= 1
    np.testing.assert_allclose(outs["auto"][0], outs["pallas"][0],
                               rtol=0, atol=1e-11)


def test_dist_mg_pallas_smoother_matches_jnp_3d():
    """3-D twins: obstacle (bitwise) and plain (ulp) dist-MG Pallas
    smoothing on a (2,2,2) mesh."""
    from jax.sharding import PartitionSpec as P

    from pampi_tpu.ops import obstacle3d as o3
    from pampi_tpu.ops.multigrid import (
        make_dist_mg_solve_3d,
        make_dist_obstacle_mg_solve_3d,
    )
    from pampi_tpu.parallel.comm import CartComm
    from pampi_tpu.parallel.comm import halo_exchange

    kmax = jmax = imax = 16
    dx = dy = dz = 1.0 / imax
    dims = (2, 2, 2)
    comm = CartComm(ndims=3, dims=dims)
    kl, jl, il = kmax // dims[0], jmax // dims[1], imax // dims[2]
    rng = np.random.default_rng(9)
    r = rng.standard_normal((kmax, jmax, imax))
    r -= r.mean()
    rhs = jnp.zeros((kmax + 2, jmax + 2, imax + 2), DT)
    rhs = rhs.at[1:-1, 1:-1, 1:-1].set(jnp.asarray(r, DT))
    p0 = jnp.zeros_like(rhs)

    def run(solve):
        def kern(p_int, rhs_int):
            pe = halo_exchange(jnp.pad(p_int, 1), comm)
            re = halo_exchange(jnp.pad(rhs_int, 1), comm)
            p, res, it = solve(pe, re)
            return p[1:-1, 1:-1, 1:-1], res, it

        spec = P("k", "j", "i")
        f = jax.jit(comm.shard_map(
            kern, in_specs=(spec, spec), out_specs=(spec, P(), P()),
            check_vma=False,
        ))
        p_out, res, it = f(p0[1:-1, 1:-1, 1:-1], rhs[1:-1, 1:-1, 1:-1])
        return np.asarray(p_out), float(res), int(it)

    fluid = o3.build_fluid_3d(imax, jmax, kmax, dx, dy, dz,
                              "0.3,0.3,0.3,0.6,0.6,0.6")
    m = o3.make_masks_3d(fluid, dx, dy, dz, 1.0, DT)
    outs = {}
    for backend in ("auto", "pallas"):
        solve, used = make_dist_obstacle_mg_solve_3d(
            comm, imax, jmax, kmax, kl, jl, il, dx, dy, dz, 1e-8, 20, m,
            DT, backend=backend,
        )
        assert used == (backend == "pallas")
        outs[backend] = run(solve)
    assert outs["auto"][2] == outs["pallas"][2]
    np.testing.assert_array_equal(outs["auto"][0], outs["pallas"][0])

    outs = {}
    for backend in ("auto", "pallas"):
        solve, used = make_dist_mg_solve_3d(
            comm, imax, jmax, kmax, kl, jl, il, dx, dy, dz, 1e-8, 20, DT,
            backend=backend,
        )
        assert used == (backend == "pallas")
        outs[backend] = run(solve)
    assert abs(outs["auto"][2] - outs["pallas"][2]) <= 1
    np.testing.assert_allclose(outs["auto"][0], outs["pallas"][0],
                               rtol=0, atol=1e-11)
