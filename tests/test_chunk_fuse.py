"""K-step fused chunks + per-tier exchange depths (ISSUE 17).

Contracts pinned here:
- OFF IS HISTORICAL: `tpu_chunk_fuse off` (and `auto` off-TPU) traces
  BITWISE to the pre-ISSUE-17 chunk — jaxpr-hash identity, so the
  committed CONTRACTS.json hashes stay valid without regeneration of
  the historical entries.
- K PARITY: a K>=2 scan-wrapped chunk reaches the same fields and step
  count as the historical chunk on every family — jnp path bitwise,
  fused path at the ulp contract — including a ragged dist decomposition
  and an obstacle dist config (the two geometries where a fused-window
  off-by-one would hide).
- DISPATCH RECORDS: every refusal (off, no TPU, K does not divide the
  chunk) and the armed scan are recorded in the jaxprcheck-parseable
  spelling; the exchange-depth knob refuses K=1, a non-dcn axis, and
  H not dividing K — and arms with the 1-exchange-per-H-steps record.
- RECORDER UNDER K: the per-chunk flight records report REAL steps
  (chunk nt advance, unchanged by the internal K grouping), rearm()
  re-baselines after rollback, and the divergence sentinel names the
  exact step INSIDE a K-block, not a block boundary.
- the halocheck depth-capture derivation rejects the geometries
  resolve_exchange_depth must refuse (mutation pins).

Compile cost: every solver is 16²/8³, itermax <= 10, te <= 0.05.
"""

import json

import numpy as np
import pytest

from pampi_tpu.analysis.jaxprcheck import jaxpr_hash, trace_chunk
from pampi_tpu.models.ns2d import NS2DSolver
from pampi_tpu.utils import dispatch, telemetry as tm
from pampi_tpu.utils.params import Parameter

_B2 = dict(name="dcavity", imax=16, jmax=16, re=10.0, te=0.02, tau=0.5,
           itermax=10, eps=1e-4, omg=1.7, gamma=0.9)
_B3 = dict(name="dcavity3d", imax=8, jmax=8, kmax=8, re=10.0, te=0.02,
           tau=0.5, itermax=8, eps=1e-4, omg=1.7, gamma=0.9)
_OBS = dict(name="canal_obstacle", imax=24, jmax=12, xlength=2.0,
            ylength=1.0, re=10.0, te=0.02, tau=0.5, itermax=10,
            eps=1e-4, omg=1.7, gamma=0.9, u_init=1.0, bcLeft=3,
            bcRight=3, obstacles="0.3,0.3,0.6,0.6")


def _ulp_close(a, b, scale=1.0):
    a, b = np.asarray(a), np.asarray(b)
    tol = 1e-12 if a.dtype == np.float64 else 2e-5
    return np.abs(a - b).max() <= tol * max(1.0, scale)


def test_off_is_historical_trace():
    """The jaxpr-hash identity: off == auto-off-TPU, and both record the
    refusal; a forced K=4 is a DIFFERENT program with the scan record."""
    h_off = jaxpr_hash(trace_chunk(
        NS2DSolver(Parameter(tpu_chunk_fuse="off", **_B2))))
    assert dispatch.last("ns2d_chunk_fuse") == \
        "historical (tpu_chunk_fuse off)"
    h_auto = jaxpr_hash(trace_chunk(NS2DSolver(Parameter(**_B2))))
    assert dispatch.last("ns2d_chunk_fuse") == "historical (no TPU)"
    assert h_off == h_auto
    h_k4 = jaxpr_hash(trace_chunk(
        NS2DSolver(Parameter(tpu_chunk_fuse="4", **_B2))))
    assert "scan (K=4" in dispatch.last("ns2d_chunk_fuse")
    assert h_k4 != h_off


def test_refusal_records():
    """K that does not divide the chunk (ns2d CHUNK=64) refuses WITH the
    arithmetic in the record; K=1 is spelled historical."""
    NS2DSolver(Parameter(tpu_chunk_fuse="7", **_B2))
    assert dispatch.last("ns2d_chunk_fuse") == \
        "historical (K=7 does not divide chunk 64)"
    NS2DSolver(Parameter(tpu_chunk_fuse="1", **_B2))
    assert dispatch.last("ns2d_chunk_fuse") == "historical (K=1)"
    with pytest.raises(ValueError, match="auto|on|off"):
        NS2DSolver(Parameter(tpu_chunk_fuse="sideways", **_B2))


def _run2(cls=NS2DSolver, comm=None, base=_B2, **kw):
    p = Parameter(**{**base, **kw})
    s = cls(p, comm=comm) if comm is not None else cls(p)
    s.run(progress=False)
    return s


@pytest.mark.parametrize("extra,tol_key", [
    ({}, "bitwise"),
    ({"tpu_fuse_phases": "on", "tpu_solver": "fft"}, "ulp"),
])
def test_k4_parity_single(extra, tol_key):
    a = _run2(tpu_chunk_fuse="off", **extra)
    b = _run2(tpu_chunk_fuse="4", **extra)
    assert "scan (K=4" in dispatch.last("ns2d_chunk_fuse")
    assert a.nt == b.nt
    ua, ub = np.asarray(a.u), np.asarray(b.u)
    pa, pb = np.asarray(a.p), np.asarray(b.p)
    if tol_key == "bitwise":
        assert np.array_equal(ua, ub) and np.array_equal(pa, pb)
    else:
        assert _ulp_close(ub, ua, scale=float(np.abs(ua).max()))
        assert _ulp_close(pb, pa, scale=float(np.abs(pa).max()))


def test_k4_parity_ns3d():
    from pampi_tpu.models.ns3d import NS3DSolver

    a = _run2(NS3DSolver, base=_B3, tpu_chunk_fuse="off")
    b = _run2(NS3DSolver, base=_B3, tpu_chunk_fuse="4")
    assert "scan (K=4" in dispatch.last("ns3d_chunk_fuse")
    assert a.nt == b.nt
    assert np.array_equal(np.asarray(a.u), np.asarray(b.u))
    assert np.array_equal(np.asarray(a.p), np.asarray(b.p))


def _dist2(dims, base=_B2, **kw):
    from pampi_tpu.models.ns2d_dist import NS2DDistSolver
    from pampi_tpu.parallel.comm import CartComm

    p = Parameter(**{**base, **kw})
    comm = CartComm(ndims=2, extents=(p.jmax, p.imax), dims=dims,
                    tiers=p.tpu_mesh_tiers)
    s = NS2DDistSolver(p, comm=comm)
    s.run(progress=False)
    u, v, pp = s.fields()
    return s, np.asarray(u), np.asarray(pp)


@pytest.mark.parametrize("base,dims,fused", [
    (_B2, (2, 2), "off"),            # jnp path: bitwise
    (_B2, (2, 2), "on"),             # fused kernels: ulp
    ({**_B2, "imax": 18, "jmax": 18}, (4, 2), "on"),   # ragged shards
    (_OBS, (2, 2), "on"),            # flag-masked obstacle config
])
def test_k4_parity_dist(base, dims, fused):
    s1, u1, p1 = _dist2(dims, base=base, tpu_chunk_fuse="off",
                        tpu_fuse_phases=fused)
    s4, u4, p4 = _dist2(dims, base=base, tpu_chunk_fuse="4",
                        tpu_fuse_phases=fused)
    assert "scan (K=4" in dispatch.last("ns2d_dist_chunk_fuse")
    assert s1.nt == s4.nt
    if fused == "off":
        assert np.array_equal(u1, u4) and np.array_equal(p1, p4)
    else:
        assert _ulp_close(u4, u1, scale=float(np.abs(u1).max()))
        assert _ulp_close(p4, p1, scale=float(np.abs(p1).max()))


def test_k4_parity_ns3d_dist():
    from pampi_tpu.models.ns3d_dist import NS3DDistSolver
    from pampi_tpu.parallel.comm import CartComm

    def run(fuse):
        p = Parameter(tpu_chunk_fuse=fuse, **_B3)
        comm = CartComm(ndims=3, extents=(p.kmax, p.jmax, p.imax),
                        dims=(2, 2, 2))
        s = NS3DDistSolver(p, comm=comm)
        s.run(progress=False)
        g = s.global_fields()
        return s.nt, np.asarray(g["u"]), np.asarray(g["p"])

    nt1, u1, p1 = run("off")
    nt4, u4, p4 = run("4")
    assert "scan (K=4" in dispatch.last("ns3d_dist_chunk_fuse")
    assert nt1 == nt4
    assert np.array_equal(u1, u4) and np.array_equal(p1, p4)


def test_exchange_depth_records():
    """The depth knob's whole refusal chain + the armed record, read off
    real dist builds (the dispatch record is the contract surface)."""
    # armed: K=4, i declared dcn, H=4 divides K, extent 8 >= 4
    _dist2((2, 2), tpu_chunk_fuse="4", tpu_fuse_phases="on",
           tpu_mesh_tiers="i=dcn", tpu_exchange_depth="i=4")
    assert dispatch.last("ns2d_dist_exchange_depth") == \
        "depth (i=4: 1 i-exchange per 4 steps)"
    # refusal: no K-fusion -> per-step
    _dist2((2, 2), tpu_chunk_fuse="off", tpu_fuse_phases="on",
           tpu_mesh_tiers="i=dcn", tpu_exchange_depth="i=4")
    assert dispatch.last("ns2d_dist_exchange_depth") == \
        "per-step (needs tpu_chunk_fuse K >= 2)"
    # refusal: axis not declared dcn-tier
    _dist2((2, 2), tpu_chunk_fuse="4", tpu_fuse_phases="on",
           tpu_exchange_depth="i=4")
    assert dispatch.last("ns2d_dist_exchange_depth") == \
        "per-step (axis 'i' is not dcn-tier)"
    # refusal: H does not divide K
    _dist2((2, 2), tpu_chunk_fuse="4", tpu_fuse_phases="on",
           tpu_mesh_tiers="i=dcn", tpu_exchange_depth="i=3")
    assert dispatch.last("ns2d_dist_exchange_depth") == \
        "per-step (H=3 does not divide K=4)"


def test_depth_capture_derivation_pins():
    """halocheck's pure-arithmetic depth-capture checks: clean at the
    production geometry, and each mutated geometry fires the matching
    violation (the refusal conditions resolve_exchange_depth encodes)."""
    from pampi_tpu.analysis.halocheck import depth_capture_violations
    from pampi_tpu.ops import ns2d_fused as nf

    assert depth_capture_violations((8, 8), 4, nf.FUSE_DEEP_HALO) == []
    v = depth_capture_violations((3, 3), 4, 3)
    assert v and any("owned" in str(s) for s in v)
    v = depth_capture_violations((8, 8), 2, 3)
    assert v and any("crop" in str(s) or "inner" in str(s) for s in v)


# --------------------------------------------------------------------
# ChunkRecorder under K-step chunks (the host plane must be unchanged:
# steps are REAL nt advances, never K-block counts)
# --------------------------------------------------------------------


@pytest.fixture()
def tel_on(tmp_path, monkeypatch):
    path = tmp_path / "run.jsonl"
    monkeypatch.setenv("PAMPI_TELEMETRY", str(path))
    tm.reset()
    yield path
    tm.reset()


def _chunk_records(path):
    return [json.loads(ln) for ln in open(path)
            if json.loads(ln).get("kind") == "chunk"]


def test_chunk_records_identical_under_k(tel_on, tmp_path, monkeypatch):
    """The flight record's per-chunk (steps, nt) sequence is IDENTICAL
    with and without K-fusion: the recorder sees the chunk's real nt
    advance, and steps/s + ETA stay honest."""
    def run(fuse, path):
        monkeypatch.setenv("PAMPI_TELEMETRY", str(path))
        tm.reset()
        s = _run2(tpu_chunk=4, tpu_chunk_fuse=fuse)
        tm.reset()
        return s, _chunk_records(path)

    s1, recs1 = run("off", tmp_path / "off.jsonl")
    s4, recs4 = run("4", tmp_path / "k4.jsonl")
    assert s1.nt == s4.nt and recs1 and recs4
    assert [(r["steps"], r["nt"]) for r in recs1] == \
        [(r["steps"], r["nt"]) for r in recs4]
    assert sum(r["steps"] for r in recs4) == s4.nt
    assert all(r["ms_per_step"] is not None for r in recs4)


def test_recorder_rearm_rebaselines(tel_on):
    """rearm(nt) after rollback: the next record reports steps from the
    rollback target (never negative), is compile-inclusive again, and
    the divergence latch re-arms for a second blow-up."""
    rec = tm.ChunkRecorder("ns2d", nt0=0)
    good = np.zeros(tm.METRICS_LEN)
    good[tm.M_BAD] = -1.0
    rec.update(0.1, 8, good)
    rec.update(0.2, 16, good)
    rec.rearm(nt=12)
    rec.update(0.3, 16, good)
    recs = _chunk_records(tel_on)
    assert [r["steps"] for r in recs] == [8, 8, 4]
    assert [r["includes_compile"] for r in recs] == [True, False, True]
    assert recs[-1]["ms_per_step"] is not None \
        and recs[-1]["ms_per_step"] >= 0
    # divergence re-latch across a rearm
    bad = good.copy()
    bad[tm.M_BAD] = 14.0
    with pytest.warns(UserWarning, match="non-finite"):
        rec.update(0.4, 20, bad)
    rec.update(0.5, 24, bad)  # latched: no second record
    rec.rearm()
    with pytest.warns(UserWarning, match="non-finite"):
        rec.update(0.6, 28, bad)
    divs = [json.loads(ln) for ln in open(tel_on)
            if json.loads(ln).get("kind") == "divergence"]
    assert len(divs) == 2
    assert all(d["first_bad_step"] == 14 for d in divs)


def test_divergence_step_exact_inside_k_block(tel_on, tmp_path,
                                              monkeypatch):
    """An injected blow-up under K=4 names the SAME first-bad step the
    historical chunk reports — the sentinel latches per step inside the
    scan, not per K-block."""
    unstable = {**_B2, "re": 1000.0, "te": 6.5, "tau": -1.0, "dt": 1.0,
                "itermax": 10, "tpu_chunk": 4}

    def first_bad(fuse, path):
        monkeypatch.setenv("PAMPI_TELEMETRY", str(path))
        tm.reset()
        s = NS2DSolver(Parameter(**unstable, tpu_chunk_fuse=fuse))
        with pytest.warns(UserWarning, match="non-finite"):
            s.run(progress=False)
        tm.reset()
        divs = [json.loads(ln) for ln in open(path)
                if json.loads(ln).get("kind") == "divergence"]
        assert len(divs) == 1
        return divs[0]["first_bad_step"], divs[0]["last_good_step"]

    fb1, lg1 = first_bad("off", tmp_path / "off.jsonl")
    fb4, lg4 = first_bad("4", tmp_path / "k4.jsonl")
    assert (fb1, lg1) == (fb4, lg4)
    assert lg4 == fb4 - 1
