"""tools/_artifact.write_merged: re-runs must refresh measured keys without
clobbering curated NESTED fields (ADVICE round-5 item — the shallow
dict.update lost any curated field under a colliding top-level key)."""

import json


def test_write_merged_recursive(tmp_path):
    from tools._artifact import write_merged

    path = str(tmp_path / "results" / "rec.json")
    write_merged(path, {
        "ms_per_step": 19.06,
        "decomposition": {"solve_ms": 12.6, "nonsolve_ms": 6.4},
    })
    # an analyst curates fields inside the tool-produced nested record
    with open(path) as fh:
        rec = json.load(fh)
    rec["decomposition"]["assessment"] = "launch-bound"
    rec["verdict"] = {"outcome": "NOT MET", "margin": -0.66}
    with open(path, "w") as fh:
        json.dump(rec, fh)
    # the re-run refreshes measured keys only
    out = write_merged(path, {
        "ms_per_step": 13.9,
        "decomposition": {"solve_ms": 12.6, "nonsolve_ms": 1.2},
    })
    assert out["ms_per_step"] == 13.9
    assert out["decomposition"]["nonsolve_ms"] == 1.2
    assert out["decomposition"]["assessment"] == "launch-bound"  # survives
    assert out["verdict"] == {"outcome": "NOT MET", "margin": -0.66}
    # a type change on a key replaces wholesale (new wins)
    out = write_merged(path, {"verdict": "MET"})
    assert out["verdict"] == "MET"
