"""Flag-field obstacle cells (ops/obstacle.py).

Tiers:
1. geometry: flag building, thin-wall rejection, mask consistency
2. reduction-to-reference: with an all-fluid flag the masked ops must equal
   the unmasked ones bit-for-bit (same arithmetic), so the obstacle machinery
   provably changes nothing when no obstacle is present
3. physics invariants on a small channel-with-block run: zero velocity inside
   the obstacle, bounded divergence in fluid cells, faster flow in the gaps
"""

import jax.numpy as jnp
import numpy as np
import pytest

from pampi_tpu.models.ns2d import NS2DSolver
from pampi_tpu.ops import ns2d as ops
from pampi_tpu.ops import obstacle as obst
from pampi_tpu.utils.params import Parameter


def test_parse_obstacles():
    assert obst.parse_obstacles("") == []
    assert obst.parse_obstacles(" ; ") == []
    assert obst.parse_obstacles("1,2,3,4") == [(1.0, 2.0, 3.0, 4.0)]
    # corners given in any order are normalized
    assert obst.parse_obstacles("3,4,1,2;0,0,1,1") == [
        (1.0, 2.0, 3.0, 4.0),
        (0.0, 0.0, 1.0, 1.0),
    ]
    with pytest.raises(ValueError):
        obst.parse_obstacles("1,2,3")


def test_build_fluid_geometry():
    # 8x8 grid on the unit square: block covering centers in (0.25,0.75)^2
    fluid = obst.build_fluid(8, 8, 1 / 8, 1 / 8, "0.25,0.25,0.75,0.75")
    interior = fluid[1:-1, 1:-1]
    # cell centers (i-0.5)/8: inside for i in {3..6}
    expected = np.ones((8, 8), bool)
    expected[2:6, 2:6] = False
    np.testing.assert_array_equal(interior, expected)
    # ghost ring always fluid
    assert fluid[0].all() and fluid[-1].all()
    assert fluid[:, 0].all() and fluid[:, -1].all()


def test_thin_wall_rejected():
    # 1-cell-thin vertical wall: x covers exactly one cell-center column
    with pytest.raises(ValueError):
        obst.build_fluid(8, 8, 1 / 8, 1 / 8, "0.28,0.2,0.35,0.8")


def test_masks_consistency():
    fluid = obst.build_fluid(8, 8, 1 / 8, 1 / 8, "0.25,0.25,0.75,0.75")
    m = obst.make_masks(fluid, 1 / 8, 1 / 8, 1.7, jnp.float64)
    assert m.any_obstacle
    # u faces: zero wherever either side is obstacle
    uf = np.asarray(m.u_face)
    fl = np.asarray(m.fluid)
    for j in range(1, 9):
        for i in range(1, 8):
            assert uf[j, i] == (fl[j, i] and fl[j, i + 1])
    # factor is 0 exactly on obstacle cells, positive on fluid interior
    fac = np.asarray(m.factor)
    np.testing.assert_array_equal(fac > 0, np.asarray(m.p_mask) > 0)


def _all_fluid_masks(imax, jmax, dx, dy, omg, dtype):
    fluid = obst.build_fluid(imax, jmax, dx, dy, "")
    return obst.make_masks(fluid, dx, dy, omg, dtype)


def test_all_fluid_reduces_to_reference_ops():
    """No obstacles -> every masked op equals its unmasked counterpart."""
    rng = np.random.default_rng(0)
    imax = jmax = 16
    dx = dy = 1.0 / 16
    m = _all_fluid_masks(imax, jmax, dx, dy, 1.7, jnp.float64)
    assert not m.any_obstacle
    shape = (jmax + 2, imax + 2)
    u = jnp.asarray(rng.standard_normal(shape))
    v = jnp.asarray(rng.standard_normal(shape))
    p = jnp.asarray(rng.standard_normal(shape))
    rhs = jnp.asarray(rng.standard_normal(shape))

    # velocity BC is the identity
    u2, v2 = obst.apply_obstacle_velocity_bc(u, v, m)
    np.testing.assert_array_equal(np.asarray(u2), np.asarray(u))
    np.testing.assert_array_equal(np.asarray(v2), np.asarray(v))

    # masked F/G is the identity
    f, g = ops.compute_fg(u, v, 0.01, 100.0, 0.0, 0.0, 0.9, dx, dy)
    f2, g2 = obst.mask_fg(f, g, u, v, m)
    np.testing.assert_array_equal(np.asarray(f2), np.asarray(f))
    np.testing.assert_array_equal(np.asarray(g2), np.asarray(g))

    # masked SOR pass equals the uniform pass
    from pampi_tpu.ops.sor import checkerboard_mask, sor_pass

    idx2, idy2 = 1.0 / (dx * dx), 1.0 / (dy * dy)
    red = checkerboard_mask(jmax, imax, 0, jnp.float64)
    factor = 1.7 * 0.5 * (dx * dx * dy * dy) / (dx * dx + dy * dy)
    p_a, r_a = sor_pass(p, rhs, red, factor, idx2, idy2)
    p_b, r_b = obst.sor_pass_obstacle(p, rhs, red, m, idx2, idy2)
    np.testing.assert_allclose(np.asarray(p_b), np.asarray(p_a), atol=1e-14)
    np.testing.assert_allclose(float(r_b), float(r_a), rtol=1e-13)

    # masked projection equals the reference projection
    ua, va = ops.adapt_uv(u, v, f, g, p, 0.01, dx, dy)
    ub, vb = obst.adapt_uv_obstacle(u, v, f, g, p, 0.01, dx, dy, m)
    np.testing.assert_allclose(np.asarray(ub), np.asarray(ua), atol=1e-14)
    np.testing.assert_allclose(np.asarray(vb), np.asarray(va), atol=1e-14)


def test_obstacle_velocity_bc_mirrors():
    """Tangential ghosts mirror the adjacent fluid value; normals are zero."""
    fluid = obst.build_fluid(8, 8, 1 / 8, 1 / 8, "0.25,0.25,0.75,0.75")
    m = obst.make_masks(fluid, 1 / 8, 1 / 8, 1.7, jnp.float64)
    rng = np.random.default_rng(1)
    u = jnp.asarray(rng.standard_normal((10, 10)))
    v = jnp.asarray(rng.standard_normal((10, 10)))
    u2, v2 = obst.apply_obstacle_velocity_bc(u, v, m)
    u2, v2 = np.asarray(u2), np.asarray(v2)
    fl = np.asarray(m.fluid) > 0
    # obstacle interior cells {3..6}x{3..6} (1-based j,i)
    # normal faces: u on the vertical obstacle walls is zero
    for j in range(3, 7):
        assert u2[j, 2] == 0.0 and u2[j, 6] == 0.0  # faces into the block
    # tangential ghost one row below the top fluid region: mirrors row above
    for i in range(3, 6):
        np.testing.assert_allclose(u2[6, i], -u2[7, i])
        np.testing.assert_allclose(u2[3, i], -u2[2, i])
        np.testing.assert_allclose(v2[i, 6], -v2[i, 7])
        np.testing.assert_allclose(v2[i, 3], -v2[i, 2])
    # deep interior faces (both cells obstacle, no adjacent fluid face) are 0
    assert u2[4, 4] == 0.0 and v2[4, 4] == 0.0


def test_canal_obstacle_run_invariants():
    """Small channel with a block: runs, stays finite, no flow through any
    obstacle face, flow accelerates in the gaps beside the block.

    (No tight divergence bound here: the canal's inflow/outflow startup makes
    the all-Neumann pressure system incompatible, so its SOR stalls at the
    incompatibility floor — the plain canal behaves identically, and the
    reference's does too; the mass-closed divergence invariant is checked in
    test_dcavity_obstacle_divergence below.)"""
    param = Parameter(
        name="canal_obstacle",
        imax=64,
        jmax=16,
        xlength=8.0,
        ylength=2.0,
        re=100.0,
        te=1.0,
        tau=0.5,
        itermax=500,
        eps=1e-6,
        omg=1.7,
        gamma=0.9,
        u_init=1.0,
        bcLeft=3,
        bcRight=3,
        obstacles="2.0,0.75,3.0,1.25",
        tpu_dtype="float64",
    )
    s = NS2DSolver(param)
    assert s.masks is not None and s.masks.any_obstacle
    s.run(progress=False)
    u, v, p = np.asarray(s.u), np.asarray(s.v), np.asarray(s.p)
    assert np.isfinite(u).all() and np.isfinite(v).all() and np.isfinite(p).all()

    uf = np.asarray(s.masks.u_face) > 0
    vf = np.asarray(s.masks.v_face) > 0
    fl = np.asarray(s.masks.fluid) > 0
    # no flow through obstacle-wall faces (faces between fluid and obstacle)
    wall_u = (~uf) & (fl | np.roll(fl, -1, axis=1))
    wall_v = (~vf) & (fl | np.roll(fl, -1, axis=0))
    assert np.abs(u[wall_u]).max() < 1e-14
    assert np.abs(v[wall_v]).max() < 1e-14

    # continuity: flow squeezed through the gaps is faster than the inflow peak
    dx = s.dx
    inflow_peak = u[1:-1, 0].max()
    # obstacle occupies x in (2,3): columns i where center in that range
    icols = [i for i in range(1, 65) if 2.0 < (i - 0.5) * dx < 3.0]
    gap_max = u[1:-1, icols].max()
    assert gap_max > inflow_peak


def test_dcavity_obstacle_divergence():
    """Mass-closed box (lid-driven cavity) with a block: the pressure system
    is compatible, so the projection must keep the fluid-cell divergence at
    solver tolerance — the real correctness invariant of the eps-coefficient
    obstacle SOR."""
    param = Parameter(
        name="dcavity",
        imax=32,
        jmax=32,
        re=10.0,
        te=0.5,
        tau=0.5,
        itermax=2000,
        eps=1e-8,
        omg=1.7,
        gamma=0.9,
        obstacles="0.3,0.3,0.6,0.6",
        tpu_dtype="float64",
    )
    s = NS2DSolver(param)
    assert s.masks is not None and s.masks.any_obstacle
    s.run(progress=False)
    u, v = np.asarray(s.u), np.asarray(s.v)
    assert np.isfinite(u).all() and np.isfinite(v).all()
    div = (u[1:-1, 1:-1] - u[1:-1, :-2]) / s.dx + (
        v[1:-1, 1:-1] - v[:-2, 1:-1]
    ) / s.dy
    fl = np.asarray(s.masks.fluid)[1:-1, 1:-1] > 0
    assert np.abs(div[fl]).max() < 1e-3
    # the lid still drives a recirculation around the block
    assert np.abs(u[1:-1, 1:-1]).max() > 1e-3


@pytest.mark.parametrize("n_inner", [1, 2, 3])
def test_masked_pallas_kernel_matches_jnp(n_inner):
    """The flag-masked temporal-blocked kernel must equal n_inner jnp
    eps-coefficient RB iterations cell-for-cell (interpret mode), including
    the last-iteration residual."""
    from pampi_tpu.ops.sor import checkerboard_mask, neumann_bc
    from pampi_tpu.ops.sor_pallas import (
        make_rb_iter_tblock,
        pad_array,
        unpad_array,
    )

    imax, jmax = 48, 40
    dx, dy = 1.0 / imax, 1.0 / jmax
    omega = 1.7
    fluid = obst.build_fluid(imax, jmax, dx, dy, "0.3,0.3,0.6,0.7")
    m = obst.make_masks(fluid, dx, dy, omega, jnp.float64)
    idx2, idy2 = 1.0 / (dx * dx), 1.0 / (dy * dy)
    red = checkerboard_mask(jmax, imax, 0, jnp.float64)
    black = checkerboard_mask(jmax, imax, 1, jnp.float64)

    rng = np.random.default_rng(3)
    p0 = jnp.asarray(rng.standard_normal((jmax + 2, imax + 2)))
    rhs = jnp.asarray(rng.standard_normal((jmax + 2, imax + 2)))

    rb, br, h = make_rb_iter_tblock(
        imax, jmax, dx, dy, omega, jnp.float64, n_inner=n_inner,
        block_rows=16, interpret=True, fluid=fluid,
    )

    p_j = p0
    for _ in range(n_inner):
        p_j, r0 = obst.sor_pass_obstacle(p_j, rhs, red, m, idx2, idy2)
        p_j, r1 = obst.sor_pass_obstacle(p_j, rhs, black, m, idx2, idy2)
        p_j = neumann_bc(p_j)
    p_p, rsq = rb(pad_array(p0, br, h), pad_array(rhs, br, h))
    np.testing.assert_allclose(
        np.asarray(unpad_array(p_p, jmax, imax, h)), np.asarray(p_j),
        atol=1e-12,
    )
    np.testing.assert_allclose(float(rsq), float(r0 + r1), rtol=1e-11)


def test_obstacle_solver_converges():
    """The eps-coefficient SOR drives the masked residual below eps."""
    imax = jmax = 32
    dx = dy = 1.0 / 32
    fluid = obst.build_fluid(imax, jmax, dx, dy, "0.4,0.4,0.7,0.7")
    m = obst.make_masks(fluid, dx, dy, 1.7, jnp.float64)
    solve = obst.make_obstacle_solver_fn(
        imax, jmax, dx, dy, 1e-7, 5000, m, jnp.float64
    )
    rng = np.random.default_rng(2)
    p0 = jnp.zeros((jmax + 2, imax + 2))
    rhs = jnp.asarray(rng.standard_normal((jmax + 2, imax + 2)))
    # Neumann-compatible rhs over the fluid region (zero fluid-mean)
    flm = np.asarray(m.fluid) > 0
    r = np.array(rhs)  # writable copy
    r[1:-1, 1:-1] -= r[1:-1, 1:-1][flm[1:-1, 1:-1]].mean()
    r[~flm] = 0.0
    p, res, it = solve(p0, jnp.asarray(r))
    assert float(res) < 1e-14  # eps^2
    assert 0 < int(it) < 5000
    assert np.isfinite(np.asarray(p)).all()


def test_canal_obstacle_dist_matches_single():
    """Distributed obstacle NS-2D (shard-sliced static masks,
    exchange-per-half-sweep eps-coefficient solve) must reproduce the
    single-device run exactly on a 2-D mesh."""
    import numpy as np

    from pampi_tpu.models.ns2d import NS2DSolver
    from pampi_tpu.models.ns2d_dist import NS2DDistSolver
    from pampi_tpu.parallel.comm import CartComm
    from pampi_tpu.utils.params import Parameter

    param = Parameter(
        name="canal_obstacle", imax=64, jmax=32, xlength=4.0, ylength=1.0,
        re=100.0, te=0.05, tau=0.5, itermax=200, eps=1e-4, omg=1.7,
        gamma=0.9, bcLeft=3, bcRight=3, bcBottom=1, bcTop=1,
        obstacles="1.0,0.3,1.5,0.7",
    )
    single = NS2DSolver(param)
    single.run(progress=False)
    for dims in [(2, 4), (1, 8)]:
        dist = NS2DDistSolver(param, CartComm(ndims=2, dims=dims))
        dist.run(progress=False)
        ud, vd, pd = dist.fields()
        assert dist.nt == single.nt, dims
        np.testing.assert_array_equal(np.asarray(single.u), ud)
        np.testing.assert_array_equal(np.asarray(single.v), vd)
        np.testing.assert_array_equal(np.asarray(single.p), pd)


def test_obstacle_dist_rejects_fft_accepts_mg():
    """fft structurally cannot solve flag fields on a mesh either; mg now
    can (make_dist_obstacle_mg_solve_2d, round 4)."""
    import pytest as _pytest

    from pampi_tpu.models.ns2d_dist import NS2DDistSolver
    from pampi_tpu.parallel.comm import CartComm
    from pampi_tpu.utils.params import Parameter

    param = Parameter(
        name="canal_obstacle", imax=32, jmax=16, re=100.0, te=1.0,
        obstacles="0.3,0.2,0.5,0.4", tpu_solver="fft",
    )
    with _pytest.raises(ValueError, match="obstacle"):
        NS2DDistSolver(param, CartComm(ndims=2))
    NS2DDistSolver(param.replace(tpu_solver="mg"), CartComm(ndims=2))  # builds


def test_canal_obstacle_dist_ca_inner2():
    """Deep-halo CA with n=2 local iterations: iteration-capped run (itermax
    even, eps tiny) must stay bitwise-equal to single device."""
    import numpy as np

    from pampi_tpu.models.ns2d import NS2DSolver
    from pampi_tpu.models.ns2d_dist import NS2DDistSolver
    from pampi_tpu.parallel.comm import CartComm
    from pampi_tpu.utils.params import Parameter

    param = Parameter(
        name="canal_obstacle", imax=64, jmax=32, xlength=4.0, ylength=1.0,
        re=100.0, te=0.02, tau=0.5, itermax=40, eps=1e-30, omg=1.7,
        gamma=0.9, bcLeft=3, bcRight=3, bcBottom=1, bcTop=1,
        obstacles="1.0,0.3,1.5,0.7", tpu_ca_inner=2,
    )
    single = NS2DSolver(param)
    single.run(progress=False)
    dist = NS2DDistSolver(param, CartComm(ndims=2, dims=(2, 4)))
    dist.run(progress=False)
    ud, vd, pd = dist.fields()
    assert dist.nt == single.nt
    np.testing.assert_array_equal(np.asarray(single.u), ud)
    np.testing.assert_array_equal(np.asarray(single.v), vd)
    np.testing.assert_array_equal(np.asarray(single.p), pd)
