"""Autopilot — the self-healing elastic control plane (ISSUE 19,
fleet/autopilot.py) — policy-loop contracts:

- SPEC GRAMMAR: `tpu_autopilot` / priority specs parse with loud
  failures (a mistyped policy knob must never silently run a different
  policy);
- HYSTERESIS: a sustained burn grows the pool EXACTLY ONCE across the
  band (then walks the ladder), a burn inside the band holds, and the
  whole storm records ZERO flaps — driven through a fake daemon stub,
  no solver in the loop;
- LADDER: one rung per decision in both directions, rung 1 flips the
  scheduler to class consolidation and recovery restores the saved
  mode, the breach→full-service clock closes once;
- HEAL: a death (raw injection or structured RankDeadError) shrinks
  capacity to the survivors, bumps the epoch, and clamps the lane pool
  to what is left — never a flap;
- QoS: priority classes weight admission quotas (floor 1 — throttled,
  never locked out), rung 3 sheds only the lowest class, rung 2 caps
  itermax at admission;
- PREEMPT PARITY: the scheduler-level park/resume roundtrip leaves
  every tenant's fields bitwise-identical to a flat run of the same
  requests (the parked-lane manifest is lossless);
- OFF IS OFF: the default daemon constructs NO autopilot — poll-site
  fault clauses stay inert, no autoscale records, no status block, no
  scheduler hooks (the byte-identity pin for the policy-less build);
- ADMISSION ROBUSTNESS: deferred files age to the front of the scan
  (starvation fix) and earn one `starving` record past the alert
  threshold; parked/ keeps a bounded census with `parked_max`
  retention.
"""

import json
import time
import types
import warnings

import numpy as np
import pytest

from pampi_tpu import fleet
from pampi_tpu.fleet import autopilot as ap_mod
from pampi_tpu.fleet.autopilot import (
    LADDER,
    Autopilot,
    ParkStore,
    parse_autopilot_spec,
    parse_priority_spec,
)
from pampi_tpu.utils import faultinject as fi
from pampi_tpu.utils import telemetry as tm
from pampi_tpu.utils.params import Parameter

PAR = ("name dcavity\nimax 12\njmax 12\nre 10.0\nte 0.02\ntau 0.5\n"
       "itermax 8\neps 0.0001\nomg 1.7\ngamma 0.9\ntpu_mesh 1\n")


@pytest.fixture()
def tel_on(tmp_path, monkeypatch):
    path = tmp_path / "run.jsonl"
    monkeypatch.setenv("PAMPI_TELEMETRY", str(path))
    tm.reset()
    yield path
    tm.reset()


def _records(path):
    return [json.loads(ln) for ln in open(path) if ln.strip()]


# ---------------------------------------------------------------------------
# spec grammar
# ---------------------------------------------------------------------------

def test_parse_autopilot_spec():
    assert parse_autopilot_spec("") is None
    assert parse_autopilot_spec("off") is None
    assert parse_autopilot_spec(None) is None
    cfg = parse_autopilot_spec("on")
    assert (cfg.burn_high, cfg.burn_low, cfg.sustain) == (3.0, 1.0, 2)
    cfg = parse_autopilot_spec("on:burn_high=4.5,sustain=3,max_lanes=8")
    assert cfg.burn_high == 4.5 and cfg.sustain == 3 \
        and cfg.max_lanes == 8
    assert cfg.burn_low == 1.0  # untouched defaults survive overrides
    for bad in ("auto", "on:bogus_key=1", "on:sustain=abc",
                "on:sustain"):
        with pytest.raises(ValueError, match="tpu_autopilot"):
            parse_autopilot_spec(bad)


def test_parse_priority_spec():
    assert parse_priority_spec("") == {}
    assert parse_priority_spec(None) == {}
    got = parse_priority_spec("zoe=high, bob=low ,default=normal")
    assert got == {"zoe": 0, "bob": 2, "default": 1}
    for bad in ("zoe", "zoe=vip", "=high"):
        with pytest.raises(ValueError, match="priority"):
            parse_priority_spec(bad)


# ---------------------------------------------------------------------------
# the fake daemon: policy logic without a solver in the loop
# ---------------------------------------------------------------------------

class _FakeSched:
    def __init__(self, lanes):
        self.classes = "auto"
        self.lanes = lanes
        self.park_store = None
        self.priority_of = None


class _FakeSlo:
    def __init__(self):
        self.burn = 0.0

    def burn_snapshot(self, now):
        return {"alice": self.burn} if self.burn else {}


class _FakeMetrics:
    def histograms(self, name=None):
        return []


class _FakeDaemon:
    def __init__(self, tmp_path, max_lanes=2, priorities="",
                 tenant_quota=8):
        self.cfg = types.SimpleNamespace(
            max_lanes=max_lanes, priorities=priorities,
            queue_dir=str(tmp_path), tenant_quota=tenant_quota)
        self.sched = _FakeSched(max_lanes)
        self.slo = _FakeSlo()
        self.metrics = _FakeMetrics()
        self.polls = 0
        self.queue_depth = 0


def _drive(d, pilot, burn, polls, depth=0):
    for _ in range(polls):
        d.polls += 1
        d.slo.burn = burn
        d.queue_depth = depth
        pilot.tick(time.time())


def test_hysteresis_one_grow_then_ladder_then_recovery(tmp_path):
    """The chaos storm's policy trajectory without the chaos: sustained
    hot grows EXACTLY once (pool cap), then degrades rung by rung to
    the bottom and holds; sustained calm recovers rung by rung to full
    service, closes the time-to-recover clock once, and never flaps."""
    d = _FakeDaemon(tmp_path, max_lanes=2)
    pilot = Autopilot(d, "on:sustain=2,cooldown=2,max_lanes=3,"
                         "idle_polls=99")
    _drive(d, pilot, burn=10.0, polls=10)
    assert pilot.counts["grow"] == 1 and pilot.lanes == 3
    assert d.sched.lanes == 3  # the act writes through to the pool
    assert pilot.counts["degrade"] == 3
    assert pilot.rung == len(LADDER) - 1  # bottom: nothing left to give
    assert d.sched.classes == "on"  # rung 1 forced consolidation
    _drive(d, pilot, burn=10.0, polls=4)
    assert pilot.counts["degrade"] == 3  # bottom rung holds, no churn
    _drive(d, pilot, burn=0.0, polls=12)
    assert pilot.counts["recover"] == 3 and pilot.rung == 0
    assert d.sched.classes == "auto"  # saved mode restored at rung 0
    assert len(pilot.recoveries_ms) == 1  # breach clock closed ONCE
    assert pilot.counts["grow"] == 1  # the storm grew exactly once
    assert pilot.counts["shrink"] == 0  # idle_polls=99 blocks shrink
    assert pilot.flaps == 0


def test_band_interior_holds_and_resets_sustain(tmp_path):
    """Between burn_low and burn_high NOTHING moves and both sustain
    counters reset — the band is the no-flap buffer: hot, hot, band,
    hot, hot must take as long as four consecutive hots from zero."""
    d = _FakeDaemon(tmp_path, max_lanes=2)
    pilot = Autopilot(d, "on:sustain=3,cooldown=0,max_lanes=3")
    _drive(d, pilot, burn=10.0, polls=2)   # above, not sustained
    _drive(d, pilot, burn=2.0, polls=1)    # inside the band: reset
    _drive(d, pilot, burn=10.0, polls=2)   # above again, still short
    assert pilot.counts["grow"] == 0 and pilot.lanes == 2
    _drive(d, pilot, burn=10.0, polls=1)   # third consecutive: act
    assert pilot.counts["grow"] == 1


def test_shrink_on_idle_and_flap_accounting(tmp_path):
    """A sustained EMPTY calm queue shrinks the pool (bounded by
    min_lanes); an opposite-direction capacity move inside flap_window
    is counted — the metric the chaos smoke pins to zero exists and
    fires when hysteresis is configured away."""
    d = _FakeDaemon(tmp_path, max_lanes=2)
    pilot = Autopilot(d, "on:sustain=1,cooldown=0,idle_polls=2,"
                         "min_lanes=1,max_lanes=3,flap_window=6")
    _drive(d, pilot, burn=0.0, polls=2, depth=0)
    assert pilot.counts["shrink"] == 1 and pilot.lanes == 1
    assert pilot.flaps == 0
    _drive(d, pilot, burn=10.0, polls=1)
    assert pilot.counts["grow"] == 1 and pilot.lanes == 2
    assert pilot.flaps == 1  # down then up within the window


def test_heal_shrinks_capacity_and_clamps_pool(tmp_path):
    """heal() drops the casualty from capacity, bumps the epoch and
    clamps the lane pool to the survivors — whether the input is the
    raw poll injection (no verdict: last device is the casualty) or a
    structured RankDeadError naming ranks + epoch."""
    from pampi_tpu.parallel.coordinator import RankDeadError

    d = _FakeDaemon(tmp_path, max_lanes=2)
    pilot = Autopilot(d, "on")
    pilot.devices = pilot.devices[:2]  # 2-device toy capacity
    pilot.heal()  # raw injection: last device dies
    assert len(pilot.devices) == 1 and pilot.epoch == 1
    assert pilot.lanes == 1 and d.sched.lanes == 1  # pool clamped
    assert pilot.counts["heal"] == 1 and pilot.flaps == 0

    d2 = _FakeDaemon(tmp_path, max_lanes=2)
    p2 = Autopilot(d2, "on")
    n = len(p2.devices)
    p2.heal(RankDeadError(ranks=[0, 2], epoch=7))
    assert len(p2.devices) == n - 2 and p2.epoch == 7
    assert p2.counts["heal"] == 1


def test_quota_weighting_shed_and_itermax_cap(tmp_path):
    """QoS plane: quotas weight 2x/1x/0.5x with floor 1; rung 3 sheds
    ONLY the lowest class; rung 2 replaces an admitted request's
    itermax with the cap (and leaves already-cheap requests alone)."""
    from pampi_tpu.fleet import queue as _q

    d = _FakeDaemon(tmp_path, priorities="zoe=high,bob=low",
                    tenant_quota=8)
    pilot = Autopilot(d, "on:itermax_cap=4")
    assert pilot.quota_for("zoe") == 16
    assert pilot.quota_for("alice") == 8   # unlisted -> normal
    assert pilot.quota_for("bob") == 4
    d.cfg.tenant_quota = 1
    assert pilot.quota_for("bob") == 1     # floor: throttled, not out

    assert not pilot.should_shed("bob")    # rung 0: nobody shed
    pilot.rung = len(LADDER) - 1
    assert pilot.should_shed("bob")
    assert not pilot.should_shed("zoe") and not pilot.should_shed("al")

    pilot.rung = LADDER.index("itermax_cap")
    req = _q.ScenarioRequest(sid="bob__x", param=Parameter(
        name="dcavity", imax=12, jmax=12, te=0.02, itermax=50))
    out = pilot.admit(req)
    assert int(out.param.itermax) == 4 and out.sid == "bob__x"
    cheap = _q.ScenarioRequest(sid="bob__y", param=Parameter(
        name="dcavity", imax=12, jmax=12, te=0.02, itermax=3))
    assert pilot.admit(cheap) is cheap     # under the cap: untouched
    pilot.rung = 0
    assert pilot.admit(req) is req         # full service: untouched

    # priorities armed the scheduler's preemption hooks at construction
    assert isinstance(d.sched.park_store, ParkStore)
    assert d.sched.priority_of("zoe__a") == 0
    assert d.sched.priority_of("mallory__a") == 1


def test_autoscale_records_tell_the_decision_story(tmp_path, tel_on):
    """Every tick is one `autoscale` record — holds included — carrying
    rung/lanes/hysteresis; stop metrics emit the trend-gated tallies."""
    d = _FakeDaemon(tmp_path, max_lanes=2)
    pilot = Autopilot(d, "on:sustain=2,cooldown=2,max_lanes=3,"
                         "idle_polls=99")
    _drive(d, pilot, burn=10.0, polls=4)
    _drive(d, pilot, burn=0.0, polls=4)
    pilot.emit_stop_metrics("cpu")
    tm.finalize()
    recs = _records(tel_on)
    auto = [r for r in recs if r["kind"] == "autoscale"]
    assert len(auto) == 8  # one per tick, holds included
    assert all(r["v"] == tm.SCHEMA_VERSION for r in auto)
    assert [r["decision"] for r in auto].count("grow") == 1
    rungs = [r["rung"] for r in auto]
    assert all(abs(b - a) <= 1 for a, b in zip(rungs, rungs[1:]))
    assert all({"lanes", "capacity", "hysteresis"} <= r.keys()
               for r in auto)
    stop = {r["metric"]: r["value"] for r in recs
            if r["kind"] == "metric"}
    assert stop["autoscale_flaps"] == 0
    assert stop["autoscale_transitions"] == sum(
        pilot.counts[k] for k in ("heal", "grow", "shrink", "degrade",
                                  "recover"))


# ---------------------------------------------------------------------------
# scheduler-level preemption parity
# ---------------------------------------------------------------------------

def test_preempt_park_resume_bitwise_parity(tmp_path):
    """2 low + 1 high over a 2-lane class pool: the high-priority
    arrival evicts a running low lane through a parked-lane manifest
    and the victim resumes bitwise — every sid's fields identical to
    the same requests served with no priorities at all."""
    from pampi_tpu.fleet.scheduler import FleetScheduler

    fleet.reset_templates()

    def reqs():
        return ([(f"bob__s{i}",
                  Parameter(name="dcavity", imax=12, jmax=12, re=10.0,
                            te=0.02 + 0.005 * i, tau=0.5, itermax=8,
                            eps=1e-4, omg=1.7, gamma=0.9,
                            tpu_mesh="1"))
                 for i in range(2)]
                + [("zoe__s9",
                    Parameter(name="dcavity", imax=12, jmax=12,
                              re=10.0, te=0.02, tau=0.5, itermax=8,
                              eps=1e-4, omg=1.7, gamma=0.9,
                              tpu_mesh="1"))])

    armed = FleetScheduler(classes="on", lanes=2, isolate=False)
    armed.park_store = ParkStore(str(tmp_path / "park"))
    armed.priority_of = lambda sid: 0 if sid.startswith("zoe") else 2
    flat = FleetScheduler(classes="on", lanes=2, isolate=False)
    for sid, p in reqs():
        armed.submit_param(sid, p)
    for sid, p in reqs():
        flat.submit_param(sid, p)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        res_a = {s.sid: s for s in armed.run().scenarios}
        res_f = {s.sid: s for s in flat.run().scenarios}
    assert armed.park_store.parked_total == 1  # one victim parked...
    assert armed.park_store.resumed_total == 1  # ...and resumed
    assert res_a.keys() == res_f.keys()
    for sid, a in res_a.items():
        f = res_f[sid]
        assert a.nt == f.nt and a.t == f.t, sid
        for x, y in zip(a.fields, f.fields):
            assert np.array_equal(np.asarray(x), np.asarray(y)), sid


# ---------------------------------------------------------------------------
# daemon integration: off is off, admission robustness
# ---------------------------------------------------------------------------

def test_daemon_off_is_byte_identical(tmp_path, monkeypatch, tel_on):
    """The default daemon constructs NO autopilot: poll-site fault
    clauses stay inert (nothing bumps the counter), the scheduler's
    preemption hooks stay None, status carries no autopilot block and
    the flight record no autoscale records — the policy-less build."""
    from pampi_tpu.fleet import FleetDaemon, ServeConfig

    fleet.reset_templates()
    monkeypatch.setenv("PAMPI_FAULTS", "dead@poll1,burst@poll1:alice*9")
    fi.reset()
    qdir = tmp_path / "queue"
    qdir.mkdir()
    (qdir / "alice__a.par").write_text(PAR)
    daemon = FleetDaemon(ServeConfig(
        queue_dir=str(qdir), poll_s=0.01, max_lanes=2, max_polls=1))
    assert daemon.autopilot is None
    assert daemon.sched.park_store is None
    assert daemon.sched.priority_of is None
    assert not daemon.sched.raise_rank_death
    assert daemon.run() == 0  # the armed death clause never fires
    assert daemon.served == 1
    st = json.loads((qdir / "status.json").read_text())
    assert "autopilot" not in st and "shed" not in st
    st["parked_census"].pop("oldest_age_s")
    assert st["parked_census"] == {"count": 0, "max": 0}
    tm.finalize()
    assert not [r for r in _records(tel_on)
                if r["kind"] == "autoscale"]
    monkeypatch.delenv("PAMPI_FAULTS")
    fi.reset()


def test_daemon_on_polls_record_and_status(tmp_path, monkeypatch,
                                           tel_on):
    """With the knob on, an idle daemon still tells its story: burst
    injections land in the SLO window, every poll emits one autoscale
    record, and the status block reports the policy posture."""
    from pampi_tpu.fleet import FleetDaemon, ServeConfig

    fleet.reset_templates()
    monkeypatch.setenv("PAMPI_FAULTS", "burst@poll2:alice*5")
    fi.reset()
    qdir = tmp_path / "queue"
    qdir.mkdir()
    daemon = FleetDaemon(ServeConfig(
        queue_dir=str(qdir), poll_s=0.01, slo="alice=800",
        autopilot="on:sustain=99", priorities="zoe=high,bob=low"))
    assert daemon.sched.raise_rank_death
    daemon.poll_once()
    daemon.poll_once()
    st = daemon.status()
    ab = st["autopilot"]
    assert ab["mode"] == "on" and ab["rung"] == 0
    assert ab["parked_lanes"] == 0 and ab["flaps"] == 0
    daemon.stop()
    tm.finalize()
    recs = _records(tel_on)
    auto = [r for r in recs if r["kind"] == "autoscale"]
    assert [r["decision"] for r in auto].count("hold") == 2
    inj = [r for r in auto if r["decision"] == "inject"]
    assert inj and inj[0]["fault"] == "burst" \
        and inj[0]["injected"] == 5
    assert any(r["kind"] == "metric"
               and r["metric"] == "autoscale_flaps" for r in recs)
    monkeypatch.delenv("PAMPI_FAULTS")
    fi.reset()


def test_defer_aging_boosts_starved_files(tmp_path, monkeypatch):
    """The starvation fix: a file deferred for polls outranks newer
    lexically-earlier arrivals at the next scan, and one `admission`
    action="starving" record fires past defer_alert_polls."""
    from pampi_tpu.fleet import FleetDaemon, ServeConfig

    fleet.reset_templates()
    jsonl = tmp_path / "run.jsonl"
    monkeypatch.setenv("PAMPI_TELEMETRY", str(jsonl))
    tm.reset()
    qdir = tmp_path / "queue"
    qdir.mkdir()
    daemon = FleetDaemon(ServeConfig(
        queue_dir=str(qdir), poll_s=0.01, tenant_quota=1,
        max_queue=0, defer_alert_polls=2))
    (qdir / "alice__old.par").write_text(PAR)
    # max_queue=0: every scan defers — the deferral counter climbs
    for _ in range(3):
        assert daemon.scan() == []
    assert daemon.deferred == 3
    # a newer, lexically EARLIER file must not starve the old one
    (qdir / "alice__aaa.par").write_text(PAR)
    daemon.cfg.max_queue = 64  # admit again; tenant_quota=1 -> one slot
    accepted = daemon.scan()
    assert [r.sid for r in accepted] == ["alice__old"]
    tm.reset()
    recs = _records(jsonl)
    starving = [r for r in recs if r["kind"] == "admission"
                and r["action"] == "starving"]
    assert len(starving) == 1  # one-shot per starvation episode
    assert starving[0]["sid"] == "alice__old"
    assert starving[0]["deferrals"] == 3 and starving[0]["boost_active"]


def test_parked_census_and_retention(tmp_path, monkeypatch):
    """parked/ is bounded: parked_max keeps the newest N malformed
    files (oldest evicted with a warning record) and status.json
    carries the census either way."""
    import os

    from pampi_tpu.fleet import FleetDaemon, ServeConfig

    fleet.reset_templates()
    jsonl = tmp_path / "run.jsonl"
    monkeypatch.setenv("PAMPI_TELEMETRY", str(jsonl))
    tm.reset()
    qdir = tmp_path / "queue"
    qdir.mkdir()
    daemon = FleetDaemon(ServeConfig(
        queue_dir=str(qdir), poll_s=0.01, parked_max=2))
    now = time.time()
    for i in range(4):
        p = qdir / f"mallory__bad{i}.par"
        p.write_text("name dcavity\nimax zzz\n")
    assert daemon.scan() == []  # all four park
    # age-order the parked files deterministically, then re-run the
    # retention pass (mtime ties inside one scan are sort-unstable)
    for i in range(4):
        dest = os.path.join(daemon.parked_dir, f"mallory__bad{i}.par")
        if os.path.exists(dest):
            os.utime(dest, (now + i, now + i))
    daemon._retain_parked()
    kept = sorted(os.listdir(daemon.parked_dir))
    assert kept == ["mallory__bad2.par", "mallory__bad3.par"]
    census = daemon.status()["parked_census"]
    assert census["count"] == 2 and census["max"] == 2
    assert census["oldest_age_s"] is not None
    tm.reset()
    recs = _records(jsonl)
    evicted = [r for r in recs if r["kind"] == "warning"
               and r.get("reason") == "parked_evicted"]
    assert evicted and evicted[0]["parked_max"] == 2


def test_shed_writes_structured_failure(tmp_path, monkeypatch):
    """Rung 3 at admission: the lowest class is refused NOW with a
    structured shed result — a decision the tenant can read, never a
    silent stall; higher classes pass the same scan."""
    from pampi_tpu.fleet import FleetDaemon, ServeConfig

    fleet.reset_templates()
    monkeypatch.setenv("PAMPI_TELEMETRY", str(tmp_path / "s.jsonl"))
    tm.reset()
    qdir = tmp_path / "queue"
    qdir.mkdir()
    daemon = FleetDaemon(ServeConfig(
        queue_dir=str(qdir), poll_s=0.01,
        autopilot="on", priorities="bob=low"))
    daemon.autopilot.rung = len(LADDER) - 1
    (qdir / "bob__x.par").write_text(PAR)
    (qdir / "alice__y.par").write_text(PAR)
    accepted = daemon.scan()
    assert [r.sid for r in accepted] == ["alice__y"]
    assert daemon.shed == 1 and daemon.failed == 1
    assert not (qdir / "bob__x.par").exists()
    row = json.loads((qdir / "results" / "bob__x.json").read_text())
    assert row["failed"] and row["shed"] and "shed" in row["error"]
    tm.reset()


def test_ladder_and_classes_are_the_module_constants():
    """The README/telemetry contract: the ladder names and priority
    classes are stable, ordered identifiers (records store indexes)."""
    assert LADDER == ("full_service", "class_consolidation",
                      "itermax_cap", "shed_low_priority")
    assert ap_mod.PRIORITY_CLASSES == {"high": 0, "normal": 1, "low": 2}
    assert ap_mod.PRIORITY_WEIGHTS[0] > ap_mod.PRIORITY_WEIGHTS[1] \
        > ap_mod.PRIORITY_WEIGHTS[2]


def test_report_merge_folds_autoscale_block(tmp_path, tel_on):
    """The `--merge` plane (tools/telemetry_report.main) folds the
    autoscale block into the artifact like every other summary — the
    chaos harness builds its artifact directly, so this is the pin
    that keeps the daemon's own merge path honest."""
    import json as _json

    from tools import check_artifact as ca
    from tools import telemetry_report as tr

    d = _FakeDaemon(tmp_path, max_lanes=2)
    pilot = Autopilot(d, "on:sustain=2,cooldown=2,max_lanes=3,"
                         "idle_polls=99")
    _drive(d, pilot, burn=10.0, polls=4)
    _drive(d, pilot, burn=0.0, polls=8)
    pilot.emit_stop_metrics("cpu")
    tm.finalize()
    art = tmp_path / "ART.json"
    assert tr.main(["telemetry_report", str(tel_on),
                    "--merge", str(art)]) == 0
    merged = _json.loads(art.read_text())
    asc = merged["autoscale"]
    assert ca.lint_autoscale(asc, "A") == []
    assert asc["decisions"]["grow"] == 1
    assert asc["flaps"] == 0 and asc["time_to_recover_ms"] is not None
    names = {m["name"] for m in merged["metrics"]}
    assert {"autoscale_flaps", "autoscale_time_to_recover_ms"} <= names
