"""Config-layer tests: .par parsing parity with the reference's strncmp-prefix
parser (assignment-5/sequential/src/parameter.c:29-86)."""

import pathlib

from pampi_tpu.utils.params import Parameter, read_parameter


def test_defaults():
    p = Parameter()
    assert p.imax == 100 and p.jmax == 100
    assert p.omg == 1.7 and p.eps == 0.0001


def test_parse_reference_poisson_par(reference_dir):
    p = read_parameter(str(reference_dir / "assignment-4" / "poisson.par"))
    assert p.name == "poisson"
    assert p.imax == 100 and p.jmax == 100
    assert p.itermax == 1000000
    assert p.eps == 1e-6
    assert p.omg == 1.9
    assert p.xlength == 1.0 and p.ylength == 1.0


def test_parse_reference_dcavity_par(reference_dir):
    p = read_parameter(
        str(reference_dir / "assignment-5" / "sequential" / "dcavity.par")
    )
    assert p.name == "dcavity"
    assert p.bcTop == p.bcBottom == p.bcLeft == p.bcRight == 1
    assert p.re == 10.0
    assert p.te == 10.0 and p.dt == 0.02 and p.tau == 0.5
    assert p.itermax == 1000 and p.eps == 0.001 and p.omg == 1.8
    assert p.gamma == 0.9
    assert p.u_init == 0.0 and p.v_init == 0.0 and p.p_init == 0.0


def test_parse_reference_canal_par(reference_dir):
    p = read_parameter(str(reference_dir / "assignment-5" / "sequential" / "canal.par"))
    assert p.name == "canal"
    assert p.bcLeft == 3 and p.bcRight == 3
    assert p.xlength == 30.0 and p.ylength == 4.0
    assert p.imax == 200 and p.jmax == 50
    assert p.u_init == 1.0


def test_prefix_match(tmp_path):
    # reference semantics: strncmp prefix match — `imaxFoo 7` still sets imax
    f = tmp_path / "t.par"
    f.write_text("imaxFoo 7\nunknownKey 3\n# comment imax 9\neps 0.5 # trail\n")
    p = read_parameter(str(f))
    assert p.imax == 7
    assert p.eps == 0.5


def test_exact_key_wins_over_prefix(tmp_path):
    """The framework keys are namespaced (tpu_coord / tpu_coord_timeout)
    where the reference's key set is prefix-free: an EXACT key token
    assigns only itself — `tpu_coord_timeout 60` must not clobber
    tpu_coord — while non-exact tokens keep the reference's strncmp
    prefix semantics (test_prefix_match)."""
    f = tmp_path / "t.par"
    f.write_text("tpu_coord  on\ntpu_coord_timeout 60\n")
    p = read_parameter(str(f))
    assert p.tpu_coord == "on"
    assert p.tpu_coord_timeout == 60.0


def test_comments_and_blank_lines(tmp_path):
    f = tmp_path / "t.par"
    f.write_text("\n\n# full comment\nomg 1.5\t# inline\n\n")
    p = read_parameter(str(f))
    assert p.omg == 1.5
