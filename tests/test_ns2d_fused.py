"""Fused NS-2D step-phase kernels (ops/ns2d_fused.py) vs the jnp chain.

Equivalence contract (module docstring of ns2d_fused): pure-copy phases
(BC strips, selects, maxes) are BITWISE identical — pinned with
array_equal; the compound F/G/RHS/projection arithmetic is the SAME
formula function and differs only by compiler fusion (fma), pinned at
ulp-scale tolerances relative to the field scale. Interpret-mode Pallas on
the CPU mesh throughout (the repo's kernel-parity discipline)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pampi_tpu.models.ns2d import NS2DSolver
from pampi_tpu.ops import ns2d as ops
from pampi_tpu.ops import ns2d_fused as nf
from pampi_tpu.utils import dispatch
from pampi_tpu.utils.params import Parameter


def _ulp_close(a, b, scale=None):
    a, b = np.asarray(a), np.asarray(b)
    tol = 1e-12 if a.dtype == np.float64 else 2e-5
    s = max(1.0, np.abs(b).max() if scale is None else scale)
    return np.abs(a - b).max() <= tol * s


def _jnp_chain(param, u, v, p, dt, dx, dy, dtype):
    u1, v1 = ops.set_boundary_conditions(
        u, v, param.bcLeft, param.bcRight, param.bcBottom, param.bcTop
    )
    if param.name == "dcavity":
        u1 = ops.set_special_bc_dcavity(u1)
    elif param.name in ("canal", "canal_obstacle"):
        u1 = ops.set_special_bc_canal(u1, dy, param.ylength, dtype)
    f, g = ops.compute_fg(u1, v1, dt, param.re, param.gx, param.gy,
                          param.gamma, dx, dy)
    rhs = ops.compute_rhs(f, g, dt, dx, dy)
    u2, v2 = ops.adapt_uv(u1, v1, f, g, p, dt, dx, dy)
    return u1, v1, f, g, rhs, u2, v2


@pytest.mark.parametrize("problem,bcs", [
    ("dcavity", (1, 1, 1, 1)),
    ("canal", (3, 3, 1, 1)),
    ("dcavity", (2, 2, 2, 2)),
    ("canal", (3, 1, 2, 1)),
])
@pytest.mark.parametrize("shape", [(32, 32), (40, 24)])
def test_phase_parity(problem, bcs, shape):
    jm, im = shape
    param = Parameter(name=problem, imax=im, jmax=jm, re=100.0, gamma=0.9,
                      bcLeft=bcs[0], bcRight=bcs[1], bcBottom=bcs[2],
                      bcTop=bcs[3])
    dx, dy = param.xlength / im, param.ylength / jm
    rng = np.random.default_rng(7)
    u = jnp.asarray(rng.normal(size=(jm + 2, im + 2)))
    v = jnp.asarray(rng.normal(size=(jm + 2, im + 2)))
    p = jnp.asarray(rng.normal(size=(jm + 2, im + 2)))
    dt = jnp.asarray(0.013)
    u1, v1, f, g, rhs, u2, v2 = _jnp_chain(
        param, u, v, p, dt, dx, dy, jnp.float64)

    pre, post, pad, unpad, _h = nf.make_fused_step_2d(
        param, jm, im, dx, dy, jnp.float64, interpret=True)
    offs = jnp.zeros((2,), jnp.int32)
    dt11 = jnp.full((1, 1), dt)
    up, vp, fp, gp, rp = pre(offs, dt11, pad(u), pad(v))
    # BC phases are pure copies/negations -> bitwise
    assert jnp.array_equal(unpad(up), u1)
    assert jnp.array_equal(unpad(vp), v1)
    # compound arithmetic: ulp-equivalent (shared formula, fma differences)
    assert _ulp_close(unpad(fp), f)
    assert _ulp_close(unpad(gp), g)
    assert _ulp_close(unpad(rp), rhs, scale=float(jnp.abs(rhs).max()))
    up2, vp2, um, vm = post(offs, dt11, up, vp, fp, gp, pad(p))
    assert _ulp_close(unpad(up2), u2)
    assert _ulp_close(unpad(vp2), v2)
    # max given equal inputs is exact; here inputs are ulp-apart
    assert abs(float(um) - float(ops.max_element(u2))) <= 1e-12
    assert abs(float(vm) - float(ops.max_element(v2))) <= 1e-12


def test_multiblock_pipeline():
    """Forced small block_rows exercises the double-buffered DMA pipeline,
    halo recompute, and the tail block across block boundaries."""
    jm, im = 100, 48
    param = Parameter(name="dcavity", imax=im, jmax=jm, re=50.0)
    dx, dy = 1.0 / im, 1.0 / jm
    rng = np.random.default_rng(3)
    u = jnp.asarray(rng.normal(size=(jm + 2, im + 2)))
    v = jnp.asarray(rng.normal(size=(jm + 2, im + 2)))
    p = jnp.asarray(rng.normal(size=(jm + 2, im + 2)))
    dt = jnp.asarray(0.01)
    u1, v1, f, g, rhs, u2, v2 = _jnp_chain(
        param, u, v, p, dt, dx, dy, jnp.float64)
    for br in (16, 40):
        pre, post, pad, unpad, _h = nf.make_fused_step_2d(
            param, jm, im, dx, dy, jnp.float64, interpret=True,
            block_rows=br)
        offs = jnp.zeros((2,), jnp.int32)
        dt11 = jnp.full((1, 1), dt)
        up, vp, fp, gp, rp = pre(offs, dt11, pad(u), pad(v))
        assert jnp.array_equal(unpad(up), u1), br
        assert _ulp_close(unpad(fp), f), br
        assert _ulp_close(unpad(rp), rhs, scale=float(jnp.abs(rhs).max()))
        up2, _vp2, um, _vm = post(offs, dt11, up, vp, fp, gp, pad(p))
        assert _ulp_close(unpad(up2), u2), br
        assert abs(float(um) - float(ops.max_element(u2))) <= 1e-12


def test_obstacle_phase_parity():
    """The flag-masked mode: obstacle velocity BC, F/G face mask and
    projection face mask vs the ops/obstacle.py jnp forms."""
    from pampi_tpu.ops import obstacle as obst

    jm, im = 32, 48
    param = Parameter(name="canal_obstacle", imax=im, jmax=jm, re=10.0,
                      bcLeft=3, bcRight=3, obstacles="0.3,0.3,0.6,0.6",
                      gamma=0.9, omg=1.7)
    dx, dy = param.xlength / im, param.ylength / jm
    fluid = obst.build_fluid(im, jm, dx, dy, param.obstacles)
    m = obst.make_masks(fluid, dx, dy, param.omg, jnp.float64)
    rng = np.random.default_rng(5)
    u = jnp.asarray(rng.normal(size=(jm + 2, im + 2)))
    v = jnp.asarray(rng.normal(size=(jm + 2, im + 2)))
    p = jnp.asarray(rng.normal(size=(jm + 2, im + 2)))
    dt = jnp.asarray(0.01)
    u1, v1 = ops.set_boundary_conditions(
        u, v, param.bcLeft, param.bcRight, param.bcBottom, param.bcTop)
    u1 = ops.set_special_bc_canal(u1, dy, param.ylength, jnp.float64)
    u1, v1 = obst.apply_obstacle_velocity_bc(u1, v1, m)
    f, g = ops.compute_fg(u1, v1, dt, param.re, 0.0, 0.0, param.gamma,
                          dx, dy)
    f, g = obst.mask_fg(f, g, u1, v1, m)
    rhs = ops.compute_rhs(f, g, dt, dx, dy)
    u2, v2 = obst.adapt_uv_obstacle(u1, v1, f, g, p, dt, dx, dy, m)

    pre, post, pad, unpad, _h = nf.make_fused_step_2d(
        param, jm, im, dx, dy, jnp.float64, fluid=m.fluid, interpret=True)
    offs = jnp.zeros((2,), jnp.int32)
    dt11 = jnp.full((1, 1), dt)
    up, vp, fp, gp, rp = pre(offs, dt11, pad(u), pad(v))
    assert jnp.array_equal(unpad(up), u1)  # flag multiplies of copies
    assert jnp.array_equal(unpad(vp), v1)
    assert _ulp_close(unpad(fp), f)
    assert _ulp_close(unpad(gp), g)
    assert _ulp_close(unpad(rp), rhs, scale=float(jnp.abs(rhs).max()))
    up2, vp2, um, vm = post(offs, dt11, up, vp, fp, gp, pad(p))
    assert _ulp_close(unpad(up2), u2)
    assert _ulp_close(unpad(vp2), v2)
    assert abs(float(um) - float(ops.max_element(u2))) <= 1e-12


def _run_solver(fuse, **kw):
    base = dict(name="dcavity", imax=32, jmax=32, re=10.0, te=0.04,
                tau=0.5, itermax=80, eps=1e-4, omg=1.7, gamma=0.9)
    base.update(kw)
    s = NS2DSolver(Parameter(tpu_fuse_phases=fuse, **base))
    s.run(progress=False)
    return s


@pytest.mark.parametrize("kw", [
    {},
    dict(name="canal", bcLeft=3, bcRight=3, te=0.02),
    dict(name="canal_obstacle", imax=48, bcLeft=3, bcRight=3,
         obstacles="0.3,0.3,0.6,0.6", te=0.02),
    dict(tau=-1.0, dt=0.002, te=0.02),
    dict(tpu_solver="fft", te=0.02),
])
def test_solver_e2e_fused_matches_jnp(kw):
    """Whole NS2DSolver runs: tpu_fuse_phases on (interpret kernels, the
    carried-padded-state chunk, carried CFL maxes) vs the jnp chain."""
    a, b = _run_solver("off", **kw), _run_solver("on", **kw)
    assert b._fused and not a._fused
    assert a.nt == b.nt
    for n in ("u", "v", "p"):
        d = np.abs(np.asarray(getattr(a, n)) - np.asarray(getattr(b, n)))
        assert np.isfinite(d).all() and d.max() < 1e-9, n


def test_dist_fused_matches_single():
    """NS2DDistSolver with fused per-shard kernels (deep-halo PRE, ext
    POST) vs the single-device jnp solver on the faked 8-device mesh."""
    from pampi_tpu.models.ns2d_dist import NS2DDistSolver
    from pampi_tpu.parallel.comm import CartComm

    param = Parameter(name="dcavity", imax=64, jmax=64, re=10.0, te=0.003,
                      tau=0.5, itermax=60, eps=1e-4, omg=1.7, gamma=0.9)
    single = NS2DSolver(param.replace(tpu_fuse_phases="off"))
    single.run(progress=False)
    for dims in [(4, 2), (1, 8)]:
        dist = NS2DDistSolver(param.replace(tpu_fuse_phases="on"),
                              CartComm(ndims=2, dims=dims))
        dist.run(progress=False)
        assert dispatch.last("ns2d_dist_phases") == "pallas_fused (forced)"
        ud, vd, pd = dist.fields()
        assert dist.nt == single.nt
        for n, (x, y) in {"u": (single.u, ud), "v": (single.v, vd),
                          "p": (single.p, pd)}.items():
            d = np.abs(np.asarray(x) - y)
            assert np.isfinite(d).all() and d.max() < 1e-10, (dims, n)


def test_dist_canal_fused_matches_single():
    """Canal exercises OUTFLOW walls and the global-j inflow profile
    (idx-dtype path) through the fused per-shard kernels."""
    from pampi_tpu.models.ns2d_dist import NS2DDistSolver
    from pampi_tpu.parallel.comm import CartComm

    param = Parameter(name="canal", imax=48, jmax=32, re=10.0, te=0.01,
                      tau=0.5, itermax=60, eps=1e-4, omg=1.7, gamma=0.9,
                      bcLeft=3, bcRight=3)
    single = NS2DSolver(param.replace(tpu_fuse_phases="off"))
    single.run(progress=False)
    dist = NS2DDistSolver(param.replace(tpu_fuse_phases="on"),
                          CartComm(ndims=2, dims=(2, 4)))
    dist.run(progress=False)
    ud, vd, pd = dist.fields()
    assert dist.nt == single.nt
    for n, (x, y) in {"u": (single.u, ud), "v": (single.v, vd),
                      "p": (single.p, pd)}.items():
        d = np.abs(np.asarray(x) - y)
        assert np.isfinite(d).all() and d.max() < 1e-10, n


def test_obstacle_calltime_flag_matches_baked():
    """The distributed-obstacle mode (fluid=True: the flag is a call-time
    argument) must be BITWISE the single-device baked-constant mode on the
    same geometry — same kernels, same windows, only the flag's delivery
    differs."""
    from pampi_tpu.ops import obstacle as obst

    jm, im = 32, 48
    param = Parameter(name="canal_obstacle", imax=im, jmax=jm, re=10.0,
                      bcLeft=3, bcRight=3, obstacles="0.3,0.3,0.6,0.6",
                      gamma=0.9, omg=1.7)
    dx, dy = param.xlength / im, param.ylength / jm
    fluid = obst.build_fluid(im, jm, dx, dy, param.obstacles)
    m = obst.make_masks(fluid, dx, dy, param.omg, jnp.float64)
    rng = np.random.default_rng(5)
    u = jnp.asarray(rng.normal(size=(jm + 2, im + 2)))
    v = jnp.asarray(rng.normal(size=(jm + 2, im + 2)))
    p = jnp.asarray(rng.normal(size=(jm + 2, im + 2)))
    dt11 = jnp.full((1, 1), 0.01)
    offs = jnp.zeros((2,), jnp.int32)
    pre_b, post_b, pad, unpad, _h = nf.make_fused_step_2d(
        param, jm, im, dx, dy, jnp.float64, fluid=m.fluid, interpret=True)
    pre_c, _p1, _u1, _h1 = nf.make_fused_pre_2d(
        param, jm, im, dx, dy, jnp.float64, fluid=True, interpret=True)
    post_c, _p2, _u2, _h2 = nf.make_fused_post_2d(
        param, jm, im, dx, dy, jnp.float64, fluid=True, interpret=True)
    flg = pad(m.fluid)
    outs_b = pre_b(offs, dt11, pad(u), pad(v))
    outs_c = pre_c(offs, dt11, pad(u), pad(v), flg)
    for a, b in zip(outs_b, outs_c):
        assert jnp.array_equal(unpad(a), unpad(b))
    up, vp, fp, gp, _r = outs_b
    got_b = post_b(offs, dt11, up, vp, fp, gp, pad(p))
    got_c = post_c(offs, dt11, up, vp, fp, gp, pad(p), flg)
    for a, b in zip(got_b[:2], got_c[:2]):
        assert jnp.array_equal(unpad(a), unpad(b))
    assert float(got_b[2]) == float(got_c[2])
    assert float(got_b[3]) == float(got_c[3])


def test_ragged_post_live_mask():
    """POST(ragged=True) must zero dead pad cells after the projection —
    bitwise the plain POST times the live mask (the jnp ragged chain's
    live_masks multiply), with the CFL max scanning live cells only."""
    jm_global, im_global = 27, 21   # trailing-shard view: block > global
    jl, il = 32, 24
    param = Parameter(name="dcavity", imax=im_global, jmax=jm_global,
                      re=10.0)
    dx, dy = 1.0 / im_global, 1.0 / jm_global
    rng = np.random.default_rng(9)
    shp = (jl + 2, il + 2)
    u = jnp.asarray(rng.normal(size=shp))
    v = jnp.asarray(rng.normal(size=shp))
    f = jnp.asarray(rng.normal(size=shp))
    g = jnp.asarray(rng.normal(size=shp))
    p = jnp.asarray(rng.normal(size=shp))
    dt11 = jnp.full((1, 1), 0.01)
    offs = jnp.zeros((2,), jnp.int32)
    kw = dict(jl=jl, il=il, interpret=True)
    post_r, pad, unpad, _h = nf.make_fused_post_2d(
        param, jm_global, im_global, dx, dy, jnp.float64, ragged=True, **kw)
    post_p, _p, _u, _h2 = nf.make_fused_post_2d(
        param, jm_global, im_global, dx, dy, jnp.float64, **kw)
    ur, vr, umr, vmr = post_r(offs, dt11, pad(u), pad(v), pad(f), pad(g),
                              pad(p))
    up, vp, _um, _vm = post_p(offs, dt11, pad(u), pad(v), pad(f), pad(g),
                              pad(p))
    gj = np.arange(jl + 2)[:, None]
    gi = np.arange(il + 2)[None, :]
    live = ((gj <= jm_global + 1) & (gi <= im_global + 1))
    assert jnp.array_equal(unpad(ur), unpad(up) * live)
    assert jnp.array_equal(unpad(vr), unpad(vp) * live)
    # the ragged CFL max never sees dead cells
    assert float(umr) == float(np.abs(np.asarray(unpad(ur))).max())
    assert float(vmr) == float(np.abs(np.asarray(unpad(vr))).max())


def test_dist_ragged_fused_matches_single():
    """Ragged shards on the fused kernels (uneven block bounds + the POST
    live-mask multiply) vs the single-device jnp chain — with and without
    an obstacle flag field riding along."""
    from pampi_tpu.models.ns2d_dist import NS2DDistSolver
    from pampi_tpu.parallel.comm import CartComm

    cases = [
        Parameter(name="dcavity", imax=50, jmax=50, re=10.0, te=0.003,
                  tau=0.5, itermax=60, eps=1e-4, omg=1.7, gamma=0.9),
        Parameter(name="canal_obstacle", imax=50, jmax=30, re=10.0,
                  te=0.003, tau=0.5, itermax=60, eps=1e-4, omg=1.7,
                  gamma=0.9, bcLeft=3, bcRight=3,
                  obstacles="0.3,0.3,0.6,0.6"),
    ]
    for param in cases:
        single = NS2DSolver(param.replace(tpu_fuse_phases="off"))
        single.run(progress=False)
        dist = NS2DDistSolver(param.replace(tpu_fuse_phases="on"),
                              CartComm(ndims=2, dims=(4, 2)))
        assert dist.ragged
        dist.run(progress=False)
        assert dispatch.last("ns2d_dist_phases") == "pallas_fused (forced)"
        ud, vd, pd = dist.fields()
        assert dist.nt == single.nt
        for n, (x, y) in {"u": (single.u, ud), "v": (single.v, vd),
                          "p": (single.p, pd)}.items():
            d = np.abs(np.asarray(x) - y)
            assert np.isfinite(d).all() and d.max() < 1e-9, (param.name, n)


def test_dist_obstacle_fused_matches_single():
    """Distributed obstacle flags through the fused kernels (per-shard
    call-time global-constant flag slices) vs the single-device jnp
    chain."""
    from pampi_tpu.models.ns2d_dist import NS2DDistSolver
    from pampi_tpu.parallel.comm import CartComm

    param = Parameter(name="canal_obstacle", imax=64, jmax=32, re=10.0,
                      te=0.003, tau=0.5, itermax=60, eps=1e-4, omg=1.7,
                      gamma=0.9, bcLeft=3, bcRight=3,
                      obstacles="0.3,0.3,0.6,0.6")
    single = NS2DSolver(param.replace(tpu_fuse_phases="off"))
    single.run(progress=False)
    dist = NS2DDistSolver(param.replace(tpu_fuse_phases="on"),
                          CartComm(ndims=2, dims=(2, 4)))
    assert not dist.ragged and dist.masks is not None
    dist.run(progress=False)
    assert dispatch.last("ns2d_dist_phases") == "pallas_fused (forced)"
    ud, vd, pd = dist.fields()
    assert dist.nt == single.nt
    for n, (x, y) in {"u": (single.u, ud), "v": (single.v, vd),
                      "p": (single.p, pd)}.items():
        d = np.abs(np.asarray(x) - y)
        assert np.isfinite(d).all() and d.max() < 1e-9, n


# the recursive pallas-launch counter lives in the shared analysis
# layer (one home for every jaxpr pin — see tools/lint.py)
from pampi_tpu.analysis.jaxprcheck import count_prim as _count_prim


def _while_body(jaxpr):
    for e in jaxpr.eqns:
        if e.primitive.name == "while":
            return e.params["body_jaxpr"].jaxpr
    raise AssertionError("no while loop in chunk jaxpr")


def test_launch_count_regression():
    """The fused chunk's step must lower to exactly TWO pallas kernels
    (pre + post; fft solve contributes none) and collapse the jnp chain's
    op count — the launch-amortization property this PR exists for."""
    param = Parameter(name="dcavity", imax=32, jmax=32, re=10.0, te=0.05,
                      tau=0.5, itermax=40, eps=1e-4, tpu_solver="fft")
    fused = NS2DSolver(param.replace(tpu_fuse_phases="on"))
    plain = NS2DSolver(param.replace(tpu_fuse_phases="off"))
    state = (plain.u, plain.v, plain.p, jnp.asarray(0.0, jnp.float64),
             jnp.asarray(0, jnp.int32))
    jx_f = jax.make_jaxpr(fused._build_chunk())(*state)
    jx_p = jax.make_jaxpr(plain._build_chunk())(*state)
    assert _count_prim(jx_f.jaxpr, "pallas_call") == 2
    assert _count_prim(jx_p.jaxpr, "pallas_call") == 0
    body_f = _while_body(jx_f.jaxpr)
    body_p = _while_body(jx_p.jaxpr)
    # the fused step body is a handful of launches (2 kernels + layout
    # slices + the solve + scalar math) vs the ~40-op jnp phase chain
    assert len(body_f.eqns) * 2 < len(body_p.eqns), (
        len(body_f.eqns), len(body_p.eqns))


def test_dist_fused_launch_count():
    """Each newly fused dist family's per-shard chunk lowers to exactly
    TWO pallas kernels per step (pre + post; the jnp CA solve contributes
    none) — the launch-amortization property, per family."""
    from pampi_tpu.models.ns2d_dist import NS2DDistSolver
    from pampi_tpu.parallel.comm import CartComm

    cases = [
        ("ragged", Parameter(name="dcavity", imax=50, jmax=50, re=10.0,
                             te=0.05, tau=0.5, itermax=20, eps=1e-3),
         (4, 2)),
        ("obstacle", Parameter(name="canal_obstacle", imax=64, jmax=32,
                               re=10.0, te=0.05, tau=0.5, itermax=20,
                               eps=1e-3, bcLeft=3, bcRight=3,
                               obstacles="0.3,0.3,0.6,0.6"), (2, 4)),
    ]
    for tag, param, dims in cases:
        fused = NS2DDistSolver(param.replace(tpu_fuse_phases="on"),
                               CartComm(ndims=2, dims=dims))
        plain = NS2DDistSolver(param.replace(tpu_fuse_phases="off"),
                               CartComm(ndims=2, dims=dims))
        state = (fused.u, fused.v, fused.p, jnp.asarray(0.0, jnp.float64),
                 jnp.asarray(0, jnp.int32))
        jx_f = jax.make_jaxpr(fused._chunk_sm)(*state)
        jx_p = jax.make_jaxpr(plain._chunk_sm)(*state)
        assert _count_prim(jx_f.jaxpr, "pallas_call") == 2, tag
        assert _count_prim(jx_p.jaxpr, "pallas_call") == 0, tag


def test_p_layout_fold():
    """The p-layout fold (the ROADMAP post-fusion knob): on the
    checkerboard solve layout the pressure solve runs DIRECTLY on the
    fused padded layout — dispatch records the fold, the chunk lowers to
    exactly THREE pallas calls (pre + tblock solve + post, no layout
    passes between them), and results match the jnp chain. The auto
    layout on even grids keeps quarters with explicit conversions."""
    base = dict(name="dcavity", imax=32, jmax=32, re=10.0, te=0.04,
                tau=0.5, itermax=80, eps=1e-4, omg=1.7, gamma=0.9,
                tpu_sor_layout="checkerboard", tpu_sor_inner=1)
    a = _run_solver("off", **base)
    b = _run_solver("on", **base)
    assert dispatch.last("ns2d_p_layout").startswith("folded")
    assert b._fused and a.nt == b.nt
    for n in ("u", "v", "p"):
        d = np.abs(np.asarray(getattr(a, n)) - np.asarray(getattr(b, n)))
        assert np.isfinite(d).all() and d.max() < 1e-9, n
    state = (a.u, a.v, a.p, jnp.asarray(0.0, jnp.float64),
             jnp.asarray(0, jnp.int32))
    jx = jax.make_jaxpr(b._build_chunk())(*state)
    assert _count_prim(jx.jaxpr, "pallas_call") == 3
    # auto on an even grid: quarters stays the solve home, no fold
    NS2DSolver(Parameter(tpu_fuse_phases="on",
                         **{**base, "tpu_sor_layout": "auto"}))
    assert dispatch.last("ns2d_p_layout") == "explicit pad/unpad"


def test_retry_backend_disables_fusion():
    """models/_driver.pallas_retry rebuilds the chunk with backend='jnp';
    the fused path must then stand down (and _uses_pallas with it)."""
    param = Parameter(name="dcavity", imax=16, jmax=16, re=10.0, te=0.02,
                      tau=0.5, itermax=20, eps=1e-3,
                      tpu_fuse_phases="on")
    s = NS2DSolver(param)
    assert s._fused and s._uses_pallas()
    s._build_chunk(backend="jnp")
    assert not s._fused
    assert dispatch.last("ns2d_phases") == "jnp (retry fallback backend)"


def test_fuse_knob_validation():
    with pytest.raises(ValueError, match="tpu_fuse_phases"):
        NS2DSolver(Parameter(name="dcavity", imax=16, jmax=16,
                             tpu_fuse_phases="always"))
