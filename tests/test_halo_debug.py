"""Halo debug dump (parallel/halo_debug.py) — the file-producing twin of the
reference's test.c checker: every interior ghost face must show the
neighbour's rank id, physical-wall ghosts keep the own id."""

import numpy as np

from pampi_tpu.parallel.comm import CartComm
from pampi_tpu.parallel.halo_debug import dump_halos, rank_id_blocks


def test_rank_id_blocks_2d():
    comm = CartComm(ndims=2)  # (4, 2) on the faked 8-device mesh
    Pj, Pi = comm.dims
    blocks = rank_id_blocks(comm, (4, 6))
    for (cj, ci), blk in blocks.items():
        rid = cj * Pi + ci
        # interior untouched
        assert (blk[1:-1, 1:-1] == rid).all()
        # ghost faces: neighbour id inward, own id at physical walls
        exp_bottom = (cj - 1) * Pi + ci if cj > 0 else rid
        exp_top = (cj + 1) * Pi + ci if cj < Pj - 1 else rid
        exp_left = cj * Pi + ci - 1 if ci > 0 else rid
        exp_right = cj * Pi + ci + 1 if ci < Pi - 1 else rid
        assert (blk[0, 1:-1] == exp_bottom).all()
        assert (blk[-1, 1:-1] == exp_top).all()
        assert (blk[1:-1, 0] == exp_left).all()
        assert (blk[1:-1, -1] == exp_right).all()


def test_dump_halos_writes_files(tmp_path):
    comm = CartComm(ndims=2)
    paths = dump_halos(comm, (4, 4), outdir=str(tmp_path))
    assert len(paths) == comm.size * 4  # 4 faces per rank in 2-D
    # spot-check: rank 0's top ghost face shows rank Pi (its +j neighbour)
    Pi = comm.dims[1]
    face = np.loadtxt(tmp_path / "halo-top-r0.txt")
    assert (face[1:-1] == Pi).all()


def test_dump_halos_3d(tmp_path):
    comm = CartComm(ndims=3)  # (2, 2, 2)
    paths = dump_halos(comm, (2, 2, 2), outdir=str(tmp_path))
    assert len(paths) == comm.size * 6
