"""Native runtime layer (native/src) vs the pure-Python twins.

The C writers must produce byte-identical files to datio.py/vtkio.py (which
are validated against the reference's golden outputs), and the C .par parser
+ echo must match params.py's read_parameter/print_parameter text exactly.
Builds the library via make on first use; skips if no C toolchain."""

import pathlib
import shutil
import subprocess

import numpy as np
import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent


@pytest.fixture(scope="session")
def native_lib():
    if shutil.which("gcc") is None and shutil.which("cc") is None:
        pytest.skip("no C toolchain")
    libs = list(REPO.glob("build/*/libpampi_native.so"))
    if not libs:
        r = subprocess.run(["make"], cwd=REPO, capture_output=True, text=True)
        if r.returncode != 0:
            pytest.skip(f"make failed: {r.stderr[-500:]}")
    from pampi_tpu.utils import native

    if not native.available():
        # library may have been built after the module import cache
        import importlib

        importlib.reload(native)
    if not native.available():
        pytest.skip("native library not loadable")
    return native


def _py_bytes(writer_fn, *args):
    """Run a pure-Python writer with the native path disabled."""
    import os

    os.environ["PAMPI_NATIVE"] = "0"
    try:
        import importlib

        from pampi_tpu.utils import native as nat

        importlib.reload(nat)
        writer_fn(*args)
    finally:
        del os.environ["PAMPI_NATIVE"]
        import importlib

        from pampi_tpu.utils import native as nat

        importlib.reload(nat)


def test_write_matrix_bytes(native_lib, tmp_path):
    rng = np.random.default_rng(0)
    a = rng.normal(size=(12, 9))
    from pampi_tpu.utils.datio import write_matrix

    _py_bytes(write_matrix, a, str(tmp_path / "py.dat"))
    assert native_lib.write_matrix(str(tmp_path / "c.dat"), a)
    assert (tmp_path / "c.dat").read_bytes() == (tmp_path / "py.dat").read_bytes()


def test_write_pressure_velocity_bytes(native_lib, tmp_path):
    rng = np.random.default_rng(1)
    p = rng.normal(size=(7, 11))
    u = rng.normal(size=(7, 11))
    v = rng.normal(size=(7, 11))
    from pampi_tpu.utils.datio import write_pressure, write_velocity

    _py_bytes(write_pressure, p, 0.25, 0.5, str(tmp_path / "pp.dat"))
    assert native_lib.write_pressure(str(tmp_path / "pc.dat"), p, 0.25, 0.5)
    assert (tmp_path / "pc.dat").read_bytes() == (tmp_path / "pp.dat").read_bytes()

    _py_bytes(write_velocity, u, v, 0.25, 0.5, str(tmp_path / "vp.dat"))
    assert native_lib.write_velocity(str(tmp_path / "vc.dat"), u, v, 0.25, 0.5)
    assert (tmp_path / "vc.dat").read_bytes() == (tmp_path / "vp.dat").read_bytes()


@pytest.mark.parametrize("binary", [False, True])
def test_vtk_bytes(native_lib, tmp_path, binary):
    from pampi_tpu.utils.grid import Grid
    from pampi_tpu.utils import vtkio

    g = Grid(imax=4, jmax=3, kmax=2, xlength=1.0, ylength=1.0, zlength=1.0)
    rng = np.random.default_rng(2)
    s = rng.normal(size=(2, 3, 4))
    u, v, w = (rng.normal(size=(2, 3, 4)) for _ in range(3))
    fmt = "binary" if binary else "ascii"

    # python writer, native disabled (reload so available() sees the flag)
    import importlib
    import os

    from pampi_tpu.utils import native as nat

    os.environ["PAMPI_NATIVE"] = "0"
    try:
        importlib.reload(nat)
        wpy = vtkio.VtkWriter("t", g, fmt=fmt, path=str(tmp_path / "py.vtk"))
        assert isinstance(wpy, vtkio.VtkWriter)
        wpy.scalar("pressure", s)
        wpy.vector("velocity", u, v, w)
        wpy.close()
    finally:
        del os.environ["PAMPI_NATIVE"]
        importlib.reload(nat)

    wc = native_lib.NativeVtk(
        str(tmp_path / "c.vtk"), "PAMPI cfd solver output",
        g.imax, g.jmax, g.kmax, g.dx, g.dy, g.dz, binary)
    wc.scalar("pressure", s)
    wc.vector("velocity", u, v, w)
    wc.close()
    assert (tmp_path / "c.vtk").read_bytes() == (tmp_path / "py.vtk").read_bytes()


@pytest.mark.parametrize(
    "cfg", ["poisson.par", "dcavity.par", "canal.par", "dcavity3d.par",
            "canal3d.par"])
def test_shim_dry_run_echo_matches_python(native_lib, cfg):
    """exe-JAX --dry-run must print exactly what the Python driver echoes."""
    import io

    from pampi_tpu.utils.params import print_parameter, read_parameter

    exe = next(REPO.glob("exe-*"), None)
    if exe is None:
        pytest.skip("exe shim not built")
    out = subprocess.run(
        [str(exe), "--dry-run", f"configs/{cfg}"],
        cwd=REPO, capture_output=True, text=True, check=True)
    param = read_parameter(str(REPO / "configs" / cfg))
    buf = io.StringIO()
    print_parameter(param, out=buf)
    assert out.stdout == buf.getvalue()


def test_shim_usage_and_bad_config(native_lib, tmp_path):
    exe = next(REPO.glob("exe-*"), None)
    if exe is None:
        pytest.skip("exe shim not built")
    out = subprocess.run([str(exe)], capture_output=True, text=True)
    assert out.returncode == 0 and "Usage" in out.stdout
    bad = tmp_path / "bad.par"
    bad.write_text("imax -3\n")
    out = subprocess.run(
        [str(exe), "--dry-run", str(bad)], capture_output=True, text=True)
    assert out.returncode != 0 and "Invalid grid" in out.stderr
