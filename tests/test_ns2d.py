"""NS-2D regression tests.

Two oracle tiers (fixtures generated from the reference C code, see
tests/fixtures/):
1. EXACT parity — `*_rb_*` fixtures come from the reference solver with its
   pressure sweep switched to the red-black ordering the reference itself
   ships in assignment-4's solveRB. Our pipeline must match these to the
   .dat writers' 1e-6 output precision, including the canal case where the
   pressure solve never converges (incompatible all-Neumann RHS).
2. PHYSICS parity — `dcavity_te0.01_*` / `canal_it5000_*` come from the
   unmodified reference (lexicographic SOR). Converged fields agree to
   ~solver tolerance; pressure only up to the Neumann nullspace constant.
"""

import numpy as np
import pytest

from pampi_tpu.models.ns2d import NS2DSolver
from pampi_tpu.utils.datio import read_pressure, read_velocity
from pampi_tpu.utils.params import Parameter, read_parameter


def _run(reference_dir, tmp_path, par_name, te, **overrides):
    param = read_parameter(
        str(reference_dir / "assignment-5" / "sequential" / par_name)
    )
    param = param.replace(te=te, **overrides)
    s = NS2DSolver(param)
    s.run(progress=False)
    s.write_result(str(tmp_path / "pressure.dat"), str(tmp_path / "velocity.dat"))
    p = read_pressure(str(tmp_path / "pressure.dat"))
    u, v = read_velocity(str(tmp_path / "velocity.dat"))
    return p, u, v


@pytest.fixture(scope="module")
def fixdir(tmp_path_factory):
    import pathlib

    return pathlib.Path(__file__).parent / "fixtures"


@pytest.mark.golden
def test_dcavity_exact_vs_rb_oracle(reference_dir, tmp_path, fixdir):
    p, u, v = _run(reference_dir, tmp_path, "dcavity.par", te=0.01)
    pg = read_pressure(str(fixdir / "dcavity_rb_te0.01_pressure.dat"))
    ug, vg = read_velocity(str(fixdir / "dcavity_rb_te0.01_velocity.dat"))
    assert np.abs(p - pg).max() <= 1e-6
    assert np.abs(u - ug).max() <= 1e-6
    assert np.abs(v - vg).max() <= 1e-6


@pytest.mark.golden
def test_canal_exact_vs_rb_oracle(reference_dir, tmp_path, fixdir):
    # canal's pressure solve hits itermax every step (residual floors above
    # eps) — exact parity here proves sweep-for-sweep equivalence, not just
    # converged-state equivalence
    p, u, v = _run(reference_dir, tmp_path, "canal.par", te=1.0)
    pg = read_pressure(str(fixdir / "canal_rb_te1.0_pressure.dat"))
    ug, vg = read_velocity(str(fixdir / "canal_rb_te1.0_velocity.dat"))
    assert np.abs(p - pg).max() <= 1e-6
    assert np.abs(u - ug).max() <= 1e-6
    assert np.abs(v - vg).max() <= 1e-6


@pytest.mark.golden
def test_dcavity_physics_vs_lexicographic_reference(
    reference_dir, tmp_path, fixdir
):
    # unmodified reference ordering; converged pressure solves ⇒ tight match
    p, u, v = _run(reference_dir, tmp_path, "dcavity.par", te=0.01)
    pg = read_pressure(str(fixdir / "dcavity_te0.01_pressure.dat"))
    ug, vg = read_velocity(str(fixdir / "dcavity_te0.01_velocity.dat"))
    assert np.abs(u - ug).max() < 5e-6
    assert np.abs(v - vg).max() < 5e-6
    dp = (p - p.mean()) - (pg - pg.mean())
    assert np.abs(dp).max() < 5e-6


@pytest.mark.golden
def test_canal_physics_vs_lexicographic_reference(reference_dir, tmp_path, fixdir):
    # non-converging pressure solves ⇒ orderings give genuinely different
    # trajectories; agreement is at the physics level only
    p, u, v = _run(reference_dir, tmp_path, "canal.par", te=1.0, itermax=5000)
    pg = read_pressure(str(fixdir / "canal_it5000_te1.0_pressure.dat"))
    ug, vg = read_velocity(str(fixdir / "canal_it5000_te1.0_velocity.dat"))
    assert np.abs(u - ug).max() < 0.05 * np.abs(ug).max()
    assert np.abs(v - vg).max() < 0.05 * np.abs(vg).max()


def test_adaptive_timestep_matches_reference_semantics():
    import jax.numpy as jnp

    from pampi_tpu.ops.ns2d import compute_timestep

    u = jnp.zeros((6, 6)).at[2, 3].set(4.0)
    v = jnp.zeros((6, 6)).at[1, 1].set(-2.0)
    # dt = min(dtBound, dx/|u|max, dy/|v|max) * tau
    dt = compute_timestep(u, v, dt_bound=10.0, dx=1.0, dy=1.0, tau=0.5)
    assert float(dt) == pytest.approx(0.25 * 0.5)
    # zero velocities: falls back to dtBound
    dt0 = compute_timestep(jnp.zeros((6, 6)), jnp.zeros((6, 6)), 10.0, 1.0, 1.0, 0.5)
    assert float(dt0) == pytest.approx(5.0)


def test_constant_dt_when_tau_negative(reference_dir):
    param = read_parameter(
        str(reference_dir / "assignment-5" / "sequential" / "dcavity.par")
    )
    param = param.replace(tau=-1.0, te=0.05, dt=0.01)
    s = NS2DSolver(param)
    s.run(progress=False)
    # 6 steps of fixed dt=0.01 run while t<=te (t: 0,.01,...,.05 all <= te)
    assert s.nt == 6
    assert s.t == pytest.approx(0.06)


def test_bfloat16_run_tracks_float64():
    """tpu_dtype bfloat16 (the TPU-native low-precision mode) must complete
    the same step count and stay within bf16-discretization distance of the
    f64 run — time accumulates in high precision by design, so the step
    count cannot stall (models/ns2d.py time_dtype note)."""
    import jax.numpy as jnp

    def run(dtype):
        param = Parameter(
            name="dcavity", imax=16, jmax=16, re=10.0, te=0.05, dt=0.02,
            tau=0.5, itermax=50, eps=1e-3, omg=1.7, gamma=0.9,
            tpu_dtype=dtype,
        )
        s = NS2DSolver(param)
        s.run(progress=False)
        return s

    lo = run("bfloat16")
    hi = run("float64")
    assert lo.u.dtype == jnp.bfloat16
    assert lo.nt == hi.nt
    ulo = np.asarray(lo.u, np.float64)
    uhi = np.asarray(hi.u)
    assert np.isfinite(ulo).all()
    # bf16 has ~3 decimal digits; the flow field is O(1)
    assert np.abs(ulo - uhi).max() < 0.05


def test_sor_lex_matches_sor_physics_and_rejects_obstacles():
    """tpu_solver sor_lex (the C binary's lexicographic ordering as an
    oracle, tools/northstar.py match4096): on a CONVERGING config the
    ordering washes out at the solve tolerance, so the physics matches the
    rb run; obstacle flag fields are rejected (no eps-coefficient form)."""
    import pytest as _pytest

    param = Parameter(
        name="dcavity", imax=32, jmax=32, re=10.0, te=0.05, tau=0.5,
        itermax=2000, eps=1e-6, omg=1.7, gamma=0.9,
    )
    a = NS2DSolver(param)
    a.run(progress=False)
    b = NS2DSolver(param.replace(tpu_solver="sor_lex"))
    b.run(progress=False)
    assert a.nt == b.nt > 1
    np.testing.assert_allclose(np.asarray(a.u), np.asarray(b.u),
                               rtol=0, atol=1e-5)
    np.testing.assert_allclose(np.asarray(a.v), np.asarray(b.v),
                               rtol=0, atol=1e-5)
    with _pytest.raises(ValueError, match="sor_lex"):
        NS2DSolver(param.replace(tpu_solver="sor_lex",
                                 obstacles="0.3,0.3,0.6,0.6"))
