"""Chunk-driver protocol tests (models/_driver.py) — the framework's
failure-handling subsystem: the chunked time loop, the one-shot transient
device-fault retry, and the pallas->jnp rebuild hook. The reference has no
failure handling at all (SURVEY.md §5: fprintf+exit), so these paths only
exist here — and they were previously exercised only implicitly."""

import warnings

import jax.numpy as jnp
import pytest

from pampi_tpu.models._driver import drive_chunks, pallas_retry


class JaxRuntimeError(Exception):
    """Name-alike of jax's runtime error: _is_transient_device_fault matches
    on the type NAME, so tests can forge faults without touching jax."""


class _Bar:
    def __init__(self):
        self.updates = []
        self.stopped = False

    def update(self, t):
        self.updates.append(t)

    def stop(self):
        self.stopped = True


def _advance(dt=1.0):
    def chunk(t, n):
        return (t + dt, n + 1)

    return chunk


def test_normal_loop_runs_until_te_and_syncs():
    bar = _Bar()
    seen = []
    state = drive_chunks(
        (jnp.asarray(0.0), jnp.asarray(0, jnp.int32)),
        _advance(), te=2.5, time_index=0, bar=bar,
        retry=lambda: None, on_state=seen.append,
    )
    # loop body runs while t <= te at chunk start: t = 0,1,2 -> 3 chunks
    assert float(state[0]) == 3.0 and int(state[1]) == 3
    assert len(seen) == 3
    assert bar.stopped and bar.updates == [1.0, 2.0, 3.0]


def test_nan_time_is_terminal_not_a_spin():
    """An adaptive-dt blow-up makes t NaN; every later chunk is a device
    no-op and `t_old > te` is false for NaN — the loop must treat NaN as
    terminal (the dist solvers' `while t <= te` already exits on NaN)
    instead of spinning forever on no-op dispatches."""
    bar = _Bar()

    def nan_chunk(t, n):
        return (jnp.asarray(float("nan")), n + 1)

    state = drive_chunks(
        (jnp.asarray(0.0), jnp.asarray(0, jnp.int32)),
        nan_chunk, te=100.0, time_index=0, bar=bar,
        retry=lambda: None,
    )
    assert float(state[0]) != float(state[0])  # NaN returned, loop exited
    assert int(state[1]) == 1  # terminated on the FIRST NaN confirmation
    assert bar.stopped


def test_transient_fault_retried_exactly_once():
    calls = {"n": 0}

    def flaky(t, n):
        calls["n"] += 1
        if calls["n"] == 2:
            raise JaxRuntimeError("UNAVAILABLE: TPU device error")
        return (t + 1.0, n + 1)

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        state = drive_chunks(
            (jnp.asarray(0.0), jnp.asarray(0, jnp.int32)),
            flaky, te=1.5, time_index=0, bar=_Bar(), retry=lambda: None,
        )
    assert float(state[0]) == 2.0
    assert any("transient" in str(x.message) for x in w)
    # 2 successful chunks + 1 faulted attempt
    assert calls["n"] == 3


def test_second_transient_fault_reraises():
    def always_faulty(t, n):
        raise JaxRuntimeError("UNAVAILABLE: TPU device error")

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with pytest.raises(JaxRuntimeError):
            drive_chunks(
                (jnp.asarray(0.0), jnp.asarray(0, jnp.int32)),
                always_faulty, te=1.0, time_index=0, bar=_Bar(),
                retry=lambda: None,
            )


def test_non_transient_error_propagates():
    def broken(t, n):
        raise ValueError("genuine bug")

    with pytest.raises(ValueError, match="genuine bug"):
        drive_chunks(
            (jnp.asarray(0.0), jnp.asarray(0, jnp.int32)),
            broken, te=1.0, time_index=0, bar=_Bar(), retry=lambda: None,
        )


def test_retry_hook_swaps_chunk_fn():
    """A failing chunk whose retry() supplies a rebuilt fn continues on the
    new fn with UNCHANGED inputs (the loop is functional)."""
    calls = {"old": 0, "new": 0}

    def old_fn(t, n):
        calls["old"] += 1
        raise ValueError("pallas kernel exploded")

    def new_fn(t, n):
        calls["new"] += 1
        return (t + 1.0, n + 1)

    state = drive_chunks(
        (jnp.asarray(0.0), jnp.asarray(0, jnp.int32)),
        old_fn, te=1.5, time_index=0, bar=_Bar(), retry=lambda: new_fn,
    )
    assert calls["old"] == 1 and calls["new"] == 2
    assert float(state[0]) == 2.0 and int(state[1]) == 2


class _FakeSolver:
    def __init__(self, backend="auto", uses_pallas=True):
        self._backend = backend
        self._uses = uses_pallas
        self.rebuilds = []

    def _uses_pallas(self):
        return self._uses

    def _build_chunk(self, backend):
        self.rebuilds.append(backend)

        def chunk(t, n):
            return (t + 1.0, n + 1)

        return chunk


def test_pallas_retry_rebuilds_once_then_gives_up():
    s = _FakeSolver()
    retry = pallas_retry(s, "pressure solve")
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        fn = retry()
    assert fn is not None and s.rebuilds == ["jnp"]
    assert s._backend == "jnp"
    assert any("jnp path" in str(x.message) for x in w)
    # a second failure now comes FROM the jnp path: no more retries
    assert retry() is None


def test_pallas_retry_none_when_pallas_not_in_play():
    s = _FakeSolver(uses_pallas=False)
    assert pallas_retry(s, "x")() is None


def test_pipelined_loop_same_results_and_hooks():
    """lookahead > 0 must not change WHAT runs — same final state, every
    chunk's state still reaches bar/on_state in order — only WHEN the host
    syncs. Overshoot chunks past te must be no-ops for the returned state
    (the real chunk_fn's while-cond guarantees it; the fake honors te)."""
    te = 2.5

    def chunk(t, n):  # te-guarded like the real device chunk
        import jax.numpy as jnp

        adv = t <= te
        return (jnp.where(adv, t + 1.0, t),
                jnp.where(adv, n + 1, n))

    for la in (1, 2, 5):
        bar = _Bar()
        seen = []
        state = drive_chunks(
            (jnp.asarray(0.0), jnp.asarray(0, jnp.int32)),
            chunk, te=te, time_index=0, bar=bar,
            retry=lambda: None, on_state=seen.append, lookahead=la,
        )
        assert float(state[0]) == 3.0 and int(state[1]) == 3
        assert bar.updates == [1.0, 2.0, 3.0] and bar.stopped
        assert [float(s[0]) for s in seen] == [1.0, 2.0, 3.0]


def test_pipelined_transient_fault_resets_to_confirmed():
    """A fault inside the pipeline rewinds to the last CONFIRMED state:
    the simulation replays the unconfirmed tail, never skips or doubles a
    step (state is t itself, so doubling would show as t jumping)."""
    te = 3.5
    calls = {"n": 0}

    def flaky(t, n):
        calls["n"] += 1
        if calls["n"] == 3:
            raise JaxRuntimeError("UNAVAILABLE: TPU device error")
        adv = float(t) <= te
        return (t + 1.0, n + 1) if adv else (t, n)

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        state = drive_chunks(
            (jnp.asarray(0.0), jnp.asarray(0, jnp.int32)),
            flaky, te=te, time_index=0, bar=_Bar(), retry=lambda: None,
            lookahead=2,
        )
    assert float(state[0]) == 4.0 and int(state[1]) == 4
    assert any("transient" in str(x.message) for x in w)


def test_pipelined_zero_trip_returns_initial_state():
    s0 = (jnp.asarray(9.0), jnp.asarray(7, jnp.int32))
    bar = _Bar()
    out = drive_chunks(s0, _advance(), te=2.0, time_index=0, bar=bar,
                       retry=lambda: None, lookahead=3)
    assert out is s0 and bar.stopped and bar.updates == []


def test_negative_lookahead_rejected():
    """Programmatic callers bypass cli.py's .par validation; the driver
    itself must refuse (a negative value would popleft an empty deque)."""
    with pytest.raises(ValueError, match="lookahead"):
        drive_chunks((jnp.asarray(0.0),), _advance(), te=2.0, time_index=0,
                     bar=_Bar(), retry=lambda: None, lookahead=-1)


def test_tpu_chunk_override_preserves_results():
    """tpu_chunk overrides the per-dispatch step count (watchdog escape for
    slow-step configs) without changing what is computed."""
    import numpy as np

    from pampi_tpu.models.ns2d import NS2DSolver
    from pampi_tpu.utils.params import Parameter

    param = Parameter(name="dcavity", imax=16, jmax=16, re=10.0, te=0.05,
                      tau=0.5, itermax=200, eps=1e-6, omg=1.7, gamma=0.9)
    a = NS2DSolver(param)
    a.run(progress=False)
    b = NS2DSolver(param.replace(tpu_chunk=3))
    b.run(progress=False)
    assert a.nt == b.nt > 3
    np.testing.assert_array_equal(np.asarray(a.u), np.asarray(b.u))
    np.testing.assert_array_equal(np.asarray(a.p), np.asarray(b.p))


# ---------------------------------------------------------------------------
# PR 4: replenishing retry budgets + rollback-recovery protocol units
# ---------------------------------------------------------------------------

def test_transient_budget_replenishes_after_clean_chunks():
    """The satellite fix: a second spaced transient is retried once the
    budget refilled (replenish_after consecutive clean chunks); pre-PR the
    per-run budget was one."""
    calls = {"n": 0}

    def flaky(t, n):
        calls["n"] += 1
        if calls["n"] in (2, 7):  # 3+ clean confirmations apart
            raise JaxRuntimeError("UNAVAILABLE: TPU device error")
        return (t + 1.0, n + 1)

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        state = drive_chunks(
            (jnp.asarray(0.0), jnp.asarray(0, jnp.int32)),
            flaky, te=7.5, time_index=0, bar=_Bar(), retry=lambda: None,
            replenish_after=3,
        )
    assert float(state[0]) == 8.0 and int(state[1]) == 8
    assert sum("transient" in str(x.message) for x in w) == 2


def test_transient_budget_stays_one_inside_window():
    """Two faults inside one replenish window still exhaust the budget."""
    calls = {"n": 0}

    def flaky(t, n):
        calls["n"] += 1
        if calls["n"] in (2, 4):
            raise JaxRuntimeError("UNAVAILABLE: TPU device error")
        return (t + 1.0, n + 1)

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with pytest.raises(JaxRuntimeError):
            drive_chunks(
                (jnp.asarray(0.0), jnp.asarray(0, jnp.int32)),
                flaky, te=9.5, time_index=0, bar=_Bar(), retry=lambda: None,
                replenish_after=10,
            )


def test_pallas_restore_after_clean_chunks():
    """restore_after > 0: after the jnp fallback runs that many clean
    chunks, the pallas chunk is rebuilt and takes over (rebuild sequence
    jnp -> original backend)."""
    s = _FakeSolver()
    retry = pallas_retry(s, "pressure solve", restore_after=2)
    first_fail = {"done": False}

    orig_build = s._build_chunk

    def build(backend):
        fn = orig_build(backend)

        def chunk(t, n):
            if backend != "jnp" and not first_fail["done"]:
                first_fail["done"] = True
                raise RuntimeError("pallas kernel exploded")
            return fn(t, n)

        return chunk

    s._build_chunk = build
    s._chunk_fn = build("auto")
    s.rebuilds.clear()  # the initial build is not a retry rebuild
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        state = drive_chunks(
            (jnp.asarray(0.0), jnp.asarray(0, jnp.int32)),
            s._chunk_fn, te=5.5, time_index=0, bar=_Bar(), retry=retry,
        )
    assert float(state[0]) == 6.0 and int(state[1]) == 6
    assert s.rebuilds == ["jnp", "auto"]  # fallback, then restore
    assert s._backend == "auto"
    assert any("restoring the pallas" in str(x.message) for x in w)


def test_pallas_refailure_after_restore_stays_jnp():
    """A pallas that breaks again right after its restore is judged
    deterministically broken: one more fallback, no further restores."""
    s = _FakeSolver()
    retry = pallas_retry(s, "x", restore_after=1)
    retry()                      # fallback 1 (pretend pallas failed)
    assert retry.on_clean_chunk() is not None   # restored after 1 clean
    s._uses = True
    retry()                      # breaks again immediately -> dead
    assert s.rebuilds == ["jnp", "auto", "jnp"]
    for _ in range(5):
        assert retry.on_clean_chunk() is None   # stays on jnp forever


class _RecSolver:
    """Minimal recovery target: state is (t, nt)."""

    def __init__(self):
        self._dt_scale = 1.0
        self.rebuilt = 0

    def _rebuild_chunk(self):
        self.rebuilt += 1
        def chunk(t, n):
            return (t + 1.0, n + 1)
        return chunk


def test_ring_recovery_rolls_back_and_clamps():
    from pampi_tpu.models._driver import RingRecovery

    s = _RecSolver()
    r = RingRecovery(s, "unit", time_index=0, ring=2, dt_scale=0.5,
                     max_attempts=2)
    for t in (1.0, 2.0, 3.0):
        r.capture((jnp.asarray(t), jnp.asarray(int(t), jnp.int32)))
    r.capture((jnp.asarray(float("nan")), jnp.asarray(9, jnp.int32)))
    # ring keeps the last 2 FINITE states; NaN is never captured
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        state, fn = r.attempt()
    assert float(state[0]) == 3.0 and s._dt_scale == 0.5 and s.rebuilt == 1
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        state, fn = r.attempt()          # digs one deeper, clamps again
    assert float(state[0]) == 2.0 and s._dt_scale == 0.25
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        assert r.attempt() is None       # attempts exhausted -> terminal


def test_drive_chunks_recovers_from_nan_time():
    """End-to-end on fake chunks: a NaN loop time with an armed recovery
    rolls back (rebuilt chunk advances cleanly) instead of terminating."""
    from pampi_tpu.models._driver import RingRecovery

    s = _RecSolver()
    r = RingRecovery(s, "unit", time_index=0, ring=4, dt_scale=0.5,
                     max_attempts=3)
    calls = {"n": 0}

    def diverging(t, n):
        calls["n"] += 1
        if calls["n"] == 3:
            return (jnp.asarray(float("nan")), n + 1)
        return (t + 1.0, n + 1)

    bar = _Bar()
    s0 = (jnp.asarray(0.0), jnp.asarray(0, jnp.int32))
    r.capture(s0)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        state = drive_chunks(
            s0, diverging, te=4.5, time_index=0, bar=bar,
            retry=lambda: None, on_state=r.capture, recover=r,
        )
    assert any("rolled back" in str(x.message) for x in w)
    assert float(state[0]) == 5.0  # finished on the rebuilt chunk
    assert s.rebuilt == 1


def test_exhausted_transient_never_consumes_pallas_fallback():
    """A transient UNAVAILABLE with the budget spent RE-RAISES — it must
    not fall into the pallas->jnp hook (which would misattribute the
    fault and could permanently retire a healthy kernel via the
    post-restore broken latch)."""
    s = _FakeSolver()
    retry = pallas_retry(s, "x")
    calls = {"n": 0}

    def flaky(t, n):
        calls["n"] += 1
        if calls["n"] in (2, 3):
            raise JaxRuntimeError("UNAVAILABLE: TPU device error")
        return (t + 1.0, n + 1)

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with pytest.raises(JaxRuntimeError):
            drive_chunks(
                (jnp.asarray(0.0), jnp.asarray(0, jnp.int32)),
                flaky, te=9.5, time_index=0, bar=_Bar(), retry=retry,
                replenish_after=10,
            )
    assert s.rebuilds == []  # the pallas budget was never touched


def test_transient_budget_zero_disables_retry():
    """transient_budget=0 (the multi-process dist guard): the first
    transient propagates — no rank-local re-dispatch."""
    def flaky(t, n):
        raise JaxRuntimeError("UNAVAILABLE: TPU device error")

    with pytest.raises(JaxRuntimeError):
        drive_chunks(
            (jnp.asarray(0.0), jnp.asarray(0, jnp.int32)),
            flaky, te=2.5, time_index=0, bar=_Bar(), retry=lambda: None,
            transient_budget=0,
        )


def test_pallas_refailure_long_after_restore_not_dead():
    """A pallas failure long after a restore (a full clean streak later)
    is a fresh fault, not probation evidence: the fallback happens again
    and a later restore is still allowed. Guards the drive-loop ordering
    (the streak must be judged BEFORE any reset)."""
    s = _FakeSolver()
    retry = pallas_retry(s, "x", restore_after=2)
    retry()                                    # fallback 1
    for _ in range(2):
        fn = retry.on_clean_chunk()
    assert fn is not None                      # restored
    for _ in range(5):
        assert retry.on_clean_chunk() is None  # long clean streak on pallas
    s._uses = True
    assert retry() is not None                 # fails again — NOT dead
    for _ in range(2):
        fn = retry.on_clean_chunk()
    assert fn is not None                      # restore still allowed
    assert s.rebuilds == ["jnp", "auto", "jnp", "auto"]
