"""Chunk-driver protocol tests (models/_driver.py) — the framework's
failure-handling subsystem: the chunked time loop, the one-shot transient
device-fault retry, and the pallas->jnp rebuild hook. The reference has no
failure handling at all (SURVEY.md §5: fprintf+exit), so these paths only
exist here — and they were previously exercised only implicitly."""

import warnings

import jax.numpy as jnp
import pytest

from pampi_tpu.models._driver import drive_chunks, pallas_retry


class JaxRuntimeError(Exception):
    """Name-alike of jax's runtime error: _is_transient_device_fault matches
    on the type NAME, so tests can forge faults without touching jax."""


class _Bar:
    def __init__(self):
        self.updates = []
        self.stopped = False

    def update(self, t):
        self.updates.append(t)

    def stop(self):
        self.stopped = True


def _advance(dt=1.0):
    def chunk(t, n):
        return (t + dt, n + 1)

    return chunk


def test_normal_loop_runs_until_te_and_syncs():
    bar = _Bar()
    seen = []
    state = drive_chunks(
        (jnp.asarray(0.0), jnp.asarray(0, jnp.int32)),
        _advance(), te=2.5, time_index=0, bar=bar,
        retry=lambda: None, on_state=seen.append,
    )
    # loop body runs while t <= te at chunk start: t = 0,1,2 -> 3 chunks
    assert float(state[0]) == 3.0 and int(state[1]) == 3
    assert len(seen) == 3
    assert bar.stopped and bar.updates == [1.0, 2.0, 3.0]


def test_nan_time_is_terminal_not_a_spin():
    """An adaptive-dt blow-up makes t NaN; every later chunk is a device
    no-op and `t_old > te` is false for NaN — the loop must treat NaN as
    terminal (the dist solvers' `while t <= te` already exits on NaN)
    instead of spinning forever on no-op dispatches."""
    bar = _Bar()

    def nan_chunk(t, n):
        return (jnp.asarray(float("nan")), n + 1)

    state = drive_chunks(
        (jnp.asarray(0.0), jnp.asarray(0, jnp.int32)),
        nan_chunk, te=100.0, time_index=0, bar=bar,
        retry=lambda: None,
    )
    assert float(state[0]) != float(state[0])  # NaN returned, loop exited
    assert int(state[1]) == 1  # terminated on the FIRST NaN confirmation
    assert bar.stopped


def test_transient_fault_retried_exactly_once():
    calls = {"n": 0}

    def flaky(t, n):
        calls["n"] += 1
        if calls["n"] == 2:
            raise JaxRuntimeError("UNAVAILABLE: TPU device error")
        return (t + 1.0, n + 1)

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        state = drive_chunks(
            (jnp.asarray(0.0), jnp.asarray(0, jnp.int32)),
            flaky, te=1.5, time_index=0, bar=_Bar(), retry=lambda: None,
        )
    assert float(state[0]) == 2.0
    assert any("transient" in str(x.message) for x in w)
    # 2 successful chunks + 1 faulted attempt
    assert calls["n"] == 3


def test_second_transient_fault_reraises():
    def always_faulty(t, n):
        raise JaxRuntimeError("UNAVAILABLE: TPU device error")

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with pytest.raises(JaxRuntimeError):
            drive_chunks(
                (jnp.asarray(0.0), jnp.asarray(0, jnp.int32)),
                always_faulty, te=1.0, time_index=0, bar=_Bar(),
                retry=lambda: None,
            )


def test_non_transient_error_propagates():
    def broken(t, n):
        raise ValueError("genuine bug")

    with pytest.raises(ValueError, match="genuine bug"):
        drive_chunks(
            (jnp.asarray(0.0), jnp.asarray(0, jnp.int32)),
            broken, te=1.0, time_index=0, bar=_Bar(), retry=lambda: None,
        )


def test_retry_hook_swaps_chunk_fn():
    """A failing chunk whose retry() supplies a rebuilt fn continues on the
    new fn with UNCHANGED inputs (the loop is functional)."""
    calls = {"old": 0, "new": 0}

    def old_fn(t, n):
        calls["old"] += 1
        raise ValueError("pallas kernel exploded")

    def new_fn(t, n):
        calls["new"] += 1
        return (t + 1.0, n + 1)

    state = drive_chunks(
        (jnp.asarray(0.0), jnp.asarray(0, jnp.int32)),
        old_fn, te=1.5, time_index=0, bar=_Bar(), retry=lambda: new_fn,
    )
    assert calls["old"] == 1 and calls["new"] == 2
    assert float(state[0]) == 2.0 and int(state[1]) == 2


class _FakeSolver:
    def __init__(self, backend="auto", uses_pallas=True):
        self._backend = backend
        self._uses = uses_pallas
        self.rebuilds = []

    def _uses_pallas(self):
        return self._uses

    def _build_chunk(self, backend):
        self.rebuilds.append(backend)

        def chunk(t, n):
            return (t + 1.0, n + 1)

        return chunk


def test_pallas_retry_rebuilds_once_then_gives_up():
    s = _FakeSolver()
    retry = pallas_retry(s, "pressure solve")
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        fn = retry()
    assert fn is not None and s.rebuilds == ["jnp"]
    assert s._backend == "jnp"
    assert any("jnp path" in str(x.message) for x in w)
    # a second failure now comes FROM the jnp path: no more retries
    assert retry() is None


def test_pallas_retry_none_when_pallas_not_in_play():
    s = _FakeSolver(uses_pallas=False)
    assert pallas_retry(s, "x")() is None


def test_pipelined_loop_same_results_and_hooks():
    """lookahead > 0 must not change WHAT runs — same final state, every
    chunk's state still reaches bar/on_state in order — only WHEN the host
    syncs. Overshoot chunks past te must be no-ops for the returned state
    (the real chunk_fn's while-cond guarantees it; the fake honors te)."""
    te = 2.5

    def chunk(t, n):  # te-guarded like the real device chunk
        import jax.numpy as jnp

        adv = t <= te
        return (jnp.where(adv, t + 1.0, t),
                jnp.where(adv, n + 1, n))

    for la in (1, 2, 5):
        bar = _Bar()
        seen = []
        state = drive_chunks(
            (jnp.asarray(0.0), jnp.asarray(0, jnp.int32)),
            chunk, te=te, time_index=0, bar=bar,
            retry=lambda: None, on_state=seen.append, lookahead=la,
        )
        assert float(state[0]) == 3.0 and int(state[1]) == 3
        assert bar.updates == [1.0, 2.0, 3.0] and bar.stopped
        assert [float(s[0]) for s in seen] == [1.0, 2.0, 3.0]


def test_pipelined_transient_fault_resets_to_confirmed():
    """A fault inside the pipeline rewinds to the last CONFIRMED state:
    the simulation replays the unconfirmed tail, never skips or doubles a
    step (state is t itself, so doubling would show as t jumping)."""
    te = 3.5
    calls = {"n": 0}

    def flaky(t, n):
        calls["n"] += 1
        if calls["n"] == 3:
            raise JaxRuntimeError("UNAVAILABLE: TPU device error")
        adv = float(t) <= te
        return (t + 1.0, n + 1) if adv else (t, n)

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        state = drive_chunks(
            (jnp.asarray(0.0), jnp.asarray(0, jnp.int32)),
            flaky, te=te, time_index=0, bar=_Bar(), retry=lambda: None,
            lookahead=2,
        )
    assert float(state[0]) == 4.0 and int(state[1]) == 4
    assert any("transient" in str(x.message) for x in w)


def test_pipelined_zero_trip_returns_initial_state():
    s0 = (jnp.asarray(9.0), jnp.asarray(7, jnp.int32))
    bar = _Bar()
    out = drive_chunks(s0, _advance(), te=2.0, time_index=0, bar=bar,
                       retry=lambda: None, lookahead=3)
    assert out is s0 and bar.stopped and bar.updates == []


def test_negative_lookahead_rejected():
    """Programmatic callers bypass cli.py's .par validation; the driver
    itself must refuse (a negative value would popleft an empty deque)."""
    with pytest.raises(ValueError, match="lookahead"):
        drive_chunks((jnp.asarray(0.0),), _advance(), te=2.0, time_index=0,
                     bar=_Bar(), retry=lambda: None, lookahead=-1)


def test_tpu_chunk_override_preserves_results():
    """tpu_chunk overrides the per-dispatch step count (watchdog escape for
    slow-step configs) without changing what is computed."""
    import numpy as np

    from pampi_tpu.models.ns2d import NS2DSolver
    from pampi_tpu.utils.params import Parameter

    param = Parameter(name="dcavity", imax=16, jmax=16, re=10.0, te=0.05,
                      tau=0.5, itermax=200, eps=1e-6, omg=1.7, gamma=0.9)
    a = NS2DSolver(param)
    a.run(progress=False)
    b = NS2DSolver(param.replace(tpu_chunk=3))
    b.run(progress=False)
    assert a.nt == b.nt > 3
    np.testing.assert_array_equal(np.asarray(a.u), np.asarray(b.u))
    np.testing.assert_array_equal(np.asarray(a.p), np.asarray(b.p))
