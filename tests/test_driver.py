"""Chunk-driver protocol tests (models/_driver.py) — the framework's
failure-handling subsystem: the chunked time loop, the one-shot transient
device-fault retry, and the pallas->jnp rebuild hook. The reference has no
failure handling at all (SURVEY.md §5: fprintf+exit), so these paths only
exist here — and they were previously exercised only implicitly."""

import warnings

import jax.numpy as jnp
import pytest

from pampi_tpu.models._driver import drive_chunks, pallas_retry


class JaxRuntimeError(Exception):
    """Name-alike of jax's runtime error: _is_transient_device_fault matches
    on the type NAME, so tests can forge faults without touching jax."""


class _Bar:
    def __init__(self):
        self.updates = []
        self.stopped = False

    def update(self, t):
        self.updates.append(t)

    def stop(self):
        self.stopped = True


def _advance(dt=1.0):
    def chunk(t, n):
        return (t + dt, n + 1)

    return chunk


def test_normal_loop_runs_until_te_and_syncs():
    bar = _Bar()
    seen = []
    state = drive_chunks(
        (jnp.asarray(0.0), jnp.asarray(0, jnp.int32)),
        _advance(), te=2.5, time_index=0, bar=bar,
        retry=lambda: None, on_state=seen.append,
    )
    # loop body runs while t <= te at chunk start: t = 0,1,2 -> 3 chunks
    assert float(state[0]) == 3.0 and int(state[1]) == 3
    assert len(seen) == 3
    assert bar.stopped and bar.updates == [1.0, 2.0, 3.0]


def test_transient_fault_retried_exactly_once():
    calls = {"n": 0}

    def flaky(t, n):
        calls["n"] += 1
        if calls["n"] == 2:
            raise JaxRuntimeError("UNAVAILABLE: TPU device error")
        return (t + 1.0, n + 1)

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        state = drive_chunks(
            (jnp.asarray(0.0), jnp.asarray(0, jnp.int32)),
            flaky, te=1.5, time_index=0, bar=_Bar(), retry=lambda: None,
        )
    assert float(state[0]) == 2.0
    assert any("transient" in str(x.message) for x in w)
    # 2 successful chunks + 1 faulted attempt
    assert calls["n"] == 3


def test_second_transient_fault_reraises():
    def always_faulty(t, n):
        raise JaxRuntimeError("UNAVAILABLE: TPU device error")

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with pytest.raises(JaxRuntimeError):
            drive_chunks(
                (jnp.asarray(0.0), jnp.asarray(0, jnp.int32)),
                always_faulty, te=1.0, time_index=0, bar=_Bar(),
                retry=lambda: None,
            )


def test_non_transient_error_propagates():
    def broken(t, n):
        raise ValueError("genuine bug")

    with pytest.raises(ValueError, match="genuine bug"):
        drive_chunks(
            (jnp.asarray(0.0), jnp.asarray(0, jnp.int32)),
            broken, te=1.0, time_index=0, bar=_Bar(), retry=lambda: None,
        )


def test_retry_hook_swaps_chunk_fn():
    """A failing chunk whose retry() supplies a rebuilt fn continues on the
    new fn with UNCHANGED inputs (the loop is functional)."""
    calls = {"old": 0, "new": 0}

    def old_fn(t, n):
        calls["old"] += 1
        raise ValueError("pallas kernel exploded")

    def new_fn(t, n):
        calls["new"] += 1
        return (t + 1.0, n + 1)

    state = drive_chunks(
        (jnp.asarray(0.0), jnp.asarray(0, jnp.int32)),
        old_fn, te=1.5, time_index=0, bar=_Bar(), retry=lambda: new_fn,
    )
    assert calls["old"] == 1 and calls["new"] == 2
    assert float(state[0]) == 2.0 and int(state[1]) == 2


class _FakeSolver:
    def __init__(self, backend="auto", uses_pallas=True):
        self._backend = backend
        self._uses = uses_pallas
        self.rebuilds = []

    def _uses_pallas(self):
        return self._uses

    def _build_chunk(self, backend):
        self.rebuilds.append(backend)

        def chunk(t, n):
            return (t + 1.0, n + 1)

        return chunk


def test_pallas_retry_rebuilds_once_then_gives_up():
    s = _FakeSolver()
    retry = pallas_retry(s, "pressure solve")
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        fn = retry()
    assert fn is not None and s.rebuilds == ["jnp"]
    assert s._backend == "jnp"
    assert any("jnp path" in str(x.message) for x in w)
    # a second failure now comes FROM the jnp path: no more retries
    assert retry() is None


def test_pallas_retry_none_when_pallas_not_in_play():
    s = _FakeSolver(uses_pallas=False)
    assert pallas_retry(s, "x")() is None
