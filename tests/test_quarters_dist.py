"""Distributed quarter-layout SOR (parallel/quarters_dist + ops/sor_qdist):
the round-3 production multi-chip path. Parity ladder:

1. jnp twin == interpret-mode Pallas kernel, bitwise, on raw stacked planes
   (arbitrary global offsets — the mask formulas must be in lockstep).
2. Distributed quarters solve == single-device oracle across mesh shapes.
3. CA-depth independence: the trajectory does not depend on n (exact
   redundant-recompute semantics, ≙ tests/test_ca_sor.py for the grid path).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pampi_tpu.models.poisson import PoissonSolver
from pampi_tpu.models.poisson_dist import DistPoissonSolver
from pampi_tpu.parallel.comm import CartComm
from pampi_tpu.parallel import quarters_dist as qd
from pampi_tpu.utils import dispatch
from pampi_tpu.utils.params import Parameter


def _param(**kw):
    base = dict(
        imax=64, jmax=64, itermax=200, eps=1e-12, omg=1.9,
        tpu_dtype="float64", tpu_sor_layout="quarters",
    )
    base.update(kw)
    return Parameter(**base)


def test_twin_bitwise_matches_interpret_kernel():
    """The jnp twin and the scalar-prefetch Pallas kernel (interpret mode)
    are the same program: bitwise-equal planes and residuals, including at
    nonzero global offsets (an off-origin shard's mask geometry)."""
    from pampi_tpu.ops.sor_qdist import make_rb_iters_qdist

    rng = np.random.default_rng(7)
    jmax = imax = 32
    jl, il = 16, 8
    n = 2
    g = qd.make_qgeom(jmax, imax, jl, il, n, jnp.float64)
    ext = jnp.asarray(rng.standard_normal((jl + 2, il + 2)))
    rhse = jnp.asarray(rng.standard_normal((jl + 2, il + 2)))
    xq = qd.pack_ext_to_q(ext, g)
    rq = qd.pack_ext_to_q(rhse, g)
    dx = dy = 1.0 / imax
    factor = 1.9 * 0.5 * (dx * dx * dy * dy) / (dx * dx + dy * dy)

    for qoff_j, qoff_i in ((0, 0), (8, 4), (0, 12)):
        m = qd.q_masks(g, qoff_j, qoff_i)
        t_x, t_r = jax.jit(qd.rb_iters_q_jnp, static_argnums=2)(
            xq, rq, g, m, factor, 1.0 / (dx * dx), 1.0 / (dy * dy)
        )
        rb = make_rb_iters_qdist(g, dx, dy, 1.9, jnp.float64, interpret=True)
        k_x, k_r = rb(jnp.asarray([qoff_j, qoff_i], jnp.int32), xq, rq)
        # the kernel stores only the band rows [h, h+nblocks*brq) — its
        # window-halo padding rows stay uninitialized (never read back)
        band = slice(g.h, g.h + g.nblocks * g.brq)
        np.testing.assert_array_equal(
            np.asarray(t_x[:, band]), np.asarray(k_x[:, band])
        )
        # residual summation order differs (per-lane/per-block accumulator
        # vs whole-array sum): ulp-level only
        np.testing.assert_allclose(float(t_r), float(k_r), rtol=1e-12)


def test_pack_unpack_roundtrip():
    g = qd.make_qgeom(32, 32, 16, 8, 2, jnp.float64)
    ext = jnp.asarray(np.random.default_rng(0).standard_normal((18, 10)))
    out = qd.unpack_q_to_ext(qd.pack_ext_to_q(ext, g), g)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ext))


@pytest.mark.parametrize("dims", [(2, 4), (1, 8), (8, 1), (2, 2)])
def test_qdist_matches_single_device_oracle(dims):
    """Forced-quarters distributed solve (interpret kernel on CPU) equals
    the single-device jnp red-black solver on every mesh shape — full
    reference-layout field to 1e-12 (observed bitwise)."""
    # 96 is divisible by every clamped CA depth these meshes produce
    # (n=3 on the thin shards, n=4 elsewhere), so no overshoot
    param = _param(itermax=96)
    ds = DistPoissonSolver(param, comm=CartComm(ndims=2, dims=dims))
    it_d, _ = ds.solve()
    assert "quarters" in dispatch.last("poisson_dist")

    ss = PoissonSolver(_param(tpu_sor_layout="checkerboard", itermax=96))
    it_s, _ = ss.solve()
    assert it_d == it_s == param.itermax
    np.testing.assert_allclose(
        ds.full_field(), np.asarray(jax.device_get(ss.p)), atol=1e-12, rtol=0
    )


def test_qdist_trajectory_independent_of_ca_depth():
    """n=1,2,3 runs produce identical fields after the same iteration count
    (exact CA semantics: deeper exchange + redundant recompute changes the
    message schedule, not the arithmetic)."""
    fields = []
    for n in (1, 2, 3):
        param = _param(itermax=24, tpu_ca_inner=n, tpu_sor_inner=n)
        ds = DistPoissonSolver(param, comm=CartComm(ndims=2, dims=(2, 4)))
        it, _ = ds.solve()
        assert it == 24
        fields.append(ds.full_field())
    np.testing.assert_array_equal(fields[0], fields[1])
    np.testing.assert_array_equal(fields[0], fields[2])


def test_qdist_f32_close_to_oracle():
    param = _param(tpu_dtype="float32", itermax=120)
    ds = DistPoissonSolver(param, comm=CartComm(ndims=2, dims=(2, 4)))
    ds.solve()
    ss = PoissonSolver(_param(tpu_dtype="float32",
                              tpu_sor_layout="checkerboard", itermax=120))
    ss.solve()
    np.testing.assert_allclose(
        ds.full_field(), np.asarray(jax.device_get(ss.p)),
        atol=5e-5, rtol=0,
    )


def test_ns2d_dist_quarters_vs_single(reference_dir):
    """Forced-quarters distributed NS-2D equals the single-device solver to
    ulp-level over several dcavity steps (the quarters association differs
    from the checkerboard jnp path — ops/sor_quarters.py policy — so this is
    allclose, not the grid path's array_equal)."""
    from pampi_tpu.models.ns2d import NS2DSolver
    from pampi_tpu.models.ns2d_dist import NS2DDistSolver
    from pampi_tpu.utils.params import read_parameter

    param = read_parameter(
        str(reference_dir / "assignment-5/sequential/dcavity.par")
    ).replace(te=0.003, imax=64, jmax=64, tpu_sor_layout="quarters")
    dist = NS2DDistSolver(param, CartComm(ndims=2, dims=(2, 4)))
    dist.run(progress=False)
    assert "quarters" in dispatch.last("ns2d_dist")

    single = NS2DSolver(param.replace(tpu_sor_layout="checkerboard"))
    single.run(progress=False)
    assert dist.nt == single.nt
    ud, vd, pd = dist.fields()
    # residual summation order can flip a convergence-gated iteration at the
    # eps threshold, so parity is trajectory-level (1e-8), not bitwise
    np.testing.assert_allclose(np.asarray(single.u), ud, atol=1e-8, rtol=0)
    np.testing.assert_allclose(np.asarray(single.v), vd, atol=1e-8, rtol=0)
    # p is the directly-iterated quantity: a flipped convergence-gated
    # iteration moves it at the per-update level near eps
    np.testing.assert_allclose(np.asarray(single.p), pd, atol=1e-6, rtol=0)


def test_qdist_clamp_and_eligibility():
    assert qd.qdist_clamp(8, 8, 8) == 3
    assert qd.qdist_clamp(0, 64, 64) == 1
    assert qd.qdist_supported(64, 64, 16, 8)
    assert not qd.qdist_supported(63, 64, 16, 8)   # odd global
    assert not qd.qdist_supported(64, 64, 16, 2)   # shard too thin
    with pytest.raises(ValueError):
        # 72/8 = 9: odd per-shard extent — forced quarters must refuse
        DistPoissonSolver(
            _param(imax=72, jmax=72),
            comm=CartComm(ndims=2, dims=(8, 1)),
        )


def test_obstacle_dist_pallas_bitwise_matches_jnp():
    """The per-shard flag-masked Pallas kernel (ops/sor_obsdist, interpret
    on CPU) is the same program as the jnp CA obstacle path — bitwise, on
    the 8-device mesh, at matched CA depth (f64: the kernel computes
    omega/denom exactly as make_masks does)."""
    from jax.sharding import PartitionSpec as P

    from pampi_tpu.ops import obstacle as obst
    from pampi_tpu.parallel.comm import halo_exchange

    imax, jmax = 64, 32
    dx, dy = 16.0 / imax, 4.0 / jmax
    fluid = obst.build_fluid(imax, jmax, dx, dy, "6.0,1.5,10.0,2.5")
    m = obst.make_masks(fluid, dx, dy, 1.7, jnp.float64)
    comm = CartComm(ndims=2, dims=(2, 4))
    jl, il = jmax // 2, imax // 4
    rng = np.random.default_rng(1)
    p0 = jnp.asarray(rng.standard_normal((jmax + 2, imax + 2)))
    rhs = jnp.asarray(rng.standard_normal((jmax + 2, imax + 2)))

    outs = {}
    for backend in ("auto", "pallas"):  # auto on CPU = jnp CA
        solve, used_pallas = obst.make_dist_obstacle_solver(
            comm, imax, jmax, jl, il, dx, dy, 1e-12, 60, m, jnp.float64,
            ca_n=2, sor_inner=2, backend=backend,
        )
        expect = "jnp_ca ca2" if backend == "auto" else "pallas ca2"
        assert dispatch.last("obstacle_dist") == expect
        assert used_pallas == (backend == "pallas")

        def kern(p_int, rhs_int, _solve=solve):
            pe = halo_exchange(jnp.pad(p_int, 1), comm)
            re = halo_exchange(jnp.pad(rhs_int, 1), comm)
            p, res, it = _solve(pe, re)
            return p[1:-1, 1:-1], res, it

        spec = P("j", "i")
        f = jax.jit(comm.shard_map(
            kern, in_specs=(spec, spec), out_specs=(spec, P(), P()),
            check_vma=False,
        ))
        p_out, res, it = f(p0[1:-1, 1:-1], rhs[1:-1, 1:-1])
        outs[backend] = (np.asarray(p_out), int(it))

    assert outs["auto"][1] == outs["pallas"][1] == 60
    np.testing.assert_array_equal(outs["auto"][0], outs["pallas"][0])


def test_obsdist_kernel_multiblock_matches_jnp_twin():
    """The multi-block DMA pipeline (nblocks >= 3: double-buffer slot
    rotation, b>=2 store drains, cross-block owned-residual accumulation)
    against ca_rb_iters_obstacle directly — plane bitwise AND residual
    parity (the mesh-level test's convergence counts are cap-bound, so it
    never checks res)."""
    from pampi_tpu.ops import obstacle as obst
    from pampi_tpu.ops import sor_pallas as sp
    from pampi_tpu.ops.sor_obsdist import make_rb_iters_obsdist
    from pampi_tpu.parallel.stencil2d import ca_masks

    imax, jmax = 64, 32
    dx, dy = 16.0 / imax, 4.0 / jmax
    fluid = obst.build_fluid(imax, jmax, dx, dy, "6.0,1.5,10.0,2.5")
    m = obst.make_masks(fluid, dx, dy, 1.7, jnp.float64)
    jl, il = jmax, imax  # single shard: offsets 0, full domain
    n = 2
    H = 2 * n
    rb, br, h = make_rb_iters_obsdist(
        jmax, imax, jl, il, n, dx, dy, 1.7, jnp.float64,
        interpret=True, block_rows=8,  # ext_j=40 -> nblocks=5
    )
    assert -(-(jl + 2 * H) // br) >= 3

    rng = np.random.default_rng(3)
    pd = jnp.asarray(rng.standard_normal((jl + 2 * H, il + 2 * H)))
    rd = jnp.asarray(rng.standard_normal((jl + 2 * H, il + 2 * H)))
    offs = jnp.asarray([0, 0], jnp.int32)
    k_p, k_r = rb(offs, sp.pad_array(pd, br, h), sp.pad_array(rd, br, h),
                  sp.pad_array(
                      jnp.pad(m.fluid, [(H - 1, H - 1)] * 2).astype(
                          jnp.float64
                      ), br, h))
    k_p = sp.unpad_array(k_p, jl + 2 * H - 2, il + 2 * H - 2, h)

    # the jnp twin's deep masks use get_offsets (axis_index), so it must
    # run under a (1,1)-mesh shard_map (compat_shard_map: the one
    # toolchain shim — this container's jax has no jax.shard_map)
    import jax as _j
    from jax.sharding import Mesh, PartitionSpec as P

    from pampi_tpu.parallel.comm import compat_shard_map

    mesh = Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1), ("j", "i"))

    def kern(pd, rd):
        cm = ca_masks(jl, il, H, jmax, imax, jnp.float64)
        om = obst.deep_obstacle_masks(m, jl, il, H)
        return obst.ca_rb_iters_obstacle(
            pd, rd, n, cm, om, 1.0 / (dx * dx), 1.0 / (dy * dy)
        )

    t_p, t_r = _j.jit(compat_shard_map(
        kern, mesh=mesh, in_specs=(P(), P()), out_specs=(P(), P()),
        check_vma=False,
    ))(pd, rd)
    np.testing.assert_array_equal(np.asarray(k_p), np.asarray(t_p))
    np.testing.assert_allclose(float(k_r), float(t_r), rtol=1e-12)


def test_obsdist_depth_backoff_keeps_pallas(monkeypatch):
    """VMEM infeasibility at deep n must back the depth off (halving) and
    keep the pallas kernel, not fall to jnp: a shallower kernel beats the
    jnp CA path at any depth (round-4 anchor: n=16 OOMs Mosaic's unrolled-
    sweep stack at a 512x2048 shard while n=8 runs at 22.9G)."""
    from pampi_tpu.ops import obstacle as obst
    from pampi_tpu.ops import sor_obsdist as so
    from pampi_tpu.parallel.comm import CartComm
    from pampi_tpu.utils import dispatch

    real = so.make_rb_iters_obsdist

    def shallow_only(jmax, imax, jl, il, n, *a, **k):
        if n > 2:
            raise ValueError("forced infeasible at deep n")
        return real(jmax, imax, jl, il, n, *a, **k)

    monkeypatch.setattr(so, "make_rb_iters_obsdist", shallow_only)

    imax = jmax = 32
    dx = dy = 1.0 / 32
    fluid = obst.build_fluid(imax, jmax, dx, dy, "0.3,0.3,0.6,0.6")
    m = obst.make_masks(fluid, dx, dy, 1.7, jnp.float64)
    comm = CartComm(ndims=2, dims=(1, 1))
    solve, used = obst.make_dist_obstacle_solver(
        comm, imax, jmax, jmax, imax, dx, dy, 1e-12, 8, m, jnp.float64,
        ca_n=8, sor_inner=8, backend="pallas",
    )
    assert used
    assert dispatch.last("obstacle_dist") == "pallas ca2"


def test_obsdist_windowed_sweeps_bitwise():
    """rb_inner_sweeps(loop=True) — the scf.for sweep windowing — is
    bitwise-equal to the unrolled form (same per-sweep op sequence).
    Round-5 outcome (VERDICT r4 item 7): the looped kernel is an EXPLICIT
    opt-in only — it crashes the production Mosaic compiler at any depth
    on the current toolchain (documented in make_rb_iters_obsdist), so
    auto mode keeps the unrolled form + depth backoff; this test pins the
    windowed variant's correctness for when the toolchain allows it."""
    from pampi_tpu.ops import obstacle as obst
    from pampi_tpu.ops import sor_obsdist as so
    from pampi_tpu.ops import sor_pallas as sp

    imax, jmax = 64, 32
    dx, dy = 16.0 / imax, 4.0 / jmax
    fluid = obst.build_fluid(imax, jmax, dx, dy, "6.0,1.5,10.0,2.5")
    m = obst.make_masks(fluid, dx, dy, 1.7, jnp.float64)
    jl, il = jmax, imax
    n = 4
    H = 2 * n

    def build(loop):
        return so.make_rb_iters_obsdist(
            jmax, imax, jl, il, n, dx, dy, 1.7, jnp.float64,
            interpret=True, loop_sweeps=loop,
        )

    rb_u, br, h = build(False)
    rb_l, br2, h2 = build(True)
    assert (br, h) == (br2, h2)

    rng = np.random.default_rng(9)
    pd = jnp.asarray(rng.standard_normal((jl + 2 * H, il + 2 * H)))
    rd = jnp.asarray(rng.standard_normal((jl + 2 * H, il + 2 * H)))
    flg = sp.pad_array(
        jnp.pad(m.fluid, [(H - 1, H - 1)] * 2).astype(jnp.float64), br, h)
    offs = jnp.asarray([0, 0], jnp.int32)
    pu, ru = rb_u(offs, sp.pad_array(pd, br, h), sp.pad_array(rd, br, h), flg)
    plp, rl = rb_l(offs, sp.pad_array(pd, br, h), sp.pad_array(rd, br, h), flg)
    np.testing.assert_array_equal(np.asarray(pu), np.asarray(plp))
    np.testing.assert_array_equal(float(ru), float(rl))
