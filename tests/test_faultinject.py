"""Fault-injection harness (utils/faultinject.py) + the recovery layer it
proves (ISSUE 4 acceptance):

- IDENTITY WHEN UNSET: with PAMPI_FAULTS unset every hook is a no-op and
  the solver chunk's jaxpr is byte-identical to the uninjected build;
  host-side fault clauses never touch traces at all (same contract as
  PAMPI_TELEMETRY).
- RECOVERABLE CLASSES complete the run: a spaced transient matches the
  uninjected run bitwise (same compiled chunk, same inputs); the pallas
  failure falls back to jnp and asserts trajectory-level invariants; an
  injected field corruption under an armed ring rolls back and re-drives
  with a clamped dt to a finite final state.
- TERMINAL CLASSES fail with a structured diagnostic naming the fault —
  never a hang, never silent NaN fields without a record.

Compile cost: every solver is 16², itermax <= 50, a few steps (the PR 3
marker-audit lever); the recovery-exhaustion test pays 3 rebuilds by
design (each rollback re-traces) and stays on the jnp chunk.
"""

import json
import warnings

import jax
import numpy as np
import pytest

from pampi_tpu.models.ns2d import NS2DSolver
from pampi_tpu.utils import faultinject as fi
from pampi_tpu.utils import telemetry as tm
from pampi_tpu.utils.params import Parameter

_BASE = dict(name="dcavity", imax=16, jmax=16, re=10.0, te=0.05, tau=0.5,
             itermax=50, eps=1e-4, omg=1.7, gamma=0.9)


# the `faults` arming fixture lives in tests/conftest.py (shared with
# test_checkpoint.py)


@pytest.fixture()
def tel_on(tmp_path, monkeypatch):
    path = tmp_path / "run.jsonl"
    monkeypatch.setenv("PAMPI_TELEMETRY", str(path))
    tm.reset()
    yield path
    tm.reset()


def _records(path):
    return [json.loads(ln) for ln in open(path) if ln.strip()]


def _kinds(path, kind):
    return [r for r in _records(path) if r["kind"] == kind]


# ---------------------------------------------------------------------------
# spec grammar
# ---------------------------------------------------------------------------

def test_spec_parse_and_errors(faults):
    import math

    faults("transient@chunk2, nan@step5:u*3 ,ckpt_torn@write1")
    assert fi.enabled()
    # one generation of field faults per take; *3 arms three builds
    for _ in range(3):
        taken = fi.take_field_faults()
        assert len(taken) == 1
        field, step, value = taken[0]
        assert field == "u" and step == 5 and math.isnan(value)
    assert fi.take_field_faults() == ()  # charges spent

    for bad in ("nan@step5", "pallas@step3", "bogus@chunk1", "nan@step2:q",
                "transient@chunk2:u"):
        faults(bad)
        with pytest.raises(fi.FaultSpecError, match="PAMPI_FAULTS"):
            fi.take_field_faults()
            fi.maybe_chunk_fault()


def test_rank_suffix_grammar(faults):
    """PR 10: `@rank<R>` targets chunk/step clauses at ONE rank; the
    sites the protocol never coordinates (lane/write/emit) and any
    malformed suffix are refused with FaultSpecError — a broken spec
    must never run silently uninjected."""
    faults("transient@chunk2@rank1,nan@step5:u@rank0*2")
    assert fi._clauses() == (
        ("transient", "chunk", 2, None, 1, 1),
        ("nan", "step", 5, "u", 2, 0),
    )
    for bad in ("ckpt_torn@write1@rank0", "telemetry@emit1@rank1",
                "nan@lane1:u@rank2", "transient@chunk1@rank",
                "transient@chunk1@bank2", "nan@step1:u@rank1x"):
        faults(bad)
        with pytest.raises(fi.FaultSpecError, match="PAMPI_FAULTS"):
            fi._clauses()


def test_dead_hang_grammar(faults):
    """PR 12: `dead@chunk<N>` / `hang@chunk<N>` parse with the PR 10
    `@rank<R>` targeting; the non-chunk sites and a :field payload are
    refused — the death clauses model a rank, not a value."""
    faults("dead@chunk2@rank1,hang@chunk3@rank0")
    assert fi._clauses() == (
        ("dead", "chunk", 2, None, 1, 1),
        ("hang", "chunk", 3, None, 1, 0),
    )
    for bad in ("dead@step2", "hang@write1", "dead@lane1",
                "dead@chunk2:u", "hang@chunk2:p"):
        faults(bad)
        with pytest.raises(fi.FaultSpecError, match="PAMPI_FAULTS"):
            fi._clauses()


def test_poll_clause_grammar(faults):
    """ISSUE 19: the daemon-plane clauses parse — `dead@poll<N>` bare,
    `burst`/`slow_lane` with a REQUIRED :<tenant> (the :<field> slot
    repurposed as a word) and *<count> as an observation count. The
    poll site is uncoordinated, so @rank<R> is refused; dead takes no
    payload."""
    faults("dead@poll3,burst@poll5:alice*50,slow_lane@poll2:bob")
    assert fi._clauses() == (
        ("dead", "poll", 3, None, 1, None),
        ("burst", "poll", 5, "alice", 50, None),
        ("slow_lane", "poll", 2, "bob", 1, None),
    )
    for bad in ("burst@poll2", "slow_lane@poll1", "dead@poll2:alice",
                "burst@poll2:alice@rank1", "dead@poll2@rank0",
                "burst@chunk2:alice"):
        faults(bad)
        with pytest.raises(fi.FaultSpecError, match="PAMPI_FAULTS"):
            fi._clauses()


def test_poll_faults_fire_and_stay_inert_unpolled(faults):
    """poll_faults() is 1-based and per-poll: burn clauses return their
    (kind, tenant, count) tuples exactly at their poll, `dead` raises
    InjectedRankDeath (a BaseException — the autopilot is its one
    structured consumer), and a counter reset re-arms the timeline.
    Solver-plane hooks never consult poll clauses: building and running
    a solver with only poll clauses armed injects nothing."""
    faults("burst@poll1:alice*3,slow_lane@poll2:bob,dead@poll3")
    assert fi.poll_faults() == (("burst", "alice", 3),)
    assert fi.poll_faults() == (("slow_lane", "bob", 1),)
    with pytest.raises(fi.InjectedRankDeath, match="poll 3"):
        fi.poll_faults()
    assert fi.poll_faults() == ()  # poll 4: timeline passed
    fi.reset()
    assert fi.poll_faults() == (("burst", "alice", 3),)  # re-armed

    faults("dead@poll1,burst@poll1:alice*9")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        s = NS2DSolver(Parameter(**{**_BASE, "te": 0.02, "itermax": 8}))
        s.run(progress=False)  # chunk/step hooks ignore poll clauses
    assert np.isfinite(np.asarray(s.p)).all()


def test_dead_rank_uncoordinated_is_loud_not_classified(faults):
    """A death injected into the UNCOORDINATED single-controller loop
    surfaces as InjectedRankDeath (a BaseException — the drive loop's
    fault-classification funnel cannot swallow it into the transient or
    pallas paths): the run dies loudly naming the injection, never
    retries on a dead rank's behalf."""
    faults("dead@chunk2@rank0")
    s = NS2DSolver(Parameter(tpu_chunk=2, **_BASE))
    with pytest.raises(fi.InjectedRankDeath, match="injected dead"):
        s.run(progress=False)


def test_rank_targeting_fires_and_preserves_charges(faults):
    """A rank-suffixed clause fires only under its rank's scope; a
    NON-matching rank neither fires nor consumes the charge (the
    take_lane_faults convention), and counters are per-rank so every
    virtual rank counts its own dispatches."""
    import math

    faults("transient@chunk2@rank1,nan@step5:u@rank0")
    # rank 1: its SECOND dispatch faults; rank 0's never does
    with fi.rank_scope(1):
        fi.maybe_chunk_fault()
        with pytest.raises(fi.JaxRuntimeError, match="UNAVAILABLE"):
            fi.maybe_chunk_fault()
    with fi.rank_scope(0):
        fi.maybe_chunk_fault()
        fi.maybe_chunk_fault()  # rank 0's dispatch 2: clean
    # the step clause: rank 1 must NOT consume rank 0's charge
    with fi.rank_scope(1):
        assert fi.take_field_faults() == ()
    with fi.rank_scope(0):
        taken = fi.take_field_faults()
    assert len(taken) == 1 and taken[0][0] == "u" and math.isnan(taken[0][2])
    with fi.rank_scope(0):
        assert fi.take_field_faults() == ()  # charge spent by its target


def test_rank_clause_for_other_rank_is_trace_identical(faults):
    """The jaxpr-pin convention (PR 4): a rank-targeted field fault
    aimed at ANOTHER rank leaves this rank's build byte-identical to
    the uninjected program — the where() bakes only into its target."""
    from pampi_tpu.analysis.jaxprcheck import (
        assert_offpath_identity,
        trace_chunk,
    )

    param = Parameter(**_BASE)
    _off, jx_off = assert_offpath_identity(lambda: NS2DSolver(param))
    faults("nan@step3:u@rank7")  # this process is rank 0
    other = NS2DSolver(param)
    assert str(trace_chunk(other)) == str(jx_off)
    faults("nan@step3:u@rank0")  # aimed HERE: the corruption bakes
    armed = NS2DSolver(param)
    assert str(trace_chunk(armed)) != str(jx_off)


def test_counters_reset(faults):
    faults("transient@chunk1")
    with pytest.raises(fi.JaxRuntimeError, match="UNAVAILABLE"):
        fi.maybe_chunk_fault()
    fi.maybe_chunk_fault()  # dispatch 2: clean
    fi.reset()
    with pytest.raises(fi.JaxRuntimeError):
        fi.maybe_chunk_fault()  # counter rewound: dispatch 1 again


# ---------------------------------------------------------------------------
# identity when unset (the PAMPI_TELEMETRY contract, acceptance-pinned)
# ---------------------------------------------------------------------------

def test_unset_is_byte_identical(faults, monkeypatch):
    """PAMPI_FAULTS unset -> the chunk is the uninjected program (two off
    builds trace identically, 5 outvars, no `select` from a corruption
    where); HOST-side clauses (chunk/write/emit sites) never touch traces;
    only nan/inf clauses change the jaxpr — and only in the armed build.
    The off-path pin is the shared analysis/jaxprcheck helper (one home
    for this contract — tests/test_telemetry.py asserts the same one)."""
    from pampi_tpu.analysis.jaxprcheck import (
        assert_offpath_identity,
        trace_chunk,
    )

    param = Parameter(**_BASE)
    _off, jx_off1 = assert_offpath_identity(lambda: NS2DSolver(param))

    faults("transient@chunk99,pallas@chunk98,ckpt_torn@write9,telemetry@emit9")
    host_only = NS2DSolver(param)
    jx_host = trace_chunk(host_only)
    assert str(jx_host) == str(jx_off1)  # host faults are not in the trace

    faults("nan@step3:u*9")
    armed = NS2DSolver(param)
    jx_armed = trace_chunk(armed)
    assert str(jx_armed) != str(jx_off1)  # the corruption where() is baked


# ---------------------------------------------------------------------------
# transient device faults (budget + replenishment)
# ---------------------------------------------------------------------------

def test_transient_injection_recovers_bitwise(faults):
    """A single spaced transient re-dispatches the same compiled chunk on
    unchanged inputs — the final fields match the uninjected run bitwise
    (the ulp-parity contract's strongest form: same arithmetic, same
    program)."""
    ref = NS2DSolver(Parameter(tpu_chunk=2, **_BASE))
    ref.run(progress=False)

    faults("transient@chunk2")
    s = NS2DSolver(Parameter(tpu_chunk=2, **_BASE))
    with pytest.warns(UserWarning, match="transient"):
        s.run(progress=False)
    assert s.nt == ref.nt
    np.testing.assert_array_equal(np.asarray(s.u), np.asarray(ref.u))
    np.testing.assert_array_equal(np.asarray(s.p), np.asarray(ref.p))


def test_spaced_transients_replenish(faults, tel_on):
    """Two transients spaced past the replenish window both retry (the
    satellite fix: the budget used to be one per run), each consumption
    leaving a structured `retry` record."""
    faults("transient@chunk2,transient@chunk9")
    s = NS2DSolver(Parameter(tpu_chunk=1, tpu_retry_replenish=3, **_BASE))
    with pytest.warns(UserWarning, match="transient"):
        s.run(progress=False)
    assert s.t > _BASE["te"] and np.isfinite(np.asarray(s.u)).all()
    retries = _kinds(tel_on, "retry")
    assert len(retries) == 2
    assert all(r["fault"] == "transient" for r in retries)


def test_back_to_back_transients_terminal(faults):
    """Transients inside one replenish window exhaust the budget: the run
    fails with the injected diagnostic (naming the fault), never a hang."""
    faults("transient@chunk2,transient@chunk3")
    s = NS2DSolver(Parameter(tpu_chunk=1, tpu_retry_replenish=50, **_BASE))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with pytest.raises(fi.JaxRuntimeError, match="UNAVAILABLE.*chunk dispatch 3"):
            s.run(progress=False)


# ---------------------------------------------------------------------------
# pallas runtime failure -> jnp rebuild
# ---------------------------------------------------------------------------

def test_pallas_injection_falls_back_to_jnp(faults, tel_on):
    """An injected pallas failure on a fused chunk rebuilds on the jnp path
    and completes. Arithmetic changes (fused kernels stand down), so the
    assertion is trajectory-level: finite fields, t past te, and the
    structured `retry` record naming the fallback."""
    faults("pallas@chunk2")
    s = NS2DSolver(Parameter(tpu_fuse_phases="on", tpu_solver="fft",
                             tpu_chunk=2, **_BASE))
    assert s._fused and s._uses_pallas()
    with pytest.warns(UserWarning, match="jnp path"):
        s.run(progress=False)
    assert s._backend == "jnp"
    assert s.t > _BASE["te"]
    assert np.isfinite(np.asarray(s.u)).all()
    assert np.isfinite(np.asarray(s.p)).all()
    falls = [r for r in _kinds(tel_on, "retry")
             if r.get("action") == "jnp_fallback"]
    assert len(falls) == 1 and falls[0]["fault"] == "pallas"


def test_pallas_injection_without_alternative_is_terminal(faults):
    """The same fault on a chunk that never ran pallas has no fallback:
    the run fails with the injected diagnostic naming the fault."""
    faults("pallas@chunk2")
    s = NS2DSolver(Parameter(tpu_chunk=1, **_BASE))  # jnp-dispatched on CPU
    assert not s._uses_pallas()
    with pytest.raises(fi.InjectedPallasError, match="chunk dispatch 2"):
        s.run(progress=False)


# ---------------------------------------------------------------------------
# field corruption -> sentinel -> rollback-recovery
# ---------------------------------------------------------------------------

def test_nan_injection_exercises_sentinel(faults, tel_on):
    """Fixed-dt run, no ring: the injected NaN surfaces as the PR 3
    structured divergence diagnostic (record + warning), end-to-end from
    the in-band sentinel — not as silent garbage."""
    faults("nan@step3:u")
    s = NS2DSolver(Parameter(tpu_chunk=2,
                             **{**_BASE, "tau": -1.0, "dt": 0.002}))
    with pytest.warns(UserWarning, match="non-finite"):
        s.run(progress=False)
    div = _kinds(tel_on, "divergence")
    assert len(div) == 1
    # corruption lands at step start nt==3; the sentinel latches nt_after
    assert div[0]["first_bad_step"] == 4
    assert div[0]["last_good_step"] == 3


def test_divergence_rollback_recovery(faults, tel_on):
    """The tentpole end-to-end: injected corruption diverges the run, the
    armed ring rolls back to the last clean captured state, the rebuilt
    chunk (injection generation spent) re-drives with a clamped dt, and
    the run COMPLETES with finite fields and a structured `recover`
    record."""
    faults("nan@step5:u")
    s = NS2DSolver(Parameter(tpu_chunk=2, tpu_recover_ring=4, **_BASE))
    with pytest.warns(UserWarning, match="rolled back"):
        s.run(progress=False)
    assert s.t > _BASE["te"]
    assert np.isfinite(np.asarray(s.u)).all()
    assert np.isfinite(np.asarray(s.p)).all()
    assert s._dt_scale == 0.5  # one attempt, clamped once
    recs = _kinds(tel_on, "recover")
    assert len(recs) == 1
    r = recs[0]
    assert r["attempt"] == 1 and r["source"] == "ring"
    assert r["nt"] == 4  # rolled back to the chunk boundary before step 5
    assert _kinds(tel_on, "divergence")  # the sentinel named the blow-up
    # the rollback re-baselines the recorder: nt rewinds at the rollback
    # point, but no chunk record may ever report negative steps/ms
    chunks = _kinds(tel_on, "chunk")
    assert chunks[-1]["nt"] == s.nt
    assert all(c["steps"] >= 0 for c in chunks)
    assert all(c["ms_per_step"] is None or c["ms_per_step"] >= 0
               for c in chunks)


def test_recovery_exhaustion_is_terminal(faults, tel_on):
    """Persistent corruption (*99 re-arms every rebuild) defeats recovery:
    max_attempts rollbacks, then a structured give-up — the run ends on
    the diverged state (early, with the diagnostic), never hangs."""
    faults("nan@step5:u*99")
    s = NS2DSolver(Parameter(tpu_chunk=2, tpu_recover_ring=4,
                             tpu_recover_max=2, **_BASE))
    with pytest.warns(UserWarning, match="gave up"):
        s.run(progress=False)
    assert not np.isfinite(np.asarray(s.u)).all()  # diverged state returned
    recs = _kinds(tel_on, "recover")
    assert [r["attempt"] for r in recs] == [1, 2, 3]
    assert recs[-1]["gave_up"] and recs[-1]["reason"] == "max_attempts"
    assert len(_kinds(tel_on, "divergence")) == 3  # rearm() per rollback


def test_dist_transient_recovers(faults):
    """The dist families now ride the same drive loop (PR 4 migration):
    an injected transient retries instead of killing the run."""
    from pampi_tpu.models.ns2d_dist import NS2DDistSolver
    from pampi_tpu.parallel.comm import CartComm

    ref = NS2DDistSolver(Parameter(**_BASE), CartComm(ndims=2, dims=(2, 2)))
    ref.run(progress=False)
    faults("transient@chunk1")
    s = NS2DDistSolver(Parameter(**_BASE), CartComm(ndims=2, dims=(2, 2)))
    with pytest.warns(UserWarning, match="transient"):
        s.run(progress=False)
    assert s.nt == ref.nt
    for a, b in zip(s.fields(), ref.fields()):
        np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# telemetry-write failure
# ---------------------------------------------------------------------------

def test_telemetry_write_failure_stands_down(faults, tel_on):
    """An injected telemetry write failure costs the flight record, never
    the run: one warning, the file keeps the records before the fault."""
    faults("telemetry@emit3")
    with pytest.warns(UserWarning, match="telemetry disabled"):
        s = NS2DSolver(Parameter(tpu_chunk=2, **_BASE))
        s.run(progress=False)
    assert s.t > _BASE["te"] and np.isfinite(np.asarray(s.u)).all()
    assert len(_records(tel_on)) == 2  # records 1-2 landed, 3 tore it down


def test_telemetry_drop_accounting(faults, tel_on):
    """The stand-down COUNTS what it drops, and finalize's last-gasp
    write lands the count (a truncated flight record names its own
    truncation instead of reading as a quiet run); the report surfaces
    it loudly."""
    faults("telemetry@emit3")
    with pytest.warns(UserWarning, match="telemetry disabled"):
        s = NS2DSolver(Parameter(tpu_chunk=2, **_BASE))
        s.run(progress=False)
    tm.finalize()
    recs = _records(tel_on)
    fins = [r for r in recs if r["kind"] == "finalize"]
    assert len(fins) == 1
    dropped = fins[0]["dropped_records"]
    # the failing record plus every post-stand-down emit of the run
    # (chunk records etc.), but NOT the finalize record itself
    assert dropped >= 2
    from tools import telemetry_report as tr

    assert "TRUNCATED" in tr.render(recs)
    assert tr.summary(recs)["dropped_records"] == dropped
    assert s.nt > 0  # the run itself was never at risk


# ---------------------------------------------------------------------------
# report + artifact-lint round-trip of the resilience kinds (satellite)
# ---------------------------------------------------------------------------

def test_resilience_records_render_and_lint(tel_on):
    """recover/retry/ckpt records flow through tools/telemetry_report.py
    (render + summary) and the summary block passes — and is actually
    checked by — tools/check_artifact.py."""
    tm.emit("retry", fault="transient", budget_left=0, t=1.25)
    tm.emit("retry", fault="pallas", action="jnp_fallback", what="solve")
    tm.emit("recover", family="ns2d", attempt=1, source="ring", t=0.5,
            nt=8, dt_scale=0.5)
    tm.emit("ckpt", event="save", path="ck.npz", t=0.5, nt=8, rotated=True)
    tm.emit("ckpt", event="rotate", path="ck.npz")
    tm.emit("ckpt", event="reject", path="ck.npz", error="CRC32")
    tm.emit("ckpt", event="load", path="ck.npz.prev", generation="prev",
            t=0.25, nt=4)

    from tools import check_artifact as ca
    from tools import telemetry_report as tr

    recs = tr.load(str(tel_on))
    text = tr.render(recs)
    for needle in ("recovery (divergence rollback)", "rolled back to",
                   "retries (budget consumptions)", "jnp_fallback",
                   "checkpoints", "reject"):
        assert needle in text, needle
    summ = tr.summary(recs)
    assert len(summ["recoveries"]) == 1 and summ["recoveries"][0]["nt"] == 8
    assert [r["fault"] for r in summ["retries"]] == ["transient", "pallas"]
    assert summ["ckpt"] == {"save": 1, "rotate": 1, "load": 1, "reject": 1,
                            "skip": 0, "elastic_save": 0, "elastic_load": 0,
                            "ledger_save": 0, "ledger_restore": 0}
    where = "BENCH.telemetry_summary"
    assert ca.lint_telemetry_summary(summ, where) == []
    # gutted blocks are FLAGGED, not waved through
    assert ca.lint_telemetry_summary({**summ, "retries": "zap"}, where)
    assert ca.lint_telemetry_summary({**summ, "recoveries": [{}]}, where)
    assert ca.lint_telemetry_summary({**summ, "ckpt": {"save": 1}}, where)


# ---------------------------------------------------------------------------
# review regressions: fault classification + generation accounting
# ---------------------------------------------------------------------------

def test_transient_while_pallas_active_stays_transient(faults):
    """A transient UNAVAILABLE while the fused/pallas chunk is active takes
    the same-chunk retry, NOT the pallas->jnp fallback — misclassifying a
    device hiccup as a kernel fault would (after a restore) trip the
    deterministically-broken latch and pay jnp speed for the whole run."""
    faults("transient@chunk2")
    s = NS2DSolver(Parameter(tpu_fuse_phases="on", tpu_solver="fft",
                             tpu_chunk=2, **_BASE))
    assert s._uses_pallas()
    with pytest.warns(UserWarning, match="transient"):
        s.run(progress=False)
    assert s._backend != "jnp" and s._fused  # never fell back
    assert s.t > _BASE["te"]


def test_pallas_fallback_keeps_armed_corruption(faults, tel_on):
    """A combined pallas+nan spec must not lose the corruption to the jnp
    fallback rebuild: the generation is taken per solver (__init__ /
    recovery rebuild), so the rebuilt chunk still carries the armed nan
    and the sentinel fires — never a silently-uninjected run."""
    faults("pallas@chunk1,nan@step3:u")
    s = NS2DSolver(Parameter(tpu_fuse_phases="on", tpu_solver="fft",
                             tpu_chunk=2,
                             **{**_BASE, "tau": -1.0, "dt": 0.002}))
    with pytest.warns(UserWarning, match="jnp path"):
        s.run(progress=False)
    # the fallback fired (and the restore may later bring pallas back —
    # that is the replenishing budget working, not a failure)
    assert any(r.get("action") == "jnp_fallback"
               for r in _kinds(tel_on, "retry"))
    div = _kinds(tel_on, "divergence")
    assert len(div) == 1 and div[0]["first_bad_step"] == 4


def test_bad_spec_fails_loudly_at_build(faults):
    """An unparseable spec surfaces as FaultSpecError at the FIRST hook —
    solver construction (the generation take) — never a silently
    uninjected run (the module's fail-loudly contract end-to-end)."""
    faults("nan@step5")  # missing the :field
    with pytest.raises(fi.FaultSpecError, match="PAMPI_FAULTS"):
        NS2DSolver(Parameter(tpu_chunk=2, **_BASE))


def test_bad_spec_not_classified_as_kernel_fault(faults):
    """If the spec error first surfaces inside the drive loop (env armed
    after build), it must re-raise directly — never routed into the
    retry/pallas classification as if a kernel had failed."""
    from pampi_tpu.models._driver import drive_chunks

    faults("nan@step5")
    called = []

    class _Bar:
        def update(self, t):
            pass

        def stop(self):
            pass

    def retry():
        called.append(1)
        return None

    import jax.numpy as jnp

    with pytest.raises(fi.FaultSpecError, match="PAMPI_FAULTS"):
        drive_chunks(
            (jnp.asarray(0.0), jnp.asarray(0, jnp.int32)),
            lambda t, n: (t + 1.0, n + 1), te=2.5, time_index=0,
            bar=_Bar(), retry=retry,
        )
    assert not called  # the retry hook never consulted
