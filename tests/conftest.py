"""Test harness config: fake an 8-device mesh on CPU (the TPU-native answer to
"multi-node without a cluster", SURVEY.md §4) and enable float64 so golden-file
comparisons run at the reference's double precision."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_ENABLE_X64"] = "1"

import jax

jax.config.update("jax_enable_x64", True)
jax.config.update("jax_platforms", "cpu")

import pathlib

import pytest

REFERENCE = pathlib.Path("/root/reference")


@pytest.fixture()
def faults(monkeypatch):
    """Arm a PAMPI_FAULTS spec via the returned setter (utils/faultinject);
    guarantees env cleanup + counter/charge reset however the test exits.
    Shared by the injection suites (test_faultinject, test_checkpoint)."""
    from pampi_tpu.utils import faultinject as fi

    def arm(spec):
        monkeypatch.setenv("PAMPI_FAULTS", spec)
        fi.reset()

    monkeypatch.delenv("PAMPI_FAULTS", raising=False)
    fi.reset()
    yield arm
    fi.reset()


@pytest.fixture(scope="session")
def reference_dir() -> pathlib.Path:
    """Path to the reference C tree. Unmounted containers (the growth/CI
    image ships without /root/reference) must see SKIPS with a reason, not
    SystemExit/FileNotFoundError failures from read_parameter — every test
    that consumes a reference .par or fixture path routes through here."""
    if not REFERENCE.exists():
        pytest.skip("reference tree not mounted at /root/reference")
    return REFERENCE


def pytest_collection_modifyitems(config, items):
    if not REFERENCE.exists():
        skip = pytest.mark.skip(reason="reference tree not mounted")
        for item in items:
            if "golden" in item.keywords:
                item.add_marker(skip)
