"""Tracecheck (pampi_tpu/analysis/ + tools/lint.py) — ISSUE 5 acceptance:

- AST LINT: the tree is clean; every rule fires on a seeded violation
  with a file:line diagnostic; `# lint: allow(<rule>)` escapes it.
- HALO FOOTPRINTS: the production registry passes and the CA entries are
  TIGHT (measured == declared, so the probe is sharp, not vacuous); the
  two mutation classes — a seeded under-halo declaration and an
  over-wide stencil — are both flagged.
- JAXPR CONTRACTS: a config subset round-trips through the baseline
  (update -> check clean -> update again byte-stable); seeded
  launch-count drift and hash drift are flagged with primitive-count
  diffs; the committed CONTRACTS.json matches the harness environment
  and the current config matrix.

Compile cost: everything here TRACES (make_jaxpr) or linearizes tiny
blocks — no jit execution of solver chunks.
"""

import json
import os
import subprocess
import sys

import pytest

from pampi_tpu.analysis import astlint, halocheck, jaxprcheck

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# astlint
# ---------------------------------------------------------------------------

def test_astlint_tree_clean():
    """The repo itself passes its own lint (the make-lint gate)."""
    violations, errors = astlint.lint_tree(REPO)
    assert errors == []
    assert violations == [], "\n".join(str(v) for v in violations)


def _lint_src(tmp_path, src, name="pampi_tpu/models/seeded.py", rules=None):
    path = tmp_path / name
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(src)
    vs, err = astlint.lint_file(str(path), rules=rules,
                                root=str(tmp_path))
    assert err is None
    return vs


def test_rogue_env_read_flagged(tmp_path):
    """The satellite bug class (PAMPI_CSV in dmvm, PAMPI_PROFILE cached at
    import): any os.environ read outside utils/flags.py is flagged at its
    line; the allow escape and the accessor home are exempt."""
    src = ("import os\n"
           "MODE = os.environ.get('PAMPI_X', '0')\n"
           "PATH = os.environ['PAMPI_Y']\n"
           "OK = os.environ.get('PAMPI_Z')  # lint: allow(env-read) — t\n")
    vs = _lint_src(tmp_path, src)
    assert [(v.line, v.rule) for v in vs] == [
        (2, "env-read"), (3, "env-read")]
    assert "flags.env()" in vs[0].message
    # the accessor layer itself is exempt by location
    vs = _lint_src(tmp_path, src, name="pampi_tpu/utils/flags.py")
    assert vs == []


def test_raw_shard_map_flagged(tmp_path):
    """The two-past-PRs rule: shard_map only through compat_shard_map —
    in EVERY spelling (qualified call, bare call after `from jax import
    shard_map`, aliased module import). Applies to harness trees too
    (tools/ and tests/ regressed before)."""
    src = ("import jax\n"
           "from jax.experimental.shard_map import shard_map\n"
           "f = jax.shard_map(lambda x: x, None, None, None)\n")
    vs = _lint_src(tmp_path, src, name="tools/seeded_tool.py")
    assert [v.line for v in vs] == [2, 3]
    assert all(v.rule == "raw-shard-map" for v in vs)
    assert "compat_shard_map" in vs[0].message
    vs = _lint_src(tmp_path, src, name="pampi_tpu/parallel/comm.py")
    assert vs == []  # the shim's home
    # ...but NOT a file that merely ends with the shim's name (path-
    # component boundary, never a bare suffix)
    vs = _lint_src(tmp_path, src, name="pampi_tpu/parallel/webcomm.py")
    assert len(vs) == 2

    # the newer-jax spelling and the aliased import both flag too
    src2 = ("from jax import shard_map\n"
            "import jax.experimental.shard_map as sm\n"
            "a = shard_map(lambda x: x, None, None, None)\n"
            "b = sm.shard_map(lambda x: x, None, None, None)\n")
    vs = _lint_src(tmp_path, src2, name="tools/seeded_tool2.py")
    assert [v.line for v in vs] == [1, 2, 3, 4]
    assert all(v.rule == "raw-shard-map" for v in vs)


def test_traced_context_rules(tmp_path):
    """np.* and nondeterminism inside a traced closure (a def nested in a
    _build_*/make_* builder); builder BODIES are trace-time host code
    where numpy is legitimate."""
    src = ("import numpy as np\n"
           "import time, random\n"
           "def make_step(n):\n"
           "    c = np.arange(n)  # builder body: constant baking, legal\n"
           "    def step(x):\n"
           "        y = np.asarray(x)\n"
           "        t = time.time()\n"
           "        r = random.random()\n"
           "        return y + c[0] + t + r\n"
           "    return step\n")
    vs = _lint_src(tmp_path, src)
    assert [(v.line, v.rule) for v in vs] == [
        (6, "np-in-traced"), (7, "traced-nondet"), (8, "traced-nondet")]


def test_broad_except_and_print(tmp_path):
    src = ("def f():\n"
           "    try:\n"
           "        pass\n"
           "    except Exception:\n"
           "        print('boom')\n"
           "    except Exception:  # lint: allow(broad-except) — probe\n"
           "        pass\n")
    vs = _lint_src(tmp_path, src)
    assert [(v.line, v.rule) for v in vs] == [
        (4, "broad-except"), (5, "print-call")]
    assert str(vs[0]).startswith("pampi_tpu/models/seeded.py:4: ")


def test_env_inventory_complete():
    """The static env-var inventory: every PAMPI_* knob the library reads
    is registered through flags.env at a named site — the rogue reads the
    satellites fixed (PAMPI_CSV, PAMPI_PROFILE) now appear here. The
    RUNTIME registry (flags.registered(), populated as accessors run)
    must agree with the static scan: a var the process actually read that
    the scan can't see would mean a non-literal name snuck past the
    lint."""
    inv = astlint.env_inventory(REPO)
    for var, home in [
        ("PAMPI_TELEMETRY", "utils/telemetry.py"),
        ("PAMPI_FAULTS", "utils/faultinject.py"),
        ("PAMPI_PROFILE", "utils/profiling.py"),
        ("PAMPI_CSV", "models/dmvm.py"),
        ("PAMPI_XLA_CACHE", "utils/xlacache.py"),
        ("PAMPI_NATIVE", "utils/native.py"),
        ("PAMPI_COORDINATOR", "parallel/multihost.py"),
    ]:
        assert var in inv, var
        assert any(home in site for site in inv[var]), (var, inv[var])

    from pampi_tpu.utils import faultinject as fi
    from pampi_tpu.utils import flags, profiling, telemetry

    telemetry.enabled()
    fi.enabled()
    profiling.enabled()
    reg = flags.registered()
    assert {"PAMPI_TELEMETRY", "PAMPI_FAULTS", "PAMPI_PROFILE"} <= set(reg)
    assert set(reg) <= set(inv) | {"PAMPI_DEBUG", "PAMPI_VERBOSE",
                                   "PAMPI_CHECK", "PAMPI_DTYPE"}
    # accessor docs ride the registry (the runtime-readable knob table)
    assert reg["PAMPI_TELEMETRY"]


# ---------------------------------------------------------------------------
# halocheck
# ---------------------------------------------------------------------------

def _ca_entry(n=1, ragged=False):
    return halocheck._ca2d_entry(n, ragged=ragged)


def test_halo_registry_subset_clean_and_tight():
    """The CA contracts hold AND are tight: ca_halo(n) layers are exactly
    consumed (divisible 2n; ragged 2n+1 — the dead-shard wall-ghost
    refresh), so the probe measures the real footprint, not a lower
    bound."""
    for n, ragged in ((1, False), (2, False), (1, True)):
        e = _ca_entry(n, ragged)
        assert halocheck.check_entry(e) == []
        assert max(halocheck.measure(e).values()) == e.declared, e.name
    post = halocheck._post2d_entry()
    assert halocheck.check_entry(post) == []
    assert halocheck.measure(post)[2] == 1  # p: exactly the halo-1 ring


def test_halo_under_declaration_flagged():
    """Mutation 1 (the seeded too-narrow halo): the same kernel declared
    one layer shallower is an under-halo read, with a file:line anchor at
    the kernel source."""
    e = _ca_entry(2)
    e.declared -= 1
    vs = halocheck.check_entry(e)
    assert len(vs) == 1
    v = vs[0]
    assert v.rule == "halo-footprint"
    assert "stencil2d.py" in v.path and v.line > 0
    assert "4 cells beyond" in v.message and "declared halo is 3" in v.message


def test_halo_overwide_stencil_flagged():
    """Mutation 2 (the seeded too-wide stencil offset): a ±2 read smuggled
    into the n=1 iteration — the regression class where someone widens a
    difference operator without bumping ca_halo. Built on a block with
    spare layers (halo 4) so the wider read has real cells to land on;
    the declaration stays the production ca_halo(1) = 2."""
    import jax.numpy as jnp

    from pampi_tpu.parallel import stencil2d as s2

    jl = il = 6
    room = 4  # block layers available; the CONTRACT stays ca_halo(1) = 2
    masks = s2.ca_masks(jl, il, room, 30, 30, float, joff=8, ioff=8)
    shape = (jl + 2 * room, il + 2 * room)

    def base(p, rhs):
        return s2.ca_rb_iters(p, rhs, 1, masks, 0.45, 1.0, 1.3)[0]

    entry = halocheck.HaloEntry(
        name="mutated.ca_rb_iters", fn=base,
        in_shapes=(shape, shape),
        owned=(slice(room, room + jl), slice(room, room + il)),
        declared=s2.ca_halo(1),
        anchor=("mutated.py", 1))
    assert halocheck.check_entry(entry) == []  # the clean tree passes

    def widened(p, rhs):
        return base(p + 0.001 * jnp.roll(p, 2, axis=0), rhs)

    entry.fn = widened
    vs = halocheck.check_entry(entry)
    assert len(vs) == 1
    assert "4 cells beyond the owned region" in vs[0].message
    assert "declared halo is 2" in vs[0].message


def test_halo_fused_pre_within_budget():
    """The fused PRE chain stays within FUSE_CHAIN on every shard
    position (the deep-halo PRE contract)."""
    for shard in ("interior", "corner_lo", "wall_hi"):
        e = halocheck._pre2d_entry(shard)
        assert halocheck.check_entry(e) == [], shard


# ---------------------------------------------------------------------------
# jaxprcheck
# ---------------------------------------------------------------------------

def _subset():
    keep = {"ns2d_jnp", "ns2d_fused_fft", "ns2d_fused_fold"}
    return [c for c in jaxprcheck.standard_configs() if c.name in keep]


@pytest.fixture(scope="module")
def subset_baseline():
    """One traced subset baseline shared by the drift tests (each config
    build is a solver construction — don't pay it per test)."""
    vs, fresh = jaxprcheck.run(baseline=None, configs=_subset(),
                               update=True)
    assert vs == []
    return fresh


def test_contracts_roundtrip_stable(subset_baseline):
    """update -> check clean -> update again byte-stable (the --update
    round-trip contract: regenerating without a code change is a no-op
    diff)."""
    vs, _ = jaxprcheck.run(baseline=subset_baseline, configs=_subset())
    assert vs == [], [str(v) for v in vs]
    _, again = jaxprcheck.run(baseline=subset_baseline, configs=_subset(),
                              update=True)
    assert json.dumps(again, sort_keys=True) == json.dumps(
        subset_baseline, sort_keys=True)


def test_seeded_launch_drift_flagged(subset_baseline):
    """Mutation: a baseline pinning a different launch count (as if a
    layout pass crept back between the fused kernels) fails with the
    dispatch decision in the diagnostic."""
    tampered = json.loads(json.dumps(subset_baseline))
    tampered["configs"]["ns2d_fused_fft"]["pallas_calls"] = 4
    cfg = [c for c in _subset() if c.name == "ns2d_fused_fft"]
    vs, _ = jaxprcheck.run(baseline=tampered, configs=cfg)
    launch = [v for v in vs if v.rule == "launch-count"]
    assert len(launch) == 1
    assert "4 -> 2" in launch[0].message
    assert launch[0].path.endswith("models/ns2d.py")


def test_seeded_hash_drift_flagged(subset_baseline):
    """Mutation: hash drift (an eqn-level change to the flag-off program)
    fails with a primitive-count diff of the offending eqns."""
    tampered = json.loads(json.dumps(subset_baseline))
    entry = tampered["configs"]["ns2d_jnp"]
    entry["hash"] = "0" * 64
    entry["prims"] = dict(entry["prims"], pallas_call=7, while_loop_x=1)
    cfg = [c for c in _subset() if c.name == "ns2d_jnp"]
    vs, _ = jaxprcheck.run(baseline=tampered, configs=cfg)
    drift = [v for v in vs if v.rule == "trace-drift"]
    assert len(drift) == 1
    msg = drift[0].message
    assert "pallas_call: 7 -> 0" in msg and "while_loop_x: 1 -> 0" in msg
    assert "--update" in msg


def test_env_mismatch_reported_not_compared(subset_baseline):
    """A baseline from another toolchain reports environment drift once
    and skips hash comparison instead of failing every config."""
    foreign = json.loads(json.dumps(subset_baseline))
    foreign["env"] = dict(foreign["env"], jax="9.9.9")
    for e in foreign["configs"].values():
        e["hash"] = "f" * 64   # would fail if compared
        e["pallas_calls"] = 9  # likewise toolchain-dependent: not compared
    vs, _ = jaxprcheck.run(baseline=foreign, configs=_subset())
    assert [v.rule for v in vs] == ["trace-drift"]
    assert "environment" in vs[0].message


def test_callback_and_dtype_detectors():
    """The primitive scanners behind the host-callback and dtype checks."""
    import jax
    import jax.numpy as jnp

    def noisy(x):
        jax.debug.print("x={}", x)
        return x * 2.0

    jx = jax.make_jaxpr(noisy)(1.0)
    assert jaxprcheck.host_callbacks(jx.jaxpr) == ["debug_callback"]

    def promoting(x):
        return x.astype(jnp.float64) + 1.0, x * jnp.float32(2)

    jx = jax.make_jaxpr(promoting)(jnp.zeros((3,), jnp.float32))
    fts = jaxprcheck.float_dtypes(jx.jaxpr)
    assert {"float32", "float64"} <= fts


def test_telemetry_arity_contract(tmp_path, monkeypatch):
    """With PAMPI_TELEMETRY armed the traced chunk and initial_state()
    agree at the metrics arity (6/6) and the signature reflects it — the
    contract every measurement tool leans on."""
    from pampi_tpu.models.ns2d import NS2DSolver
    from pampi_tpu.utils import telemetry as tm
    from pampi_tpu.utils.params import Parameter

    monkeypatch.setenv("PAMPI_TELEMETRY", str(tmp_path / "t.jsonl"))
    tm.reset()
    s = NS2DSolver(Parameter(name="dcavity", imax=16, jmax=16, re=10.0,
                             te=0.02, tau=0.5, itermax=10, eps=1e-4))
    sig = jaxprcheck.chunk_signature(s)
    assert sig["state_arity"] == sig["invars"] == sig["outvars"] == 6
    tm.reset()


def test_committed_baseline_current():
    """The committed CONTRACTS.json was generated in THIS harness
    environment and covers exactly the current config matrix — a stale
    baseline (config added/renamed without --update) fails here, not on
    an operator's machine."""
    path = os.path.join(REPO, "CONTRACTS.json")
    with open(path) as fh:
        baseline = json.load(fh)
    assert baseline["env"] == jaxprcheck.environment()
    assert set(baseline["configs"]) == {
        c.name for c in jaxprcheck.standard_configs()}
    # and it passes the shared artifact lint (the one import spelling the
    # other suites use — don't load the module under a second name)
    from tools import check_artifact as ca

    assert ca.lint_contracts(baseline) == []
    assert ca.lint_contracts({"version": 1}) != []


def test_lint_driver_ast_pass():
    """tools/lint.py --only ast runs standalone (no jax import needed for
    the rule pass) and exits clean on the tree — and on an explicit file
    path (the per-file pre-commit invocation)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint.py"),
         "--only", "ast"],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "[ast] ok" in proc.stdout
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint.py"),
         "--only", "ast", "pampi_tpu/utils/flags.py"],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "[ast] ok" in proc.stdout
