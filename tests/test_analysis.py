"""Tracecheck (pampi_tpu/analysis/ + tools/lint.py) — ISSUE 5/6 acceptance:

- AST LINT: the tree is clean; every rule fires on a seeded violation
  with a file:line diagnostic; `# lint: allow(<rule>)` escapes it.
- HALO FOOTPRINTS: the production registry passes and the CA entries are
  TIGHT (measured == declared, so the probe is sharp, not vacuous); the
  two mutation classes — a seeded under-halo declaration and an
  over-wide stencil — are both flagged; the FUSE_CHAIN slack is pinned.
- JAXPR CONTRACTS: a config subset round-trips through the baseline
  (update -> check clean -> update again byte-stable); seeded
  launch-count drift and hash drift are flagged with primitive-count
  diffs; the committed CONTRACTS.json matches the harness environment
  and the current config matrix.
- COMM CONTRACTS (ISSUE 6): the collective census round-trips
  byte-stable through the comm baseline; a smuggled extra exchange, a
  byte-volume drift, and a resharding collective are each flagged with
  per-primitive diffs; the telemetry halo record cross-check fires on a
  mis-priced record and on a dropped deep-exchange message.
- PALLAS RESOURCES (ISSUE 6): the traced matrix + large-grid kernel
  builds are clean; an over-budget VMEM block, an OOB index map, a
  mistiled partitioned block, and both aliasing hazards are each
  flagged with the kernel's file:line.

Compile cost: everything here TRACES (make_jaxpr) or linearizes tiny
blocks — no jit execution of solver chunks.
"""

import json
import os
import subprocess
import sys
import types

import pytest

from pampi_tpu.analysis import (astlint, commcheck, halocheck, jaxprcheck,
                                palcheck)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# astlint
# ---------------------------------------------------------------------------

def test_astlint_tree_clean():
    """The repo itself passes its own lint (the make-lint gate)."""
    violations, errors = astlint.lint_tree(REPO)
    assert errors == []
    assert violations == [], "\n".join(str(v) for v in violations)


def _lint_src(tmp_path, src, name="pampi_tpu/models/seeded.py", rules=None):
    path = tmp_path / name
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(src)
    vs, err = astlint.lint_file(str(path), rules=rules,
                                root=str(tmp_path))
    assert err is None
    return vs


def test_rogue_env_read_flagged(tmp_path):
    """The satellite bug class (PAMPI_CSV in dmvm, PAMPI_PROFILE cached at
    import): any os.environ read outside utils/flags.py is flagged at its
    line; the allow escape and the accessor home are exempt."""
    src = ("import os\n"
           "MODE = os.environ.get('PAMPI_X', '0')\n"
           "PATH = os.environ['PAMPI_Y']\n"
           "OK = os.environ.get('PAMPI_Z')  # lint: allow(env-read) — t\n")
    vs = _lint_src(tmp_path, src)
    assert [(v.line, v.rule) for v in vs] == [
        (2, "env-read"), (3, "env-read")]
    assert "flags.env()" in vs[0].message
    # the accessor layer itself is exempt by location
    vs = _lint_src(tmp_path, src, name="pampi_tpu/utils/flags.py")
    assert vs == []


def test_raw_shard_map_flagged(tmp_path):
    """The two-past-PRs rule: shard_map only through compat_shard_map —
    in EVERY spelling (qualified call, bare call after `from jax import
    shard_map`, aliased module import). Applies to harness trees too
    (tools/ and tests/ regressed before)."""
    src = ("import jax\n"
           "from jax.experimental.shard_map import shard_map\n"
           "f = jax.shard_map(lambda x: x, None, None, None)\n")
    vs = _lint_src(tmp_path, src, name="tools/seeded_tool.py")
    assert [v.line for v in vs] == [2, 3]
    assert all(v.rule == "raw-shard-map" for v in vs)
    assert "compat_shard_map" in vs[0].message
    vs = _lint_src(tmp_path, src, name="pampi_tpu/parallel/comm.py")
    assert vs == []  # the shim's home
    # ...but NOT a file that merely ends with the shim's name (path-
    # component boundary, never a bare suffix)
    vs = _lint_src(tmp_path, src, name="pampi_tpu/parallel/webcomm.py")
    assert len(vs) == 2

    # the newer-jax spelling and the aliased import both flag too
    src2 = ("from jax import shard_map\n"
            "import jax.experimental.shard_map as sm\n"
            "a = shard_map(lambda x: x, None, None, None)\n"
            "b = sm.shard_map(lambda x: x, None, None, None)\n")
    vs = _lint_src(tmp_path, src2, name="tools/seeded_tool2.py")
    assert [v.line for v in vs] == [1, 2, 3, 4]
    assert all(v.rule == "raw-shard-map" for v in vs)


def test_traced_context_rules(tmp_path):
    """np.* and nondeterminism inside a traced closure (a def nested in a
    _build_*/make_* builder); builder BODIES are trace-time host code
    where numpy is legitimate."""
    src = ("import numpy as np\n"
           "import time, random\n"
           "def make_step(n):\n"
           "    c = np.arange(n)  # builder body: constant baking, legal\n"
           "    def step(x):\n"
           "        y = np.asarray(x)\n"
           "        t = time.time()\n"
           "        r = random.random()\n"
           "        return y + c[0] + t + r\n"
           "    return step\n")
    vs = _lint_src(tmp_path, src)
    assert [(v.line, v.rule) for v in vs] == [
        (6, "np-in-traced"), (7, "traced-nondet"), (8, "traced-nondet")]


def test_broad_except_and_print(tmp_path):
    src = ("def f():\n"
           "    try:\n"
           "        pass\n"
           "    except Exception:\n"
           "        print('boom')\n"
           "    except Exception:  # lint: allow(broad-except) — probe\n"
           "        pass\n")
    vs = _lint_src(tmp_path, src)
    assert [(v.line, v.rule) for v in vs] == [
        (4, "broad-except"), (5, "print-call")]
    assert str(vs[0]).startswith("pampi_tpu/models/seeded.py:4: ")


def test_env_inventory_complete():
    """The static env-var inventory: every PAMPI_* knob the library reads
    is registered through flags.env at a named site — the rogue reads the
    satellites fixed (PAMPI_CSV, PAMPI_PROFILE) now appear here. The
    RUNTIME registry (flags.registered(), populated as accessors run)
    must agree with the static scan: a var the process actually read that
    the scan can't see would mean a non-literal name snuck past the
    lint."""
    inv = astlint.env_inventory(REPO)
    for var, home in [
        ("PAMPI_TELEMETRY", "utils/telemetry.py"),
        ("PAMPI_FAULTS", "utils/faultinject.py"),
        ("PAMPI_PROFILE", "utils/profiling.py"),
        ("PAMPI_CSV", "models/dmvm.py"),
        ("PAMPI_XLA_CACHE", "utils/xlacache.py"),
        ("PAMPI_NATIVE", "utils/native.py"),
        ("PAMPI_COORDINATOR", "parallel/multihost.py"),
    ]:
        assert var in inv, var
        assert any(home in site for site in inv[var]), (var, inv[var])

    from pampi_tpu.utils import faultinject as fi
    from pampi_tpu.utils import flags, profiling, telemetry

    telemetry.enabled()
    fi.enabled()
    profiling.enabled()
    reg = flags.registered()
    assert {"PAMPI_TELEMETRY", "PAMPI_FAULTS", "PAMPI_PROFILE"} <= set(reg)
    assert set(reg) <= set(inv) | {"PAMPI_DEBUG", "PAMPI_VERBOSE",
                                   "PAMPI_CHECK", "PAMPI_DTYPE"}
    # accessor docs ride the registry (the runtime-readable knob table)
    assert reg["PAMPI_TELEMETRY"]


# ---------------------------------------------------------------------------
# halocheck
# ---------------------------------------------------------------------------

def _ca_entry(n=1, ragged=False):
    return halocheck._ca2d_entry(n, ragged=ragged)


def test_halo_registry_subset_clean_and_tight():
    """The CA contracts hold AND are tight: ca_halo(n) layers are exactly
    consumed (divisible 2n; ragged 2n+1 — the dead-shard wall-ghost
    refresh), so the probe measures the real footprint, not a lower
    bound."""
    for n, ragged in ((1, False), (2, False), (1, True)):
        e = _ca_entry(n, ragged)
        assert halocheck.check_entry(e) == []
        assert max(halocheck.measure(e).values()) == e.declared, e.name
    post = halocheck._post2d_entry()
    assert halocheck.check_entry(post) == []
    assert halocheck.measure(post)[2] == 1  # p: exactly the halo-1 ring


def test_halo_under_declaration_flagged():
    """Mutation 1 (the seeded too-narrow halo): the same kernel declared
    one layer shallower is an under-halo read, with a file:line anchor at
    the kernel source."""
    e = _ca_entry(2)
    e.declared -= 1
    vs = halocheck.check_entry(e)
    assert len(vs) == 1
    v = vs[0]
    assert v.rule == "halo-footprint"
    assert "stencil2d.py" in v.path and v.line > 0
    assert "4 cells beyond" in v.message and "declared halo is 3" in v.message


def test_halo_overwide_stencil_flagged():
    """Mutation 2 (the seeded too-wide stencil offset): a ±2 read smuggled
    into the n=1 iteration — the regression class where someone widens a
    difference operator without bumping ca_halo. Built on a block with
    spare layers (halo 4) so the wider read has real cells to land on;
    the declaration stays the production ca_halo(1) = 2."""
    import jax.numpy as jnp

    from pampi_tpu.parallel import stencil2d as s2

    jl = il = 6
    room = 4  # block layers available; the CONTRACT stays ca_halo(1) = 2
    masks = s2.ca_masks(jl, il, room, 30, 30, float, joff=8, ioff=8)
    shape = (jl + 2 * room, il + 2 * room)

    def base(p, rhs):
        return s2.ca_rb_iters(p, rhs, 1, masks, 0.45, 1.0, 1.3)[0]

    entry = halocheck.HaloEntry(
        name="mutated.ca_rb_iters", fn=base,
        in_shapes=(shape, shape),
        owned=(slice(room, room + jl), slice(room, room + il)),
        declared=s2.ca_halo(1),
        anchor=("mutated.py", 1))
    assert halocheck.check_entry(entry) == []  # the clean tree passes

    def widened(p, rhs):
        return base(p + 0.001 * jnp.roll(p, 2, axis=0), rhs)

    entry.fn = widened
    vs = halocheck.check_entry(entry)
    assert len(vs) == 1
    assert "4 cells beyond the owned region" in vs[0].message
    assert "declared halo is 2" in vs[0].message


def test_halo_fused_pre_within_budget():
    """The fused PRE chain stays within FUSE_CHAIN on every shard
    position (the deep-halo PRE contract)."""
    for shard in ("interior", "corner_lo", "wall_hi"):
        e = halocheck._pre2d_entry(shard)
        assert halocheck.check_entry(e) == [], shard


def test_fuse_chain_slack_pinned():
    """The ROADMAP carried-forward shrink, landed and pinned: the
    MEASURED PRE-chain footprint (2) now IS the declaration
    (`FUSE_FOOTPRINT`), and the deep exchange ships exactly
    footprint + 1 (`FUSE_DEEP_HALO = 3`, down from the conservative
    FUSE_CHAIN + 1 = 4) — zero slack. If the chain ever widens, the
    re-derivation here AND halocheck's PRE entries (declared =
    FUSE_FOOTPRINT) fail before any distributed run corrupts."""
    from pampi_tpu.ops import ns2d_fused as nf

    measured = halocheck.pre_chain_footprint()
    assert measured == nf.FUSE_FOOTPRINT == 2, (
        "PRE-chain footprint moved — re-audit FUSE_DEEP_HALO/OVERLAP_RIM "
        "and re-run dist parity + make lint-update")
    assert nf.FUSE_CHAIN == 3  # the stage-count budget, documentation
    assert nf.FUSE_DEEP_HALO == nf.FUSE_FOOTPRINT + 1 == 3
    assert nf.OVERLAP_RIM == nf.FUSE_FOOTPRINT + 1 == 3


# ---------------------------------------------------------------------------
# jaxprcheck
# ---------------------------------------------------------------------------

def _subset():
    keep = {"ns2d_jnp", "ns2d_fused_fft", "ns2d_fused_fold"}
    return [c for c in jaxprcheck.standard_configs() if c.name in keep]


@pytest.fixture(scope="module")
def subset_baseline():
    """One traced subset baseline shared by the drift tests (each config
    build is a solver construction — don't pay it per test)."""
    vs, fresh = jaxprcheck.run(baseline=None, configs=_subset(),
                               update=True)
    assert vs == []
    return fresh


def test_contracts_roundtrip_stable(subset_baseline):
    """update -> check clean -> update again byte-stable (the --update
    round-trip contract: regenerating without a code change is a no-op
    diff)."""
    vs, _ = jaxprcheck.run(baseline=subset_baseline, configs=_subset())
    assert vs == [], [str(v) for v in vs]
    _, again = jaxprcheck.run(baseline=subset_baseline, configs=_subset(),
                              update=True)
    assert json.dumps(again, sort_keys=True) == json.dumps(
        subset_baseline, sort_keys=True)


def test_seeded_launch_drift_flagged(subset_baseline):
    """Mutation: a baseline pinning a different launch count (as if a
    layout pass crept back between the fused kernels) fails with the
    dispatch decision in the diagnostic."""
    tampered = json.loads(json.dumps(subset_baseline))
    tampered["configs"]["ns2d_fused_fft"]["pallas_calls"] = 4
    cfg = [c for c in _subset() if c.name == "ns2d_fused_fft"]
    vs, _ = jaxprcheck.run(baseline=tampered, configs=cfg)
    launch = [v for v in vs if v.rule == "launch-count"]
    assert len(launch) == 1
    assert "4 -> 2" in launch[0].message
    assert launch[0].path.endswith("models/ns2d.py")


def test_seeded_hash_drift_flagged(subset_baseline):
    """Mutation: hash drift (an eqn-level change to the flag-off program)
    fails with a primitive-count diff of the offending eqns."""
    tampered = json.loads(json.dumps(subset_baseline))
    entry = tampered["configs"]["ns2d_jnp"]
    entry["hash"] = "0" * 64
    entry["prims"] = dict(entry["prims"], pallas_call=7, while_loop_x=1)
    cfg = [c for c in _subset() if c.name == "ns2d_jnp"]
    vs, _ = jaxprcheck.run(baseline=tampered, configs=cfg)
    drift = [v for v in vs if v.rule == "trace-drift"]
    assert len(drift) == 1
    msg = drift[0].message
    assert "pallas_call: 7 -> 0" in msg and "while_loop_x: 1 -> 0" in msg
    assert "--update" in msg


def test_env_mismatch_reported_not_compared(subset_baseline):
    """A baseline from another toolchain reports environment drift once
    and skips hash comparison instead of failing every config."""
    foreign = json.loads(json.dumps(subset_baseline))
    foreign["env"] = dict(foreign["env"], jax="9.9.9")
    for e in foreign["configs"].values():
        e["hash"] = "f" * 64   # would fail if compared
        e["pallas_calls"] = 9  # likewise toolchain-dependent: not compared
    vs, _ = jaxprcheck.run(baseline=foreign, configs=_subset())
    assert [v.rule for v in vs] == ["trace-drift"]
    assert "environment" in vs[0].message


def test_callback_and_dtype_detectors():
    """The primitive scanners behind the host-callback and dtype checks."""
    import jax
    import jax.numpy as jnp

    def noisy(x):
        jax.debug.print("x={}", x)
        return x * 2.0

    jx = jax.make_jaxpr(noisy)(1.0)
    assert jaxprcheck.host_callbacks(jx.jaxpr) == ["debug_callback"]

    def promoting(x):
        return x.astype(jnp.float64) + 1.0, x * jnp.float32(2)

    jx = jax.make_jaxpr(promoting)(jnp.zeros((3,), jnp.float32))
    fts = jaxprcheck.float_dtypes(jx.jaxpr)
    assert {"float32", "float64"} <= fts


def test_telemetry_arity_contract(tmp_path, monkeypatch):
    """With PAMPI_TELEMETRY armed the traced chunk and initial_state()
    agree at the metrics arity (6/6) and the signature reflects it — the
    contract every measurement tool leans on."""
    from pampi_tpu.models.ns2d import NS2DSolver
    from pampi_tpu.utils import telemetry as tm
    from pampi_tpu.utils.params import Parameter

    monkeypatch.setenv("PAMPI_TELEMETRY", str(tmp_path / "t.jsonl"))
    tm.reset()
    s = NS2DSolver(Parameter(name="dcavity", imax=16, jmax=16, re=10.0,
                             te=0.02, tau=0.5, itermax=10, eps=1e-4))
    sig = jaxprcheck.chunk_signature(s)
    assert sig["state_arity"] == sig["invars"] == sig["outvars"] == 6
    tm.reset()


def test_committed_baseline_current():
    """The committed CONTRACTS.json was generated in THIS harness
    environment and covers exactly the current config matrix — a stale
    baseline (config added/renamed without --update) fails here, not on
    an operator's machine."""
    path = os.path.join(REPO, "CONTRACTS.json")
    with open(path) as fh:
        baseline = json.load(fh)
    assert baseline["env"] == jaxprcheck.environment()
    assert set(baseline["configs"]) == {
        c.name for c in jaxprcheck.standard_configs()}
    # the comm census covers the SAME matrix (ISSUE 6: the comm baseline
    # is committed, not optional)
    assert set(baseline["comm"]) == set(baseline["configs"])
    for entry in baseline["comm"].values():
        assert set(entry) >= {"collectives", "ppermute_bytes", "strips",
                              "halo"}
    # so does the precision census (ISSUE 20: the cast contract is
    # committed alongside)
    assert set(baseline["precision"]) == set(baseline["configs"])
    for entry in baseline["precision"].values():
        assert set(entry) >= {"dtype", "float_dtypes", "casts",
                              "narrowing", "reductions"}
    # and it passes the shared artifact lint (the one import spelling the
    # other suites use — don't load the module under a second name)
    from tools import check_artifact as ca

    assert ca.lint_contracts(baseline) == []
    assert ca.lint_contracts({"version": 1}) != []
    # a truncated comm section is a lint error, not a silent no-op
    broken = json.loads(json.dumps(baseline))
    broken["comm"].popitem()
    assert any(".comm" in e for e in ca.lint_contracts(broken))
    broken2 = json.loads(json.dumps(baseline))
    next(iter(broken2["comm"].values())).pop("ppermute_bytes")
    assert any("ppermute_bytes" in e for e in ca.lint_contracts(broken2))


def test_lint_driver_ast_pass():
    """tools/lint.py --only ast runs standalone (no jax import needed for
    the rule pass) and exits clean on the tree — and on an explicit file
    path (the per-file pre-commit invocation)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint.py"),
         "--only", "ast"],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "[ast] ok" in proc.stdout
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint.py"),
         "--only", "ast", "pampi_tpu/utils/flags.py"],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "[ast] ok" in proc.stdout


def test_lint_driver_only_multiselect():
    """--only takes a comma list (the ISSUE 6 satellite: the overlap
    refactor's inner loop runs `--only comm` alone; `ast,artifacts` here
    keeps the test jax-trace-free), runs passes in CANONICAL order
    regardless of the flag's spelling (artifacts must follow a pending
    --update flush), and rejects unknown pass names."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint.py"),
         "--only", "artifacts,ast"],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "[ast] ok" in proc.stdout
    assert "[artifacts] ok" in proc.stdout
    assert proc.stdout.index("[ast]") < proc.stdout.index("[artifacts]")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint.py"),
         "--only", "ast,nonsense"],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 2
    assert "nonsense" in proc.stderr


def test_lint_partial_update_no_mixed_env_baseline(tmp_path, monkeypatch,
                                                  comm_traced):
    """A partial `--update` (comm section only) under a CHANGED trace
    environment must not pair the new `env` key with configs hashes
    traced under the old one — the driver regenerates the missing
    section from the shared matrix instead of preserving it."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import lint as lint_mod
    finally:
        sys.path.pop(0)

    _, configs_fresh = jaxprcheck.run(traced=comm_traced, update=True)
    _, comm_fresh = commcheck.run(traced=comm_traced, update=True)
    stale = dict(configs_fresh, comm=comm_fresh)
    stale["env"] = dict(stale["env"], jax="0.0.0")  # another toolchain
    path = tmp_path / "CONTRACTS.json"
    path.write_text(json.dumps(stale))

    ctx = lint_mod.TraceContext(str(path), update=True)
    ctx._traced = comm_traced  # the subset matrix, already built
    vs = ctx.run_comm()
    assert vs == []
    assert ctx.fresh_configs is None  # only the comm pass ran
    ctx.write()
    merged = json.loads(path.read_text())
    assert merged["env"] == jaxprcheck.environment()
    # configs were REGENERATED under the new env, not carried over
    assert merged["configs"] == configs_fresh["configs"]
    # and a full check against the result is clean
    vs, _ = jaxprcheck.run(baseline=merged, traced=comm_traced)
    assert vs == [], [str(v) for v in vs]


# ---------------------------------------------------------------------------
# commcheck
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def comm_traced():
    """One traced subset shared by the comm/pallas suites (each config is
    a solver build — don't pay it per test): a single-device chunk, a
    jnp dist chunk, and a fused dist chunk (deep exchange + both fused
    kernels)."""
    keep = {"ns2d_jnp", "ns2d_dist_jnp", "ns2d_dist_fused"}
    cfgs = [c for c in jaxprcheck.standard_configs() if c.name in keep]
    return jaxprcheck.trace_matrix(cfgs)


def _fused(traced):
    return next(t for t in traced if t.cfg.name == "ns2d_dist_fused")


def test_comm_roundtrip_stable(comm_traced):
    """update -> check clean -> update again byte-stable (the comm
    section --update contract, the ISSUE 6 satellite)."""
    vs, fresh = commcheck.run(traced=comm_traced, update=True)
    assert vs == [], [str(v) for v in vs]
    vs, _ = commcheck.run(baseline=fresh, traced=comm_traced)
    assert vs == [], [str(v) for v in vs]
    _, again = commcheck.run(traced=comm_traced, update=True)
    assert json.dumps(again, sort_keys=True) == json.dumps(
        fresh, sort_keys=True)


def test_comm_extra_collective_flagged(comm_traced):
    """Mutation 1: a baseline recording fewer exchanges (as if the
    current tree smuggled extras in) fails with a per-primitive diff —
    and a byte drift with a per-strip diff."""
    _, fresh = commcheck.run(traced=comm_traced, update=True)
    tampered = json.loads(json.dumps(fresh))
    entry = tampered["ns2d_dist_fused"]
    entry["collectives"]["ppermute"] -= 2
    vs, _ = commcheck.run(baseline=tampered, traced=comm_traced)
    count = [v for v in vs if v.rule == commcheck.RULE_COUNT]
    assert len(count) == 1
    assert "ppermute: 18 -> 20 (+2)" in count[0].message
    assert count[0].path.endswith("models/ns2d_dist.py")

    tampered = json.loads(json.dumps(fresh))
    entry = tampered["ns2d_dist_fused"]
    entry["ppermute_bytes"] -= 1024
    entry["strips"]["3x14:float64"] -= 1
    vs, _ = commcheck.run(baseline=tampered, traced=comm_traced)
    bytes_vs = [v for v in vs if v.rule == commcheck.RULE_BYTES]
    assert len(bytes_vs) == 1
    assert "3x14:float64: 3 -> 4 (+1)" in bytes_vs[0].message


def test_comm_smuggled_exchange_census():
    """Mutation 2, on a real program pair: the same shard_map stencil
    body with a DUPLICATED halo_exchange censuses to exactly double the
    ppermute count/bytes, and checking the doubled program against the
    clean baseline fails both rules."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from pampi_tpu.parallel.comm import CartComm, halo_exchange

    comm = CartComm(ndims=2, dims=(2, 2))
    spec = P("j", "i")

    def once(x):
        return halo_exchange(x, comm)

    def twice(x):
        return halo_exchange(halo_exchange(x, comm), comm)

    x = jnp.zeros((16, 16))
    jx1 = jax.make_jaxpr(comm.shard_map(once, (spec,), spec))(x)
    jx2 = jax.make_jaxpr(comm.shard_map(twice, (spec,), spec))(x)
    c1, c2 = commcheck.census(jx1.jaxpr), commcheck.census(jx2.jaxpr)
    assert c1["collectives"]["ppermute"] == 4  # 2 axes x 2 directions
    assert c2["collectives"]["ppermute"] == 8
    assert c2["ppermute_bytes"] == 2 * c1["ppermute_bytes"] > 0

    clean = dict(c1, halo=None)
    mutant = types.SimpleNamespace(
        cfg=types.SimpleNamespace(name="mutated", family="ns2d_dist",
                                  dims=(2, 2)),
        solver=object(), jaxpr=jx2)
    vs, _ = commcheck.check_config(mutant, clean, env_matches=True)
    rules = {v.rule for v in vs}
    assert commcheck.RULE_COUNT in rules and commcheck.RULE_BYTES in rules
    assert any("ppermute: 4 -> 8 (+4)" in v.message for v in vs)


def test_comm_reshard_flagged():
    """A resharding collective (what sharding propagation inserts behind
    an explicit schedule) is banned outright — no baseline needed."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from pampi_tpu.parallel.comm import CartComm

    comm = CartComm(ndims=2, dims=(2, 2))

    def gathers(x):
        return lax.all_gather(x, "j")

    jx = jax.make_jaxpr(
        comm.shard_map(gathers, (P("j", "i"),), P(None, "j", "i"))
    )(jnp.zeros((16, 16)))
    bad = types.SimpleNamespace(
        cfg=types.SimpleNamespace(name="reshard", family="ns2d_dist",
                                  dims=(2, 2)),
        solver=object(), jaxpr=jx)
    vs, _ = commcheck.check_config(bad, None, env_matches=True)
    assert [v.rule for v in vs] == [commcheck.RULE_RESHARD]
    assert "all_gather" in vs[0].message


def test_comm_single_device_collective_flagged(comm_traced):
    """A collective in a single-device chunk means a mesh axis leaked —
    the census of a dist program checked under a dims=None config
    fails."""
    dist = _fused(comm_traced)
    leaked = types.SimpleNamespace(
        cfg=types.SimpleNamespace(name="leaked", family="ns2d",
                                  dims=None),
        solver=object(), jaxpr=dist.jaxpr)
    vs, _ = commcheck.check_config(leaked, None, env_matches=True)
    assert any(v.rule == commcheck.RULE_COUNT
               and "single-device" in v.message for v in vs)


def test_comm_telemetry_crosscheck(comm_traced):
    """The halo-record cross-check: the solver's own static accounting
    (a) prices exactly what comm.halo_exchange_bytes says, (b) declares
    deep-exchange messages the trace really contains — and a mis-priced
    record or a dropped/duplicated deep strip is flagged."""
    t = _fused(comm_traced)
    entry = commcheck.config_entry(t)
    rec = t.solver._halo_record()
    assert commcheck.crosscheck_record(rec, entry) == []

    # (a) a record hand-computing bytes (off by one strip) is caught
    bad = dict(rec, deep_exchange_bytes=rec["deep_exchange_bytes"] - 64)
    errs = commcheck.crosscheck_record(bad, entry)
    assert any("deep_exchange_bytes" in e for e in errs)

    # (b) a trace missing one declared deep message is caught (exact
    # count for the deep class: a duplicated exchange can't hide either)
    thin = json.loads(json.dumps(entry))
    thin["strips"]["3x14:float64"] -= 1
    errs = commcheck.crosscheck_record(rec, thin)
    assert any("deep-exchange strip" in e for e in errs)


def test_comm_halo_record_is_shared_accounting(comm_traced):
    """The ISSUE 6 dedupe satellite: the PR 3 telemetry `halo` record and
    commcheck both price through parallel/comm.halo_exchange_bytes — the
    solver hook returns the SAME dict the telemetry plane emits, and the
    utils/telemetry spelling is an alias of the comm helper."""
    import numpy as np

    from pampi_tpu.parallel.comm import (halo_exchange_bytes,
                                         halo_strip_shapes)
    from pampi_tpu.utils import telemetry as tm

    rec = _fused(comm_traced).solver._halo_record()
    isz = np.dtype(rec["dtype"]).itemsize
    shard = tuple(rec["shard"])
    assert rec["exchange_bytes_depth1"] == halo_exchange_bytes(
        shard, 1, isz)
    assert rec["deep_exchange_bytes"] == halo_exchange_bytes(
        shard, rec["deep_halo"], isz)
    # the alias and the helper agree (and the strip geometry sums to it)
    assert tm.halo_exchange_bytes((8, 16), 1, 4) == halo_exchange_bytes(
        (8, 16), 1, 4)
    strips = halo_strip_shapes(shard, rec["deep_halo"])
    total = sum(2 * int(np.prod(s)) for s in strips) * isz
    assert total == rec["deep_exchange_bytes"]


# ---------------------------------------------------------------------------
# palcheck
# ---------------------------------------------------------------------------

def _toy_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0


def _toy_call(grid, in_spec, out_spec, shape=(256, 256), **kw):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    if grid is not None:
        kw["grid"] = grid
    f = pl.pallas_call(
        _toy_kernel,
        out_shape=jax.ShapeDtypeStruct(shape, jnp.float32),
        in_specs=[in_spec], out_specs=out_spec,
        interpret=True, **kw)
    return jax.make_jaxpr(f)(jnp.ones(shape, jnp.float32))


def test_palcheck_matrix_and_extras_clean(comm_traced):
    """The production kernels pass: the fused dist chunk's launches (the
    matrix population) and the standalone large-grid builds (where the
    grid actually partitions: pipelined tblock, aliased rb kernel)."""
    assert palcheck.run(traced=comm_traced, extras=False) == []
    extras = palcheck.extra_entries()
    # rb + tblock + quarters (2-D) + tblock 3-D — all four solve-kernel
    # layouts, at grids large enough to partition
    assert len(extras) == 4
    for name, jx in extras:
        vs = palcheck.check_jaxpr(jx.jaxpr, context=f"{name}/")
        assert vs == [], [str(v) for v in vs]
        # the decoded launches carry real kernel anchors
        for launch in palcheck.launches(jx.jaxpr):
            assert "/ops/sor" in launch.path and launch.path.endswith(".py")
            assert launch.line > 0


def test_palcheck_oversized_block_flagged():
    """Mutation: a block whose window exceeds the VMEM budget — the
    failure class `tblock_feasible` guards at build time, now also caught
    on any kernel statically."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    jx = _toy_call(None, pl.BlockSpec((2048, 2048), lambda: (0, 0)),
                   pl.BlockSpec((2048, 2048), lambda: (0, 0)),
                   shape=(2048, 2048))
    vs = palcheck.check_jaxpr(jx.jaxpr, budget=1 << 20)
    assert [v.rule for v in vs] == [palcheck.RULE_VMEM]
    assert "exceeds the budget" in vs[0].message
    # within budget: clean
    assert palcheck.check_jaxpr(jx.jaxpr, budget=64 << 20) == []


def test_palcheck_oob_index_map_flagged():
    """Mutation: an index map shifted one block past the array — every
    grid point's window start must land inside the operand."""
    from jax.experimental import pallas as pl

    jx = _toy_call((2,),
                   pl.BlockSpec((128, 256), lambda i: (i + 1, 0)),
                   pl.BlockSpec((128, 256), lambda i: (i, 0)))
    vs = palcheck.check_jaxpr(jx.jaxpr)
    assert [v.rule for v in vs] == [palcheck.RULE_OOB]
    assert "grid point (1,)" in vs[0].message
    assert "starts at element 256" in vs[0].message


def test_palcheck_mistiled_block_flagged():
    """Mutation: a partitioned block off the (8, 128) f32 granularity is
    flagged per offending dim; a FULL-extent unaligned block is exempt
    (Mosaic pads whole-array windows — the repo's own (40, 128)-style
    blocks rely on that)."""
    from jax.experimental import pallas as pl

    jx = _toy_call((4, 4),
                   pl.BlockSpec((60, 60), lambda i, j: (i, j)),
                   pl.BlockSpec((60, 60), lambda i, j: (i, j)),
                   shape=(240, 240))
    vs = palcheck.check_jaxpr(jx.jaxpr)
    tiles = [v for v in vs if v.rule == palcheck.RULE_TILE]
    assert len(tiles) == 4  # 2 operands x 2 misaligned dims
    assert any("granularity 128" in v.message for v in tiles)
    assert any("granularity 8" in v.message for v in tiles)
    # full-extent block, unaligned sublane: exempt
    jx = _toy_call((1,), pl.BlockSpec((30, 128), lambda i: (0, 0)),
                   pl.BlockSpec((30, 128), lambda i: (0, 0)),
                   shape=(30, 128))
    assert palcheck.check_jaxpr(jx.jaxpr) == []


def test_palcheck_alias_hazards_flagged():
    """Mutations: (a) an aliased pair windowed through DIFFERENT index
    maps — the donated buffer is rewritten elsewhere than it is read;
    (b) a donated input also read through a second operand of the same
    call (use-after-donation)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def k2(x_ref, y_ref, o_ref):
        o_ref[...] = x_ref[...] + y_ref[...]

    x = jnp.ones((256, 256), jnp.float32)
    f = pl.pallas_call(
        k2, out_shape=jax.ShapeDtypeStruct((256, 256), jnp.float32),
        grid=(2,),
        in_specs=[pl.BlockSpec((128, 256), lambda i: (i, 0)),
                  pl.BlockSpec((128, 256), lambda i: (1 - i, 0))],
        out_specs=pl.BlockSpec((128, 256), lambda i: (i, 0)),
        input_output_aliases={1: 0}, interpret=True)
    vs = palcheck.check_jaxpr(jax.make_jaxpr(f)(x, x).jaxpr)
    assert [v.rule for v in vs] == [palcheck.RULE_ALIAS]
    assert "index maps differ" in vs[0].message

    f2 = pl.pallas_call(
        k2, out_shape=jax.ShapeDtypeStruct((256, 256), jnp.float32),
        input_output_aliases={0: 0}, interpret=True)
    vs = palcheck.check_jaxpr(jax.make_jaxpr(lambda a: f2(a, a))(x).jaxpr)
    assert [v.rule for v in vs] == [palcheck.RULE_ALIAS]
    assert "use-after-donation" in vs[0].message


def test_palcheck_squeezed_block_dims():
    """A pallas_call windowing with squeezed dims (None in the BlockSpec,
    a Mapped sentinel in the jaxpr param) must CHECK, not crash the lint
    driver: extents count as 1 for VMEM/coverage, and squeezed dims are
    exempt from the tiling rule (iteration, not windowing)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def row_kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...] * 2.0

    f = pl.pallas_call(
        row_kernel,
        out_shape=jax.ShapeDtypeStruct((16, 128), jnp.float32),
        grid=(16,),
        in_specs=[pl.BlockSpec((None, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((None, 128), lambda i: (i, 0)),
        interpret=True)
    jx = jax.make_jaxpr(f)(jnp.ones((16, 128), jnp.float32))
    assert palcheck.check_jaxpr(jx.jaxpr) == []
    (launch,) = palcheck.launches(jx.jaxpr)
    assert palcheck.block_extents(launch.in_mappings[0]) == (1, 128)
    assert palcheck.vmem_estimate(launch) > 0
    # an OOB map through a squeezed dim still flags (start = index * 1)
    f2 = pl.pallas_call(
        row_kernel,
        out_shape=jax.ShapeDtypeStruct((16, 128), jnp.float32),
        grid=(16,),
        in_specs=[pl.BlockSpec((None, 128), lambda i: (i + 1, 0))],
        out_specs=pl.BlockSpec((None, 128), lambda i: (i, 0)),
        interpret=True)
    jx2 = jax.make_jaxpr(f2)(jnp.ones((16, 128), jnp.float32))
    vs = palcheck.check_jaxpr(jx2.jaxpr)
    assert [v.rule for v in vs] == [palcheck.RULE_OOB]


def test_palcheck_vmem_estimate_scratch_and_pipeline():
    """The estimator's two accounting rules on a production kernel: ANY
    operands charge nothing (their windows enter via explicit VMEM
    scratch), and the declared compiler vmem_limit is the default
    budget."""
    name, jx = palcheck.extra_entries()[0]  # rb_iter: ANY + 2 VMEM scratch
    (launch,) = palcheck.launches(jx.jaxpr)
    est = palcheck.vmem_estimate(launch)
    import numpy as np

    want = sum(
        int(np.prod(a.shape)) * np.dtype(a.dtype).itemsize
        for a in launch.scratch_avals if palcheck._mspace(a) == "vmem")
    # + the (1, 1) smem residual block charges nothing; ANY blocks either
    assert est == want > 0
    assert launch.vmem_limit == 100 << 20  # sor_pallas.VMEM_LIMIT_BYTES
    assert launch.aliases == ((0, 0),)
