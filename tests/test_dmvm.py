"""DMVM ring-matvec tests (assignment-3a/3b capability)."""

import jax
import numpy as np
import pytest

from pampi_tpu.models.dmvm import RingDMVM, SequentialDMVM, init_ax


def test_ring_matvec_correct_8_devices():
    # blocked ring over 8 devices must produce y = A·x exactly
    N = 64
    ring = RingDMVM(N, dtype=jax.numpy.float64)
    y, _, _ = ring.run(1)
    a, x = init_ax(N, np.float64)
    expected = np.asarray(a) @ np.asarray(x)
    np.testing.assert_allclose(np.asarray(y), expected, rtol=1e-12)


def test_ring_overlap_and_blocking_agree():
    N = 48
    y1, _, _ = RingDMVM(N, dtype=jax.numpy.float64, overlap=True).run(2)
    y2, _, _ = RingDMVM(N, dtype=jax.numpy.float64, overlap=False).run(2)
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))


def test_ring_iter_accumulates_like_reference():
    # y accumulates across iterations (y is never reset, main.c:70-74)
    N = 32
    y1, _, _ = RingDMVM(N, dtype=jax.numpy.float64).run(1)
    y3, _, _ = RingDMVM(N, dtype=jax.numpy.float64).run(3)
    np.testing.assert_allclose(np.asarray(y3), 3 * np.asarray(y1), rtol=1e-12)


def test_sequential_matches_ring():
    N = 40
    ys, _ = SequentialDMVM(N, dtype=jax.numpy.float64).run(2)
    yr, _, _ = RingDMVM(N, dtype=jax.numpy.float64).run(2)
    np.testing.assert_allclose(np.asarray(ys), np.asarray(yr), rtol=1e-12)


def test_indivisible_ring_rejected():
    with pytest.raises(ValueError):
        RingDMVM(30)  # 30 % 8 != 0


def test_check_flag_prints_sum_and_zeroes_y(monkeypatch, capfd):
    """PAMPI_CHECK ≙ -DCHECK (assignment-3a/src/dmvm.c:26-36): per iteration
    print `Sum: %f` of y to stderr, then reset y."""
    monkeypatch.setenv("PAMPI_CHECK", "1")
    N = 32
    s = SequentialDMVM(N, dtype=jax.numpy.float64)
    y, _ = s.run(2)
    assert float(np.abs(np.asarray(y)).max()) == 0.0
    err = capfd.readouterr().err
    sums = [l for l in err.splitlines() if l.startswith("Sum: ")]
    assert len(sums) == 2  # exactly one per timed iteration (reference count)
    # closed form: sum(A@x) = N*sum(c^2) + (sum r)(sum c)
    c = np.arange(N, dtype=np.float64)
    expect = N * (c**2).sum() + c.sum() ** 2
    assert abs(float(sums[0].split()[1]) - expect) < 1e-6
