"""Boundary-condition oracle tests: every BC kind on every wall/face,
against literal numpy transcriptions of the reference switch ladders
(assignment-5/sequential/src/solver.c:236-337 for 2-D,
assignment-6/src/solver.c:364-577 for 3-D). The solver-level golden tests
only exercise NOSLIP and OUTFLOW (dcavity/canal); these cover SLIP and
PERIODIC too — uniform on all walls, all 4! distinct-kind orderings in 2-D,
and randomized (repeats allowed) mixes in 2-D and 3-D."""

import itertools

import jax.numpy as jnp
import numpy as np
import pytest

from pampi_tpu.ops.ns2d import set_boundary_conditions
from pampi_tpu.ops.ns3d import set_boundary_conditions_3d

NOSLIP, SLIP, OUTFLOW, PERIODIC = 1, 2, 3, 4
KINDS = (NOSLIP, SLIP, OUTFLOW, PERIODIC)


def ref_bcs_2d(u, v, bc_left, bc_right, bc_bottom, bc_top):
    """Transcription of solver.c:236-337; arrays [j, i] (U(i,j) = u[j,i])."""
    u, v = u.copy(), v.copy()
    # left: U(0,j), V(0,j) for j in 1..jmax
    if bc_left == NOSLIP:
        u[1:-1, 0] = 0.0
        v[1:-1, 0] = -v[1:-1, 1]
    elif bc_left == SLIP:
        u[1:-1, 0] = 0.0
        v[1:-1, 0] = v[1:-1, 1]
    elif bc_left == OUTFLOW:
        u[1:-1, 0] = u[1:-1, 1]
        v[1:-1, 0] = v[1:-1, 1]
    # right: U(imax,j), V(imax+1,j)
    if bc_right == NOSLIP:
        u[1:-1, -2] = 0.0
        v[1:-1, -1] = -v[1:-1, -2]
    elif bc_right == SLIP:
        u[1:-1, -2] = 0.0
        v[1:-1, -1] = v[1:-1, -2]
    elif bc_right == OUTFLOW:
        u[1:-1, -2] = u[1:-1, -3]
        v[1:-1, -1] = v[1:-1, -2]
    # bottom: V(i,0), U(i,0)
    if bc_bottom == NOSLIP:
        v[0, 1:-1] = 0.0
        u[0, 1:-1] = -u[1, 1:-1]
    elif bc_bottom == SLIP:
        v[0, 1:-1] = 0.0
        u[0, 1:-1] = u[1, 1:-1]
    elif bc_bottom == OUTFLOW:
        u[0, 1:-1] = u[1, 1:-1]
        v[0, 1:-1] = v[1, 1:-1]
    # top: V(i,jmax), U(i,jmax+1)
    if bc_top == NOSLIP:
        v[-2, 1:-1] = 0.0
        u[-1, 1:-1] = -u[-2, 1:-1]
    elif bc_top == SLIP:
        v[-2, 1:-1] = 0.0
        u[-1, 1:-1] = u[-2, 1:-1]
    elif bc_top == OUTFLOW:
        u[-1, 1:-1] = u[-2, 1:-1]
        v[-2, 1:-1] = v[-3, 1:-1]
    return u, v


def ref_bcs_3d(u, v, w, bc):
    """Transcription of assignment-6 solver.c:364-577; arrays [k, j, i]
    (U(i,j,k) = u[k,j,i]); same face order: top, bottom, left, right,
    front, back."""
    u, v, w = u.copy(), v.copy(), w.copy()
    I = np.s_[1:-1]
    k = bc["top"]
    if k == NOSLIP:
        u[I, -1, I] = -u[I, -2, I]
        v[I, -2, I] = 0.0
        w[I, -1, I] = -w[I, -2, I]
    elif k == SLIP:
        u[I, -1, I] = u[I, -2, I]
        v[I, -2, I] = 0.0
        w[I, -1, I] = w[I, -2, I]
    elif k == OUTFLOW:
        u[I, -1, I] = u[I, -2, I]
        v[I, -2, I] = v[I, -3, I]
        w[I, -1, I] = w[I, -2, I]
    k = bc["bottom"]
    if k == NOSLIP:
        u[I, 0, I] = -u[I, 1, I]
        v[I, 0, I] = 0.0
        w[I, 0, I] = -w[I, 1, I]
    elif k == SLIP:
        u[I, 0, I] = u[I, 1, I]
        v[I, 0, I] = 0.0
        w[I, 0, I] = w[I, 1, I]
    elif k == OUTFLOW:
        u[I, 0, I] = u[I, 1, I]
        v[I, 0, I] = v[I, 1, I]
        w[I, 0, I] = w[I, 1, I]
    k = bc["left"]
    if k == NOSLIP:
        u[I, I, 0] = 0.0
        v[I, I, 0] = -v[I, I, 1]
        w[I, I, 0] = -w[I, I, 1]
    elif k == SLIP:
        u[I, I, 0] = 0.0
        v[I, I, 0] = v[I, I, 1]
        w[I, I, 0] = w[I, I, 1]
    elif k == OUTFLOW:
        u[I, I, 0] = u[I, I, 1]
        v[I, I, 0] = v[I, I, 1]
        w[I, I, 0] = w[I, I, 1]
    k = bc["right"]
    if k == NOSLIP:
        u[I, I, -2] = 0.0
        v[I, I, -1] = -v[I, I, -2]
        w[I, I, -1] = -w[I, I, -2]
    elif k == SLIP:
        u[I, I, -2] = 0.0
        v[I, I, -1] = v[I, I, -2]
        w[I, I, -1] = w[I, I, -2]
    elif k == OUTFLOW:
        u[I, I, -2] = u[I, I, -3]
        v[I, I, -1] = v[I, I, -2]
        w[I, I, -1] = w[I, I, -2]
    k = bc["front"]
    if k == NOSLIP:
        u[0, I, I] = -u[1, I, I]
        v[0, I, I] = -v[1, I, I]
        w[0, I, I] = 0.0
    elif k == SLIP:
        u[0, I, I] = u[1, I, I]
        v[0, I, I] = v[1, I, I]
        w[0, I, I] = 0.0
    elif k == OUTFLOW:
        u[0, I, I] = u[1, I, I]
        v[0, I, I] = v[1, I, I]
        w[0, I, I] = w[1, I, I]
    k = bc["back"]
    if k == NOSLIP:
        u[-1, I, I] = -u[-2, I, I]
        v[-1, I, I] = -v[-2, I, I]
        w[-2, I, I] = 0.0
    elif k == SLIP:
        u[-1, I, I] = u[-2, I, I]
        v[-1, I, I] = v[-2, I, I]
        w[-2, I, I] = 0.0
    elif k == OUTFLOW:
        u[-1, I, I] = u[-2, I, I]
        v[-1, I, I] = v[-2, I, I]
        w[-2, I, I] = w[-3, I, I]
    return u, v, w


def _rand2(shape, seed):
    rng = np.random.default_rng(seed)
    return rng.standard_normal(shape)


@pytest.mark.parametrize("kind", KINDS)
def test_2d_uniform_kind_all_walls(kind):
    u0 = _rand2((9, 12), 0)
    v0 = _rand2((9, 12), 1)
    ur, vr = ref_bcs_2d(u0, v0, kind, kind, kind, kind)
    uo, vo = set_boundary_conditions(
        jnp.asarray(u0), jnp.asarray(v0), kind, kind, kind, kind
    )
    np.testing.assert_array_equal(np.asarray(uo), ur)
    np.testing.assert_array_equal(np.asarray(vo), vr)


@pytest.mark.parametrize(
    "bcl,bcr,bcb,bct", list(itertools.permutations(KINDS))
)
def test_2d_mixed_kinds(bcl, bcr, bcb, bct):
    u0 = _rand2((8, 10), 2)
    v0 = _rand2((8, 10), 3)
    ur, vr = ref_bcs_2d(u0, v0, bcl, bcr, bcb, bct)
    uo, vo = set_boundary_conditions(
        jnp.asarray(u0), jnp.asarray(v0), bcl, bcr, bcb, bct
    )
    np.testing.assert_array_equal(np.asarray(uo), ur)
    np.testing.assert_array_equal(np.asarray(vo), vr)


@pytest.mark.parametrize("seed", range(8))
def test_2d_random_repeated_kinds(seed):
    rng = np.random.default_rng(200 + seed)
    bcl, bcr, bcb, bct = (int(rng.integers(1, 5)) for _ in range(4))
    u0 = _rand2((8, 10), 20 + seed)
    v0 = _rand2((8, 10), 40 + seed)
    ur, vr = ref_bcs_2d(u0, v0, bcl, bcr, bcb, bct)
    uo, vo = set_boundary_conditions(
        jnp.asarray(u0), jnp.asarray(v0), bcl, bcr, bcb, bct
    )
    np.testing.assert_array_equal(np.asarray(uo), ur)
    np.testing.assert_array_equal(np.asarray(vo), vr)


@pytest.mark.parametrize("kind", KINDS)
def test_3d_uniform_kind_all_faces(kind):
    shape = (7, 8, 9)
    u0, v0, w0 = (_rand2(shape, s) for s in (4, 5, 6))
    bc = {f: kind for f in ("top", "bottom", "left", "right", "front", "back")}
    ur, vr, wr = ref_bcs_3d(u0, v0, w0, bc)
    uo, vo, wo = set_boundary_conditions_3d(
        jnp.asarray(u0), jnp.asarray(v0), jnp.asarray(w0), bc
    )
    np.testing.assert_array_equal(np.asarray(uo), ur)
    np.testing.assert_array_equal(np.asarray(vo), vr)
    np.testing.assert_array_equal(np.asarray(wo), wr)


@pytest.mark.parametrize("seed", range(6))
def test_3d_random_mixed_kinds(seed):
    rng = np.random.default_rng(100 + seed)
    faces = ("top", "bottom", "left", "right", "front", "back")
    bc = {f: int(rng.integers(1, 5)) for f in faces}
    shape = (6, 7, 8)
    u0, v0, w0 = (_rand2(shape, 10 * seed + s) for s in (0, 1, 2))
    ur, vr, wr = ref_bcs_3d(u0, v0, w0, bc)
    uo, vo, wo = set_boundary_conditions_3d(
        jnp.asarray(u0), jnp.asarray(v0), jnp.asarray(w0), bc
    )
    np.testing.assert_array_equal(np.asarray(uo), ur)
    np.testing.assert_array_equal(np.asarray(vo), vr)
    np.testing.assert_array_equal(np.asarray(wo), wr)
