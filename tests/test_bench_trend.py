"""tools/bench_trend.py: the BENCH trend normalization + regression gate.

The committed BENCH_r01-r06 series must render populated (the round-8
bench-trend input parsed to [] — the normalized `metrics` schema exists
so that never recurs) and regression-free; a synthetic injected
regression must fail; CPU and TPU points must never gate against each
other."""

import json

from tools import bench_trend as bt


def _art(tmp_path, n, metrics):
    p = tmp_path / f"BENCH_r{n:02d}.json"
    with open(p, "w") as fh:
        json.dump({"n": n, "cmd": "x", "rc": 0, "tail": "",
                   "schema_version": 1, "metrics": metrics}, fh)
    return str(p)


def _pt(value, name="m", unit="updates/s", backend="tpu"):
    return {"name": name, "value": value, "unit": unit, "backend": backend}


def test_committed_artifacts_render_populated():
    """The acceptance pin: the committed BENCH_r01-r06 set yields a
    multi-round, backend-partitioned series — never [] — and carries no
    regression at the default tolerance."""
    files = bt.default_files()
    assert len(files) >= 6
    series = bt.build_series(bt.load_points(files))
    assert series, "committed BENCH artifacts yielded zero trend points"
    # multi-round: the TPU poisson headline spans rounds 1-5
    key = ("lattice_site_updates_per_sec_per_chip_poisson4096_rbsor", "tpu")
    assert key in series and len(series[key]) >= 4
    # backend partition: round 6 is the CPU growth container
    assert ("lattice_site_updates_per_sec_per_chip_poisson4096_rbsor",
            "cpu") in series
    assert bt.lint() == []
    table = bt.render(series)
    assert "r01" in table and "r06" in table and "[tpu]" in table


def test_synthetic_regression_fails(tmp_path):
    """An injected regression beyond tolerance fails; within tolerance
    passes (the make lint trend gate's contract)."""
    files = [_art(tmp_path, 1, [_pt(100.0)]),
             _art(tmp_path, 2, [_pt(80.0)])]  # -20% on a rate
    errs = bt.lint(files, tolerance=0.10)
    assert len(errs) == 1 and "dropped 20.0%" in errs[0]
    assert bt.lint(files, tolerance=0.25) == []
    # within tolerance
    files = [_art(tmp_path, 1, [_pt(100.0)]), _art(tmp_path, 2, [_pt(95.0)])]
    assert bt.lint(files, tolerance=0.10) == []


def test_gate_vs_best_not_last(tmp_path):
    """The gate compares against the BEST earlier point, not merely the
    previous round — a slow multi-round slide cannot ratchet the
    baseline down."""
    files = [_art(tmp_path, i, [_pt(v)])
             for i, v in ((1, 100.0), (2, 94.0), (3, 89.0))]
    errs = bt.lint(files, tolerance=0.10)
    assert len(errs) == 1 and "100" in errs[0]


def test_backend_partition_never_cross_gates(tmp_path):
    """A CPU trend point after strong TPU rounds is NOT a regression —
    the series are keyed (metric, backend)."""
    files = [_art(tmp_path, 1, [_pt(1e11, backend="tpu")]),
             _art(tmp_path, 2, [_pt(5e7, backend="cpu")])]
    assert bt.lint(files) == []
    series = bt.build_series(bt.load_points(files))
    assert ("m", "tpu") in series and ("m", "cpu") in series


def test_latency_direction(tmp_path):
    """ms/step regresses UPWARD; unknown units render but never gate."""
    files = [_art(tmp_path, 1, [_pt(10.0, unit="ms/step")]),
             _art(tmp_path, 2, [_pt(12.0, unit="ms/step")])]
    errs = bt.lint(files, tolerance=0.10)
    assert len(errs) == 1 and "rose" in errs[0]
    files = [_art(tmp_path, 1, [_pt(10.0, unit="bananas")]),
             _art(tmp_path, 2, [_pt(99.0, unit="bananas")])]
    assert bt.lint(files, tolerance=0.10) == []


def test_cpu_series_gate_at_wider_tolerance(tmp_path):
    """cpu series gate at CPU_TOLERANCE (growth containers are different
    hardware round to round — the r08 container runs the identical r06
    poisson loop 21% slower when idle), while tpu series keep the tight
    default; real breakage beyond CPU_TOLERANCE still fails."""
    files = [_art(tmp_path, 1, [_pt(100.0, backend="cpu")]),
             _art(tmp_path, 2, [_pt(76.0, backend="cpu")])]  # -24%
    assert bt.lint(files, tolerance=0.10) == []
    files = [_art(tmp_path, 1, [_pt(100.0, backend="cpu")]),
             _art(tmp_path, 2, [_pt(60.0, backend="cpu")])]  # -40%
    errs = bt.lint(files, tolerance=0.10)
    assert len(errs) == 1 and "35% tolerance" in errs[0]
    # tpu stays tight: the same -24% fails at 10%
    files = [_art(tmp_path, 1, [_pt(100.0, backend="tpu")]),
             _art(tmp_path, 2, [_pt(76.0, backend="tpu")])]
    assert len(bt.lint(files, tolerance=0.10)) == 1


def test_launch_census_direction(tmp_path):
    """launches_per_step gates DOWNWARD by name (ISSUE 17): the static
    census is deterministic, so ANY rise means a fusion regression — and
    the name pin survives a unit-string drift that would otherwise
    un-gate the series."""
    assert bt.higher_is_better("launches/step", "launches_per_step") is False
    assert bt.higher_is_better("bananas", "launches_per_step") is False
    pt = dict(name="launches_per_step", unit="launches/step", backend="cpu")
    files = [_art(tmp_path, 1, [dict(pt, value=0.5)]),
             _art(tmp_path, 2, [dict(pt, value=2.0)])]
    errs = bt.lint(files, tolerance=0.10)
    assert len(errs) == 1 and "launches_per_step" in errs[0] \
        and "rose" in errs[0]
    assert bt.lint([_art(tmp_path, 1, [dict(pt, value=0.5)]),
                    _art(tmp_path, 2, [dict(pt, value=0.5)])]) == []
    # the small serving-regime line is name-pinned downward too
    assert bt.higher_is_better(
        "bananas", "ns2d_small_ms_per_step") is False


def test_legacy_artifact_fallback(tmp_path):
    """Artifacts without a normalized metrics list fall back to the same
    normalizer over their parsed* blocks (never tail scraping)."""
    p = tmp_path / "BENCH_r01.json"
    with open(p, "w") as fh:
        json.dump({"n": 1, "cmd": "x", "rc": 0, "tail": "",
                   "parsed": {"metric": "legacy", "value": 5.0,
                              "unit": "updates/s", "backend": "pallas"}}, fh)
    pts = bt.load_points([str(p)])
    assert pts == [{"round": 1, "name": "legacy", "value": 5.0,
                    "unit": "updates/s", "backend": "tpu",
                    "file": "BENCH_r01.json"}]


def test_empty_input_is_a_violation(tmp_path):
    """The trend pass FAILS on an empty series — the round-8 `[]` shape
    is a lint error, not a silent pass."""
    assert bt.lint([]) != []
    p = tmp_path / "BENCH_r01.json"
    with open(p, "w") as fh:
        json.dump({"n": 1, "cmd": "x", "rc": 0, "tail": ""}, fh)
    assert any("zero trend points" in e for e in bt.lint([str(p)]))


def test_comm_hidden_fraction_higher_is_better(tmp_path):
    """The overlap headline gates UPWARD: a drop in comm_hidden_fraction
    means exchange time slid back onto the critical path (ROADMAP item 2;
    NAME_DIRECTIONS overrides the unit heuristic for this metric)."""
    assert bt.higher_is_better("fraction", "comm_hidden_fraction") is True
    assert bt.higher_is_better("fraction") is None  # unit alone: no gate
    pt = dict(name="comm_hidden_fraction", unit="fraction", backend="tpu")
    files = [_art(tmp_path, 1, [dict(pt, value=0.6)]),
             _art(tmp_path, 2, [dict(pt, value=0.3)])]
    errs = bt.lint(files, tolerance=0.10)
    assert len(errs) == 1 and "comm_hidden_fraction" in errs[0] \
        and "dropped" in errs[0]
    files = [_art(tmp_path, 1, [dict(pt, value=0.6)]),
             _art(tmp_path, 2, [dict(pt, value=0.58)])]
    assert bt.lint(files, tolerance=0.10) == []


def test_comm_hidden_fraction_normalized_from_block(tmp_path):
    """collect_metrics surfaces the merged comm_hidden_fraction block as
    a normalized metric, backend-tagged from the run it came from (a CPU
    smoke plane must not seed a chip-gating series)."""
    from tools._artifact import collect_metrics

    rec = {"comm_hidden_fraction": {"mode": "trace", "hidden_fraction": 0.4},
           "telemetry_summary": {"backend": "cpu"}}
    (m,) = collect_metrics(rec)
    assert m == {"name": "comm_hidden_fraction", "value": 0.4,
                 "unit": "fraction", "backend": "cpu"}
    rec["telemetry_summary"]["backend"] = "tpu"
    assert collect_metrics(rec)[0]["backend"] == "tpu"
    # a null hidden fraction (attribution failure) yields no point
    rec["comm_hidden_fraction"]["hidden_fraction"] = None
    assert collect_metrics(rec) == []


def test_autoscale_directions(tmp_path):
    """The control-plane health lines gate DOWNWARD by name (ISSUE 19):
    a longer time-to-recover or more capacity flaps under the same
    chaos script is a policy regression, whatever the unit says."""
    assert bt.higher_is_better(
        "ms", "autoscale_time_to_recover_ms") is False
    assert bt.higher_is_better("bananas", "autoscale_flaps") is False
    pt = dict(name="autoscale_time_to_recover_ms", unit="ms",
              backend="cpu")
    files = [_art(tmp_path, 1, [dict(pt, value=4000.0)]),
             _art(tmp_path, 2, [dict(pt, value=9000.0)])]
    errs = bt.lint(files, tolerance=0.35)
    assert len(errs) == 1 and "autoscale_time_to_recover_ms" in errs[0]
    assert bt.lint([_art(tmp_path, 1, [dict(pt, value=4000.0)]),
                    _art(tmp_path, 2, [dict(pt, value=4100.0)])],
                   tolerance=0.35) == []


def test_autoscale_normalized_from_block(tmp_path):
    """collect_metrics surfaces the merged autoscale block's flap count
    and recovery latency as normalized, backend-tagged trend points."""
    from tools._artifact import collect_metrics

    rec = {"autoscale": {"records": 25, "flaps": 0,
                         "time_to_recover_ms": 4204.7},
           "telemetry_summary": {"backend": "cpu"}}
    pts = {m["name"]: m for m in collect_metrics(rec)}
    assert pts["autoscale_flaps"]["value"] == 0
    assert pts["autoscale_flaps"]["backend"] == "cpu"
    assert pts["autoscale_time_to_recover_ms"]["value"] == 4204.7
    assert pts["autoscale_time_to_recover_ms"]["unit"] == "ms"
    # an unfinished storm (no recovery) yields no latency point
    rec["autoscale"]["time_to_recover_ms"] = None
    names = [m["name"] for m in collect_metrics(rec)]
    assert "autoscale_time_to_recover_ms" not in names \
        and "autoscale_flaps" in names
