"""3-D fused/temporal-blocked Pallas SOR kernel (ops/sor3d_pallas.py) vs the
jnp half-sweep composition it replaces (models/ns3d.sor_pass_3d +
neumann_faces_3d) — trajectory equality in interpret mode, plus end-to-end
backend equivalence of the NS-3D pressure solve. float32 only (the kernel's
dtype domain; f64 dispatches to jnp in production)."""

import numpy as np
import pytest

import jax.numpy as jnp

from pampi_tpu.models.ns3d import (
    checkerboard_mask_3d,
    make_pressure_solve_3d,
    neumann_faces_3d,
    sor_coefficients_3d,
    sor_pass_3d,
)
from pampi_tpu.ops.sor3d_pallas import (
    make_rb_iter_tblock_3d,
    pad_array_3d,
    pick_block_k,
    unpad_array_3d,
)

DT = jnp.float32


def _fields(K, J, I, seed=0):
    rng = np.random.default_rng(seed)
    p = jnp.asarray(rng.standard_normal((K + 2, J + 2, I + 2)), DT)
    rhs = jnp.asarray(rng.standard_normal((K + 2, J + 2, I + 2)), DT)
    return p, rhs


def _jnp_iter_fn(K, J, I, dx, dy, dz, omega):
    factor, idx2, idy2, idz2 = sor_coefficients_3d(dx, dy, dz, omega)
    odd = checkerboard_mask_3d(K, J, I, 1, DT)
    even = checkerboard_mask_3d(K, J, I, 0, DT)

    def one(p, rhs):
        p, r0 = sor_pass_3d(p, rhs, odd, factor, idx2, idy2, idz2)
        p, r1 = sor_pass_3d(p, rhs, even, factor, idx2, idy2, idz2)
        return neumann_faces_3d(p), r0 + r1

    return one


@pytest.mark.parametrize("shape", [(10, 12, 14), (7, 9, 11), (16, 16, 16)])
@pytest.mark.parametrize("n_inner", [1, 2])
def test_kernel_matches_jnp_trajectory(shape, n_inner):
    K, J, I = shape
    dx, dy, dz, omega = 1.0 / I, 1.0 / J, 1.0 / K, 1.7
    p0, rhs = _fields(K, J, I)
    one = _jnp_iter_fn(K, J, I, dx, dy, dz, omega)

    rb, bk = make_rb_iter_tblock_3d(
        I, J, K, dx, dy, dz, omega, DT, n_inner=n_inner, interpret=True
    )
    pp = pad_array_3d(p0, bk, n_inner)
    rp = pad_array_3d(rhs, bk, n_inner)

    want = p0
    for _outer in range(3):  # three kernel calls: halo logic must be stable
        pp, res = rb(pp, rp)
        wres = None
        for _ in range(n_inner):
            want, wres = one(want, rhs)
        got = unpad_array_3d(pp, K, J, I, n_inner)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=0, atol=5e-5)
        assert float(res) == pytest.approx(float(wres), rel=1e-4)


@pytest.mark.parametrize("block_k", [2, 3, 5, 64])
def test_kernel_block_size_invariance(block_k):
    """The owned-block/halo split must not affect the result (redundant halo
    recompute produces identical values)."""
    K, J, I = 12, 10, 18
    dx, dy, dz, omega = 1.0 / I, 1.0 / J, 1.0 / K, 1.5
    p0, rhs = _fields(K, J, I, seed=3)
    one = _jnp_iter_fn(K, J, I, dx, dy, dz, omega)
    want, wres = one(p0, rhs)

    rb, bk = make_rb_iter_tblock_3d(
        I, J, K, dx, dy, dz, omega, DT, n_inner=1, block_k=block_k,
        interpret=True,
    )
    pp, res = rb(pad_array_3d(p0, bk, 1), pad_array_3d(rhs, bk, 1))
    got = unpad_array_3d(pp, K, J, I, 1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=0, atol=5e-6)
    assert float(res) == pytest.approx(float(wres), rel=1e-4)


def test_block_k_degeneracy_guard():
    """A budget-forced block_k below the halo depth must be flagged (huge
    in-plane sizes), while small grids (grid-limited block_k) must not."""
    from pampi_tpu.ops.sor3d_pallas import block_k_degenerate, pick_block_k

    # huge plane: 4096x4096 f32 -> ~64 MiB/plane, bk collapses to 1
    bk = pick_block_k(4096, 4096, 4096, DT, n_inner=4)
    assert block_k_degenerate(bk, 4096, 4)
    # tiny grid: bk is grid-limited, not budget-limited -> fine
    bk = pick_block_k(4, 4, 4, DT, n_inner=4)
    assert not block_k_degenerate(bk, 4, 4)
    # headline shape: healthy block in the measured-fast range
    bk = pick_block_k(128, 128, 128, DT, n_inner=4)
    assert 8 <= bk <= 32 and not block_k_degenerate(bk, 128, 4)


def test_padding_roundtrip_and_dead_cells():
    K, J, I = 5, 6, 7
    p0, _ = _fields(K, J, I, seed=1)
    bk = pick_block_k(K, J, I, DT, 1)
    pp = pad_array_3d(p0, bk, 1)
    assert float(jnp.sum(jnp.abs(pp))) == pytest.approx(
        float(jnp.sum(jnp.abs(p0))), rel=1e-6
    )
    back = unpad_array_3d(pp, K, J, I, 1)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(p0))


def test_pressure_solve_backend_equivalence():
    """make_pressure_solve_3d(backend='pallas'/interpret) must converge to the
    same field and iteration count as the jnp backend."""
    K = J = I = 12
    dx, dy, dz = 1.0 / I, 1.0 / J, 1.0 / K
    rng = np.random.default_rng(5)
    p0 = jnp.zeros((K + 2, J + 2, I + 2), DT)
    rhs_i = rng.standard_normal((K, J, I))
    rhs_i -= rhs_i.mean()  # compatible RHS for the all-Neumann problem
    rhs = jnp.zeros_like(p0).at[1:-1, 1:-1, 1:-1].set(jnp.asarray(rhs_i, DT))

    s_jnp = make_pressure_solve_3d(I, J, K, dx, dy, dz, 1.7, 1e-4, 500, DT,
                                   backend="jnp")
    p_a, res_a, it_a = s_jnp(p0, rhs)

    s_pl = make_pressure_solve_3d(I, J, K, dx, dy, dz, 1.7, 1e-4, 500, DT,
                                  backend="pallas")
    p_b, res_b, it_b = s_pl(p0, rhs)

    assert int(it_a) == int(it_b)
    assert float(res_b) == pytest.approx(float(res_a), rel=1e-3)
    np.testing.assert_allclose(np.asarray(p_b), np.asarray(p_a),
                               rtol=0, atol=1e-4)


def test_pressure_solve_n_inner_accounting():
    """With n_inner=2 the pallas loop advances `it` by 2 per step and stops at
    the same convergence point (within one fused step's granularity)."""
    K = J = I = 10
    dx, dy, dz = 1.0 / I, 1.0 / J, 1.0 / K
    rng = np.random.default_rng(6)
    p0 = jnp.zeros((K + 2, J + 2, I + 2), DT)
    rhs_i = rng.standard_normal((K, J, I))
    rhs_i -= rhs_i.mean()
    rhs = jnp.zeros_like(p0).at[1:-1, 1:-1, 1:-1].set(jnp.asarray(rhs_i, DT))

    s1 = make_pressure_solve_3d(I, J, K, dx, dy, dz, 1.7, 1e-4, 500, DT,
                                backend="jnp")
    _, _, it1 = s1(p0, rhs)
    s2 = make_pressure_solve_3d(I, J, K, dx, dy, dz, 1.7, 1e-4, 500, DT,
                                backend="pallas", n_inner=2)
    p2, res2, it2 = s2(p0, rhs)
    assert int(it2) % 2 == 0
    assert abs(int(it2) - int(it1)) <= 2
    assert float(res2) < 1e-8  # eps² = 1e-8


def test_ns3d_solver_backend_equivalence():
    """Full NS-3D time loop: forcing the pallas (interpret) backend must
    reproduce the auto/jnp run on CPU."""
    from pampi_tpu.models.ns3d import NS3DSolver
    from pampi_tpu.utils.params import Parameter

    param = Parameter(
        name="dcavity3d", imax=8, jmax=8, kmax=8,
        re=10.0, te=0.03, tau=0.5, itermax=100, eps=1e-4, omg=1.7,
        gamma=0.9, tpu_dtype="float32",
    )
    a = NS3DSolver(param, dtype=DT)
    a.run(progress=False)

    b = NS3DSolver(param, dtype=DT)
    b._chunk_fn = __import__("jax").jit(b._build_chunk(backend="pallas"))
    b._backend = "pallas"
    b.run(progress=False)

    np.testing.assert_allclose(np.asarray(b.p), np.asarray(a.p),
                               rtol=0, atol=5e-4)
    np.testing.assert_allclose(np.asarray(b.u), np.asarray(a.u),
                               rtol=0, atol=5e-4)
    assert a.nt == b.nt
