"""Poisson solver tests: trajectory parity with a pure-numpy transcription of
the reference's red-black scheme, convergence, and regression vs the committed
golden `assignment-4/p.dat` (SURVEY.md §4: golden outputs are the reference's
regression baselines)."""

import numpy as np
import pytest

from pampi_tpu.utils.datio import read_matrix
from pampi_tpu.utils.params import Parameter, read_parameter
from pampi_tpu.models.poisson import PoissonSolver, init_fields


def numpy_rb_reference(p, rhs, imax, jmax, dx, dy, omega, eps, itermax):
    """Literal numpy port of solveRB semantics (assignment-4/src/solver.c:179-237)
    used as an in-repo oracle: stride-2 checkerboard, in-place, res over visited
    cells, Neumann ghost copy after the sweep, res normalized by imax*jmax."""
    p = p.copy()
    dx2, dy2 = dx * dx, dy * dy
    idx2, idy2 = 1.0 / dx2, 1.0 / dy2
    factor = omega * 0.5 * (dx2 * dy2) / (dx2 + dy2)
    epssq = eps * eps
    it, res = 0, 1.0
    while res >= epssq and it < itermax:
        res = 0.0
        jsw = 1
        for _pass in range(2):
            isw = jsw
            for j in range(1, jmax + 1):
                for i in range(isw, imax + 1, 2):
                    r = rhs[j, i] - (
                        (p[j, i + 1] - 2.0 * p[j, i] + p[j, i - 1]) * idx2
                        + (p[j + 1, i] - 2.0 * p[j, i] + p[j - 1, i]) * idy2
                    )
                    p[j, i] -= factor * r
                    res += r * r
                isw = 3 - isw
            jsw = 3 - jsw
        p[0, 1:-1] = p[1, 1:-1]
        p[-1, 1:-1] = p[-2, 1:-1]
        p[1:-1, 0] = p[1:-1, 1]
        p[1:-1, -1] = p[1:-1, -2]
        res = res / (imax * jmax)
        it += 1
    return p, res, it


def test_rb_trajectory_matches_reference_scheme():
    """On a small grid, the jitted masked red-black step must reproduce the
    reference's stride-2 in-place sweep to float64 roundoff."""
    param = Parameter(imax=16, jmax=12, itermax=25, eps=1e-30, omg=1.8)
    s = PoissonSolver(param, problem=2)
    p0, rhs = init_fields(param, problem=2)
    p_np, res_np, it_np = numpy_rb_reference(
        np.asarray(p0), np.asarray(rhs), 16, 12, s.dx, s.dy, 1.8, 1e-30, 25
    )
    it, res = s.solve()
    assert it == it_np == 25
    np.testing.assert_allclose(np.asarray(s.p), p_np, rtol=0, atol=1e-12)
    assert abs(res - res_np) < 1e-12 * max(1.0, abs(res_np))


def test_poisson_converges_default_config(reference_dir):
    param = read_parameter(str(reference_dir / "assignment-4" / "poisson.par"))
    s = PoissonSolver(param, problem=2)
    it, res = s.solve()
    assert res < param.eps**2
    assert 0 < it < param.itermax


@pytest.mark.golden
def test_init_fields_matches_golden_initdat(reference_dir):
    """The committed `assignment-4/init.dat` is writeResult applied to the
    INITIAL field — a golden for the initSolver formula itself
    (p = sin(4πi·dx)+sin(4πj·dy) incl. ghosts, solver.c:105-116). %f format
    carries 6 decimals, so compare at 1e-6."""
    param = read_parameter(str(reference_dir / "assignment-4" / "poisson.par"))
    p0, _rhs = init_fields(param, problem=2)
    golden = read_matrix(str(reference_dir / "assignment-4" / "init.dat"))
    assert golden.shape == np.asarray(p0).shape
    np.testing.assert_allclose(np.asarray(p0), golden, rtol=0, atol=1.1e-6)


@pytest.mark.golden
def test_poisson_matches_golden_pdat(reference_dir, tmp_path):
    """Converged field vs committed golden p.dat (produced by the reference's
    lexicographic `solve`). The all-Neumann problem is singular — solutions
    differ by a constant — and the orderings differ, so compare interiors
    after removing the mean, at discretization-level tolerance."""
    param = read_parameter(str(reference_dir / "assignment-4" / "poisson.par"))
    s = PoissonSolver(param, problem=2)
    s.solve()
    golden = read_matrix(str(reference_dir / "assignment-4" / "p.dat"))
    ours = np.asarray(s.p)
    assert golden.shape == ours.shape
    gi = golden[1:-1, 1:-1]
    oi = ours[1:-1, 1:-1]
    diff = (oi - oi.mean()) - (gi - gi.mean())
    assert np.sqrt((diff**2).mean()) < 1e-5, np.abs(diff).max()

    # output writer format parity: full array incl. ghosts, %f-formatted
    s.write_result(str(tmp_path / "p.dat"))
    reread = read_matrix(str(tmp_path / "p.dat"))
    assert reread.shape == golden.shape


def numpy_lex_reference(p, rhs, imax, jmax, dx, dy, omega, eps, itermax):
    """Literal numpy port of the lexicographic `solve`
    (assignment-4/src/solver.c:126-176): j-outer/i-inner in-place sweep."""
    p = p.copy()
    dx2, dy2 = dx * dx, dy * dy
    idx2, idy2 = 1.0 / dx2, 1.0 / dy2
    factor = omega * 0.5 * (dx2 * dy2) / (dx2 + dy2)
    epssq = eps * eps
    it, res = 0, 1.0
    while res >= epssq and it < itermax:
        res = 0.0
        for j in range(1, jmax + 1):
            for i in range(1, imax + 1):
                r = rhs[j, i] - (
                    (p[j, i - 1] - 2.0 * p[j, i] + p[j, i + 1]) * idx2
                    + (p[j - 1, i] - 2.0 * p[j, i] + p[j + 1, i]) * idy2
                )
                p[j, i] -= factor * r
                res += r * r
        p[0, 1:-1] = p[1, 1:-1]
        p[-1, 1:-1] = p[-2, 1:-1]
        p[1:-1, 0] = p[1:-1, 1]
        p[1:-1, -1] = p[1:-1, -2]
        res = res / (imax * jmax)
        it += 1
    return p, res, it


def test_lex_trajectory_matches_reference_scheme():
    """The scan/associative-scan lexicographic solver (tpu_solver sor_lex)
    must reproduce the reference's in-place j-outer/i-inner sweep to f64
    roundoff — same dependency structure, only FP association differs."""
    param = Parameter(imax=16, jmax=12, itermax=25, eps=1e-30, omg=1.9,
                      tpu_solver="sor_lex")
    s = PoissonSolver(param, problem=2)
    p0, rhs = init_fields(param, problem=2)
    p_np, res_np, it_np = numpy_lex_reference(
        np.asarray(p0), np.asarray(rhs), 16, 12, s.dx, s.dy, 1.9, 1e-30, 25
    )
    it, res = s.solve()
    assert it == it_np == 25
    np.testing.assert_allclose(np.asarray(s.p), p_np, rtol=0, atol=1e-11)
    assert abs(res - res_np) < 1e-11 * max(1.0, abs(res_np))


@pytest.mark.golden
def test_solver_trio_iteration_parity(reference_dir):
    """The assignment-4 solver trio (solve/solveRB/solveRBA,
    solver.c:126/179/240) as selectable modes: on the reference's own
    poisson.par (100 sq, eps=1e-6, omega=1.9) each variant's iteration count
    must match the C reference binary within +-1. Golden counts obtained by
    compiling assignment-4/src/{solver,parameter,allocate,timing}.c with a
    3-line driver calling each variant: ALL THREE converge in 2388."""
    param = read_parameter(str(reference_dir / "assignment-4" / "poisson.par"))
    for mode in ("sor_lex", "sor", "sor_rba"):
        param.tpu_solver = mode
        s = PoissonSolver(param, problem=2)
        it, res = s.solve()
        assert abs(it - 2388) <= 1, (mode, it)
        assert res < param.eps**2


@pytest.mark.golden
def test_lex_writes_byte_identical_golden_pdat(reference_dir, tmp_path):
    """tpu_solver sor_lex reproduces the committed golden p.dat
    BYTE-IDENTICALLY (the golden was produced by the C binary's `solve`,
    which main.c calls; %f formatting absorbs the scan's FP-association
    roundoff)."""
    param = read_parameter(str(reference_dir / "assignment-4" / "poisson.par"))
    param.tpu_solver = "sor_lex"
    s = PoissonSolver(param, problem=2)
    s.solve()
    out = tmp_path / "p.dat"
    s.write_result(str(out))
    assert out.read_bytes() == (
        reference_dir / "assignment-4" / "p.dat"
    ).read_bytes()


def test_rba_matches_rb_trajectory():
    """solveRBA is solveRB with omega applied separately — identical cell
    visitation, factor differs only in FP association; fields must agree to
    roundoff on a fixed iteration budget."""
    param = Parameter(imax=16, jmax=12, itermax=25, eps=1e-30, omg=1.8)
    rb = PoissonSolver(param, problem=2)
    rb.solve()
    param2 = Parameter(imax=16, jmax=12, itermax=25, eps=1e-30, omg=1.8,
                       tpu_solver="sor_rba")
    rba = PoissonSolver(param2, problem=2)
    rba.solve()
    np.testing.assert_allclose(
        np.asarray(rba.p), np.asarray(rb.p), rtol=0, atol=1e-12
    )


def test_flat_solve_bitwise_on_capped_runs():
    """tpu_flat_solve (round 5): exactly ceil(itermax/n) fori trips, no
    res-gated cond. On a capped run (eps unreachable) the body sequence is
    identical -> bitwise-equal field, residual and iteration count; on a
    converging run it overdrives to the cap with a residual at or below
    the while version's."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pampi_tpu.models.poisson import make_solver_fn

    DT = jnp.float64
    J = I = 64
    dx = dy = 1.0 / I
    rng = np.random.default_rng(3)
    r = rng.standard_normal((J, I))
    r -= r.mean()
    rhs = jnp.zeros((J + 2, I + 2), DT).at[1:-1, 1:-1].set(jnp.asarray(r, DT))
    p0 = jnp.zeros_like(rhs)

    # capped: eps unreachable -> bitwise parity
    w = jax.jit(make_solver_fn(I, J, dx, dy, 1.8, 1e-30, 60, DT,
                               backend="jnp", n_inner=1))
    f = jax.jit(make_solver_fn(I, J, dx, dy, 1.8, 1e-30, 60, DT,
                               backend="jnp", n_inner=1, flat=True))
    pw, resw, itw = w(p0, rhs)
    pf, resf, itf = f(p0, rhs)
    assert int(itw) == int(itf) == 60
    np.testing.assert_array_equal(np.asarray(pw), np.asarray(pf))
    assert float(resw) == float(resf)

    # converging: flat overdrives to the cap, residual only improves
    w2 = jax.jit(make_solver_fn(I, J, dx, dy, 1.8, 1e-6, 100000, DT,
                                backend="jnp", n_inner=1))
    f2 = jax.jit(make_solver_fn(I, J, dx, dy, 1.8, 1e-6, 5000, DT,
                                backend="jnp", n_inner=1, flat=True))
    _, resw2, itw2 = w2(p0, rhs)
    _, resf2, itf2 = f2(p0, rhs)
    assert int(itw2) < 5000 == int(itf2)
    assert float(resf2) <= float(resw2)
