"""Device-time profiling plane (utils/xprof + the comm named scopes).

Four contracts (ISSUE 8):
- GOLDEN INGESTION: the trace-event aggregation (per-scope/collective/
  kernel device ms, busy/idle, exchange device-vs-exposed) is pinned
  against a committed golden trace fixture, so the whole plane is
  testable off-chip.
- OFF-PATH ZERO COST: PAMPI_XPROF is host-side only — the traced chunk
  is byte-identical with the flag set or unset (the PAMPI_TELEMETRY /
  PAMPI_FAULTS contract), and the always-on `jax.named_scope` exchange
  attribution never changes the jaxpr text (CONTRACTS.json hashes).
- NAMED-SCOPE PRESENCE: every dist chunk's ppermutes carry the
  `halo_exchange.*`/`halo_shift.*` scopes, keyed by the SAME strip_key
  the commcheck census uses (one naming convention across trace, lint
  and telemetry).
- EXCHANGE SPAN ROUND-TRIP: a dist run with telemetry armed emits the
  serial-probe `.exchange` span; report -> merge -> artifact lint all
  pass and the comm_hidden_fraction block lands in the artifact.
"""

import gzip
import json
import os
import shutil

import jax
import pytest

from pampi_tpu.models.ns2d_dist import NS2DDistSolver
from pampi_tpu.parallel.comm import (
    CartComm,
    exchange_schedule_bytes,
    halo_exchange_bytes,
    strip_key,
    time_exchange_ms,
)
from pampi_tpu.utils import telemetry as tm
from pampi_tpu.utils import xprof
from pampi_tpu.utils.params import Parameter

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "xprof_golden.trace.json")

_BASE = dict(name="dcavity", imax=16, jmax=16, re=10.0, te=0.02, tau=0.5,
             itermax=10, eps=1e-4, omg=1.7, gamma=0.9)


@pytest.fixture()
def tel_on(tmp_path, monkeypatch):
    path = tmp_path / "run.jsonl"
    monkeypatch.setenv("PAMPI_TELEMETRY", str(path))
    tm.reset()
    yield path
    tm.reset()


def _records(path):
    return [json.loads(ln) for ln in open(path) if ln.strip()]


# ---------------------------------------------------------------------------
# golden-fixture ingestion
# ---------------------------------------------------------------------------

def test_golden_trace_aggregation():
    """The committed fixture's numbers, pinned (see the fixture's metadata
    note for the track layout): 2 device tracks, the host python track
    ignored, exchange 1.1 ms of which 0.4 ms hides under fusion.2."""
    s = xprof.summarize(xprof.load_trace_events(FIXTURE))
    assert s["tracks"] == 2  # the /host:CPU python track is not a device
    assert s["total_ms"] == 2.8
    assert s["busy_ms"] == pytest.approx(5.4)   # 2.6 + 2.8 across tracks
    assert s["idle_ms"] == pytest.approx(0.2)   # track 1's [2400, 2600] gap
    # scope attribution by the comm strip_key convention
    assert s["scopes"] == {
        "halo_exchange.j.4x18:float32": pytest.approx(0.7),  # cp.1 + cp.3
        "halo_exchange.i.18x4:float32": pytest.approx(0.4),  # cp.2
    }
    assert s["collectives"] == {"collective-permute": pytest.approx(1.1)}
    # kernels summed by name across tracks
    assert s["kernels"]["fusion.1"] == pytest.approx(2.2)
    assert s["kernels"]["fusion.2"] == pytest.approx(1.0)
    # the comm-hidden inputs: cp.2 is fully covered by fusion.2
    assert s["exchange_device_ms"] == pytest.approx(1.1)
    assert s["exchange_exposed_ms"] == pytest.approx(0.7)
    assert xprof.hidden_fraction(s) == pytest.approx(1 - 0.7 / 1.1,
                                                     abs=1e-4)


def test_golden_gzip_and_discovery(tmp_path):
    """Ingestion reads the profiler's gzipped form and latest_trace_file
    finds it under the nested plugins/profile/<ts>/ layout."""
    d = tmp_path / "plugins" / "profile" / "2026_01_01"
    d.mkdir(parents=True)
    with open(FIXTURE, "rb") as src, gzip.open(d / "host.trace.json.gz",
                                               "wb") as dst:
        shutil.copyfileobj(src, dst)
    found = xprof.latest_trace_file(str(tmp_path))
    assert found and found.endswith(".trace.json.gz")
    assert xprof.summarize(xprof.load_trace_events(found)) \
        == xprof.summarize(xprof.load_trace_events(FIXTURE))


def test_container_ops_do_not_hide_exchange():
    """A while-loop container event spanning the whole chunk (the CPU
    thunk executor's form) must not count as compute cover — otherwise
    every exchange reads as 100% hidden."""
    events = [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": 1000,
         "name": "while.1", "args": {"hlo_op": "while.1"}},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 100, "dur": 200,
         "name": "collective-permute.1",
         "args": {"hlo_op": "collective-permute.1"}},
    ]
    s = xprof.summarize(events)
    assert s["exchange_device_ms"] == pytest.approx(0.2)
    assert s["exchange_exposed_ms"] == pytest.approx(0.2)  # NOT hidden
    assert xprof.hidden_fraction(s) == 0.0


def test_empty_trace_degrades():
    s = xprof.summarize([])
    assert s["tracks"] == 0 and s["exchange_device_ms"] == 0.0
    assert xprof.hidden_fraction(s) is None


# ---------------------------------------------------------------------------
# the comm_hidden_fraction block (tools/telemetry_report)
# ---------------------------------------------------------------------------

def test_comm_hidden_fraction_trace_mode():
    from tools import telemetry_report as tr

    summ = xprof.summarize(xprof.load_trace_events(FIXTURE))
    records = [
        {"v": 3, "kind": "xprof", "ts": 0, "region": "ns2d_dist",
         "steps": 10, "mode": "trace", **summ},
        {"v": 3, "kind": "span", "ts": 0, "name": "ns2d_dist.exchange",
         "ms": 0.2, "mode": "serial_probe"},
    ]
    chf = tr.comm_hidden_fraction(records)
    assert chf["mode"] == "trace" and chf["steps"] == 10
    assert chf["exchange_device_ms_per_step"] == pytest.approx(0.11)
    assert chf["exchange_exposed_ms_per_step"] == pytest.approx(0.07)
    assert chf["exchange_serial_ms_per_step"] == 0.2
    assert chf["hidden_fraction"] == pytest.approx(1 - 0.7 / 1.1, abs=1e-4)
    # the block survives the artifact lint
    from tools import check_artifact as ca

    assert ca.lint_comm_hidden(chf, "t") == []


def test_comm_hidden_fraction_zero_attribution_stays_trace():
    """A real trace that attributed ZERO exchange time (scope drift, a
    single-device capture) must surface as mode 'trace' with hidden
    None — never dressed up as a clean wallclock measurement."""
    from tools import telemetry_report as tr

    records = [
        {"v": 3, "kind": "xprof", "ts": 0, "region": "ns2d", "steps": 4,
         "mode": "trace", "exchange_device_ms": 0.0,
         "exchange_exposed_ms": 0.0},
        {"v": 3, "kind": "span", "ts": 0, "name": "ns2d_dist.exchange",
         "ms": 0.3},
    ]
    chf = tr.comm_hidden_fraction(records)
    assert chf["mode"] == "trace"
    assert chf["hidden_fraction"] is None
    assert chf["exchange_serial_ms_per_step"] == 0.3


def test_comm_hidden_fraction_wallclock_mode():
    """Degraded mode: only the serial probe exists — fully exposed."""
    from tools import telemetry_report as tr

    records = [{"v": 3, "kind": "span", "ts": 0,
                "name": "ns3d_dist.exchange", "ms": 1.5}]
    chf = tr.comm_hidden_fraction(records)
    assert chf["mode"] == "wallclock"
    assert chf["hidden_fraction"] == 0.0
    assert chf["exchange_device_ms_per_step"] == 1.5
    assert tr.comm_hidden_fraction([]) is None


# ---------------------------------------------------------------------------
# off-path identity + named-scope presence
# ---------------------------------------------------------------------------

def test_offpath_jaxpr_identity_xprof(tmp_path, monkeypatch):
    """PAMPI_XPROF set vs unset: the traced dist chunk is byte-identical
    (capture/ingestion are host-side; the named scopes are always on and
    jaxpr-invisible — the CONTRACTS.json hash contract)."""
    from pampi_tpu.analysis.jaxprcheck import trace_chunk

    monkeypatch.delenv("PAMPI_XPROF", raising=False)
    param = Parameter(**_BASE)
    off = NS2DDistSolver(param, CartComm(ndims=2, dims=(2, 2)))
    jx_off = trace_chunk(off)
    monkeypatch.setenv("PAMPI_XPROF", str(tmp_path / "trace"))
    on = NS2DDistSolver(param, CartComm(ndims=2, dims=(2, 2)))
    jx_on = trace_chunk(on)
    assert str(jx_off) == str(jx_on)
    assert not (tmp_path / "trace").exists()  # tracing never armed


def test_named_scopes_pinned_on_dist_chunk():
    """Every dist chunk's step-level exchanges carry the halo_exchange /
    halo_shift named scopes, keyed by the commcheck strip_key — the
    static twin of the xprof trace attribution (and the `comm-scope`
    lint rule's contract)."""
    from pampi_tpu.analysis.commcheck import census, scoped_exchanges
    from pampi_tpu.analysis.jaxprcheck import trace_chunk

    s = NS2DDistSolver(Parameter(**_BASE), CartComm(ndims=2, dims=(2, 2)))
    jx = trace_chunk(s)
    scoped = scoped_exchanges(jx.jaxpr)
    ex_labels = [l for l in scoped if l.startswith("halo_exchange.")]
    sh_labels = [l for l in scoped if l.startswith("halo_shift.")]
    assert ex_labels, f"no scoped exchanges in {scoped}"
    assert sh_labels, f"no scoped shifts in {scoped}"  # F/G donor edges
    # one naming convention: every scope's strip token is a census key
    strips = census(jx.jaxpr)["strips"]
    for label in ex_labels:
        token = label.split(".", 2)[2]
        assert token in strips, (label, sorted(strips))


def test_strip_key_convention():
    import numpy as np

    assert strip_key((4, 18), np.dtype("float32")) == "4x18:float32"
    # commcheck's spelling routes through the same helper
    from pampi_tpu.analysis.commcheck import strip_key as ck

    assert ck((4, 18), np.dtype("float32")) == strip_key(
        (4, 18), np.dtype("float32"))


# ---------------------------------------------------------------------------
# capture + the exchange probe + the artifact round-trip
# ---------------------------------------------------------------------------

def test_capture_emits_record(tel_on, tmp_path, monkeypatch):
    """End-to-end on this container: capture() around a jitted region
    emits one `xprof` record (trace mode when the profiler writes a
    parseable trace-event file — this CPU backend does — wallclock
    otherwise; both are legal, neither may crash)."""
    import jax.numpy as jnp

    monkeypatch.setenv("PAMPI_XPROF", str(tmp_path / "trace"))
    with xprof.capture("unit.region", steps=7):
        x = jax.jit(lambda a: a * 2 + 1)(jnp.ones((32, 32)))
        x.block_until_ready()
    recs = [r for r in _records(tel_on) if r["kind"] == "xprof"]
    assert len(recs) == 1
    r = recs[0]
    assert r["region"] == "unit.region" and r["steps"] == 7
    assert r["mode"] in ("trace", "wallclock") and r["wall_ms"] > 0
    if r["mode"] == "trace":
        assert r["busy_ms"] >= 0 and isinstance(r["scopes"], dict)


def test_capture_noop_when_unset(tel_on, monkeypatch):
    monkeypatch.delenv("PAMPI_XPROF", raising=False)
    with xprof.capture("unit.off"):
        pass
    if os.path.exists(tel_on):
        assert not any(r["kind"] == "xprof" for r in _records(tel_on))


def test_exchange_probe_and_bytes():
    """The serial exchange probe prices and times the declared schedule;
    the byte accounting composes from the shared comm helpers."""
    comm = CartComm(ndims=2, dims=(2, 2))
    rec = {"family": "ns2d_dist", "mesh": [2, 2], "shard": [8, 8],
           "dtype": "float64", "path": "jnp",
           "exchange_bytes_depth1": halo_exchange_bytes((8, 8), 1, 8),
           "exchanges_per_step": {"depth1": 4, "shift": 2}}
    # 4 full depth-1 exchanges + one single-direction strip per axis
    want = 4 * halo_exchange_bytes((8, 8), 1, 8) + 2 * (10 * 1 * 8)
    assert exchange_schedule_bytes(rec) == want
    ms = time_exchange_ms(comm, rec, reps=2)
    assert ms > 0


def test_exchange_span_roundtrip(tel_on, tmp_path):
    """A dist run with telemetry armed emits the `.exchange` span; the
    record flows report -> merge -> artifact lint, and the comm-hidden
    block lands in the artifact (wallclock mode here: no PAMPI_XPROF)."""
    s = NS2DDistSolver(Parameter(**_BASE), CartComm(ndims=2, dims=(2, 2)))
    s.run(progress=False)
    tm.finalize()
    recs = _records(tel_on)
    spans = [r for r in recs if r["kind"] == "span"
             and r["name"] == "ns2d_dist.exchange"]
    assert len(spans) == 1
    sp = spans[0]
    assert sp["ms"] > 0 and sp["mode"] == "serial_probe"
    assert sp["bytes_per_step"] == exchange_schedule_bytes(s._halo_record())

    from tools import check_artifact as ca
    from tools import telemetry_report as tr
    from tools._artifact import write_merged

    chf = tr.comm_hidden_fraction(recs)
    assert chf["mode"] == "wallclock" and chf["hidden_fraction"] == 0.0
    art = str(tmp_path / "BENCH_unit.json")
    with open(art, "w") as fh:
        json.dump({"n": 8, "cmd": "unit", "rc": 0, "tail": ""}, fh)
    merged = write_merged(art, {"telemetry_summary": tr.summary(recs),
                                "comm_hidden_fraction": chf})
    assert ca.lint_bench(merged) == []
    # a malformed hidden fraction is flagged
    bad = dict(merged,
               comm_hidden_fraction=dict(chf, hidden_fraction=1.7))
    assert any("hidden_fraction" in e for e in ca.lint_bench(bad))
