"""Comm-layer tests on a faked 8-device CPU mesh.

The reference's only distributed test is the rank-id halo checker
(assignment-6/src/test.c:125-228 and printExchange/printShift,
assignment-5/ex5-nazifkar/src/solver.c:34-124): fill each rank's field with
its rank id, exchange, and assert every ghost strip shows the neighbour's id.
These tests are the automated version of exactly that."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from pampi_tpu.parallel.comm import (
    CartComm,
    dims_create,
    get_offsets,
    halo_exchange,
    halo_shift,
    is_boundary,
    reduction,
)


def test_dims_create_balanced():
    assert dims_create(8, 2) == (4, 2)
    assert dims_create(8, 3) == (2, 2, 2)
    assert dims_create(12, 3) == (3, 2, 2)
    assert dims_create(6, 2) == (3, 2)
    assert dims_create(7, 2) == (7, 1)
    assert dims_create(1, 3) == (1, 1, 1)


def _rank_blocks(comm, jl, il, fn):
    """Run fn (kernel returning an extended local block) and return blocks
    indexed [cj][ci] on the host."""
    Pj, Pi = comm.dims
    out = comm.shard_map(fn, in_specs=(), out_specs=P("j", "i"))()
    glob = np.asarray(out)
    return [
        [
            glob[cj * (jl + 2) : (cj + 1) * (jl + 2), ci * (il + 2) : (ci + 1) * (il + 2)]
            for ci in range(Pi)
        ]
        for cj in range(Pj)
    ]


@pytest.fixture(scope="module")
def comm2d():
    assert len(jax.devices()) == 8, "conftest must fake 8 CPU devices"
    return CartComm(ndims=2)  # (4, 2)


def test_halo_exchange_rank_id(comm2d):
    comm = comm2d
    Pj, Pi = comm.dims
    jl, il = 4, 6

    def kernel():
        rank = lax.axis_index("j") * Pi + lax.axis_index("i")
        ext = jnp.full((jl + 2, il + 2), -1.0)
        ext = ext.at[1:-1, 1:-1].set(rank.astype(ext.dtype))
        return halo_exchange(ext, comm)

    blocks = _rank_blocks(comm, jl, il, kernel)
    for cj in range(Pj):
        for ci in range(Pi):
            b = blocks[cj][ci]
            rank = cj * Pi + ci
            assert (b[1:-1, 1:-1] == rank).all()
            # low/high j ghosts: neighbour's id, or untouched -1 at the wall
            exp_lo_j = (cj - 1) * Pi + ci if cj > 0 else -1
            exp_hi_j = (cj + 1) * Pi + ci if cj < Pj - 1 else -1
            assert (b[0, 1:-1] == exp_lo_j).all(), (cj, ci, b[0])
            assert (b[-1, 1:-1] == exp_hi_j).all()
            exp_lo_i = cj * Pi + (ci - 1) if ci > 0 else -1
            exp_hi_i = cj * Pi + (ci + 1) if ci < Pi - 1 else -1
            assert (b[1:-1, 0] == exp_lo_i).all()
            assert (b[1:-1, -1] == exp_hi_i).all()
            # corners consistent after second axis: diagonal neighbour's id
            if cj > 0 and ci > 0:
                assert b[0, 0] == (cj - 1) * Pi + (ci - 1)


def test_halo_shift_one_directional(comm2d):
    comm = comm2d
    Pj, Pi = comm.dims
    jl, il = 3, 5

    def kernel():
        rank = lax.axis_index("j") * Pi + lax.axis_index("i")
        ext = jnp.full((jl + 2, il + 2), -1.0)
        ext = ext.at[1:-1, 1:-1].set(rank.astype(ext.dtype))
        return halo_shift(ext, comm, "i")

    blocks = _rank_blocks(comm, jl, il, kernel)
    for cj in range(Pj):
        for ci in range(Pi):
            b = blocks[cj][ci]
            exp = cj * Pi + (ci - 1) if ci > 0 else -1
            assert (b[1:-1, 0] == exp).all()
            # one-directional: high ghost must stay untouched
            assert (b[1:-1, -1] == -1).all()


def test_periodic_exchange_wraps(comm2d):
    comm = comm2d
    Pj, Pi = comm.dims
    jl, il = 3, 4

    def kernel():
        rank = lax.axis_index("j") * Pi + lax.axis_index("i")
        ext = jnp.full((jl + 2, il + 2), -1.0)
        ext = ext.at[1:-1, 1:-1].set(rank.astype(ext.dtype))
        return halo_exchange(ext, comm, periodic=("j",))

    blocks = _rank_blocks(comm, jl, il, kernel)
    for ci in range(Pi):
        top = blocks[Pj - 1][ci]
        bot = blocks[0][ci]
        assert (top[-1, 1:-1] == 0 * Pi + ci).all()  # wraps to cj=0
        assert (bot[0, 1:-1] == (Pj - 1) * Pi + ci).all()


def test_reduction_and_coords(comm2d):
    comm = comm2d
    Pj, Pi = comm.dims

    def kernel():
        rank = lax.axis_index("j") * Pi + lax.axis_index("i")
        s = reduction(rank, comm, "sum")
        m = reduction(rank, comm, "max")
        lo = is_boundary("j", Pj, "lo")
        off = get_offsets("j", 10)
        return jnp.stack([rank, s, m, lo.astype(jnp.int32), off])[None, :]

    out = comm.shard_map(kernel, in_specs=(), out_specs=P(("j", "i"), None))()
    out = np.asarray(out)
    n = comm.size
    for row in out:
        rank, s, m, lo, off = row
        assert s == n * (n - 1) // 2
        assert m == n - 1
        assert lo == (1 if rank < Pi else 0)
        assert off == (rank // Pi) * 10


def test_local_shape_divisibility():
    comm = CartComm(ndims=2)
    assert comm.local_shape((8, 8)) == (2, 4)
    with pytest.raises(ValueError):
        comm.local_shape((9, 8))


def test_halo_strip_shapes_and_bytes():
    """The ONE message-geometry statement (ISSUE 6 dedupe satellite):
    `halo_strip_shapes` describes per-axis exchange strips (depth layers
    wide, full EXTENDED extent across — ghost corners ride along), and
    `halo_exchange_bytes` is exactly two directions of each. The
    utils/telemetry spelling is an alias of the same helper."""
    from pampi_tpu.parallel.comm import halo_exchange_bytes, halo_strip_shapes
    from pampi_tpu.utils import telemetry as tm

    assert halo_strip_shapes((8, 8), 1) == [(1, 10), (10, 1)]
    assert halo_strip_shapes((8, 8), 4) == [(4, 16), (16, 4)]
    assert halo_strip_shapes((4, 4, 4), 2) == [
        (2, 8, 8), (8, 2, 8), (8, 8, 2)]
    # the historical closed form: per axis, 2 * depth * prod(other ext)
    assert halo_exchange_bytes((8, 16), 1, 8) == (2 * 18 + 2 * 10) * 8
    assert halo_exchange_bytes((8, 8), 4, 8) == (2 * 4 * 16 * 2) * 8
    assert tm.halo_exchange_bytes((8, 16), 1, 8) == halo_exchange_bytes(
        (8, 16), 1, 8)


def test_multiprocess_capability_probe():
    """The tests/test_multihost.py gate (ISSUE 6 satellite): backend
    DETECTION, not a blanket skip. On this CPU container the probe must
    say incapable-with-reason iff the jaxlib ships no gloo collectives;
    on TPU/GPU it is always capable (ROADMAP item 4's acceptance suite
    un-gates itself on real hardware)."""
    from pampi_tpu.parallel.multihost import multiprocess_capable

    capable, reason = multiprocess_capable()
    if jax.default_backend() != "cpu":
        assert capable
    if capable:
        assert reason == ""
    else:
        assert "collectives" in reason


# ---------------------------------------------------------------------------
# hierarchical mesh tiers (tpu_mesh_tiers, ROADMAP item 3)
# ---------------------------------------------------------------------------

def test_mesh_tiers_parse_and_validate():
    from pampi_tpu.parallel.comm import CartComm, parse_mesh_tiers

    assert parse_mesh_tiers("auto", ("j", "i")) == {"j": "ici", "i": "ici"}
    assert parse_mesh_tiers("j=dcn", ("j", "i")) == {"j": "dcn",
                                                    "i": "ici"}
    with pytest.raises(ValueError, match="unknown mesh axis"):
        parse_mesh_tiers("q=dcn", ("j", "i"))
    with pytest.raises(ValueError, match="not in"):
        parse_mesh_tiers("j=pcie", ("j", "i"))
    with pytest.raises(ValueError, match="axis=tier"):
        parse_mesh_tiers("dcn", ("j", "i"))
    comm = CartComm(ndims=2, dims=(2, 2), tiers="j=dcn")
    assert comm.multi_tier and comm.tier_of("j") == "dcn"
    assert not CartComm(ndims=2, dims=(2, 2)).multi_tier


def test_tiered_schedule_value_safe():
    """Reordering full-strip axis exchanges is VALUE-safe: the tiered
    schedule (DCN axis posted first) fills every ghost with the same
    bytes the flat schedule does."""
    from pampi_tpu.parallel.comm import CartComm, persistent_exchange

    flat = CartComm(ndims=2, dims=(2, 2))
    tiered = CartComm(ndims=2, dims=(2, 2), tiers="i=dcn")
    assert [x[1] for x in persistent_exchange(tiered, 2).plan] == ["i", "j"]
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((2 * 12, 2 * 12)))

    def run(comm):
        sched = persistent_exchange(comm, 2)
        spec = comm.spec()
        fn = jax.jit(comm.shard_map(sched, in_specs=(spec,),
                                    out_specs=spec))
        return np.asarray(fn(x))

    assert np.array_equal(run(flat), run(tiered))


def test_halo_tier_bytes_accounting():
    """Per-tier bytes: size-1 axes charge nothing, the single-tier
    default puts all moved bytes under ici, and the dcn entry feeds the
    solver records' dcn_exchange_bytes."""
    from pampi_tpu.parallel.comm import (
        CartComm,
        exchange_schedule_tier_bytes,
        halo_tier_bytes,
    )

    flat = CartComm(ndims=2, dims=(2, 2))
    t = halo_tier_bytes(flat, (8, 8), 1, 8)
    assert t == {"ici": (2 * 10 + 2 * 10) * 8}
    row = CartComm(ndims=2, dims=(2, 1), tiers="j=dcn")
    t = halo_tier_bytes(row, (8, 8), 1, 8)
    assert t == {"dcn": 2 * 10 * 8, "ici": 0}  # i axis size 1: no bytes
    rec = {"shard": [8, 8], "dtype": "float64", "deep_halo": 3,
           "exchanges_per_step": {"deep": 2}}
    tiered = CartComm(ndims=2, dims=(2, 2), tiers="i=dcn")
    per = exchange_schedule_tier_bytes(tiered, rec)
    assert per["dcn"] == 2 * 2 * 3 * 14 * 8
    assert per["dcn"] + per["ici"] > 0


def test_per_tier_census_and_mutation():
    """The per-tier trace census covers every ppermute byte, and a
    MIS-TIERED strip shows up as a per-tier diff against the baseline
    (the ISSUE 13 mutation): re-tiering an axis moves its bytes between
    the dcn and ici buckets at constant totals."""
    import json

    from pampi_tpu.analysis import commcheck
    from pampi_tpu.analysis.jaxprcheck import trace_chunk
    from pampi_tpu.models.ns2d_dist import NS2DDistSolver
    from pampi_tpu.parallel.comm import CartComm
    from pampi_tpu.utils.params import Parameter

    param = Parameter(name="dcavity", imax=16, jmax=16, re=10.0, te=0.02,
                      tau=0.5, itermax=10, eps=1e-4, omg=1.7, gamma=0.9,
                      tpu_fuse_phases="on", tpu_sor_layout="checkerboard")
    s = NS2DDistSolver(param, CartComm(ndims=2, dims=(2, 2),
                                       tiers="i=dcn"))
    jx = trace_chunk(s)
    entry = commcheck.config_entry(
        type("T", (), {"jaxpr": jx, "solver": s})())
    tiers = entry["tiers"]
    assert set(tiers) >= {"dcn", "ici"}
    assert sum(t["bytes"] for t in tiers.values()) \
        == entry["ppermute_bytes"]
    # mutation: the same program censused under the FLAT map books the
    # dcn bytes under ici — a per-tier diff at identical totals
    flat = commcheck.census_tiers(jx.jaxpr,
                                  {"j": "ici", "i": "ici"})
    assert sum(t["bytes"] for t in flat.values()) \
        == entry["ppermute_bytes"]
    assert flat != tiers
    base = json.loads(json.dumps(entry))
    base["tiers"] = {k: dict(v) for k, v in flat.items()}
    vs, _ = commcheck.check_config(
        type("T", (), {"cfg": type("C", (), {
            "name": "tier_mutation", "family": "ns2d_dist",
            "dims": (2, 2)})(), "jaxpr": jx, "solver": s})(),
        base, env_matches=True)
    assert any(v.rule == commcheck.RULE_TIER for v in vs)
