"""Distributed NS-2D: exact equality with the single-device solver on the
faked 8-device mesh — stricter than the reference's own MPI parity (see the
equivalence policy in models/ns2d_dist.py)."""

import numpy as np
import pytest

from pampi_tpu.models.ns2d import NS2DSolver
from pampi_tpu.models.ns2d_dist import NS2DDistSolver
from pampi_tpu.parallel.comm import CartComm
from pampi_tpu.utils.params import read_parameter

DC = "assignment-5/sequential/dcavity.par"
CA = "assignment-5/sequential/canal.par"


def _compare(param, dims):
    single = NS2DSolver(param)
    single.run(progress=False)
    dist = NS2DDistSolver(param, CartComm(ndims=2, dims=dims))
    dist.run(progress=False)
    ud, vd, pd = dist.fields()
    assert dist.nt == single.nt
    np.testing.assert_array_equal(np.asarray(single.u), ud)
    np.testing.assert_array_equal(np.asarray(single.v), vd)
    np.testing.assert_array_equal(np.asarray(single.p), pd)


@pytest.mark.parametrize("dims", [(4, 2), (2, 4), (1, 8), (8, 1)])
def test_dcavity_dist_exact_vs_single(reference_dir, dims):
    param = read_parameter(str(reference_dir / DC)).replace(
        te=0.003, imax=96, jmax=96
    )
    _compare(param, dims)


def test_canal_dist_exact_vs_single(reference_dir):
    # canal exercises OUTFLOW walls, the parabolic-inflow special BC with
    # global y coordinates, and a never-converging pressure solve
    param = read_parameter(str(reference_dir / CA)).replace(te=0.5)
    _compare(param, (2, 4))


def test_debug_phase_harness(reference_dir):
    # the per-phase debug kernel (≙ test.c halo dump) must agree with the
    # single-device ops on the first step's intermediates
    import jax.numpy as jnp

    from pampi_tpu.ops import ns2d as ops

    param = read_parameter(str(reference_dir / DC)).replace(
        te=0.0, imax=32, jmax=32
    )
    dist = NS2DDistSolver(param, CartComm(ndims=2, dims=(4, 2)))
    u, v, f, g, rhs, p1, dt, _res, _it = dist._debug_sm(
        dist.u, dist.v, dist.p, jnp.asarray(0, jnp.int32)
    )
    shape = (34, 34)
    us = jnp.full(shape, param.u_init, jnp.float64)
    vs = jnp.full(shape, param.v_init, jnp.float64)
    dts = ops.compute_timestep(us, vs, dist.dt_bound, dist.dx, dist.dy, param.tau)
    assert float(dt) == float(dts)
    us, vs = ops.set_boundary_conditions(
        us, vs, param.bcLeft, param.bcRight, param.bcBottom, param.bcTop
    )
    us = ops.set_special_bc_dcavity(us)
    fs, gs = ops.compute_fg(
        us, vs, dts, param.re, param.gx, param.gy, param.gamma, dist.dx, dist.dy
    )
    rs = ops.compute_rhs(fs, gs, dts, dist.dx, dist.dy)
    np.testing.assert_array_equal(dist._assemble(u), np.asarray(us))
    np.testing.assert_array_equal(dist._assemble(v), np.asarray(vs))
    np.testing.assert_array_equal(
        dist._assemble(f)[1:-1, 1:-1], np.asarray(fs)[1:-1, 1:-1]
    )
    np.testing.assert_array_equal(
        dist._assemble(rhs)[1:-1, 1:-1], np.asarray(rs)[1:-1, 1:-1]
    )


def test_bad_mesh_dims_rejected():
    import pytest as _pytest

    for dims in [(0, 8), (2, -2)]:
        with _pytest.raises(ValueError):
            CartComm(ndims=2, dims=dims)


def test_canal_dist_j_split_crosses_inflow_profile(reference_dir):
    # j-split puts the inflow profile across shard boundaries (50/2=25 rows)
    param = read_parameter(str(reference_dir / CA)).replace(te=0.5)
    _compare(param, (2, 1))
