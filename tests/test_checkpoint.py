"""Checkpoint/restart round-trip: a run interrupted at a host sync and
resumed from the .npz must finish bit-identical to an uninterrupted run
(the subsystem the reference lacks, SURVEY.md §5)."""

import numpy as np
import pytest

from pampi_tpu.models.ns2d import NS2DSolver
from pampi_tpu.utils import checkpoint as ckpt
from pampi_tpu.utils.params import Parameter, read_parameter


def _param(te):
    return Parameter(
        name="dcavity", imax=32, jmax=32, re=10.0, te=te, tau=0.5,
        itermax=100, eps=1e-3, omg=1.8, gamma=0.9, tpu_dtype="float64",
    )


def test_roundtrip_bitwise(tmp_path):
    path = str(tmp_path / "ck.npz")

    # uninterrupted run
    ref = NS2DSolver(_param(te=0.5))
    ref.run(progress=False)

    # interrupted: checkpoint at EVERY host sync, stop partway by using a
    # shorter te, then restore into a fresh solver and continue to te
    first = NS2DSolver(_param(te=0.2))
    first.run(progress=False, on_sync=ckpt.periodic_writer(path, every=1))
    ckpt.save_checkpoint(path, first)

    second = NS2DSolver(_param(te=0.5))
    ckpt.load_checkpoint(path, second)
    assert second.t == first.t and second.nt == first.nt
    second.run(progress=False)

    assert ref.nt == second.nt
    np.testing.assert_array_equal(np.asarray(ref.p), np.asarray(second.p))
    np.testing.assert_array_equal(np.asarray(ref.u), np.asarray(second.u))
    np.testing.assert_array_equal(np.asarray(ref.v), np.asarray(second.v))


def test_shape_mismatch_rejected(tmp_path):
    path = str(tmp_path / "ck.npz")
    s = NS2DSolver(_param(te=0.1))
    ckpt.save_checkpoint(path, s)
    other = NS2DSolver(
        Parameter(name="dcavity", imax=16, jmax=16, re=10.0, te=0.1,
                  tpu_dtype="float64")
    )
    with pytest.raises(ValueError, match="checkpoint grid"):
        ckpt.load_checkpoint(path, other)


def test_par_keys_parsed(tmp_path):
    par = tmp_path / "r.par"
    par.write_text(
        "name dcavity\ntpu_checkpoint ck.npz\ntpu_ckpt_every 3\n"
        "tpu_restart old.npz\n"
    )
    p = read_parameter(str(par))
    assert p.tpu_checkpoint == "ck.npz"
    assert p.tpu_ckpt_every == 3
    assert p.tpu_restart == "old.npz"


def test_roundtrip_distributed(tmp_path):
    """Dist solvers carry stacked extended blocks; save/restore on the same
    mesh must continue bit-identical, and a mesh mismatch must be refused."""
    from pampi_tpu.models.ns3d_dist import NS3DDistSolver
    from pampi_tpu.parallel.comm import CartComm

    def p3(te):
        return Parameter(
            name="dcavity3d", imax=8, jmax=8, kmax=8, re=10.0, te=te,
            tau=0.5, itermax=50, eps=1e-3, omg=1.7, gamma=0.9,
            tpu_dtype="float64",
        )

    path = str(tmp_path / "ck3d.npz")
    dims = (2, 2, 2)
    ref = NS3DDistSolver(p3(0.2), CartComm(ndims=3, dims=dims))
    ref.run(progress=False)

    first = NS3DDistSolver(p3(0.08), CartComm(ndims=3, dims=dims))
    first.run(progress=False)
    ckpt.save_checkpoint(path, first)

    second = NS3DDistSolver(p3(0.2), CartComm(ndims=3, dims=dims))
    ckpt.load_checkpoint(path, second)
    assert second.t == first.t and second.nt == first.nt
    second.run(progress=False)
    assert ref.nt == second.nt
    for a, b in zip(ref.collect(), second.collect()):
        np.testing.assert_array_equal(a, b)

    other = NS3DDistSolver(p3(0.2), CartComm(ndims=3, dims=(1, 2, 4)))
    with pytest.raises(ValueError, match="mesh"):
        ckpt.load_checkpoint(path, other)
